#!/usr/bin/env python
"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline: f32 Cholesky (potrf) GFLOP/s on the attached TPU chip at
n=4096, the reference's ex07 north-star config on one chip (BASELINE.md;
TPU has no f64 MXU path, so f32 is the native headline precision — the
reference's own mixed-precision solvers deliver d-accuracy, see
slate_tpu.linalg.lu.gesv_mixed). The four BASELINE.md routines
(gemm/potrf/getrf/geqrf) are all measured; extras carry the full table
including n=8192 (geqrf at 8192 is skipped: its 64 Pallas panel
compilations through the remote-compile tunnel exceed the bench's time
budget; the 4096 number is representative).

vs_baseline: potrf GFLOP/s divided by measured big-gemm GFLOP/s on the
same chip in the same process — the fraction of the chip's attainable
matmul rate the full factorization sustains (self-calibrating analogue
of "within X% of cuBLAS" from BASELINE.json). The ratio is measured
same-process because the chip's absolute f32 rate drifts 20-40% between
processes (thermal/clock), while same-process ratios are stable.

Timing notes: the axon tunnel has ~90 ms dispatch latency, so each
measurement chains K dependency-linked iterations inside one jit and
uses the two-point slope (T(k2)-T(k1))/(k2-k1), which cancels both the
RPC floor and one-off costs. Matrices are generated ON DEVICE
(jax.random) — host arrays at n=8192 exceed the tunnel's payload limit —
and are passed as jit arguments, never closure-captured (a captured
concrete array becomes an HLO constant shipped with every compile).
Both sides use Precision.HIGHEST so vs_baseline compares f32-accurate
math to f32-accurate math.
"""

import dataclasses
import functools
import json
import sys
import time


def _slope(f2, x0, aux, est_hint, reps=5, target=0.6):
    """Per-iteration time of f2, robust to the tunnel's ~90-150 ms and
    drifting dispatch floor: chain k dependency-linked iterations inside
    one jit (k is a *runtime* trip count — one compile serves every k)
    and take the two-point slope with k2 sized so the signal
    (k2-k1)*t >= `target` seconds, far above the floor's jitter.
    `est_hint`: rough seconds/iter used only to pick k before the
    measured estimate refines it."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(x, aux, k):
        return jax.lax.fori_loop(0, k, lambda i, x: f2(x, aux), x)

    def once(k, r=reps):
        for attempt in range(4):     # tunnel hiccup retry (compile rpc)
            try:
                float(jnp.ravel(run(x0, aux, k))[0])
                break
            except Exception:
                if attempt == 3:
                    raise
                time.sleep(3)
        best = float("inf")
        for _ in range(r):
            t0 = time.perf_counter()
            out = run(x0, aux, k)
            float(jnp.ravel(out)[0])        # scalar fetch forces sync
            best = min(best, time.perf_counter() - t0)
        return best

    # refine the estimate with a cheap two-point probe
    ka = max(2, int(0.05 / est_hint))
    kb = ka + max(4, int(0.15 / est_hint))
    est = max((once(kb, 3) - once(ka, 3)) / (kb - ka), est_hint / 10)
    k2 = min(max(int(target / est), 8), 512)
    k1 = max(2, k2 // 8)
    t = (once(k2) - once(k1)) / (k2 - k1)
    return max(t, 1e-9)


def bench_size(st, tl, n, with_geqrf, budget_scale=1.0):
    import jax
    import jax.numpy as jnp
    from slate_tpu.core.enums import Diag, MatrixType, Op, Uplo
    HI = jax.lax.Precision.HIGHEST

    @jax.jit
    def gen():
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (n, n), jnp.float32)
        spd = jnp.matmul(x, x.T, precision=HI) / n \
            + 4.0 * jnp.eye(n, dtype=jnp.float32)
        return x, spd

    xj, spd_j = gen()
    xj.block_until_ready()

    scale = (n / 4096.0) ** 3
    out = {}

    t = _slope(lambda c, g: jnp.matmul(g, c, precision=HI) * (1.0 / n),
               xj, xj, est_hint=5e-3 * scale,
               target=0.6 * budget_scale)
    out["gemm"] = 2.0 * n ** 3 / t / 1e9

    nb = 512
    H = tl.TiledMatrix(data=spd_j, m=n, n=n, mb=nb, nb=nb,
                       mtype=MatrixType.Hermitian, uplo=Uplo.Lower,
                       op=Op.NoTrans, diag=Diag.NonUnit)

    def potrf_f(d, aux):
        L = st.potrf(dataclasses.replace(H, data=d))
        return aux + L.data * 1e-30

    t = _slope(potrf_f, spd_j, spd_j, est_hint=2e-3 * scale,
               target=0.6 * budget_scale)
    out["potrf"] = (n ** 3 / 3.0) / t / 1e9

    G = tl.TiledMatrix(data=xj, m=n, n=n, mb=nb, nb=nb,
                       mtype=MatrixType.General, uplo=Uplo.General,
                       op=Op.NoTrans, diag=Diag.NonUnit)

    def getrf_f(d, aux):
        F = st.getrf(dataclasses.replace(G, data=d))
        return aux + F.LU.data * 1e-30

    t = _slope(getrf_f, xj, xj, est_hint=3e-3 * scale * scale,
               target=0.6 * budget_scale)
    out["getrf"] = (2.0 * n ** 3 / 3.0) / t / 1e9

    if with_geqrf:
        def geqrf_f(d, aux):
            F = st.geqrf(dataclasses.replace(G, data=d))
            return aux + F.QR.data * 1e-30

        try:
            # geqrf's many Pallas panel compiles are the flakiest part
            # of the run — never let them take the headline down
            t = _slope(geqrf_f, xj, xj, est_hint=2e-2 * scale, reps=3,
                       target=0.5 * budget_scale)
            out["geqrf"] = (4.0 * n ** 3 / 3.0) / t / 1e9
        except Exception as e:
            out["geqrf_error"] = str(e)[:120]

    return out


def main():
    sys.path.insert(0, ".")
    import slate_tpu as st
    import slate_tpu.core.tiles as tl

    r4 = bench_size(st, tl, 4096, with_geqrf=True)
    try:
        r8 = bench_size(st, tl, 8192, with_geqrf=False, budget_scale=0.4)
    except Exception as e:           # keep the headline if 8192 dies
        r8 = {"error": str(e)[:120]}

    extras = {f"{k}_n4096": round(v, 1) for k, v in r4.items()}
    extras.update({f"{k}_n8192": (round(v, 1)
                                  if isinstance(v, float) else v)
                   for k, v in r8.items()})
    extras["potrf_vs_gemm_n8192"] = (
        round(r8["potrf"] / r8["gemm"], 4)
        if isinstance(r8.get("potrf"), float) else None)
    extras["getrf_vs_gemm_n4096"] = round(r4["getrf"] / r4["gemm"], 4)
    if isinstance(r4.get("geqrf"), float):
        extras["geqrf_vs_gemm_n4096"] = round(r4["geqrf"] / r4["gemm"],
                                              4)

    print(json.dumps({
        "metric": "potrf_f32_gflops_n4096",
        "value": round(r4["potrf"], 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(r4["potrf"] / r4["gemm"], 4),
        "extras": extras,
    }))


if __name__ == "__main__":
    main()
