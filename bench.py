#!/usr/bin/env python
"""Benchmark driver: streams one JSON line per measurement, ends with ONE
headline JSON line.

Headline: f32 Cholesky (potrf) GFLOP/s on the attached TPU chip at
n=16384, the reference's ex07 north-star config on one chip
(BASELINE.md; TPU has no f64 MXU path, so f32 is the native headline
precision — the reference's own mixed-precision solvers deliver
d-accuracy, see slate_tpu.linalg.lu.gesv_mixed). n=16384 leads because
the reference's headline regime is large matrices (BASELINE.json north
star is n=131072) and per-kernel overheads amortize with n (measured
potrf/gemm: 0.39 at 4096, 0.56 at 8192, ~0.70 at 16384); n=8192 and
n=4096 follow for round-over-round comparability with BENCH_r01/r02.
The BASELINE.md routines (gemm/potrf/getrf/geqrf) are all measured at
the two largest sizes; the lookahead pair runs at n=8192 only (the
Tiled potrf at 16384 is a long compile for a number that tracks the
8192 one) and the smallest size gets a reduced set.

vs_baseline: potrf GFLOP/s divided by measured big-gemm GFLOP/s on the
same chip in the same process — the fraction of the chip's attainable
matmul rate the full factorization sustains (self-calibrating analogue
of "within X% of cuBLAS" from BASELINE.json). The ratio is measured
same-process because the chip's absolute f32 rate drifts 20-40% between
processes (thermal/clock), while same-process ratios are stable.

Loss-proofing (the round-2 run died mid-flight and took every completed
measurement with it):
  * The backend is probed FIRST in a subprocess with a hard timeout —
    a dead TPU tunnel hangs backend init in C code forever, which no
    in-process timeout can interrupt. On probe failure the script emits
    a skip headline and exits 0.
  * Every routine×size measurement is individually try/except'd and its
    JSON line is printed (flushed) the moment it exists, so a backend
    loss mid-run still leaves everything measured so far on stdout and
    exits 0.

Flags (combinable with the default sweep unless noted): ``--micro``
``--tune`` ``--ooc`` ``--serve`` ``--serve-daemon`` ``--shard``
``--faults`` ``--graph`` ``--fuse`` ``--lint``
run their own suites; ``--obs`` enables the observability bus for the
whole run, ships the metrics/driver/analysis snapshot in the headline
extras, AND runs the **regression leg** (ISSUE 14): the current run's
per-driver walls, counters, and shared numeric extras are compared
against the most recent ``BENCH_r*.json`` in the checkout and the
per-metric deltas land in ``extras["obs_regression"]`` — the BENCH
trajectory read back instead of write-only. ``--shard`` additionally
gates on the flight-recorder attribution leg (>= 95% of the measured
sharded-potrf wall attributed to named ledger phases).

Timing notes: the axon tunnel has ~90 ms dispatch latency, so each
measurement chains K dependency-linked iterations inside one jit and
uses the two-point slope (T(k2)-T(k1))/(k2-k1), which cancels both the
RPC floor and one-off costs. Matrices are generated ON DEVICE
(jax.random) — host arrays at n=8192 exceed the tunnel's payload limit —
and are passed as jit arguments, never closure-captured (a captured
concrete array becomes an HLO constant shipped with every compile).
Both sides use Precision.HIGHEST so vs_baseline compares f32-accurate
math to f32-accurate math.
"""

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from slate_tpu.utils.backend import force_cpu, probe_backend  # noqa: E402


def emit(obj):
    """Print one JSON line immediately — never buffer a measurement."""
    print(json.dumps(obj), flush=True)


def _slope(f2, x0, aux, est_hint, reps=5, target=0.6):
    """Per-iteration time of f2, robust to the tunnel's ~90-150 ms and
    drifting dispatch floor: chain k dependency-linked iterations inside
    one jit (k is a *runtime* trip count — one compile serves every k)
    and take the two-point slope with k2 sized so the signal
    (k2-k1)*t >= `target` seconds, far above the floor's jitter.
    `est_hint`: rough seconds/iter used only to pick k before the
    measured estimate refines it."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(x, aux, k):
        return jax.lax.fori_loop(0, k, lambda i, x: f2(x, aux), x)

    def once(k, r=reps):
        for attempt in range(4):     # tunnel hiccup retry (compile rpc)
            try:
                float(jnp.ravel(run(x0, aux, k))[0])
                break
            except Exception:
                if attempt == 3:
                    raise
                time.sleep(3)
        best = float("inf")
        for _ in range(r):
            t0 = time.perf_counter()
            out = run(x0, aux, k)
            float(jnp.ravel(out)[0])        # scalar fetch forces sync
            best = min(best, time.perf_counter() - t0)
        return best

    # refine the estimate with a cheap two-point probe; clamp the probe
    # trip counts so a small-n/slow-backend run (CPU smoke test) cannot
    # explode into thousands of chained iterations
    ka = min(max(2, int(0.05 / est_hint)), 32)
    kb = ka + min(max(4, int(0.15 / est_hint)), 64)
    est = max((once(kb, 3) - once(ka, 3)) / (kb - ka), est_hint / 10)
    k2 = min(max(int(target / est), 8), 512)
    k1 = max(2, k2 // 8)
    t = (once(k2) - once(k1)) / (k2 - k1)
    return max(t, 1e-9)


def bench_size(st, tl, n, with_geqrf, results, budget_scale=1.0,
               with_lookahead=False, with_getrf=True,
               headline_best_of=1):
    """Measure gemm/potrf[/getrf][/geqrf][/lookahead pair] at size n.
    Each routine is individually guarded; successes are emitted
    immediately and stored in `results` under '<routine>_n<n>'.
    headline_best_of > 1 repeats the potrf measurement that many
    times and keeps the best — the headline metric was swinging +-9%
    on run noise between rounds (VERDICT r5 weak #4), and a best-of-3
    slope is stable where a single slope is not."""
    import jax
    import jax.numpy as jnp
    from slate_tpu.core.enums import Diag, MatrixType, Op, Uplo
    HI = jax.lax.Precision.HIGHEST

    @jax.jit
    def gen():
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (n, n), jnp.float32)
        spd = jnp.matmul(x, x.T, precision=HI) / n \
            + 4.0 * jnp.eye(n, dtype=jnp.float32)
        return x, spd

    xj, spd_j = gen()
    xj.block_until_ready()

    scale = (n / 4096.0) ** 3
    nb = 512

    def record(name, gflops):
        key = "%s_n%d" % (name, n)
        results[key] = round(gflops, 1)
        emit({"metric": "%s_f32_gflops_n%d" % (name, n),
              "value": round(gflops, 1), "unit": "GFLOP/s"})

    def guarded(name, fn):
        failed = False
        try:
            fn()
        except Exception as e:
            results["%s_n%d_error" % (name, n)] = str(e)[:160]
            emit({"metric": "%s_f32_gflops_n%d" % (name, n),
                  "error": str(e)[:160]})
            failed = True
        if failed:
            # a failed attempt (esp. OOM) pins device buffers via the
            # exception's traceback frames; those frames are only
            # released once the except block EXITS, so the collect
            # must happen here, after it
            import gc
            gc.collect()

    def m_gemm():
        t = _slope(lambda c, g: jnp.matmul(g, c, precision=HI)
                   * (1.0 / n),
                   xj, xj, est_hint=5e-3 * scale,
                   target=0.6 * budget_scale)
        record("gemm", 2.0 * n ** 3 / t / 1e9)

    H = tl.TiledMatrix(data=spd_j, m=n, n=n, mb=nb, nb=nb,
                       mtype=MatrixType.Hermitian, uplo=Uplo.Lower,
                       op=Op.NoTrans, diag=Diag.NonUnit)

    def m_potrf():
        def potrf_f(d, aux):
            L = st.potrf(dataclasses.replace(H, data=d))
            return aux + L.data * 1e-30
        # best-of-N independent slope measurements for the headline
        # size (module doc of bench_size); each repeat re-enters the
        # same jitted executable, so repeats cost steady-state time
        # only, not recompiles
        t = min(_slope(potrf_f, spd_j, spd_j, est_hint=2e-3 * scale,
                       target=0.6 * budget_scale)
                for _ in range(max(headline_best_of, 1)))
        record("potrf", (n ** 3 / 3.0) / t / 1e9)

    G = tl.TiledMatrix(data=xj, m=n, n=n, mb=nb, nb=nb,
                       mtype=MatrixType.General, uplo=Uplo.General,
                       op=Op.NoTrans, diag=Diag.NonUnit)

    def m_getrf():
        def getrf_f(d, aux):
            F = st.getrf(dataclasses.replace(G, data=d))
            return aux + F.LU.data * 1e-30
        t = _slope(getrf_f, xj, xj, est_hint=3e-3 * scale * scale,
                   target=0.6 * budget_scale)
        record("getrf", (2.0 * n ** 3 / 3.0) / t / 1e9)

    def m_getrf_fused():
        # XLA's native LU, the baseline the default (Tiled carry) path
        # is chosen over — measured so the policy stays data-backed
        from slate_tpu.core.methods import MethodFactor
        from slate_tpu.core.options import Option
        fo = {Option.MethodFactor: MethodFactor.Fused}

        def getrf_f(d, aux):
            F = st.getrf(dataclasses.replace(G, data=d), fo)
            return aux + F.LU.data * 1e-30
        t = _slope(getrf_f, xj, xj, est_hint=3e-3 * scale * scale,
                   reps=3, target=0.4 * budget_scale)
        record("getrf_fused", (2.0 * n ** 3 / 3.0) / t / 1e9)

    def m_lookahead():
        # lookahead evidence (VERDICT r2 item 2): the Tiled potrf with
        # the software-pipelined loop (Option.Lookahead=1) vs the plain
        # right-looking order, same method/path otherwise
        from slate_tpu.core.methods import MethodFactor
        from slate_tpu.core.options import Option
        for la in (0, 1):
            opts = {Option.MethodFactor: MethodFactor.Tiled,
                    Option.Lookahead: la}

            def f(d, aux, opts=opts):
                L = st.potrf(dataclasses.replace(H, data=d), opts)
                return aux + L.data * 1e-30

            t = _slope(f, spd_j, spd_j, est_hint=4e-3 * scale, reps=3,
                       target=0.4 * budget_scale)
            record("potrf_tiled_la%d" % la, (n ** 3 / 3.0) / t / 1e9)

    def m_geqrf():
        def geqrf_f(d, aux):
            F = st.geqrf(dataclasses.replace(G, data=d))
            return aux + F.QR.data * 1e-30
        # geqrf's many Pallas panel compiles are the flakiest part of
        # the run — reps=3 keeps it inside the time budget
        t = _slope(geqrf_f, xj, xj, est_hint=2e-2 * scale, reps=3,
                   target=0.5 * budget_scale)
        record("geqrf", (4.0 * n ** 3 / 3.0) / t / 1e9)
        # fused alternative (ONE whole-matrix native geqrf, packed
        # contract): measured so the blocked-vs-fused default can be
        # chosen from hardware data
        from slate_tpu.core.methods import MethodFactor
        from slate_tpu.core.options import Option
        fopts = {Option.MethodFactor: MethodFactor.Fused}

        def geqrf_fused_f(d, aux):
            F = st.geqrf(dataclasses.replace(G, data=d), fopts)
            return aux + F.QR.data * 1e-30

        t = _slope(geqrf_fused_f, xj, xj, est_hint=1e-2 * scale,
                   reps=3, target=0.4 * budget_scale)
        record("geqrf_fused", (4.0 * n ** 3 / 3.0) / t / 1e9)

    guarded("gemm", m_gemm)
    guarded("potrf", m_potrf)
    if with_getrf:
        guarded("getrf", m_getrf)
        guarded("getrf_fused", m_getrf_fused)
    if with_geqrf:
        guarded("geqrf", m_geqrf)
    if with_lookahead:
        guarded("potrf_tiled_la", m_lookahead)
    import gc
    gc.collect()


def bench_large(st, tl, n, results, budget_scale=0.5):
    """LU/QR entries at n beyond the native-LU compile limit (the
    round-3 gap: no getrf/geqrf number at the 16384 headline).
    Routes that work there: the Tiled carry LU whose tall panels fall
    back to the masked fori_loop kernel (true partial pivoting, slow
    but real), the CALU tournament LU whose chunked native rounds
    sidestep the height limit at matmul-ish rate (getrf_tntpiv), and
    the blocked carry geqrf with the n-scaled block size (19 TF/s;
    the 64-step nb=256 unroll RESOURCE_EXHAUSTS here, which is why
    Auto widens nb with n — scan form kept as the guarded
    fallback)."""
    import jax
    import jax.numpy as jnp
    from slate_tpu.core.enums import Diag, MatrixType, Op, Uplo
    from slate_tpu.core.methods import MethodLU
    from slate_tpu.core.options import Option

    @jax.jit
    def gen():
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (n, n), jnp.float32)
        return x + 0.05 * n * jnp.eye(n, dtype=jnp.float32)

    xj = gen()
    xj.block_until_ready()
    G = tl.TiledMatrix(data=xj, m=n, n=n, mb=512, nb=512,
                       mtype=MatrixType.General, uplo=Uplo.General,
                       op=Op.NoTrans, diag=Diag.NonUnit)

    def record(name, gflops):
        results["%s_n%d" % (name, n)] = round(gflops, 1)
        emit({"metric": "%s_f32_gflops_n%d" % (name, n),
              "value": round(gflops, 1), "unit": "GFLOP/s"})

    def guarded(name, fn):
        try:
            fn()
        except Exception as e:
            results["%s_n%d_error" % (name, n)] = str(e)[:160]
            emit({"metric": "%s_f32_gflops_n%d" % (name, n),
                  "error": str(e)[:160]})
            import gc
            gc.collect()

    def m_getrf_tntpiv():
        opts = {Option.MethodLU: MethodLU.CALU}

        def f(d, aux):
            F = st.getrf_tntpiv(dataclasses.replace(G, data=d), opts)
            return aux + F.LU.data * 1e-30
        t = _slope(f, xj, xj, est_hint=3e-1, reps=3,
                   target=0.6 * budget_scale)
        record("getrf_tntpiv", (2.0 * n ** 3 / 3.0) / t / 1e9)

    def m_getrf_tiled():
        def f(d, aux):
            F = st.getrf(dataclasses.replace(G, data=d))
            return aux + F.LU.data * 1e-30
        t = _slope(f, xj, xj, est_hint=1.5, reps=3,
                   target=0.5 * budget_scale)
        record("getrf", (2.0 * n ** 3 / 3.0) / t / 1e9)

    def m_geqrf(opts=None):
        def f(d, aux):
            F = st.geqrf(dataclasses.replace(G, data=d), opts)
            return aux + F.QR.data * 1e-30
        t = _slope(f, xj, xj, est_hint=4e-1, reps=3,
                   target=0.5 * budget_scale)
        record("geqrf", (4.0 * n ** 3 / 3.0) / t / 1e9)

    def m_geqrf_routed():
        # Auto routes to the blocked carry form with the n-scaled nb
        # (1024 at 16384: 19.0 TF/s measured round 4). If a smaller
        # HBM ever RESOURCE_EXHAUSTs it, fall back to the fixed-shape
        # scan form (BlockSize=128 pushes the step count past the
        # scan threshold; bounded live intermediates, ~4 TF/s). Only
        # OOM reroutes — any other failure must surface as a geqrf
        # error, not be silently remeasured as the scan. The retry is
        # best-effort: a post-OOM process can keep failing allocations
        # (PERF.md round-4b), so the fallback emits a marker line and
        # the guarded() wrapper still records a total loss honestly.
        try:
            m_geqrf()
        except Exception as e:
            if "RESOURCE_EXHAUSTED" not in str(e):
                raise
            import gc
            gc.collect()
            # distinct key: consumers keyed on 'metric' must not see
            # two records for geqrf_f32_gflops_n%d (ADVICE r4)
            emit({"metric": "geqrf_f32_fallback_n%d" % n,
                  "note": "carry form RESOURCE_EXHAUSTED; the "
                          "geqrf_f32_gflops value below is the "
                          "scan-form fallback in the same (possibly "
                          "poisoned) process"})
            m_geqrf({Option.BlockSize: 128})

    guarded("getrf_tntpiv", m_getrf_tntpiv)
    guarded("getrf", m_getrf_tiled)
    guarded("geqrf", m_geqrf_routed)
    import gc
    gc.collect()


def bench_solvers(st, tl, full_n, results, budget_scale=0.5):
    """Solver-level entries (BASELINE.md configs ex06-ex11; reference
    test/ sweeps every driver): posv + gesv at full_n with 64 rhs,
    tall-skinny gels, heev and svd with vectors at 4096. GFLOP/s uses
    the NOMINAL classical counts (LAPACK convention: posv n^3/3 +
    2n^2 r, gesv 2n^3/3 + 2n^2 r, gels 2n^2(m - n/3), heev 4/3 n^3,
    svd 8/3 n^3) so ratios against gemm read as fractions of chip
    rate, not algorithm-internal flops."""
    import jax
    import jax.numpy as jnp
    from slate_tpu.core.enums import Diag, MatrixType, Op, Uplo
    HI = jax.lax.Precision.HIGHEST
    nrhs = 64

    def record(name, gflops):
        results[name] = round(gflops, 1)
        emit({"metric": "%s_f32_gflops" % name,
              "value": round(gflops, 1), "unit": "GFLOP/s"})

    def guarded(name, fn):
        try:
            fn()
        except Exception as e:
            results["%s_error" % name] = str(e)[:160]
            emit({"metric": name, "error": str(e)[:160]})
            import gc
            gc.collect()

    def mk(data, mtype=MatrixType.General, uplo=Uplo.General, nb=512):
        return tl.TiledMatrix(data=data, m=data.shape[0],
                              n=data.shape[1], mb=nb, nb=nb,
                              mtype=mtype, uplo=uplo, op=Op.NoTrans,
                              diag=Diag.NonUnit)

    n = full_n
    scale = (n / 4096.0) ** 3

    @jax.jit
    def gen():
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (n, n), jnp.float32)
        spd = jnp.matmul(x, x.T, precision=HI) / n \
            + 4.0 * jnp.eye(n, dtype=jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(1), (n, nrhs),
                              jnp.float32)
        return x + 0.05 * n * jnp.eye(n, dtype=jnp.float32), spd, b

    xj, spd_j, bj = gen()
    xj.block_until_ready()

    def m_posv():
        def f(d, aux):
            _, X = st.posv(mk(d, MatrixType.Hermitian, Uplo.Lower),
                           mk(aux))
            return d + X.data[:, :1] * 1e-30
        t = _slope(f, spd_j, bj, est_hint=4e-3 * scale, reps=3,
                   target=0.5 * budget_scale)
        record("posv_n%d_r%d" % (n, nrhs),
               (n ** 3 / 3.0 + 2.0 * n * n * nrhs) / t / 1e9)

    def m_gesv():
        def f(d, aux):
            _, X = st.gesv(mk(d), mk(aux))
            return d + X.data[:, :1] * 1e-30
        t = _slope(f, xj, bj, est_hint=8e-3 * scale, reps=3,
                   target=0.5 * budget_scale)
        record("gesv_n%d_r%d" % (n, nrhs),
               (2.0 * n ** 3 / 3.0 + 2.0 * n * n * nrhs) / t / 1e9)

    gm, gn = 4 * full_n, max(full_n // 4, 64)   # tall-skinny (ex09)

    @jax.jit
    def gen_ls():
        key = jax.random.PRNGKey(2)
        return (jax.random.normal(key, (gm, gn), jnp.float32),
                jax.random.normal(jax.random.PRNGKey(3), (gm, nrhs),
                                  jnp.float32))

    def m_gels():
        aj, bbj = gen_ls()

        def f(d, aux):
            X = st.gels(mk(d), mk(aux))
            return d + X.data[:1, :1] * 1e-30
        t = _slope(f, aj, bbj, est_hint=2e-2, reps=3,
                   target=0.4 * budget_scale)
        record("gels_m%d_n%d_r%d" % (gm, gn, nrhs),
               2.0 * gn * gn * (gm - gn / 3.0) / t / 1e9)

    # eigen/SVD sizes: 4096 for round-over-round comparability AND
    # 8192 — the size the eigensolver perf work is judged at (VERDICT
    # r5 weak #6: the one gap being worked on was not tracked by the
    # harness that drives the verdict)
    eig_sizes = [min(4096, full_n)]
    if full_n >= 8192 and 8192 not in eig_sizes:
        eig_sizes.append(8192)

    def gen_eig(ne):
        @jax.jit
        def g():
            key = jax.random.PRNGKey(4)
            x = jax.random.normal(key, (ne, ne), jnp.float32)
            return jnp.matmul(x, x.T, precision=HI) / ne \
                + jnp.eye(ne, dtype=jnp.float32)
        return g()

    def m_heev(ne):
        hj = gen_eig(ne)

        def f(d, aux):
            r = st.heev(mk(d, MatrixType.Hermitian, Uplo.Lower))
            return d + r.vectors.data * 1e-30
        t = _slope(f, hj, hj, est_hint=5e-1 * (ne / 4096.0) ** 3,
                   reps=3, target=0.4 * budget_scale)
        record("heev_n%d" % ne, (4.0 * ne ** 3 / 3.0) / t / 1e9)

    def m_svd(ne):
        sj = gen_eig(ne)

        def f(d, aux):
            r = st.svd(mk(d))
            return d + r.U.data * 1e-30
        t = _slope(f, sj, sj, est_hint=9e-1 * (ne / 4096.0) ** 3,
                   reps=3, target=0.4 * budget_scale)
        record("svd_n%d" % ne, (8.0 * ne ** 3 / 3.0) / t / 1e9)

    guarded("posv", m_posv)
    guarded("gesv", m_gesv)
    guarded("gels", m_gels)
    if full_n >= 4096:       # QDWH at 1024+ is too slow for the CPU
        for ne in eig_sizes:      # smoke tier; real runs always hit
            # size-qualified guard names: a failure at one size must
            # not collide with (or shadow) the other size's record
            guarded("heev_n%d" % ne, lambda ne=ne: m_heev(ne))
            guarded("svd_n%d" % ne, lambda ne=ne: m_svd(ne))
            import gc
            gc.collect()
    import gc
    gc.collect()


def bench_micro(st, results):
    """`--micro`: regenerate the microbenchmarks behind the in-code
    perf claims (VERDICT r2 'perf-claim hygiene') — the v5e numbers
    quoted in blocked.py's module docstring (dense vs lower-only
    trailing updates), invert_triangular/trtri, the Pallas panel
    kernels, and XLA's native kernels that set the Fused/Tiled policy
    (methods.py). Times are milliseconds per call via the same slope
    method as the main bench; each line is emitted as measured."""
    import jax
    import jax.numpy as jnp
    HI = jax.lax.Precision.HIGHEST

    key = jax.random.PRNGKey(0)
    # calibrate a platform speed factor so the slope probes pick sane
    # trip counts on slow backends (est_hints below are v5e-scale; a
    # CPU run is ~100-1000x slower per call). The calibration itself
    # must be slope-based: a single timed call through the tunnel is
    # ~100 ms of RPC floor, which would inflate `speed` ~1000x and
    # wreck every downstream est_hint.
    xcal = jax.random.normal(key, (1024, 1024), jnp.float32)

    @jax.jit
    def fcal(x, aux, k):
        # aux passed as an argument, never closure-captured (a captured
        # concrete array becomes an HLO constant shipped per compile)
        return jax.lax.fori_loop(
            0, k, lambda i, x: jnp.matmul(x, aux, precision=HI)
            * (1.0 / 32.0), x)

    def tcal(k):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            fcal(xcal, xcal, k).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    fcal(xcal, xcal, 2).block_until_ready()        # compile
    t_mm = max((tcal(34) - tcal(2)) / 32.0, 1e-6)
    speed = max(t_mm / 1e-4, 1.0)

    def emit_ms(name, t):
        results[name + "_ms"] = round(t * 1e3, 3)
        emit({"metric": name + "_ms", "value": round(t * 1e3, 3),
              "unit": "ms"})

    def guarded(name, fn):
        try:
            fn()
        except Exception as e:
            results[name + "_error"] = str(e)[:160]
            emit({"metric": name, "error": str(e)[:160]})

    def m_trtri():
        # hot-path inversion (XLA solve leaf since round 3) vs the
        # retired Pallas substitution kernel — the measurement behind
        # the round-3 rerouting (PERF.md)
        from slate_tpu.linalg.blocked import invert_triangular
        from slate_tpu.ops import pallas_kernels as pk
        l = jnp.tril(jax.random.normal(key, (512, 512), jnp.float32)) \
            + 8.0 * jnp.eye(512, dtype=jnp.float32)
        t = _slope(lambda x, aux: invert_triangular(x, True) + aux * 0,
                   l, l, est_hint=3e-4 * speed, reps=3, target=0.3)
        emit_ms("micro_trtri_lower_512", t)
        if pk.pallas_available(l.dtype):
            t = _slope(lambda x, aux: pk.trtri_lower(x) + aux * 0,
                       l, l, est_hint=3e-4 * speed, reps=3, target=0.3)
            emit_ms("micro_pallas_trtri_512", t)

    def m_xla_trisolve():
        # the number that retired invert-then-matmul from the
        # single-device paths: TriangularSolve at matmul rate
        l = jnp.tril(jax.random.normal(key, (256, 256), jnp.float32)) \
            + 8.0 * jnp.eye(256, dtype=jnp.float32)
        b = jax.random.normal(key, (256, 256), jnp.float32)
        t = _slope(lambda x, aux: jax.lax.linalg.triangular_solve(
            aux, x, left_side=True, lower=True), b, l,
            est_hint=5e-4 * speed, reps=3, target=0.3)
        emit_ms("micro_xla_triangular_solve_256", t)

    def m_chol_panel():
        # hot-path diag factor (XLA cholesky since round 3) vs the
        # retired Pallas panel
        from slate_tpu.linalg.blocked import chol_diag_factor
        from slate_tpu.ops import pallas_kernels as pk
        x = jax.random.normal(key, (512, 512), jnp.float32)
        s = jnp.matmul(x, x.T, precision=HI) / 512 \
            + 4.0 * jnp.eye(512, dtype=jnp.float32)
        t = _slope(lambda d, aux: chol_diag_factor(d) + aux * 0,
                   s, s, est_hint=5e-4 * speed, reps=3, target=0.3)
        emit_ms("micro_chol_panel_512", t)
        if pk.pallas_available(s.dtype):
            t = _slope(lambda d, aux: pk.chol_panel(d) + aux * 0,
                       s, s, est_hint=5e-4 * speed, reps=3, target=0.3)
            emit_ms("micro_pallas_chol_512", t)

    def m_lu_panel():
        # the LU panel wall table (PERF.md Round-4/Round-10): the
        # routed _lu_panel (native custom call where it can compile)
        # vs the rank-1 Pallas kernel vs the block-recursive
        # lu_panel_rec, per-column µs per size. On TPU the widths
        # bracket the production nb choices AND the >NATIVE_LU_MAX_M
        # heights the native call cannot compile at all (there the
        # only exact-pivoting alternatives are fori and the rec
        # kernel); on the CPU tier the kernels run INTERPRETED at
        # reduced sizes — recorded as informational (the TPU numbers
        # ride the consolidated hardware round, ROADMAP).
        from slate_tpu.linalg.lu import _lu_panel, lu_panel_fori
        from slate_tpu.ops import pallas_kernels as pk
        on_tpu = jax.default_backend() not in ("cpu",)
        sizes = [(4096, 128), (4096, 256), (4096, 512)] if on_tpu \
            else [(512, 64), (512, 128)]
        tall = [(16384, 256), (32768, 128)] if on_tpu else [(1024, 64)]
        results["micro_lu_panel_informational"] = not on_tpu

        def line(name, m, w, fn, hint):
            t = _slope(lambda d, aux: fn(d)[0] + aux * 0, p, p,
                       est_hint=hint * speed, reps=3, target=0.3)
            emit_ms("micro_%s_%dx%d" % (name, m, w), t)
            results["micro_%s_%dx%d_uspercol" % (name, m, w)] = \
                round(t * 1e6 / w, 3)

        for m, w in sizes:
            p = jax.random.normal(key, (m, w), jnp.float32)
            line("lu_panel", m, w, _lu_panel, 2e-3)
            if pk.lu_panel(p) is not None:
                line("pallas_lu_panel", m, w, pk.lu_panel, 2e-3)
            if pk.lu_panel_rec(p) is not None:
                line("pallas_lu_panel_rec", m, w, pk.lu_panel_rec,
                     2e-3)
        for m, w in tall:
            # beyond the native height cap: fori (the current exact-
            # pivoting fallback) vs the recursive kernel's split path.
            # The CPU tier forces the split with a reduced budget so
            # the tall machinery is exercised (informational).
            p = jax.random.normal(key, (m, w), jnp.float32)
            # the forced budget must still fit an (m, ib) base panel
            cap = None if on_tpu else m * max(w // 2, 32)
            line("lu_panel_fori", m, w, lu_panel_fori, 2e-2)
            if pk.lu_panel_rec(p, max_elems=cap) is not None:
                line("pallas_lu_panel_rec_tall", m, w,
                     lambda d: pk.lu_panel_rec(d, max_elems=cap),
                     2e-2)

    def m_givens_chain():
        # steqr2/bdsqr sweep accumulation: dense chain compose + one
        # (n, n) matmul vs the blocked Pallas apply (banded (2b, 2b)
        # factors, O(n^2 b) per sweep) — ISSUE 6
        from slate_tpu.linalg.svd import _givens_chain_matrix
        from slate_tpu.ops import pallas_kernels as pk
        on_tpu = jax.default_backend() not in ("cpu",)
        n = 2048 if on_tpu else 512
        th = jax.random.uniform(key, (n - 1,), jnp.float32)
        cs, sn = jnp.cos(th), jnp.sin(th)
        Z = jax.random.normal(key, (n, n), jnp.float32)

        def dense(z, aux):
            G = _givens_chain_matrix(cs, sn, n, jnp.float32)
            return jnp.matmul(z, G, precision=HI) + aux * 0

        t = _slope(dense, Z, Z, est_hint=2e-3 * speed, reps=3,
                   target=0.3)
        emit_ms("micro_givens_dense_n%d" % n, t)
        if pk.givens_chain_eligible(n, n, Z.dtype):
            t = _slope(lambda z, aux: pk.givens_chain_apply(z, cs, sn)
                       + aux * 0, Z, Z, est_hint=2e-3 * speed,
                       reps=3, target=0.3)
            emit_ms("micro_givens_chain_apply_n%d" % n, t)

    def m_trailing():
        # blocked.py claim: dense full-square trailing update beats
        # lower-only variants (m=7680, k=512). The panel is perturbed
        # by the carried state so the matmul cannot be hoisted out of
        # the timing loop as a loop invariant.
        pan = jax.random.normal(key, (7680, 512), jnp.float32)
        x0 = jnp.zeros((7680, 7680), jnp.float32)

        def f(x, pan):
            p2 = pan + x[:, :512] * 1e-30
            return jnp.matmul(p2, p2.T, precision=HI)

        t = _slope(f, x0, pan, est_hint=2e-3 * speed, reps=3,
                   target=0.3)
        emit_ms("micro_dense_trailing_7680x512", t)

    def m_native():
        # methods.py policy inputs: XLA native cholesky/lu/qr at 4096
        x = jax.random.normal(key, (4096, 4096), jnp.float32)
        s = jnp.matmul(x, x.T, precision=HI) / 4096 \
            + 4.0 * jnp.eye(4096, dtype=jnp.float32)
        t = _slope(lambda d, aux: jax.lax.linalg.cholesky(
            d, symmetrize_input=False) * 1e-30 + d, s, s,
            est_hint=5e-3 * speed, reps=3, target=0.4)
        emit_ms("micro_xla_cholesky_4096", t)
        t = _slope(lambda d, aux: jax.lax.linalg.lu(d)[0] * 1e-30 + d,
                   x, x, est_hint=1e-2 * speed, reps=3, target=0.4)
        emit_ms("micro_xla_lu_4096", t)

    guarded("micro_trtri", m_trtri)
    guarded("micro_xla_trisolve", m_xla_trisolve)
    guarded("micro_chol_panel", m_chol_panel)
    guarded("micro_lu_panel", m_lu_panel)
    guarded("micro_givens_chain", m_givens_chain)
    guarded("micro_dense_trailing", m_trailing)
    guarded("micro_native", m_native)


def bench_tune():
    """`--tune`: populate the persistent autotuning cache (ISSUE 1)
    and record before/after numbers into the BENCH trajectory.

    Per op: measure the frozen-defaults configuration (tune.select
    bypassed), run the microbenchmark probe over candidate configs,
    persist the winner (tune.cache), then re-measure with tuned
    selection live. One JSON line per op carries both numbers, the
    chosen config, and whether it differs from the frozen default;
    the final line carries the tune.stats counter snapshot (decisions
    by source, cache hits/misses, probe wall time), so the BENCH_*
    trajectory can attribute every win to a measured decision."""
    import jax
    import numpy as np
    from slate_tpu.tune import cache as tcache
    from slate_tpu.tune import probe, select, stats

    platform = jax.default_backend()
    try:
        n = int(os.environ.get("SLATE_TUNE_N", "0"))
    except ValueError:
        n = 0
    if not n:
        # CPU default 1024: below that the n-scaled frozen defaults
        # are already optimal on the CI box and the run demonstrates
        # nothing; at 1024 the measured winner (nb=1024, ~1.5x over
        # the frozen 512) is genuinely non-default
        n = 2048 if platform == "tpu" else 1024
    cands = [c for c in (64, 128, 256, 512, 1024) if c <= n]
    # potrf is not in the default set: its probed nb is tile-size
    # guidance only (the driver takes nb from the caller's tiles;
    # probe._blocksize_runner) — opt in via SLATE_TUNE_OPS=potrf,...
    ops = [s.strip() for s in os.environ.get(
        "SLATE_TUNE_OPS", "getrf,geqrf").split(",") if s.strip()]
    emit({"tune": "start", "platform": platform, "n": n,
          "candidates": cands, "ops": ops,
          "cache": tcache.cache_path()})

    from slate_tpu.tune.probe import _blocksize_runner

    for op in ops:
        try:
            if op == "heev":
                # method-routing probe: Auto default is the baseline;
                # a staged route is cached only if it beats it
                n_eig = min(n, 512)
                results = probe.probe_method_eig(n_eig, np.float32,
                                                 reps=2)
                auto_t = next(r["seconds"] for r in results
                              if r["method"] == "auto")
                best = results[0]
                non_default = best["method"] != "auto" \
                    and best["seconds"] \
                    < (1.0 - probe.WIN_MARGIN) * auto_t
                if non_default:
                    tcache.get_cache().put(
                        "heev", np.float32, n_eig,
                        {"method_eig": best["method"]},
                        meta={"n": n_eig, "results": results})
                    tcache.get_cache().save()
                emit({"tune": op, "n": n_eig,
                      "before_ms": round(auto_t * 1e3, 3),
                      "after_ms": round(best["seconds"] * 1e3, 3),
                      "default_method": "auto",
                      "chosen_method": best["method"]
                      if non_default else "auto",
                      "non_default": non_default,
                      "speedup": round(
                          auto_t / max(best["seconds"], 1e-12), 3),
                      "results": results})
                continue
            if op == "ooc":
                frozen_w = min(8192, n)      # label only
                cands_ooc = sorted({max(n // 8, 32), max(n // 4, 64),
                                    max(n // 2, 128)})
                # baseline (panel_cols=None, the driver's frozen
                # width) is measured inside the probe
                results = probe.probe_ooc_panel(n, cands_ooc, reps=2)
                before = next(r["seconds"] for r in results
                              if r["panel_cols"] is None)
                best = results[0]
                non_default = best["panel_cols"] is not None \
                    and best["seconds"] \
                    < (1.0 - probe.WIN_MARGIN) * before
                if non_default:
                    tcache.get_cache().put(
                        "ooc", np.float32, n,
                        {"panel_cols": best["panel_cols"]},
                        meta={"n": n, "results": results})
                    tcache.get_cache().save()
                emit({"tune": op, "n": n,
                      "before_ms": round(before * 1e3, 3),
                      "after_ms": round(best["seconds"] * 1e3, 3),
                      "default_panel_cols": frozen_w,
                      "chosen_panel_cols": best["panel_cols"]
                      if non_default else frozen_w,
                      "non_default": non_default,
                      "speedup": round(
                          before / max(best["seconds"], 1e-12), 3),
                      "results": results})
                continue
            # frozen_nb labels the emitted line — taken from the
            # drivers' own helpers, never re-derived here
            if op == "getrf":
                from slate_tpu.linalg.lu import _lu_nb
                with select.disabled():
                    frozen_nb = _lu_nb(None, min(256, n), (n, n),
                                       None)
            elif op == "geqrf":
                from slate_tpu.linalg.qr import geqrf_default_nb
                frozen_nb = geqrf_default_nb(n, min(256, n))
            else:
                frozen_nb = 256
            # probe_blocksize measures the driver's own default path
            # (entry nb=None, cache bypassed) as the baseline every
            # winner must beat — never-regress by construction
            results = probe.probe_blocksize(
                op, n, np.float32, sorted(set(cands) | {frozen_nb}))
            before = next(r["seconds"] for r in results
                          if r["nb"] is None)
            best = results[0]
            # a winner must beat the default baseline beyond the
            # noise margin (which also discards a candidate that is
            # configuration-identical to the baseline, e.g. the
            # explicit frozen nb ranked first by jitter)
            non_default = best["nb"] is not None \
                and best["seconds"] < (1.0 - probe.WIN_MARGIN) * before
            if non_default and op != "geqrf" \
                    and best["nb"] == frozen_nb:
                # for geqrf a Tiled winner at the frozen nb still
                # differs from the Fused default route, so only the
                # non-geqrf ops treat frozen-nb equality as default
                non_default = False
            if non_default:
                chosen = {"nb": best["nb"]}
                if op == "geqrf":
                    # Tiled winner: route the bucket to it (Auto
                    # would take the Fused crossover and skip nb)
                    chosen["fused_max_n"] = 0
                tcache.get_cache().put(op, np.float32, n, chosen,
                                       meta={"n": n,
                                             "results": results})
                tcache.get_cache().save()
            emit({"tune": op, "n": n,
                  "before_ms": round(before * 1e3, 3),
                  "after_ms": round(best["seconds"] * 1e3, 3),
                  "default_nb": frozen_nb,
                  "chosen_nb": best["nb"] if non_default
                  else frozen_nb,
                  "non_default": non_default,
                  "speedup": round(
                      before / max(best["seconds"], 1e-12), 3),
                  "results": results})
        except Exception as e:
            emit({"tune": op, "error": str(e)[:200]})
            import gc
            gc.collect()

    # demonstrate the cached decision being TAKEN: a fresh driver call
    # with default options must now resolve the tuned value and the
    # decision must land in the stats counters
    probe_snap = stats.snapshot()      # keep probe wall time/decisions
    stats.reset()
    try:
        import dataclasses as _dc                       # noqa: F401
        import slate_tpu as st
        from slate_tpu.core.enums import Diag, MatrixType, Op, Uplo
        from slate_tpu.core.tiles import TiledMatrix
        import jax.numpy as jnp
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (n, n), jnp.float32)
        G = TiledMatrix(data=x, m=n, n=n, mb=min(256, n),
                        nb=min(256, n), mtype=MatrixType.General,
                        uplo=Uplo.General, op=Op.NoTrans,
                        diag=Diag.NonUnit)
        jax.block_until_ready(st.getrf(G).LU.data)
    except Exception as e:
        emit({"tune": "decision_check", "error": str(e)[:200]})
    snap = stats.snapshot()
    emit({"metric": "tune", "value": 1, "unit": "suite",
          "vs_baseline": 1,
          "extras": {"probe_seconds": probe_snap["probe_seconds"],
                     "probe_stats": probe_snap["decisions"],
                     "decision_check": snap}})
    return 0


def bench_ooc():
    """`--ooc`: streamed-driver smoke (ISSUE 4) — small-n potrf_ooc +
    getrf_ooc through the stream engine, uncached (budget 0, the
    frozen default = the pre-engine schedule) vs cached (budget
    holding ~3/4 of the factor panels), with the engine's stats (hit
    rate, h2d/d2h bytes, prefetch/writeback overlap fractions,
    eviction/invalidation counts) shipped into the BENCH_*.json
    extras. Numbers come from the obs metrics registry (counter
    deltas around each run) plus stream.last_stats(), so trajectory
    diffs can attribute transfer-volume changes to cache decisions."""
    import numpy as np
    from slate_tpu import obs
    from slate_tpu.linalg import ooc, stream
    from slate_tpu.obs import metrics as om

    obs.enable()
    try:
        n = int(os.environ.get("SLATE_OOC_N", "1024"))
    except ValueError:
        n = 1024
    w = max(n // 8, 32)
    nt = (n + w - 1) // w
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, n)).astype(np.float32)
    a = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)
    g = x + 0.2 * n * np.eye(n, dtype=np.float32)
    b = rng.standard_normal((n, 8)).astype(np.float32)
    budget = 6 * n * w * 4        # ~3nt/4 full f32 panels at nt=8
    extras = {"n": n, "panel_cols": w, "nt": nt,
              "cache_budget_bytes": budget}

    def counters():
        return dict(om.snapshot()["counters"])

    def delta(after, before, key):
        return int(after.get(key, 0) - before.get(key, 0))

    results = {}

    def run(name, fn, budget_bytes, engine_stats=True,
            keep_result=False):
        """engine_stats=False for composite drivers (posv = potrf +
        potrs, TWO engines): stream.last_stats() reflects only the
        last-finished engine, so pairing it with byte deltas that
        span both phases would misattribute — composite records
        carry the cross-phase deltas only. Cache counters for ALL
        engines still accumulate in the obs ooc.cache.* counters,
        which are reported as deltas here too. `keep_result` retains
        the driver's return value for cross-leg comparisons — only
        the solve legs ask for it: at hardware-round n (65536) a
        retained factor is 16 GB, and a dozen of them would OOM a
        host whose premise is that ONE matrix barely fits."""
        c0 = counters()
        t0 = time.perf_counter()
        try:
            out = fn(budget_bytes)
            results[name] = out if keep_result else True
            del out
        except Exception as e:
            extras["%s_error" % name] = str(e)[:160]
            emit({"ooc": name, "error": str(e)[:160]})
            return
        wall = time.perf_counter() - t0
        c1 = counters()
        rec = {"wall_s": round(wall, 3),
               "h2d_bytes": delta(c1, c0, "ooc.h2d_bytes"),
               "d2h_bytes": delta(c1, c0, "ooc.d2h_bytes"),
               "cache_hits": delta(c1, c0, "ooc.cache.hits"),
               "cache_misses": delta(c1, c0, "ooc.cache.misses"),
               "cache_evictions":
                   delta(c1, c0, "ooc.cache.evictions"),
               "cache_invalidations":
                   delta(c1, c0, "ooc.cache.invalidations"),
               "lu_invalidations":
                   delta(c1, c0, "ooc.lu_invalidations"),
               "lu_invalidation_bytes":
                   delta(c1, c0, "ooc.lu_invalidation_bytes"),
               "cast_demote_bytes":
                   delta(c1, c0, "ooc.cast_demote_bytes"),
               "cast_promote_bytes":
                   delta(c1, c0, "ooc.cast_promote_bytes"),
               "mixed_to_full":
                   delta(c1, c0, "resil.fallback.mixed_to_full"),
               "served_bytes":
                   delta(c1, c0, "ooc.cache.served_bytes")}
        if engine_stats:
            s = stream.last_stats()
            rec.update({
                "hit_rate": s.get("hit_rate", 0.0),
                "prefetch_overlap_fraction":
                    s.get("prefetch_overlap_fraction", 0.0),
                "d2h_overlap_fraction":
                    s.get("d2h_overlap_fraction", 0.0)})
        extras[name] = rec
        emit(dict({"ooc": name}, **rec))

    run("potrf_uncached",
        lambda bb: ooc.potrf_ooc(a, panel_cols=w,
                                 cache_budget_bytes=bb), 0)
    run("potrf_cached",
        lambda bb: ooc.potrf_ooc(a, panel_cols=w,
                                 cache_budget_bytes=bb), budget)
    run("getrf_uncached",
        lambda bb: ooc.getrf_ooc(g, panel_cols=w,
                                 cache_budget_bytes=bb), 0)
    run("getrf_cached",
        lambda bb: ooc.getrf_ooc(g, panel_cols=w,
                                 cache_budget_bytes=bb), budget)
    # the tournament-pivot LU stream (ISSUE 10): immutable factor
    # panels, so lu_invalidations stays 0 and the budget actually
    # serves revisits. The diagonally-shifted `g` above never pivots
    # across panels (its fixups are no-ops), so the per-cause delta
    # runs on a row-scaled matrix whose every panel pivots across
    # panel boundaries — the partial path's invalidation storm vs
    # the tournament path's 0, side by side at the same budget
    gp = g * (1.0 + np.arange(n, dtype=np.float32))[:, None]
    run("getrf_pivoting_cached",
        lambda bb: ooc.getrf_ooc(gp, panel_cols=w,
                                 cache_budget_bytes=bb), budget)
    run("getrf_tntpiv_pivoting_cached",
        lambda bb: ooc.getrf_tntpiv_ooc(gp, panel_cols=w,
                                        cache_budget_bytes=bb),
        budget)
    run("posv_cached",
        lambda bb: ooc.posv_ooc(a, b, panel_cols=w,
                                cache_budget_bytes=bb), budget,
        engine_stats=False)      # two engines: deltas only
    # mixed-precision legs (ISSUE 12): bf16 residency vs the f32
    # stream at EQUAL cache budget. The pair runs in the thrash/fit
    # regime — a budget holding 3 f32 panels (the f32 stream must
    # re-upload evicted revisits) holds 6 demoted ones (bf16 revisits
    # mostly hit, and the uploads that remain ship half the bytes) —
    # which is exactly where the byte/flop win lives; the solve legs
    # price the refinement's accuracy contract against the f32
    # answers (residual <= 1e-5 or a recorded mixed_to_full
    # escalation, the acceptance gate)
    pbudget = 3 * n * w * 4
    extras["precision_budget_bytes"] = pbudget
    # the f32 baselines are PINNED explicit — once a measured bf16
    # ooc/precision entry lands in the tune cache (the outcome these
    # legs exist to justify), an Auto baseline would silently become
    # a vacuous bf16-vs-bf16 comparison
    run("potrf_f32_eqbudget",
        lambda bb: ooc.potrf_ooc(a, panel_cols=w,
                                 cache_budget_bytes=bb,
                                 precision="f32"), pbudget)
    run("potrf_bf16_eqbudget",
        lambda bb: ooc.potrf_ooc(a, panel_cols=w,
                                 cache_budget_bytes=bb,
                                 precision="bf16"), pbudget)
    run("posv_f32",
        lambda bb: ooc.posv_ooc(a, b, panel_cols=w,
                                cache_budget_bytes=bb,
                                precision="f32"), budget,
        engine_stats=False, keep_result=True)
    run("posv_bf16",
        lambda bb: ooc.posv_ooc(a, b, panel_cols=w,
                                cache_budget_bytes=bb,
                                precision="bf16"), budget,
        engine_stats=False, keep_result=True)
    run("gesv_bf16",
        lambda bb: ooc.gesv_ooc(g, b, panel_cols=w,
                                cache_budget_bytes=bb,
                                precision="bf16"), budget,
        engine_stats=False, keep_result=True)
    run("gesv_f32",
        lambda bb: ooc.gesv_ooc(g, b, panel_cols=w,
                                cache_budget_bytes=bb,
                                precision="f32"), budget,
        engine_stats=False, keep_result=True)
    ok = True
    pf, pb = extras.get("potrf_f32_eqbudget"), \
        extras.get("potrf_bf16_eqbudget")
    if pf and pb and pf.get("h2d_bytes"):
        red = 1.0 - pb["h2d_bytes"] / pf["h2d_bytes"]
        extras["precision_h2d_reduction"] = round(red, 4)
        ok &= red >= 0.40            # acceptance: >= 40% at equal
        #                              budget on the CPU protocol
    else:
        ok = False

    def _rel(name_lo, name_hi, pick):
        if name_lo not in results or name_hi not in results:
            return None
        xb, xf = pick(results[name_lo]), pick(results[name_hi])
        return float(np.abs(xb - xf).max()
                     / max(np.abs(xf).max(), 1e-30))

    rel_posv = _rel("posv_bf16", "posv_f32", lambda r: r[1])
    rel_gesv = _rel("gesv_bf16", "gesv_f32", lambda r: r[1])
    # the escalation excuse is PER LEG (the run() rec's own counter
    # delta): one leg's legitimate mixed_to_full fallback must not
    # blanket-pass another leg's unconverged-but-unescalated answer
    for key, rel, leg in (
            ("precision_posv_rel_vs_f32", rel_posv, "posv_bf16"),
            ("precision_gesv_rel_vs_f32", rel_gesv, "gesv_bf16")):
        if rel is None:
            ok = False
            continue
        extras[key] = rel
        ok &= rel <= 1e-5 \
            or extras.get(leg, {}).get("mixed_to_full", 0) > 0
    # the refine sweep count (obs satellite): how many lo-solve
    # corrections the bf16 answers needed
    extras["refine_ooc_iters"] = \
        om.snapshot()["histograms"].get("refine.ooc.iters")
    extras["precision_ok"] = ok
    pu, pc = extras.get("potrf_uncached"), extras.get("potrf_cached")
    if pu and pc and pu.get("h2d_bytes"):
        extras["potrf_h2d_reduction"] = round(
            1.0 - pc["h2d_bytes"] / pu["h2d_bytes"], 4)
    gc, gt = extras.get("getrf_pivoting_cached"), \
        extras.get("getrf_tntpiv_pivoting_cached")
    if gc and gt:
        # the per-cause delta: bytes the partial path's row-swap
        # fixups evicted (re-uploaded later) that the tournament
        # path never pays
        extras["getrf_lu_invalidation_bytes_removed"] = \
            gc.get("lu_invalidation_bytes", 0) \
            - gt.get("lu_invalidation_bytes", 0)
        if gc.get("h2d_bytes"):
            extras["getrf_tntpiv_h2d_reduction_vs_partial"] = round(
                1.0 - gt["h2d_bytes"] / gc["h2d_bytes"], 4)
    emit({"metric": "ooc", "value": 1 if ok else 0, "unit": "suite",
          "vs_baseline": 1 if ok else 0, "extras": extras})
    return 0


def bench_shard():
    """`--shard`: the sharded out-of-core layer (ISSUE 7) —
    shard_potrf_ooc / shard_geqrf_ooc over a grid spanning every
    local device vs the single-engine stream, with per-host staging
    bytes (obs ooc.h2d_bytes deltas — one host here; the 2-process
    protocol lives in tests/test_shard_multiproc.py), the ownership
    schedule's exact byte prediction, tree-broadcast counts
    (ooc.shard.bcast_* + the scheduled ppermutes), spill counts and
    overlap fractions in the BENCH extras. The lookahead depth sweep
    (ISSUE 11: *_shard_la1 / potrf_shard_la2 legs vs the FROZEN
    depth-0 *_shard baselines) reports per-leg broadcast-wait wall,
    update-compute wall, overlap fraction, and H2D bytes — bitwise
    equality and the exact-schedule prediction are ASSERTED at every
    depth, and the spill-regime overlap probe (nt=16) gates the
    suite on the depth-1 overlap-fraction gain; the absolute
    broadcast-wait walls are REPORTED, not gated (2-core-box flap,
    PERF Round-13 — the TPU round judges them). On the CPU tier
    main() pins 8 virtual devices before jax initializes; on real
    hardware the grid is whatever the process sees."""
    import numpy as np
    import jax
    from slate_tpu import obs
    import slate_tpu as st
    from slate_tpu.dist import shard_ooc
    from slate_tpu.dist.tree import schedule_ppermutes
    from slate_tpu.linalg import ooc, stream
    from slate_tpu.obs import metrics as om

    obs.enable()
    try:
        n = int(os.environ.get("SLATE_SHARD_N", "1024"))
    except ValueError:
        n = 1024
    w = max(n // 8, 32)
    nt = (n + w - 1) // w
    grid = st.make_grid()
    nranks = grid.p * grid.q
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, n)).astype(np.float32)
    a = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)
    g = x + 0.2 * n * np.eye(n, dtype=np.float32)
    budget = 64 * n * w * 4
    extras = {"n": n, "panel_cols": w, "nt": nt,
              "grid": [grid.p, grid.q],
              "cache_budget_bytes": budget,
              "tree_ppermutes_per_bcast":
                  schedule_ppermutes(nranks, 2)}

    def counters():
        return dict(om.snapshot()["counters"])

    def delta(after, before, key):
        return int(after.get(key, 0) - before.get(key, 0))

    results = {}

    def fdelta(after, before, key):
        return float(after.get(key, 0.0) - before.get(key, 0.0))

    def run(name, fn):
        c0 = counters()
        t0 = time.perf_counter()
        try:
            out = fn()
        except Exception as e:
            extras["%s_error" % name] = str(e)[:160]
            emit({"shard": name, "error": str(e)[:160]})
            return None
        wall = time.perf_counter() - t0
        c1 = counters()
        s = stream.last_stats()
        # lookahead attribution (ISSUE 11): the per-leg broadcast-wait
        # wall, issue-to-completion wall, ahead-issue count, and the
        # overlap fraction the depth sweep is judged on
        bwait = fdelta(c1, c0, "ooc.shard.bcast_wait_seconds")
        bflight = fdelta(c1, c0, "ooc.shard.bcast_inflight_seconds")
        rec = {"wall_s": round(wall, 3),
               "h2d_bytes": delta(c1, c0, "ooc.h2d_bytes"),
               "d2h_bytes": delta(c1, c0, "ooc.d2h_bytes"),
               "bcast_panels": delta(c1, c0, "ooc.shard.bcast_panels"),
               "bcast_bytes": delta(c1, c0, "ooc.shard.bcast_bytes"),
               "bcast_ahead": delta(c1, c0, "ooc.shard.bcast_ahead"),
               "bcast_compiles":
                   delta(c1, c0, "ooc.shard.bcast_compiles"),
               "bcast_wait_s": round(bwait, 4),
               "bcast_inflight_s": round(bflight, 4),
               "bcast_overlap_fraction":
                   round(max(0.0, 1.0 - bwait / bflight), 4)
                   if bflight > 0 else 0.0,
               "update_s": round(
                   fdelta(c1, c0, "ooc.shard.update_seconds"), 4),
               "ppermutes_scheduled":
                   delta(c1, c0, "comms.ppermute.scheduled"),
               "lu_invalidations":
                   delta(c1, c0, "ooc.lu_invalidations"),
               "lu_invalidation_bytes":
                   delta(c1, c0, "ooc.lu_invalidation_bytes"),
               "spills": s.get("spills", 0),
               "prefetch_overlap_fraction":
                   s.get("prefetch_overlap_fraction", 0.0),
               "d2h_overlap_fraction":
                   s.get("d2h_overlap_fraction", 0.0)}
        extras[name] = rec
        emit(dict({"shard": name}, **rec))
        results[name] = out
        return out

    sched = shard_ooc.CyclicSchedule(nt, grid)
    extras["my_panels"] = sched.my_panels()
    extras["expected_shard_h2d_bytes"] = sched.staged_bytes(
        {k: n - k * w for k in range(nt)}, w, n - (nt - 1) * w, 4)
    # the QR and LU streams stage FULL-height columns (QR panel
    # states / original-row-order store, ISSUE 10), so their
    # per-host predictions use height m
    extras["expected_shard_fullheight_h2d_bytes"] = \
        sched.staged_bytes({k: n for k in range(nt)}, w,
                           n - (nt - 1) * w, 4)
    extras["expected_shard_getrf_h2d_bytes"] = \
        extras["expected_shard_fullheight_h2d_bytes"]
    # the pivot mode the cold/tuned cache resolves for this size —
    # recorded so the TPU hardware round can earn (or refuse) a
    # measured ooc/lu_pivot entry against these numbers
    from slate_tpu.core.methods import MethodLUPivot
    extras["lu_pivot_resolved"] = MethodLUPivot.resolve(
        n, np.float32).value
    run("potrf_single",
        lambda: ooc.potrf_ooc(a, panel_cols=w, cache_budget_bytes=0))
    # equal-budget single-engine legs: on a SINGLE-process mesh every
    # panel is "mine", so shard-vs-uncached mostly measures the
    # residency cache; the apples-to-apples sharding delta is against
    # the single engine at the SAME budget (the per-host split needs
    # a real multi-process mesh — tests/test_shard_multiproc.py)
    run("potrf_single_cached",
        lambda: ooc.potrf_ooc(a, panel_cols=w,
                              cache_budget_bytes=budget))
    run("potrf_shard",
        lambda: shard_ooc.shard_potrf_ooc(
            a, grid, panel_cols=w, cache_budget_bytes=budget))
    run("geqrf_single",
        lambda: ooc.geqrf_ooc(g, panel_cols=w, cache_budget_bytes=0))
    run("geqrf_single_cached",
        lambda: ooc.geqrf_ooc(g, panel_cols=w,
                              cache_budget_bytes=budget))
    run("geqrf_shard",
        lambda: shard_ooc.shard_geqrf_ooc(
            g, grid, panel_cols=w, cache_budget_bytes=budget))
    # LU legs (ISSUE 10): the uncached partial-pivot single engine
    # (the fixup/invalidation baseline), the equal-budget partial
    # engine (shows the invalidation storm eating the cache), the
    # tournament single engine at equal budget, and the sharded
    # tournament stream
    run("getrf_single",
        lambda: ooc.getrf_ooc(g, panel_cols=w, cache_budget_bytes=0))
    run("getrf_single_cached",
        lambda: ooc.getrf_ooc(g, panel_cols=w,
                              cache_budget_bytes=budget))
    run("getrf_tntpiv_cached",
        lambda: ooc.getrf_tntpiv_ooc(g, panel_cols=w,
                                     cache_budget_bytes=budget))
    run("getrf_shard",
        lambda: shard_ooc.shard_getrf_ooc(
            g, grid, panel_cols=w, cache_budget_bytes=budget))
    # lookahead depth sweep (ISSUE 11): the *_shard legs above run at
    # the FROZEN depth 0 (the synchronous baseline); these re-run the
    # same problems with 1 and 2 broadcast frames in flight. The per-
    # leg extras carry the broadcast-wait wall, overlap fraction, and
    # H2D bytes the TPU round prices a nonzero default against
    run("potrf_shard_la1",
        lambda: shard_ooc.shard_potrf_ooc(
            a, grid, panel_cols=w, cache_budget_bytes=budget,
            lookahead=1))
    run("potrf_shard_la2",
        lambda: shard_ooc.shard_potrf_ooc(
            a, grid, panel_cols=w, cache_budget_bytes=budget,
            lookahead=2))
    run("geqrf_shard_la1",
        lambda: shard_ooc.shard_geqrf_ooc(
            g, grid, panel_cols=w, cache_budget_bytes=budget,
            lookahead=1))
    run("getrf_shard_la1",
        lambda: shard_ooc.shard_getrf_ooc(
            g, grid, panel_cols=w, cache_budget_bytes=budget,
            lookahead=1))
    # mixed-precision leg (ISSUE 12): the bf16 broadcast frames —
    # every ppermute hop carries half the payload bytes (the
    # deterministic halving the TPU round prices against accuracy);
    # the factor itself is bf16-update-grade, compared loosely
    run("potrf_shard_bf16",
        lambda: shard_ooc.shard_potrf_ooc(
            a, grid, panel_cols=w, cache_budget_bytes=budget,
            precision="bf16"))

    ok = True
    # overlap probe (ISSUE 11 acceptance): the eviction-free legs
    # above have near-zero per-step host work after step 0, so the
    # CPU protocol's async dispatch already hides most update
    # execution under the depth-0 wait — the wait delta only shows
    # where each step does real synchronous staging. Probe in the
    # SPILL regime (nt = 16 >= 8, a 3-panel budget re-stages the
    # trailing shard every step), median of 3 alternating reps per
    # depth; the overlap-fraction gain is the gated criterion and
    # the wait walls are the reported data (see the gate comment
    # below)
    n2 = 2 * n
    w2 = max(n2 // 16, 32)
    x2 = rng.standard_normal((n2, n2)).astype(np.float32)
    a2 = x2 @ x2.T / n2 + 4.0 * np.eye(n2, dtype=np.float32)
    budget2 = 3 * n2 * w2 * 4
    try:
        for d in (0, 1):          # warm every program first
            shard_ooc.shard_potrf_ooc(a2, grid, panel_cols=w2,
                                      cache_budget_bytes=budget2,
                                      lookahead=d)
        waits = {0: [], 1: []}
        fracs = {0: [], 1: []}
        for _rep in range(3):
            for d in (0, 1):
                c0 = counters()
                shard_ooc.shard_potrf_ooc(
                    a2, grid, panel_cols=w2,
                    cache_budget_bytes=budget2, lookahead=d)
                c1 = counters()
                bw = fdelta(c1, c0, "ooc.shard.bcast_wait_seconds")
                bf = fdelta(c1, c0,
                            "ooc.shard.bcast_inflight_seconds")
                waits[d].append(bw)
                fracs[d].append(max(0.0, 1.0 - bw / bf)
                                if bf > 0 else 0.0)
        med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
        # compare the UNROUNDED medians — on hardware where the wait
        # wall is microseconds, rounding first would zero the
        # baseline and make the strict reduction unpassable
        w0, w1 = med(waits[0]), med(waits[1])
        f0, f1 = med(fracs[0]), med(fracs[1])
        probe = {"n": n2, "panel_cols": w2, "nt": n2 // w2,
                 "cache_budget_bytes": budget2,
                 "la0_wait_s": round(w0, 6),
                 "la1_wait_s": round(w1, 6),
                 "la0_overlap_fraction": round(f0, 4),
                 "la1_overlap_fraction": round(f1, 4)}
        probe["wait_reduced"] = w1 < w0
        probe["wait_reduction"] = round(1.0 - w1 / w0, 4) \
            if w0 > 0 else 0.0
        probe["overlap_gain"] = round(f1 - f0, 4)
        extras["potrf_overlap_probe"] = probe
        emit(dict({"shard": "potrf_overlap_probe"}, **probe))
        # gate on the overlap-fraction gain (5-13x on every CPU-tier
        # rep — the window the schedule opens is robustly
        # attributable); the absolute wait delta is REPORTED but not
        # gated: on a 2-core box the 8 virtual devices' collective IS
        # host compute, so a 3-rep median flaps ±10% with no code
        # defect (PERF Round-13 records +8.4% median-of-3 when quiet;
        # the TPU round judges the wall on real DMA/ICI pipes)
        ok &= probe["overlap_gain"] > 0.05
    except Exception as e:
        extras["potrf_overlap_probe_error"] = str(e)[:160]
        ok = False

    # flight-recorder attribution leg (ISSUE 14 acceptance): re-run
    # the depth-1 sharded potrf with the obs/ledger.py recorder on
    # and require >= 95% of the measured driver wall attributed to
    # the named step phases (factor/update/bcast_wait/stage/cache/
    # other — the per-step split is exhaustive, so the fraction
    # measures how much of the run the step loop actually covers)
    from slate_tpu.obs import ledger as obs_ledger
    from slate_tpu.obs import xprof as obs_xprof
    try:
        obs_ledger.reset()
        obs_ledger.enable()
        t0 = time.perf_counter()
        shard_ooc.shard_potrf_ooc(a, grid, panel_cols=w,
                                  cache_budget_bytes=budget,
                                  lookahead=1)
        wall = time.perf_counter() - t0
        att = obs_xprof.attribute_run(
            records=obs_ledger.records("shard_potrf_ooc"))
        frac = att["total_wall_s"] / wall if wall > 0 else 0.0
        rec = {"wall_s": round(wall, 4),
               "ledger_records": att["records"],
               "attributed_s": att["total_wall_s"],
               "fraction_attributed": round(frac, 4),
               "buckets": att["buckets"],
               "compile_s": att["compile_s"],
               "slowest_panel": (att["top_panels"] or [None])[0]}
        extras["ledger_attribution"] = rec
        emit(dict({"shard": "ledger_attribution"}, **rec))
        ok &= frac >= 0.95
    except Exception as e:
        extras["ledger_attribution_error"] = str(e)[:160]
        ok = False
    finally:
        obs_ledger.reset()

    # every leg must have RUN for the suite to emit green — run()
    # swallows a leg's exception into extras, which must read as
    # failure, not as a vacuously-passed comparison
    ok &= len(results) == 15
    if "potrf_shard" in results and "potrf_shard_bf16" in results:
        ph, pm = extras["potrf_shard"], extras["potrf_shard_bf16"]
        if ph.get("bcast_bytes"):
            red = 1.0 - pm["bcast_bytes"] / ph["bcast_bytes"]
            extras["potrf_bf16_bcast_reduction"] = round(red, 4)
            ok &= red >= 0.45        # frames demote exactly 2x
        close = bool(np.allclose(results["potrf_shard"],
                                 results["potrf_shard_bf16"],
                                 rtol=5e-2, atol=5e-2))
        extras["potrf_bf16_allclose_loose"] = close
        ok &= close
    if "potrf_single" in results and "potrf_shard" in results:
        p_ok = bool(np.allclose(results["potrf_single"],
                                results["potrf_shard"],
                                rtol=1e-5, atol=1e-5))
        extras["potrf_allclose"] = p_ok
        ok &= p_ok
        ps, ph = extras["potrf_single"], extras["potrf_shard"]
        if ps.get("h2d_bytes"):
            extras["potrf_h2d_reduction_vs_uncached"] = round(
                1.0 - ph["h2d_bytes"] / ps["h2d_bytes"], 4)
        pc = extras.get("potrf_single_cached")
        if pc and pc.get("h2d_bytes"):
            extras["potrf_h2d_reduction_vs_cached"] = round(
                1.0 - ph["h2d_bytes"] / pc["h2d_bytes"], 4)
        extras["potrf_h2d_exact_schedule"] = \
            ph["h2d_bytes"] == extras["expected_shard_h2d_bytes"]
    if "geqrf_single" in results and "geqrf_shard" in results:
        q_ok = bool(np.allclose(results["geqrf_single"][0],
                                results["geqrf_shard"][0],
                                rtol=1e-4, atol=1e-4))
        extras["geqrf_allclose"] = q_ok
        ok &= q_ok
    if "getrf_tntpiv_cached" in results and "getrf_shard" in results:
        # acceptance (ISSUE 10): sharded LU bitwise == the
        # single-engine tournament stream at the same pivot mode,
        # per-host staged bytes exactly the schedule prediction, and
        # the H2D reduction vs the uncached single engine in the
        # potrf/geqrf band
        lt, pt = results["getrf_tntpiv_cached"], results["getrf_shard"]
        g_ok = bool(np.array_equal(lt[0], pt[0])
                    and np.array_equal(lt[1], pt[1]))
        extras["getrf_shard_bitwise_vs_tntpiv"] = g_ok
        ok &= g_ok
        from slate_tpu.linalg.ooc import _swaps_to_perm
        perm = _swaps_to_perm(pt[1], n)
        L = np.tril(pt[0], -1) + np.eye(n, dtype=np.float32)
        resid = float(np.abs(g[perm] - L @ np.triu(pt[0])).max()
                      / max(np.abs(g).max(), 1.0))
        extras["getrf_shard_relative_residual"] = resid
        ok &= resid < 1e-4
        gs, gh = extras.get("getrf_single"), extras["getrf_shard"]
        if gs and gs.get("h2d_bytes"):
            extras["getrf_h2d_reduction_vs_uncached"] = round(
                1.0 - gh["h2d_bytes"] / gs["h2d_bytes"], 4)
        gc = extras.get("getrf_single_cached")
        if gc and gc.get("h2d_bytes"):
            extras["getrf_h2d_reduction_vs_cached"] = round(
                1.0 - gh["h2d_bytes"] / gc["h2d_bytes"], 4)
        extras["getrf_h2d_exact_schedule"] = \
            gh["h2d_bytes"] == extras["expected_shard_getrf_h2d_bytes"]
    # lookahead acceptance (ISSUE 11): every depth is BITWISE the
    # depth-0 schedule and stages exactly the (depth-invariant)
    # schedule prediction — both asserted here; the overlap criterion
    # is gated by the probe above
    if "potrf_shard" in results:
        for leg in ("potrf_shard_la1", "potrf_shard_la2"):
            if leg not in results:
                continue
            bit = bool(np.array_equal(results["potrf_shard"],
                                      results[leg]))
            extras["%s_bitwise_vs_la0" % leg] = bit
            ok &= bit
            exact = extras[leg]["h2d_bytes"] \
                == extras["expected_shard_h2d_bytes"]
            extras["%s_h2d_exact_schedule" % leg] = exact
            ok &= exact
    if "geqrf_shard" in results and "geqrf_shard_la1" in results:
        q0, q1 = results["geqrf_shard"], results["geqrf_shard_la1"]
        bit = bool(np.array_equal(q0[0], q1[0])
                   and np.array_equal(q0[1], q1[1]))
        extras["geqrf_shard_la1_bitwise_vs_la0"] = bit
        ok &= bit
        extras["geqrf_shard_la1_h2d_exact_schedule"] = \
            extras["geqrf_shard_la1"]["h2d_bytes"] \
            == extras["expected_shard_fullheight_h2d_bytes"]
        ok &= extras["geqrf_shard_la1_h2d_exact_schedule"]
    if "getrf_shard" in results and "getrf_shard_la1" in results:
        l0, l1 = results["getrf_shard"], results["getrf_shard_la1"]
        bit = bool(np.array_equal(l0[0], l1[0])
                   and np.array_equal(l0[1], l1[1]))
        extras["getrf_shard_la1_bitwise_vs_la0"] = bit
        ok &= bit
        extras["getrf_shard_la1_h2d_exact_schedule"] = \
            extras["getrf_shard_la1"]["h2d_bytes"] \
            == extras["expected_shard_getrf_h2d_bytes"]
        ok &= extras["getrf_shard_la1_h2d_exact_schedule"]
    emit({"metric": "shard", "value": 1 if ok else 0,
          "unit": "suite", "vs_baseline": 1 if ok else 0,
          "extras": extras})
    return 0


def bench_graph():
    """`--graph`: the task-graph runtime (ISSUE 17) — scheduler
    "graph" vs the FROZEN "walk" on the same problems, single-engine
    and sharded. Reports per-leg wall, node counts, and the pure
    issue-loop overhead per node (sched.issue_overhead_seconds /
    sched.nodes_issued — the scheduling cost the construct-then-
    execute route adds over the hand-written loops). GATES on (a)
    bitwise equality of every graph/walk pair, (b) the sharded graph
    leg staging exactly the ownership schedule's (depth-invariant)
    byte prediction, and (c) >= 95% of the graph sharded potrf wall
    attributed to named ledger phases — the flight-recorder contract
    carried onto the graph route (node kinds map 1:1 onto PHASES).
    Walls are REPORTED, not gated (2-core-box flap; the TPU round
    judges them)."""
    import numpy as np
    from slate_tpu import obs
    import slate_tpu as st
    from slate_tpu.dist import shard_ooc
    from slate_tpu.linalg import ooc
    from slate_tpu.obs import metrics as om

    obs.enable()
    try:
        n = int(os.environ.get("SLATE_GRAPH_N", "1024"))
    except ValueError:
        n = 1024
    w = max(n // 8, 32)
    nt = (n + w - 1) // w
    grid = st.make_grid()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, n)).astype(np.float32)
    a = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)
    g = x + 0.2 * n * np.eye(n, dtype=np.float32)
    budget = 64 * n * w * 4
    extras = {"n": n, "panel_cols": w, "nt": nt,
              "grid": [grid.p, grid.q],
              "cache_budget_bytes": budget}

    def counters():
        return dict(om.snapshot()["counters"])

    results = {}

    def run(name, fn):
        c0 = counters()
        t0 = time.perf_counter()
        try:
            out = fn()
        except Exception as e:
            extras["%s_error" % name] = str(e)[:160]
            emit({"graph": name, "error": str(e)[:160]})
            return None
        wall = time.perf_counter() - t0
        c1 = counters()
        nodes = int(c1.get("sched.nodes_issued", 0)
                    - c0.get("sched.nodes_issued", 0))
        over = float(c1.get("sched.issue_overhead_seconds", 0)
                     - c0.get("sched.issue_overhead_seconds", 0))
        rec = {"wall_s": round(wall, 4),
               "h2d_bytes": int(c1.get("ooc.h2d_bytes", 0)
                                - c0.get("ooc.h2d_bytes", 0)),
               "nodes_issued": nodes,
               "issue_overhead_s": round(over, 6),
               "issue_overhead_per_node_us":
                   round(1e6 * over / nodes, 3) if nodes else 0.0}
        extras[name] = rec
        emit(dict({"graph": name}, **rec))
        results[name] = out
        return out

    # single-engine pairs (same budget, walk then graph)
    run("potrf_walk",
        lambda: ooc.potrf_ooc(a, panel_cols=w,
                              cache_budget_bytes=budget,
                              scheduler="walk"))
    run("potrf_graph",
        lambda: ooc.potrf_ooc(a, panel_cols=w,
                              cache_budget_bytes=budget,
                              scheduler="graph"))
    run("geqrf_walk",
        lambda: ooc.geqrf_ooc(g, panel_cols=w,
                              cache_budget_bytes=budget,
                              scheduler="walk"))
    run("geqrf_graph",
        lambda: ooc.geqrf_ooc(g, panel_cols=w,
                              cache_budget_bytes=budget,
                              scheduler="graph"))
    run("getrf_walk",
        lambda: ooc.getrf_tntpiv_ooc(g, panel_cols=w,
                                     cache_budget_bytes=budget,
                                     scheduler="walk"))
    run("getrf_graph",
        lambda: ooc.getrf_tntpiv_ooc(g, panel_cols=w,
                                     cache_budget_bytes=budget,
                                     scheduler="graph"))
    # sharded pair at lookahead 1 (the depth where the graph's
    # slot-keyed issue order actually interleaves work)
    run("potrf_shard_walk",
        lambda: shard_ooc.shard_potrf_ooc(
            a, grid, panel_cols=w, cache_budget_bytes=budget,
            lookahead=1, scheduler="walk"))
    run("potrf_shard_graph",
        lambda: shard_ooc.shard_potrf_ooc(
            a, grid, panel_cols=w, cache_budget_bytes=budget,
            lookahead=1, scheduler="graph"))

    ok = True
    for base in ("potrf", "geqrf", "getrf"):
        wv, gv = results.get(base + "_walk"), \
            results.get(base + "_graph")
        if wv is None or gv is None:
            ok = False
            continue
        if base == "potrf":
            bit = bool(np.array_equal(wv, gv))
        else:
            bit = bool(np.array_equal(np.asarray(wv[0]),
                                      np.asarray(gv[0]))
                       and np.array_equal(np.asarray(wv[1]),
                                          np.asarray(gv[1])))
        extras["%s_graph_bitwise" % base] = bit
        ok &= bit
    if results.get("potrf_shard_walk") is not None \
            and results.get("potrf_shard_graph") is not None:
        bit = bool(np.array_equal(results["potrf_shard_walk"],
                                  results["potrf_shard_graph"]))
        extras["potrf_shard_graph_bitwise"] = bit
        ok &= bit
        sched = shard_ooc.CyclicSchedule(nt, grid)
        expect = sched.staged_bytes(
            {k: n - k * w for k in range(nt)}, w,
            n - (nt - 1) * w, 4, depth=1)
        exact = extras["potrf_shard_graph"]["h2d_bytes"] == expect
        extras["potrf_shard_graph_h2d_exact_schedule"] = exact
        ok &= exact
    else:
        ok = False
    # walk-vs-graph wall + per-node overhead summary (reported)
    for pair in (("potrf", "potrf"), ("potrf_shard", "potrf_shard")):
        wrec = extras.get(pair[0] + "_walk")
        grec = extras.get(pair[1] + "_graph")
        if wrec and grec and wrec["wall_s"] > 0:
            extras["%s_graph_wall_ratio" % pair[1]] = round(
                grec["wall_s"] / wrec["wall_s"], 4)

    # ledger attribution on the GRAPH route (ISSUE 17 acceptance):
    # node frames land in the same closed phase columns as the walk,
    # so >= 95% of the sharded graph wall stays attributed
    from slate_tpu.obs import ledger as obs_ledger
    from slate_tpu.obs import xprof as obs_xprof
    try:
        obs_ledger.reset()
        obs_ledger.enable()
        t0 = time.perf_counter()
        shard_ooc.shard_potrf_ooc(a, grid, panel_cols=w,
                                  cache_budget_bytes=budget,
                                  lookahead=1, scheduler="graph")
        wall = time.perf_counter() - t0
        att = obs_xprof.attribute_run(
            records=obs_ledger.records("shard_potrf_ooc"))
        frac = att["total_wall_s"] / wall if wall > 0 else 0.0
        rec = {"wall_s": round(wall, 4),
               "ledger_records": att["records"],
               "attributed_s": att["total_wall_s"],
               "fraction_attributed": round(frac, 4),
               "buckets": att["buckets"]}
        extras["graph_ledger_attribution"] = rec
        emit(dict({"graph": "ledger_attribution"}, **rec))
        ok &= frac >= 0.95
    except Exception as e:
        extras["graph_ledger_attribution_error"] = str(e)[:160]
        ok = False
    finally:
        obs_ledger.disable()
        obs_ledger.reset()

    emit({"metric": "graph", "value": 1 if ok else 0,
          "unit": "suite", "vs_baseline": 1 if ok else 0,
          "extras": extras})
    return 0


def bench_fuse():
    """`--fuse`: fused visit sweeps (ISSUE 20) — visit_fuse="fused"
    vs the FROZEN "per_panel" walk on the same problems. GATES on
    (a) >= 60% fewer update dispatches at nt=16 (measured by the
    ooc.visits_fused / ooc.visit_dispatches_saved coalescing
    counters against the nt*(nt-1)/2 per-panel visit count; the
    left-looking ladder's actual reduction is 87.5%), (b) numeric
    agreement per op at the route's documented grade (geqrf BITWISE
    — the fused sweep is the per-panel kernel under a scan; potrf /
    getrf allclose — the wide GEMM reassociates; getrf pivots
    IDENTICAL), (c) the jit cache bounded by the count-bucket
    ladder: a same-shape rerun adds ZERO visit_fuse_compiles and
    ZERO jit.recompiles, (d) the sharded fused route bitwise vs the
    sharded walk with >= 95% of its wall attributed to named ledger
    phases. Issue-loop overhead per node is REPORTED against the
    unfused graph route (the fused graph has fewer, fatter nodes).
    With ``--obs`` also on the command line, the regression leg
    compares these extras against the checked-in BENCH trajectory."""
    import numpy as np
    from slate_tpu import obs
    import slate_tpu as st
    from slate_tpu.dist import shard_ooc
    from slate_tpu.linalg import ooc
    from slate_tpu.obs import metrics as om

    obs.enable()
    try:
        n = int(os.environ.get("SLATE_FUSE_N", "1024"))
    except ValueError:
        n = 1024
    w = max(n // 16, 32)
    nt = (n + w - 1) // w
    grid = st.make_grid()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, n)).astype(np.float32)
    a = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)
    g = x + 0.2 * n * np.eye(n, dtype=np.float32)
    budget = 64 * n * w * 4
    extras = {"n": n, "panel_cols": w, "nt": nt,
              "grid": [grid.p, grid.q],
              "cache_budget_bytes": budget}

    def counters():
        return dict(om.snapshot()["counters"])

    results = {}

    def run(name, fn):
        c0 = counters()
        t0 = time.perf_counter()
        try:
            out = fn()
        except Exception as e:
            extras["%s_error" % name] = str(e)[:160]
            emit({"fuse": name, "error": str(e)[:160]})
            return None
        wall = time.perf_counter() - t0
        c1 = counters()
        nodes = int(c1.get("sched.nodes_issued", 0)
                    - c0.get("sched.nodes_issued", 0))
        over = float(c1.get("sched.issue_overhead_seconds", 0)
                     - c0.get("sched.issue_overhead_seconds", 0))
        rec = {"wall_s": round(wall, 4),
               "nodes_issued": nodes,
               "issue_overhead_s": round(over, 6),
               "issue_overhead_per_node_us":
                   round(1e6 * over / nodes, 3) if nodes else 0.0,
               "visits_fused": int(c1.get("ooc.visits_fused", 0)
                                   - c0.get("ooc.visits_fused", 0)),
               "dispatches_saved": int(
                   c1.get("ooc.visit_dispatches_saved", 0)
                   - c0.get("ooc.visit_dispatches_saved", 0)),
               "fuse_compiles": int(
                   c1.get("ooc.visit_fuse_compiles", 0)
                   - c0.get("ooc.visit_fuse_compiles", 0)),
               "jit_recompiles": int(c1.get("jit.recompiles", 0)
                                     - c0.get("jit.recompiles", 0))}
        extras[name] = rec
        emit(dict({"fuse": name}, **rec))
        results[name] = out
        return out

    run("potrf_per_panel",
        lambda: ooc.potrf_ooc(a, panel_cols=w,
                              cache_budget_bytes=budget,
                              visit_fuse="per_panel"))
    run("potrf_fused",
        lambda: ooc.potrf_ooc(a, panel_cols=w,
                              cache_budget_bytes=budget,
                              visit_fuse="fused"))
    run("geqrf_per_panel",
        lambda: ooc.geqrf_ooc(g, panel_cols=w,
                              cache_budget_bytes=budget,
                              visit_fuse="per_panel"))
    run("geqrf_fused",
        lambda: ooc.geqrf_ooc(g, panel_cols=w,
                              cache_budget_bytes=budget,
                              visit_fuse="fused"))
    run("getrf_per_panel",
        lambda: ooc.getrf_tntpiv_ooc(g, panel_cols=w,
                                     cache_budget_bytes=budget,
                                     visit_fuse="per_panel"))
    run("getrf_fused",
        lambda: ooc.getrf_tntpiv_ooc(g, panel_cols=w,
                                     cache_budget_bytes=budget,
                                     visit_fuse="fused"))

    ok = True
    # (b) numeric agreement per op at the route's documented grade
    pv, fv = results.get("potrf_per_panel"), \
        results.get("potrf_fused")
    if pv is not None and fv is not None:
        close = bool(np.allclose(pv, fv, rtol=1e-4, atol=1e-4))
        extras["potrf_fused_allclose"] = close
        ok &= close
    else:
        ok = False
    pv, fv = results.get("geqrf_per_panel"), \
        results.get("geqrf_fused")
    if pv is not None and fv is not None:
        bit = bool(np.array_equal(np.asarray(pv[0]),
                                  np.asarray(fv[0]))
                   and np.array_equal(np.asarray(pv[1]),
                                      np.asarray(fv[1])))
        extras["geqrf_fused_bitwise"] = bit
        ok &= bit
    else:
        ok = False
    pv, fv = results.get("getrf_per_panel"), \
        results.get("getrf_fused")
    if pv is not None and fv is not None:
        piv = bool(np.array_equal(np.asarray(pv[1]),
                                  np.asarray(fv[1])))
        close = bool(np.allclose(np.asarray(pv[0]),
                                 np.asarray(fv[0]),
                                 rtol=1e-3, atol=1e-3))
        extras["getrf_fused_pivots_identical"] = piv
        extras["getrf_fused_allclose"] = close
        ok &= piv and close
    else:
        ok = False

    # (a) the dispatch-reduction gate at nt=16: per_panel issues one
    # update dispatch per visit (nt*(nt-1)/2); the fused route
    # replaces each multi-member sweep with ONE
    visits_total = nt * (nt - 1) // 2
    saved = sum(extras.get(k, {}).get("dispatches_saved", 0)
                for k in ("potrf_fused",))
    red = saved / visits_total if visits_total else 0.0
    extras["fuse_update_dispatches_per_panel"] = visits_total
    extras["fuse_update_dispatches_fused"] = visits_total - saved
    extras["fuse_dispatch_reduction"] = round(red, 4)
    emit({"fuse": "dispatch_reduction", "per_panel": visits_total,
          "fused": visits_total - saved, "reduction": round(red, 4)})
    ok &= red >= 0.60

    # (c) retrace guard: the jit cache keys on the count bucket, so
    # a same-shape rerun adds nothing
    rerun = run("potrf_fused_rerun",
                lambda: ooc.potrf_ooc(a, panel_cols=w,
                                      cache_budget_bytes=budget,
                                      visit_fuse="fused"))
    if rerun is not None:
        rr = extras["potrf_fused_rerun"]
        steady = rr["fuse_compiles"] == 0 \
            and rr["jit_recompiles"] == 0
        extras["fuse_rerun_steady_state"] = steady
        ok &= steady
    else:
        ok = False
    # issue overhead: fused vs unfused graph route (REPORTED)
    run("potrf_graph_unfused",
        lambda: ooc.potrf_ooc(a, panel_cols=w,
                              cache_budget_bytes=budget,
                              scheduler="graph"))
    gr = extras.get("potrf_graph_unfused")
    fr = extras.get("potrf_fused_rerun")
    if gr and fr and gr["issue_overhead_per_node_us"]:
        extras["fuse_issue_overhead_ratio"] = round(
            fr["issue_overhead_per_node_us"]
            / gr["issue_overhead_per_node_us"], 4)

    # (d) sharded fused route: bitwise vs the sharded walk, >= 95%
    # of the wall attributed to named ledger phases
    from slate_tpu.obs import ledger as obs_ledger
    from slate_tpu.obs import xprof as obs_xprof
    try:
        Lw = shard_ooc.shard_potrf_ooc(a, grid, panel_cols=w,
                                       cache_budget_bytes=budget)
        obs_ledger.reset()
        obs_ledger.enable()
        t0 = time.perf_counter()
        Lf = shard_ooc.shard_potrf_ooc(a, grid, panel_cols=w,
                                       cache_budget_bytes=budget,
                                       visit_fuse="fused")
        wall = time.perf_counter() - t0
        bit = bool(np.array_equal(np.asarray(Lw), np.asarray(Lf)))
        extras["potrf_shard_fused_bitwise"] = bit
        ok &= bit
        att = obs_xprof.attribute_run(
            records=obs_ledger.records("shard_potrf_ooc"))
        frac = att["total_wall_s"] / wall if wall > 0 else 0.0
        rec = {"wall_s": round(wall, 4),
               "ledger_records": att["records"],
               "attributed_s": att["total_wall_s"],
               "fraction_attributed": round(frac, 4),
               "buckets": att["buckets"]}
        extras["fuse_ledger_attribution"] = rec
        emit(dict({"fuse": "ledger_attribution"}, **rec))
        ok &= frac >= 0.95
    except Exception as e:
        extras["fuse_shard_error"] = str(e)[:160]
        ok = False
    finally:
        obs_ledger.disable()
        obs_ledger.reset()

    if "--obs" in sys.argv[1:]:
        # the regression comparator reads the numeric fuse extras
        # (dispatch reduction, attribution fraction, walls) against
        # the most recent BENCH_r*.json
        try:
            bench_obs_regression(extras)
        except Exception as e:
            extras["obs_regression"] = {
                "skipped": "error: %s" % str(e)[:120]}

    emit({"metric": "fuse", "value": 1 if ok else 0,
          "unit": "suite", "vs_baseline": 1 if ok else 0,
          "extras": extras})
    return 0


def bench_elastic():
    """`--elastic`: the elastic mesh (ISSUE 19) — throughput-driven
    panel re-ownership under a seeded straggler, on a REAL 2-process
    mesh. Three gated legs:

      * **straggler**: a seeded ``slow`` plan stalls host 1 on every
        panel it OWNS (``{"host": 1, "mine": true}`` — the injected
        cost is ownership-proportional, a deterministic multiplier on
        the straggler's step wall). The FROZEN static route pays it
        for half the stream; the elastic route measures, agrees, and
        re-owns panels off the straggler. GATE: elastic wall >= 15%
        under static wall, both factors bitwise vs the single-engine
        stream, and the elastic leg actually remapped. Extras report
        remap count, panels moved, and the straggler-idle fraction
        (fast-host bcast_wait / wall) per leg.
      * **shrink**: a seeded kill takes host 1 down mid-stream
        (checkpointing on); :func:`~slate_tpu.dist.elastic.
        shrink_to_fit` records the ``shard_shrink`` rung and the
        survivor resume (this process, same checkpoint root) must
        complete BITWISE vs the unfaulted single-engine stream.
      * **attribution**: a single-process elastic run with installed
        skewed speeds (real remaps) under the flight recorder —
        remap decisions land on the bus while >= 95% of the wall
        stays attributed to named ledger phases (the ISSUE 17 gate
        carried onto the segmented route)."""
    import numpy as np
    from slate_tpu import obs
    import slate_tpu as st
    from slate_tpu.dist import elastic, shard_ooc
    from slate_tpu.linalg import ooc
    from slate_tpu.obs import metrics as om
    from slate_tpu.resil import faults, guard
    from slate_tpu.testing import multiproc as mp

    obs.enable()
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "elastic_worker.py")
    extras = {}
    ok = True

    def worker_recs(outs):
        return [mp.results(out).get("elastic", {}) for out in outs]

    # -- leg 1: seeded straggler, static vs elastic wall ------------
    slow_plan = faults.FaultPlan([
        {"site": "step",
         "match": {"op": "shard_potrf_ooc", "host": 1, "mine": True},
         "kind": "slow", "times": 10 ** 6, "slow_s": 2.0}])
    legs = {}
    for mode in ("slow_static", "slow_elastic"):
        try:
            procs, outs = mp.launch(
                worker, num_processes=2, extra_args=[mode],
                env=faults.install_env_var(slow_plan), timeout=300)
            mp.assert_success(procs, outs)
            recs = worker_recs(outs)
            wall = max(r.get("wall_s", 0.0) for r in recs)
            rec = {"wall_s": wall,
                   "remaps": max(r.get("remaps", 0) for r in recs),
                   "panels_moved": max(r.get("panels_moved", 0)
                                       for r in recs),
                   # host 0 is the FAST host: its broadcast wait is
                   # time spent idle behind the straggler
                   "straggler_idle_fraction": round(
                       recs[0].get("bcast_wait_s", 0.0)
                       / max(recs[0].get("wall_s", 0.0), 1e-9), 4),
                   "bitwise": all(r.get("bitwise_vs_stream", False)
                                  for r in recs)}
            legs[mode] = rec
            extras[mode] = rec
            emit(dict({"elastic": mode}, **rec))
            ok &= rec["bitwise"]
        except Exception as e:
            extras["%s_error" % mode] = str(e)[:160]
            emit({"elastic": mode, "error": str(e)[:160]})
            ok = False
    if "slow_static" in legs and "slow_elastic" in legs:
        sw = legs["slow_static"]["wall_s"]
        ew = legs["slow_elastic"]["wall_s"]
        imp = 1.0 - ew / sw if sw > 0 else 0.0
        extras["elastic_wall_improvement"] = round(imp, 4)
        extras["elastic_remapped"] = legs["slow_elastic"]["remaps"] >= 1
        ok &= imp >= 0.15
        ok &= legs["slow_elastic"]["remaps"] >= 1
        ok &= legs["slow_static"]["remaps"] == 0
    else:
        ok = False

    # -- leg 2: seeded WorkerLost -> shrink-to-fit survivor resume --
    import tempfile
    kill_plan = faults.FaultPlan([
        {"site": "step",
         "match": {"op": "shard_potrf_ooc", "step": 3, "host": 1},
         "times": 1, "kind": "kill"}])
    n, w = 160, 32
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, n)).astype(np.float32)
    a = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)
    with tempfile.TemporaryDirectory() as ck:
        def primary():
            procs, outs = mp.launch(
                worker, num_processes=2, extra_args=["crash", ck],
                env=faults.install_env_var(kill_plan), timeout=300,
                death_grace=10.0)
            mp.assert_success(procs, outs)   # a no-kill run is a bug
            return None

        def survivors(exc):
            # this process IS the survivor mesh: resume from the
            # same checkpoint root (host0's mirror holds every
            # committed panel — the complete() mirror contract)
            grid = st.make_grid()
            return shard_ooc.shard_potrf_ooc(
                a, grid, panel_cols=w, cache_budget_bytes=0,
                ckpt_path=ck, ckpt_every=1)

        try:
            c0 = guard.counts()
            L = elastic.shrink_to_fit(primary, survivors,
                                      op="shard_potrf_ooc")
            L0 = ooc.potrf_ooc(a, panel_cols=w, cache_budget_bytes=0)
            shr = guard.counts().get(
                "resil.fallback.shard_shrink", 0) \
                - c0.get("resil.fallback.shard_shrink", 0)
            rec = {"completed": L is not None,
                   "shrink_escalations": shr,
                   "bitwise_vs_unfaulted":
                       bool(np.array_equal(np.asarray(L), L0))}
            extras["shrink"] = rec
            emit(dict({"elastic": "shrink"}, **rec))
            ok &= rec["completed"] and shr == 1 \
                and rec["bitwise_vs_unfaulted"]
        except Exception as e:
            extras["shrink_error"] = str(e)[:160]
            emit({"elastic": "shrink", "error": str(e)[:160]})
            ok = False

    # -- leg 3: remap decisions on the bus, wall still attributed ---
    from slate_tpu.obs import ledger as obs_ledger
    from slate_tpu.obs import xprof as obs_xprof
    try:
        grid = st.make_grid()
        nranks = grid.p * grid.q
        elastic.install_speeds([1.0] * (nranks // 2)
                               + [0.25] * (nranks - nranks // 2))
        obs_ledger.reset()
        obs_ledger.enable()
        c0 = dict(om.snapshot()["counters"])
        t0 = time.perf_counter()
        shard_ooc.shard_potrf_ooc(a, grid, panel_cols=w,
                                  cache_budget_bytes=0,
                                  ownership="elastic")
        wall = time.perf_counter() - t0
        c1 = dict(om.snapshot()["counters"])
        att = obs_xprof.attribute_run(
            records=obs_ledger.records("shard_potrf_ooc"))
        frac = att["total_wall_s"] / wall if wall > 0 else 0.0
        remaps = int(c1.get("ooc.shard.remaps", 0)
                     - c0.get("ooc.shard.remaps", 0))
        rec = {"wall_s": round(wall, 4), "remaps": remaps,
               "ledger_records": att["records"],
               "attributed_s": att["total_wall_s"],
               "fraction_attributed": round(frac, 4)}
        extras["elastic_ledger_attribution"] = rec
        emit(dict({"elastic": "ledger_attribution"}, **rec))
        ok &= frac >= 0.95 and remaps >= 1
    except Exception as e:
        extras["elastic_ledger_attribution_error"] = str(e)[:160]
        ok = False
    finally:
        elastic.install_speeds(None)
        obs_ledger.disable()
        obs_ledger.reset()

    emit({"metric": "elastic", "value": 1 if ok else 0,
          "unit": "suite", "vs_baseline": 1 if ok else 0,
          "extras": extras})
    return 0


def bench_faults():
    """`--faults`: resilience smoke lane (ISSUE 9) — a seeded fault
    plan injected into a small potrf_ooc stream, reporting retry
    counts (transient H2D/D2H faults absorbed by the guard, result
    bitwise the clean run's), checkpoint overhead (MUST be 0 bytes at
    the FROZEN ``resil/ckpt_every`` = 0 — the off-state contract —
    and the measured on-disk/wall cost at a real cadence), the
    interrupt->resume bitwise pin, and one shard->stream escalation
    (the degradation ladder's first rung) with its ``resil.*``
    counters in the BENCH extras."""
    import tempfile
    import numpy as np
    import slate_tpu as st
    from slate_tpu import obs
    from slate_tpu.core.methods import MethodOOC
    from slate_tpu.linalg import ooc
    from slate_tpu.resil import faults, guard

    obs.enable()
    try:
        n = int(os.environ.get("SLATE_FAULTS_N", "256"))
    except ValueError:
        n = 256
    w = max(n // 8, 32)
    nt = (n + w - 1) // w
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, n)).astype(np.float32)
    a = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)
    extras = {"n": n, "panel_cols": w, "nt": nt}
    ok = True

    def dir_bytes(d):
        return sum(os.path.getsize(os.path.join(r, f))
                   for r, _dirs, fs in os.walk(d) for f in fs)

    guard.reset_counts()
    t0 = time.perf_counter()
    L0 = ooc.potrf_ooc(a, panel_cols=w)
    clean_wall = time.perf_counter() - t0
    extras["clean_wall_s"] = round(clean_wall, 4)

    # -- off-state contract: a ckpt_path at the FROZEN cadence (0)
    # touches NOTHING and stays bit-identical
    ckdir_off = tempfile.mkdtemp(prefix="slate_faults_off_")
    L_off = ooc.potrf_ooc(a, panel_cols=w, ckpt_path=ckdir_off)
    extras["ckpt_bytes_at_every0"] = dir_bytes(ckdir_off)
    extras["ckpt_off_bitwise"] = bool(np.array_equal(L0, L_off))
    ok &= extras["ckpt_bytes_at_every0"] == 0
    ok &= extras["ckpt_off_bitwise"]

    # -- transient transfer faults absorbed by the retry guard
    guard.reset_counts()
    plan = faults.install(faults.FaultPlan([
        {"site": "h2d", "match": {"buf": "A", "idx": 1}, "times": 1},
        {"site": "d2h", "match": {"buf": "L", "idx": 2}, "times": 1},
    ], seed=0))
    t0 = time.perf_counter()
    L1 = ooc.potrf_ooc(a, panel_cols=w)
    faulted_wall = time.perf_counter() - t0
    faults.clear()
    c = guard.counts()
    extras["retry"] = {
        "injected": plan.fired(), "retries": c.get("resil.retries", 0),
        "bitwise": bool(np.array_equal(L0, L1)),
        "wall_s": round(faulted_wall, 4)}
    ok &= extras["retry"]["bitwise"] and plan.fired() == 2

    # -- interrupt at an injected fault, resume from checkpoint
    guard.reset_counts()
    ckdir = tempfile.mkdtemp(prefix="slate_faults_ck_")
    faults.install(faults.FaultPlan([
        {"site": "step", "match": {"op": "potrf_ooc", "step": nt // 2},
         "times": 1}]))
    interrupted = False
    t0 = time.perf_counter()
    try:
        ooc.potrf_ooc(a, panel_cols=w, ckpt_path=ckdir, ckpt_every=2)
    except faults.InjectedFault:
        interrupted = True
    faults.clear()
    part_wall = time.perf_counter() - t0
    ck_bytes = dir_bytes(ckdir)
    t0 = time.perf_counter()
    L2 = ooc.potrf_ooc(a, panel_cols=w, ckpt_path=ckdir, ckpt_every=2)
    resume_wall = time.perf_counter() - t0
    extras["resume"] = {
        "interrupted": interrupted, "ckpt_bytes": ck_bytes,
        "commits": guard.counts().get("resil.ckpt_commits", 0),
        "bitwise": bool(np.array_equal(L0, np.asarray(L2))),
        "interrupted_wall_s": round(part_wall, 4),
        "resume_wall_s": round(resume_wall, 4),
        "ckpt_overhead_vs_clean": round(
            (part_wall + resume_wall) / clean_wall, 3)
        if clean_wall else None}
    ok &= interrupted and extras["resume"]["bitwise"] and ck_bytes > 0

    # -- degradation ladder: sharded route fails -> single-engine
    # stream (needs the virtual-device mesh main() pins on CPU)
    try:
        guard.reset_counts()
        grid = st.make_grid()
        faults.install(faults.FaultPlan([
            {"site": "ppermute", "match": {"op": "shard_bcast"},
             "times": 999}]))
        L3 = ooc.potrf_ooc(a, panel_cols=w, grid=grid,
                           method=MethodOOC.Sharded)
        faults.clear()
        c = guard.counts()
        extras["escalation"] = {
            "retries": c.get("resil.retries", 0),
            "shard_to_stream":
                c.get("resil.fallback.shard_to_stream", 0),
            "bitwise": bool(np.array_equal(L0, L3))}
        ok &= extras["escalation"]["shard_to_stream"] == 1
        ok &= extras["escalation"]["bitwise"]
    except Exception as e:
        faults.clear()
        extras["escalation_error"] = str(e)[:160]
        ok = False

    extras["counters"] = {k: v for k, v in guard.counts().items()}
    emit({"metric": "faults", "value": 1 if ok else 0,
          "unit": "suite", "vs_baseline": 1 if ok else 0,
          "extras": extras})
    return 0


def bench_lint():
    """--lint: the slate_lint static-analysis smoke leg (ISSUE 13
    satellite). No backend, no jax — this runs the AST analyzers over
    the checkout and reports per-analyzer wall time, so the tier-1
    budget the lint consumes stays visible in the BENCH trajectory
    (the suite gates on zero live findings, same as CI)."""
    t0 = time.perf_counter()
    try:
        from tools.slate_lint import core as lint_core
        res = lint_core.run()
    except Exception as e:
        emit({"metric": "lint", "value": 0, "unit": "suite",
              "vs_baseline": 0, "error": str(e)[:160]})
        return 0
    wall = time.perf_counter() - t0
    extras = {
        "wall_s": round(wall, 3),
        "analyzers": len(res.timings),
        "timings_ms": {k: round(v * 1e3, 1)
                       for k, v in sorted(res.timings.items())},
        "findings": [f.render() for f in res.findings][:20],
        "exempted": len(res.exempted),
        "baselined": len(res.baselined),
    }
    ok = res.ok
    emit({"metric": "lint", "value": 1 if ok else 0, "unit": "suite",
          "vs_baseline": 1 if ok else 0, "extras": extras})
    return 0


def bench_serve():
    """`--serve`: the batched serving tier (ISSUE 5) — a synthetic
    lognormal problem-size stream (SLATE_SERVE_REQS requests, n
    clipped to [64, 1024]) of SPD solves pushed through the
    coalescing micro-batch queue, against per-request dispatch of the
    SAME vmapped drivers (batch size 1: bit-identical results, the
    drivers.py determinism contract). Reports matrices/sec, p50/p99
    submit-to-result latency, dispatches-saved, batch occupancy and
    padding-waste fractions — the occupancy/waste numbers also land
    in obs.snapshot() (batch.* metrics) and everything ships in the
    BENCH extras. Equal-results policy: bitwise vs the per-request
    dispatch for same-bucket exact-size requests, allclose for
    padded ones, plus an allclose spot-check against the UNBATCHED
    single-matrix core (vmap lowers batched matmuls through a
    different contraction kernel, so cross-form bitwise is not a
    thing — measured ~1e-15 relative). The ragged leg (ISSUE 15) runs
    the same stream under strategy="ragged" and gates on a >= 40%
    padding_waste_flops reduction vs the bucket strategy at equal
    results, dispatch count reported (kernels interpreted on the CPU
    tier — wall flagged, the TPU round prices it)."""
    import numpy as np
    from slate_tpu import batch, obs
    from slate_tpu.obs import metrics as om

    obs.enable()
    try:
        reqs = int(os.environ.get("SLATE_SERVE_REQS", "256"))
    except ValueError:
        reqs = 256
    rng = np.random.default_rng(0)
    # lognormal size stream: median ~180, clipped to the serving band
    sizes = np.clip(np.rint(np.exp(rng.normal(np.log(180.0), 0.6,
                                              reqs))).astype(int),
                    64, 1024)
    mats = []
    for n in sizes:
        x = rng.standard_normal((n, n)).astype(np.float32)
        mats.append(x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32))
    buckets = sorted({batch.bucket_for(int(n)) for n in sizes})
    extras = {"requests": reqs, "op": "potrf",
              "n_range": [int(sizes.min()), int(sizes.max())],
              "buckets": buckets}
    emit({"serve": "stream", "requests": reqs, "buckets": buckets})

    def stream(max_batch, strategy=None):
        q = batch.CoalescingQueue(max_batch=max_batch, max_wait_us=0,
                                  strategy=strategy)
        with q:
            t0 = time.perf_counter()
            tickets = [q.submit("potrf", a) for a in mats]
            q.flush()
            outs = [t.result() for t in tickets]
            wall = time.perf_counter() - t0
            lats = sorted(t.latency_s for t in tickets)
        s = q.stats()
        rec = {"wall_s": round(wall, 3),
               "matrices_per_s": round(reqs / wall, 1),
               "p50_ms": round(lats[reqs // 2] * 1e3, 3),
               "p99_ms": round(lats[min(int(reqs * 0.99), reqs - 1)]
                               * 1e3, 3),
               "dispatches": s["dispatches"],
               "dispatches_saved": s["dispatches_saved"],
               "mean_occupancy": round(s["mean_occupancy"], 2),
               "max_occupancy": s["max_occupancy"],
               "padding_waste": round(s["mean_padding_waste"], 4),
               "padding_waste_flops":
                   round(s["mean_padding_waste_flops"], 4),
               "mean_occupancy_weighted":
                   round(s["mean_occupancy_weighted"], 2),
               "ragged_dispatches": s["ragged_dispatches"]}
        return outs, rec

    # warmup both phases (compile), then measure; jit cache persists
    for mb in (1, None):
        try:
            stream(mb)
        except Exception as e:
            extras["warmup_error"] = str(e)[:160]
            emit({"error": "serve warmup died: %s" % str(e)[:160]})
            emit({"metric": "serve", "value": 0, "unit": "suite",
                  "vs_baseline": 0, "extras": extras})
            return 0
    per_req, rec1 = stream(1)
    emit(dict({"serve": "per_request"}, **rec1))
    coal, recb = stream(None)
    emit(dict({"serve": "coalesced"}, **recb))
    extras["per_request"] = rec1
    extras["coalesced"] = recb
    ratio = rec1["dispatches"] / max(recb["dispatches"], 1)
    extras["dispatch_reduction"] = round(ratio, 2)
    extras["throughput_gain"] = round(
        recb["matrices_per_s"] / max(rec1["matrices_per_s"], 1e-9), 3)

    # equal-results: bitwise vs per-request dispatch where the request
    # hits its bucket exactly; allclose (f32) for padded requests
    exact = padded = 0
    bitwise_ok = close_ok = True
    for n, a, b in zip(sizes, per_req, coal):
        if int(n) in buckets and int(n) == batch.bucket_for(int(n)):
            exact += 1
            bitwise_ok &= bool(np.array_equal(a, b))
        else:
            padded += 1
            close_ok &= bool(np.allclose(a, b, rtol=1e-5, atol=1e-5))
    extras["equal_results"] = {
        "exact_size_requests": exact, "bitwise_ok": bitwise_ok,
        "padded_requests": padded, "allclose_ok": close_ok}
    # cross-form spot check vs the unbatched single-matrix core (one
    # jit per distinct n — sampled, not the full stream, to keep the
    # compile budget bounded)
    import jax
    from slate_tpu.batch import drivers as bd
    sample = list(range(0, reqs, max(reqs // 6, 1)))[:6]
    spot_ok = True
    for i in sample:
        ref = np.asarray(jax.jit(bd.potrf_core)(mats[i]))
        spot_ok &= bool(np.allclose(coal[i], ref, rtol=1e-4,
                                    atol=1e-4))
    extras["single_core_spot_allclose"] = spot_ok

    # ragged leg (ISSUE 15): the SAME lognormal stream through the
    # ragged strategy — the coalescing key drops the bucket dimension
    # (every potrf request shares one bucket, flushing at max_batch),
    # each flush stacks at ITS max live size with the per-element
    # sizes vector, and the masked ragged Pallas kernels bound work to
    # true extents. On the CPU tier the kernels execute under the
    # Pallas interpreter, so the wall is informational (flagged); the
    # gates are the ones hardware keeps: padding_waste_flops reduction
    # >= 40% vs the bucket strategy at equal results (allclose), and
    # no more dispatches than the bucket leg.
    ragged_ok = False
    try:
        rag, recr = stream(None, strategy="ragged")
        recr["wall_flagged"] = "interpreted Pallas kernels (CPU tier)"
        emit(dict({"serve": "ragged"}, **recr))
        extras["ragged"] = recr
        r_close = all(
            np.allclose(a, b, rtol=1e-4, atol=1e-4)
            for a, b in zip(per_req, rag))
        red = 1.0 - recr["padding_waste_flops"] / max(
            recb["padding_waste_flops"], 1e-12)
        extras["ragged_allclose_ok"] = r_close
        extras["ragged_waste_flops_reduction"] = round(red, 4)
        ragged_ok = r_close and red >= 0.4 \
            and recr["dispatches"] <= recb["dispatches"]
        emit({"metric": "serve_ragged_waste_reduction",
              "value": round(red, 3), "unit": "fraction",
              "vs_baseline": 1 if ragged_ok else 0})
    except Exception as e:
        extras["ragged_error"] = str(e)[:200]
        emit({"error": "serve ragged leg died: %s" % str(e)[:200]})

    snap = om.snapshot()
    extras["obs_batch_counters"] = {
        k: v for k, v in snap["counters"].items()
        if k.startswith("batch.")}
    extras["obs_batch_histograms"] = {
        k: v for k, v in snap["histograms"].items()
        if k.startswith("batch.")}
    ok = bitwise_ok and close_ok and spot_ok and ratio >= 10 \
        and ragged_ok
    emit({"metric": "serve_dispatch_reduction",
          "value": round(ratio, 2), "unit": "x",
          "vs_baseline": 1 if ok else 0, "extras": extras})
    return 0


def bench_serve_daemon():
    """`--serve-daemon`: the serving daemon (ISSUE 16) — a
    repeated-solve stream (the BLASX scheduler-reuse pattern: many
    solves against the SAME small set of operators) through
    :class:`slate_tpu.serve.Server` with the factor cache off vs on.
    Per round every warm operator gets BOTH a potrf and a posv
    request; cache-off that is two fused dispatches per round (one
    potrf bucket + one posv bucket), cache-on the potrf requests are
    served from cache (ZERO dispatches) and the posv requests ride
    the solve-only potrs bucket (one dispatch) — the repeat-leg gate
    is dispatch reduction >= 2x at BITWISE-equal results (the
    split-factor-vs-fused contract drivers.py pins). The drain leg
    injects a transient fault at the queue dispatch site plus one at
    ``serve_drain`` and gates on graceful drain completing every
    in-flight ticket through the retry ladder."""
    import numpy as np
    from slate_tpu import serve
    from slate_tpu.batch.queue import CoalescingQueue
    from slate_tpu.resil import faults

    try:
        n_ops = int(os.environ.get("SLATE_SERVE_DAEMON_OPS", "4"))
        rounds = int(os.environ.get("SLATE_SERVE_DAEMON_ROUNDS", "6"))
    except ValueError:
        n_ops, rounds = 4, 6
    n = 128
    rng = np.random.default_rng(7)
    operators = []
    for _ in range(n_ops):
        x = rng.standard_normal((n, n)).astype(np.float32)
        operators.append(x @ x.T + 2.0 * n
                         * np.eye(n, dtype=np.float32))
    rhss = [rng.standard_normal((n, 2)).astype(np.float32)
            for _ in range(rounds)]
    extras = {"operators": n_ops, "rounds": rounds, "n": n}
    emit({"serve_daemon": "stream", "operators": n_ops,
          "rounds": rounds})

    def run(cache_mb):
        # non-background queue: each round's requests coalesce into
        # full-occupancy buckets flushed by the first result() —
        # deterministic dispatch counts on both legs
        q = CoalescingQueue(background=False)
        srv = serve.Server(queue=q, cache_mb=cache_mb)
        outs = []
        warm_disp = 0
        t0 = time.perf_counter()
        for r in range(rounds):
            ts = []
            for a in operators:
                ts.append(srv.submit("potrf", a))
                ts.append(srv.submit("posv", a, rhss[r]))
            outs.append([np.asarray(t.result(timeout=120))
                         for t in ts])
            if r == 0:
                # round 0 is the warm phase (cache-on pays its
                # factorizations here); the gate measures the rest
                warm_disp = q.stats()["dispatches"]
        wall = time.perf_counter() - t0
        s = srv.stats()
        rec = {"wall_s": round(wall, 3),
               "dispatches_total": s["queue"]["dispatches"],
               "dispatches_repeat":
                   s["queue"]["dispatches"] - warm_disp,
               "cache": s["cache"],
               "admission": s["admission"]}
        srv.close()
        return outs, rec

    try:
        off, rec_off = run(0)
        emit(dict({"serve_daemon": "cache_off"}, **rec_off))
        on, rec_on = run(64)
        emit(dict({"serve_daemon": "cache_on"}, **rec_on))
    except Exception as e:
        extras["error"] = str(e)[:200]
        emit({"error": "serve daemon stream died: %s" % str(e)[:200]})
        emit({"metric": "serve_daemon", "value": 0, "unit": "suite",
              "vs_baseline": 0, "extras": extras})
        return 0
    extras["cache_off"] = rec_off
    extras["cache_on"] = rec_on
    ratio = rec_off["dispatches_repeat"] / max(
        rec_on["dispatches_repeat"], 1)
    extras["repeat_dispatch_reduction"] = round(ratio, 2)
    bitwise = all(
        np.array_equal(a, b)
        for ra, rb in zip(off, on) for a, b in zip(ra, rb))
    extras["bitwise_ok"] = bitwise

    # drain leg: one transient fault at the queue dispatch site and
    # one at serve_drain; the retry ladder must absorb both and every
    # in-flight ticket must still complete
    drain_ok = False
    try:
        faults.install(faults.FaultPlan([
            {"site": "batch", "match": {"op": "posv"}, "times": 1},
            {"site": "serve_drain", "times": 1},
        ]))
        srv = serve.Server(queue=CoalescingQueue(background=False),
                           cache_mb=0)
        ts = [srv.submit("posv", operators[i % n_ops], rhss[0])
              for i in range(n_ops)]
        summary = srv.drain(timeout=120)
        srv.close()
        extras["drain"] = summary
        drain_ok = (summary["drained"] == len(ts)
                    and summary["failed"] == 0)
        emit(dict({"serve_daemon": "drain"}, **summary))
    except Exception as e:
        extras["drain_error"] = str(e)[:200]
        emit({"error": "serve daemon drain leg died: %s"
              % str(e)[:200]})
    finally:
        faults.clear()

    # telemetry leg (ISSUE 18 satellite): the cache-off stream again
    # with request tracing + SLO series ON — prices the enabled-state
    # overhead (the off-state is already pinned bitwise by tests) and
    # reports the daemon's p50/p95/p99 plus the admit/queue/dispatch/
    # solve wall split from the sketches themselves
    from slate_tpu.obs import reqtrace, series
    try:
        reqtrace.enable()
        series.enable()
        traced, rec_tr = run(0)
        emit(dict({"serve_daemon": "traced"}, **rec_tr))
        extras["trace_bitwise_ok"] = all(
            np.array_equal(a, b)
            for ra, rb in zip(off, traced) for a, b in zip(ra, rb))
        lat = {}
        split = {}
        for op_ in ("potrf", "posv"):
            q_ = series.quantiles("serve.latency_s",
                                  tenant="default", op=op_)
            if q_:
                lat[op_] = {k: round(v * 1e3, 4)
                            for k, v in q_.items()}
            for ph_ in ("admit_wait", "queue_wait", "dispatch",
                        "solve"):
                sm = series.summary("serve.%s_s" % ph_,
                                    tenant="default", op=op_)
                if sm:
                    split[ph_] = round(split.get(ph_, 0.0)
                                       + sm["sum"] * 1e3, 4)
        extras["latency_ms"] = lat
        extras["phase_split_ms"] = split
        extras["reqtrace_overhead_pct"] = round(
            (rec_tr["wall_s"] / max(rec_off["wall_s"], 1e-9) - 1)
            * 100, 2)
        emit({"serve_daemon": "telemetry", "latency_ms": lat,
              "phase_split_ms": split,
              "overhead_pct": extras["reqtrace_overhead_pct"]})
    except Exception as e:
        extras["telemetry_error"] = str(e)[:200]
        emit({"error": "serve daemon telemetry leg died: %s"
              % str(e)[:200]})
    finally:
        reqtrace.reset()
        series.reset()

    ok = bitwise and ratio >= 2.0 and drain_ok
    emit({"metric": "serve_daemon_repeat_dispatch_reduction",
          "value": round(ratio, 2), "unit": "x",
          "vs_baseline": 1 if ok else 0, "extras": extras})
    return 0


def bench_obs_regression(extras):
    """`--obs` regression leg (ISSUE 14 satellite): compare THIS
    run's per-driver walls and obs counters against the most recent
    ``BENCH_r*.json`` in the checkout — the BENCH trajectory finally
    read back instead of write-only. Emits per-metric deltas (shared
    numeric extras keys as cur/base ratios, per-driver wall deltas
    when both sides ran --obs, changed counters) into
    ``extras["obs_regression"]`` plus one summary line. Best-effort:
    a missing/mismatched baseline records why and never fails the
    run."""
    import glob
    here = os.path.dirname(os.path.abspath(__file__))
    files = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    if not files:
        extras["obs_regression"] = {"skipped": "no BENCH_r*.json"}
        return
    path = files[-1]
    try:
        with open(path) as f:
            base = json.load(f)
        parsed = base.get("parsed") or {}
        bex = parsed.get("extras") or {}
    except Exception as e:
        extras["obs_regression"] = {
            "skipped": "unreadable %s: %s"
            % (os.path.basename(path), str(e)[:80])}
        return
    out = {"baseline_file": os.path.basename(path),
           "baseline_metric": parsed.get("metric"),
           "baseline_value": parsed.get("value")}
    deltas = {}
    for k in sorted(bex):
        v, cur = bex[k], extras.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and isinstance(cur, (int, float)) \
                and not isinstance(cur, bool):
            deltas[k] = {"base": v, "cur": cur,
                         "ratio": round(cur / v, 4) if v else None}
        if len(deltas) >= 60:
            break
    out["metric_deltas"] = deltas
    bobs = bex.get("obs") or {}
    cobs = extras.get("obs") or {}
    bdrv = bobs.get("drivers") or {}
    cdrv = cobs.get("drivers") or {}
    if bdrv and cdrv:
        dd = {}
        for op in sorted(set(bdrv) & set(cdrv)):
            b, c = bdrv[op], cdrv[op]
            dd[op] = {"wall_base_s": b.get("wall_seconds"),
                      "wall_cur_s": c.get("wall_seconds"),
                      "calls_delta": c.get("calls", 0)
                      - b.get("calls", 0)}
        out["driver_wall_deltas"] = dd
    bc = (bobs.get("metrics") or {}).get("counters") or {}
    cc = (cobs.get("metrics") or {}).get("counters") or {}
    if bc or cc:
        cd = {}
        for k in sorted(set(bc) | set(cc)):
            if bc.get(k, 0) != cc.get(k, 0):
                cd[k] = {"base": bc.get(k, 0), "cur": cc.get(k, 0)}
            if len(cd) >= 40:
                break
        out["counter_deltas"] = cd
    extras["obs_regression"] = out
    emit({"obs": "regression", "baseline": out["baseline_file"],
          "metric_deltas": len(deltas),
          "driver_wall_deltas": len(out.get("driver_wall_deltas",
                                            {})),
          "counter_deltas": len(out.get("counter_deltas", {}))})


def bench_obs_analyze(st, tl, n, results):
    """`--obs`: compiled-program attribution for the headline driver
    (ISSUE 3): jit potrf at size n, pull the compiler cost model
    (analytic FLOPs, bytes, peak memory), the compile-vs-execute wall
    split, and the collective counts from the compiled HLO. The record
    lands in the obs analyses registry (merged into the headline
    extras) and one summary line is emitted immediately."""
    import jax
    import jax.numpy as jnp
    from slate_tpu import obs
    from slate_tpu.core.enums import Diag, MatrixType, Op, Uplo
    HI = jax.lax.Precision.HIGHEST

    @jax.jit
    def gen():
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (n, n), jnp.float32)
        return jnp.matmul(x, x.T, precision=HI) / n \
            + 4.0 * jnp.eye(n, dtype=jnp.float32)

    spd_j = gen()
    spd_j.block_until_ready()
    H = tl.TiledMatrix(data=spd_j, m=n, n=n, mb=512, nb=512,
                       mtype=MatrixType.Hermitian, uplo=Uplo.Lower,
                       op=Op.NoTrans, diag=Diag.NonUnit)

    @jax.jit
    def f(d):
        return st.potrf(dataclasses.replace(H, data=d)).data

    rec = obs.analyze("potrf_n%d" % n, f, spd_j)
    emit({"obs": "analyze", "label": rec["label"],
          "flops": rec.get("flops"),
          "peak_bytes": rec.get("peak_bytes"),
          "compile_seconds": rec.get("compile_seconds"),
          "execute_seconds": rec.get("execute_seconds"),
          "collectives": rec.get("collectives")})
    results["obs_potrf_flops_n%d" % n] = rec.get("flops")


def main():
    # SLATE_BENCH_SIZES=1024 lets CI smoke-test the full flow cheaply;
    # the driver always runs the default 16384,8192,4096. A malformed
    # falls back to the default — this script must always emit a
    # headline and exit 0.
    try:
        sizes = [int(s) for s in
                 os.environ.get("SLATE_BENCH_SIZES",
                                "16384,8192,4096").split(",") if s.strip()]
        assert sizes
    except Exception:
        sizes = [16384, 8192, 4096]
    headline_n = sizes[0]

    micro = "--micro" in sys.argv[1:]
    tune = "--tune" in sys.argv[1:]
    ooc = "--ooc" in sys.argv[1:]
    serve = "--serve" in sys.argv[1:]
    serve_daemon = "--serve-daemon" in sys.argv[1:]
    shard = "--shard" in sys.argv[1:]
    with_faults = "--faults" in sys.argv[1:]
    with_graph = "--graph" in sys.argv[1:]
    with_fuse = "--fuse" in sys.argv[1:]
    with_elastic = "--elastic" in sys.argv[1:]
    with_obs = "--obs" in sys.argv[1:]

    if "--lint" in sys.argv[1:]:
        # pure AST — runs (and must stay green) with no backend at all
        return bench_lint()

    if (shard or with_faults or with_graph or with_fuse
            or with_elastic) and (
            os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
            or os.environ.get("SLATE_FORCE_CPU") == "1"):
        # the sharded-OOC suite needs a mesh: on the CPU tier pin 8
        # virtual devices BEFORE the in-process backend initializes
        # (real hardware keeps whatever the process sees)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    ok, info = probe_backend()
    if not ok:
        name = "tune" if tune else "micro" if micro \
            else "ooc" if ooc else "serve_daemon" if serve_daemon \
            else "serve" if serve \
            else "shard" if shard else "faults" if with_faults \
            else "graph" if with_graph \
            else "fuse" if with_fuse \
            else "elastic" if with_elastic \
            else "potrf_f32_gflops_n%d" % headline_n
        emit({"metric": name, "value": 0,
              "unit": "suite" if (micro or tune or ooc or serve
                                  or serve_daemon
                                  or shard or with_faults
                                  or with_graph or with_fuse
                                  or with_elastic)
              else "GFLOP/s",
              "vs_baseline": 0,
              "skipped": "backend unavailable: %s" % info})
        return 0
    emit({"probe": "ok", "platform": info})

    if os.environ.get("SLATE_FORCE_CPU") == "1":
        force_cpu()

    if tune:
        return bench_tune()
    if ooc:
        return bench_ooc()
    if serve_daemon:
        return bench_serve_daemon()
    if serve:
        return bench_serve()
    if shard:
        return bench_shard()
    if with_faults:
        return bench_faults()
    if with_graph:
        return bench_graph()
    if with_fuse:
        return bench_fuse()
    if with_elastic:
        return bench_elastic()

    import slate_tpu as st
    import slate_tpu.core.tiles as tl

    if with_obs:
        # metrics/bus on for the whole run: driver counters, compile
        # accounting and recompile detection accumulate alongside the
        # measurements and ship in the headline extras (ISSUE 3)
        from slate_tpu import obs
        obs.enable()
        emit({"obs": "enabled"})

    if micro:
        results = {}
        bench_micro(st, results)
        if with_obs:
            # the micro path returns before the headline emit, so the
            # obs snapshot must ride the suite line itself
            try:
                from slate_tpu import obs as _obs
                snap = _obs.snapshot()
                results["obs"] = {"metrics": snap["metrics"],
                                  "drivers": snap["drivers"],
                                  "events_recorded": snap["events"]}
            except Exception as e:
                results["obs_snapshot_error"] = str(e)[:160]
        emit({"metric": "micro", "value": 1, "unit": "suite",
              "vs_baseline": 1, "extras": results})
        return 0

    results = {}
    for i, n in enumerate(sizes):
        try:
            # n=16384: XLA's native LU cannot compile there (scoped-
            # vmem height limit, methods.NATIVE_LU_MAX_M) and the
            # unrolled geqrf exceeds HBM under the chained harness —
            # bench_size covers gemm+potrf and bench_large adds the
            # routes that DO work at that size (fori-panel Tiled LU,
            # CALU tournament LU, scan-form geqrf). Full set at 8192
            # (+ the lookahead pair); gemm/potrf/getrf at 4096.
            full_n = 8192 if 8192 in sizes else sizes[0]
            bench_size(st, tl, n,
                       with_getrf=(n <= 8192),
                       with_geqrf=(n == full_n and n <= 8192),
                       results=results,
                       budget_scale=1.0 if i == 0 else 0.5,
                       with_lookahead=(n == full_n and n <= 8192),
                       headline_best_of=3 if n == headline_n else 1)
            if n > 8192:
                bench_large(st, tl, n, results, budget_scale=0.5)
        except Exception as e:       # belt over the per-routine braces
            results["n%d_fatal" % n] = str(e)[:160]
            emit({"error": "n%d sweep died: %s" % (n, str(e)[:160])})
        import gc
        gc.collect()     # outside the handler: its frames pin buffers

    if os.environ.get("SLATE_BENCH_SOLVERS", "1") != "0":
        try:
            # solver-level entries (BASELINE.md ex06-ex11 configs)
            bench_solvers(st, tl, full_n, results, budget_scale=0.5)
        except Exception as e:
            results["solvers_fatal"] = str(e)[:160]
            emit({"error": "solver sweep died: %s" % str(e)[:160]})
        gc.collect()

    if with_obs:
        try:
            # attribution at the smallest size: one extra compile,
            # bounded (the 16384 headline compile would double the
            # run's compile budget for a number that scales with n^3)
            bench_obs_analyze(st, tl, min(sizes), results)
        except Exception as e:
            results["obs_fatal"] = str(e)[:160]
            emit({"error": "obs analyze died: %s" % str(e)[:160]})

    def ratio(a, b):
        va, vb = results.get(a), results.get(b)
        return round(va / vb, 4) if isinstance(va, float) \
            and isinstance(vb, float) and vb else None

    extras = dict(results)
    if with_obs:
        try:
            from slate_tpu import obs
            snap = obs.snapshot()
            # the metrics snapshot + collective counts ride the
            # headline JSON next to the --tune stats (ISSUE 3); bus
            # events stay out (they are the Perfetto export's payload,
            # not trajectory data)
            extras["obs"] = {"metrics": snap["metrics"],
                             "drivers": snap["drivers"],
                             "analyses": snap["analyses"],
                             "events_recorded": snap["events"]}
        except Exception as e:
            extras["obs_snapshot_error"] = str(e)[:160]
    for nn in sizes:
        for r in ("potrf", "getrf", "getrf_tntpiv", "geqrf"):
            v = ratio("%s_n%d" % (r, nn), "gemm_n%d" % nn)
            if v is not None:
                extras["%s_vs_gemm_n%d" % (r, nn)] = v
    for key in list(results):
        for r in ("posv", "gesv", "heev", "svd"):
            if key.startswith(r + "_n"):
                nn = key.split("_n")[1].split("_")[0]
                v = ratio(key, "gemm_n%s" % nn)
                if v is not None:
                    extras["%s_vs_gemm_n%s" % (r, nn)] = v

    if with_obs:
        # regression leg (ISSUE 14): read the trajectory back. AFTER
        # the *_vs_gemm_* ratios land in extras — those normalized
        # efficiency numbers are the most size-independent regression
        # signals the baseline carries
        try:
            bench_obs_regression(extras)
        except Exception as e:
            extras["obs_regression"] = {
                "skipped": "error: %s" % str(e)[:120]}

    potrf = results.get("potrf_n%d" % headline_n)
    vsb = ratio("potrf_n%d" % headline_n, "gemm_n%d" % headline_n)
    emit({
        "metric": "potrf_f32_gflops_n%d" % headline_n,
        "value": potrf if potrf is not None else 0,
        "unit": "GFLOP/s",
        "vs_baseline": vsb if vsb is not None else 0,
        "extras": extras,
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
