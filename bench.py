#!/usr/bin/env python
"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline: f32 Cholesky (potrf) GFLOP/s on the attached TPU chip at
n=4096, the reference's ex07 north-star config on one chip (BASELINE.md;
TPU has no f64 MXU path, so f32 is the native headline precision — the
reference's own mixed-precision solvers deliver d-accuracy, see
slate_tpu.linalg.lu.gesv_mixed).

vs_baseline: potrf GFLOP/s divided by measured big-gemm GFLOP/s on the
same chip — the fraction of the chip's attainable matmul rate the full
blocked factorization sustains (self-calibrating analogue of "within X%
of cuBLAS" from BASELINE.json).

Timing notes: the axon tunnel has ~90 ms dispatch latency and
block_until_ready on large device-resident outputs returns early, so we
time K dependency-chained iterations inside one jit (totals >> the RPC
floor) and force completion by fetching a scalar. Both sides use
Precision.HIGHEST so vs_baseline compares f32-accurate math to
f32-accurate math.
"""

import dataclasses
import json
import sys
import time

import numpy as np

K_GEMM = 64   # chained iterations per measurement; totals must
K_POTRF = 32  # dwarf the ~90 ms tunnel round-trip


def main():
    import jax
    import jax.numpy as jnp
    sys.path.insert(0, ".")
    import slate_tpu as st

    n = 4096
    nb = 512
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, n)).astype(np.float32)
    spd = x @ x.T / n + np.eye(n, dtype=np.float32) * 4.0

    A = st.HermitianMatrix(st.Uplo.Lower, spd, mb=nb)
    G = st.Matrix(x, mb=nb)

    def gemm_chain(g):
        def body(i, c):
            return jnp.matmul(g.data, c,
                              precision=jax.lax.Precision.HIGHEST) \
                * (1.0 / n)
        return jax.lax.fori_loop(0, K_GEMM, body, g.data).sum()

    def potrf_chain(a):
        def body(i, carry):
            prev, acc = carry
            ai = dataclasses.replace(a, data=a.data + prev * 1e-30)
            L = st.potrf(ai)
            return L.data[0, 0], acc + L.data[0, 0]
        _, acc = jax.lax.fori_loop(0, K_POTRF, body,
                                   (jnp.float32(0), jnp.float32(0)))
        return acc

    def timeit(f, arg, k, reps=2):
        float(f(arg))                        # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(f(arg))                    # scalar fetch forces sync
            best = min(best, time.perf_counter() - t0)
        return best / k

    t_gemm = timeit(jax.jit(gemm_chain), G, K_GEMM)
    t_potrf = timeit(jax.jit(potrf_chain), A, K_POTRF)

    gemm_gflops = 2.0 * n ** 3 / t_gemm / 1e9
    potrf_gflops = (n ** 3 / 3.0) / t_potrf / 1e9

    print(json.dumps({
        "metric": "potrf_f32_gflops_n4096",
        "value": round(potrf_gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(potrf_gflops / gemm_gflops, 4),
    }))


if __name__ == "__main__":
    main()
