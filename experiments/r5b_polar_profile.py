"""Round-5 session-2 profile: the numbers that decide the eigensolver
redesign. bf16 gemm rate (is low-precision a 2-4x lever?), polar @8192
(iters x per-iter cost), QR-complete @8192 (subspace extraction cost),
vmapped-vs-sequential Jacobi leaves (does a level-batched agenda pay?),
and a bf16 Halley step (can early polar iterations run at bf16 rate?).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _slope, emit  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from slate_tpu.linalg.polar import polar_unitary, _chol_halley_step  # noqa: E402

HI = jax.lax.Precision.HIGHEST


def guarded(name, fn):
    try:
        fn()
    except Exception as e:
        emit({"metric": name, "error": str(e)[:200]})


# ---- bf16 vs f32-HIGHEST gemm rate --------------------------------------
for n in (4096, 8192):
    @jax.jit
    def gen(n=n):
        x = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32)
        return x, x.astype(jnp.bfloat16)

    xf, xb = gen()
    xf.block_until_ready()

    def m_f32(n=n, xf=xf):
        t = _slope(lambda c, a: jnp.matmul(a, c, precision=HI) * (1.0 / n),
                   xf, xf, est_hint=5e-3 * (n / 4096.0) ** 3, reps=3,
                   target=0.4)
        emit({"metric": "gemm_f32_hi_%d" % n,
              "gflops": round(2.0 * n ** 3 / t / 1e9, 1)})

    def m_bf16(n=n, xb=xb):
        t = _slope(lambda c, a: jnp.matmul(a, c,
                                           precision=jax.lax.Precision.DEFAULT)
                   .astype(jnp.bfloat16) * (1.0 / n),
                   xb, xb, est_hint=1e-3 * (n / 4096.0) ** 3, reps=3,
                   target=0.4)
        emit({"metric": "gemm_bf16_%d" % n,
              "gflops": round(2.0 * n ** 3 / t / 1e9, 1)})

    def m_f32_default(n=n, xf=xf):
        # f32 inputs, DEFAULT precision (bf16x6 or bf16x3 passes?)
        t = _slope(lambda c, a: jnp.matmul(a, c,
                                           precision=jax.lax.Precision.DEFAULT)
                   * (1.0 / n),
                   xf, xf, est_hint=2e-3 * (n / 4096.0) ** 3, reps=3,
                   target=0.4)
        emit({"metric": "gemm_f32_default_%d" % n,
              "gflops": round(2.0 * n ** 3 / t / 1e9, 1)})

    guarded("gemm_f32_hi_%d" % n, m_f32)
    guarded("gemm_bf16_%d" % n, m_bf16)
    guarded("gemm_f32_default_%d" % n, m_f32_default)

# ---- polar @8192: iteration count and total time ------------------------
n = 8192


@jax.jit
def gen_h(n=n):
    x = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)
    a = jnp.matmul(x, x.T, precision=HI) / n + jnp.eye(n, dtype=jnp.float32)
    sig = jnp.median(jnp.diagonal(a))
    return a, a - sig * jnp.eye(n, dtype=jnp.float32)


an, hs = gen_h()
an.block_until_ready()


def m_polar_iters():
    u, k, conv = polar_unitary(hs)
    emit({"metric": "polar_iters_8192", "value": int(k), "conv": bool(conv)})


def m_polar():
    def f(d, aux):
        u, k, c = polar_unitary(d)
        return d + u * 1e-30
    t = _slope(f, hs, hs, est_hint=0.6, reps=3, target=0.4)
    emit({"metric": "polar_8192_ms", "value": round(t * 1e3, 1)})


def m_chstep():
    a = jnp.asarray(3.0, jnp.float32)
    b = jnp.asarray(1.0, jnp.float32)
    c = jnp.asarray(3.0, jnp.float32)

    def f(d, aux):
        return _chol_halley_step(d, a, b, c) * (1.0 - 1e-30)
    t = _slope(f, hs, hs, est_hint=0.12, reps=3, target=0.4)
    emit({"metric": "chol_step_8192_ms", "value": round(t * 1e3, 1)})


def m_chstep_bf16():
    # the same Halley step with the gram + solves in bf16 storage:
    # viability + rate of a low-precision early iteration
    a = jnp.asarray(3.0, jnp.float32)
    b = jnp.asarray(1.0, jnp.float32)
    c = jnp.asarray(3.0, jnp.float32)

    def step_bf(u, a, b, c):
        ub = u.astype(jnp.bfloat16)
        g = jnp.matmul(ub.T, ub, precision=jax.lax.Precision.DEFAULT)
        g = g.astype(jnp.float32)
        x = c * g + jnp.eye(u.shape[0], dtype=jnp.float32)
        r = jax.lax.linalg.cholesky(x, symmetrize_input=False)
        z = jax.lax.linalg.triangular_solve(
            r, u.T, left_side=True, lower=True)
        z = jax.lax.linalg.triangular_solve(
            r, z, left_side=True, lower=True, transpose_a=True).T
        e = b / c
        return e * u + (a - e) * z

    def f(d, aux):
        return step_bf(d, a, b, c) * (1.0 - 1e-30)
    t = _slope(f, hs, hs, est_hint=0.08, reps=3, target=0.4)
    emit({"metric": "chol_step_bf16gram_8192_ms", "value": round(t * 1e3, 1)})


def m_qr_complete():
    def f(d, aux):
        q, _ = jnp.linalg.qr(d, mode="complete")
        return d + q * 1e-30
    t = _slope(f, hs, hs, est_hint=0.11, reps=3, target=0.4)
    emit({"metric": "qr_complete_8192_ms", "value": round(t * 1e3, 1)})


def m_trisolve_8192():
    # one full-width triangular solve at 8192 (polar inner op)
    r = jnp.tril(an) + 8.0 * jnp.eye(n, dtype=jnp.float32)

    def f(d, aux):
        return jax.lax.linalg.triangular_solve(
            aux, d, left_side=True, lower=True) * (1.0 - 1e-30)
    t = _slope(f, hs, r, est_hint=0.02, reps=3, target=0.4)
    emit({"metric": "trisolve_8192_full_ms", "value": round(t * 1e3, 1)})


guarded("polar_iters_8192", m_polar_iters)
guarded("polar_8192", m_polar)
guarded("chstep_8192", m_chstep)
guarded("chstep_bf16_8192", m_chstep_bf16)
guarded("qr_complete_8192", m_qr_complete)
guarded("trisolve_8192", m_trisolve_8192)


# ---- batched leaf eigh: vmap(32 x 256) vs known 1.92 ms sequential ------
def m_jacobi_batched():
    @jax.jit
    def genb():
        x = jax.random.normal(jax.random.PRNGKey(2), (32, 256, 256),
                              jnp.float32)
        return jnp.einsum("bij,bkj->bik", x, x) / 256

    hb = genb()
    hb.block_until_ready()

    def f(d, aux):
        v, w = jax.vmap(lambda h: jax.lax.linalg.eigh(
            h, symmetrize_input=False))(d)
        return d + v * 1e-30
    t = _slope(f, hb, hb, est_hint=0.03, reps=3, target=0.4)
    emit({"metric": "jacobi_vmap32x256_ms", "value": round(t * 1e3, 1)})


def m_polar_batched():
    # 2 x 4096 batched polar-step matmul/chol/solve (level-2 agenda
    # batching candidate): per-step cost vs 2x sequential 4096 steps
    @jax.jit
    def genb():
        x = jax.random.normal(jax.random.PRNGKey(3), (2, 4096, 4096),
                              jnp.float32)
        return jnp.einsum("bij,bkj->bik", x, x) / 4096

    hb = genb()
    hb.block_until_ready()
    a = jnp.asarray(3.0, jnp.float32)
    b = jnp.asarray(1.0, jnp.float32)
    c = jnp.asarray(3.0, jnp.float32)

    def f(d, aux):
        return jax.vmap(lambda u: _chol_halley_step(u, a, b, c))(d) \
            * (1.0 - 1e-30)
    t = _slope(f, hb, hb, est_hint=0.03, reps=3, target=0.4)
    emit({"metric": "chol_step_vmap2x4096_ms", "value": round(t * 1e3, 1)})


guarded("jacobi_vmap32x256", m_jacobi_batched)
guarded("chol_step_vmap2x4096", m_polar_batched)

emit({"metric": "r5b_polar_profile_done"})
