"""Round-5 profiling part 2: batched-primitive behavior on v5e + fixed
eigh timings. Determines the stage-2 window-kernel design: if batched
QR/Cholesky/TriangularSolve are batch-parallel (HLO expanders), window
panels can use them; if batch-sequential (like native LU and Jacobi,
PERF.md / memory), panels must be hand-built batched Householder.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _slope, emit  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

HI = jax.lax.Precision.HIGHEST


def guarded(name, fn):
    try:
        fn()
    except Exception as e:
        emit({"metric": name, "error": str(e)[:200]})


def main():
    key = jax.random.PRNGKey(0)

    # fixed eigh timing (correct unpack this time)
    for n in (4096, 8192):
        @jax.jit
        def gen(n=n):
            x = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)
            return jnp.matmul(x, x.T, precision=HI) / n + jnp.eye(n, dtype=jnp.float32)
        an = gen()
        an.block_until_ready()

        def m_eigh(an=an, n=n):
            def f(d, aux):
                v, w = jax.lax.linalg.eigh(d)
                return d + v * 1e-30 + w[None, :] * 1e-30
            t = _slope(f, an, an, est_hint=0.7 * (n / 4096) ** 3, reps=3,
                       target=0.3)
            emit({"metric": "lax_eigh_%d_ms" % n, "value": round(t * 1e3, 1),
                  "nominal_gflops": round(4 / 3 * n**3 / t / 1e9, 1)})
        guarded("eigh_%d" % n, m_eigh)

    # batched QR: 8 x (512, 256)
    p8 = jax.random.normal(key, (8, 512, 256), jnp.float32)
    p1 = p8[0]

    def m_qr_batch():
        def f1(d, aux):
            q, r = jnp.linalg.qr(d)
            return d + q * 1e-30
        t1 = _slope(f1, p1, p1, est_hint=1e-3, reps=3, target=0.3)
        emit({"metric": "qr_512x256_ms", "value": round(t1 * 1e3, 3)})
        t8 = _slope(f1, p8, p8, est_hint=8e-3, reps=3, target=0.3)
        emit({"metric": "qr_512x256_x8_ms", "value": round(t8 * 1e3, 3),
              "batch_ratio": round(t8 / t1, 2)})
    guarded("qr_batch", m_qr_batch)

    # batched Cholesky: 8 x (256, 256)
    g1 = jnp.matmul(p1.T, p1, precision=HI) / 512 + jnp.eye(256)
    g8 = jnp.broadcast_to(g1, (8, 256, 256)).copy()

    def m_chol_batch():
        def f(d, aux):
            return d + jax.lax.linalg.cholesky(d, symmetrize_input=False) * 1e-30
        t1 = _slope(f, g1, g1, est_hint=5e-4, reps=3, target=0.3)
        emit({"metric": "chol_256_ms", "value": round(t1 * 1e3, 3)})
        t8 = _slope(f, g8, g8, est_hint=4e-3, reps=3, target=0.3)
        emit({"metric": "chol_256_x8_ms", "value": round(t8 * 1e3, 3),
              "batch_ratio": round(t8 / t1, 2)})
    guarded("chol_batch", m_chol_batch)

    # batched TriangularSolve: 8 x solve((256,256) lower, (256, 512))
    l1 = jnp.tril(g1) + 4 * jnp.eye(256)
    l8 = jnp.broadcast_to(l1, (8, 256, 256)).copy()
    b1 = jax.random.normal(key, (256, 512), jnp.float32)
    b8 = jnp.broadcast_to(b1, (8, 256, 512)).copy()

    def m_trsm_batch():
        def f(d, aux):
            return d + jax.lax.linalg.triangular_solve(
                aux, d, left_side=True, lower=True) * 1e-30
        t1 = _slope(f, b1, l1, est_hint=5e-4, reps=3, target=0.3)
        emit({"metric": "trsm_256x512_ms", "value": round(t1 * 1e3, 3)})
        t8 = _slope(f, b8, l8, est_hint=4e-3, reps=3, target=0.3)
        emit({"metric": "trsm_256x512_x8_ms", "value": round(t8 * 1e3, 3),
              "batch_ratio": round(t8 / t1, 2)})
    guarded("trsm_batch", m_trsm_batch)

    # batched small matmul throughput: 8 x (1088,1088)@(1088,1088)
    w8 = jax.random.normal(key, (8, 1088, 1088), jnp.float32)

    def m_mm_batch():
        def f(d, aux):
            return jnp.matmul(d, aux, precision=HI) * (1.0 / 1088)
        t8 = _slope(f, w8, w8, est_hint=2e-3, reps=3, target=0.3)
        emit({"metric": "mm_1088_x8_ms", "value": round(t8 * 1e3, 3),
              "gflops": round(8 * 2 * 1088**3 / t8 / 1e9, 1)})
    guarded("mm_batch", m_mm_batch)

    # dynamic_slice gather/scatter of 8 windows from an 8192^2 dense
    a = jax.random.normal(key, (8192, 8192), jnp.float32)
    offs = jnp.arange(8, dtype=jnp.int32) * 1024

    def m_window():
        def f(d, offs):
            def get(o):
                return jax.lax.dynamic_slice(d, (o, o), (1088, 1088))
            ws = jax.vmap(get)(offs)
            ws = ws * 1.000001

            def put(dd, i):
                o = offs[i]
                return jax.lax.dynamic_update_slice(dd, ws[i], (o, o))
            d2 = jax.lax.fori_loop(0, 8, lambda i, dd: put(dd, i), d)
            return d2
        t = _slope(f, a, offs, est_hint=5e-3, reps=3, target=0.3)
        emit({"metric": "window_gather_scatter_8x1088_ms",
              "value": round(t * 1e3, 3)})
    guarded("window", m_window)

    # per-step latency floor of a trivial scan (what T steps cost)
    def m_scan_floor():
        x = jnp.zeros((512, 512), jnp.float32)

        def f(d, aux):
            def step(c, _):
                return c * 1.000001 + aux * 1e-30, None
            out, _ = jax.lax.scan(step, d, None, length=512)
            return out
        t = _slope(f, x, x, est_hint=5e-3, reps=3, target=0.3)
        emit({"metric": "scan_512steps_trivial_ms", "value": round(t * 1e3, 3),
              "per_step_us": round(t / 512 * 1e6, 2)})
    guarded("scan_floor", m_scan_floor)

    emit({"metric": "batch_profile_done", "value": 1})


if __name__ == "____main__":
    main()


if __name__ == "__main__":
    main()
