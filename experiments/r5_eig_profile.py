"""Round-5 profiling: decompose the heev/svd cost on the chip.

VERDICT r4 weak #2: "nobody has profiled where the time goes". This
script times, on the real TPU:
  1. jax.lax.linalg.eigh (the QDWH spectral D&C Auto path) @ 4096, 8192
  2. one qdwh polar decomposition @ 4096 (per-split dominant cost)
  3. one complete QR @ 4096 (subspace extraction per split)
  4. the Jacobi base case @ 256 (and batched x16)
  5. he2hb stage-1 @ 4096/8192 (staged-path ingredient)
  6. stedc_solve on a tridiagonal @ 4096/8192 (staged-path ingredient)
  7. gemm reference rate @ 4096

Timing uses bench.py's _slope (chained fori two-point slope) — the
tunnel's block_until_ready does not block; only scalar fetch syncs.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _slope, emit  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

HI = jax.lax.Precision.HIGHEST


def sym(n, key=0):
    @jax.jit
    def gen():
        x = jax.random.normal(jax.random.PRNGKey(key), (n, n), jnp.float32)
        return jnp.matmul(x, x.T, precision=HI) / n + jnp.eye(n, dtype=jnp.float32)
    a = gen()
    a.block_until_ready()
    return a


def guarded(name, fn):
    try:
        fn()
    except Exception as e:
        emit({"metric": name, "error": str(e)[:200]})


def main():
    # 7. gemm reference
    a4 = sym(4096)

    def m_gemm():
        t = _slope(lambda c, g: jnp.matmul(g, c, precision=HI) * (1.0 / 4096),
                   a4, a4, est_hint=5e-3, reps=3, target=0.4)
        emit({"metric": "gemm_4096_ms", "value": round(t * 1e3, 2),
              "gflops": round(2 * 4096**3 / t / 1e9, 1)})
    guarded("gemm", m_gemm)

    # 1. full eigh
    for n in (4096, 8192):
        an = sym(n)

        def m_eigh(an=an, n=n):
            def f(d, aux):
                v, w = jax.lax.linalg.eigh(d)   # (vectors, values)
                return d + v * 1e-30 + w[None, :] * 1e-30
            t = _slope(f, an, an, est_hint=0.7 * (n / 4096) ** 3, reps=3,
                       target=0.3)
            emit({"metric": "lax_eigh_%d_ms" % n, "value": round(t * 1e3, 1),
                  "nominal_gflops": round(4 / 3 * n**3 / t / 1e9, 1)})
        guarded("eigh_%d" % n, m_eigh)

    # 2. one qdwh polar @4096 (hermitian shifted matrix, like a split)
    from jax._src.tpu.linalg import qdwh as _qdwh

    def m_qdwh():
        def f(d, aux):
            u, h, iters, conv = _qdwh.qdwh(d, is_hermitian=True)
            return d + u * 1e-30
        t = _slope(f, a4, a4, est_hint=0.2, reps=3, target=0.3)
        emit({"metric": "qdwh_4096_ms", "value": round(t * 1e3, 1),
              "xn3_flops": round(t * 30.7e12 / 4096**3, 1)})
    guarded("qdwh", m_qdwh)

    def m_qdwh_iters():
        u, h, iters, conv = _qdwh.qdwh(a4, is_hermitian=True)
        emit({"metric": "qdwh_4096_iters", "value": int(iters)})
    guarded("qdwh_iters", m_qdwh_iters)

    # 3. complete QR @4096 (subspace extraction); also @2048
    for n in (2048, 4096):
        an = sym(n)

        def m_qr(an=an, n=n):
            def f(d, aux):
                q, _ = jnp.linalg.qr(d, mode="complete")
                return d + q * 1e-30
            t = _slope(f, an, an, est_hint=0.05 * (n / 4096) ** 3, reps=3,
                       target=0.3)
            emit({"metric": "qr_complete_%d_ms" % n, "value": round(t * 1e3, 1)})
        guarded("qr_%d" % n, m_qr)

    # 4. Jacobi base case @256, single and batched
    a256 = sym(256)

    def m_jacobi():
        def f(d, aux):
            v, w = jax.lax.linalg.eigh(
                d, sort_eigenvalues=False,
                implementation=jax.lax.linalg.EighImplementation.JACOBI)
            return d + v * 1e-30
        t = _slope(f, a256, a256, est_hint=5e-3, reps=3, target=0.3)
        emit({"metric": "jacobi_256_ms", "value": round(t * 1e3, 2)})
    guarded("jacobi", m_jacobi)

    def m_jacobi_batch():
        ab = jnp.broadcast_to(a256, (16, 256, 256)) + \
            1e-3 * jax.random.normal(jax.random.PRNGKey(9), (16, 256, 256))

        def f(d, aux):
            v, w = jax.lax.linalg.eigh(
                d, sort_eigenvalues=False,
                implementation=jax.lax.linalg.EighImplementation.JACOBI)
            return d + v * 1e-30
        t = _slope(f, ab, ab, est_hint=5e-2, reps=3, target=0.3)
        emit({"metric": "jacobi_256_x16_ms", "value": round(t * 1e3, 2)})
    guarded("jacobi_batch", m_jacobi_batch)

    # 5. he2hb stage 1 (nb=512) @4096/8192
    import dataclasses
    from slate_tpu.core.tiles import TiledMatrix
    from slate_tpu.core.enums import Diag, MatrixType, Op, Uplo
    from slate_tpu.linalg.eig import he2hb

    for n in (4096, 8192):
        an = sym(n)
        H = TiledMatrix(data=an, m=n, n=n, mb=512, nb=512,
                        mtype=MatrixType.Hermitian, uplo=Uplo.Lower,
                        op=Op.NoTrans, diag=Diag.NonUnit)

        def m_he2hb(an=an, H=H, n=n):
            def f(d, aux):
                B, Q = he2hb(dataclasses.replace(H, data=d), want_q=True)
                return d + B.data * 1e-30 + Q.data * 1e-30
            t = _slope(f, an, an, est_hint=0.1 * (n / 4096) ** 3, reps=3,
                       target=0.3)
            emit({"metric": "he2hb_%d_nb512_ms" % n, "value": round(t * 1e3, 1)})
        guarded("he2hb_%d" % n, m_he2hb)

    # 6. stedc_solve on a tridiagonal @4096/8192
    from slate_tpu.linalg.stedc import stedc_solve

    for n in (4096, 8192):
        key = jax.random.PRNGKey(3)
        d0 = jax.random.normal(key, (n,), jnp.float32)
        e0 = jax.random.normal(jax.random.PRNGKey(4), (n - 1,), jnp.float32)

        def m_stedc(d0=d0, e0=e0, n=n):
            def f(d, e):
                w, v = stedc_solve(d, e)
                return d + w * 1e-30 + v[:, 0] * 1e-30
            t = _slope(f, d0, e0, est_hint=0.2 * (n / 4096) ** 2, reps=3,
                       target=0.3)
            emit({"metric": "stedc_%d_ms" % n, "value": round(t * 1e3, 1)})
        guarded("stedc_%d" % n, m_stedc)

    emit({"metric": "profile_done", "value": 1})


if __name__ == "__main__":
    main()
