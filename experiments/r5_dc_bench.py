"""Benchmark eigh_dc vs lax.linalg.eigh on the chip."""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _slope, emit
import jax, jax.numpy as jnp
from slate_tpu.linalg.spectral_dc import eigh_dc
HI = jax.lax.Precision.HIGHEST

for n in (4096, 8192):
    @jax.jit
    def gen(n=n):
        x = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)
        return jnp.matmul(x, x.T, precision=HI) / n + jnp.eye(n, dtype=jnp.float32)
    an = gen(); an.block_until_ready()

    # correctness spot-check on chip
    try:
        w, v, _ok = eigh_dc(an)
        res = float(jnp.max(jnp.abs(jnp.matmul(an, v, precision=HI) - v * w[None, :])))
        orth = float(jnp.max(jnp.abs(jnp.matmul(v.T, v, precision=HI) - jnp.eye(n))))
        emit({"metric": "dc_check_%d" % n, "res": res, "orth": orth})
    except Exception as e:
        emit({"metric": "dc_check_%d" % n, "error": str(e)[:300]})
        continue

    def m(an=an, n=n):
        def f(d, aux):
            w, v, _ok = eigh_dc(d)
            return d + v * 1e-30 + w[None, :] * 1e-30
        t = _slope(f, an, an, est_hint=0.3 * (n / 4096) ** 3, reps=3, target=0.3)
        emit({"metric": "eigh_dc_%d_ms" % n, "value": round(t * 1e3, 1),
              "nominal_gflops": round(4 / 3 * n**3 / t / 1e9, 1)})
    try:
        m()
    except Exception as e:
        emit({"metric": "eigh_dc_%d" % n, "error": str(e)[:300]})
emit({"metric": "dc_bench_done"})
