"""Decompose eigh_dc cost on chip: capped polar, split, chol-step."""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _slope, emit
import jax, jax.numpy as jnp
from slate_tpu.linalg.polar import polar_unitary, _chol_halley_step
from slate_tpu.linalg.spectral_dc import _split_spectrum, eigh_dc
HI = jax.lax.Precision.HIGHEST

def guarded(name, fn):
    try:
        fn()
    except Exception as e:
        emit({"metric": name, "error": str(e)[:200]})

for n in (4096, 8192):
    @jax.jit
    def gen(n=n):
        x = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)
        return jnp.matmul(x, x.T, precision=HI) / n + jnp.eye(n, dtype=jnp.float32)
    an = gen(); an.block_until_ready()
    sig = jnp.median(jnp.diagonal(an))
    hs = an - sig * jnp.eye(n, dtype=jnp.float32)

    def m_iters(hs=hs, n=n):
        u, k, conv = polar_unitary(hs)
        emit({"metric": "polar_iters_%d" % n, "value": int(k), "conv": bool(conv)})
    guarded("it%d" % n, m_iters)

    def m_polar(hs=hs, n=n):
        def f(d, aux):
            u, k, c = polar_unitary(d)
            return d + u * 1e-30
        t = _slope(f, hs, hs, est_hint=0.06 * (n / 4096) ** 3, reps=3, target=0.3)
        emit({"metric": "polar_%d_ms" % n, "value": round(t * 1e3, 1)})
    guarded("polar%d" % n, m_polar)

    def m_chstep(hs=hs, n=n):
        a = jnp.asarray(3.0, jnp.float32)
        b = jnp.asarray(1.0, jnp.float32)
        c = jnp.asarray(3.0, jnp.float32)
        def f(d, aux):
            return _chol_halley_step(d, a, b, c) * (1.0 - 1e-30)
        t = _slope(f, hs, hs, est_hint=0.015 * (n / 4096) ** 3, reps=3, target=0.3)
        emit({"metric": "chol_step_%d_ms" % n, "value": round(t * 1e3, 1)})
    guarded("chstep%d" % n, m_chstep)

    def m_split(an=an, n=n):
        def f(d, aux):
            spl = _split_spectrum(d, jnp.asarray(n, jnp.int32), None)
            return d + spl.Q * 1e-30 + spl.W * 1e-30
        t = _slope(f, an, an, est_hint=0.15 * (n / 4096) ** 3, reps=3, target=0.3)
        emit({"metric": "split_%d_ms" % n, "value": round(t * 1e3, 1)})
    guarded("split%d" % n, m_split)

    def m_dc(an=an, n=n):
        def f(d, aux):
            w, v, _ok = eigh_dc(d)
            return d + v * 1e-30 + w[None, :] * 1e-30
        t = _slope(f, an, an, est_hint=0.3 * (n / 4096) ** 3, reps=3, target=0.3)
        emit({"metric": "eigh_dc_%d_ms" % n, "value": round(t * 1e3, 1),
              "nominal_gflops": round(4 / 3 * n**3 / t / 1e9, 1)})
    guarded("dc%d" % n, m_dc)
emit({"metric": "dc_profile_done"})
