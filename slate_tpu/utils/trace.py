"""Tracing / phase timers (reference auxiliary/Trace.hh:98-108 RAII
events + Trace.cc:359-627 SVG timeline; per-phase timer map returned in
opts, heev.cc:108).

TPU-native: heavy kernel profiling belongs to the jax profiler
(jax.profiler.trace -> Perfetto/XPlane). This module keeps the
reference's two lightweight surfaces: named-phase wall timers (the
`timers["heev::he2hb"]` map) and a minimal SVG timeline of recorded
blocks for quick eyeballing without tooling.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional, Tuple

_state = threading.local()


def _events() -> List[Tuple[str, float, float]]:
    if not hasattr(_state, "events"):
        _state.events = []
    return _state.events


_enabled = False


def on() -> None:
    """Reference trace::Trace::on()."""
    global _enabled
    _enabled = True


def off() -> None:
    global _enabled
    _enabled = False


@contextlib.contextmanager
def block(name: str):
    """RAII-style trace event (reference trace::Block)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if _enabled:
            _events().append((name, t0, time.perf_counter()))


def mark(name: str) -> None:
    """Zero-length event: a point-in-time annotation on the timeline
    (tune/select.py logs every autotuned decision through this, so
    decisions appear alongside the phase blocks they influenced)."""
    if _enabled:
        t = time.perf_counter()
        _events().append((name, t, t))


class Timers:
    """Named-phase timer map (reference opts timers, heev.cc:108)."""

    def __init__(self) -> None:
        self.values: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.values[name] = self.values.get(name, 0.0) \
                + time.perf_counter() - t0

    def __getitem__(self, k: str) -> float:
        return self.values[k]

    def __repr__(self) -> str:
        return "Timers(" + ", ".join(
            f"{k}={v:.4f}s" for k, v in self.values.items()) + ")"


def phases(opts):
    """Driver hook: returns `Timers.phase` when the caller passed an
    Option.Timers instance, else a no-op context factory — so every
    driver can phase-time unconditionally (reference per-phase timers
    returned in opts, heev.cc:108)."""
    from ..core.options import Option, get_option
    tm = get_option(opts, Option.Timers, None)
    if tm is None:
        @contextlib.contextmanager
        def noop(name):
            yield
        return noop
    return tm.phase


def finish(path: Optional[str] = None) -> Optional[str]:
    """Emit the SVG timeline (reference Trace::finish, Trace.cc:359-594)
    and clear events. Returns the SVG text (also written to path)."""
    evs = _events()
    if not evs:
        return None
    t_min = min(e[1] for e in evs)
    t_max = max(e[2] for e in evs)
    span = max(t_max - t_min, 1e-9)
    width, row_h, pad = 1000.0, 22.0, 4.0
    names = sorted({e[0] for e in evs})
    colors = ["#4e79a7", "#f28e2b", "#59a14f", "#e15759", "#76b7b2",
              "#edc948", "#b07aa1", "#9c755f"]
    color = {n: colors[i % len(colors)] for i, n in enumerate(names)}
    rows = {n: i for i, n in enumerate(names)}
    h = row_h * len(names) + 2 * pad
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" '
             f'width="{width + 220}" height="{h}">']
    for n in names:
        y = pad + rows[n] * row_h
        parts.append(f'<text x="4" y="{y + row_h * 0.7:.1f}" '
                     f'font-size="12">{n}</text>')
    for name, t0, t1 in evs:
        x = 200 + (t0 - t_min) / span * width
        w = max((t1 - t0) / span * width, 0.5)
        y = pad + rows[name] * row_h
        parts.append(f'<rect x="{x:.1f}" y="{y:.1f}" width="{w:.1f}" '
                     f'height="{row_h - 4:.1f}" fill="{color[name]}">'
                     f'<title>{name}: {(t1 - t0) * 1e3:.2f} ms</title>'
                     f'</rect>')
    parts.append("</svg>")
    svg = "\n".join(parts)
    evs.clear()
    if path:
        with open(path, "w") as f:
            f.write(svg)
    return svg
