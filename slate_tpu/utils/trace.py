"""Tracing / phase timers (reference auxiliary/Trace.hh:98-108 RAII
events + Trace.cc:359-627 SVG timeline; per-phase timer map returned in
opts, heev.cc:108).

Since ISSUE 3 this module is a thin view over the unified event bus
(slate_tpu/obs/events.py): `on()`/`off()` toggle the bus, `block`/
`mark` publish spans/instants into it, and `finish()` renders the SVG
quick-look from the bus's merged stream — ALL threads' events, unlike
the old per-thread buffers where OOC host-staging phases recorded off
the main thread silently vanished. The primary timeline artifact is
now the Perfetto JSON (obs/export.py: chrome_trace / write_trace);
the SVG stays for eyeballing without tooling.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional
from xml.sax.saxutils import escape

from ..obs import events as _bus


def on() -> None:
    """Reference trace::Trace::on() — enables the shared bus."""
    _bus.enable()


def off() -> None:
    """Disables the SHARED bus (one process-wide flag, ISSUE 3): a
    concurrently enabled obs session (bench --obs, tester
    --trace-out) stops collecting too. Inside such a session, prefer
    finish() alone — it renders and clears only this module's
    categories and leaves collection running."""
    _bus.disable()


def block(name: str):
    """RAII-style trace event (reference trace::Block), published to
    the bus under cat 'trace' (one span implementation lives in
    obs/events.py; this is a category-tagged view of it)."""
    return _bus.span(name, cat="trace")


def mark(name: str) -> None:
    """Zero-length event: a point-in-time annotation on the timeline
    (tune/select.py logs every autotuned decision through this, so
    decisions appear alongside the phase blocks they influenced)."""
    _bus.publish(name, _bus.PH_INSTANT, cat="tune")


class Timers:
    """Named-phase timer map (reference opts timers, heev.cc:108).
    Each phase also lands on the bus (cat 'phase') when it is on, so
    opts-timed driver phases show up in the Perfetto export."""

    def __init__(self) -> None:
        self.values = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            self.values[name] = self.values.get(name, 0.0) + t1 - t0
            _bus.publish(name, _bus.PH_SPAN, t0, t1, cat="phase")

    def __getitem__(self, k: str) -> float:
        return self.values[k]

    def __repr__(self) -> str:
        return "Timers(" + ", ".join(
            f"{k}={v:.4f}s" for k, v in self.values.items()) + ")"


def phases(opts):
    """Driver hook: returns a phase-context factory — `Timers.phase`
    when the caller passed an Option.Timers instance, a bus-only span
    factory when the bus is on (so instrumented drivers publish their
    phases with NO options plumbing), and a no-op context otherwise.
    The disabled path costs one boolean check per phase (reference
    per-phase timers returned in opts, heev.cc:108)."""
    from ..core.options import Option, get_option
    tm = get_option(opts, Option.Timers, None)
    if tm is not None:
        return tm.phase

    def bus_phase(name):
        return _bus.span(name, cat="phase")
    return bus_phase


#: the bus categories this module's legacy surface owns — what the
#: old per-thread store held. finish() drains ONLY these: a
#: concurrent obs session's driver/jit/comms/metric records survive a
#: user's trace.on()/finish() cycle (obs/export.py owns those).
_TRACE_CATS = ("trace", "phase", "tune")


def finish(path: Optional[str] = None) -> Optional[str]:
    """Emit the SVG timeline (reference Trace::finish, Trace.cc:359-594)
    from the bus's merged multi-thread stream and clear those events
    (only this module's categories, see _TRACE_CATS). Returns the
    SVG text (also written to path). Event names are XML-escaped: tuner
    marks legitimately contain <>& (e.g. "tune::eig.method=<MethodEig.
    DC: 'dc'> [frozen]") and must not produce malformed SVG."""
    evs = _bus.drain(cats=_TRACE_CATS)
    if not evs:
        return None
    t_min = min(e.t0 for e in evs)
    t_max = max(e.t1 for e in evs)
    span = max(t_max - t_min, 1e-9)
    width, row_h, pad = 1000.0, 22.0, 4.0
    names = sorted({e.name for e in evs})
    colors = ["#4e79a7", "#f28e2b", "#59a14f", "#e15759", "#76b7b2",
              "#edc948", "#b07aa1", "#9c755f"]
    color = {n: colors[i % len(colors)] for i, n in enumerate(names)}
    rows = {n: i for i, n in enumerate(names)}
    h = row_h * len(names) + 2 * pad
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" '
             f'width="{width + 220}" height="{h}">']
    for n in names:
        y = pad + rows[n] * row_h
        parts.append(f'<text x="4" y="{y + row_h * 0.7:.1f}" '
                     f'font-size="12">{escape(n)}</text>')
    for e in evs:
        x = 200 + (e.t0 - t_min) / span * width
        w = max((e.t1 - e.t0) / span * width, 0.5)
        y = pad + rows[e.name] * row_h
        parts.append(f'<rect x="{x:.1f}" y="{y:.1f}" width="{w:.1f}" '
                     f'height="{row_h - 4:.1f}" fill="{color[e.name]}">'
                     f'<title>{escape(e.name)}: '
                     f'{(e.t1 - e.t0) * 1e3:.2f} ms</title>'
                     f'</rect>')
    parts.append("</svg>")
    svg = "\n".join(parts)
    if path:
        with open(path, "w") as f:
            f.write(svg)
    return svg
