"""Backend availability probe.

A dead TPU tunnel hangs `jax.devices()` inside PJRT client init — C
code that no in-process signal can interrupt — so the only reliable
probe is a throwaway SUBPROCESS with a hard timeout. Used by bench.py
and examples/run_all.py to fail fast instead of hanging forever
(reference analogue: the MPI stub build letting everything run serially
when no cluster exists, SURVEY.md §4).
"""

import json
import os
import subprocess
import sys

_PROBE_CODE = (
    "import os, jax, json\n"
    # JAX_PLATFORMS env is overridden by site plugin registration;
    # config.update after import is what sticks.
    "if os.environ.get('SLATE_FORCE_CPU') == '1':\n"
    "    jax.config.update('jax_platforms', 'cpu')\n"
    "d = jax.devices()\n"
    "import jax.numpy as jnp\n"
    "x = jnp.ones((128, 128), jnp.float32)\n"
    "s = float((x @ x).sum())\n"
    "print(json.dumps({'platform': d[0].platform, 'n': len(d),"
    " 's': s}))\n"
)


def probe_backend(timeout=None):
    """Run a trivial op on the ambient jax backend in a subprocess.

    Returns (ok, platform_or_error). `timeout` defaults to
    $SLATE_BACKEND_PROBE_TIMEOUT or 240 s (first TPU compile through
    the tunnel is 20-40 s; backend init can add more).
    """
    if timeout is None:
        try:
            timeout = int(os.environ.get("SLATE_BACKEND_PROBE_TIMEOUT",
                                         "240"))
        except ValueError:
            timeout = 240    # malformed env must not break fail-fast
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False, "backend init timed out after %ds" % timeout
    if r.returncode != 0:
        return False, (r.stderr or r.stdout).strip()[-200:]
    try:
        info = json.loads(r.stdout.strip().splitlines()[-1])
        return True, info["platform"]
    except Exception:
        return False, "unparseable probe output: %r" % r.stdout[-200:]


def force_cpu():
    """Point the current process at the CPU backend. Must run before
    the first backend use; works even when site customization pinned
    the platform via jax.config (plain JAX_PLATFORMS env does not)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
