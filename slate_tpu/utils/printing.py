"""Matrix printing (reference src/print.cc, 1281 LoC; Option::Print*
keys, enums.hh:79-89: full / 4-corner edgeitems modes)."""

from __future__ import annotations

import numpy as np

from ..core.tiles import TiledMatrix


def sprint_matrix(label: str, A: TiledMatrix, edgeitems: int = 4,
                  width: int = 10, precision: int = 4) -> str:
    """Render like the reference's slate::print: full if small, else
    4-corner with ellipses."""
    a = np.asarray(A.to_dense())
    m, n = a.shape
    lines = [f"{label} = [  % {m}x{n}, tiles {A.mb}x{A.nb}, "
             f"{A.mtype.name}"]

    def fmt(v):
        if np.iscomplexobj(a):
            return f"{v.real:{width}.{precision}f}" \
                   f"{v.imag:+{width}.{precision}f}i"
        return f"{v:{width}.{precision}f}"

    if m <= 2 * edgeitems and n <= 2 * edgeitems:
        for i in range(m):
            lines.append("  " + " ".join(fmt(v) for v in a[i]))
    else:
        ri = list(range(min(edgeitems, m))) + \
            list(range(max(m - edgeitems, edgeitems), m))
        ci = list(range(min(edgeitems, n))) + \
            list(range(max(n - edgeitems, edgeitems), n))
        for k, i in enumerate(ri):
            row = " ".join(fmt(a[i, j]) for j in ci[:edgeitems])
            row += "  ...  " + " ".join(fmt(a[i, j])
                                        for j in ci[edgeitems:])
            lines.append("  " + row)
            if k == edgeitems - 1 and m > 2 * edgeitems:
                lines.append("  ...")
    lines.append("]")
    return "\n".join(lines)


def print_matrix(label: str, A: TiledMatrix, **kw) -> None:
    print(sprint_matrix(label, A, **kw))
