"""Matrix printing (reference src/print.cc; Option::Print* keys,
enums.hh:79-89).

Implements the reference's five verbosity levels:
  0: nothing
  1: metadata only (dimensions, tiling, type, uplo/op)
  2: first & last `edgeitems` rows & cols of the matrix (4-corner
     with ellipses) — the default
  3: the 4 corner elements of EVERY tile (tile-structure debugging)
  4: the full matrix
Driven either by keyword arguments or an options mapping with
Option.PrintVerbose / PrintEdgeItems / PrintWidth / PrintPrecision
(types.hh advice: width = precision + 6).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.options import Option, OptionsLike, get_option
from ..core.tiles import TiledMatrix


def _fmt_factory(complex_: bool, width: int, precision: int):
    def fmt(v):
        if complex_:
            return (f"{v.real:{width}.{precision}f}"
                    f"{v.imag:+{width}.{precision}f}i")
        return f"{v:{width}.{precision}f}"
    return fmt


def _meta(label: str, A: TiledMatrix) -> str:
    m, n = A.shape
    return (f"{label} = [  % {m}x{n}, tiles {A.mb}x{A.nb} "
            f"(mt={A.mt}, nt={A.nt}), {A.mtype.name}, "
            f"uplo={A.uplo.name}, op={A.op.name}, "
            f"dtype={np.dtype(A.dtype).name}")


def _rows_full(a, fmt):
    return ["  " + " ".join(fmt(v) for v in row) for row in a]


def _rows_corners(a, fmt, edgeitems):
    m, n = a.shape
    lines = []
    ri = list(range(min(edgeitems, m))) + \
        list(range(max(m - edgeitems, edgeitems), m))
    ci = list(range(min(edgeitems, n))) + \
        list(range(max(n - edgeitems, edgeitems), n))
    ci_lo = [j for j in ci if j < edgeitems]
    ci_hi = [j for j in ci if j >= edgeitems]
    for k, i in enumerate(ri):
        row = " ".join(fmt(a[i, j]) for j in ci_lo)
        if ci_hi:
            row += "  ...  " + " ".join(fmt(a[i, j]) for j in ci_hi)
        lines.append("  " + row)
        if k == len([i for i in ri if i < edgeitems]) - 1 \
                and m > 2 * edgeitems:
            lines.append("  ...")
    return lines


def _rows_tile_corners(A: TiledMatrix, fmt):
    """Verbose 3 (reference print.cc tile-corner mode): the 4 corner
    elements of every tile, one block row of tiles per paragraph."""
    lines = []
    for i in range(A.mt):
        top, bot = [], []
        for j in range(A.nt):
            # crop the stored tile to its logical extent — the padded
            # remainder is not matrix data
            t = np.asarray(A.tile(i, j))[:A.tileMb(i), :A.tileNb(j)]
            tm, tn = t.shape
            if tm <= 0 or tn <= 0:
                continue
            top.append(f"[{fmt(t[0, 0])} .. {fmt(t[0, tn - 1])}]")
            bot.append(f"[{fmt(t[tm - 1, 0])} .. {fmt(t[tm - 1, tn - 1])}]")
        lines.append("  tile row %d:" % i)
        lines.append("    " + " ".join(top))
        lines.append("    " + " ".join(bot))
    return lines


def sprint_matrix(label: str, A: TiledMatrix, edgeitems: int = 4,
                  width: int = 10, precision: int = 4,
                  verbose: Optional[int] = None,
                  opts: OptionsLike = None) -> str:
    """Render like the reference's slate::print (print.cc): verbosity
    levels 0-4 per enums.hh:79-84; defaults to level 2 (edgeitems
    corners), or level 4 (full) when the matrix already fits within
    the edgeitems window."""
    if opts:
        verbose = get_option(opts, Option.PrintVerbose,
                             verbose if verbose is not None else 2)
        edgeitems = get_option(opts, Option.PrintEdgeItems, edgeitems)
        width = get_option(opts, Option.PrintWidth, width)
        precision = get_option(opts, Option.PrintPrecision, precision)
    if verbose is None:
        verbose = 2
    if verbose <= 0:
        return ""
    lines = [_meta(label, A)]
    if verbose >= 2:
        fmt = _fmt_factory(A.is_complex, width, precision)
        if verbose == 3:
            # tile mode reads per-tile — never gathers the full dense
            lines += _rows_tile_corners(A, fmt)
        else:
            a = np.asarray(A.to_dense())
            m, n = a.shape
            small = m <= 2 * edgeitems and n <= 2 * edgeitems
            if verbose >= 4 or small:
                lines += _rows_full(a, fmt)
            else:
                lines += _rows_corners(a, fmt, edgeitems)
    lines.append("]")
    return "\n".join(lines)


def print_matrix(label: str, A: TiledMatrix, **kw) -> None:
    """Reference slate::print entry (print.cc); see sprint_matrix."""
    out = sprint_matrix(label, A, **kw)
    if out:
        print(out)
