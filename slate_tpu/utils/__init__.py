from . import trace
from .printing import print_matrix, sprint_matrix
from .trace import Timers
