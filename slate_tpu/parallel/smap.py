"""shard_map compatibility shim.

jax moved shard_map twice: new trees expose `jax.shard_map` with a
`check_vma` flag; older ones (<= 0.4.x) keep it under
`jax.experimental.shard_map.shard_map` with the same flag named
`check_rep`. Every explicit-schedule module (parallel/collectives.py,
the dist/ algorithm package) goes through this one resolver so the
surface difference lives in exactly one place.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """`jax.shard_map` where this jax has it, else the experimental
    path with `check_vma` mapped onto its old `check_rep` name. The
    default (False) matches the explicit-collective modules: values
    replicated by hand-placed all_gather/psum/ppermute trees are
    intended, not statically inferable."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
