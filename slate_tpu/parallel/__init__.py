from . import collectives
from .mesh import ProcessGrid, make_grid, single_device_grid
