from . import collectives, sharding
from .mesh import ProcessGrid, make_grid, single_device_grid
from .sharding import distribute_cyclic, undistribute
