"""2D block-cyclic tile distribution (reference func.hh:178-185
``process_2d_grid``, BaseMatrix.hh:161 gridinfo).

The reference distributes tile (i, j) to rank (i % p, j % q): as a
factorization sweeps its trailing submatrix, every grid row/column
still owns a share, so no rank idles. A contiguous `NamedSharding`
(P('p','q')) cannot express that assignment directly — after half the
steps of potrf, the devices owning the top block rows have nothing
left to do *if computation follows storage*.

Two TPU-native mechanisms replace it:

1. **Cyclic relayout** (`to_cyclic` / `from_cyclic`): a tile-row/column
   permutation that reorders storage so the block-cyclic assignment
   becomes contiguous — tile i of p=2 moves to storage slot
   [0,2,4,... then 1,3,5,...]. On the permuted array,
   `grid.matrix_sharding()` IS 2D block-cyclic over the logical tiles.
   This is the layout used for ScaLAPACK-style interop and
   `redistribute`, and costs one gather (an all-to-all under SPMD).

2. **Per-step sharding constraints** (`constrain`, used by the Tiled
   factorization drivers): under XLA SPMD the FLOP placement of a
   matmul follows the *sharding of its operands/output*, not the
   storage position of the logical submatrix. Constraining each block
   step's panel and trailing update to P('p','q') makes XLA partition
   every step's work across the full mesh — the load-balancing effect
   block-cyclic storage buys in MPI-land, with the compiler inserting
   the same column/row broadcasts the reference hand-codes
   (potrf.cc:108 tileBcast). This is why the drivers do NOT permute
   tiles: the permutation would destroy the contiguous slab slicing
   that feeds the MXU, while constraints deliver the balance for free.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tiles import TiledMatrix, ceil_div
from .mesh import ProcessGrid


def cyclic_tile_order(nt: int, p: int) -> np.ndarray:
    """Storage order of logical tile indices for a p-fold cyclic
    distribution: all tiles owned by rank 0 first (i % p == 0), then
    rank 1, ... Matches the reference's process_2d_grid row assignment
    (func.hh:178: rank = i % p)."""
    return np.concatenate([np.arange(r, nt, p) for r in range(max(p, 1))])


def _row_perm(npad: int, b: int, p: int) -> np.ndarray:
    nt = npad // b
    order = cyclic_tile_order(nt, p)
    return (order[:, None] * b + np.arange(b)[None, :]).reshape(-1)


def to_cyclic(a: jax.Array, mb: int, nb: int, p: int, q: int
              ) -> jax.Array:
    """Permute a padded (M, N) array into 2D block-cyclic storage order
    for a p x q grid; on the result, contiguous P('p','q') sharding
    assigns logical tile (i, j) to device (i % p, j % q)."""
    M, N = a.shape
    out = a
    if p > 1 and M // mb > 1:
        out = out[jnp.asarray(_row_perm(M, mb, p))]
    if q > 1 and N // nb > 1:
        out = out[:, jnp.asarray(_row_perm(N, nb, q))]
    return out


def from_cyclic(a: jax.Array, mb: int, nb: int, p: int, q: int
                ) -> jax.Array:
    """Inverse of `to_cyclic`."""
    M, N = a.shape
    out = a
    if p > 1 and M // mb > 1:
        out = out[jnp.asarray(np.argsort(_row_perm(M, mb, p)))]
    if q > 1 and N // nb > 1:
        out = out[:, jnp.asarray(np.argsort(_row_perm(N, nb, q)))]
    return out


def cyclic_sharding(grid: ProcessGrid) -> NamedSharding:
    """Sharding to pair with `to_cyclic` storage: contiguous P('p','q')
    on the permuted array == block-cyclic on logical tiles."""
    return grid.matrix_sharding()


def distribute_cyclic(A: TiledMatrix, grid: ProcessGrid) -> TiledMatrix:
    """Place A's storage on the grid in 2D block-cyclic layout
    (permuted storage + contiguous sharding). The result's `data` is
    device-resident; use `undistribute` to recover logical layout.
    Reference analogue: fromScaLAPACK + the default 2D block-cyclic
    constructors (Matrix.hh:73)."""
    import dataclasses
    perm = to_cyclic(A.data, A.mb, A.nb, grid.p, grid.q)
    return dataclasses.replace(
        A, data=jax.device_put(perm, cyclic_sharding(grid)))


def undistribute(A: TiledMatrix, grid: ProcessGrid) -> TiledMatrix:
    """Inverse of distribute_cyclic: gather + un-permute."""
    import dataclasses
    return dataclasses.replace(
        A, data=from_cyclic(A.data, A.mb, A.nb, grid.p, grid.q))


# -- constraint helpers used by the Tiled driver paths --------------------

def constrain(x: jax.Array, grid: Optional[ProcessGrid],
              spec: Optional[P] = None) -> jax.Array:
    """with_sharding_constraint when a grid is present, identity
    otherwise — lets the blocked drivers be grid-agnostic.

    Mesh axes that do not divide the corresponding dimension are
    dropped from the spec (XLA requires divisibility): a ragged RHS
    (say 10 columns on a q=4 grid) keeps its row sharding and
    replicates over 'q' instead of erroring — the balance degrades
    gracefully exactly where the reference's block-cyclic assignment
    would leave partial tiles."""
    if grid is None:
        return x
    if spec is None:
        spec = P("p", "q")
    sizes = dict(grid.mesh.shape)
    entries = list(spec) + [None] * (x.ndim - len(spec))
    fixed = []
    for dim in range(x.ndim):
        e = entries[dim]
        if e is None:
            fixed.append(None)
            continue
        names = e if isinstance(e, tuple) else (e,)
        prod = 1
        for nm in names:
            prod *= sizes[nm]
        fixed.append(e if x.shape[dim] % prod == 0 else None)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(grid.mesh, P(*fixed)))


def panel_spec() -> P:
    """Tall-skinny panels: rows over the whole mesh (the reference's
    panel-column rank set, getrf.cc:91)."""
    return P(("p", "q"), None)
