"""Device mesh and process grid (reference gridinfo / GridOrder machinery,
BaseMatrix.hh:161; MPI communicator plumbing).

The reference builds a p x q MPI rank grid and assigns tiles
block-cyclically. TPU-native: a `jax.sharding.Mesh` with axes ('p', 'q');
a matrix's padded data is sharded over ('p', 'q') with NamedSharding.
Multi-host / multi-slice works transparently: jax device lists span hosts,
ICI carries intra-slice axes and DCN inter-slice ones — the axis ordering
here puts 'q' innermost so the hot row-broadcasts of panel algorithms ride
the fastest links.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.enums import GridOrder
from ..core.func import process_2d_grid


def _near_square_factors(n: int) -> Tuple[int, int]:
    p = int(math.isqrt(n))
    while n % p:
        p -= 1
    return p, n // p


@dataclasses.dataclass(frozen=True)
class ProcessGrid:
    """A p x q grid over jax devices (reference BLACS-style grid)."""

    mesh: Mesh
    order: GridOrder = GridOrder.Col

    @property
    def p(self) -> int:
        return self.mesh.shape["p"]

    @property
    def q(self) -> int:
        return self.mesh.shape["q"]

    @property
    def nprocs(self) -> int:
        return self.p * self.q

    def tile_rank_func(self):
        """The reference tileRank lambda equivalent for this grid."""
        return process_2d_grid(self.order, self.p, self.q)

    def gridinfo(self):
        """(order, p, q) plus the per-device grid coordinates —
        reference BaseMatrix::gridinfo (BaseMatrix.hh:161). Under SPMD
        there is no ambient "my rank"; the coordinate map covers every
        device in the mesh."""
        coords = {dev: (r, c)
                  for r in range(self.p) for c in range(self.q)
                  for dev in [self.mesh.devices[r][c]]}
        return self.order, self.p, self.q, coords

    def matrix_sharding(self) -> NamedSharding:
        """Sharding for a padded (m_pad, n_pad) matrix: rows over 'p',
        cols over 'q'. Contiguous-block distribution; see
        sharding.py:block_cyclic for the cyclic tile permutation used by
        factorization drivers for load balance."""
        return NamedSharding(self.mesh, P("p", "q"))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def row_sharding(self) -> NamedSharding:
        """1D: rows over all devices (p*q) — for tall-skinny panels."""
        return NamedSharding(self.mesh, P(("p", "q"), None))


def make_grid(p: Optional[int] = None, q: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None,
              order: GridOrder = GridOrder.Col) -> ProcessGrid:
    """Build a ProcessGrid over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    nd = len(devices)
    if p is None and q is None:
        p, q = _near_square_factors(nd)
    elif p is None:
        if q <= 0 or nd % q:
            raise ValueError(f"q={q} does not divide {nd} devices")
        p = nd // q
    elif q is None:
        if p <= 0 or nd % p:
            raise ValueError(f"p={p} does not divide {nd} devices")
        q = nd // p
    if p <= 0 or q <= 0 or p * q > nd:
        raise ValueError(f"grid {p}x{q} needs {p*q} devices, have {nd}")
    arr = np.array(devices[: p * q]).reshape(p, q)
    return ProcessGrid(mesh=Mesh(arr, ("p", "q")), order=order)


def single_device_grid() -> ProcessGrid:
    return make_grid(1, 1, devices=jax.devices()[:1])
