"""Explicit collective helpers over the process grid (reference §2.4:
BcastList/ReduceList hypercube tile broadcasts — BaseMatrix.hh:1999
listBcast, :2219 listReduce, cubeBcastPattern internal_comm.cc:72).

Under jit + SPMD most communication is inserted by XLA from sharding
constraints; these shard_map helpers are the explicit layer for
algorithms that want manual control of the communication schedule (the
role the reference's per-tile MPI layer plays), and they compile to the
same ICI collectives (all_gather / psum / psum_scatter / ppermute).

The mapping (SURVEY §2.4 table):
    tileBcast along a row of ranks   -> row_bcast   (all_gather on 'q')
    tileBcast down a column          -> col_bcast   (all_gather on 'p')
    listReduce of partial tiles      -> col_reduce / row_reduce (psum)
    hypercube pipelined patterns     -> ring_shift  (ppermute ring)
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import ProcessGrid


def _smap(grid: ProcessGrid, f: Callable, in_specs, out_specs):
    # check_vma=False: replication produced by explicit collectives
    # (all_gather/psum) is intended, not statically inferable
    return jax.shard_map(f, mesh=grid.mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


def row_bcast(grid: ProcessGrid, x: jax.Array) -> jax.Array:
    """Broadcast each q-shard to the whole row of the grid: x sharded
    (P('p','q')) -> replicated over 'q' (reference tileBcast across a
    block row)."""
    def f(xs):
        return jax.lax.all_gather(xs, "q", axis=1, tiled=True)
    return _smap(grid, f, P("p", "q"), P("p", None))(x)


def col_bcast(grid: ProcessGrid, x: jax.Array) -> jax.Array:
    """Broadcast each p-shard down its grid column (reference tileBcast
    of the panel column, potrf.cc:108)."""
    def f(xs):
        return jax.lax.all_gather(xs, "p", axis=0, tiled=True)
    return _smap(grid, f, P("p", "q"), P(None, "q"))(x)


def col_reduce(grid: ProcessGrid, x: jax.Array) -> jax.Array:
    """Sum partial results over the 'p' axis, replicating the sum
    (reference listReduce with tile::add, BaseMatrix.hh:2219)."""
    def f(xs):
        return jax.lax.psum(xs, "p")
    return _smap(grid, f, P("p", "q"), P(None, "q"))(x)


def row_reduce(grid: ProcessGrid, x: jax.Array) -> jax.Array:
    def f(xs):
        return jax.lax.psum(xs, "q")
    return _smap(grid, f, P("p", "q"), P("p", None))(x)


def col_reduce_scatter(grid: ProcessGrid, x: jax.Array) -> jax.Array:
    """Sum over 'p' and scatter shards back down the column — the
    bandwidth-optimal form of the reduce list (psum_scatter rides ICI
    as a ring, like the reference's cubeReducePattern)."""
    def f(xs):
        return jax.lax.psum_scatter(xs, "p", scatter_dimension=0,
                                    tiled=True)
    return _smap(grid, f, P("p", "q"), P("p", "q"))(x)


def ring_shift(grid: ProcessGrid, x: jax.Array, axis: str = "q",
               shift: int = 1) -> jax.Array:
    """Rotate shards around a mesh axis ring (ppermute) — the building
    block of SUMMA/Cannon schedules and the analogue of the reference's
    pipelined hypercube broadcasts."""
    size = grid.mesh.shape[axis]
    perm = [(i, (i + shift) % size) for i in range(size)]

    def f(xs):
        return jax.lax.ppermute(xs, axis, perm)
    spec = P("p", "q")
    return _smap(grid, f, spec, spec)(x)


def summa_gemm(grid: ProcessGrid, a: jax.Array, b: jax.Array,
               precision=jax.lax.Precision.HIGHEST) -> jax.Array:
    """Explicit SUMMA matmul with a hand-written communication schedule
    (reference gemmC SUMMA loop, gemmC.cc:84-117: broadcast a column of
    A and a row of B per step, accumulate local outer products).

    This is the explicit-comm counterpart of the default gemm driver
    (which lets XLA's SPMD partitioner choose). The bulk schedule —
    gather A's block row across 'q', gather B's block column down 'p',
    one local matmul — moves exactly the bytes of the reference's
    per-step column/row broadcasts, batched. a: (m, k), b: (k, n), both
    sharded P('p','q'); result sharded P('p','q')."""
    q = grid.q

    def f(ash, bsh):
        # ash: (m/p, k/q) local; bsh: (k/p, n/q) local
        a_row = jax.lax.all_gather(ash, "q", axis=1, tiled=True)
        b_col = jax.lax.all_gather(bsh, "p", axis=0, tiled=True)
        return jnp.matmul(a_row, b_col, precision=precision)

    return _smap(grid, f, (P("p", "q"), P("p", "q")), P("p", "q"))(a, b)
