"""Explicit collective helpers over the process grid (reference §2.4:
BcastList/ReduceList hypercube tile broadcasts — BaseMatrix.hh:1999
listBcast, :2219 listReduce, cubeBcastPattern internal_comm.cc:72).

Under jit + SPMD most communication is inserted by XLA from sharding
constraints; these shard_map helpers are the explicit layer for
algorithms that want manual control of the communication schedule (the
role the reference's per-tile MPI layer plays), and they compile to the
same ICI collectives (all_gather / psum / psum_scatter / ppermute).

The mapping (SURVEY §2.4 table):
    tileBcast along a row of ranks   -> row_bcast   (all_gather on 'q')
    tileBcast down a column          -> col_bcast   (all_gather on 'p')
    listReduce of partial tiles      -> col_reduce / row_reduce (psum)
    hypercube pipelined patterns     -> ring_shift  (ppermute ring)
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import ProcessGrid
from .smap import shard_map


def _smap(grid: ProcessGrid, f: Callable, in_specs, out_specs):
    # check_vma=False: replication produced by explicit collectives
    # (all_gather/psum) is intended, not statically inferable
    return shard_map(f, mesh=grid.mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)


def row_bcast(grid: ProcessGrid, x: jax.Array) -> jax.Array:
    """Broadcast each q-shard to the whole row of the grid: x sharded
    (P('p','q')) -> replicated over 'q' (reference tileBcast across a
    block row)."""
    def f(xs):
        return jax.lax.all_gather(xs, "q", axis=1, tiled=True)
    return _smap(grid, f, P("p", "q"), P("p", None))(x)


def col_bcast(grid: ProcessGrid, x: jax.Array) -> jax.Array:
    """Broadcast each p-shard down its grid column (reference tileBcast
    of the panel column, potrf.cc:108)."""
    def f(xs):
        return jax.lax.all_gather(xs, "p", axis=0, tiled=True)
    return _smap(grid, f, P("p", "q"), P(None, "q"))(x)


def col_reduce(grid: ProcessGrid, x: jax.Array) -> jax.Array:
    """Sum partial results over the 'p' axis, replicating the sum
    (reference listReduce with tile::add, BaseMatrix.hh:2219)."""
    def f(xs):
        return jax.lax.psum(xs, "p")
    return _smap(grid, f, P("p", "q"), P(None, "q"))(x)


def row_reduce(grid: ProcessGrid, x: jax.Array) -> jax.Array:
    def f(xs):
        return jax.lax.psum(xs, "q")
    return _smap(grid, f, P("p", "q"), P("p", None))(x)


def col_reduce_scatter(grid: ProcessGrid, x: jax.Array) -> jax.Array:
    """Sum over 'p' and scatter shards back down the column — the
    bandwidth-optimal form of the reduce list (psum_scatter rides ICI
    as a ring, like the reference's cubeReducePattern)."""
    def f(xs):
        return jax.lax.psum_scatter(xs, "p", scatter_dimension=0,
                                    tiled=True)
    return _smap(grid, f, P("p", "q"), P("p", "q"))(x)


def ring_shift(grid: ProcessGrid, x: jax.Array, axis: str = "q",
               shift: int = 1) -> jax.Array:
    """Rotate shards around a mesh axis ring (ppermute) — the building
    block of SUMMA/Cannon schedules and the analogue of the reference's
    pipelined hypercube broadcasts."""
    size = grid.mesh.shape[axis]
    perm = [(i, (i + shift) % size) for i in range(size)]

    def f(xs):
        return jax.lax.ppermute(xs, axis, perm)
    spec = P("p", "q")
    return _smap(grid, f, spec, spec)(x)


#: shard-mapped tree_allreduce callables keyed by (mesh, axis, fanin,
#: op, rank). A fresh closure per call would defeat jax's jit cache —
#: on a multi-process mesh every invocation then pays a full
#: distributed retrace/compile (seconds), which the elastic
#: controller's per-boundary speed agreement turns into a per-segment
#: tax. The mesh participates in the key so regridding can't alias.
_TREE_ALLREDUCE_CACHE: dict = {}


def tree_allreduce(grid: ProcessGrid, x: jax.Array, op=jnp.add,
                   axis=("p", "q"), fanin: int = 2) -> jax.Array:
    """Explicitly scheduled log-depth reduction over a mesh axis:
    the ppermute pairwise-combine tree (dist/tree.py engine — the
    reference's hypercube ReduceList pattern, internal_comm.cc:72)
    instead of one opaque psum. Semantically psum-like for
    associative `op` (every device ends with the full reduction);
    its value is the SCHEDULE being explicit — the same engine the
    distributed algorithms (dist/tsqr.py ttqrt role) hang structured
    combines on. x sharded rows over `axis`; result replicated."""
    from ..dist import tree as _tree
    size = _tree.axis_size(grid, axis)
    _tree.record_schedule("tree_allreduce", size, fanin)
    key = (grid.mesh, axis if isinstance(axis, str) else tuple(axis),
           fanin, op, x.ndim)
    fn = _TREE_ALLREDUCE_CACHE.get(key)
    if fn is None:
        def f(xs):
            return _tree.tree_combine(
                xs, lambda vals: functools.reduce(op, vals), axis,
                size, fanin=fanin)

        in_spec = P(axis, *([None] * (x.ndim - 1)))
        fn = _TREE_ALLREDUCE_CACHE[key] = _smap(grid, f, in_spec,
                                                P())
    return fn(x)


def summa_gemm(grid: ProcessGrid, a: jax.Array, b: jax.Array,
               precision=jax.lax.Precision.HIGHEST) -> jax.Array:
    """Explicit SUMMA matmul with the reference's per-step panel
    schedule (gemmC SUMMA loop, gemmC.cc:84-117: broadcast ONE block
    column of A and ONE block row of B per step, accumulate local
    outer products — one panel in flight, never a whole gathered
    block row/column).

    k is split into p*q panels of width kb = k/(p*q), so every panel
    lives wholly inside one q-shard of A and one p-shard of B. Per
    step, the owner's panel is broadcast by masked psum (the dynamic-
    source broadcast idiom; ICI ring bytes within 2x of an ideal
    bcast), and every device accumulates a (m/p, kb) x (kb, n/q)
    matmul. Peak per-device working set is O(m/p*kb + kb*n/q) — the
    reference gemmC's one-panel discipline, not the O(m/p*k + k*n/q)
    of a full all-gather (round-2 finding). a: (m, k), b: (k, n), both
    sharded P('p','q'); a k that is not a multiple of p*q is
    zero-padded here (exact — zero panels contribute nothing), the
    ragged-tile case the reference's SUMMA handles natively;
    result sharded P('p','q')."""
    from ..core.tiles import round_up
    p, q = grid.p, grid.q
    m, k = a.shape
    n = b.shape[1]
    kp = round_up(k, p * q)
    if kp != k:
        a = jnp.pad(a, ((0, 0), (0, kp - k)))
        b = jnp.pad(b, ((0, kp - k), (0, 0)))
        k = kp
    kb = k // (p * q)
    mp_, nq_ = m // p, n // q
    out_dt = jnp.result_type(a.dtype, b.dtype)
    # accumulate across the p*q steps at >= f32 so the panel schedule
    # does not round a low-precision acc once per step (the bulk
    # variant's single matmul rounds once)
    acc_dt = jnp.promote_types(out_dt, jnp.float32)

    def f(ash, bsh):
        qi = jax.lax.axis_index("q")
        pi = jax.lax.axis_index("p")

        def step(s, acc):
            apan = jax.lax.dynamic_slice(ash, (0, (s % p) * kb),
                                         (mp_, kb))
            apan = jnp.where(qi == s // p, apan, 0)
            apan = jax.lax.psum(apan, "q")
            bpan = jax.lax.dynamic_slice(bsh, ((s % q) * kb, 0),
                                         (kb, nq_))
            bpan = jnp.where(pi == s // q, bpan, 0)
            bpan = jax.lax.psum(bpan, "p")
            return acc + jnp.matmul(apan, bpan, precision=precision,
                                    preferred_element_type=acc_dt)

        acc0 = jnp.zeros((mp_, nq_), acc_dt)
        return jax.lax.fori_loop(0, p * q, step, acc0).astype(out_dt)

    return _smap(grid, f, (P("p", "q"), P("p", "q")), P("p", "q"))(a, b)


def summa_gemm_allgather(grid: ProcessGrid, a: jax.Array, b: jax.Array,
                         precision=jax.lax.Precision.HIGHEST
                         ) -> jax.Array:
    """Bulk-synchronous SUMMA variant: gather A's whole block row and
    B's whole block column, one local matmul. Fewer, larger collectives
    than the per-step schedule at O(m/p*k + k*n/q) per-device memory —
    the right trade for small k; kept for comparison and tests."""
    def f(ash, bsh):
        a_row = jax.lax.all_gather(ash, "q", axis=1, tiled=True)
        b_col = jax.lax.all_gather(bsh, "p", axis=0, tiled=True)
        return jnp.matmul(a_row, b_col, precision=precision)

    return _smap(grid, f, (P("p", "q"), P("p", "q")), P("p", "q"))(a, b)
