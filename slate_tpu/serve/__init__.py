"""slate_serve: the production serving daemon (ISSUE 16 tentpole).

A persistent multi-tenant serving tier over the batch substrate:

  * :class:`Server` — process-level submit API over the existing
    :class:`~slate_tpu.batch.queue.CoalescingQueue` (serve/server.py);
  * :class:`~slate_tpu.serve.rpc.RpcServer` /
    :class:`~slate_tpu.serve.rpc.RpcClient` — length-prefixed socket
    framing for out-of-process clients, zero-copy ingestion;
  * :class:`AdmissionController` + :class:`TenantConfig` — per-tenant
    quotas and priority classes, decisions driven by the obs
    substrate (queue stats, ledger dispatch records, the watchdog's
    ETA gauge), every non-admit funneled through the resil guard;
  * :class:`FactorCache` — fingerprint-keyed LRU of potrf/getrf
    factors so repeated solves against the same operator skip the
    O(n^3) re-factorization and ride the solve-only ragged stream.

Cold route (tuned ``serve/cache_mb`` 0, the FROZEN default):
bitwise-identical to direct queue use — the daemon adds policy, not
a second numerics path.
"""

from .admission import (ADMIT, DEGRADE, PRIORITIES, REJECT, SHED,
                        AdmissionController, TenantConfig)
from .cache import FactorCache
from .rpc import RpcClient, RpcServer
from .server import CACHED_OPS, ServeRejected, Server, ServeTicket

__all__ = [
    "ADMIT", "DEGRADE", "PRIORITIES", "REJECT", "SHED",
    "AdmissionController", "TenantConfig", "FactorCache",
    "RpcClient", "RpcServer", "CACHED_OPS", "ServeRejected",
    "Server", "ServeTicket",
]
