"""The serving daemon (ISSUE 16 tentpole, part 1 + 4).

A persistent multi-tenant serving tier layered on the PR 5/15 batch
substrate: requests enter through :meth:`Server.submit` (in-process;
serve/rpc.py adds the out-of-process socket framing) and are
admission-controlled (serve/admission.py), optionally served from the
fingerprint-keyed factor cache (serve/cache.py), and coalesced by the
existing :class:`~slate_tpu.batch.queue.CoalescingQueue` — the daemon
adds policy, never a second dispatch path.

Factor-cache routing (cache ON, i.e. a nonzero tuned/explicit
``serve/cache_mb``):

  * ``potrf``/``getrf`` requests that HIT return the cached factor
    immediately — zero dispatches (the bench --serve-daemon repeat
    leg's 2x);
  * ``posv``/``gesv`` requests that HIT skip straight to the
    solve-only dispatch (batch/drivers potrs / getrs, with gesv's
    pivot permutation applied host-side — an exact gather), which the
    queue coalesces per solve key; PR 15's ragged strategy coalesces
    the solve-only stream across sizes;
  * misses submit the factorization ONCE per operator — concurrent
    misses on the same fingerprint share the pending factor ticket
    (in-flight dedup) — and a small chainer thread caches the factor
    and fans the waiting solves out to the queue, where they land in
    ONE solve bucket.

Bitwise contract (pinned by tests + the bench leg): the split
factor + solve-only path produces bitwise-identical results to the
fused posv/gesv dispatch — identity bucket padding keeps the padded
factor block-diagonal exact, the pivot gather is exact, and the trsm
pair is the same primitive sequence the fused core lowers. With
``cache_mb`` 0 (the FROZEN row) no cache object exists and every
request forwards unchanged to the queue: the cold route is
bitwise-identical to direct queue use.

Graceful drain (part 4): :meth:`drain` stops admission, passes the
``serve_drain`` fault site through the PR 9 retry ladder (an injected
transient fault is absorbed, not fatal), force-flushes the queue, and
rides ``Ticket.result(timeout=)`` to completion for every in-flight
request — the bench gates drain completing ALL tickets under an
injected fault.
"""

from __future__ import annotations

import queue as _stdqueue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..batch.queue import CoalescingQueue
from ..obs import metrics as _om
from ..obs import reqtrace as _rt
from ..resil import faults as _faults
from ..resil import guard as _guard
from ..resil.checkpoint import fingerprint
from .admission import (ADMIT, DEGRADE, REJECT, SHED,
                        AdmissionController)
from .cache import FactorCache

#: cacheable request op -> (factor family, factor op, solve-only op).
#: The family scopes the cache key: a posv and a gesv against the
#: same bytes need DIFFERENT factors.
CACHED_OPS = {
    "potrf": ("chol", "potrf", None),
    "posv": ("chol", "potrf", "potrs"),
    "getrf": ("lu", "getrf", None),
    "gesv": ("lu", "getrf", "getrs"),
}


class ServeRejected(RuntimeError):
    """A request the admission ladder refused (decision ``shed`` or
    ``reject``) or that arrived while the daemon was draining."""

    def __init__(self, decision: str, tenant: str, op: str,
                 why: str = "") -> None:
        self.decision = decision
        self.tenant = tenant
        self.op = op
        super().__init__(
            "serve request %r (tenant %r) %s%s"
            % (op, tenant, decision, (": " + why) if why else ""))


class ServeTicket:
    """One admitted request's handle. Resolution is two-stage: the
    ticket is first BOUND to its final queue ticket (immediately for
    direct routes; after the shared factor lands for cache misses),
    then ``result()`` delegates. ``decision`` records the admission
    outcome ("admit"/"degrade"), ``cache`` the cache outcome
    ("hit"/"miss"/None when the cache is off or the op uncacheable).
    A degraded request's result comes back float32 — the documented
    degrade-precision contract."""

    def __init__(self, tenant: str, decision: str,
                 cache: Optional[str] = None) -> None:
        self.tenant = tenant
        self.decision = decision
        self.cache = cache
        #: the request's root reqtrace Span (None with tracing off)
        self.span = None
        self._bound = threading.Event()
        self._inner = None          # the final queue Ticket, or None
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def _bind(self, ticket) -> None:
        self._inner = ticket
        self._bound.set()

    def _resolve(self, value) -> None:
        self._value = value
        self._bound.set()

    def _fail(self, e: BaseException) -> None:
        self._error = e
        self._bound.set()

    def done(self) -> bool:
        return self._bound.is_set() and (self._inner is None
                                         or self._inner.done())

    def result(self, timeout: Optional[float] = None):
        deadline = None if timeout is None \
            else time.perf_counter() + timeout
        if not self._bound.wait(timeout):
            raise TimeoutError(
                "serve request (tenant %r) still awaiting its "
                "factor after %.4gs" % (self.tenant, timeout))
        if self._error is not None:
            raise self._error
        if self._inner is None:
            return self._value
        rem = None if deadline is None \
            else max(deadline - time.perf_counter(), 1e-3)
        return self._inner.result(rem)


class _FactorFuture:
    """One in-flight factorization (cache-miss dedup): the factor
    ticket plus every (serve ticket, op, rhs) waiting on it."""

    __slots__ = ("key", "ticket", "waiters", "trace_id")

    def __init__(self, key) -> None:
        self.key = key
        self.ticket = None
        self.waiters: List[Tuple[ServeTicket, str, Any]] = []
        #: the FIRST miss's trace id (reqtrace): the shared factor
        #: dispatch runs as a child span of that request
        self.trace_id: Optional[str] = None


class Server:
    """The serving daemon (module doc). Owns a background
    CoalescingQueue unless handed one; use as a context manager or
    call :meth:`close`."""

    def __init__(self, queue: Optional[CoalescingQueue] = None,
                 cache_mb: Optional[float] = None,
                 tenants=None, opts=None,
                 max_batch: Optional[int] = None,
                 max_wait_us: Optional[int] = None,
                 strategy=None) -> None:
        from ..tune.select import tuned_int
        if queue is None:
            queue = CoalescingQueue(max_batch=max_batch,
                                    max_wait_us=max_wait_us,
                                    opts=opts, background=True,
                                    strategy=strategy)
            self._owns_queue = True
        else:
            self._owns_queue = False
        self._queue = queue
        mb = float(cache_mb) if cache_mb is not None \
            else float(tuned_int("serve", "cache_mb", 0, opts=opts))
        self.cache: Optional[FactorCache] = \
            FactorCache(mb) if mb > 0 else None
        self.admission = AdmissionController(queue, tenants=tenants,
                                             opts=opts)
        self._lock = threading.Lock()
        #: tenant -> unresolved ServeTickets (pruned on access)
        self._inflight: Dict[str, List[ServeTicket]] = {}
        self._pending_factors: Dict[Any, _FactorFuture] = {}
        self._submitted = 0
        self._draining = False
        self._closed = False
        self._chain_q: "_stdqueue.Queue" = _stdqueue.Queue()
        self._chainer: Optional[threading.Thread] = None
        if self.cache is not None:
            self._chainer = threading.Thread(
                target=self._chain_loop, name="serve-chainer",
                daemon=True)
            self._chainer.start()

    # -- submission -------------------------------------------------------

    def submit(self, op: str, a, b=None, tenant: str = "default",
               trace_parent=None) -> ServeTicket:
        """Admit, route, and enqueue one request. `a`/`b` follow
        queue.submit's single-problem shapes and are ingested
        zero-copy (np.asarray views — the RPC layer hands frombuffer
        views straight through). Raises :class:`ServeRejected` on a
        shed/reject decision or while draining.

        `trace_parent` (obs/reqtrace.py) continues a caller's trace —
        the RPC server passes the client header's {"trace", "span"}
        — so one request shares a single trace_id across the process
        boundary. With the FROZEN obs/reqtrace row off this is one
        boolean: no span, no header growth, bitwise results."""
        if self._closed or self._draining:
            raise ServeRejected(
                "reject", tenant, op,
                "daemon is %s" % ("closed" if self._closed
                                  else "draining"))
        _faults.check("serve_admit", tenant=tenant, op=op)
        a = np.asarray(a)
        # the root span opens BEFORE admission so admit-wait is
        # inside it; activation makes the trace id visible to the
        # ladder's escalation payloads on this thread
        sp = _rt.begin(_rt.REQUEST_SPAN, tenant=tenant, op=op,
                       parent=trace_parent)
        t = self.admission.tenant(tenant)
        t_adm = time.perf_counter() if sp is not None else 0.0
        with _rt.active(sp):
            decision = self.admission.admit(
                t, op, a.dtype, self.tenant_inflight(tenant))
        if sp is not None:
            sp.phases["admit_s"] = time.perf_counter() - t_adm
            sp.args["decision"] = decision
        if decision in (SHED, REJECT):
            if sp is not None:
                sp.finish(outcome=decision)
            raise ServeRejected(decision, tenant, op)
        if decision == DEGRADE:
            a = a.astype(np.float32)
            if b is not None:
                b = np.asarray(b).astype(np.float32)
        st = ServeTicket(tenant, decision)
        st.span = sp
        with self._lock:
            self._submitted += 1
            self._inflight.setdefault(tenant, []).append(st)
        try:
            with _rt.active(sp):
                self._route(st, op, a, b)
        except BaseException as e:
            st._fail(e)
            if sp is not None:
                sp.finish(error=e)
            raise
        return st

    def _route(self, st: ServeTicket, op: str, a, b) -> None:
        sp = st.span
        fam = CACHED_OPS.get(op)
        if self.cache is None or fam is None:
            # the span rides the queue ticket: Ticket._resolve closes
            # it from the resolving thread with the full wall split
            st._bind(self._queue.submit(op, a, b, trace=sp))
            return
        family, factor_op, _solve_op = fam
        _faults.check("serve_cache", op=op)
        key = (family, fingerprint(a))
        factors = self.cache.get(
            key, trace=None if sp is None else sp.trace_id)
        if factors is not None:
            st.cache = "hit"
            _om.inc("serve.cache.hits")
            if sp is not None:
                sp.args["cache"] = "hit"
            self._finish_with_factors(st, op, factors, b)
            return
        st.cache = "miss"
        _om.inc("serve.cache.misses")
        if sp is not None:
            sp.args["cache"] = "miss"
        with self._lock:
            fut = self._pending_factors.get(key)
            if fut is None:
                fut = _FactorFuture(key)
                self._pending_factors[key] = fut
                fut.waiters.append((st, op, b))
                new = True
            else:
                fut.waiters.append((st, op, b))
                new = False
        if new:
            # submit OUTSIDE the lock: queue.submit may flush inline.
            # The shared factor dispatch is a CHILD span of the first
            # miss (its own closure must not end the request's root —
            # the root still has the solve ahead of it)
            if sp is not None:
                fut.trace_id = sp.trace_id
            fsp = None if sp is None else sp.child("serve::factor")
            fut.ticket = self._queue.submit(factor_op, a, trace=fsp)
            self._chain_q.put(fut)

    def _finish_with_factors(self, st: ServeTicket, op: str,
                             factors: tuple, b) -> None:
        """Resolve one request against known factors: factor requests
        complete immediately (zero dispatches — cached arrays are
        read-only views, serve/cache.py doc); solves go to the queue
        as solve-only dispatches."""
        sp = st.span
        if op == "potrf":
            st._resolve(factors[0])
            if sp is not None:      # zero-dispatch path: close here
                sp.finish(cache=st.cache)
        elif op == "getrf":
            st._resolve((factors[0], factors[1]))
            if sp is not None:
                sp.finish(cache=st.cache)
        elif op == "posv":
            b = _match_dtype(np.asarray(b), factors[0])
            st._bind(self._queue.submit("potrs", factors[0], b,
                                        trace=sp))
        else:                                  # gesv
            lu, piv = factors
            bp = _apply_pivots(
                _match_dtype(np.asarray(b), lu), piv)
            st._bind(self._queue.submit("getrs", lu, bp, trace=sp))

    def _chain_loop(self) -> None:
        """The factor-completion chainer: waits each pending
        factorization out (granting the coalescing window a grace
        before result() force-flushes), caches the factors, and fans
        the waiting solves out to the queue — they land in one
        solve-only bucket."""
        while True:
            fut = self._chain_q.get()
            if fut is None:
                return
            if self._queue._flusher is not None:
                fut.ticket._done.wait(
                    self._queue.max_wait_us / 1e6 + 1e-3)
            try:
                raw = fut.ticket.result()
            except BaseException as e:
                waiters = self._drop_future(fut)
                for (st, _op, _b) in waiters:
                    st._fail(e)
                    if st.span is not None:
                        st.span.finish(error=e)
                continue
            factors = raw if isinstance(raw, tuple) else (raw,)
            evicted = self.cache.put(fut.key, factors)
            if evicted:
                _om.inc("serve.cache.evictions", evicted)
            cached = self.cache.peek(fut.key) or factors
            waiters = self._drop_future(fut)
            from ..obs import events as _oe
            if _oe.enabled() and fut.trace_id is not None:
                _oe.instant("serve::factor_ready", cat="serve",
                            trace=fut.trace_id,
                            waiters=len(waiters))
            for (st, op, b) in waiters:
                try:
                    self._finish_with_factors(st, op, cached, b)
                except BaseException as e:
                    st._fail(e)
                    if st.span is not None:
                        st.span.finish(error=e)

    def _drop_future(self, fut: _FactorFuture) -> list:
        """Unregister a pending factorization and snapshot its
        waiters under the lock (a submit racing this either joined
        the snapshot or will see the cache/miss afresh)."""
        with self._lock:
            self._pending_factors.pop(fut.key, None)
            waiters, fut.waiters = fut.waiters, []
        return waiters

    # -- bookkeeping ------------------------------------------------------

    def tenant_inflight(self, tenant: str) -> int:
        """Unresolved requests this tenant has in the daemon (the
        quota the admission ladder bounds)."""
        with self._lock:
            ts = self._inflight.get(tenant)
            if not ts:
                return 0
            live = [t for t in ts if not t.done()]
            self._inflight[tenant] = live
            return len(live)

    def pending(self) -> int:
        with self._lock:
            tickets = [t for ts in self._inflight.values()
                       for t in ts]
        return sum(1 for t in tickets if not t.done())

    def stats(self) -> Dict[str, Any]:
        """One merged local view (obs-bus-off safe): submissions,
        admission decision counts, cache counters, and the queue's
        stats() including the per-key pending breakdown."""
        return {"submitted": self._submitted,
                "pending": self.pending(),
                "admission": self.admission.counts(),
                "cache": None if self.cache is None
                else self.cache.stats(),
                "queue": self._queue.stats()}

    def metrics_text(self) -> str:
        """The Prometheus text exposition of obs/series.py (empty
        with the FROZEN serve/metrics row off) — the RPC layer's
        ``{cmd: "metrics"}`` command serves this."""
        from ..obs import series as _series
        return _series.render_prometheus()

    # -- drain / shutdown -------------------------------------------------

    def drain(self, timeout: Optional[float] = None
              ) -> Dict[str, Any]:
        """Graceful drain (module doc): stop admitting, absorb any
        injected ``serve_drain`` fault through the retry ladder,
        flush the queue, and wait every in-flight ticket out within
        `timeout`. Returns a summary; re-raises nothing — per-ticket
        failures are counted and sampled in the summary, the drain
        itself always completes."""
        self._draining = True
        self._drain_guarded()
        self._queue.flush()
        deadline = None if timeout is None \
            else time.perf_counter() + timeout
        with self._lock:
            tickets = [t for ts in self._inflight.values()
                       for t in ts]
        done = failed = 0
        errors: List[str] = []
        for t in tickets:
            rem = None if deadline is None \
                else max(deadline - time.perf_counter(), 1e-3)
            try:
                t.result(rem)
                done += 1
            except BaseException as e:
                failed += 1
                if len(errors) < 4:
                    errors.append(str(e)[:160])
        return {"drained": done, "failed": failed, "errors": errors}

    def _drain_guarded(self) -> None:
        """The ``serve_drain`` fault site behind the same ladder as
        queue dispatches: without a plan it is one attribute load;
        with one, an injected transient fault is retried within the
        tuned budget instead of aborting the drain."""
        def _once():
            _faults.check("serve_drain", pending=self.pending())
            return True

        if _faults.active() is not None:
            _guard.retry(_once, "serve_drain")
        else:
            _once()

    def close(self, timeout: Optional[float] = 60.0) -> None:
        """drain() then release the chainer and (if owned) the
        queue. Idempotent."""
        if self._closed:
            return
        try:
            self.drain(timeout=timeout)
        finally:
            self._closed = True
            if self._chainer is not None:
                self._chain_q.put(None)
                self._chainer.join(timeout=1.0)
            if self._owns_queue:
                self._queue.close()

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _match_dtype(b: np.ndarray, factor: np.ndarray) -> np.ndarray:
    """Align the rhs dtype with the cached factor's — the queue
    already downcasts fused submissions when x64 is off, so the
    split solve-only path must mirror it rather than trip the
    queue's dtype check."""
    return b if b.dtype == factor.dtype else b.astype(factor.dtype)


def _apply_pivots(b: np.ndarray, piv: np.ndarray) -> np.ndarray:
    """Host-side LAPACK swap-target application (the gesv pre-solve
    row permutation) — an exact gather, so the split getrs path stays
    bitwise-equal to the fused gesv dispatch."""
    b2 = b[:, None] if b.ndim == 1 else b
    perm = np.arange(b2.shape[0])
    for i, p in enumerate(np.asarray(piv)):
        pi = int(p)
        perm[i], perm[pi] = perm[pi], perm[i]
    return b2[perm]
