"""LRU factor cache (ISSUE 16 tentpole, part 3).

The dominant production pattern BLASX's scheduler-level reuse result
points at (PAPERS.md): many solves against the SAME operator. Each
such solve through the plain queue re-runs the O(n^3) factorization;
this cache keys the CROPPED host factors (potrf's L, getrf's packed
L\\U + pivots) by ``resil.checkpoint.fingerprint``'s strided-CRC —
the identity check the checkpoint layer already trusts to tell "the
same matrix" from "a different one" — so a repeat solve skips
straight to the O(n^2) solve-only dispatch (batch/drivers potrs /
getrs), and the PR 15 ragged path coalesces the resulting solve-only
stream.

Mechanism only: byte-bounded LRU over host numpy arrays, thread-safe,
with local hit/miss/eviction counts (readable with the obs bus off —
serve/server.py publishes the ``serve.cache.*`` obs mirrors at its
decision points). Cached arrays are stored contiguous and
WRITE-PROTECTED: a factor served from cache is handed to callers as
the cached buffer itself (zero-copy), so the read-only flag is what
keeps a mutating caller from silently corrupting every later hit.

The budget rides the tuned ``serve/cache_mb`` row — FROZEN 0 = no
cache object exists at all and the daemon forwards requests unchanged
to the queue (the cold route is bitwise-identical to direct queue
use, pinned by tests).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np


class FactorCache:
    """Byte-bounded LRU of factor tuples keyed by
    ``(family, fingerprint)``. Values are tuples of host arrays —
    ``(L,)`` for the Cholesky family, ``(lu, piv)`` for LU."""

    def __init__(self, budget_mb: float) -> None:
        self.budget_bytes = int(float(budget_mb) * (1 << 20))
        self._lock = threading.Lock()
        #: key -> (factors tuple, nbytes), LRU order (last = MRU)
        self._entries: "OrderedDict[Any, Tuple[tuple, int]]" = \
            OrderedDict()
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    def get(self, key, trace: Optional[str] = None
            ) -> Optional[tuple]:
        """The cached factor tuple (promoted to MRU), or None.

        `trace` (obs/reqtrace.py): the requesting span's trace id.
        When given and the bus is on, the lookup outcome is published
        as a trace-stamped ``serve::cache`` instant — the hit/miss
        leg of a single request stays reconstructable end-to-end.
        None (tracing off) skips even the bus check."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self._misses += 1
                out = None
            else:
                self._entries.move_to_end(key)
                self._hits += 1
                out = e[0]
        if trace is not None:
            from ..obs import events as _oe
            if _oe.enabled():
                # published OUTSIDE the lock (lock-discipline)
                _oe.instant("serve::cache", cat="serve", trace=trace,
                            outcome="miss" if out is None else "hit")
        return out

    def peek(self, key) -> Optional[tuple]:
        """get() without counting or promotion — for the server's
        chainer, which re-reads the entry it just put (serving the
        write-protected stored arrays) and must not skew hit stats."""
        with self._lock:
            e = self._entries.get(key)
            return None if e is None else e[0]

    def put(self, key, factors: tuple) -> int:
        """Insert one factor tuple, evicting LRU entries until the
        byte budget holds. Returns the number of evictions this
        insert caused (serve/server.py publishes the obs mirror). An
        entry larger than the whole budget is not cached (0
        evictions — never flush a working set for one oversized
        operator); a re-insert of a present key just promotes it."""
        factors = tuple(
            _readonly(np.ascontiguousarray(f)) for f in factors)
        nb = sum(int(f.nbytes) for f in factors)
        if nb > self.budget_bytes:
            return 0
        evicted = 0
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return 0
            self._entries[key] = (factors, nb)
            self._bytes += nb
            while self._bytes > self.budget_bytes and \
                    len(self._entries) > 1:
                _k, (_f, old_nb) = self._entries.popitem(last=False)
                self._bytes -= old_nb
                self._evictions += 1
                evicted += 1
        return evicted

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> Dict[str, int]:
        """Local mirror of the serve.cache.* obs counters (works with
        the bus disabled, like queue.stats())."""
        with self._lock:
            return {"hits": self._hits, "misses": self._misses,
                    "evictions": self._evictions,
                    "entries": len(self._entries),
                    "bytes": self._bytes,
                    "budget_bytes": self.budget_bytes}


def _readonly(a: np.ndarray) -> np.ndarray:
    a.setflags(write=False)
    return a
