"""Length-prefixed socket RPC for out-of-process clients (ISSUE 16
tentpole, part 1b).

Deliberately minimal framing — this is a loopback/cluster-internal
wire, not a public protocol:

  request  := u32be header_len | header JSON | payload bytes
  response := u32be header_len | header JSON | payload bytes

The submit header carries ``{cmd, op, tenant, dtype, shape,
[rhs_dtype, rhs_shape]}`` and the payload is the C-order array bytes
(A then B). The server ingests payloads with ``recv_into`` into one
preallocated buffer and hands ``np.frombuffer`` views straight to
:meth:`Server.submit` — zero-copy from socket buffer to the
coalescing queue's staging pad. Responses mirror the scheme:
``{status: "ok", parts: [{dtype, shape}...], decision, cache}``
followed by the result bytes, or ``{status: "rejected"|"error",
error, decision}`` with no payload.

``{cmd: "stats"}`` returns the merged :meth:`Server.stats` dict
(tuple keys of the queue's per-key breakdown stringified for JSON);
``{cmd: "metrics"}`` returns obs/series.py's Prometheus text
exposition (``{"text": ...}``; empty with serve/metrics off).

Trace propagation (ISSUE 18, obs/reqtrace.py): with tracing ON the
client mints a ``serve::rpc`` span and adds ``{"trace", "span"}`` to
the submit header; the server continues that trace through
``Server.submit(trace_parent=)`` and echoes the trace id in the ok
response. With the FROZEN obs/reqtrace row off NEITHER side adds a
field — the wire format is byte-identical to PR 17 (pinned).

One daemon thread accepts; one thread per connection serves
sequential requests (clients pipeline by opening more connections —
coalescing across connections is exactly what the queue is for).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import reqtrace as _rt
from .server import Server, ServeRejected

_HDR = struct.Struct(">I")
#: refuse absurd frames rather than allocate attacker-sized buffers
MAX_HEADER_BYTES = 1 << 20


def _send_frame(sock: socket.socket, header: Dict[str, Any],
                payloads: Tuple[np.ndarray, ...] = ()) -> None:
    hb = json.dumps(header, separators=(",", ":")).encode()
    sock.sendall(_HDR.pack(len(hb)) + hb)
    for p in payloads:
        sock.sendall(np.ascontiguousarray(p).data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[memoryview]:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            return None
        got += r
    return memoryview(buf)


def _recv_frame(sock: socket.socket
                ) -> Optional[Tuple[Dict[str, Any], socket.socket]]:
    raw = _recv_exact(sock, _HDR.size)
    if raw is None:
        return None
    (hlen,) = _HDR.unpack(raw)
    if hlen > MAX_HEADER_BYTES:
        raise ValueError("rpc header of %d bytes refused" % hlen)
    hb = _recv_exact(sock, hlen)
    if hb is None:
        return None
    return json.loads(bytes(hb)), sock


def _recv_array(sock: socket.socket, dtype: str,
                shape: List[int]) -> Optional[np.ndarray]:
    dt = np.dtype(dtype)
    n = int(np.prod(shape)) if shape else 1
    raw = _recv_exact(sock, n * dt.itemsize)
    if raw is None:
        return None
    # frombuffer: the recv buffer IS the array (zero-copy ingestion)
    return np.frombuffer(raw, dtype=dt).reshape(shape)


class RpcServer:
    """Socket front-end over one :class:`Server`. Binds immediately
    (port 0 = ephemeral; read ``.address``)."""

    def __init__(self, server: Server, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self._server = server
        self._sock = socket.socket(socket.AF_INET,
                                   socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET,
                              socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.address: Tuple[str, int] = self._sock.getsockname()
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="serve-rpc-accept",
            daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return                      # socket closed
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="serve-rpc-conn",
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
                while True:
                    frame = _recv_frame(conn)
                    if frame is None:
                        return
                    self._handle(frame[0], conn)
        except OSError:
            return

    def _handle(self, hdr: Dict[str, Any],
                conn: socket.socket) -> None:
        cmd = hdr.get("cmd")
        if cmd == "stats":
            _send_frame(conn, {"status": "ok",
                               "stats": _jsonable(
                                   self._server.stats())})
            return
        if cmd == "metrics":
            _send_frame(conn, {"status": "ok",
                               "text":
                               self._server.metrics_text()})
            return
        if cmd != "submit":
            _send_frame(conn, {"status": "error",
                               "error": "unknown cmd %r" % (cmd,)})
            return
        a = _recv_array(conn, hdr["dtype"], hdr["shape"])
        b = None
        if hdr.get("rhs_shape") is not None:
            b = _recv_array(conn, hdr.get("rhs_dtype", hdr["dtype"]),
                            hdr["rhs_shape"])
        if a is None or (hdr.get("rhs_shape") is not None
                         and b is None):
            return                          # peer hung up mid-frame
        # the client's trace context, when it sent one (reqtrace on):
        # Server.submit continues the trace across the wire
        parent = {"trace": hdr["trace"],
                  "span": hdr.get("span")} if "trace" in hdr else None
        try:
            t = self._server.submit(hdr["op"], a, b,
                                    tenant=hdr.get("tenant",
                                                   "default"),
                                    trace_parent=parent)
            out = t.result(timeout=hdr.get("timeout_s", 120.0))
        except ServeRejected as e:
            _send_frame(conn, {"status": "rejected",
                               "decision": e.decision,
                               "error": str(e)})
            return
        except Exception as e:
            _send_frame(conn, {"status": "error",
                               "error": "%s: %s"
                               % (type(e).__name__, e)})
            return
        parts = tuple(np.asarray(p) for p in
                      (out if isinstance(out, tuple) else (out,)))
        rh = {"status": "ok",
              "decision": t.decision, "cache": t.cache,
              "parts": [{"dtype": p.dtype.str,
                         "shape": list(p.shape)}
                        for p in parts]}
        if t.span is not None:      # echo only when traced: the off
            rh["trace"] = t.span.trace_id   # wire stays identical
        _send_frame(conn, rh, parts)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "RpcServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RpcClient:
    """Blocking client for one connection (open more for pipelining —
    the daemon's queue coalesces across connections)."""

    def __init__(self, address: Tuple[str, int]) -> None:
        self._sock = socket.create_connection(address)
        self._sock.setsockopt(socket.IPPROTO_TCP,
                              socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        #: trace id of the most recent submit's response (tracing on
        #: both sides), else None — lets a caller join its local
        #: records to the daemon's without reparsing headers
        self.last_trace: Optional[str] = None

    def submit(self, op: str, a, b=None, tenant: str = "default",
               timeout_s: float = 120.0):
        """Round-trip one request. Returns the result array (or
        tuple); raises :class:`ServeRejected` on shed/reject and
        RuntimeError on server-side errors. With tracing on, mints
        the client ``serve::rpc`` span and carries its ids in the
        header — the daemon's spans share this trace_id."""
        a = np.ascontiguousarray(a)
        hdr: Dict[str, Any] = {
            "cmd": "submit", "op": op, "tenant": tenant,
            "timeout_s": timeout_s,
            "dtype": a.dtype.str, "shape": list(a.shape)}
        sp = _rt.begin(_rt.CLIENT_SPAN, tenant=tenant, op=op)
        if sp is not None:
            hdr["trace"] = sp.trace_id
            hdr["span"] = sp.span_id
        payloads: List[np.ndarray] = [a]
        if b is not None:
            b = np.ascontiguousarray(b)
            hdr["rhs_dtype"] = b.dtype.str
            hdr["rhs_shape"] = list(b.shape)
            payloads.append(b)
        try:
            with self._lock:
                _send_frame(self._sock, hdr, tuple(payloads))
                resp = _recv_frame(self._sock)
                if resp is None:
                    raise RuntimeError("rpc server hung up")
                rh = resp[0]
                if rh["status"] == "rejected":
                    raise ServeRejected(
                        rh.get("decision", "reject"),
                        tenant, op, rh.get("error", ""))
                if rh["status"] != "ok":
                    raise RuntimeError("rpc error: %s"
                                       % rh.get("error"))
                parts = []
                for spec in rh["parts"]:
                    p = _recv_array(self._sock, spec["dtype"],
                                    spec["shape"])
                    if p is None:
                        raise RuntimeError("rpc server hung up "
                                           "mid-payload")
                    parts.append(p)
            self.last_trace = rh.get("trace")
            if sp is not None:
                sp.finish(decision=rh.get("decision") or "",
                          cache=rh.get("cache") or "")
        except BaseException as e:
            if sp is not None:
                sp.finish(error=e)
            raise
        return parts[0] if len(parts) == 1 else tuple(parts)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            _send_frame(self._sock, {"cmd": "stats"})
            resp = _recv_frame(self._sock)
        if resp is None or resp[0].get("status") != "ok":
            raise RuntimeError("rpc stats failed")
        return resp[0]["stats"]

    def metrics(self) -> str:
        """The daemon's Prometheus text exposition (obs/series.py;
        empty string with serve/metrics off)."""
        with self._lock:
            _send_frame(self._sock, {"cmd": "metrics"})
            resp = _recv_frame(self._sock)
        if resp is None or resp[0].get("status") != "ok":
            raise RuntimeError("rpc metrics failed")
        return resp[0]["text"]

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "RpcClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _jsonable(x):
    """Deep-convert a stats dict for JSON: tuple keys (the queue's
    per-key pending breakdown) become strings, numpy scalars become
    Python numbers."""
    if isinstance(x, dict):
        return {(k if isinstance(k, str) else repr(k)): _jsonable(v)
                for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.generic):
        return x.item()
    return x
