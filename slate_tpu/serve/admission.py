"""Tenant-aware admission control (ISSUE 16 tentpole, part 2).

Every request entering the daemon passes ONE decision point, driven
by the obs substrate PRs 14-15 built rather than by guesswork:

  * **queue composition** — per-key pending count, queued true-extent
    flops, and oldest-request age from ``CoalescingQueue.stats()``'s
    ``pending_by_key`` breakdown (ISSUE 16 satellite), plus the
    flops-weighted mean occupancy;
  * **dispatch history** — strategy/ceiling and padding-waste-flops
    from the flight recorder's ``batch.dispatch`` ledger records
    (obs/ledger.py, when the recorder is on);
  * **load forecast** — the stall watchdog's ``health.eta_seconds``
    gauge (obs/health.py heartbeats).

The decision ladder (strictest first):

  ``reject``   the tenant's pending-request quota is full — a hard
               per-tenant bound, every priority class;
  ``shed``     the watchdog forecasts more than ``serve/shed_eta_s``
               seconds of backlog and the tenant rides the lowest
               priority class — drop now, retry later beats queuing
               behind work that cannot finish in SLO;
  ``degrade``  the oldest pending request is older than
               ``serve/max_queue_age_ms`` and the request is a
               degradable f64 — serve it in f32 (half the bytes and
               roughly half the MXU time) instead of shedding it;
  ``admit``    everything else.

Every non-admit decision funnels through the PR 9 resil guard
(:func:`~slate_tpu.resil.guard.record_escalation` rungs
``serve_shed`` / ``serve_degrade`` / ``serve_reject`` — the lint
rule-4 contract) with the elastic-mesh remap-record mirror attached
(dist/elastic.py ``remap_records()``, ISSUE 19 — a shed during mesh
churn must be attributable to the churn), is counted as its
``serve.*`` obs counter, and appends a ``serve.admit`` ledger record
carrying the pressure inputs it was made from. Thresholds ride the tune subsystem (explicit
argument > measured entry > FROZEN ``serve/*`` rows).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from ..obs import ledger as _ledger
from ..obs import reqtrace as _reqtrace
from ..obs import series as _series
from ..resil import guard as _guard

#: priority classes, lowest first: "batch" work sheds first under
#: load, "interactive" work is never shed or degraded
PRIORITIES = ("batch", "standard", "interactive")

ADMIT = "admit"
SHED = "shed"
DEGRADE = "degrade"
REJECT = "reject"

#: decision -> the serve.* obs counter it bumps (server publishes)
DECISION_COUNTERS = {ADMIT: "serve.admitted", SHED: "serve.shed",
                     DEGRADE: "serve.degraded",
                     REJECT: "serve.rejected"}


class TenantConfig:
    """One tenant's admission contract: quota (pending-request cap,
    None = the tuned ``serve/max_pending`` default), priority class,
    and whether its f64 requests may be served degraded in f32."""

    __slots__ = ("name", "priority", "max_pending", "degradable")

    def __init__(self, name: str, priority: str = "standard",
                 max_pending: Optional[int] = None,
                 degradable: bool = True) -> None:
        if priority not in PRIORITIES:
            raise ValueError(f"unknown priority {priority!r}; have "
                             f"{PRIORITIES}")
        self.name = str(name)
        self.priority = priority
        self.max_pending = None if max_pending is None \
            else int(max_pending)
        self.degradable = bool(degradable)


class AdmissionController:
    """The daemon's single admission decision point (module doc).
    Thread-safe; keeps local decision counters readable with the obs
    bus off (the queue.stats() pattern)."""

    def __init__(self, queue, tenants=None, opts=None,
                 max_pending: Optional[int] = None,
                 shed_eta_s: Optional[float] = None,
                 max_queue_age_ms: Optional[float] = None) -> None:
        from ..tune.select import tuned_int
        self._queue = queue
        self.default_max_pending = int(max_pending) \
            if max_pending is not None \
            else tuned_int("serve", "max_pending", 4096, opts=opts)
        self.shed_eta_s = float(shed_eta_s) \
            if shed_eta_s is not None \
            else float(tuned_int("serve", "shed_eta_s", 30,
                                 opts=opts))
        self.max_queue_age_s = (float(max_queue_age_ms)
                                if max_queue_age_ms is not None
                                else float(tuned_int(
                                    "serve", "max_queue_age_ms", 500,
                                    opts=opts))) / 1e3
        #: SLO burn percentage above which the ladder sheds lowest-
        #: priority work / degrades degradable f64 (ISSUE 18: the
        #: series SLO windows feed admission, not just dashboards)
        self.slo_burn_pct = float(tuned_int(
            "serve", "slo_burn_pct", 50, opts=opts))
        self._lock = threading.Lock()
        self._tenants: Dict[str, TenantConfig] = {}
        for t in (tenants or []):
            self._tenants[t.name] = t
        self._counts = {d: 0 for d in DECISION_COUNTERS}
        self._led_seq = 0

    def tenant(self, name: str) -> TenantConfig:
        """The named tenant's config (auto-registered at defaults on
        first sight — an open daemon; pass ``tenants=`` for closed
        quota sets)."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                t = self._tenants[name] = TenantConfig(name)
            return t

    def quota(self, t: TenantConfig) -> int:
        return t.max_pending if t.max_pending is not None \
            else self.default_max_pending

    # -- pressure inputs --------------------------------------------------

    def pressure(self) -> Dict[str, Any]:
        """One snapshot of every admission input (module doc): queue
        composition from stats()'s per-key breakdown, the watchdog ETA
        gauge, and strategy/ceiling/padding-waste from the most recent
        ledger dispatch records (empty/None when those substrates are
        off — decisions then fall through to the quota bound alone)."""
        s = self._queue.stats()
        pend = s.get("pending_by_key", {})
        p: Dict[str, Any] = {
            "pending": sum(v["count"] for v in pend.values()),
            "pending_keys": len(pend),
            "queued_flops": float(sum(v["queued_flops"]
                                      for v in pend.values())),
            "oldest_age_s": max((v["age_s"] for v in pend.values()),
                                default=0.0),
            "occupancy_weighted": s.get("mean_occupancy_weighted",
                                        0.0),
            "eta_s": None, "recent_waste_flops": None,
            "recent_strategy": None, "recent_ceiling": None,
        }
        from ..obs import events as obs_events
        if obs_events.enabled():
            from ..obs import metrics as om
            p["eta_s"] = om.get_gauge("health.eta_seconds")
        if _ledger.enabled():
            recs = _ledger.records("batch.dispatch")[-16:]
            wastes = [r.meta["waste_flops"] for r in recs
                      if "waste_flops" in r.meta]
            if wastes:
                p["recent_waste_flops"] = round(
                    sum(wastes) / len(wastes), 4)
            if recs:
                p["recent_strategy"] = recs[-1].meta.get("strategy")
                p["recent_ceiling"] = recs[-1].meta.get("ceiling")
        return p

    # -- the decision -----------------------------------------------------

    def decide(self, t: TenantConfig, op: str, dtype,
               inflight: int,
               pressure: Optional[Dict[str, Any]] = None) -> str:
        """Pure decision (module-doc ladder) — no counters, no
        publication; unit-testable on a fabricated pressure dict.
        ``pressure["slo_burn"]`` (obs/series.py :func:`slo_burn`
        shape, attached by :meth:`admit` when metrics are on) adds
        the SLO rungs: a tenant burning past ``serve/slo_burn_pct``
        sheds at the lowest priority and degrades where the age rung
        would — latency debt is pressure even when the queue is
        momentarily calm."""
        if pressure is None:
            pressure = self.pressure()
        return self._decide_why(t, op, dtype, inflight, pressure)[0]

    def _decide_why(self, t: TenantConfig, op: str, dtype,
                    inflight: int, pressure: Dict[str, Any]):
        """(decision, why): the ladder plus WHICH objective drove a
        non-admit — admit() records it in the escalation payload."""
        if inflight >= self.quota(t):
            return REJECT, {"inflight": inflight,
                            "quota": self.quota(t)}
        eta = pressure.get("eta_s")
        if eta is not None and eta > self.shed_eta_s \
                and t.priority == PRIORITIES[0]:
            return SHED, {"eta_s": eta}
        burn = pressure.get("slo_burn")
        burning = burn is not None \
            and burn["burn"] * 100.0 > self.slo_burn_pct
        if burning and t.priority == PRIORITIES[0]:
            return SHED, {"objective": burn["objective"],
                          "burn": burn["burn"]}
        degradable = t.degradable and t.priority != PRIORITIES[-1] \
            and np.dtype(dtype) == np.float64
        if pressure.get("oldest_age_s", 0.0) > self.max_queue_age_s \
                and degradable:
            return DEGRADE, {"oldest_age_s":
                             round(pressure["oldest_age_s"], 4)}
        if burning and degradable:
            return DEGRADE, {"objective": burn["objective"],
                             "burn": burn["burn"]}
        return ADMIT, {}

    def admit(self, t: TenantConfig, op: str, dtype,
              inflight: int) -> str:
        """decide() plus the bookkeeping contract: count the decision
        (local + ``serve.*`` obs counter), funnel every non-admit
        through the resil escalation ladder, and append the
        ``serve.admit`` ledger record carrying the pressure inputs."""
        t0 = time.perf_counter()
        pressure = self.pressure()
        burn = _series.slo_burn(t.name)
        if burn is not None:
            pressure["slo_burn"] = burn
        decision, why = self._decide_why(t, op, dtype, inflight,
                                         pressure)
        with self._lock:
            self._counts[decision] += 1
            seq = self._led_seq
            self._led_seq += 1
        # every escalation stamps the active trace id (reqtrace's
        # thread-local — None with tracing off, which the funnel's
        # ctx filter drops) and the objective the ladder shed/
        # degraded on (the `why` dict); linted by SL801
        tid = _reqtrace.current_trace_id()
        mesh = None
        if decision != ADMIT:
            # elastic-mesh churn context (ISSUE 19): a shed/degrade
            # fired while the mesh is re-owning panels or shrinking
            # around a lost host must say so — the escalation payload
            # carries the remap-record mirror (dist/elastic.py,
            # readable with the obs bus off)
            from ..dist.elastic import remap_records
            mesh = remap_records()
            why = dict(why, mesh_remaps=mesh["remaps"],
                       mesh_panels_moved=mesh["panels_moved"],
                       mesh_shrinks=mesh["shrinks"])
            if mesh["last"] is not None:
                why["mesh_last_remap"] = "%s@%d+%d" % (
                    mesh["last"]["op"], mesh["last"]["boundary"],
                    mesh["last"]["moved"])
        if decision == SHED:
            _guard.record_escalation(
                "serve_shed", tenant=t.name, op=op, trace=tid,
                **why)
        elif decision == DEGRADE:
            _guard.record_escalation(
                "serve_degrade", tenant=t.name, op=op, trace=tid,
                **why)
        elif decision == REJECT:
            _guard.record_escalation(
                "serve_reject", tenant=t.name, op=op, trace=tid,
                **why)
        from ..obs import events as obs_events
        if obs_events.enabled():
            # literal per-decision publishes (not a DECISION_COUNTERS
            # lookup): the obs-literals analyzer collects these names
            # into docs/OBS_REFERENCE.md and near-miss-checks them
            from ..obs import metrics as om
            if decision == SHED:
                om.inc("serve.shed")
            elif decision == DEGRADE:
                om.inc("serve.degraded")
            elif decision == REJECT:
                om.inc("serve.rejected")
            else:
                om.inc("serve.admitted")
        if _ledger.enabled():
            meta = {"tenant": t.name, "op": op,
                    "decision": decision, "inflight": inflight}
            meta.update({k: v for k, v in pressure.items()
                         if v is not None})
            if mesh is not None:
                meta["mesh_remap"] = mesh
            _ledger.append("serve.admit", step=seq,
                           phases={"other":
                                   time.perf_counter() - t0},
                           meta=meta)
        return decision

    def counts(self) -> Dict[str, int]:
        """Local decision counters (obs-bus-off mirror)."""
        with self._lock:
            return dict(self._counts)
