"""slate_tpu — TPU-native distributed dense linear algebra.

A brand-new framework with the capabilities of SLATE (the ScaLAPACK
successor): parallel BLAS-3, LU/Cholesky/indefinite solvers with
mixed-precision refinement, QR/LQ least squares, SVD, Hermitian
eigensolvers — built on JAX/XLA/Pallas for TPU meshes instead of
MPI+OpenMP+CUDA for GPU clusters. See SURVEY.md for the reference map.
"""

from .core import *          # noqa: F401,F403
from .parallel import *      # noqa: F401,F403
from .linalg import *        # noqa: F401,F403
from . import ops            # noqa: F401
from .matgen import generate_matrix  # noqa: F401
from . import api, batch, c_api, dist, obs, resil, serve, tune, utils  # noqa: F401,E501
from .api import simplified  # noqa: F401
from .utils import Timers, print_matrix  # noqa: F401

__version__ = "0.1.0"
