"""Merged observability snapshot + human-readable per-run report
(ISSUE 3 tentpole part 4, second half).

snapshot() is the machine surface: metrics registries, xprof
analyses, per-driver span aggregates from the bus, and the tune/
decision counters, one JSON-serializable dict — bench.py --obs emits
it into the BENCH trajectory next to the --tune stats.

report() is the human surface the acceptance criteria read: per
driver, invocation counts and wall (compile-side vs eager split),
and — when an xprof analysis exists for it — analytic FLOPs, peak
memory, compile-vs-execute wall, and the collective counts by kind.
"""

from __future__ import annotations

import io
from typing import Any, Dict, Optional

from . import events, health, ledger, metrics, series, xprof


def _driver_aggregate(evs) -> Dict[str, Dict[str, Any]]:
    """Fold the bus's driver/jit spans into per-op totals: `calls`
    (eager entries), `trace_calls` (entries under jit tracing),
    and wall seconds for each."""
    agg: Dict[str, Dict[str, Any]] = {}
    for e in evs:
        if e.ph != events.PH_SPAN or e.cat not in ("driver", "jit"):
            continue
        if e.cat == "jit" and e.name in ("backend_compile",):
            continue
        d = agg.setdefault(e.name, {"calls": 0, "wall_seconds": 0.0,
                                    "trace_calls": 0,
                                    "trace_seconds": 0.0})
        if e.cat == "driver":
            d["calls"] += 1
            d["wall_seconds"] += e.dur
        else:
            d["trace_calls"] += 1
            d["trace_seconds"] += e.dur
    for d in agg.values():
        d["wall_seconds"] = round(d["wall_seconds"], 6)
        d["trace_seconds"] = round(d["trace_seconds"], 6)
    return dict(sorted(agg.items()))


def snapshot() -> Dict[str, Any]:
    """One JSON-serializable dict of everything observed so far."""
    try:
        from ..tune import stats as tune_stats
        tune_snap = tune_stats.snapshot()
    except Exception:
        tune_snap = {}
    evs = events.events()          # ONE ring copy serves everything
    snap = {
        "enabled": events.enabled(),
        "events": len(evs),
        "events_dropped": events.dropped(),
        "metrics": metrics.snapshot(),
        "drivers": _driver_aggregate(evs),
        "analyses": xprof.analyses(),
        "tune": tune_snap,
    }
    # flight recorder + watchdog (ISSUE 14): the critical-path
    # attribution of every ledger step record, and the stall stats —
    # both empty-cheap when the FROZEN off-state kept them silent
    if ledger.count():
        snap["ledger"] = xprof.attribute_run(
            counters=snap["metrics"]["counters"])
    hs = health.stats()
    if hs["heartbeats"] or hs["stalls"]:
        snap["health"] = hs
    # serving-tier SLO time-series (ISSUE 18): quantile summaries +
    # per-tenant burn, present only when serve/metrics is on AND at
    # least one sample landed (the FROZEN off-state adds no key)
    if series.enabled():
        ss = series.snapshot()
        if ss["series"] or ss["slo"]:
            snap["serve_series"] = ss
    return snap


def _fmt_bytes(b) -> str:
    if b is None:
        return "-"
    b = float(b)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if b < 1024 or unit == "GiB":
            return "%.1f %s" % (b, unit)
        b /= 1024
    return "%.1f GiB" % b


def _fmt_flops(f) -> str:
    if not f:
        return "-"
    f = float(f)
    for unit in ("", "K", "M", "G", "T"):
        if f < 1000 or unit == "T":
            return "%.2f %sFLOP" % (f, unit)
        f /= 1000
    return "%.2f TFLOP" % f


def report(path: Optional[str] = None) -> str:
    """Render the per-run report; also written to `path` when given."""
    snap = snapshot()
    out = io.StringIO()
    w = out.write
    w("== slate_tpu observability report ==\n")
    w("events: %d recorded (%d dropped)\n"
      % (snap["events"], snap["events_dropped"]))
    if snap["events_dropped"]:
        # ISSUE 14 satellite: a silently-evicted ring invalidates
        # every span-derived number below — say so ONCE, loudly
        w("WARNING: %d events were dropped from the bounded ring — "
          "span-derived attribution undercounts; raise "
          "events.EVENT_CAP or drain more often\n"
          % snap["events_dropped"])
    cnt = snap["metrics"]["counters"]
    if cnt:
        w("\n-- counters --\n")
        for k, v in cnt.items():
            w("  %-42s %s\n" % (k, round(v, 6)))
    hists = snap["metrics"]["histograms"]
    if hists:
        w("\n-- timings/samples (count, mean, min..max) --\n")
        for k, h in hists.items():
            w("  %-42s n=%-5d mean=%.4g  [%.4g .. %.4g]\n"
              % (k, h["count"], h["mean"], h["min"], h["max"]))
    drv = snap["drivers"]
    if drv:
        w("\n-- drivers (bus spans) --\n")
        w("  %-18s %6s %12s %8s %12s\n"
          % ("op", "calls", "wall(s)", "traces", "trace(s)"))
        for op, d in drv.items():
            w("  %-18s %6d %12.4f %8d %12.4f\n"
              % (op, d["calls"], d["wall_seconds"], d["trace_calls"],
                 d["trace_seconds"]))
    ana = snap["analyses"]
    if ana:
        w("\n-- compiled-program attribution (xprof) --\n")
        for label, r in sorted(ana.items()):
            w("  %s:\n" % label)
            w("    flops          %s\n" % _fmt_flops(r.get("flops")))
            w("    bytes accessed %s\n"
              % _fmt_bytes(r.get("bytes_accessed")))
            w("    peak memory    %s\n"
              % _fmt_bytes(r.get("peak_bytes")))
            w("    compile        %.4f s\n"
              % r.get("compile_seconds", 0.0))
            if "execute_seconds" in r:
                w("    execute        %.6f s\n" % r["execute_seconds"])
            coll = r.get("collectives") or {}
            shown = {k: v for k, v in coll.items()
                     if k != "total" and v}
            w("    collectives    %s\n"
              % (", ".join("%s=%d" % kv for kv in sorted(shown.items()))
                 if shown else "none"))
    led = snap.get("ledger")
    if led and led.get("records"):
        w("\n-- critical path (flight recorder, %d step records"
          % led["records"])
        if led.get("dropped"):
            w("; WARNING %d dropped — attribution undercounts"
              % led["dropped"])
        w(") --\n")
        total = led["total_wall_s"] or 1e-12
        w("  total step wall %.4f s; compile (overlapping) %.4f s\n"
          % (led["total_wall_s"], led.get("compile_s", 0.0)))
        for b, s in sorted(led["buckets"].items(),
                           key=lambda kv: -kv[1]):
            w("  %-16s %10.4f s  %5.1f%%\n" % (b, s, 100 * s / total))
        for h, d in led.get("by_host", {}).items():
            w("  host %-4s wall %.4f s  %s\n"
              % (h, d["wall_s"],
                 " ".join("%s=%.4f" % kv
                          for kv in sorted(d["phases"].items()))))
        top = led.get("top_panels") or []
        if top:
            w("  slowest panels:\n")
            for p in top[:4]:
                w("    %-18s step %-4d host %d  %.4f s  (%s)\n"
                  % (p["op"], p["step"], p["host"], p["wall_s"],
                     ", ".join("%s=%.4f" % kv
                               for kv in sorted(p["phases"].items()))))
    hs = snap.get("health")
    if hs:
        w("\n-- watchdog --\n")
        w("  heartbeats=%d stalls=%d\n"
          % (hs.get("heartbeats", 0), hs.get("stalls", 0)))
        for op, t in sorted((hs.get("ops") or {}).items()):
            w("  %-20s step=%s/%s median_step=%.4gs%s\n"
              % (op, t["step"], t["total"], t["median_step_s"],
                 "  STALLED" if t["stalled"] else ""))
    sv = snap.get("serve_series")
    if sv:
        w("\n-- serving latency (obs/series sketches) --\n")
        for key, sm in sorted(sv.get("series", {}).items()):
            if not sm:
                continue
            name, tenant, op = (key.split("|") + ["", ""])[:3]
            w("  %-22s %-10s %-8s n=%-5d p50=%.4gs p95=%.4gs "
              "p99=%.4gs\n"
              % (name, tenant or "-", op or "-", sm["count"],
                 sm.get("p50", 0.0), sm.get("p95", 0.0),
                 sm.get("p99", 0.0)))
        slo = {t: b for t, b in (sv.get("slo") or {}).items() if b}
        if slo:
            w("  SLO burn:\n")
            for t, b in sorted(slo.items()):
                w("    %-20s %s burn=%.2f%% (window %d)\n"
                  % (t, b["objective"], 100 * b["burn"],
                     b["window"]))
    tune = snap.get("tune") or {}
    if tune.get("decisions_total"):
        w("\n-- tuned decisions --\n")
        w("  total=%d by_source=%r cache_hits=%d cache_misses=%d\n"
          % (tune.get("decisions_total", 0),
             tune.get("decisions_by_source", {}),
             tune.get("cache_hits", 0), tune.get("cache_misses", 0)))
    text = out.getvalue()
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text
