"""Counter/gauge/histogram registry + jit compile accounting (ISSUE 3
tentpole part 4, first half; obs/report.py renders it).

What lives here and who publishes it:
  * driver invocation counters — events.driver hook;
  * jit compile wall time — a jax.monitoring duration listener
    (backend_compile / jaxpr_trace events), installed once on
    events.enable();
  * recompile detection keyed by (fn, shapes/dtype) — record_trace,
    fed by events.driver whenever a driver body runs under tracing
    (a jit cache hit never re-enters Python, so a second trace at a
    NEW key is exactly a recompile);
  * iterative-solver sweep counts, polar/refine convergence flags,
    mixed-precision fallbacks — linalg/refine.py + eig/svd drivers via
    observe_concrete (values under jit tracing are Tracers and are
    skipped: runtime values are unobservable from Python there);
  * OOC panel staging bytes — linalg/stream.py's _h2d/_d2h — and the
    stream engine's residency-cache counters
    (ooc.cache.hits/misses/evictions/invalidations/served_bytes) and
    prefetch/writeback overlap fractions (ooc.prefetch.*, ooc.d2h.*),
    published by StreamEngine.finish().

All mutation is gated on events.enabled() — the same single flag as
the bus — so the disabled path stays one boolean check.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Tuple

from . import events

_lock = threading.Lock()
_counters: Dict[str, float] = {}
_gauges: Dict[str, Any] = {}
#: name -> [count, total, min, max]
_hists: Dict[str, list] = {}
#: fn -> set of (shape, dtype) signatures already traced
_trace_keys: Dict[str, set] = {}

_monitoring_installed = False


def inc(name: str, value: float = 1) -> None:
    if not events.enabled():
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + value


def set_gauge(name: str, value) -> None:
    if not events.enabled():
        return
    with _lock:
        _gauges[name] = value


def get_gauge(name: str, default=None):
    """Point read of one gauge (the serve/ admission layer polls the
    watchdog's ``health.eta_seconds`` this way per decision — a full
    snapshot() deep copy per request would be waste)."""
    with _lock:
        return _gauges.get(name, default)


def observe(name: str, value: float) -> None:
    """Histogram sample (count/total/min/max — enough for a per-run
    report without binning policy)."""
    if not events.enabled():
        return
    v = float(value)
    with _lock:
        h = _hists.get(name)
        if h is None:
            _hists[name] = [1, v, v, v]
        else:
            h[0] += 1
            h[1] += v
            h[2] = min(h[2], v)
            h[3] = max(h[3], v)


def observe_concrete(name: str, value) -> bool:
    """observe() only when `value` is a concrete number — under jit
    tracing it is a Tracer and the sample is silently skipped (the
    eager/bench path is where these flags are readable). Returns
    whether the sample landed."""
    if not events.enabled():
        return False
    try:
        v = float(value)
    except Exception:
        return False
    observe(name, v)
    return True


def flag_concrete(name: str, flag_value) -> bool:
    """Count how often a boolean runtime flag is SET (e.g. a refine
    fallback taken, a polar iteration unconverged). Tracer-safe like
    observe_concrete."""
    if not events.enabled():
        return False
    try:
        f = bool(flag_value)
    except Exception:
        return False
    if f:
        inc(name)
    return True


def record_trace(fn: str, sig: Tuple) -> str:
    """One jit trace of `fn` at signature `sig` observed. Returns
    'first' (fn never traced), 'new-shape' (fn known, sig new — a
    RECOMPILE: the jit cache grew another entry for the same driver),
    or 'retrace' (sig seen before — e.g. a second jit wrapper around
    the same driver). Recompiles bump jit.recompiles and drop an
    instant on the timeline so the Perfetto view shows where compile
    storms happen."""
    if not events.enabled():
        return "disabled"
    with _lock:
        keys = _trace_keys.get(fn)
        if keys is None:
            _trace_keys[fn] = {sig}
            kind = "first"
        elif sig not in keys:
            keys.add(sig)
            kind = "new-shape"
        else:
            kind = "retrace"
        _counters["jit.traces"] = _counters.get("jit.traces", 0) + 1
        if kind == "new-shape":
            _counters["jit.recompiles"] = \
                _counters.get("jit.recompiles", 0) + 1
    if kind == "new-shape":
        events.instant("recompile:%s" % fn, cat="jit",
                       sig=repr(sig))
    return kind


def recompiles() -> int:
    with _lock:
        return int(_counters.get("jit.recompiles", 0))


def install_jax_monitoring() -> None:
    """Register the compile-duration listener once per process.
    jax.monitoring fires '/jax/core/compile/*_duration' events around
    every backend compile; they accumulate into jit.*_seconds counters
    and land as spans (ending now) on the bus, which is how the report
    splits compile wall from execute wall even for user-jitted
    drivers this module never sees directly."""
    global _monitoring_installed
    if _monitoring_installed:
        return
    try:
        import jax.monitoring as jmon
        register = jmon.register_event_duration_secs_listener
    except Exception:
        # no monitoring on this jax: enable() degrades to
        # no-compile-accounting instead of raising; left uninstalled
        # so a later enable() under a capable jax can still register
        return

    def _listener(name: str, secs: float, **kw) -> None:
        if not events.enabled():
            return
        if "compile" not in name:
            return
        leaf = name.rsplit("/", 1)[-1]
        key = "jit.%s_seconds" % leaf.replace("_duration", "")
        with _lock:
            _counters[key] = _counters.get(key, 0.0) + float(secs)
        if leaf == "backend_compile_duration":
            import time as _t
            t1 = _t.perf_counter()
            events.publish("backend_compile", events.PH_SPAN,
                           t1 - float(secs), t1, cat="jit")

    try:
        register(_listener)
    except Exception:
        return
    _monitoring_installed = True


def snapshot() -> Dict[str, Any]:
    """Point-in-time deep copy of every registry (bench.py --obs emits
    this into the BENCH trajectory)."""
    with _lock:
        return {
            "counters": dict(sorted(_counters.items())),
            "gauges": dict(sorted(_gauges.items())),
            "histograms": {
                k: {"count": int(h[0]), "total": h[1],
                    "min": h[2], "max": h[3],
                    "mean": h[1] / h[0] if h[0] else 0.0}
                for k, h in sorted(_hists.items())},
            "jit_trace_keys": {k: len(v)
                               for k, v in sorted(_trace_keys.items())},
        }


#: named counter baselines for incremental snapshots (counters_delta)
_delta_prev: Dict[str, Dict[str, float]] = {}


def counters_delta(name: str = "default") -> Dict[str, float]:
    """Counters CHANGED since the previous call with this `name`, as
    deltas (ISSUE 10 satellite: streaming per-host obs snapshots —
    long sharded runs report staging/broadcast counters incrementally
    over the multiproc handshake instead of one exit snapshot;
    testing/multiproc.emit_obs_delta rides this). Each `name` keeps
    its own baseline, so independent consumers (a per-step driver
    hook, the handshake emitter) never steal each other's deltas.
    Successive deltas for one name sum EXACTLY to the full counter
    values — pinned by test."""
    with _lock:
        cur = dict(_counters)
        prev = _delta_prev.get(name, {})
        delta = {k: v - prev.get(k, 0.0) for k, v in cur.items()
                 if v != prev.get(k, 0.0)}
        _delta_prev[name] = cur
    return delta


def reset() -> None:
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
        _trace_keys.clear()
        _delta_prev.clear()
