"""Request-scoped trace/span context for the serving tier (ISSUE 18
tentpole, part 1).

A request crossing RPC -> admission -> coalescing queue -> ragged
dispatch -> response previously had no causal identity: the flight
recorder attributes *steps* and the bus attributes *flushes*, but no
record said which flush a given tenant's solve rode, or how its wall
split between admit-wait, queue-wait, dispatch staging, and the solve
itself. This module is that identity:

  * :func:`begin` mints a :class:`Span` (trace_id, span_id, parent,
    tenant, op) at ``Server.submit`` / ``RpcClient.submit``; the RPC
    header carries ``{"trace", "span"}`` across the process boundary
    (serve/rpc.py) so client and server spans share one trace_id;
  * a thread-local activation stack (:func:`activate` /
    :func:`current_trace_id`) lets synchronous callees — the
    admission ladder's escalation payloads — stamp the active id
    without plumbing an argument through every signature;
  * ACROSS threads the context rides data, not ambient state: the
    span object is handed to ``CoalescingQueue.submit(..., trace=)``
    and stored on the ticket, the queue's dispatch stamps flush
    timestamps + a flush id onto traced tickets, and
    ``Ticket._resolve`` calls :meth:`Span.on_resolved` from whichever
    thread resolves — closing the span with the full
    admit/queue/dispatch/solve split;
  * span closure fans out to the obs bus (a ``serve::request`` span
    event Perfetto can flow-link to its ``batch::flush`` slice —
    obs/export.py), to obs/series.py's per-tenant/per-op quantile
    sketches + SLO burn windows, and to a ``serve.request`` ledger
    record ``xprof.attribute_run`` folds in.

Off-state contract (the PR 3/14 FROZEN discipline, pinned by tests):
the FROZEN ``("obs", "reqtrace") = "off"`` row means :func:`begin`
returns None, every propagation site is a single ``is not None``
check on an attribute that is never set, the RPC header gains no
fields, and zero spans are recorded — the serve/queue cold routes
stay bitwise and allocation-free.
"""

from __future__ import annotations

import collections
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

#: bounded ring capacity for finished spans (oldest dropped, counted)
SPAN_CAP = 65_536

#: the root span name every admitted request closes under; series,
#: ledger, and the export flow pass key on it
REQUEST_SPAN = "serve::request"
#: client-side RPC round-trip span (same trace_id as the server root)
CLIENT_SPAN = "serve::rpc"
#: the co-batched flush linkage record (args carry flush_id + the
#: trace ids that rode it; export.py turns these into flow ends)
FLUSH_SPAN = "batch::flush"

_lock = threading.Lock()
_spans: "collections.deque" = collections.deque(maxlen=SPAN_CAP)
_dropped = 0
_flush_seq = 0
_req_seq = 0

_explicit: Optional[bool] = None
_resolved: Optional[bool] = None

_tls = threading.local()


# -- the gate (obs/ledger.py discipline) ----------------------------------

def enable() -> None:
    """Force tracing on for this process (tests/bench)."""
    global _explicit
    _explicit = True


def disable() -> None:
    global _explicit
    _explicit = False


def enabled() -> bool:
    """Explicit override > memoized FROZEN ``obs/reqtrace`` row."""
    if _explicit is not None:
        return _explicit
    global _resolved
    if _resolved is None:
        try:
            from ..tune.select import resolve
            _resolved = str(resolve("obs", "reqtrace")) == "on"
        except Exception:
            _resolved = False
    return _resolved


def reset() -> None:
    """Drop every span and forget both the explicit override and the
    memoized tune row (test isolation)."""
    global _explicit, _resolved, _dropped, _flush_seq, _req_seq
    with _lock:
        _spans.clear()
        _dropped = 0
        _flush_seq = 0
        _req_seq = 0
    _explicit = None
    _resolved = None


# -- spans ----------------------------------------------------------------

def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One traced unit of work. Mutation is single-writer by
    construction: phases/args are written by whichever thread holds
    the request at that stage (submit thread, then the resolving
    thread), never concurrently — the queue hands the span off with
    the ticket."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "tenant",
                 "op", "t0", "t1", "phases", "args")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], tenant: str, op: str,
                 t0: Optional[float] = None) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.tenant = tenant
        self.op = op
        self.t0 = time.perf_counter() if t0 is None else t0
        self.t1: Optional[float] = None
        self.phases: Dict[str, float] = {}
        self.args: Dict[str, Any] = {}

    def child(self, name: str, op: Optional[str] = None) -> "Span":
        """A child span in the same trace (the chainer's shared
        factor dispatch)."""
        return Span(name, self.trace_id, _new_id(), self.span_id,
                    self.tenant, self.op if op is None else op)

    def on_resolved(self, ticket) -> None:
        """Queue-ticket closure hook, called by ``Ticket._resolve``
        from the resolving thread: derive the queue-wait / dispatch /
        solve split from the flush timestamps the dispatcher stamped,
        record the flush linkage, and finish. Must never raise into
        the resolve path (the caller guards, this stays total)."""
        t1 = time.perf_counter()
        t_flush = getattr(ticket, "t_flush", None)
        if t_flush is not None:
            t_disp = getattr(ticket, "t_dispatch", None) or t_flush
            self.phases["queue_wait_s"] = t_flush - ticket._t_submit
            self.phases["dispatch_s"] = t_disp - t_flush
            self.phases["solve_s"] = t1 - t_disp
        fid = getattr(ticket, "flush_id", None)
        if fid is not None:
            self.args["flush_id"] = fid
        self.finish(error=ticket._error, t1=t1)

    def finish(self, error: Optional[BaseException] = None,
               t1: Optional[float] = None, **args) -> "Span":
        """Close the span (idempotent) and commit it to the ring,
        the bus, and — for the request root — series + ledger."""
        if self.t1 is not None:
            return self
        self.t1 = time.perf_counter() if t1 is None else t1
        if args:
            self.args.update(args)
        if error is not None:
            self.args["error"] = str(error)[:120]
        _commit(self)
        return self


def begin(name: str = REQUEST_SPAN, tenant: str = "", op: str = "",
          parent: Any = None) -> Optional[Span]:
    """Mint a span, or None when tracing is off (the whole off-state
    cost at every call site is this one boolean). ``parent`` may be
    another :class:`Span` or the RPC header's ``{"trace", "span"}``
    dict — either continues the existing trace."""
    if not enabled():
        return None
    if isinstance(parent, Span):
        tid, pid = parent.trace_id, parent.span_id
    elif isinstance(parent, dict) and parent.get("trace"):
        tid, pid = str(parent["trace"]), parent.get("span")
    else:
        tid, pid = _new_id(), None
    return Span(name, tid, _new_id(), pid, str(tenant), str(op))


def record_flush(op: str, t0: float, t1: float, flush_id: int,
                 trace_ids: List[str], occupancy: int,
                 strategy: str) -> None:
    """One co-batched flush's linkage record: which traces rode it.
    Only called by the queue when at least one ticket is traced."""
    sp = Span(FLUSH_SPAN, "", _new_id(), None, "", op, t0=t0)
    sp.args.update({"flush_id": flush_id, "trace_ids": trace_ids,
                    "occupancy": occupancy, "strategy": strategy})
    sp.finish(t1=t1)


def next_flush_id() -> int:
    global _flush_seq
    with _lock:
        _flush_seq += 1
        return _flush_seq


def _commit(sp: Span) -> None:
    global _dropped, _req_seq
    with _lock:
        if len(_spans) == SPAN_CAP:
            _dropped += 1
        _spans.append(sp)
    from . import events as _ev
    if _ev.enabled():
        args: Dict[str, Any] = {"span_id": sp.span_id}
        if sp.trace_id:
            args["trace_id"] = sp.trace_id
        if sp.parent_id:
            args["parent_id"] = sp.parent_id
        if sp.tenant:
            args["tenant"] = sp.tenant
        if sp.op:
            args["op"] = sp.op
        args.update(sp.args)
        args.update({k: round(v, 6) for k, v in sp.phases.items()})
        _ev.publish(sp.name, _ev.PH_SPAN, sp.t0, sp.t1, cat="serve",
                    args=args)
    if sp.name != REQUEST_SPAN:
        return
    total = sp.t1 - sp.t0
    from . import series as _series
    if _series.enabled():
        # literal publish sites (not a loop over names): the
        # obs-literals analyzer collects these into
        # docs/OBS_REFERENCE.md and near-miss-checks them (SL802)
        _series.sample("serve.latency_s", total, tenant=sp.tenant,
                       op=sp.op)
        ph = sp.phases
        if "admit_s" in ph:
            _series.sample("serve.admit_wait_s", ph["admit_s"],
                           tenant=sp.tenant, op=sp.op)
        if "queue_wait_s" in ph:
            _series.sample("serve.queue_wait_s", ph["queue_wait_s"],
                           tenant=sp.tenant, op=sp.op)
        if "dispatch_s" in ph:
            _series.sample("serve.dispatch_s", ph["dispatch_s"],
                           tenant=sp.tenant, op=sp.op)
        if "solve_s" in ph:
            _series.sample("serve.solve_s", ph["solve_s"],
                           tenant=sp.tenant, op=sp.op)
        _series.note_slo(sp.tenant, total)
    from . import ledger as _ledger
    if _ledger.enabled():
        with _lock:
            seq = _req_seq
            _req_seq += 1
        meta: Dict[str, Any] = {"trace": sp.trace_id,
                                "tenant": sp.tenant, "op": sp.op}
        meta.update({k: v for k, v in sp.args.items()
                     if isinstance(v, (str, int, float, bool))})
        meta.update({k: round(v, 6) for k, v in sp.phases.items()})
        _ledger.append("serve.request", step=seq,
                       phases={"other": total}, meta=meta)


# -- thread-local activation ---------------------------------------------

def activate(sp: Optional[Span]) -> None:
    if sp is None:
        return
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(sp)


def deactivate(sp: Optional[Span]) -> None:
    if sp is None:
        return
    stack = getattr(_tls, "stack", None)
    if stack and stack[-1] is sp:
        stack.pop()
    elif stack and sp in stack:
        stack.remove(sp)


@contextmanager
def active(sp: Optional[Span]):
    """Make `sp` the thread's current span for the block; a None span
    is a no-op (the off state costs nothing here either)."""
    if sp is None:
        yield
        return
    activate(sp)
    try:
        yield
    finally:
        deactivate(sp)


def current() -> Optional[Span]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def current_trace_id() -> Optional[str]:
    """The active span's trace id, or None (off, or no span active) —
    escalation payloads pass this straight through; record_escalation
    drops None values."""
    sp = current()
    return None if sp is None else sp.trace_id


# -- accessors ------------------------------------------------------------

def spans(name: Optional[str] = None) -> List[Span]:
    """Snapshot of finished spans, optionally filtered by name."""
    with _lock:
        out = list(_spans)
    if name is not None:
        out = [s for s in out if s.name == name]
    return out


def trace(trace_id: str) -> List[Span]:
    """Every finished span of one trace, oldest first — the
    end-to-end reconstruction of a single request."""
    return [s for s in spans() if s.trace_id == trace_id]


def count() -> int:
    with _lock:
        return len(_spans)


def dropped() -> int:
    with _lock:
        return _dropped


def clear() -> None:
    global _dropped
    with _lock:
        _spans.clear()
        _dropped = 0
