"""Process-wide structured event bus (ISSUE 3 tentpole part 1).

One store for every observability record in the process: the RAII
trace blocks of utils/trace.py, the autotuner's decision marks
(tune/stats.py), and the driver hooks below all publish here. The
reference keeps three disjoint stores (Trace.cc's per-thread vectors,
the opts timer maps, the tuner counters); merging them is what makes
the Perfetto export (obs/export.py) one coherent timeline and lets
obs/report.py attribute a run without stitching.

Events carry thread identity (OOC host staging records from worker
threads land in the same stream — the reference Trace.cc:359 merges
per-thread vectors the same way at finish) and a category:

    trace   utils/trace.py blocks and marks
    phase   driver phase timers (trace.phases / Timers.phase)
    driver  driver-entry spans (the `driver` hook below)
    jit     compile-side records (tracing spans, recompile instants,
            backend-compile durations from jax.monitoring)
    tune    autotuner decision marks
    comms   scheduled-collective accounting (dist/ tree schedules)
    metric  counter samples

Everything is gated on ONE module flag read without a lock: disabled,
every hook is a single boolean check (the zero-cost contract drivers
rely on — instrumentation stays wired in production code paths).
The store is a bounded ring (EVENT_CAP) so an always-on bus cannot
grow without bound; drops are counted, never silent.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: span kinds, Chrome-trace phase letters ("X" complete span,
#: "i" instant, "C" counter sample)
PH_SPAN = "X"
PH_INSTANT = "i"
PH_COUNTER = "C"
#: Perfetto flow-event phase letters (ISSUE 18): export.py emits
#: these to link a traced request's serve::request span to the
#: batch::flush slice it rode — never published onto the bus itself
PH_FLOW_START = "s"
PH_FLOW_END = "f"

#: bounded ring capacity; oldest events drop first (counted).
#: deque(maxlen) keeps publish O(1) — a list trim would memmove the
#: whole ring under the lock on every publish once full
EVENT_CAP = 100_000

_enabled = False
_lock = threading.Lock()
_events: "collections.deque[Event]" = collections.deque(
    maxlen=EVENT_CAP)
_dropped = 0


@dataclasses.dataclass(frozen=True)
class Event:
    name: str
    ph: str                    # PH_SPAN / PH_INSTANT / PH_COUNTER
    t0: float                  # perf_counter seconds
    t1: float                  # == t0 for instants/counters
    tid: int
    thread: str
    cat: str = ""
    args: Optional[Dict[str, Any]] = None

    @property
    def dur(self) -> float:
        return self.t1 - self.t0


def enable() -> None:
    """Turn the bus on (also installs the jax.monitoring compile-time
    listener once — obs/metrics.py)."""
    global _enabled
    _enabled = True
    from . import metrics
    metrics.install_jax_monitoring()


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def publish(name: str, ph: str = PH_INSTANT, t0: Optional[float] = None,
            t1: Optional[float] = None, cat: str = "",
            args: Optional[Dict[str, Any]] = None) -> None:
    """Append one event (no-op when disabled). Timestamps default to
    now; spans pass their own (t0, t1)."""
    if not _enabled:
        return
    global _dropped
    t = time.perf_counter() if t0 is None else t0
    th = threading.current_thread()
    ev = Event(name=name, ph=ph, t0=t, t1=(t if t1 is None else t1),
               tid=threading.get_ident(), thread=th.name, cat=cat,
               args=args)
    with _lock:
        if len(_events) == EVENT_CAP:
            _dropped += 1               # deque maxlen evicts oldest
        _events.append(ev)


@contextlib.contextmanager
def span(name: str, cat: str = "", **args):
    """RAII span published on exit (the trace::Block shape, but into
    the shared bus)."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        publish(name, PH_SPAN, t0, time.perf_counter(), cat=cat,
                args=args or None)


def instant(name: str, cat: str = "", **args) -> None:
    publish(name, PH_INSTANT, cat=cat, args=args or None)


def counter(name: str, value, cat: str = "metric") -> None:
    """One counter sample (Perfetto renders these as tracks)."""
    publish(name, PH_COUNTER, cat=cat, args={"value": value})


def _tracing() -> bool:
    """True when called under a jax trace (the Python body of a jitted
    driver runs only while (re)compiling — a cache hit never reaches
    it, which is exactly the recompile signal metrics.record_trace
    keys on)."""
    try:
        import jax
        return not jax.core.trace_state_clean()
    except Exception:
        return False


@contextlib.contextmanager
def driver(op: str, shape: Optional[Tuple[int, ...]] = None,
           dtype=None, **args):
    """Driver-entry hook: every public linalg/dist driver wraps its
    body in one of these. Publishes a span (cat 'driver' eagerly,
    'jit' while tracing), bumps the per-driver invocation counter, and
    feeds the recompile detector with (op, shape, dtype) — the key a
    jit cache miss is attributed to. One boolean check when disabled."""
    if not _enabled:
        yield
        return
    from . import metrics
    tracing = _tracing()
    sig = (tuple(shape) if shape is not None else None,
           str(dtype) if dtype is not None else None)
    a = dict(args)
    if shape is not None:
        a["shape"] = "x".join(str(s) for s in shape)
    if dtype is not None:
        a["dtype"] = str(dtype)
    if tracing:
        # a trace entry is a compile, not an execution: it feeds the
        # recompile detector and jit.traces, never the calls counter
        # (which must agree with the report's eager `calls` column)
        metrics.record_trace(op, sig)
    else:
        metrics.inc("driver.%s.calls" % op)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        cat = "jit" if tracing else "driver"
        publish(op, PH_SPAN, t0, t1, cat=cat, args=a or None)
        metrics.observe("%s.%s_seconds" % (op, "trace" if tracing
                                           else "wall"), t1 - t0)


def instrument_driver(op: str):
    """Decorator form of `driver` for public driver entry points:
    pulls (shape, dtype) for the recompile key from the first
    TiledMatrix-like or array argument. Disabled cost: one boolean
    check, then a plain call."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _enabled:
                return fn(*args, **kwargs)
            shape = dtype = None
            for a in args:
                if hasattr(a, "mtype") and hasattr(a, "data"):
                    shape = tuple(a.data.shape)
                    dtype = getattr(a.data, "dtype", None)
                    break
                if hasattr(a, "shape") and hasattr(a, "dtype"):
                    shape, dtype = tuple(a.shape), a.dtype
                    break
            with driver(op, shape=shape, dtype=dtype):
                return fn(*args, **kwargs)
        return wrapper
    return deco


def events(cat: Optional[str] = None) -> List[Event]:
    """Snapshot (copy) of the ring, optionally filtered by category."""
    with _lock:
        evs = list(_events)
    if cat is not None:
        evs = [e for e in evs if e.cat == cat]
    return evs


def count() -> int:
    """Ring occupancy without copying it."""
    with _lock:
        return len(_events)


def dropped() -> int:
    """Lifetime ring evictions. Reads under `_lock` like count()/
    events() — `_dropped` is written under the lock at publish time,
    and a torn read here would let report() print a drop count that
    disagrees with the ring snapshot taken one line earlier (ISSUE 14
    satellite: the accessors are consistent, drops are never
    under-reported to the attribution warning)."""
    with _lock:
        return _dropped


def clear() -> None:
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


def drain(cats: Optional[Tuple[str, ...]] = None) -> List[Event]:
    """Atomically snapshot and clear (trace.finish / export use this
    so concurrent publishers cannot land between read and clear).
    With `cats`, only events in those categories are removed and
    returned — trace.finish() drains just the legacy trace store's
    categories so it cannot destroy a concurrent obs session's
    driver/compile records. The drop counter tracks lifetime ring
    evictions and resets only on a FULL drain/clear; a partial drain
    deliberately leaves it (the evictions still happened)."""
    global _dropped
    with _lock:
        if cats is None:
            evs = list(_events)
            _events.clear()
            _dropped = 0
            return evs
        evs = [e for e in _events if e.cat in cats]
        kept = [e for e in _events if e.cat not in cats]
        _events.clear()
        _events.extend(kept)
    return evs
