"""Compiled-program introspection (ISSUE 3 tentpole part 2): for any
jitted driver, pull the compiler's own cost model
(`Compiled.cost_analysis()`: analytic FLOPs, bytes accessed),
`memory_analysis()` (argument/output/temp bytes — peak HBM), and walk
the compiled HLO text to count collectives by kind. This is the
library form of the ad-hoc assertion tests/test_dist.py makes
("collective-permute" in hlo): the dist/ tree schedules (tsqr
butterfly, stedc merge, tree_allreduce) get EXACT comms accounting
per compiled call, attributable next to the wall numbers — the BLASX
DAG/communication-accounting play (PAPERS.md) for the TPU port.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Callable, Dict

from . import events, metrics

#: collective kinds counted in compiled HLO, in reporting order.
#: ppermute lowers to collective-permute (the dist/tree.py signature);
#: SPMD-inserted resharding shows up as the gather/reduce kinds.
COLLECTIVE_KINDS = ("collective-permute", "all-reduce", "all-gather",
                    "reduce-scatter", "all-to-all")

#: matches one collective instruction: the op name at a word boundary,
#: optionally in its async '-start' form, followed by its operand
#: list. The '-done' halves are deliberately NOT matched so an async
#: pair counts once.
_COLL_RE = re.compile(
    r"\b(%s)(?:-start)?\(" % "|".join(COLLECTIVE_KINDS))

_lock = threading.Lock()
_analyses: Dict[str, Dict[str, Any]] = {}


def collective_counts(hlo_text: str) -> Dict[str, int]:
    """Count collectives by kind in compiled-HLO text. Every kind is
    present in the result (0 when absent) so callers can assert on a
    full comms signature, not just the kinds that happened to occur."""
    counts = {k: 0 for k in COLLECTIVE_KINDS}
    for m in _COLL_RE.finditer(hlo_text):
        counts[m.group(1)] += 1
    counts["total"] = sum(counts[k] for k in COLLECTIVE_KINDS)
    return counts


def _cost_dict(compiled) -> Dict[str, float]:
    """Normalize Compiled.cost_analysis() across jax versions (dict,
    or a one-element list of dicts)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def cost_summary(compiled) -> Dict[str, float]:
    """The attribution-relevant slice of the compiler cost model."""
    ca = _cost_dict(compiled)
    return {
        "flops": float(ca.get("flops", 0.0)),
        "transcendentals": float(ca.get("transcendentals", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
    }


def memory_summary(compiled) -> Dict[str, int]:
    """Compiled.memory_analysis() flattened; peak_bytes is the live
    HBM high-water estimate (arguments + outputs + temporaries)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    arg = int(getattr(ma, "argument_size_in_bytes", 0))
    out = int(getattr(ma, "output_size_in_bytes", 0))
    tmp = int(getattr(ma, "temp_size_in_bytes", 0))
    alias = int(getattr(ma, "alias_size_in_bytes", 0))
    return {
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": tmp,
        "generated_code_bytes":
            int(getattr(ma, "generated_code_size_in_bytes", 0)),
        "peak_bytes": arg + out + tmp - alias,
    }


def lower_compiled(fn: Callable, *args, **kwargs):
    """jit-lower `fn` at `args` and compile; returns (compiled,
    compile_seconds). `fn` may already be jitted (jax.jit is
    idempotent for lowering purposes)."""
    import jax
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    lowered = jitted.lower(*args, **kwargs)
    t0 = time.perf_counter()
    compiled = lowered.compile()
    return compiled, time.perf_counter() - t0


def analyze(label: str, fn: Callable, *args, run: bool = True,
            **kwargs) -> Dict[str, Any]:
    """Full attribution record for one driver call: compile the jitted
    `fn` at `args`, read the cost/memory model, count collectives in
    the compiled HLO, and (run=True) execute the compiled program once
    with a blocking fetch to split compile wall from execute wall.
    The record lands in the analyses registry (obs.report merges it)
    and as gauges + an instant on the bus."""
    compiled, compile_s = lower_compiled(fn, *args, **kwargs)
    rec: Dict[str, Any] = {"label": label,
                           "compile_seconds": round(compile_s, 6)}
    rec.update(cost_summary(compiled))
    rec.update(memory_summary(compiled))
    try:
        rec["collectives"] = collective_counts(compiled.as_text())
    except Exception:
        rec["collectives"] = {}
    if run:
        import jax
        out = compiled(*args, **kwargs)
        jax.block_until_ready(out)          # warm (may include h2d)
        t0 = time.perf_counter()
        out = compiled(*args, **kwargs)
        jax.block_until_ready(out)
        rec["execute_seconds"] = round(time.perf_counter() - t0, 6)
    with _lock:
        _analyses[label] = rec
    if events.enabled():
        events.instant("xprof:%s" % label, cat="jit",
                       flops=rec.get("flops"),
                       peak_bytes=rec.get("peak_bytes"))
        metrics.set_gauge("xprof.%s.flops" % label, rec.get("flops"))
        metrics.set_gauge("xprof.%s.peak_bytes" % label,
                          rec.get("peak_bytes"))
    return rec


#: ledger phase -> critical-path bucket (ISSUE 14): the attribution
#: vocabulary of the ROADMAP's hardware round ("regressions attribute
#: to compile vs kernel vs collective time") mapped onto the flight
#: recorder's closed phase set. Compile is NOT a ledger phase — jit
#: tracing happens inside whatever phase dispatched it — so the
#: compile wall rides the jit.* counters alongside, never summed into
#: the buckets (it overlaps them).
PHASE_BUCKETS = {
    "factor": "kernel",
    "update": "kernel",
    "bcast_wait": "collective_wait",
    "stage": "staging",
    "cache": "cache_stall",
    "other": "idle",
}


def attribute_run(records=None, counters=None) -> Dict[str, Any]:
    """The critical-path analyzer (ISSUE 14 tentpole, part 3): fold
    flight-recorder step records (obs/ledger.py) into per-run
    attribution — total wall per phase and per bucket
    (kernel / collective-wait / cache-stall / staging / idle), split
    per host and per op, the top wall-eating panels, and the compile
    wall from the jit counters next to it. Everything is derived from
    the exhaustive per-step phase split, so ``fraction_attributed``
    against a driver's measured wall is the acceptance number
    ``bench.py --shard`` gates on (>= 0.95)."""
    from . import ledger as _ledger
    if records is None:
        records = _ledger.records()
    if counters is None:
        counters = metrics.snapshot()["counters"]
    phases: Dict[str, float] = {}
    by_host: Dict[int, Dict[str, Any]] = {}
    by_op: Dict[str, Dict[str, Any]] = {}
    total = 0.0
    panels = []
    for r in records:
        total += r.wall
        for ph, s in r.phases.items():
            phases[ph] = phases.get(ph, 0.0) + s
        for key, agg2 in ((r.host, by_host), (r.op, by_op)):
            d = agg2.setdefault(key, {"wall_s": 0.0, "phases": {}})
            d["wall_s"] += r.wall
            for ph, s in r.phases.items():
                d["phases"][ph] = d["phases"].get(ph, 0.0) + s
        if r.step >= 0 and not r.meta.get("drain"):
            panels.append(r)      # drain records are not panels
    panels.sort(key=lambda r: -r.wall)
    buckets: Dict[str, float] = {}
    for ph, s in phases.items():
        b = PHASE_BUCKETS.get(ph, "idle")
        buckets[b] = buckets.get(b, 0.0) + s

    def _round(d):
        return {k: round(v, 6) for k, v in sorted(d.items())}

    return {
        "records": len(records),
        "dropped": _ledger.dropped(),
        "total_wall_s": round(total, 6),
        "phases": _round(phases),
        "buckets": _round(buckets),
        "compile_s": round(float(
            counters.get("jit.backend_compile_seconds", 0.0)), 6),
        "by_host": {h: {"wall_s": round(d["wall_s"], 6),
                        "phases": _round(d["phases"])}
                    for h, d in sorted(by_host.items())},
        "by_op": {op: {"wall_s": round(d["wall_s"], 6),
                       "phases": _round(d["phases"])}
                  for op, d in sorted(by_op.items())},
        "top_panels": [
            {"op": r.op, "step": r.step, "host": r.host,
             "owner": r.owner, "wall_s": round(r.wall, 6),
             "phases": _round(r.phases)}
            for r in panels[:8]],
    }


def analyses() -> Dict[str, Dict[str, Any]]:
    with _lock:
        return {k: dict(v) for k, v in _analyses.items()}


def clear_analyses() -> None:
    with _lock:
        _analyses.clear()
