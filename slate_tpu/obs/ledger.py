"""Per-run flight recorder (ISSUE 14 tentpole, part 1): a bounded
ring of **per-step structured records** for every long-running path in
the streaming stack.

The event bus (events.py) answers "what spans ran"; the metrics
registry answers "how much, in aggregate". Neither answers the
question a stalled or slow 131072² shard run actually poses: *which
panel, on which host, in which phase, ate the wall*. The ledger does —
each step of an OOC/sharded stream (and each coalesced batch
dispatch) appends one :class:`StepRecord` carrying the panel index,
the owning host, the resume epoch, and a **per-phase wall breakdown**
over the closed phase set :data:`PHASES`::

    stage       host->HBM staging the step waited on (sync uploads,
                prefetch waits)
    factor      the panel factor kernel (the critical path)
    update      trailing-update sweeps
    bcast_wait  blocked completion of a broadcast collective
    cache       cache stalls: writeback fences, checkpoint drains,
                spill re-stages
    other       everything the step did that no phase claims
                (attribution is exhaustive by construction:
                sum(phases) == the step's wall, exactly)

Phase accounting is **self-time over a frame stack**: drivers wrap
regions in :func:`frame` (nesting pauses the parent — a staging fetch
inside the update sweep charges ``stage``, not ``update`` twice), and
leaf waits measured elsewhere (linalg/stream.py's writeback fences)
land through :func:`credit`, which deducts from the enclosing frame
the same way. obs/xprof.py folds the records into the critical-path
attribution obs/report.py renders, and obs/export.py emits each
phase as a Perfetto counter track next to the span timeline.

Gate discipline (the one-boolean contract every obs layer keeps):
the recorder rides the FROZEN ``obs/ledger`` tunable, shipped
``"off"`` — a cold cache records NOTHING, allocates no ring entries,
spills no files, and every driver's results are bit-identical
(pinned by tests). :func:`enable`/:func:`disable` override
explicitly; the tune row is resolved once per process and memoized,
so the steady-state gate is one boolean load.

Post-mortem spill: a recorder created with ``spill_dir`` (the OOC
drivers pass their checkpoint directory) appends every committed
record to ``<spill_dir>/ledger.host<i>.jsonl``, flushed per line —
a killed run leaves the full step history on disk next to the
durable factor panels it was producing.

The ring is bounded (:data:`LEDGER_CAP`); evictions are counted,
never silent (obs/report.py warns — a silently-truncated ledger
invalidates attribution the same way a dropped event ring does).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: the CLOSED set of step phases (tools/slate_lint SL602 fails any
#: frame()/credit() literal outside it — a typo'd phase name would be
#: a silently-empty attribution column)
PHASES = ("stage", "factor", "update", "bcast_wait", "cache", "other")

#: bounded ring capacity; oldest records drop first (counted)
LEDGER_CAP = 65_536

_lock = threading.Lock()
_records: "collections.deque[StepRecord]" = collections.deque(
    maxlen=LEDGER_CAP)
_dropped = 0
_seq = 0                     # monotonically increasing record id
#: per-consumer tail cursors (testing/multiproc.emit_obs_delta)
_tail_prev: Dict[str, int] = {}

#: explicit override > memoized tune-row resolution (module doc)
_explicit: Optional[bool] = None
_resolved: Optional[bool] = None
#: count of live recorders — the one-boolean gate frame()/credit()
#: check before touching thread-local state
_active = 0

_tls = threading.local()


@dataclasses.dataclass
class StepRecord:
    """One committed step: identity + the exhaustive phase split."""
    op: str
    step: int
    host: int
    owner: int               # owning host (== host off-mesh)
    epoch: int               # resume epoch the run started from
    t0: float                # perf_counter seconds (bus clock)
    t1: float
    phases: Dict[str, float]
    meta: Dict[str, Any]
    seq: int = 0

    @property
    def wall(self) -> float:
        return self.t1 - self.t0

    def to_dict(self) -> Dict[str, Any]:
        return {"op": self.op, "step": self.step, "host": self.host,
                "owner": self.owner, "epoch": self.epoch,
                "wall_s": round(self.wall, 6),
                "phases": {k: round(v, 6)
                           for k, v in sorted(self.phases.items())},
                **({"meta": self.meta} if self.meta else {})}


def _host() -> int:
    try:
        import jax
        return int(jax.process_index())
    except Exception:
        return 0


def enable() -> None:
    """Turn the recorder on explicitly (wins over the tune row)."""
    global _explicit
    _explicit = True


def disable() -> None:
    global _explicit
    _explicit = False


def enabled() -> bool:
    """The gate: explicit override, else the FROZEN ``obs/ledger``
    tunable resolved once per process ("on" turns the recorder on —
    an earned or explicit decision; the shipped default is "off")."""
    if _explicit is not None:
        return _explicit
    global _resolved
    if _resolved is None:
        try:
            from ..tune.select import resolve
            _resolved = str(resolve("obs", "ledger")) == "on"
        except Exception:
            _resolved = False
    return _resolved


def reset() -> None:
    """Forget records, cursors, AND the memoized tune resolution
    (tests repoint the cache between cases)."""
    global _dropped, _explicit, _resolved, _seq
    with _lock:
        _records.clear()
        _tail_prev.clear()
        _dropped = 0
        _seq = 0
    _explicit = None
    _resolved = None


def _append(rec: StepRecord) -> None:
    global _dropped, _seq
    with _lock:
        _seq += 1
        rec.seq = _seq
        if len(_records) == LEDGER_CAP:
            _dropped += 1            # deque maxlen evicts oldest
        _records.append(rec)


def records(op: Optional[str] = None) -> List[StepRecord]:
    """Snapshot (copy) of the ring, optionally filtered by op."""
    with _lock:
        recs = list(_records)
    if op is not None:
        recs = [r for r in recs if r.op == op]
    return recs


def count() -> int:
    with _lock:
        return len(_records)


def dropped() -> int:
    with _lock:
        return _dropped


def tail(name: str) -> List[StepRecord]:
    """Records committed since the previous ``tail(name)`` call —
    per-consumer incremental reads, the counters_delta shape carried
    to step records (testing/multiproc.emit_obs_delta streams the
    per-host ledger over the result handshake with this)."""
    with _lock:
        prev = _tail_prev.get(name, 0)
        out = [r for r in _records if r.seq > prev]
        _tail_prev[name] = _seq
    return out


# -- phase accounting ------------------------------------------------------

@contextlib.contextmanager
def frame(phase: str):
    """Charge the enclosed region's SELF time to `phase` on the
    current open record (no-op without one — one integer check when
    the recorder is off). Nested frames pause the parent: a stage
    fetch inside an update frame charges ``stage``, and the update
    frame keeps only its own time, so committed phases always sum to
    the step wall."""
    if not _active:
        yield
        return
    rec = getattr(_tls, "rec", None)
    if rec is None:
        yield
        return
    stack = _tls.stack
    stack.append(0.0)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - t0
        child = stack.pop()
        rec.phases[phase] = rec.phases.get(phase, 0.0) \
            + max(dur - child, 0.0)
        if stack:
            stack[-1] += dur


def credit(phase: str, seconds: float) -> None:
    """Charge an externally-measured leaf wait (a writeback fence in
    linalg/stream.py) to `phase` on the current open record,
    deducting it from the enclosing frame like a nested frame would.
    No-op without an open record on this thread — worker-thread waits
    never misattribute to whatever step the main thread has open."""
    if not _active:
        return
    rec = getattr(_tls, "rec", None)
    if rec is None:
        return
    rec.phases[phase] = rec.phases.get(phase, 0.0) + seconds
    stack = _tls.stack
    if stack:
        stack[-1] += seconds


class RunRecorder:
    """One driver invocation's recorder: ``begin(step)`` opens a
    record on the calling thread, :func:`frame`/:func:`credit` charge
    phases into it, ``commit()`` closes it (the unclaimed remainder
    lands in ``other``) and appends it to the ring + the spill file.
    ``close()`` in the driver's ``finally`` releases the active
    gate."""

    def __init__(self, op: str, nt: Optional[int] = None,
                 spill_dir: Optional[str] = None) -> None:
        self.op = op
        self.nt = nt
        self.host = _host()
        self._spill = None
        if spill_dir:
            try:
                os.makedirs(spill_dir, exist_ok=True)
                self._spill = open(
                    os.path.join(spill_dir,
                                 "ledger.host%d.jsonl" % self.host),
                    "a")
            except OSError:
                self._spill = None       # post-mortem is best-effort
        self._closed = False

    def begin(self, step: int, owner: Optional[int] = None,
              epoch: int = 0, drain: bool = False) -> "RunRecorder":
        """Open step `step`'s record (commits a still-open one first
        — a driver that raises mid-step still leaves that step's
        partial phases on the ring). ``drain=True`` marks the final
        post-loop record (writeback drain, engine shutdown): its
        step index is NOT a panel, and the critical-path analyzer
        keeps it out of the slowest-panels ranking."""
        if getattr(_tls, "rec", None) is not None:
            self.commit()
        _tls.rec = StepRecord(
            op=self.op, step=int(step), host=self.host,
            owner=self.host if owner is None else int(owner),
            epoch=int(epoch), t0=time.perf_counter(), t1=0.0,
            phases={}, meta={"drain": True} if drain else {})
        _tls.stack = []
        return self

    def commit(self, **meta) -> Optional[StepRecord]:
        """Close and append the open record; the wall not claimed by
        any frame/credit goes to ``other`` so the split is exhaustive."""
        rec = getattr(_tls, "rec", None)
        if rec is None:
            return None
        _tls.rec = None
        _tls.stack = []
        rec.t1 = time.perf_counter()
        claimed = sum(rec.phases.values())
        rest = rec.wall - claimed
        if rest > 0:
            rec.phases["other"] = rec.phases.get("other", 0.0) + rest
        if meta:
            rec.meta.update(meta)
        _append(rec)
        if self._spill is not None:
            try:
                self._spill.write(json.dumps(rec.to_dict(),
                                             sort_keys=True) + "\n")
                self._spill.flush()
            except OSError:
                pass
        return rec

    def close(self) -> None:
        """Commit any open record, close the spill file, release the
        active gate. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.commit()
        if self._spill is not None:
            try:
                self._spill.close()
            except OSError:
                pass
        global _active
        with _lock:
            _active = max(_active - 1, 0)

    def __enter__(self) -> "RunRecorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def recorder(op: str, nt: Optional[int] = None,
             spill_dir: Optional[str] = None
             ) -> Optional[RunRecorder]:
    """A driver's recorder when the ledger is on, else None — the
    step loops gate every ledger touch on this one reference, so the
    off path costs nothing per step."""
    if not enabled():
        return None
    global _active
    with _lock:
        _active += 1
    return RunRecorder(op, nt=nt, spill_dir=spill_dir)


def append(op: str, step: int, phases: Dict[str, float],
           meta: Optional[Dict[str, Any]] = None) -> None:
    """One-shot record (the batch/queue.py dispatch path — no step
    loop to hold a recorder open). Gated like :func:`recorder`."""
    if not enabled():
        return
    t1 = time.perf_counter()
    wall = sum(phases.values())
    rec = StepRecord(op=op, step=int(step), host=_host(),
                     owner=_host(), epoch=0, t0=t1 - wall, t1=t1,
                     phases=dict(phases), meta=dict(meta or {}))
    _append(rec)
