"""Chrome-trace / Perfetto JSON export of the merged event stream
(ISSUE 3 tentpole part 3). The produced object follows the Trace Event
Format (the JSON `chrome://tracing` and ui.perfetto.dev load): one
`traceEvents` array of {ph, ts, name, ...} records, timestamps in
microseconds. This supersedes utils/trace.py's SVG as the primary
timeline — `trace.finish()` stays as a thin quick-look view over the
same bus.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from . import events as _events_mod
from .events import PH_COUNTER, PH_SPAN, Event


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except TypeError:
        return repr(v)


def chrome_trace(evs: Optional[List[Event]] = None,
                 clear: bool = False) -> Dict[str, Any]:
    """Build the Trace Event Format object from `evs` (default: a
    snapshot of the bus; clear=True drains it instead). Timestamps
    are rebased to the earliest event so the viewer opens at t=0."""
    if evs is None:
        evs = _events_mod.drain() if clear else _events_mod.events()
    pid = os.getpid()
    t_min = min((e.t0 for e in evs), default=0.0)
    out: List[Dict[str, Any]] = []
    threads = {}
    for e in evs:
        threads.setdefault(e.tid, e.thread)
        rec: Dict[str, Any] = {
            "name": e.name,
            "ph": e.ph,
            "ts": round((e.t0 - t_min) * 1e6, 3),
            "pid": pid,
            "tid": e.tid,
        }
        if e.cat:
            rec["cat"] = e.cat
        if e.ph == PH_SPAN:
            rec["dur"] = round((e.t1 - e.t0) * 1e6, 3)
        elif e.ph != PH_COUNTER:
            rec["s"] = "t"               # instant scope: thread
        if e.args:
            rec["args"] = {k: _jsonable(v) for k, v in e.args.items()}
        out.append(rec)
    # thread-name metadata rows so Perfetto labels OOC staging workers
    for tid, name in sorted(threads.items()):
        out.append({"name": "thread_name", "ph": "M", "ts": 0,
                    "pid": pid, "tid": tid, "args": {"name": name}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_trace(path: str, evs: Optional[List[Event]] = None,
                clear: bool = False) -> str:
    """Serialize chrome_trace() to `path`; returns the path."""
    obj = chrome_trace(evs, clear=clear)
    with open(path, "w") as f:
        json.dump(obj, f)
    return path
