"""Chrome-trace / Perfetto JSON export of the merged event stream
(ISSUE 3 tentpole part 3). The produced object follows the Trace Event
Format (the JSON `chrome://tracing` and ui.perfetto.dev load): one
`traceEvents` array of {ph, ts, name, ...} records, timestamps in
microseconds. This supersedes utils/trace.py's SVG as the primary
timeline — `trace.finish()` stays as a thin quick-look view over the
same bus.

Multihost (ISSUE 5 satellite; ROADMAP "one Perfetto view shows the
whole mesh"): each host writes its own trace file, and `host=`
namespaces it — pid becomes the host id, thread ids move into a
per-host block (host * _HOST_TID_STRIDE + compact local index), and
thread/process name metadata carry the host label. Concatenating the
per-host ``traceEvents`` arrays (or loading the files together in
Perfetto) then yields one mesh timeline with no tid collisions.
host=None (the default) keeps the single-host layout unless jax is
running multi-process, in which case the process index is used
automatically.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from . import events as _events_mod
from .events import (PH_COUNTER, PH_FLOW_END, PH_FLOW_START,
                     PH_SPAN, Event)

#: per-host thread-id block size: local thread ids are compacted into
#: [host*stride, host*stride + #threads), so traces from up to
#: `stride` threads/host merge collision-free
_HOST_TID_STRIDE = 100_000


def _resolve_host(host) -> Optional[int]:
    """Explicit host wins; otherwise auto-namespace only when jax is
    actually multi-process (a single host keeps the legacy layout,
    byte-stable for existing tooling)."""
    if host is not None:
        return int(host)
    try:
        import jax
        if jax.process_count() > 1:
            return int(jax.process_index())
    except Exception:
        pass
    return None


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except TypeError:
        return repr(v)


def chrome_trace(evs: Optional[List[Event]] = None,
                 clear: bool = False,
                 host: Optional[int] = None,
                 include_ledger: bool = True) -> Dict[str, Any]:
    """Build the Trace Event Format object from `evs` (default: a
    snapshot of the bus; clear=True drains it instead). Timestamps
    are rebased to the earliest event so the viewer opens at t=0.
    `host` namespaces pid/tid per mesh host (module doc).

    ``include_ledger`` (ISSUE 14): flight-recorder step records
    (obs/ledger.py — same perf_counter clock as the bus) are appended
    as per-phase **counter tracks** (``ledger:stage`` /
    ``ledger:factor`` / ...), one sample at each step's end, so the
    Perfetto view shows the phase breakdown as stacked counters right
    under the span timeline. With the recorder off (the FROZEN
    default) there are zero records and the output is byte-identical
    to the pre-ledger export."""
    if evs is None:
        evs = _events_mod.drain() if clear else _events_mod.events()
    led_recs = []
    if include_ledger:
        from . import ledger as _ledger
        led_recs = _ledger.records()
    t_min_led = min((r.t0 for r in led_recs), default=None)
    h = _resolve_host(host)
    pid = os.getpid() if h is None else h
    t_min = min((e.t0 for e in evs), default=t_min_led or 0.0)
    if t_min_led is not None:
        t_min = min(t_min, t_min_led)
    out: List[Dict[str, Any]] = []
    threads: Dict[int, str] = {}
    tid_map: Dict[int, int] = {}

    def map_tid(tid: int) -> int:
        if h is None:
            return tid
        if tid not in tid_map:
            tid_map[tid] = h * _HOST_TID_STRIDE + len(tid_map)
        return tid_map[tid]

    for e in evs:
        threads.setdefault(e.tid, e.thread)
        rec: Dict[str, Any] = {
            "name": e.name,
            "ph": e.ph,
            "ts": round((e.t0 - t_min) * 1e6, 3),
            "pid": pid,
            "tid": map_tid(e.tid),
        }
        if e.cat:
            rec["cat"] = e.cat
        if e.ph == PH_SPAN:
            rec["dur"] = round((e.t1 - e.t0) * 1e6, 3)
        elif e.ph != PH_COUNTER:
            rec["s"] = "t"               # instant scope: thread
        if e.args:
            rec["args"] = {k: _jsonable(v) for k, v in e.args.items()}
        out.append(rec)
    # Perfetto flow events (ISSUE 18 satellite): each traced
    # request's serve::request span starts a flow (trace_id as the
    # flow id) that the batch::flush slice carrying it terminates —
    # the viewer draws the arrow from request to the co-batched
    # dispatch it rode. Only trace-stamped serve-cat span events
    # produce these, so with obs/reqtrace off there are none and the
    # export output is byte-identical (pinned).
    for e in evs:
        if e.cat != "serve" or e.ph != PH_SPAN or not e.args:
            continue
        if e.name == "serve::request" and e.args.get("trace_id"):
            flow_ph, flow_ids = PH_FLOW_START, [e.args["trace_id"]]
        elif e.name == "batch::flush" and e.args.get("trace_ids"):
            flow_ph, flow_ids = PH_FLOW_END, e.args["trace_ids"]
        else:
            continue
        for fid in flow_ids:
            # ts nudged inside the slice so the flow binds to it
            frec: Dict[str, Any] = {
                "name": "serve.flow", "cat": "serve", "ph": flow_ph,
                "id": str(fid),
                "ts": round((e.t0 - t_min) * 1e6 + 0.001, 3),
                "pid": pid, "tid": map_tid(e.tid)}
            if flow_ph == PH_FLOW_END:
                frec["bp"] = "e"
            out.append(frec)
    # flight-recorder phase counter tracks (module doc): one "C"
    # sample per committed step per phase, valued in milliseconds,
    # named per op so concurrent drivers get separate tracks
    for r in led_recs:
        ts = round((r.t1 - t_min) * 1e6, 3)
        for ph, secs in sorted(r.phases.items()):
            out.append({"name": "ledger:%s:%s" % (r.op, ph),
                        "ph": PH_COUNTER, "ts": ts, "pid": pid,
                        "tid": 0 if h is None
                        else h * _HOST_TID_STRIDE,
                        "args": {"value": round(secs * 1e3, 4)}})
    # thread-name metadata rows so Perfetto labels OOC staging workers
    # (and, namespaced, which HOST each thread row belongs to)
    for tid, name in sorted(threads.items()):
        label = name if h is None else "host%d:%s" % (h, name)
        out.append({"name": "thread_name", "ph": "M", "ts": 0,
                    "pid": pid, "tid": map_tid(tid),
                    "args": {"name": label}})
    if h is not None:
        out.append({"name": "process_name", "ph": "M", "ts": 0,
                    "pid": pid, "tid": h * _HOST_TID_STRIDE,
                    "args": {"name": "host %d" % h}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_trace(path: str, evs: Optional[List[Event]] = None,
                clear: bool = False,
                host: Optional[int] = None,
                include_ledger: bool = True) -> str:
    """Serialize chrome_trace() to `path`; returns the path."""
    obj = chrome_trace(evs, clear=clear, host=host,
                       include_ledger=include_ledger)
    with open(path, "w") as f:
        json.dump(obj, f)
    return path
