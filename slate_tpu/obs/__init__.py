"""slate_tpu.obs — unified observability (ISSUE 3).

One event bus for every record in the process (events.py), compiled-
program cost/comms attribution (xprof.py), Perfetto JSON export
(export.py), and a metrics registry + per-run report (metrics.py /
report.py). utils/trace.py and tune/stats.py publish into the same
bus, so `chrome://tracing` shows phase timers, tuner decisions,
driver spans, compile events and OOC staging on one timeline.

Quick use::

    from slate_tpu import obs
    obs.enable()
    ...                                   # run drivers
    obs.analyze("potrf", jitted_fn, arg)  # FLOPs/memory/collectives
    print(obs.report())
    obs.write_trace("/tmp/run.trace.json")
"""

from . import (events, export, health, ledger,    # noqa: F401
               metrics, reqtrace, series, xprof)
from .events import (clear, counter, disable, driver, enable,  # noqa: F401
                     enabled, instant, publish, span)
from .events import events as bus_events          # noqa: F401
from .export import chrome_trace, write_trace     # noqa: F401
from .xprof import (COLLECTIVE_KINDS, analyze,    # noqa: F401
                    collective_counts)
from .report import report, snapshot              # noqa: F401
