"""Stall watchdog + progress health (ISSUE 14 tentpole, part 2).

A long OOC/sharded stream that wedges — a hung transfer, a dead gloo
peer, a lost flush — today presents as *silence*: the step loop stops
publishing and nothing in the process notices until an outer timeout
(if any) fires. The watchdog turns silence into a signal:

* step loops publish :func:`heartbeat` once per panel step, plus one
  **completion beat** at ``step == total`` after the loop, so the
  last real step stays monitored and a finished run stops being
  watched (one boolean check when the watchdog is off — the same
  zero-cost gate discipline as events.py). An op with no completed
  step interval yet is never flagged: the first step's wall includes
  the cold jit compile, which is not a stall;
* a daemon **monitor thread** (started lazily by the first heartbeat
  when the watchdog is on; named ``obs-watchdog`` so the off-state
  tests can assert its absence) watches every op's last beat against
  a **median-step budget**: ``max(stall_factor * median step
  interval, min_budget_s)`` — a run is its own baseline, so a slow
  problem is not a stall but a step taking 8x its own median is;
* a detected stall publishes one ``health::stall`` obs instant
  carrying the stalled op, the last panel step, and this host — the
  panel/host attribution a post-mortem needs — bumps the
  ``health.stalls`` counter, and (``escalate=True``) hands the stall
  to the resil guard funnel (guard.record_escalation, rung
  ``watchdog_stall``) so the same degradation bookkeeping that
  records retries and fallbacks records hangs. One stall per
  episode: the flag clears on the next heartbeat.
* each heartbeat updates the ``health.eta_seconds`` gauge
  (remaining steps x median step seconds) — the per-run ETA the
  serving/elastic-mesh layers read for admission and re-mapping
  decisions. With the step ledger on, the per-step estimate is the
  median over LIVE hosts' own per-host median step walls consumed
  incrementally from ``ledger.tail("health.eta")`` — a straggler
  shifts the forecast instead of being averaged away, and a host
  that stopped reporting (its newest record trails the mesh's newest
  by more than its own stall budget) is dropped from the median so a
  dead peer can never freeze the gauge. Ledger off or empty: the
  local own-op median, exactly the pre-elastic behaviour.

Gate: the FROZEN ``obs/watchdog`` tunable, shipped ``"off"`` — a cold
cache starts NO thread and records nothing (pinned by tests);
:func:`enable`/:func:`disable` override explicitly, and the tune row
is resolved once per process like obs/ledger.py's.

Testable today: seed a ``kind="hang"`` fault plan (resil/faults.py)
into any stream's ``h2d`` site — the injected sleep starves the
heartbeat past the budget and the watchdog fires mid-hang (pinned by
tests on the CPU tier, sharded stream included).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, Optional

from . import events as _events
from . import metrics as _metrics

#: a step slower than this multiple of the op's own median step
#: interval is a stall (a run is its own baseline)
STALL_FACTOR = 8.0

#: floor on the stall budget — median math on the first steps of a
#: fast stream must not declare microsecond "stalls"
MIN_BUDGET_S = 1.0

#: monitor poll interval
INTERVAL_S = 0.05

#: per-op step-interval history window (median over the last N)
_HISTORY = 64

_lock = threading.Lock()
_explicit: Optional[bool] = None
_resolved: Optional[bool] = None
_escalate = False
_stall_factor = STALL_FACTOR
_min_budget_s = MIN_BUDGET_S
_interval_s = INTERVAL_S

_monitor: Optional[threading.Thread] = None
#: the CURRENT monitor's private stop event — per-thread, so a
#: disable/enable cycle can never resurrect an old monitor by
#: clearing a shared event it is still polling (an orphaned thread
#: that outlived its join timeout holds a set event and exits on its
#: next wake, whatever newer monitors are doing)
_monitor_stop: Optional[threading.Event] = None

#: local mirrors readable with the obs bus off (the guard.counts
#: shape): stalls + heartbeats observed
_stats: Dict[str, int] = {"heartbeats": 0, "stalls": 0}


class _Track:
    __slots__ = ("step", "total", "last", "durs", "stalled", "host")

    def __init__(self) -> None:
        self.step = -1
        self.total: Optional[int] = None
        self.last = 0.0
        self.durs: "collections.deque[float]" = collections.deque(
            maxlen=_HISTORY)
        self.stalled = False
        self.host = 0


_tracks: Dict[str, _Track] = {}

#: per-(op, host) step-wall history consumed from the ledger tail
#: (the ETA fix riding the elastic mesh): medians per HOST, never a
#: global mean — one straggler must move the forecast, not dissolve
#: into it. _eta_last holds each key's newest committed t1 (ledger
#: bus clock) for the stale-host guard.
_eta_durs: Dict[tuple, "collections.deque[float]"] = {}
_eta_last: Dict[tuple, float] = {}

# one host-resolution helper for the whole flight-recorder layer —
# the ledger's records and the stall instants must never disagree on
# which host they attribute to
from .ledger import _host  # noqa: E402


def enable(stall_factor: Optional[float] = None,
           min_budget_s: Optional[float] = None,
           interval_s: Optional[float] = None,
           escalate: bool = False) -> None:
    """Turn the watchdog on explicitly (wins over the tune row) and
    start the monitor. ``escalate=True`` routes every detected stall
    through the resil guard funnel (rung ``watchdog_stall``)."""
    global _explicit, _escalate, _stall_factor, _min_budget_s, \
        _interval_s
    if stall_factor is not None:
        _stall_factor = float(stall_factor)
    if min_budget_s is not None:
        _min_budget_s = float(min_budget_s)
    if interval_s is not None:
        _interval_s = float(interval_s)
    _escalate = bool(escalate)
    _explicit = True
    _ensure_monitor()


def disable() -> None:
    """Stop the monitor and reject heartbeats (explicit off)."""
    global _explicit, _monitor, _monitor_stop
    _explicit = False
    with _lock:
        mon, stop = _monitor, _monitor_stop
        _monitor = None
        _monitor_stop = None
    if stop is not None:
        stop.set()
    # join OUTSIDE the lock — the monitor loop takes it per tick
    if mon is not None and mon.is_alive():
        mon.join(timeout=1.0)


def enabled() -> bool:
    if _explicit is not None:
        return _explicit
    global _resolved
    if _resolved is None:
        try:
            from ..tune.select import resolve
            _resolved = str(resolve("obs", "watchdog")) == "on"
        except Exception:
            _resolved = False
    return _resolved


def thread_alive() -> bool:
    """Whether the monitor thread is running (the off-state contract
    tests assert False on a cold cache)."""
    mon = _monitor
    return mon is not None and mon.is_alive()


def reset() -> None:
    """Stop everything and forget all state (tests)."""
    global _explicit, _resolved, _escalate, _stall_factor, \
        _min_budget_s, _interval_s
    disable()
    with _lock:
        _tracks.clear()
        _eta_durs.clear()
        _eta_last.clear()
        _stats["heartbeats"] = 0
        _stats["stalls"] = 0
    _explicit = None
    _resolved = None
    _escalate = False
    _stall_factor = STALL_FACTOR
    _min_budget_s = MIN_BUDGET_S
    _interval_s = INTERVAL_S


def stats() -> Dict[str, Any]:
    with _lock:
        out: Dict[str, Any] = dict(_stats)
        out["ops"] = {op: {"step": t.step, "total": t.total,
                           "stalled": t.stalled,
                           "median_step_s": _median(t.durs)}
                      for op, t in _tracks.items()}
    return out


def _median(durs) -> float:
    if not durs:
        return 0.0
    s = sorted(durs)
    return s[len(s) // 2]


def _eta_step_s(op: str, own_med: float) -> float:
    """Per-step seconds for the ETA gauge. Ledger on: drain the
    ``health.eta`` tail cursor into per-(op, host) wall histories and
    return the median over LIVE hosts' per-host medians — a host
    whose newest record trails the mesh's newest by more than its own
    stall budget (``max(stall_factor * its median, min_budget_s)``,
    measured on the ledger's bus clock) is stale and excluded, so a
    peer that stopped reporting can never freeze the forecast.
    Ledger off, or no records for `op` yet: `own_med` (the local
    track's own-op median — the pre-elastic path)."""
    from . import ledger as _ledger
    if not _ledger.enabled():
        return own_med
    fresh = _ledger.tail("health.eta")
    with _lock:
        for rec in fresh:
            key = (rec.op, rec.host)
            d = _eta_durs.get(key)
            if d is None:
                d = _eta_durs[key] = collections.deque(
                    maxlen=_HISTORY)
            d.append(rec.wall)
            if rec.t1 > _eta_last.get(key, 0.0):
                _eta_last[key] = rec.t1
        keys = [k for k in _eta_durs if k[0] == op and _eta_durs[k]]
        if not keys:
            return own_med
        newest = max(_eta_last[k] for k in keys)
        meds = []
        for k in keys:
            med = _median(_eta_durs[k])
            budget = max(_stall_factor * med, _min_budget_s)
            if newest - _eta_last[k] <= budget:
                meds.append(med)
    if not meds:
        return own_med
    return _median(meds)


def heartbeat(op: str, step: int, total: Optional[int] = None
              ) -> None:
    """Progress pulse from a step loop: one boolean check when the
    watchdog is off; on, it updates the op's track, refreshes the
    median-step estimate, publishes the ETA gauge, and clears any
    standing stall flag (the episode ended — progress resumed)."""
    if not enabled():
        return
    _ensure_monitor()
    now = time.monotonic()
    remaining = None
    with _lock:
        t = _tracks.get(op)
        if t is None:
            t = _tracks[op] = _Track()
            t.host = _host()
        if t.step >= 0 and step > t.step:
            t.durs.append((now - t.last) / max(step - t.step, 1))
        t.step = int(step)
        if total is not None:
            t.total = int(total)
        t.last = now
        t.stalled = False
        _stats["heartbeats"] += 1
        med = _median(t.durs)
        if t.total is not None:
            # a beat fires at the START of step `step`, so steps
            # step..total-1 all remain — total - step of them (the
            # completion beat at step == total reads 0)
            remaining = max(t.total - t.step, 0)
    if remaining is not None and _events.enabled():
        step_s = _eta_step_s(op, med)
        if step_s > 0:
            _metrics.set_gauge("health.eta_seconds",
                               round(remaining * step_s, 6))


def _ensure_monitor() -> None:
    global _monitor, _monitor_stop
    if _monitor is not None and _monitor.is_alive():
        return
    with _lock:
        if _monitor is not None and _monitor.is_alive():
            return
        stop = threading.Event()
        mon = threading.Thread(target=_monitor_loop, args=(stop,),
                               name="obs-watchdog", daemon=True)
        _monitor = mon
        _monitor_stop = stop
        # start() INSIDE the lock: a not-yet-started thread reads
        # is_alive() False, so a concurrent first heartbeat in the
        # window between assign and start would spawn a SECOND
        # monitor (double-counted stalls, an orphaned thread)
        mon.start()


def _monitor_loop(stop: threading.Event) -> None:
    while not stop.wait(_interval_s):
        if not enabled():
            return            # disable() raced our last wake
        now = time.monotonic()
        fired = []
        with _lock:
            for op, t in _tracks.items():
                if t.stalled or t.step < 0:
                    continue
                if t.total is not None and t.step >= t.total:
                    # the COMPLETION beat (step == total, published
                    # after each step loop): the run is done. The
                    # last REAL step (total-1) stays monitored — its
                    # trailing sweep is the largest of the stream
                    continue
                if not t.durs:
                    # no completed step interval yet: the first
                    # step's wall includes jit compile (seconds cold,
                    # tens of seconds on a real chip) — a run is its
                    # own baseline only after one measured step, so
                    # never cry stall during the cold prologue
                    continue
                budget = max(_stall_factor * _median(t.durs),
                             _min_budget_s)
                silent = now - t.last
                if silent > budget:
                    t.stalled = True
                    _stats["stalls"] += 1
                    fired.append((op, t.step, t.host, silent, budget))
        for op, step, host, silent, budget in fired:
            _publish_stall(op, step, host, silent, budget)


def _publish_stall(op: str, step: int, host: int, silent: float,
                   budget: float) -> None:
    """One stall episode: the obs instant + counter (bus on), and the
    guard-funnel handoff when escalation is armed. The local _stats
    mirror was already bumped under the lock, so obs-off callers
    still see the count."""
    if _events.enabled():
        _metrics.inc("health.stalls")
        _events.instant("health::stall", cat="health", op=op,
                        step=step, host=host,
                        stalled_s=round(silent, 4),
                        budget_s=round(budget, 4))
    if _escalate:
        from ..resil import guard as _guard
        _guard.record_escalation("watchdog_stall", op=op, step=step,
                                 host=host)
