"""SLO time-series telemetry (ISSUE 18 tentpole, part 2).

Bounded ring time-series with streaming quantile sketches, fed
per-tenant and per-op from obs/reqtrace.py span closure:

  * every series is a ``(name, tenant, op)`` key holding a bounded
    ring of ``(t, value)`` samples plus a :class:`QuantileSketch` —
    a fixed-bin log histogram (geometric bins, ratio :data:`GAMMA`)
    whose p50/p95/p99 estimates land within one bin of
    ``np.percentile`` on the raw sample (pinned by tests);
  * per-tenant SLO burn accounting: :func:`note_slo` records each
    request's latency against the tuned ``serve/slo_ms`` objective in
    a rolling window; :func:`slo_burn` exposes the violation fraction
    as an *input* to the admission ladder (serve/admission.py sheds /
    degrades on burn and records the violated objective in its
    escalation payload);
  * :func:`render_prometheus` is the text exposition — the RPC
    ``{cmd: "metrics"}`` command and ``Server.metrics_text()`` serve
    it (Prometheus summary syntax, quantile labels).

Gate discipline (obs/ledger.py): the FROZEN ``("serve", "metrics") =
"off"`` row keeps every publisher a single boolean check — zero
series, zero SLO windows, an empty exposition, and no growth on any
cold-route structure.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

#: log-histogram geometry: bin i covers [V0*GAMMA^i, V0*GAMMA^(i+1)).
#: 512 bins at 5% ratio span 1 microsecond .. ~7e4 seconds — every
#: latency this daemon can produce, at a resolution finer than any
#: SLO anyone writes.
V0 = 1e-6
GAMMA = 1.05
NBINS = 512

#: per-series sample ring capacity
RING_CAP = 1024

#: SLO burn window: the last N closed requests per tenant
SLO_WINDOW = 256

_lock = threading.Lock()
_series: Dict[Tuple[str, str, str], "Series"] = {}
_slo: Dict[str, "collections.deque"] = {}

_explicit: Optional[bool] = None
_resolved: Optional[bool] = None
_slo_target: Optional[float] = None

_LOG_GAMMA = math.log(GAMMA)


# -- the gate -------------------------------------------------------------

def enable() -> None:
    global _explicit
    _explicit = True


def disable() -> None:
    global _explicit
    _explicit = False


def enabled() -> bool:
    """Explicit override > memoized FROZEN ``serve/metrics`` row."""
    if _explicit is not None:
        return _explicit
    global _resolved
    if _resolved is None:
        try:
            from ..tune.select import resolve
            _resolved = str(resolve("serve", "metrics")) == "on"
        except Exception:
            _resolved = False
    return _resolved


def reset() -> None:
    global _explicit, _resolved, _slo_target
    with _lock:
        _series.clear()
        _slo.clear()
    _explicit = None
    _resolved = None
    _slo_target = None


# -- the sketch -----------------------------------------------------------

def bin_index(v: float) -> int:
    """The log-histogram bin holding `v` (clamped to the range)."""
    if v <= V0:
        return 0
    return min(int(math.log(v / V0) / _LOG_GAMMA), NBINS - 1)


class QuantileSketch:
    """Streaming quantiles over a fixed-bin log histogram: O(1)
    insert, O(bins) query, and a pinned accuracy contract — the
    estimate's bin is within one bin of ``np.percentile``'s on the
    same sample (a <=~10% relative envelope at GAMMA=1.05)."""

    __slots__ = ("bins", "count", "sum", "min", "max")

    def __init__(self) -> None:
        self.bins = np.zeros(NBINS, dtype=np.int64)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def add(self, v: float) -> None:
        v = float(v)
        self.bins[bin_index(v)] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def quantile(self, q: float) -> Optional[float]:
        """Geometric midpoint of the bin holding the q-quantile rank,
        or None on an empty sketch."""
        if not self.count:
            return None
        rank = min(max(int(math.ceil(q * self.count)), 1), self.count)
        cum = np.cumsum(self.bins)
        i = int(np.searchsorted(cum, rank))
        return V0 * GAMMA ** (i + 0.5)


class Series:
    """One (name, tenant, op) time-series: sample ring + sketch.
    Mutated only under the module lock (sample())."""

    __slots__ = ("name", "tenant", "op", "ring", "sketch")

    def __init__(self, name: str, tenant: str, op: str) -> None:
        self.name = name
        self.tenant = tenant
        self.op = op
        self.ring: "collections.deque" = \
            collections.deque(maxlen=RING_CAP)
        self.sketch = QuantileSketch()


# -- publishers -----------------------------------------------------------

def sample(name: str, value: float, tenant: str = "",
           op: str = "") -> None:
    """THE series publisher: literal first arg at every call site —
    tools/slate_lint collects these names into the obs-literals
    near-miss check (SL802) and docs/OBS_REFERENCE.md."""
    if not enabled():
        return
    key = (name, str(tenant), str(op))
    with _lock:
        s = _series.get(key)
        if s is None:
            s = _series[key] = Series(*key)
        s.ring.append((time.perf_counter(), float(value)))
        s.sketch.add(value)


def slo_target_s() -> float:
    """The latency objective in seconds (tuned ``serve/slo_ms``)."""
    global _slo_target
    if _slo_target is None:
        try:
            from ..tune.select import tuned_int
            _slo_target = tuned_int("serve", "slo_ms", 500) / 1e3
        except Exception:
            _slo_target = 0.5
    return _slo_target


def note_slo(tenant: str, latency_s: float) -> None:
    """Record one closed request against the tenant's latency
    objective (rolling SLO_WINDOW of violation flags)."""
    if not enabled():
        return
    bad = 1 if latency_s > slo_target_s() else 0
    with _lock:
        d = _slo.get(tenant)
        if d is None:
            d = _slo[tenant] = collections.deque(maxlen=SLO_WINDOW)
        d.append(bad)


def slo_burn(tenant: str) -> Optional[Dict[str, Any]]:
    """The tenant's current burn — the fraction of its rolling window
    violating the objective — or None (metrics off / no traffic).
    The dict names the objective so an admission decision made on it
    can record exactly what was violated."""
    if not enabled():
        return None
    with _lock:
        d = _slo.get(tenant)
        if not d:
            return None
        burn = sum(d) / len(d)
        window = len(d)
    target = slo_target_s()
    return {"objective": "latency_ms<=%d" % round(target * 1e3),
            "target_ms": round(target * 1e3, 3),
            "burn": round(burn, 4), "window": window}


# -- readers --------------------------------------------------------------

def get(name: str, tenant: str = "", op: str = ""
        ) -> Optional[Series]:
    with _lock:
        return _series.get((name, str(tenant), str(op)))


def quantiles(name: str, tenant: str = "", op: str = "",
              qs: Tuple[float, ...] = (0.5, 0.95, 0.99)
              ) -> Optional[Dict[str, float]]:
    """{"p50": ..., "p95": ..., "p99": ...} for one series, or None."""
    s = get(name, tenant, op)
    if s is None or not s.sketch.count:
        return None
    return {"p%g" % (q * 100): s.sketch.quantile(q) for q in qs}


def summary(name: str, tenant: str = "", op: str = ""
            ) -> Optional[Dict[str, Any]]:
    s = get(name, tenant, op)
    if s is None or not s.sketch.count:
        return None
    sk = s.sketch
    out: Dict[str, Any] = {"count": sk.count, "sum": sk.sum,
                           "mean": sk.sum / sk.count,
                           "min": sk.min, "max": sk.max}
    out.update(quantiles(name, tenant, op) or {})
    return out


def snapshot() -> Dict[str, Any]:
    """Every series' summary plus every tenant's SLO burn (keys are
    "name|tenant|op" strings — JSON/stats-friendly)."""
    with _lock:
        keys = list(_series)
        tenants = list(_slo)
    return {"series": {"|".join(k): summary(*k) for k in keys},
            "slo": {t: slo_burn(t) for t in tenants}}


def render_prometheus() -> str:
    """Prometheus text exposition (summary syntax): one metric per
    series name, tenant/op as labels, quantile sub-samples plus
    _count/_sum; per-tenant SLO burn as a gauge. Empty string when
    metrics are off (the RPC ``metrics`` command's off-state)."""
    if not enabled():
        return ""
    with _lock:
        entries = [(k, _series[k]) for k in sorted(_series)]
        tenants = sorted(_slo)
    lines: List[str] = []
    seen = set()
    for (name, tenant, op), s in entries:
        metric = "slate_" + name.replace(".", "_").replace("::", "_")
        if metric not in seen:
            seen.add(metric)
            lines.append("# TYPE %s summary" % metric)
        labels = 'tenant="%s",op="%s"' % (tenant, op)
        for q in (0.5, 0.95, 0.99):
            v = s.sketch.quantile(q)
            if v is not None:
                lines.append('%s{%s,quantile="%g"} %.9g'
                             % (metric, labels, q, v))
        lines.append("%s_count{%s} %d" % (metric, labels,
                                          s.sketch.count))
        lines.append("%s_sum{%s} %.9g" % (metric, labels,
                                          s.sketch.sum))
    if tenants:
        lines.append("# TYPE slate_serve_slo_burn gauge")
        for t in tenants:
            b = slo_burn(t)
            if b is not None:
                lines.append('slate_serve_slo_burn{tenant="%s",'
                             'objective="%s"} %.4f'
                             % (t, b["objective"], b["burn"]))
    return "\n".join(lines) + ("\n" if lines else "")
