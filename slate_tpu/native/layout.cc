// Host-side tiled-layout engine (C++, OpenMP).
//
// TPU-native counterpart of the reference's native data-path pieces:
// Matrix::fromLAPACK (Matrix.hh:58) and fromScaLAPACK (:73-96) layout
// adoption, and the scalapack_api descriptor decode
// (scalapack_slate.hh:27-29). JAX owns device memory; what remains
// native is the host-side repack between user layouts (column-major
// LAPACK, 2D-block-cyclic ScaLAPACK locals) and the framework's padded
// row-major canonical form — bandwidth-bound loops that benefit from
// OpenMP and avoid numpy temporaries.
//
// Built by slate_tpu.native (g++ -O3 -fopenmp -shared); all entry
// points are extern "C" for ctypes.

#include <cstdint>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

template <typename T>
void pack_colmajor(const T* src, int64_t m, int64_t n, int64_t ld,
                   T* dst, int64_t mpad, int64_t npad) {
  // column-major (m, n, leading dim ld) -> zero-padded row-major
  // (mpad, npad)
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < mpad; ++i) {
    T* drow = dst + i * npad;
    if (i < m) {
      for (int64_t j = 0; j < n; ++j) drow[j] = src[i + j * ld];
      if (n < npad) std::memset(drow + n, 0, sizeof(T) * (npad - n));
    } else {
      std::memset(drow, 0, sizeof(T) * npad);
    }
  }
}

template <typename T>
void unpack_colmajor(const T* src, int64_t mpad, int64_t npad, T* dst,
                     int64_t m, int64_t n, int64_t ld) {
  // padded row-major (mpad, npad) -> column-major (m, n, ld)
#pragma omp parallel for schedule(static)
  for (int64_t j = 0; j < n; ++j) {
    T* dcol = dst + j * ld;
    for (int64_t i = 0; i < m; ++i) dcol[i] = src[i * npad + j];
  }
}

template <typename T>
void bc_import(const T* local, int64_t llm, int64_t lln, T* dst,
               int64_t m, int64_t n, int64_t npad, int64_t mb,
               int64_t nb, int64_t p, int64_t q, int64_t pi,
               int64_t qi) {
  // Scatter one rank's ScaLAPACK 2D-block-cyclic local array
  // (column-major, llm x lln) into the global padded row-major dense.
  // Global tile (ti, tj) lives on rank (ti % p, tj % q) at local tile
  // (ti / p, tj / q) — the BLACS descriptor decode of
  // scalapack_slate.hh:27-29.
  int64_t mt = (m + mb - 1) / mb;
  int64_t nt = (n + nb - 1) / nb;
#pragma omp parallel for collapse(2) schedule(static)
  for (int64_t ti = 0; ti < mt; ++ti) {
    for (int64_t tj = 0; tj < nt; ++tj) {
      if (ti % p != pi || tj % q != qi) continue;
      int64_t li = (ti / p) * mb;   // local row offset
      int64_t lj = (tj / q) * nb;   // local col offset
      int64_t gi = ti * mb;
      int64_t gj = tj * nb;
      int64_t hm = (m - gi < mb) ? (m - gi) : mb;
      int64_t hn = (n - gj < nb) ? (n - gj) : nb;
      for (int64_t i = 0; i < hm; ++i) {
        for (int64_t j = 0; j < hn; ++j) {
          dst[(gi + i) * npad + (gj + j)] =
              local[(li + i) + (lj + j) * llm];
        }
      }
    }
  }
}

template <typename T>
void bc_export(const T* src, int64_t m, int64_t n, int64_t npad,
               T* local, int64_t llm, int64_t lln, int64_t mb,
               int64_t nb, int64_t p, int64_t q, int64_t pi,
               int64_t qi) {
  int64_t mt = (m + mb - 1) / mb;
  int64_t nt = (n + nb - 1) / nb;
#pragma omp parallel for collapse(2) schedule(static)
  for (int64_t ti = 0; ti < mt; ++ti) {
    for (int64_t tj = 0; tj < nt; ++tj) {
      if (ti % p != pi || tj % q != qi) continue;
      int64_t li = (ti / p) * mb;
      int64_t lj = (tj / q) * nb;
      int64_t gi = ti * mb;
      int64_t gj = tj * nb;
      int64_t hm = (m - gi < mb) ? (m - gi) : mb;
      int64_t hn = (n - gj < nb) ? (n - gj) : nb;
      for (int64_t i = 0; i < hm; ++i) {
        for (int64_t j = 0; j < hn; ++j) {
          local[(li + i) + (lj + j) * llm] =
              src[(gi + i) * npad + (gj + j)];
        }
      }
    }
  }
}

}  // namespace

extern "C" {

#define DEFINE_API(T, SUFFIX)                                              \
  void pack_colmajor_##SUFFIX(const T* src, int64_t m, int64_t n,          \
                              int64_t ld, T* dst, int64_t mpad,            \
                              int64_t npad) {                              \
    pack_colmajor<T>(src, m, n, ld, dst, mpad, npad);                      \
  }                                                                        \
  void unpack_colmajor_##SUFFIX(const T* src, int64_t mpad, int64_t npad,  \
                                T* dst, int64_t m, int64_t n,              \
                                int64_t ld) {                              \
    unpack_colmajor<T>(src, mpad, npad, dst, m, n, ld);                    \
  }                                                                        \
  void bc_import_##SUFFIX(const T* local, int64_t llm, int64_t lln,        \
                          T* dst, int64_t m, int64_t n, int64_t npad,      \
                          int64_t mb, int64_t nb, int64_t p, int64_t q,    \
                          int64_t pi, int64_t qi) {                        \
    bc_import<T>(local, llm, lln, dst, m, n, npad, mb, nb, p, q, pi, qi);  \
  }                                                                        \
  void bc_export_##SUFFIX(const T* src, int64_t m, int64_t n,              \
                          int64_t npad, T* local, int64_t llm,             \
                          int64_t lln, int64_t mb, int64_t nb,             \
                          int64_t p, int64_t q, int64_t pi, int64_t qi) {  \
    bc_export<T>(src, m, n, npad, local, llm, lln, mb, nb, p, q, pi, qi); \
  }

DEFINE_API(float, f32)
DEFINE_API(double, f64)

int64_t slate_tpu_native_abi_version() { return 1; }

}  // extern "C"
