"""Native (C++) host-side layout engine loader.

Builds/loads _layout.so (see layout.cc for the reference mapping) via
ctypes; every entry point has a numpy fallback so the package works
without a toolchain. Rebuilds on demand when the .so is missing or
stale relative to layout.cc.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
from typing import Optional

import numpy as np

_HERE = pathlib.Path(__file__).parent
_SO = _HERE / "_layout.so"
_SRC = _HERE / "layout.cc"

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # Portable -O3 by default; -march=native is opt-in (a binary built
    # on one host must not SIGILL on another). The .so is never
    # committed (gitignored) — it is built from source at first use, so
    # a loaded artifact always matches this host and layout.cc.
    cflags = ["-O3"]
    if os.environ.get("SLATE_TPU_NATIVE_MARCH_NATIVE"):
        cflags.append("-march=native")
    try:
        subprocess.run(
            ["g++", *cflags, "-fopenmp", "-shared",
             "-fPIC", "-o", str(_SO), str(_SRC)],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not _SO.exists() or (_SRC.exists()
                            and _SRC.stat().st_mtime > _SO.stat().st_mtime):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(str(_SO))
        assert lib.slate_tpu_native_abi_version() == 1
        _lib = lib
    except Exception:
        _lib = None
    return _lib


_SUFFIX = {np.dtype(np.float32): "f32", np.dtype(np.float64): "f64"}


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def pack_colmajor(src: np.ndarray, mpad: int, npad: int) -> np.ndarray:
    """Column-major (m, n) -> zero-padded row-major (mpad, npad)
    (reference fromLAPACK layout adoption, Matrix.hh:58)."""
    m, n = src.shape
    suffix = _SUFFIX.get(src.dtype)
    lib = get_lib()
    if lib is None or suffix is None or not src.flags.f_contiguous:
        out = np.zeros((mpad, npad), src.dtype)
        out[:m, :n] = src
        return out
    out = np.empty((mpad, npad), src.dtype)
    getattr(lib, f"pack_colmajor_{suffix}")(
        _ptr(src), ctypes.c_int64(m), ctypes.c_int64(n),
        ctypes.c_int64(m), _ptr(out), ctypes.c_int64(mpad),
        ctypes.c_int64(npad))
    return out


def unpack_colmajor(src: np.ndarray, m: int, n: int) -> np.ndarray:
    """Padded row-major -> column-major (m, n) (reference in-place
    output adoption for LAPACK-layout users)."""
    mpad, npad = src.shape
    suffix = _SUFFIX.get(src.dtype)
    lib = get_lib()
    if lib is None or suffix is None or not src.flags.c_contiguous:
        return np.asfortranarray(src[:m, :n])
    out = np.empty((m, n), src.dtype, order="F")
    getattr(lib, f"unpack_colmajor_{suffix}")(
        _ptr(src), ctypes.c_int64(mpad), ctypes.c_int64(npad),
        _ptr(out), ctypes.c_int64(m), ctypes.c_int64(n),
        ctypes.c_int64(m))
    return out


def bc_import(local: np.ndarray, dst: np.ndarray, m: int, n: int,
              mb: int, nb: int, p: int, q: int, pi: int, qi: int
              ) -> None:
    """Scatter one rank's ScaLAPACK 2D-block-cyclic local (column-major)
    into the global padded row-major dense (in place) — the
    scalapack_api import path (scalapack_slate.hh:27-29)."""
    suffix = _SUFFIX.get(local.dtype)
    lib = get_lib()
    npad = dst.shape[1]
    if lib is None or suffix is None or not local.flags.f_contiguous:
        mt = -(-m // mb)
        nt = -(-n // nb)
        for ti in range(mt):
            for tj in range(nt):
                if ti % p != pi or tj % q != qi:
                    continue
                li, lj = (ti // p) * mb, (tj // q) * nb
                gi, gj = ti * mb, tj * nb
                hm, hn = min(mb, m - gi), min(nb, n - gj)
                dst[gi:gi + hm, gj:gj + hn] = \
                    local[li:li + hm, lj:lj + hn]
        return
    getattr(lib, f"bc_import_{suffix}")(
        _ptr(local), ctypes.c_int64(local.shape[0]),
        ctypes.c_int64(local.shape[1]), _ptr(dst), ctypes.c_int64(m),
        ctypes.c_int64(n), ctypes.c_int64(npad), ctypes.c_int64(mb),
        ctypes.c_int64(nb), ctypes.c_int64(p), ctypes.c_int64(q),
        ctypes.c_int64(pi), ctypes.c_int64(qi))


def bc_export(src: np.ndarray, m: int, n: int, mb: int, nb: int,
              p: int, q: int, pi: int, qi: int, llm: int, lln: int
              ) -> np.ndarray:
    """Gather rank (pi, qi)'s block-cyclic local array (column-major)
    from the global padded row-major dense."""
    local = np.zeros((llm, lln), src.dtype, order="F")
    suffix = _SUFFIX.get(src.dtype)
    lib = get_lib()
    if lib is None or suffix is None or not src.flags.c_contiguous:
        mt = -(-m // mb)
        nt = -(-n // nb)
        for ti in range(mt):
            for tj in range(nt):
                if ti % p != pi or tj % q != qi:
                    continue
                li, lj = (ti // p) * mb, (tj // q) * nb
                gi, gj = ti * mb, tj * nb
                hm, hn = min(mb, m - gi), min(nb, n - gj)
                local[li:li + hm, lj:lj + hn] = \
                    src[gi:gi + hm, gj:gj + hn]
        return local
    getattr(lib, f"bc_export_{suffix}")(
        _ptr(src), ctypes.c_int64(m), ctypes.c_int64(n),
        ctypes.c_int64(src.shape[1]), _ptr(local),
        ctypes.c_int64(llm), ctypes.c_int64(lln), ctypes.c_int64(mb),
        ctypes.c_int64(nb), ctypes.c_int64(p), ctypes.c_int64(q),
        ctypes.c_int64(pi), ctypes.c_int64(qi))
    return local
