"""Checkpoint/resume for the OOC factorization streams (ISSUE 9
tentpole, part 2).

An out-of-core factorization's full state already lives in HOST memory
(the accumulating factor the D2H writer fills panel by panel); this
module makes that state DURABLE at a panel cadence so a crashed stream
resumes mid-factorization instead of restarting:

* the factor (and side arrays: geqrf's taus) is backed by a
  **memory-mapped .npy file** instead of an anonymous host array — the
  existing D2H writer then writes panels straight into the durable
  file, no extra copy, no second write path;
* after every ``ckpt_every``-th completed panel the driver drains the
  writeback queue (every panel <= k is on disk) and :meth:`commit`\\ s:
  msync the maps, then atomically (tmp + rename) advance ``meta.json``
  to epoch k+1. A crash at ANY point leaves a consistent checkpoint:
  the meta is either the old epoch or the new one, and panels beyond
  the committed epoch are simply refactored on resume;
* :func:`maybe_checkpointer` re-opens a directory whose meta matches
  (driver, shape, dtype, panel width, input fingerprint) and reports
  the committed ``epoch`` — the driver starts its panel loop there.
  A mismatched or absent meta starts fresh at epoch 0. The input
  fingerprint (strided-sample CRC) keeps a stale checkpoint from
  silently resuming a DIFFERENT matrix's factorization.

Bitwise resume contract: the left-looking streams recompute panel k
from the input plus factor panels 0..k-1, all of which the checkpoint
holds bit-exactly (the D2H writer wrote the same device bytes the
uninterrupted run wrote), so an interrupted-then-resumed factorization
produces THE SAME factor bitwise (pinned by tests, single-engine and
2-process sharded). The sharded right-looking drivers additionally
(a) agree on the resume epoch with a tree min-reduction (hosts crash
at different commit points) and (b) catch trailing panels up by
replaying factors 0..epoch-1 from the durable mirror — the identical
kernel/operand sequence the uninterrupted run applied.

The cadence rides the tune subsystem: explicit ``ckpt_every`` argument
> measured entry > FROZEN ``resil/ckpt_every`` = 0. At 0 (or with no
checkpoint path) no checkpointer exists, no file is touched, and the
drivers are bit-identical to the pre-resil code — the bench ``--faults``
lane pins the 0-byte overhead.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

SCHEMA_VERSION = 1

_META = "meta.json"


def fingerprint(a: np.ndarray, cap: int = 1 << 17) -> str:
    """Cheap input identity: CRC32 of <= `cap` strided samples plus
    the INPUT's shape/dtype — enough to catch "resumed with a
    different matrix" without hashing gigabytes."""
    shape, dtype = a.shape, a.dtype
    s = np.ascontiguousarray(a.reshape(-1)[:: max(a.size // cap, 1)])
    return "%08x:%s:%s" % (zlib.crc32(s.tobytes()) & 0xFFFFFFFF,
                           "x".join(map(str, shape)),
                           np.dtype(dtype).str)


class Checkpointer:
    """One OOC driver invocation's durable snapshot set. Drivers use:
    ``ck.array(name)`` for the memmapped output arrays (the D2H writer
    targets slices of these), ``ck.epoch`` for the resume start,
    ``ck.due(k)`` / ``ck.commit(k + 1)`` at the panel cadence."""

    def __init__(self, path: str, driver: str,
                 arrays: Dict[str, Tuple[Tuple[int, ...], Any]],
                 panel_cols: int, nt: int, every: int,
                 fp: str = "",
                 extra_meta: Optional[Dict[str, Any]] = None) -> None:
        self.path = str(path)
        self.driver = driver
        self.every = max(int(every), 1)
        self.nt = int(nt)
        self.epoch = 0
        self.commits = 0
        self._specs = {name: (tuple(shape), np.dtype(dt).str)
                       for name, (shape, dt) in arrays.items()}
        self._meta_core = {"version": SCHEMA_VERSION, "driver": driver,
                           "panel_cols": int(panel_cols),
                           "nt": self.nt, "arrays": self._specs,
                           "fingerprint": fp}
        # algorithm-identity keys beyond the array specs (ISSUE 10:
        # the OOC-LU drivers record their `lu_pivot` mode): part of
        # the fingerprint guard, so resuming a checkpoint written
        # under a DIFFERENT mode is rejected — _read_meta sees the
        # mismatch and the stream starts fresh at epoch 0 instead of
        # mixing two pivot disciplines' panels in one factor
        if extra_meta:
            self._meta_core.update(
                {str(k): v for k, v in extra_meta.items()})
        self.arrays: Dict[str, np.ndarray] = {}
        os.makedirs(self.path, exist_ok=True)
        meta = self._read_meta()
        if meta is not None:
            self.epoch = int(meta.get("epoch", 0))
            for name, (shape, dt) in self._specs.items():
                self.arrays[name] = np.lib.format.open_memmap(
                    self._file(name), mode="r+")
        else:
            self.epoch = 0
            for name, (shape, dt) in self._specs.items():
                # fresh maps read as zeros (new file pages), matching
                # the zeros-initialized factor the drivers start from
                self.arrays[name] = np.lib.format.open_memmap(
                    self._file(name), mode="w+", shape=shape,
                    dtype=np.dtype(dt))
            self._write_meta(0)
        self._publish_open()

    # -- layout -----------------------------------------------------

    def _file(self, name: str) -> str:
        return os.path.join(self.path, "%s.npy" % name)

    def _read_meta(self) -> Optional[Dict[str, Any]]:
        """The on-disk meta IF it matches this invocation's identity
        (driver, array specs, panel width, fingerprint) and every
        array file exists — else None (start fresh)."""
        try:
            with open(os.path.join(self.path, _META)) as f:
                meta = json.load(f)
        except Exception:
            return None
        core = {k: meta.get(k) for k in self._meta_core}
        # JSON round-trips tuples as lists; normalize before compare
        want = json.loads(json.dumps(self._meta_core))
        if core != want:
            return None
        if not all(os.path.exists(self._file(n)) for n in self._specs):
            return None
        return meta

    def _write_meta(self, epoch: int) -> None:
        meta = dict(self._meta_core, epoch=int(epoch))
        tmp = os.path.join(self.path, _META + ".tmp.%d" % os.getpid())
        with open(tmp, "w") as f:
            json.dump(meta, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, _META))

    # -- driver-facing API ------------------------------------------

    def array(self, name: str) -> np.ndarray:
        return self.arrays[name]

    @property
    def factor(self) -> np.ndarray:
        return self.arrays["factor"]

    @property
    def complete(self) -> bool:
        return self.epoch >= self.nt

    def due(self, k: int) -> bool:
        """Commit after panel k? — every `every` panels and at the
        final panel (so a finished run resumes as a no-op)."""
        return (k + 1) % self.every == 0 or k == self.nt - 1

    def commit(self, epoch: int) -> None:
        """Advance the durable epoch: the caller has drained the D2H
        writer for every panel < epoch; msync the maps, then the
        atomic meta swap makes the progress visible to a resume."""
        for arr in self.arrays.values():
            arr.flush()
        self._write_meta(epoch)
        self.epoch = int(epoch)
        self.commits += 1
        from . import guard
        guard._count("resil.ckpt_commits")
        from ..obs import events as obs_events
        if obs_events.enabled():
            from ..obs import metrics as obs_metrics
            obs_metrics.inc("resil.ckpt_commits")
            obs_metrics.set_gauge("resil.ckpt_bytes",
                                  self.bytes_on_disk())
            obs_events.instant("resil::ckpt_commit", cat="resil",
                              driver=self.driver, epoch=self.epoch)

    def bytes_on_disk(self) -> int:
        """Durable footprint (the bench --faults overhead metric; 0
        when no checkpointer exists)."""
        total = 0
        for name in self._specs:
            try:
                total += os.path.getsize(self._file(name))
            except OSError:
                pass
        try:
            total += os.path.getsize(os.path.join(self.path, _META))
        except OSError:
            pass
        return total

    def _publish_open(self) -> None:
        from ..obs import events as obs_events
        if not obs_events.enabled():
            return
        obs_events.instant("resil::ckpt_open", cat="resil",
                           driver=self.driver, epoch=self.epoch,
                           nt=self.nt, every=self.every)


def resolve_every(every: Optional[int], n: Optional[int] = None,
                  dtype=None) -> int:
    """The commit cadence: explicit argument > measured tune entry >
    FROZEN ``resil/ckpt_every`` (0 = checkpointing off)."""
    if every is not None:
        return int(every)
    from ..tune.select import resolve
    return int(resolve("resil", "ckpt_every", n=n, dtype=dtype))


def maybe_checkpointer(path: Optional[str], driver: str,
                       a: np.ndarray, panel_cols: int, nt: int,
                       every: Optional[int] = None,
                       extra_arrays: Optional[
                           Dict[str, Tuple[Tuple[int, ...], Any]]
                       ] = None,
                       extra_meta: Optional[Dict[str, Any]] = None
                       ) -> Optional[Checkpointer]:
    """The drivers' entry: None (checkpointing off — the bit-identical
    default) when no path is given or the resolved cadence is 0, else
    a Checkpointer whose ``factor`` array matches `a`'s shape/dtype
    plus any `extra_arrays` (geqrf's taus, the LU streams' pivot
    vectors). `extra_meta` joins the identity guard (the LU streams'
    ``lu_pivot`` mode — a mode-mismatched resume starts fresh)."""
    if path is None:
        return None
    every = resolve_every(every, n=a.shape[-1], dtype=a.dtype)
    if every <= 0:
        return None
    arrays = {"factor": (tuple(a.shape), a.dtype)}
    arrays.update(extra_arrays or {})
    return Checkpointer(path, driver, arrays, panel_cols, nt, every,
                        fp=fingerprint(a), extra_meta=extra_meta)
