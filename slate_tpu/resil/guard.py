"""Guarded execution + fallback escalation (ISSUE 9 tentpole, part 3).

Three layers, all OFF-by-default and observable when they act:

* **Bounded retry with backoff** (:func:`retry`) around the operations
  that fail transiently in production — host<->HBM transfers
  (stream.py H2D uploads / D2H writebacks), scheduled collective
  traversals (dist/tree.py, the shard broadcast), and batched
  dispatches (batch/queue.py). The budget rides the tune subsystem:
  explicit argument > measured entry > FROZEN ``resil/max_retries`` /
  ``resil/backoff_us``. Retries only engage on failure, so steady
  state is bit-identical and dispatch-free; every attempt publishes a
  ``resil::retry`` obs instant and bumps ``resil.retries``.

* **Structured failures**: :class:`WorkerLost` (a mesh worker died —
  testing/multiproc.py raises it with the worker's output tail instead
  of a bare timeout), :class:`RetriesExhausted` (the retry budget ran
  out; still transient, so an escalation rung above it can reroute),
  :class:`PanelHealthError` (a factored panel failed the non-finite /
  growth-factor sentinel).

* **The degradation ladder** (:data:`ESCALATIONS`): when a route fails
  transiently or a sentinel trips, drivers step DOWN to a slower but
  sturdier route instead of dying —

      ``shard_to_stream``  sharded OOC stream -> single-engine stream
                           (linalg/ooc.py grid routes)
      ``rbt_to_getrf``     gesv_rbt's no-pivot RBT solve -> partial-
                           pivot gesv (linalg/lu.py, sentinel-gated)
      ``mixed_to_full``    mixed-precision refinement -> full-precision
                           solve (linalg/refine.py, the reference's
                           iters<0 convention)

  Every escalation funnels through :func:`record_escalation`, which
  publishes a ``resil::fallback`` obs instant and increments the
  rung's ``resil.*`` counter (tools/check_instrumented.py rule 4 lints
  this contract: the funnel exists, every rung's counter is
  ``resil.``-prefixed, and every rung is wired into a driver).

Panel sentinels (:func:`check_panel`) are gated on
:func:`enable_checks` because reading a panel's health synchronizes on
it (one extra reduction dispatch per panel) — the same deliberate
observer-effect trade linalg/refine.py documents. Disabled (default),
the drivers' jitted steady state is untouched.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from .faults import InjectedFault

#: the degradation ladder: rung -> the resil.* counter it increments.
#: A plain literal — tools/check_instrumented.py (rule 4) parses it
#: and verifies every rung is wired into a driver module.
ESCALATIONS = {
    "shard_to_stream": "resil.fallback.shard_to_stream",
    "rbt_to_getrf": "resil.fallback.rbt_to_getrf",
    "mixed_to_full": "resil.fallback.mixed_to_full",
    # a no-progress stall detected by the obs/health.py watchdog and
    # handed to THIS funnel (enable(escalate=True)) — not a reroute
    # itself, but the same bookkeeping surface the serving daemon's
    # policy layer will act on (ISSUE 14)
    "watchdog_stall": "resil.fallback.watchdog_stall",
    # the serving daemon's SLO-aware degradation ladder (ISSUE 16,
    # serve/admission.py): shed a low-priority request under load,
    # degrade an f64 request to f32 under queue-age pressure, reject
    # on a hard tenant quota — each decision is counted here (the
    # resil funnel) AND as its serve.* counter at the daemon
    # shrink-to-fit resume (ISSUE 19, dist/elastic.py): a WorkerLost
    # mid-stream no longer aborts the mesh — the survivors relaunch
    # from the durable min-epoch checkpoint with the dead host's
    # unfinished panels re-owned, one rung ABOVE shard_to_stream
    # (keeps the sharded route, sheds only the lost capacity)
    "shard_shrink": "resil.fallback.shard_shrink",
    "serve_shed": "resil.fallback.serve_shed",
    "serve_degrade": "resil.fallback.serve_degrade",
    "serve_reject": "resil.fallback.serve_reject",
}

#: growth-factor cap of the panel sentinel: |panel|_max may exceed
#: |input|_max by this factor before the panel is declared sick
#: (partial pivoting's worst case is 2^k, but a production stream at
#: 1e6x growth is numerically dead — the reference's gesv_rbt
#: breakdown regime)
GROWTH_CAP = 1.0e6


class ResilError(RuntimeError):
    """Base of the structured resilience failures."""


class WorkerLost(ResilError):
    """A coordinated mesh worker died (testing/multiproc.py reaps the
    rest and surfaces the dead worker's output tail here)."""

    def __init__(self, process_id: int, returncode: Optional[int],
                 tail: str = "", outs: Optional[list] = None) -> None:
        self.process_id = int(process_id)
        self.returncode = returncode
        self.tail = tail
        self.outs = outs or []
        super().__init__(
            "worker %d lost (rc=%s); last output:\n%s"
            % (process_id, returncode, tail[-2000:]))


class RetriesExhausted(ResilError):
    """The bounded retry budget ran out. Carries the site and the
    last failure; still transient, so escalation rungs above the
    retry layer can reroute instead of dying."""

    def __init__(self, site: str, attempts: int,
                 last: BaseException) -> None:
        self.site = site
        self.attempts = attempts
        self.last = last
        super().__init__("site %r failed %d attempt(s); last: %s"
                         % (site, attempts, last))


class PanelHealthError(ResilError):
    """A factored panel failed the non-finite / growth sentinel."""

    def __init__(self, op: str, panel: int, reason: str) -> None:
        self.op = op
        self.panel = panel
        self.reason = reason
        super().__init__("%s panel %d failed health check: %s"
                         % (op, panel, reason))


#: exception types the guard treats as transient (retry/escalate);
#: production hooks may extend this tuple for backend-specific
#: failures (e.g. a jaxlib transfer RuntimeError class)
TRANSIENT_TYPES = (InjectedFault, WorkerLost, RetriesExhausted,
                   TimeoutError, ConnectionError)


def is_transient(e: BaseException) -> bool:
    return isinstance(e, TRANSIENT_TYPES)


#: local mirrors of the resil.* counters (readable with the obs bus
#: off — bench --faults and the obs-disabled tests use these)
_lock = threading.Lock()
_counts: Dict[str, int] = {}


def _count(name: str, value: int = 1) -> None:
    with _lock:
        _counts[name] = _counts.get(name, 0) + value


def counts() -> Dict[str, int]:
    """Copy of the local retry/fallback/sentinel counters."""
    with _lock:
        return dict(_counts)


def reset_counts() -> None:
    with _lock:
        _counts.clear()


def _resolve_budget(retries: Optional[int], backoff_us: Optional[int]
                    ) -> tuple:
    from ..tune.select import resolve
    if retries is None:
        retries = int(resolve("resil", "max_retries"))
    if backoff_us is None:
        backoff_us = int(resolve("resil", "backoff_us"))
    return max(int(retries), 0), max(int(backoff_us), 0)


def retry(fn: Callable[[], Any], site: str,
          retries: Optional[int] = None,
          backoff_us: Optional[int] = None, **ctx) -> Any:
    """Run `fn` with up to `retries` re-attempts on TRANSIENT failure
    (exponential backoff: backoff_us * 2^attempt). Non-transient
    exceptions propagate immediately — the guard must never mask a
    logic bug as flakiness. Exhaustion raises :class:`RetriesExhausted`
    chained from the last failure."""
    retries, backoff_us = _resolve_budget(retries, backoff_us)
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception as e:
            if not is_transient(e):
                raise
            last = e
            if attempt >= retries:
                break
            _count("resil.retries")
            _publish_retry(site, attempt, e, ctx)
            if backoff_us:
                time.sleep(backoff_us * (1 << attempt) / 1e6)
    raise RetriesExhausted(site, retries + 1, last) from last


def retry_after_failure(fn: Callable[[], Any], site: str,
                        first: BaseException, **ctx) -> Any:
    """Continuation for a TRANSIENT failure observed OUTSIDE the
    retry frame: the zero-overhead fast paths (stream._guard_transfer,
    PanelBroadcaster, queue._dispatch) try ``fn()`` bare first and
    only enter the guard on failure — count and publish that
    triggering failure like an in-loop attempt, then run the
    remaining budget."""
    _count("resil.retries")
    _publish_retry(site, 0, first, ctx)
    return retry(fn, site, **ctx)


def _publish_retry(site: str, attempt: int, err: BaseException,
                   ctx: Dict[str, Any]) -> None:
    from ..obs import events as obs_events
    if not obs_events.enabled():
        return
    from ..obs import metrics as obs_metrics
    obs_metrics.inc("resil.retries")
    obs_events.instant("resil::retry", cat="resil", site=site,
                       attempt=attempt, error=str(err)[:120],
                       **{k: v for k, v in ctx.items()
                          if isinstance(v, (str, int, float, bool))})


def record_escalation(rung: str, **ctx) -> None:
    """THE escalation funnel: every ladder step publishes one obs
    instant and increments its rung counter plus the aggregate
    ``resil.fallbacks`` (tools/check_instrumented.py rule 4 pins this
    function's shape)."""
    counter = ESCALATIONS[rung]
    _count(counter)
    _count("resil.fallbacks")
    from ..obs import events as obs_events
    if not obs_events.enabled():
        return
    from ..obs import metrics as obs_metrics
    obs_metrics.inc(counter)
    obs_metrics.inc("resil.fallbacks")
    obs_events.instant("resil::fallback", cat="resil", rung=rung,
                       **{k: v for k, v in ctx.items()
                          if isinstance(v, (str, int, float, bool))})


def escalate(primary: Callable[[], Any], fallback: Callable[[], Any],
             rung: str, **ctx) -> Any:
    """Run `primary`; on a TRANSIENT failure, record the ladder step
    and run `fallback` instead. Non-transient failures propagate —
    a wrong answer must never be retried into a different route."""
    try:
        return primary()
    except Exception as e:
        if not is_transient(e):
            raise
        record_escalation(rung, error=str(e)[:120], **ctx)
        return fallback()


# -- panel sentinels ------------------------------------------------------

_checks_enabled = False


def enable_checks(flag: bool = True) -> None:
    """Turn the per-panel non-finite / growth sentinels on. OFF by
    default: reading a panel's health synchronizes on it (one extra
    reduction dispatch per panel), and the frozen contract is that
    resil-off drivers add no dispatches."""
    global _checks_enabled
    _checks_enabled = bool(flag)


def checks_enabled() -> bool:
    return _checks_enabled


def check_panel(op: str, panel: int, arr, ref=None) -> None:
    """Sentinel for a just-factored panel: every entry finite, and
    max|panel| within GROWTH_CAP of max|ref| (the panel's input state)
    when `ref` is given. No-op unless :func:`enable_checks` ran.
    Violations publish ``resil::sentinel`` + ``resil.sentinels`` and
    raise :class:`PanelHealthError` naming the panel — the stream
    stops AT the sick panel instead of propagating NaNs through every
    trailing update."""
    if not _checks_enabled:
        return
    import jax.numpy as jnp
    finite = bool(jnp.isfinite(arr).all())
    reason = None
    if not finite:
        reason = "non-finite entries"
    elif ref is not None:
        amax = float(jnp.max(jnp.abs(arr)))
        rmax = float(jnp.max(jnp.abs(ref)))
        if amax > GROWTH_CAP * max(rmax, 1e-300):
            reason = "growth factor %.3g exceeds cap %.3g" \
                % (amax / max(rmax, 1e-300), GROWTH_CAP)
    if reason is None:
        return
    _count("resil.sentinels")
    from ..obs import events as obs_events
    if obs_events.enabled():
        from ..obs import metrics as obs_metrics
        obs_metrics.inc("resil.sentinels")
        obs_events.instant("resil::sentinel", cat="resil", op=op,
                           panel=panel, reason=reason)
    raise PanelHealthError(op, panel, reason)
