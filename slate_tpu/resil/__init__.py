"""Resilience subsystem (ISSUE 9 tentpole): deterministic fault
injection (`faults`), panel-granular checkpoint/resume for the OOC
streams (`checkpoint`), and guarded execution with bounded retries plus
the fallback-escalation ladder (`guard`).

The three pieces compose: a seeded :mod:`faults` plan makes a failure
reproducible, :mod:`guard` absorbs it (retry) or reroutes around it
(escalation ladder), and :mod:`checkpoint` bounds the blast radius of
the failures neither can absorb (process death) to one panel cadence.
Everything is OFF by default — no plan installed, checkpointing frozen
at ``resil/ckpt_every = 0``, sentinels disabled — and the off state is
bit-identical to the pre-resil drivers (pinned by tests).
"""

from . import checkpoint, faults, guard  # noqa: F401

__all__ = ["checkpoint", "faults", "guard"]
