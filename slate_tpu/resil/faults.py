"""Deterministic fault injection (ISSUE 9 tentpole, part 1).

A process-wide **fault plan** — seeded, step-indexed, JSON-serializable
— drives injection points threaded through the layers that can actually
fail in production:

  site          where it fires                      context keys
  ----          --------------                      ------------
  ``h2d``       StreamEngine uploads                buf, idx
  ``d2h``       StreamEngine writeback tasks        buf, idx
  ``ppermute``  dist/tree.py scheduled traversals   op, size
  ``step``      OOC driver panel-step loops         op, step, mine
  ``batch``     batch/queue.py dispatches           op
  ``batch_submit``  batch/queue.py submissions      op
  ``flusher``   batch/queue.py background flusher   busy
  ``worker``    testing/multiproc.py worker init    process
  ``serve_admit``  serve/server.py admission        tenant, op
  ``serve_cache``  serve/server.py factor cache     op
  ``serve_drain``  serve/server.py drain/shutdown   pending

(The table mirrors the machine-readable :data:`SITES` registry below;
tools/slate_lint's fault-site analyzer pins schema == live ``check``
call sites. Panel CORRUPTION has no site of its own: the ``nan`` kind
poisons the payload at the ``h2d``/``d2h`` transfer sites — an
earlier draft of this table advertised a ``panel`` site that no code
ever checked, exactly the silent-drift class the lint now fails.)

Plan JSON schema (one object; ``FaultPlan.to_json``/``from_json``)::

    {
      "seed": 0,                     # drives probabilistic rules
      "faults": [
        {
          "site":  "h2d",            # injection site (table above)
          "match": {"buf": "L", "idx": 5, "host": 1},
                                     # every key must equal the call
                                     # context; "host" matches
                                     # jax.process_index(); omitted
                                     # keys match anything
          "after": 0,                # skip the first `after` matches
          "times": 1,                # then fire on the next `times`
          "prob":  1.0,              # per-match firing probability,
                                     # hashed from (seed, rule,
                                     # occurrence) — deterministic
                                     # regardless of thread timing
          "kind":  "error"     # error | hang | nan | kill | slow
        }
      ]
    }

Kinds: ``error`` raises :class:`InjectedFault` (transient — the guard
retry ladder absorbs it); ``hang`` sleeps ``hang_s`` (default 30)
first, then raises — the shape a stuck transfer or lost flush presents
to timeout guards; ``nan`` returns the string ``"nan"`` to the call
site, which poisons its payload (the non-finite sentinel's test
vector); ``kill`` calls ``os._exit(KILL_EXIT_CODE)`` — a dead worker,
for the multiproc crash/resume coverage; ``slow`` sleeps ``slow_s``
(default 0.05) and then lets the step proceed normally — the
deterministic straggler the elastic-mesh remapper and ``bench.py
--elastic`` are tested against (ISSUE 19).

Determinism contract: a rule's occurrence counter increments once per
matching ``check`` call, under one lock, and probabilistic firing
hashes ``(seed, rule index, occurrence)`` — so the same plan over the
same driver call sequence produces the same injection sequence
bit-identically (pinned by tests). Rules scoped to a unique event
(buf+idx, or step) are exactly reproducible even when prefetch worker
threads race the main loop; broad unscoped rules are deterministic up
to the engine's thread interleaving, so tests scope their rules.

Multi-process propagation: the parent serializes the plan into the
``SLATE_RESIL_FAULTS`` environment variable (``install_env_var``);
workers pick it up in ``testing/multiproc.init`` via
``install_from_env``. Per-host scoping rides the ``host`` match key.

Every injection is logged in the plan (``log()``) and published as an
obs instant (cat ``resil``) plus a ``resil.injected`` counter when the
bus is on — faults are never silent.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: exit status of a `kill` injection — parents assert on it to tell a
#: planned death from a crash
KILL_EXIT_CODE = 17

#: environment variable carrying a serialized plan into subprocesses
ENV_VAR = "SLATE_RESIL_FAULTS"

_KINDS = ("error", "hang", "nan", "kill", "slow")

#: the fault-site schema: site name -> where it fires. This is the
#: machine-readable registry the module docstring's table mirrors;
#: tools/slate_lint (SL501-SL503) statically verifies every entry has
#: a live ``check(site)``/``_guard_transfer(site)`` call, every live
#: call site is listed here, and every plan rule in the tree names a
#: listed site — a rule naming anything else can never fire.
SITES = {
    "h2d": "StreamEngine uploads (buf, idx)",
    "d2h": "StreamEngine writeback tasks (buf, idx)",
    "ppermute": "dist/tree.py scheduled traversals (op, size)",
    "step": "OOC driver panel-step loops (op, step; sharded loops "
            "add mine=<this host owns the panel> so straggler plans "
            "can scope their slowdown to owned work)",
    "batch": "batch/queue.py dispatches (op)",
    "batch_submit": "batch/queue.py submissions (op)",
    "flusher": "batch/queue.py background flusher (busy)",
    "worker": "testing/multiproc.py worker init (process)",
    "serve_admit": "serve/server.py admission decisions (tenant, op)",
    "serve_cache": "serve/server.py factor-cache lookups (op)",
    "serve_drain": "serve/server.py drain/shutdown (pending)",
}


class InjectedFault(RuntimeError):
    """A planned failure (kind ``error``/``hang``). Transient by
    construction — guard.retry absorbs it within the retry budget."""

    def __init__(self, site: str, rule: int, occurrence: int,
                 ctx: Dict[str, Any]) -> None:
        self.site = site
        self.rule = rule
        self.occurrence = occurrence
        self.ctx = dict(ctx)
        super().__init__(
            "injected fault at site %r (rule %d, occurrence %d, "
            "ctx %r)" % (site, rule, occurrence, ctx))


class FaultPlan:
    """The parsed plan + its replay state (occurrence counters and the
    injection log). State is per-install: re-installing the same plan
    resets the counters, which is what makes a replay start clean."""

    def __init__(self, faults: List[Dict[str, Any]],
                 seed: int = 0) -> None:
        self.seed = int(seed)
        self.rules: List[Dict[str, Any]] = []
        for i, f in enumerate(faults or []):
            kind = f.get("kind", "error")
            if kind not in _KINDS:
                raise ValueError("fault rule %d: unknown kind %r "
                                 "(have %s)" % (i, kind, list(_KINDS)))
            self.rules.append({
                "site": str(f["site"]),
                "match": dict(f.get("match", {})),
                "after": int(f.get("after", 0)),
                "times": int(f.get("times", 1)),
                "prob": float(f.get("prob", 1.0)),
                "kind": kind,
                "hang_s": float(f.get("hang_s", 30.0)),
                "slow_s": float(f.get("slow_s", 0.05)),
            })
        self._lock = threading.Lock()
        self._seen = [0] * len(self.rules)
        self._fired = [0] * len(self.rules)
        self._log: List[Dict[str, Any]] = []

    # -- serialization ----------------------------------------------

    def to_json(self) -> str:
        return json.dumps({"seed": self.seed, "faults": self.rules},
                          sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        raw = json.loads(text)
        return cls(raw.get("faults", []), seed=raw.get("seed", 0))

    # -- matching ---------------------------------------------------

    @staticmethod
    def _host() -> int:
        try:
            import jax
            return int(jax.process_index())
        except Exception:
            return 0

    def _matches(self, rule: Dict[str, Any], site: str,
                 ctx: Dict[str, Any]) -> bool:
        if rule["site"] != site:
            return False
        for key, want in rule["match"].items():
            have = self._host() if key == "host" else ctx.get(key)
            if have != want:
                return False
        return True

    def _roll(self, rule_idx: int, occurrence: int) -> float:
        """Deterministic per-(rule, occurrence) uniform in [0, 1):
        a hash, not an RNG stream, so thread timing cannot reorder
        the draws."""
        h = hashlib.sha256(("%d:%d:%d" % (self.seed, rule_idx,
                                          occurrence)).encode())
        return int.from_bytes(h.digest()[:8], "big") / 2.0 ** 64

    def _check(self, site: str, ctx: Dict[str, Any]) -> Optional[str]:
        action = None
        for i, rule in enumerate(self.rules):
            if not self._matches(rule, site, ctx):
                continue
            with self._lock:
                occ = self._seen[i]
                self._seen[i] += 1
                live = rule["after"] <= occ < rule["after"] \
                    + rule["times"]
                fire = live and (rule["prob"] >= 1.0
                                 or self._roll(i, occ) < rule["prob"])
                if fire:
                    self._fired[i] += 1
                    self._log.append({"site": site, "rule": i,
                                      "occurrence": occ,
                                      "kind": rule["kind"],
                                      "ctx": dict(ctx)})
            if not fire:
                continue
            _publish(site, i, occ, rule["kind"], ctx)
            if rule["kind"] == "kill":
                os._exit(KILL_EXIT_CODE)
            if rule["kind"] == "nan":
                action = "nan"
                continue
            if rule["kind"] == "slow":
                # a deterministic straggler: stall the matched step by
                # slow_s and CONTINUE — no exception, no retry; the
                # elastic remapper (dist/elastic.py) is what notices
                time.sleep(rule["slow_s"])
                continue
            if rule["kind"] == "hang":
                time.sleep(rule["hang_s"])
            raise InjectedFault(site, i, occ, ctx)
        return action

    # -- replay evidence --------------------------------------------

    def log(self) -> List[Dict[str, Any]]:
        """Copy of the injection log — the replay-determinism pin
        compares two runs' logs for equality."""
        with self._lock:
            return [dict(r) for r in self._log]

    def fired(self) -> int:
        with self._lock:
            return sum(self._fired)


def _publish(site: str, rule: int, occ: int, kind: str,
             ctx: Dict[str, Any]) -> None:
    from ..obs import events as obs_events
    if not obs_events.enabled():
        return
    from ..obs import metrics as obs_metrics
    obs_metrics.inc("resil.injected")
    obs_events.instant("resil::inject", cat="resil", site=site,
                       rule=rule, occurrence=occ, kind=kind,
                       **{k: v for k, v in ctx.items()
                          if isinstance(v, (str, int, float, bool))})


#: the process-wide active plan; None = injection entirely off (the
#: default — check() is then one attribute load and a compare)
_PLAN: Optional[FaultPlan] = None


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Activate `plan` process-wide (None clears). Returns it."""
    global _PLAN
    _PLAN = plan
    return plan


def clear() -> None:
    install(None)


def active() -> Optional[FaultPlan]:
    return _PLAN


def check(site: str, **ctx) -> Optional[str]:
    """The injection point: no-op (None) without a plan; with one,
    evaluates the rules — possibly raising, sleeping, or exiting per
    the matched rule's kind — and returns ``"nan"`` when a corruption
    rule fired (the call site poisons its payload)."""
    plan = _PLAN
    if plan is None:
        return None
    return plan._check(site, dict(ctx))


def install_env_var(plan: FaultPlan,
                    env: Optional[Dict[str, str]] = None
                    ) -> Dict[str, str]:
    """Serialize `plan` into an environment mapping for a subprocess
    (testing/multiproc.launch merges it over the worker env)."""
    env = dict(env or {})
    env[ENV_VAR] = plan.to_json()
    return env


def install_from_env() -> Optional[FaultPlan]:
    """Install the plan carried by ``SLATE_RESIL_FAULTS``, if any
    (workers call this via testing/multiproc.init)."""
    text = os.environ.get(ENV_VAR)
    if not text:
        return None
    return install(FaultPlan.from_json(text))
