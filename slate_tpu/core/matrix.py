"""Matrix class hierarchy (reference include/slate/*.hh, 12 classes —
SURVEY layer map row 4).

The reference's C++ hierarchy (Matrix, BaseTrapezoidMatrix →
Trapezoid/Triangular/Symmetric/Hermitian, band variants) exists primarily
to dispatch structure-aware algorithms and constrain constructors. Here the
structure lives in TiledMatrix metadata; these thin constructors give the
same vocabulary and validation. Each returns a TiledMatrix tagged with the
right MatrixType, so the whole hierarchy stays a single pytree type and
every driver accepts any of them.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .enums import Diag, MatrixType, Uplo
from .exceptions import DimensionError
from .tiles import TiledMatrix


def Matrix(a=None, *, m: int = 0, n: int = 0, mb: int = 256,
           nb: Optional[int] = None, dtype=jnp.float32) -> TiledMatrix:
    """General m x n matrix (reference Matrix.hh:26)."""
    if a is not None:
        return TiledMatrix.from_dense(a, mb, nb)
    return TiledMatrix.zeros(m, n, mb, nb, dtype)


def _structured(a, m, n, mb, nb, dtype, mtype, uplo, diag=Diag.NonUnit,
                kl=-1, ku=-1, square=True) -> TiledMatrix:
    if a is not None:
        t = TiledMatrix.from_dense(a, mb, nb, mtype=mtype, uplo=uplo,
                                   diag=diag, kl=kl, ku=ku)
    else:
        t = TiledMatrix.zeros(m, n or m, mb, nb, dtype, mtype=mtype,
                              uplo=uplo, diag=diag, kl=kl, ku=ku)
    if square and t.m != t.n:
        raise DimensionError(f"{mtype.name} matrix must be square, "
                             f"got {t.m}x{t.n}")
    return t


def TrapezoidMatrix(uplo: Uplo, a=None, *, m=0, n=0, mb=256, nb=None,
                    diag=Diag.NonUnit, dtype=jnp.float32) -> TiledMatrix:
    """Reference TrapezoidMatrix.hh:26."""
    return _structured(a, m, n, mb, nb, dtype, MatrixType.Trapezoid, uplo,
                       diag, square=False)


def TriangularMatrix(uplo: Uplo, a=None, *, n=0, mb=256, nb=None,
                     diag=Diag.NonUnit, dtype=jnp.float32) -> TiledMatrix:
    """Reference TriangularMatrix.hh:30."""
    return _structured(a, n, n, mb, nb, dtype, MatrixType.Triangular, uplo,
                       diag)


def SymmetricMatrix(uplo: Uplo, a=None, *, n=0, mb=256, nb=None,
                    dtype=jnp.float32) -> TiledMatrix:
    """Reference SymmetricMatrix.hh:26."""
    return _structured(a, n, n, mb, nb, dtype, MatrixType.Symmetric, uplo)


def HermitianMatrix(uplo: Uplo, a=None, *, n=0, mb=256, nb=None,
                    dtype=jnp.float32) -> TiledMatrix:
    """Reference HermitianMatrix.hh:26."""
    return _structured(a, n, n, mb, nb, dtype, MatrixType.Hermitian, uplo)


def BandMatrix(kl: int, ku: int, a=None, *, m=0, n=0, mb=256, nb=None,
               dtype=jnp.float32) -> TiledMatrix:
    """General band matrix (reference BandMatrix.hh:26). Storage is dense
    tile-aligned with the band mask applied logically — the TPU-native
    trade: HBM is cheap relative to the cost of ragged gather/scatter, and
    band algorithms below restrict computation to the band's tile
    diagonals."""
    return _structured(a, m, n, mb, nb, dtype, MatrixType.GeneralBand,
                       Uplo.General, kl=kl, ku=ku, square=False)


def TriangularBandMatrix(uplo: Uplo, kd: int, a=None, *, n=0, mb=256,
                         nb=None, diag=Diag.NonUnit,
                         dtype=jnp.float32) -> TiledMatrix:
    """Reference TriangularBandMatrix.hh:28."""
    kl, ku = (kd, 0) if uplo is Uplo.Lower else (0, kd)
    return _structured(a, n, n, mb, nb, dtype, MatrixType.TriangularBand,
                       uplo, diag, kl=kl, ku=ku)


def HermitianBandMatrix(uplo: Uplo, kd: int, a=None, *, n=0, mb=256,
                        nb=None, dtype=jnp.float32) -> TiledMatrix:
    """Reference HermitianBandMatrix.hh:29."""
    kl, ku = (kd, 0) if uplo is Uplo.Lower else (0, kd)
    return _structured(a, n, n, mb, nb, dtype, MatrixType.HermitianBand,
                       uplo, kl=kl, ku=ku)
