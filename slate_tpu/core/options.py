"""Options handling (reference include/slate/types.hh:32-58).

The reference threads a ``std::map<Option, OptionValue>`` through every
routine. Here options are a plain dict keyed by :class:`Option` (or str
aliases), read through :func:`get_option` with typed defaults.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Union

from .enums import Option, Target

OptionsLike = Optional[Mapping[Union[Option, str], Any]]

# String aliases so pythonic call sites can write opts={"lookahead": 2}.
_STR_ALIASES = {
    "lookahead": Option.Lookahead,
    "block_size": Option.BlockSize,
    "nb": Option.BlockSize,
    "inner_blocking": Option.InnerBlocking,
    "ib": Option.InnerBlocking,
    "max_panel_threads": Option.MaxPanelThreads,
    "tolerance": Option.Tolerance,
    "tol": Option.Tolerance,
    "max_iterations": Option.MaxIterations,
    "itermax": Option.MaxIterations,
    "use_fallback_solver": Option.UseFallbackSolver,
    "pivot_threshold": Option.PivotThreshold,
    "target": Option.Target,
    "depth": Option.Depth,
    "method_lu": Option.MethodLU,
    "method_gels": Option.MethodGels,
    "method_gemm": Option.MethodGemm,
    "method_hemm": Option.MethodHemm,
    "method_trsm": Option.MethodTrsm,
    "method_cholqr": Option.MethodCholQR,
    "method_eig": Option.MethodEig,
    "method_svd": Option.MethodSVD,
    "tune": Option.Tune,
}

_DEFAULTS = {
    Option.Lookahead: 1,
    Option.BlockSize: 256,
    # TPU-tuned: each ib-wide sub-panel is one fused Pallas dispatch, so
    # wider is fewer latency-bound dispatches (the reference's CPU ib=16
    # tuning does not transfer)
    Option.InnerBlocking: 128,
    Option.MaxPanelThreads: 1,
    Option.Tolerance: None,       # routine-specific
    Option.MaxIterations: 30,
    Option.UseFallbackSolver: True,
    Option.PivotThreshold: 1.0,
    Option.Target: Target.Devices,
    Option.Depth: 2,
    Option.Tune: True,
}

#: Option -> tune-cache parameter name, for get_option_tuned
_TUNE_PARAM = {
    Option.BlockSize: "nb",
    Option.InnerBlocking: "ib",
    Option.Lookahead: "lookahead",
}


def normalize_options(opts: OptionsLike) -> dict:
    """Resolve string aliases to Option keys; validate keys."""
    out: dict = {}
    if not opts:
        return out
    for k, v in opts.items():
        if isinstance(k, str):
            kk = _STR_ALIASES.get(k.lower())
            if kk is None:
                raise KeyError(f"unknown option {k!r}")
            out[kk] = v
        elif isinstance(k, Option):
            out[k] = v
        else:
            raise KeyError(f"unknown option key type {type(k)}")
    return out


def get_option(opts: OptionsLike, key: Option, default: Any = None) -> Any:
    """Reference get_option<T> (types.hh). A plain lookup: resolves the
    requested key (and its string aliases) without validating unrelated
    keys — call normalize_options once at driver entry for validation."""
    if opts:
        if key in opts:
            return opts[key]
        for s, k in _STR_ALIASES.items():
            if k is key and s in opts:
                return opts[s]
    if default is not None:
        return default
    return _DEFAULTS.get(key)


def has_option(opts: OptionsLike, key: Option) -> bool:
    """True iff the caller EXPLICITLY passed `key` (directly or via a
    string alias) — the guard that keeps autotuned values from ever
    overriding a user choice (tune/select.py precedence rule 1)."""
    if not opts:
        return False
    if key in opts:
        return True
    return any(k is key and s in opts for s, k in _STR_ALIASES.items())


def get_option_tuned(opts: OptionsLike, key: Option, op: str,
                     n: Optional[int] = None, dtype: Any = None,
                     fallback: Any = None) -> Any:
    """get_option with the autotuner spliced between explicit options
    and defaults: explicit `opts` value > measured tune-cache entry
    for (op, backend, device, dtype, size-bucket) > `fallback` (the
    caller's pre-tune default) > the _DEFAULTS registry. Only the keys
    in _TUNE_PARAM are tunable; anything else degrades to get_option.
    """
    param = _TUNE_PARAM.get(key)
    if param is None:
        return get_option(opts, key, fallback)
    from ..tune.select import resolve
    if fallback is None:
        # no caller formula: resolve falls through to the FROZEN
        # shipped table, whose "*" rows mirror _DEFAULTS for these
        # keys (pinned equal by test_tune.py)
        return resolve(op, param, opts=opts, option=key, n=n,
                       dtype=dtype)
    return resolve(op, param, opts=opts, option=key, n=n, dtype=dtype,
                   fallback=fallback)
