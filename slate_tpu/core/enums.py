"""Core enums for slate_tpu.

TPU-native re-design of the reference's enum vocabulary
(include/slate/enums.hh:34-149). The reference's ``Target`` selects between
OpenMP-task / nested / batch / GPU execution paths; on TPU everything is a
single XLA program, so ``Target`` survives only as a compatibility shim
selecting jit options. MOSI coherency states (enums.hh:138-144) do not exist
here: XLA owns residency.
"""

from __future__ import annotations

import enum


class Uplo(enum.Enum):
    """Which triangle of a matrix is referenced (blaspp Uplo)."""

    General = "g"
    Lower = "l"
    Upper = "u"

    def flip(self) -> "Uplo":
        if self is Uplo.Lower:
            return Uplo.Upper
        if self is Uplo.Upper:
            return Uplo.Lower
        return self


class Op(enum.Enum):
    """Transposition flag carried on matrix views (transpose-by-flag,
    reference BaseMatrix op_ field)."""

    NoTrans = "n"
    Trans = "t"
    ConjTrans = "c"


class Diag(enum.Enum):
    NonUnit = "n"
    Unit = "u"


class Side(enum.Enum):
    Left = "l"
    Right = "r"


class Norm(enum.Enum):
    One = "1"
    Inf = "i"
    Fro = "f"
    Max = "m"


class NormScope(enum.Enum):
    """Reference enums.hh:115."""

    Columns = "c"
    Rows = "r"
    Matrix = "m"


class GridOrder(enum.Enum):
    """Process-grid ordering (reference enums.hh:125)."""

    Col = "c"
    Row = "r"


class Target(enum.Enum):
    """Execution-target compatibility shim (reference enums.hh:34-40).

    On TPU there is one compiled path; ``Host*`` variants all alias the
    default jit path so reference-style call sites keep working.
    """

    Host = "h"
    HostTask = "t"
    HostNest = "n"
    HostBatch = "b"
    Devices = "d"


class TileKind(enum.Enum):
    """Reference Tile.hh:120 — retained for API parity; in the functional
    TPU design all storage is framework-owned device memory."""

    Workspace = "w"
    SlateOwned = "o"
    UserOwned = "u"


class Layout(enum.Enum):
    """Reference layout flag. Canonical storage here is always row-major
    (C-order) jax arrays; kept so layout-sensitive call sites can assert."""

    ColMajor = "c"
    RowMajor = "r"


class Option(enum.Enum):
    """Typed option keys (reference enums.hh:63-99). Used as keys of an
    options mapping threaded through every driver."""

    ChunkSize = enum.auto()
    Lookahead = enum.auto()
    BlockSize = enum.auto()
    InnerBlocking = enum.auto()
    MaxPanelThreads = enum.auto()
    Tolerance = enum.auto()
    MaxIterations = enum.auto()
    UseFallbackSolver = enum.auto()
    PivotThreshold = enum.auto()
    Target = enum.auto()
    PrintVerbose = enum.auto()
    PrintEdgeItems = enum.auto()
    PrintWidth = enum.auto()
    PrintPrecision = enum.auto()
    HoldLocalWorkspace = enum.auto()
    Depth = enum.auto()          # RBT depth
    MethodCholQR = enum.auto()
    MethodEig = enum.auto()
    MethodGels = enum.auto()
    MethodGemm = enum.auto()
    MethodHemm = enum.auto()
    MethodLU = enum.auto()
    MethodFactor = enum.auto()
    Grid = enum.auto()           # ProcessGrid for Tiled/SPMD execution
    MethodTrsm = enum.auto()
    MethodSVD = enum.auto()


class MatrixType(enum.Enum):
    """Structure tag for the matrix class hierarchy."""

    General = "ge"
    Trapezoid = "tz"
    Triangular = "tr"
    Symmetric = "sy"
    Hermitian = "he"
    GeneralBand = "gb"
    TriangularBand = "tb"
    HermitianBand = "hb"


#: Reference HostNum=-1 (enums.hh:132-134); kept for API parity.
HostNum = -1
AllDevices = -2
AnyDevice = -3
