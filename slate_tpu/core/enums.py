"""Core enums for slate_tpu.

TPU-native re-design of the reference's enum vocabulary
(include/slate/enums.hh:34-149). The reference's ``Target`` selects between
OpenMP-task / nested / batch / GPU execution paths; on TPU everything is a
single XLA program, so ``Target`` survives only as a compatibility shim
selecting jit options. MOSI coherency states (enums.hh:138-144) do not exist
here: XLA owns residency.
"""

from __future__ import annotations

import enum


class Uplo(enum.Enum):
    """Which triangle of a matrix is referenced (blaspp Uplo)."""

    General = "g"
    Lower = "l"
    Upper = "u"

    def flip(self) -> "Uplo":
        if self is Uplo.Lower:
            return Uplo.Upper
        if self is Uplo.Upper:
            return Uplo.Lower
        return self


class Op(enum.Enum):
    """Transposition flag carried on matrix views (transpose-by-flag,
    reference BaseMatrix op_ field)."""

    NoTrans = "n"
    Trans = "t"
    ConjTrans = "c"


class Diag(enum.Enum):
    NonUnit = "n"
    Unit = "u"


class Side(enum.Enum):
    Left = "l"
    Right = "r"


class Norm(enum.Enum):
    One = "1"
    Inf = "i"
    Fro = "f"
    Max = "m"


class NormScope(enum.Enum):
    """Reference enums.hh:115."""

    Columns = "c"
    Rows = "r"
    Matrix = "m"


class GridOrder(enum.Enum):
    """Process-grid ordering (reference enums.hh:125)."""

    Col = "c"
    Row = "r"


class Target(enum.Enum):
    """Execution-target compatibility shim (reference enums.hh:34-40).

    On TPU there is one compiled path; ``Host*`` variants all alias the
    default jit path so reference-style call sites keep working.
    """

    Host = "h"
    HostTask = "t"
    HostNest = "n"
    HostBatch = "b"
    Devices = "d"


class TileKind(enum.Enum):
    """Reference Tile.hh:120 — retained for API parity; in the functional
    TPU design all storage is framework-owned device memory."""

    Workspace = "w"
    SlateOwned = "o"
    UserOwned = "u"


class Layout(enum.Enum):
    """Reference layout flag. Canonical storage here is always row-major
    (C-order) jax arrays; kept so layout-sensitive call sites can assert."""

    ColMajor = "c"
    RowMajor = "r"


class Option(enum.Enum):
    """Typed option keys (reference enums.hh:63-99). Used as keys of an
    options mapping threaded through every driver.

    Semantics map per key (live vs dissolved — every reference option
    is accepted; 'dissolved' keys are no-ops BY DESIGN because the
    mechanism they tune does not exist under XLA, with the dissolution
    documented here rather than silently):

    - Lookahead — LIVE (>= 1 selects the software-pipelined blocked
      loop, blocked.chol_loop_pipelined): the reference pipelines
      panel k+1..k+la against step k's trailing update via OpenMP
      task deps (potrf.cc:136-176); here the block step is reordered
      so the next panel and the wide trailing matmul are independent
      nodes of the compiled graph — XLA can only overlap what the
      dataflow leaves independent, so the knob's lever is the
      dependency structure itself. Depths > 1 behave as 1.
    - MaxPanelThreads — dissolved. Panels are single fused kernels
      (Pallas) or vectorized loops; the VPU lanes are the thread team.
    - Target — dissolved (one compiled path); MethodFactor is the live
      analogue choosing Fused (XLA-native kernel) vs Tiled (blocked
      SPMD algorithm).
    - InnerBlocking — LIVE: sub-panel width of the blocked QR panel
      (qr._qr_panel_blocked ib).
    - PivotThreshold — accepted for CALU API parity; the tournament
      panel (linalg/ca.py) always plays exact local partial pivoting,
      which satisfies any threshold <= 1.
    - BlockSize/ChunkSize — live where a driver takes a block size not
      implied by the tile geometry (tsqr chunk, refinement blocking).
    - Tolerance/MaxIterations/UseFallbackSolver/Depth — live
      (mixed-precision refinement, RBT).
    - MethodFactor/Grid/Method* — live routing (methods.py).
    - Print*/HoldLocalWorkspace — accepted for parity; printing goes
      through utils.printing, workspace residency is XLA's.
    """

    ChunkSize = enum.auto()
    Lookahead = enum.auto()
    BlockSize = enum.auto()
    InnerBlocking = enum.auto()
    MaxPanelThreads = enum.auto()
    Tolerance = enum.auto()
    MaxIterations = enum.auto()
    UseFallbackSolver = enum.auto()
    PivotThreshold = enum.auto()
    Target = enum.auto()
    PrintVerbose = enum.auto()
    PrintEdgeItems = enum.auto()
    PrintWidth = enum.auto()
    PrintPrecision = enum.auto()
    HoldLocalWorkspace = enum.auto()
    Depth = enum.auto()          # RBT depth
    MethodCholQR = enum.auto()
    MethodEig = enum.auto()
    MethodGels = enum.auto()
    MethodGemm = enum.auto()
    MethodHemm = enum.auto()
    MethodLU = enum.auto()
    MethodFactor = enum.auto()
    Grid = enum.auto()           # ProcessGrid for Tiled/SPMD execution
    #: utils.trace.Timers instance: drivers record named phase wall
    #: times into it (reference timers["heev::he2hb"], heev.cc:108).
    #: Wall time measures the Python-side build/dispatch when called
    #: under jit tracing; call outside jit for end-to-end phase times.
    Timers = enum.auto()
    MethodTrsm = enum.auto()
    MethodSVD = enum.auto()
    #: per-call autotuning switch (tune/select.py): False bypasses the
    #: measured cache for this call, leaving explicit options + frozen
    #: defaults — the process-wide analogue is SLATE_TPU_TUNE=0.
    Tune = enum.auto()


class MatrixType(enum.Enum):
    """Structure tag for the matrix class hierarchy."""

    General = "ge"
    Trapezoid = "tz"
    Triangular = "tr"
    Symmetric = "sy"
    Hermitian = "he"
    GeneralBand = "gb"
    TriangularBand = "tb"
    HermitianBand = "hb"


#: Reference HostNum=-1 (enums.hh:132-134); kept for API parity.
HostNum = -1
AllDevices = -2
AnyDevice = -3
