from .enums import (Diag, GridOrder, Layout, MatrixType, Norm, NormScope,
                    Op, Option, Side, Target, TileKind, Uplo)
from .exceptions import (DimensionError, OptionError, SlateError,
                         slate_assert, slate_error_if)
from .matrix import (BandMatrix, HermitianBandMatrix, HermitianMatrix,
                     Matrix, SymmetricMatrix, TrapezoidMatrix,
                     TriangularBandMatrix, TriangularMatrix)
from .methods import (MethodCholQR, MethodEig, MethodGels, MethodGemm,
                      MethodHemm, MethodLU, MethodSVD, MethodTrsm,
                      str2method)
from .options import get_option, normalize_options
from .tiles import TiledMatrix, ceil_div, round_up
