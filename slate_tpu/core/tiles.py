"""TiledMatrix — the central distributed-matrix abstraction.

TPU-native re-design of the reference's Tile / MatrixStorage / BaseMatrix
stack (include/slate/Tile.hh:129, internal/MatrixStorage.hh:151,
BaseMatrix.hh). The reference keeps a hash-map of individually-allocated
mb×nb tiles with per-device MOSI coherency states and explicit MPI
broadcasts; under XLA none of that machinery survives — the compiler owns
residency and communication. What survives is the *semantic* layer:

- tile-aligned storage: canonical form is a zero-padded dense 2D jax array
  whose padded dims are multiples of the tile sizes (mb, nb). Tiles are a
  logical indexing concept (``tile(i, j)`` is a static slice), which keeps
  every op a large, MXU-friendly dense op while preserving the reference's
  blocked-algorithm structure.
- transpose-by-flag (reference BaseMatrix op_): ``transpose()`` /
  ``conj_transpose()`` flip a metadata flag; data is shared. XLA fuses the
  eventual physical transpose into consumers.
- structure flags: uplo/diag and a MatrixType tag replace the reference's
  12-class C++ hierarchy's dispatch role; thin Python subclasses in
  ``matrix.py`` give the same construction vocabulary.
- ``sub()`` / ``slice()`` views (BaseMatrix.hh:104-122): functional slices
  rather than aliasing views — XLA turns them into zero-copy fusion in
  practice.

Padding invariant: out-of-range rows/cols of ``data`` are zero. Routines
that need a nonsingular padded diagonal (trsm, potrf, getrf) locally patch
the padded diagonal block to identity; helpers here provide that.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .enums import Diag, MatrixType, Op, Uplo
from .exceptions import DimensionError, slate_assert


_warned_downcast = False


def _asarray_warn_downcast(a):
    """jnp.asarray with the one-time float64-downcast warning: with jax
    x64 disabled, double input silently becomes single, which changes
    solver accuracy — every TiledMatrix constructor funnels through
    this so the warning cannot be bypassed."""
    orig_dtype = getattr(a, "dtype", None)
    out = jnp.asarray(a)
    global _warned_downcast
    if (not _warned_downcast and orig_dtype is not None
            and orig_dtype in (np.float64, np.complex128)
            and out.dtype != orig_dtype):
        import warnings
        warnings.warn(
            "TiledMatrix: float64 input downcast to float32 because "
            "jax x64 is disabled; enable it with "
            "jax.config.update('jax_enable_x64', True) or pass "
            "float32 data (warning shown once)", UserWarning,
            stacklevel=3)
        _warned_downcast = True
    return out


def ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def round_up(a: int, b: int) -> int:
    return ceil_div(a, b) * b


def next_pow2(x: int) -> int:
    """Smallest power of two >= x (x >= 1)."""
    p = 1
    while p < x:
        p *= 2
    return p


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class TiledMatrix:
    """A tiled, padded, optionally-sharded matrix.

    data : (m_pad, n_pad) jax array, m_pad = mt*mb, n_pad = nt*nb,
           zero-padded outside [:m, :n]. If ``op != NoTrans`` the *stored*
           array is the un-transposed original; logical shape is (n, m).

    Non-uniform tiles (reference BaseMatrix.hh:80-101 per-index
    tileMb/tileNb lambdas, examples/ex13_non_uniform_block_size.cc):
    optional ``rb``/``cb`` tuples of tile BOUNDARY offsets
    (0 = b_0 < b_1 < ... < b_mt = m) override the uniform grid for
    tile indexing — tileMb/tileNb, tile(), sub() follow the
    boundaries. On TPU the compute layout stays one dense array (XLA
    wants uniform blocks; the boundaries are static Python metadata,
    free at trace time); ``uniform()`` re-tiles to the uniform padded
    layout the factorization drivers use. Non-uniform storage is
    EXACT (m, n) — no padding — so to_dense/gemm/_store work
    unchanged.
    """

    data: jax.Array
    m: int
    n: int
    mb: int
    nb: int
    mtype: MatrixType = MatrixType.General
    uplo: Uplo = Uplo.General
    op: Op = Op.NoTrans
    diag: Diag = Diag.NonUnit
    kl: int = -1          # band lower bandwidth (band types only)
    ku: int = -1          # band upper bandwidth
    rb: Optional[Tuple[int, ...]] = None   # non-uniform row boundaries
    cb: Optional[Tuple[int, ...]] = None   # non-uniform col boundaries

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        aux = (self.m, self.n, self.mb, self.nb, self.mtype, self.uplo,
               self.op, self.diag, self.kl, self.ku, self.rb, self.cb,
               type(self))
        return (self.data,), aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        (data,) = children
        m, n, mb, nb, mtype, uplo, op, diag, kl, ku, rb, cb, klass = aux
        return klass(data=data, m=m, n=n, mb=mb, nb=nb, mtype=mtype,
                     uplo=uplo, op=op, diag=diag, kl=kl, ku=ku,
                     rb=rb, cb=cb)

    # -- basic geometry ----------------------------------------------------
    @property
    def mt(self) -> int:
        """Number of tile rows of the *stored* array (reference mt())."""
        if self.rb is not None:
            return len(self.rb) - 1
        return self.data.shape[0] // self.mb

    @property
    def nt(self) -> int:
        if self.cb is not None:
            return len(self.cb) - 1
        return self.data.shape[1] // self.nb

    @property
    def shape(self) -> Tuple[int, int]:
        """Logical (op-resolved) shape."""
        if self.op is Op.NoTrans:
            return (self.m, self.n)
        return (self.n, self.m)

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def is_complex(self) -> bool:
        return jnp.issubdtype(self.data.dtype, jnp.complexfloating)

    def tileMb(self, i: int) -> int:
        """Rows of tile i (reference tileMb) — ragged last tile, or the
        per-index boundary span when non-uniform."""
        if self.rb is not None:
            return self.rb[i + 1] - self.rb[i]
        return min(self.mb, self.m - i * self.mb)

    def tileNb(self, j: int) -> int:
        if self.cb is not None:
            return self.cb[j + 1] - self.cb[j]
        return min(self.nb, self.n - j * self.nb)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_dense(cls, a, mb: int = 256, nb: Optional[int] = None,
                   mtype: MatrixType = MatrixType.General,
                   uplo: Uplo = Uplo.General, diag: Diag = Diag.NonUnit,
                   kl: int = -1, ku: int = -1) -> "TiledMatrix":
        """Wrap a dense array, padding to tile multiples (reference
        fromLAPACK, Matrix.hh:58).

        Double-precision input with jax x64 disabled is downcast to
        single by jax; that silently changes solver accuracy, so the
        first occurrence warns (enable x64 via
        ``jax.config.update("jax_enable_x64", True)`` — CPU mesh only;
        TPU has no native f64 path — or pass f32 data explicitly)."""
        a = _asarray_warn_downcast(a)
        if a.ndim != 2:
            raise DimensionError(f"expected 2D, got {a.shape}")
        nb = nb or mb
        m, n = a.shape
        mp, np_ = round_up(max(m, 1), mb), round_up(max(n, 1), nb)
        a = jnp.pad(a, ((0, mp - m), (0, np_ - n)))
        return cls(data=a, m=m, n=n, mb=mb, nb=nb, mtype=mtype, uplo=uplo,
                   diag=diag, kl=kl, ku=ku)

    @staticmethod
    def _boundaries(extent: int, sizes) -> Tuple[int, ...]:
        """Evaluate a per-index tile-size spec (a func.TileSizeFunc
        lambda or a sequence of sizes) into boundary offsets covering
        `extent` exactly."""
        bounds = [0]
        if callable(sizes):
            i = 0
            while bounds[-1] < extent:
                s = int(sizes(i))
                slate_assert(s > 0, f"tile size func gave {s} at {i}")
                bounds.append(min(bounds[-1] + s, extent))
                i += 1
        else:
            for s in sizes:
                s = int(s)
                slate_assert(s > 0, f"tile sizes must be positive, "
                                    f"got {s}")
                bounds.append(bounds[-1] + s)
            slate_assert(bounds[-1] == extent,
                         f"tile sizes sum to {bounds[-1]}, "
                         f"expected {extent}")
        return tuple(bounds)

    @classmethod
    def from_func(cls, a, tileMb, tileNb=None,
                  mtype: MatrixType = MatrixType.General,
                  uplo: Uplo = Uplo.General,
                  diag: Diag = Diag.NonUnit) -> "TiledMatrix":
        """Wrap a dense array with NON-UNIFORM tiles driven by per-index
        size lambdas or explicit size lists (reference
        BaseMatrix.hh:80-101 lambda constructors,
        examples/ex13_non_uniform_block_size.cc; func.uniform_blocksize
        is the uniform special case). Storage stays one exact dense
        array — the boundaries are static indexing metadata (free at
        trace time), which is the TPU-native shape of this feature:
        XLA's layout does not change with the logical tiling."""
        a = _asarray_warn_downcast(a)
        if a.ndim != 2:
            raise DimensionError(f"expected 2D, got {a.shape}")
        m, n = a.shape
        if m == 0 or n == 0:
            raise DimensionError(
                f"from_func: zero-sized matrix {a.shape} not tileable")
        rb = cls._boundaries(m, tileMb)
        cb = cls._boundaries(n, tileNb if tileNb is not None else tileMb)
        return cls(data=a, m=m, n=n,
                   mb=max(b - a_ for a_, b in zip(rb, rb[1:])),
                   nb=max(b - a_ for a_, b in zip(cb, cb[1:])),
                   mtype=mtype, uplo=uplo, diag=diag, rb=rb, cb=cb)

    def uniform(self) -> "TiledMatrix":
        """Re-tile to the uniform padded layout (mb x nb) the
        factorization drivers assume; no-op if already uniform."""
        if self.rb is None and self.cb is None:
            return self
        r = self.resolve()
        return TiledMatrix.from_dense(
            r.data[:r.m, :r.n], r.mb, r.nb, mtype=r.mtype, uplo=r.uplo,
            diag=r.diag, kl=r.kl, ku=r.ku)

    @classmethod
    def zeros(cls, m: int, n: int, mb: int = 256, nb: Optional[int] = None,
              dtype=jnp.float32, **kw) -> "TiledMatrix":
        nb = nb or mb
        data = jnp.zeros((round_up(max(m, 1), mb), round_up(max(n, 1), nb)),
                         dtype)
        return cls(data=data, m=m, n=n, mb=mb, nb=nb, **kw)

    def emptyLike(self, m: Optional[int] = None, n: Optional[int] = None,
                  dtype=None) -> "TiledMatrix":
        """Reference emptyLike (Matrix.hh:117) — preserves structure
        metadata (mtype/uplo/diag/band)."""
        m = self.m if m is None else m
        n = self.n if n is None else n
        return TiledMatrix.zeros(
            m, n, self.mb, self.nb, dtype or self.dtype, mtype=self.mtype,
            uplo=self.uplo, diag=self.diag, kl=self.kl, ku=self.ku)

    # -- transpose-by-flag -------------------------------------------------
    def transpose(self) -> "TiledMatrix":
        new_op = {Op.NoTrans: Op.Trans, Op.Trans: Op.NoTrans,
                  Op.ConjTrans: Op.NoTrans}[self.op]
        # conj_trans -> trans composition would need a conj; handle exactly:
        if self.op is Op.ConjTrans:
            return dataclasses.replace(self, data=jnp.conj(self.data),
                                       op=Op.NoTrans)
        return dataclasses.replace(self, op=new_op)

    def conj_transpose(self) -> "TiledMatrix":
        new = {Op.NoTrans: Op.ConjTrans, Op.ConjTrans: Op.NoTrans,
               Op.Trans: Op.NoTrans}[self.op]
        if self.op is Op.Trans:
            return dataclasses.replace(self, data=jnp.conj(self.data),
                                       op=Op.NoTrans)
        return dataclasses.replace(self, op=new)

    @property
    def T(self) -> "TiledMatrix":
        return self.transpose()

    @property
    def H(self) -> "TiledMatrix":
        return self.conj_transpose()

    # -- views -------------------------------------------------------------
    def tile(self, i: int, j: int) -> jax.Array:
        """Tile (i, j) of the stored array, including padding (static
        indices; reference BaseMatrix::at). Non-uniform tiles slice at
        their boundary offsets (exact size, no padding)."""
        r0 = self.rb[i] if self.rb is not None else i * self.mb
        r1 = self.rb[i + 1] if self.rb is not None else (i + 1) * self.mb
        c0 = self.cb[j] if self.cb is not None else j * self.nb
        c1 = self.cb[j + 1] if self.cb is not None else (j + 1) * self.nb
        return self.data[r0:r1, c0:c1]

    def sub(self, i1: int, i2: int, j1: int, j2: int) -> "TiledMatrix":
        """Tile-index submatrix [i1..i2] x [j1..j2] inclusive (reference
        sub(), BaseMatrix.hh:104). Returns a functional copy-on-write
        view; transposed views resolve first (the reference indexes
        through the op flag, BaseMatrix.hh tileIndex logic — here the
        transpose materializes, which XLA fuses). Non-uniform views
        keep their boundary structure (re-based to the sub's origin)."""
        base = self if self.op is Op.NoTrans else self.resolve()
        if base.rb is not None or base.cb is not None:
            rb = base.rb or tuple(
                min(k * base.mb, base.m)
                for k in range(base.mt + 1))
            cb = base.cb or tuple(
                min(k * base.nb, base.n)
                for k in range(base.nt + 1))
            data = base.data[rb[i1]:rb[i2 + 1], cb[j1]:cb[j2 + 1]]
            new_rb = tuple(b - rb[i1] for b in rb[i1:i2 + 2])
            new_cb = tuple(b - cb[j1] for b in cb[j1:j2 + 2])
            return dataclasses.replace(
                base, data=data, m=new_rb[-1], n=new_cb[-1],
                mtype=MatrixType.General, uplo=Uplo.General,
                rb=new_rb, cb=new_cb)
        mm = min((i2 + 1) * base.mb, base.m) - i1 * base.mb
        nn = min((j2 + 1) * base.nb, base.n) - j1 * base.nb
        data = base.data[i1 * base.mb:(i2 + 1) * base.mb,
                         j1 * base.nb:(j2 + 1) * base.nb]
        return dataclasses.replace(base, data=data, m=mm, n=nn,
                                   mtype=MatrixType.General,
                                   uplo=Uplo.General)

    def slice(self, row1: int, row2: int, col1: int, col2: int
              ) -> "TiledMatrix":
        """Element-index submatrix [row1..row2] x [col1..col2] inclusive
        (reference slice(), BaseMatrix.hh:122). Re-tiles from element 0.

        Slices the *stored* data (not the densified matrix), preserving
        structure flags. For structured types the slice must be
        diagonal-aligned (row1 == col1), matching the reference's
        constraint on trapezoid slices."""
        r = self.resolve()
        if r.mtype is not MatrixType.General:
            slate_assert(row1 == col1,
                         "slice of structured matrix must be "
                         "diagonal-aligned (row1 == col1)")
        d = r.data[:r.m, :r.n][row1:row2 + 1, col1:col2 + 1]
        return TiledMatrix.from_dense(d, r.mb, r.nb, mtype=r.mtype,
                                      uplo=r.uplo, diag=r.diag,
                                      kl=r.kl, ku=r.ku)

    # -- densification -----------------------------------------------------
    def resolve(self) -> "TiledMatrix":
        """Materialize the op flag into the data (XLA fuses the transpose).

        Structure flags travel with the resolve: a transposed Lower
        triangular view resolves to an Upper triangular matrix."""
        if self.op is Op.NoTrans:
            return self
        d = self.data.T
        if self.op is Op.ConjTrans:
            d = jnp.conj(d)
        return dataclasses.replace(
            self, data=d, m=self.n, n=self.m, mb=self.nb, nb=self.mb,
            op=Op.NoTrans, uplo=self.uplo.flip(), kl=self.ku, ku=self.kl,
            rb=self.cb, cb=self.rb)

    def to_dense(self) -> jax.Array:
        """The mathematical (logical) matrix as a dense array: applies op,
        mirrors symmetric/Hermitian triangles, zeroes the unstored triangle
        of triangular/trapezoid types, applies unit diagonals and band
        masks."""
        r = self.resolve()
        a = r.data[:r.m, :r.n]
        mt = self.mtype
        if mt in (MatrixType.Symmetric, MatrixType.Hermitian,
                  MatrixType.HermitianBand):
            ii = jnp.arange(r.m)[:, None]
            jj = jnp.arange(r.n)[None, :]
            if r.uplo is Uplo.Lower:
                tri = jnp.where(ii >= jj, a, 0)
            else:
                tri = jnp.where(ii <= jj, a, 0)
            other = tri.T if mt is MatrixType.Symmetric else jnp.conj(tri.T)
            diag_part = jnp.diagonal(tri)
            if mt in (MatrixType.Hermitian, MatrixType.HermitianBand):
                diag_part = jnp.real(diag_part).astype(a.dtype)
            a = tri + other - jnp.diag(diag_part)
        elif mt in (MatrixType.Triangular, MatrixType.Trapezoid,
                    MatrixType.TriangularBand):
            ii = jnp.arange(r.m)[:, None]
            jj = jnp.arange(r.n)[None, :]
            if r.uplo is Uplo.Lower:
                a = jnp.where(ii >= jj, a, 0)
            else:
                a = jnp.where(ii <= jj, a, 0)
            if r.diag is Diag.Unit:
                k = min(r.m, r.n)
                a = a.at[jnp.arange(k), jnp.arange(k)].set(1)
        if mt in (MatrixType.GeneralBand, MatrixType.TriangularBand,
                  MatrixType.HermitianBand):
            kl = r.kl if r.kl >= 0 else r.m
            ku = r.ku if r.ku >= 0 else r.n
            if mt is MatrixType.HermitianBand:
                # after mirroring, bandwidth kd applies on both sides
                kl = ku = max(kl, ku)
            ii = jnp.arange(r.m)[:, None]
            jj = jnp.arange(r.n)[None, :]
            a = jnp.where((jj - ii <= ku) & (ii - jj <= kl), a, 0)
        return a

    # -- numpy interop for tests ------------------------------------------
    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.to_dense())

    def __repr__(self) -> str:
        return (f"TiledMatrix({self.shape[0]}x{self.shape[1]}, "
                f"tiles {self.mb}x{self.nb}, {self.mtype.name}, "
                f"uplo={self.uplo.name}, op={self.op.name}, "
                f"dtype={self.data.dtype})")


def pad_diag_identity(data: jax.Array, m: int, n: int) -> jax.Array:
    """Set the padded part of the diagonal to 1 so padded triangular solves
    and factorizations stay nonsingular. data is (m_pad, n_pad), logical
    (m, n)."""
    mp, np_ = data.shape
    if min(mp, np_) <= min(m, n):
        return data                   # no padded diagonal to touch
    k = min(mp, np_)
    idx = jnp.arange(k)
    cur = data[idx, idx]
    ones = jnp.ones((k,), data.dtype)
    newdiag = jnp.where(idx < min(m, n), cur, ones)
    return data.at[idx, idx].set(newdiag)
