"""Algorithm-variant selection (reference include/slate/method.hh:27-319).

Each family exposes named variants plus an Auto heuristic mirroring the
reference's selection logic (method.hh cites inline).
"""

from __future__ import annotations

import enum


class MethodTrsm(enum.Enum):
    """Reference method.hh:27-60: trsmA broadcasts B to A's ranks (better
    for few RHS); trsmB broadcasts A (better for many RHS)."""
    Auto = "auto"
    A = "A"
    B = "B"

    @staticmethod
    def select(side_left: bool, a_n: int, b_m: int, b_n: int
               ) -> "MethodTrsm":
        # reference heuristic: many RHS relative to A's order -> trsmB.
        # RHS count is B's cols for Left, B's rows for Right.
        nrhs = b_n if side_left else b_m
        return MethodTrsm.B if nrhs >= a_n else MethodTrsm.A


class MethodGemm(enum.Enum):
    """Reference method.hh:79: small n (few C columns) -> gemmA.
    ``Summa`` selects the explicit shard_map SUMMA schedule
    (parallel/collectives.summa_gemm) instead of letting XLA's SPMD
    partitioner pick the communication — the hand-written counterpart
    of the reference's gemmC broadcast loop (gemmC.cc:84-117); requires
    Option.Grid."""
    Auto = "auto"
    A = "A"
    C = "C"
    Summa = "summa"

    @staticmethod
    def select(m: int, n: int, k: int) -> "MethodGemm":
        return MethodGemm.A if n <= 256 and k >= 4 * n else MethodGemm.C


class MethodHemm(enum.Enum):
    """Reference method.hh:132."""
    Auto = "auto"
    A = "A"
    C = "C"

    @staticmethod
    def select(m: int, n: int) -> "MethodHemm":
        return MethodHemm.A if n <= 256 else MethodHemm.C


class MethodCholQR(enum.Enum):
    """Reference method.hh:184: how to form A^H A."""
    Auto = "auto"
    GemmA = "gemmA"
    GemmC = "gemmC"
    HerkA = "herkA"
    HerkC = "herkC"

    @staticmethod
    def select(m: int, n: int) -> "MethodCholQR":
        return MethodCholQR.HerkC


class MethodGels(enum.Enum):
    """Reference method.hh:237: QR (robust) vs CholQR (fast,
    well-conditioned tall-skinny). TSQR is the communication-avoiding
    tree QR (reference ttqrt role, linalg/ca.py) — as robust as QR,
    log-depth instead of column-sequential, best for very tall-skinny
    panels over a mesh."""
    Auto = "auto"
    QR = "qr"
    CholQR = "cholqr"
    TSQR = "tsqr"

    @staticmethod
    def select(m: int, n: int) -> "MethodGels":
        return MethodGels.CholQR if m >= 3 * n else MethodGels.QR


class MethodLU(enum.Enum):
    """Reference method.hh:281: partial-pivot / communication-avoiding
    tournament / no-pivot (+RBT handled by gesv_rbt)."""
    Auto = "auto"
    PartialPiv = "PPLU"
    CALU = "CALU"
    NoPiv = "NoPiv"
    BEAM = "BEAM"

    @staticmethod
    def select() -> "MethodLU":
        return MethodLU.PartialPiv


class MethodFactor(enum.Enum):
    """Execution path for the dense factorizations (potrf/getrf/geqrf).

    This is the TPU-native analogue of the reference's Target dispatch
    (potrf.cc:262-277 switching HostTask/Devices): ``Fused`` hands the
    whole factorization to XLA's native blocked kernel — one fused
    device program, the fastest single-device path (measured on v5e:
    cholesky 68%, lu 75% of the chip's attainable f32 matmul rate);
    ``Tiled`` runs the framework's blocked tile algorithm, whose block
    steps carry sharding constraints so SPMD distributes them over a
    mesh — required for multi-device execution, and the path that mirrors
    the reference's task DAG. ``Auto`` picks Fused unless the input is
    concretely sharded across more than one device."""
    Auto = "auto"
    Fused = "fused"
    Tiled = "tiled"

    @staticmethod
    def native_lu_dtype_ok(dtype) -> bool:
        """XLA's LuDecomposition custom call only implements f32/c64
        (+f64/c128 on CPU); bf16 factors (the mixed-precision lo path
        on TPU) must take the Tiled blocked LU. Cholesky is NOT
        restricted — its TPU lowering is an expander that handles bf16
        (verified on v5e)."""
        import numpy as _np
        return _np.dtype(dtype).name in ("float32", "float64",
                                         "complex64", "complex128")

    @staticmethod
    def select(data, dtype_ok: bool = True) -> "MethodFactor":
        """Auto resolution: Tiled iff `data` is a concrete array sharded
        over >1 device, or the driver reports its native kernel cannot
        handle the dtype (`dtype_ok=False` — getrf passes
        native_lu_dtype_ok). Traced (in-jit) arrays resolve to Fused —
        distributed callers inside jit pass MethodFactor.Tiled
        explicitly (as the in-repo mesh tests and dryrun do)."""
        if not dtype_ok:
            return MethodFactor.Tiled
        try:
            s = data.sharding          # tracers raise / lack this
            if len(s.device_set) > 1 and not s.is_fully_replicated:
                return MethodFactor.Tiled
        except Exception:
            pass
        return MethodFactor.Fused


class MethodEig(enum.Enum):
    """Eigensolver backend: QR iteration vs divide & conquer."""
    Auto = "auto"
    QRIteration = "qr_iteration"
    DC = "dc"

    @staticmethod
    def select(n: int, want_vectors: bool) -> "MethodEig":
        return MethodEig.DC if want_vectors else MethodEig.QRIteration


class MethodSVD(enum.Enum):
    Auto = "auto"
    QRIteration = "qr_iteration"
    DC = "dc"


def str2method(family: str, s: str):
    fam = {
        "trsm": MethodTrsm, "gemm": MethodGemm, "hemm": MethodHemm,
        "cholqr": MethodCholQR, "gels": MethodGels, "lu": MethodLU,
        "factor": MethodFactor, "eig": MethodEig, "svd": MethodSVD,
    }[family]
    for mem in fam:
        if mem.value.lower() == s.lower() or mem.name.lower() == s.lower():
            return mem
    raise KeyError(f"unknown {family} method {s!r}")
