"""Algorithm-variant selection (reference include/slate/method.hh:27-319).

Each family exposes named variants plus an Auto heuristic mirroring the
reference's selection logic (method.hh cites inline).
"""

from __future__ import annotations

import enum


class MethodTrsm(enum.Enum):
    """Reference method.hh:27-60: trsmA broadcasts B to A's ranks (better
    for few RHS); trsmB broadcasts A (better for many RHS)."""
    Auto = "auto"
    A = "A"
    B = "B"

    @staticmethod
    def select(side_left: bool, a_n: int, b_m: int, b_n: int
               ) -> "MethodTrsm":
        # reference heuristic: many RHS relative to A's order -> trsmB.
        # RHS count is B's cols for Left, B's rows for Right.
        nrhs = b_n if side_left else b_m
        return MethodTrsm.B if nrhs >= a_n else MethodTrsm.A


class MethodGemm(enum.Enum):
    """Reference method.hh:79: small n (few C columns) -> gemmA.
    ``Summa`` selects the explicit shard_map SUMMA schedule
    (parallel/collectives.summa_gemm) instead of letting XLA's SPMD
    partitioner pick the communication — the hand-written counterpart
    of the reference's gemmC broadcast loop (gemmC.cc:84-117); requires
    Option.Grid."""
    Auto = "auto"
    A = "A"
    C = "C"
    Summa = "summa"

    @staticmethod
    def select(m: int, n: int, k: int) -> "MethodGemm":
        return MethodGemm.A if n <= 256 and k >= 4 * n else MethodGemm.C


class MethodHemm(enum.Enum):
    """Reference method.hh:132."""
    Auto = "auto"
    A = "A"
    C = "C"

    @staticmethod
    def select(m: int, n: int) -> "MethodHemm":
        return MethodHemm.A if n <= 256 else MethodHemm.C


class MethodCholQR(enum.Enum):
    """Reference method.hh:184: how to form A^H A."""
    Auto = "auto"
    GemmA = "gemmA"
    GemmC = "gemmC"
    HerkA = "herkA"
    HerkC = "herkC"

    @staticmethod
    def select(m: int, n: int) -> "MethodCholQR":
        return MethodCholQR.HerkC


class MethodGels(enum.Enum):
    """Reference method.hh:237: QR (robust) vs CholQR (fast,
    well-conditioned tall-skinny). TSQR is the communication-avoiding
    tree QR (reference ttqrt role, linalg/ca.py) — as robust as QR,
    log-depth instead of column-sequential, best for very tall-skinny
    panels over a mesh."""
    Auto = "auto"
    QR = "qr"
    CholQR = "cholqr"
    TSQR = "tsqr"

    @staticmethod
    def select(m: int, n: int, on_grid: bool = False) -> "MethodGels":
        # single device: tall-skinny -> CholQR (reference heuristic).
        # On a mesh the same regime routes to the cross-device TSQR
        # tree (dist/tsqr.py): one log-depth R combine of (n, n)
        # blocks versus CholQR's gathered Gram + replicated Cholesky,
        # with QR-grade robustness (the ttqrt rationale,
        # geqrf.cc:161).
        if m >= 3 * n:
            return MethodGels.TSQR if on_grid else MethodGels.CholQR
        return MethodGels.QR


class MethodLU(enum.Enum):
    """Reference method.hh:281: partial-pivot / communication-avoiding
    tournament / no-pivot (+RBT handled by gesv_rbt)."""
    Auto = "auto"
    PartialPiv = "PPLU"
    CALU = "CALU"
    NoPiv = "NoPiv"
    BEAM = "BEAM"

    @staticmethod
    def select() -> "MethodLU":
        return MethodLU.PartialPiv


#: tallest f32 operand XLA's TPU LuDecompositionBlock custom call can
#: take: it stages (m, 128) column blocks in VMEM, so tall operands
#: trip the 16 MB scoped-vmem limit at compile time ("Ran out of
#: memory in memory space vmem ... f32[32768,128]", v5e, 2026-07-31 —
#: also why the whole-matrix Fused getrf cannot compile at n=16384).
#: Measured f32 boundary: m=10240 compiles, m=11264 does not; 8192
#: kept as the safe margin, scaled by itemsize for wider dtypes
#: (native_lu_ok). CPU has no such limit.
NATIVE_LU_MAX_M = 8192


def vmem_height_cap(base_m: int, dtype) -> int:
    """Itemsize-proportional VMEM height/element cap for kernels whose
    scalar recurrences run in f32 regardless of the panel dtype: a
    narrower panel dtype buys vmem only on the panel itself, not the
    f32 temporaries, so sub-f32 dtypes SHRINK the cap (bf16 halves it
    — measured on v5e: bf16 8192x256 dies in compile at 20.24M of
    scoped-vmem stack vs the 16M limit while f32 4096x256 and bf16
    4096x256 both run, PERF.md round-3 sweep). Wider dtypes clamp at
    the f32 cap. The one height-cap rule every Pallas panel gate
    shares (ops/pallas_kernels.py)."""
    import numpy as _np
    return base_m * min(_np.dtype(dtype).itemsize, 4) // 4


class MethodFactor(enum.Enum):
    """Execution path for the dense factorizations (potrf/getrf/geqrf).

    This is the TPU-native analogue of the reference's Target dispatch
    (potrf.cc:262-277 switching HostTask/Devices): ``Fused`` hands the
    whole factorization to XLA's native blocked kernel — one fused
    device program; ``Tiled`` runs the framework's blocked tile
    algorithm, whose block steps carry sharding constraints so SPMD
    distributes them over a mesh — required for multi-device execution,
    and (since round 3) also the fastest single-device LU: the
    carry-the-trailing-matrix form beats XLA's native LU 1.9x at
    n=8192 on v5e (PERF.md, regenerated by bench.py). Which variant
    ``Auto`` resolves to is per-routine, measured policy: potrf keeps
    Fused (native cholesky won every composition experiment), getrf
    prefers Tiled single-device, geqrf picks by size — see each
    driver."""
    Auto = "auto"
    Fused = "fused"
    Tiled = "tiled"

    @staticmethod
    def native_lu_dtype_ok(dtype) -> bool:
        """XLA's LuDecomposition custom call only implements f32/c64
        (+f64/c128 on CPU); bf16 factors (the mixed-precision lo path
        on TPU) must take the Tiled blocked LU. Cholesky is NOT
        restricted — its TPU lowering is an expander that handles bf16
        (verified on v5e)."""
        import numpy as _np
        return _np.dtype(dtype).name in ("float32", "float64",
                                         "complex64", "complex128")

    @staticmethod
    def native_lu_ok(dtype, m: int) -> bool:
        """dtype support AND the TPU scoped-vmem height limit (see
        NATIVE_LU_MAX_M). On CPU (tests, x64) only the dtype gate
        applies."""
        if not MethodFactor.native_lu_dtype_ok(dtype):
            return False
        import jax
        if jax.default_backend() == "cpu":
            return True
        import numpy as _np
        return m * _np.dtype(dtype).itemsize <= NATIVE_LU_MAX_M * 4

    @staticmethod
    def select(data, dtype_ok: bool = True) -> "MethodFactor":
        """Auto resolution: Tiled iff `data` is a concrete array sharded
        over >1 device, or the driver reports its native kernel cannot
        handle the dtype (`dtype_ok=False` — getrf passes
        native_lu_dtype_ok). Traced (in-jit) arrays resolve to Fused —
        distributed callers inside jit pass MethodFactor.Tiled
        explicitly (as the in-repo mesh tests and dryrun do)."""
        if not dtype_ok:
            return MethodFactor.Tiled
        try:
            s = data.sharding          # tracers raise / lack this
            if len(s.device_set) > 1 and not s.is_fully_replicated:
                return MethodFactor.Tiled
        except Exception:
            pass
        return MethodFactor.Fused


class MethodLUPanel(enum.Enum):
    """Execution route for ONE LU panel factorization (lu._lu_panel) —
    the per-panel arbitration under every LU consumer (getrf carry /
    pipelined / scan, getrf_tntpiv chunk nomination, band windows,
    indefinite Aasen panels, ooc._lu_panel_factor, batch drivers):

      * ``Native``: XLA's LuDecompositionBlock custom call — fastest
        where its dtype support and scoped-vmem height limit allow
        (NATIVE_LU_MAX_M);
      * ``PallasRec``: the block-recursive Pallas panel
        (ops/pallas_kernels.lu_panel_rec) — rank-ib MXU updates
        outside an ib-wide base case, row-block-gridded above the
        one-dispatch height, the only exact-pivoting panel at heights
        the native call cannot compile;
      * ``Pallas``: the round-3 rank-1 fused kernel (bf16 fallback /
        bench comparison point);
      * ``Fori``: the masked fori_loop kernel — pure XLA, always
        correct, vmappable (the batch layer's route).

    ``Auto`` resolves via the tune cache (a MEASURED
    ``method_lu_panel`` entry per (op, size, dtype) bucket), falling
    back to ``cold_default`` — exactly the pre-round-10 chain, so a
    cold cache routes bit-identically to the old code."""
    Auto = "auto"
    Native = "native"
    Fori = "fori"
    Pallas = "pallas"
    PallasRec = "pallas_rec"

    @staticmethod
    def cold_default(m: int, w: int, dtype) -> "MethodLUPanel":
        """The frozen (pre-arbitration) routing chain: native custom
        call where dtype + height allow, the fused rank-1 Pallas
        kernel where the native cannot (TPU bf16), else the fori
        kernel. Pinned by test_pallas_rec.py's cold-route test."""
        if MethodFactor.native_lu_ok(dtype, m):
            return MethodLUPanel.Native
        from ..ops import pallas_kernels as pk
        if pk.lu_panel_eligible(m, w, dtype):
            return MethodLUPanel.Pallas
        return MethodLUPanel.Fori

    @staticmethod
    def resolve(m: int, w: int, dtype) -> "MethodLUPanel":
        """Measured cache entry (validated against the hard gates),
        else cold_default."""
        from ..tune.select import tuned_method
        cached = tuned_method("lu_panel", "lu_panel", n=m, dtype=dtype)
        if cached is MethodLUPanel.Native \
                and not MethodFactor.native_lu_ok(dtype, m):
            cached = None     # a cached Native must not bypass the
            #                   dtype/height safety gates (size
            #                   buckets span shapes the probe never
            #                   ran — the getrf Fused revalidation
            #                   rule)
        if cached is not None and cached is not MethodLUPanel.Auto:
            return cached
        return MethodLUPanel.cold_default(m, w, dtype)


class MethodOOC(enum.Enum):
    """Execution route for the out-of-core streaming drivers when a
    grid is supplied (ISSUE 7):

      * ``Stream``: the single-device host<->HBM stream
        (linalg/ooc.py through linalg/stream.py) — panels staged and
        factored on this process's device only;
      * ``Sharded``: the 2D-block-cyclic sharded stream
        (dist/shard_ooc.py) — panels owned cyclically by mesh
        positions, each host's StreamEngine staging only its shard,
        factor panels broadcast over the dist/tree.py ppermute tree.

    ``Auto`` resolves through the tune cache (the ``ooc/shard_method``
    tunable; FROZEN default "stream"), so a COLD CACHE ROUTES
    BIT-IDENTICALLY to the single-device stream path even when a grid
    is passed — sharding is an earned (measured) or explicit decision,
    pinned by tests. A measured "sharded" entry is still gated on the
    problem having at least ``ooc/shard_min_panels`` panels per mesh
    rank (below that the cyclic walk cannot balance and the broadcast
    tree is pure overhead).

    The sharded drivers' broadcast-pipeline depth (ISSUE 11) rides the
    companion ``ooc/shard_lookahead`` tunable resolved by
    :meth:`lookahead` — FROZEN 0 is the step-synchronous schedule
    (bit-identical to the pre-lookahead drivers), depth >= 1 overlaps
    each step's trailing updates with the NEXT panel's factor
    broadcast (an earned/explicit decision like every reordering
    here; depth changes only WHEN identical jitted kernels run, never
    their operands, so every depth is bitwise-pinned against 0)."""
    Auto = "auto"
    Stream = "stream"
    Sharded = "sharded"

    @staticmethod
    def resolve(n: int, nt: int, nranks: int, dtype) -> "MethodOOC":
        """Auto resolution: the tuned/frozen ``ooc/shard_method``
        route, demoted to Stream when the panel count cannot give
        every rank its ``ooc/shard_min_panels`` share."""
        from ..tune.select import resolve as _resolve
        try:
            m = str2method("ooc", str(_resolve(
                "ooc", "shard_method", n=n, dtype=dtype)))
        except KeyError:
            m = MethodOOC.Stream   # newer cache vs older tree: the
            #                        frozen route, never an error
        if m is MethodOOC.Sharded:
            minp = int(_resolve("ooc", "shard_min_panels", n=n,
                                dtype=dtype))
            if nt < minp * max(int(nranks), 1):
                return MethodOOC.Stream
        return MethodOOC.Stream if m is MethodOOC.Auto else m

    @staticmethod
    def lookahead(n: int, dtype) -> int:
        """The sharded drivers' broadcast-pipeline depth: the tuned /
        frozen ``ooc/shard_lookahead`` row, clamped non-negative
        (class doc; a non-integer entry from a newer cache demotes to
        the frozen synchronous 0, never an error)."""
        from ..tune.select import resolve as _resolve
        try:
            return max(int(_resolve("ooc", "shard_lookahead", n=n,
                                    dtype=dtype)), 0)
        except (TypeError, ValueError):
            return 0


class MethodPrecision(enum.Enum):
    """Arithmetic-precision mode of the out-of-core streams
    (ISSUE 12):

      * ``Full``: every staged byte and every update runs in the
        input dtype — the PR 11 schedule bit-identically;
      * ``Mixed``: panels still FACTOR in the input dtype (the
        critical path keeps full precision), but trailing-matrix
        updates run in the lo pair dtype (refine.lo_dtype — bf16 for
        f32 input, the TPU MXU's native halved-byte contraction) and
        the PanelCache holds lo residents (demote on ``put``, promote
        on gather), so cache budget, H2D/D2H staging, and the sharded
        layer's broadcast payloads all pay half the bytes. Solves
        finish with iterative refinement (refine.host_ir) whose
        residual sentinel drives the ``mixed_to_full`` escalation
        through the resil guard funnel.

    ``Auto`` resolves through the tune cache (the ``ooc/precision``
    tunable; FROZEN default "f32"), so a COLD CACHE keeps the
    full-precision stream bit-identically — bf16 is an earned
    (measured, ``bench.py --ooc``/``--shard`` precision legs) or
    explicit decision, pinned by tests."""
    Auto = "auto"
    Full = "f32"
    Mixed = "bf16"

    @staticmethod
    def resolve(n: int, dtype) -> "MethodPrecision":
        """The tuned/frozen ``ooc/precision`` route (unknown values
        from a newer cache demote to the frozen Full, never an
        error)."""
        from ..tune.select import resolve as _resolve
        try:
            m = str2method("precision", str(_resolve(
                "ooc", "precision", n=n, dtype=dtype)))
        except KeyError:
            m = MethodPrecision.Full
        return MethodPrecision.Full if m is MethodPrecision.Auto \
            else m


class MethodBatchStrategy(enum.Enum):
    """Stacking strategy of the batched execution layer's coalescing
    queue (ISSUE 15):

      * ``Bucket``: the PR 5 pow2 shape ladder — every request rounds
        up a geometric bucket ladder with validity-masked padding, one
        vmapped dispatch per (op, bucket, nrhs, dtype). Bounded jit
        cache, but a lognormal size stream pays 30-60% of its cubic
        flops to padding (obs ``batch.padding_waste_flops``);
      * ``Ragged``: one dispatch over a RAGGED batch — requests stack
        to the max live size rounded to lane alignment (no pow2
        rounding; the coalescing key drops the bucket dimension, so
        previously-separate buckets merge into one dispatch) and the
        masked ragged Pallas kernels
        (ops/pallas_kernels.ragged_potrf/getrf/trsm) bound every
        element's work to its true extent via a per-element sizes
        vector. Fewer dispatches AND less padding — the Ragged Paged
        Attention play applied to dense factorizations.

    ``Auto`` resolves through the tune cache (the ``batch/strategy``
    tunable; FROZEN default "bucket"), so a COLD CACHE keeps the PR 5
    bucket routing bit-identically — ragged is an earned (bench
    ``--serve`` ragged leg on hardware) or explicit decision, pinned
    by tests."""
    Auto = "auto"
    Bucket = "bucket"
    Ragged = "ragged"

    @staticmethod
    def resolve(dtype=None) -> "MethodBatchStrategy":
        """The tuned/frozen ``batch/strategy`` route (unknown values
        from a newer cache demote to the frozen Bucket, never an
        error)."""
        from ..tune.select import resolve as _resolve
        try:
            m = str2method("batch", str(_resolve(
                "batch", "strategy", dtype=dtype)))
        except KeyError:
            m = MethodBatchStrategy.Bucket
        return MethodBatchStrategy.Bucket \
            if m is MethodBatchStrategy.Auto else m


class MethodLUPivot(enum.Enum):
    """Pivot discipline of the out-of-core LU stream (ISSUE 10):

      * ``Partial``: partial pivoting confined to the resident panel
        (the PR 4 ``getrf_ooc`` discipline) — the panel's row swaps
        are applied host-side to already-written L panels, which
        retires every cached L panel (the stream.py epoch bump) and
        bars the sharded layer (a per-pivot cross-shard re-stage
        storm);
      * ``Tournament``: CALU-style tournament pivoting
        (ca.tournament_pivot_rows) — the pivot permutation is
        finalized BEFORE the panel's factor column is written, factor
        panels are stored in ORIGINAL row order and never rewritten
        (zero revisit invalidations; the MRU residency cache finally
        works for LU), and the sharded 2D-block-cyclic stream
        (dist/shard_ooc.shard_getrf_ooc) becomes possible. Pivot
        growth is bounded like CALU's (2^(nb*depth) worst case vs
        partial's 2^(n-1); benign in practice) — the documented CALU
        trade.

    ``Auto`` resolves through the tune cache (the ``ooc/lu_pivot``
    tunable; FROZEN default "partial"), so a COLD CACHE keeps the
    PR 9 ``getrf_ooc`` path bit-identically — tournament is an earned
    (measured) or explicit decision, pinned by tests."""
    Auto = "auto"
    Partial = "partial"
    Tournament = "tournament"

    @staticmethod
    def resolve(n: int, dtype) -> "MethodLUPivot":
        """The tuned/frozen ``ooc/lu_pivot`` route (never an error on
        a newer cache vs an older tree — unknown values demote to the
        frozen Partial)."""
        from ..tune.select import resolve as _resolve
        try:
            m = str2method("lu_pivot", str(_resolve(
                "ooc", "lu_pivot", n=n, dtype=dtype)))
        except KeyError:
            m = MethodLUPivot.Partial
        return MethodLUPivot.Partial if m is MethodLUPivot.Auto else m


class MethodScheduler(enum.Enum):
    """Issue-loop scheduler of the streaming OOC drivers (ISSUE 17):

      * ``Walk``: the hand-written static schedules — the
        single-engine left-looking loops in linalg/ooc.py and the
        ``_BcastPipeline`` walk in dist/shard_ooc.py, untouched;
      * ``Graph``: construct-then-execute through the task-graph
        runtime (slate_tpu/sched/) — the same loop bodies as typed
        dependency-graph nodes, issued by sched/runtime.py in an
        order that is a linear extension of the walk's (bitwise-equal
        results, pinned per op / per lookahead depth, single-engine
        and sharded).

    ``Auto`` resolves through the tune cache (the ``ooc/scheduler``
    tunable; FROZEN default "walk"), so a COLD CACHE keeps the legacy
    walks bit-identically — the graph route is an earned (measured,
    ``bench.py --graph``) or explicit decision, pinned by tests."""
    Auto = "auto"
    Walk = "walk"
    Graph = "graph"

    @staticmethod
    def resolve(n: int, dtype) -> "MethodScheduler":
        """The tuned/frozen ``ooc/scheduler`` route (unknown values
        from a newer cache demote to the frozen Walk, never an
        error)."""
        from ..tune.select import resolve as _resolve
        try:
            m = str2method("scheduler", str(_resolve(
                "ooc", "scheduler", n=n, dtype=dtype)))
        except KeyError:
            m = MethodScheduler.Walk
        return MethodScheduler.Walk if m is MethodScheduler.Auto \
            else m


class MethodVisitFuse(enum.Enum):
    """Update-dispatch granularity of the streaming OOC drivers
    (ISSUE 20):

      * ``PerPanel``: one jitted visit kernel per (factor panel,
        target panel) pair — the hand-written dispatch schedule of
        linalg/ooc.py and dist/shard_ooc.py, untouched (O(nt^2)
        launches per stream);
      * ``Fused``: each step's update sweep coalesced into ONE
        dispatch — a single wide GEMM over the concatenated factor
        widths for the potrf/getrf left-looking visits, an in-jit
        ``lax.scan`` for geqrf's ordered compact-WY applies and the
        sharded right-looking trailing sweep — compiled once per
        (height, width, count-bucket) so the jit cache stays bounded.

    ``Auto`` resolves through the tune cache (the ``ooc/visit_fuse``
    tunable; FROZEN default "per_panel"), so a COLD CACHE keeps the
    per-panel dispatch stream bit-identically — the fused route is an
    earned (measured, ``bench.py --fuse``) or explicit decision,
    pinned by tests."""
    Auto = "auto"
    PerPanel = "per_panel"
    Fused = "fused"

    @staticmethod
    def resolve(n: int, dtype) -> "MethodVisitFuse":
        """The tuned/frozen ``ooc/visit_fuse`` route (unknown values
        from a newer cache demote to the frozen PerPanel, never an
        error)."""
        from ..tune.select import resolve as _resolve
        try:
            m = str2method("visit_fuse", str(_resolve(
                "ooc", "visit_fuse", n=n, dtype=dtype)))
        except KeyError:
            m = MethodVisitFuse.PerPanel
        return MethodVisitFuse.PerPanel if m is MethodVisitFuse.Auto \
            else m


class MethodOwnership(enum.Enum):
    """Panel-ownership policy of the sharded OOC stream (ISSUE 19):

      * ``Static``: the pure 2D-block-cyclic ``CyclicSchedule``
        assignment — ownership is arithmetic on the panel index,
        fixed for the life of the stream;
      * ``Elastic``: throughput-driven re-ownership
        (dist/elastic.py) — per-host effective speeds (EWMA over
        phase-split-corrected ledger step walls) drive an
        epoch-boundary re-map of not-yet-factored panels away from
        stragglers, rebuilding the remaining subgraph under the new
        map. With uniform throughput the planner never fires, so the
        route stays bitwise vs Static.

    ``Auto`` resolves through the tune cache (the ``mesh/ownership``
    tunable; FROZEN default "static"), so a COLD CACHE keeps the
    static cyclic map bit-identically — elastic is an earned
    (measured, ``bench.py --elastic``) or explicit decision, pinned
    by tests."""
    Auto = "auto"
    Static = "static"
    Elastic = "elastic"

    @staticmethod
    def resolve(n: int, dtype) -> "MethodOwnership":
        """The tuned/frozen ``mesh/ownership`` route (unknown values
        from a newer cache demote to the frozen Static, never an
        error)."""
        from ..tune.select import resolve as _resolve
        try:
            m = str2method("ownership", str(_resolve(
                "mesh", "ownership", n=n, dtype=dtype)))
        except KeyError:
            m = MethodOwnership.Static
        return MethodOwnership.Static if m is MethodOwnership.Auto \
            else m


class MethodEig(enum.Enum):
    """Eigensolver backend: QR iteration vs divide & conquer."""
    Auto = "auto"
    QRIteration = "qr_iteration"
    DC = "dc"

    @staticmethod
    def select(n: int, want_vectors: bool) -> "MethodEig":
        return MethodEig.DC if want_vectors else MethodEig.QRIteration


class MethodSVD(enum.Enum):
    Auto = "auto"
    QRIteration = "qr_iteration"
    DC = "dc"


def str2method(family: str, s: str):
    fam = {
        "trsm": MethodTrsm, "gemm": MethodGemm, "hemm": MethodHemm,
        "cholqr": MethodCholQR, "gels": MethodGels, "lu": MethodLU,
        "factor": MethodFactor, "eig": MethodEig, "svd": MethodSVD,
        "lu_panel": MethodLUPanel, "ooc": MethodOOC,
        "lu_pivot": MethodLUPivot, "precision": MethodPrecision,
        "batch": MethodBatchStrategy, "scheduler": MethodScheduler,
        "ownership": MethodOwnership, "visit_fuse": MethodVisitFuse,
    }[family]
    for mem in fam:
        if mem.value.lower() == s.lower() or mem.name.lower() == s.lower():
            return mem
    raise KeyError(f"unknown {family} method {s!r}")
