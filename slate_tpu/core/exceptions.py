"""Exceptions (reference include/slate/Exception.hh:16-100)."""

from __future__ import annotations


class SlateError(Exception):
    """Base error for slate_tpu (reference slate::Exception)."""


class DimensionError(SlateError):
    """Shape / conformability violation."""


class OptionError(SlateError):
    """Bad option key or value."""


def slate_assert(cond: bool, msg: str = "") -> None:
    """Reference slate_assert macro (Exception.hh)."""
    if not cond:
        raise SlateError(msg or "assertion failed")


def slate_error_if(cond: bool, msg: str = "") -> None:
    if cond:
        raise SlateError(msg or "error condition")
