"""Layout import/export (reference constructors fromLAPACK
(Matrix.hh:58), fromScaLAPACK (:73-96) and the scalapack_api
distribution-import role). Host-side repack runs through the native C++
engine (slate_tpu.native) with numpy fallback."""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .. import native
from .tiles import TiledMatrix, round_up


def fromLAPACK(a: np.ndarray, mb: int = 256,
               nb: Optional[int] = None) -> TiledMatrix:
    """Adopt a column-major (LAPACK-layout) host array."""
    nb = nb or mb
    a = np.asfortranarray(a)
    m, n = a.shape
    packed = native.pack_colmajor(a, round_up(max(m, 1), mb),
                                  round_up(max(n, 1), nb))
    return TiledMatrix(data=jnp.asarray(packed), m=m, n=n, mb=mb, nb=nb)


def toLAPACK(A: TiledMatrix) -> np.ndarray:
    """Export to a column-major host array."""
    r = A.resolve()
    return native.unpack_colmajor(np.asarray(r.data), r.m, r.n)


def fromScaLAPACK(locals_: Iterable[Tuple[int, int, np.ndarray]],
                  m: int, n: int, mb: int, nb: int, p: int,
                  q: int) -> TiledMatrix:
    """Assemble a TiledMatrix from per-rank 2D-block-cyclic local
    arrays: locals_ yields (pi, qi, local_colmajor). The block-cyclic
    descriptor decode runs in the native engine."""
    dst = np.zeros((round_up(max(m, 1), mb), round_up(max(n, 1), nb)))
    first = True
    for pi, qi, local in locals_:
        local = np.asfortranarray(local)
        if first:
            dst = dst.astype(local.dtype)
            first = False
        native.bc_import(local, dst, m, n, mb, nb, p, q, pi, qi)
    return TiledMatrix(data=jnp.asarray(dst), m=m, n=n, mb=mb, nb=nb)


def toScaLAPACK(A: TiledMatrix, p: int, q: int
                ) -> Dict[Tuple[int, int], np.ndarray]:
    """Export to per-rank 2D-block-cyclic local arrays."""
    r = A.resolve()
    src = np.asarray(r.data)
    m, n, mb, nb = r.m, r.n, r.mb, r.nb
    mt = -(-m // mb)
    nt = -(-n // nb)
    out = {}
    for pi in range(p):
        for qi in range(q):
            # local dims padded to whole tiles (simplifies round trips)
            ntile_rows = max(sum(1 for ti in range(mt)
                                 if ti % p == pi), 1)
            ntile_cols = max(sum(1 for tj in range(nt)
                                 if tj % q == qi), 1)
            out[(pi, qi)] = native.bc_export(
                src, m, n, mb, nb, p, q, pi, qi,
                ntile_rows * mb, ntile_cols * nb)
    return out
