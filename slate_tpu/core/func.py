"""Distribution functions (reference include/slate/func.hh:39-265).

The reference parameterizes tile→rank and tile→device maps with lambdas;
the defaults are 2D block-cyclic grids. Here these functions serve two
roles: (1) API parity — users can query which mesh coordinate owns a tile;
(2) they drive construction of jax shardings and the ``redistribute``
driver. Under XLA SPMD the map must be *affine enough* to express as a
NamedSharding; arbitrary lambdas fall back to redistribute-by-gather.
"""

from __future__ import annotations

from typing import Callable, Tuple

from .enums import GridOrder

TileRankFunc = Callable[[Tuple[int, int]], int]
TileSizeFunc = Callable[[int], int]


def uniform_blocksize(n: int, nb: int) -> TileSizeFunc:
    """Reference func.hh:39 — tile i size, ragged last tile."""
    def size(i: int) -> int:
        return min(nb, n - i * nb)
    return size


def process_2d_grid(order: GridOrder, p: int, q: int) -> TileRankFunc:
    """2D block-cyclic tile→rank map (reference func.hh:178-185)."""
    def rank(ij: Tuple[int, int]) -> int:
        i, j = ij
        if order is GridOrder.Col:
            return int(i % p + (j % q) * p)
        return int((i % p) * q + j % q)
    return rank


def process_1d_grid(order: GridOrder, size: int) -> TileRankFunc:
    """1D cyclic map (column of processes if Col)."""
    def rank(ij: Tuple[int, int]) -> int:
        i, j = ij
        return int(i % size) if order is GridOrder.Col else int(j % size)
    return rank


def device_2d_grid(order: GridOrder, p: int, q: int) -> TileRankFunc:
    """Reference func.hh:100-121 — tile→local-device map. On TPU local
    devices are mesh entries like remote ones, so this is the same map."""
    return process_2d_grid(order, p, q)


def device_1d_grid(order: GridOrder, size: int) -> TileRankFunc:
    """Reference func.hh:146."""
    return process_1d_grid(order, size)


def transpose_grid(f: TileRankFunc) -> TileRankFunc:
    """Reference func.hh:229."""
    def rank(ij: Tuple[int, int]) -> int:
        i, j = ij
        return f((j, i))
    return rank


def is_2d_cyclic_grid(mt: int, nt: int, f: TileRankFunc
                      ) -> Tuple[bool, GridOrder, int, int]:
    """Detect whether f is a 2D block-cyclic grid on an mt x nt tile grid
    (reference func.hh:265). Returns (is_cyclic, order, p, q)."""
    if mt <= 0 or nt <= 0:
        return (True, GridOrder.Col, 1, 1)
    # p = first i whose rank repeats rank(0,0) going down the column
    r00 = f((0, 0))
    p = mt
    for i in range(1, mt):
        if f((i, 0)) == r00:
            p = i
            break
    q = nt
    for j in range(1, nt):
        if f((0, j)) == r00:
            q = j
            break
    order = GridOrder.Col
    if mt > 1 and p > 1:
        order = GridOrder.Col if f((1, 0)) == r00 + 1 else GridOrder.Row
    expect = process_2d_grid(order, p, q)
    ok = all(f((i, j)) == expect((i, j))
             for i in range(mt) for j in range(nt))
    return (ok, order, p, q)
