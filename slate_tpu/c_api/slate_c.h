/* slate_tpu C API — flat-function interop layer.
 *
 * Reference analogue: include/slate/c_api/slate.h +
 * src/c_api/wrappers.cc (1517 LoC of generated flat wrappers over the
 * C++ classes). Here the flat functions wrap the Python/JAX runtime by
 * embedding CPython: the first call initializes an interpreter, imports
 * slate_tpu.c_api.bridge, and every entry point hands raw host buffers
 * (by address) to the bridge, which wraps them with ctypes/numpy,
 * runs the framework driver on the configured JAX backend, and writes
 * results back in place.
 *
 * Conventions (match LAPACK / reference c_api):
 *   - matrices are row-major contiguous (C order), lda == row stride
 *     in elements;
 *   - dtype selects f32/f64 ('s'/'d'); f64 enables jax x64 (CPU);
 *   - return value is the LAPACK info code (0 success; < 0 internal /
 *     bridge failure).
 */

#ifndef SLATE_TPU_C_API_H
#define SLATE_TPU_C_API_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Initialize the embedded runtime explicitly (optional — every entry
 * point initializes lazily). platform: "cpu", "tpu" or NULL for the
 * environment default. Returns 0 on success. */
int slate_tpu_init(const char* platform);

/* Cholesky factorization, lower triangle, in place (potrf). */
int slate_potrf(char dtype, int64_t n, void* a, int64_t lda);

/* Solve A X = B by LU with partial pivoting (gesv); A is overwritten
 * with the packed factors, B with the solution, ipiv (length n,
 * 0-based swap targets) with the pivots. */
int slate_gesv(char dtype, int64_t n, int64_t nrhs, void* a,
               int64_t lda, int32_t* ipiv, void* b, int64_t ldb);

/* SPD solve A X = B via Cholesky (posv); A overwritten with L,
 * B with X. */
int slate_posv(char dtype, int64_t n, int64_t nrhs, void* a,
               int64_t lda, void* b, int64_t ldb);

/* C := alpha A B + beta C (gemm), all row-major. */
int slate_gemm(char dtype, int64_t m, int64_t n, int64_t k,
               double alpha, const void* a, int64_t lda,
               const void* b, int64_t ldb,
               double beta, void* c, int64_t ldc);

/* Least squares min ||A x - b|| (gels), m >= n; solution in the first
 * n rows of B. A is clobbered. */
int slate_gels(char dtype, int64_t m, int64_t n, int64_t nrhs,
               void* a, int64_t lda, void* b, int64_t ldb);

/* Hermitian eigenvalues (ascending) into w; A clobbered (heev). */
int slate_heev(char dtype, int64_t n, void* a, int64_t lda, void* w);

/* Singular values (descending) into s, length min(m,n) (svd_vals). */
int slate_svd_vals(char dtype, int64_t m, int64_t n, void* a,
                   int64_t lda, void* s);

#ifdef __cplusplus
}
#endif

#endif /* SLATE_TPU_C_API_H */
