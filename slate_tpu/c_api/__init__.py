"""C API builder/loader (reference include/slate/c_api + src/c_api).

`build_library()` compiles slate_c.c into libslate_tpu_c.so (linking
libpython via python3-config --embed) so C/C++/Fortran programs can
call the flat slate_* functions in slate_c.h; the heavy lifting runs in
the embedded interpreter through bridge.py. The .so is built from
source on demand and never committed.
"""

from __future__ import annotations

import pathlib
import subprocess
import sysconfig
from typing import Optional

_HERE = pathlib.Path(__file__).parent
_SO = _HERE / "libslate_tpu_c.so"
_SRC = _HERE / "slate_c.c"

HEADER = _HERE / "slate_c.h"


def _embed_flags():
    cflags = subprocess.run(
        ["python3-config", "--includes"], check=True,
        capture_output=True, text=True).stdout.split()
    ldflags = subprocess.run(
        ["python3-config", "--ldflags", "--embed"], check=True,
        capture_output=True, text=True).stdout.split()
    libdir = sysconfig.get_config_var("LIBDIR")
    rpath = [f"-Wl,-rpath,{libdir}"] if libdir else []
    return cflags, ldflags + rpath


def build_library(force: bool = False) -> Optional[pathlib.Path]:
    """Build libslate_tpu_c.so; returns its path or None if no
    toolchain is available."""
    newest_src = max(_SRC.stat().st_mtime, HEADER.stat().st_mtime)
    if _SO.exists() and not force \
            and _SO.stat().st_mtime > newest_src:
        return _SO
    try:
        cflags, ldflags = _embed_flags()
        subprocess.run(
            ["gcc", "-O2", "-shared", "-fPIC", str(_SRC), "-o",
             str(_SO), *cflags, *ldflags],
            check=True, capture_output=True, timeout=180, text=True)
        return _SO
    except FileNotFoundError:
        return None                   # genuinely no toolchain
    except subprocess.CalledProcessError as e:
        # a real build failure must be visible, not mistaken for a
        # missing toolchain (which silently skips the C API tests)
        raise RuntimeError(
            f"slate_tpu C API build failed:\n{e.stderr}") from e
