"""Python side of the C API (reference src/c_api/wrappers.cc role).

Every function receives scalars plus raw host buffer ADDRESSES from the
C shim (slate_c.c), maps them with ctypes/numpy (zero copy), runs the
corresponding framework driver, writes results back into the caller's
memory, and returns the LAPACK info code as an int. Row-major (C
order) buffers, lda/ldb = row stride in elements.

This module must stay importable inside a bare embedded interpreter:
only stdlib + numpy at import time; jax/slate_tpu load lazily on first
call (so `slate_tpu_init("cpu")` can pin the backend first).
"""

from __future__ import annotations

import ctypes

import numpy as np

_DT = {"s": np.float32, "d": np.float64}


def _wrap(addr: int, rows: int, cols: int, ld: int, dtype):
    """View caller memory as a (rows, cols) row-major array (stride ld)."""
    if rows <= 0 or cols <= 0:
        return np.empty((max(rows, 0), max(cols, 0)), dtype)
    buf = (ctypes.c_byte * (rows * ld * np.dtype(dtype).itemsize)
           ).from_address(addr)
    return np.frombuffer(buf, dtype=dtype).reshape(rows, ld)[:, :cols]


def _vec(addr: int, n: int, dtype):
    buf = (ctypes.c_byte * (n * np.dtype(dtype).itemsize)
           ).from_address(addr)
    return np.frombuffer(buf, dtype=dtype)


#: platform requested through slate_tpu_init() when the host process
#: had already booted Python — setenv would race host threads' getenv
#: (POSIX setenv is not thread-safe), so the C shim passes it here
_platform_override = None


def set_platform(platform):
    """Record the backend platform to apply at first framework use
    (called by slate_c.c when Python predates slate_tpu_init)."""
    global _platform_override
    _platform_override = platform
    return 0


def _st(dtype_char):
    """Import the framework lazily; enable x64 for the 'd' dtype.

    JAX_PLATFORMS (env or init-time override) is applied via
    config.update — in environments where jax is preloaded with another
    backend plugin the env var alone does not take (same recipe as
    tests/conftest.py)."""
    import os

    import jax
    plat = _platform_override or os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass
    if dtype_char == "d" and not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)
    import slate_tpu as st
    return st


def potrf(dtype, n, a_addr, lda):
    try:
        st = _st(dtype)
        dt = _DT[dtype]
        a = _wrap(a_addr, n, n, lda, dt)
        A = st.HermitianMatrix(st.Uplo.Lower, np.ascontiguousarray(a),
                               mb=min(max(n, 1), 256))
        L, info = st.potrf(A, return_info=True)
        a[:] = np.tril(L.to_numpy())
        return int(info)
    except Exception:
        import traceback
        traceback.print_exc()
        return -1


def gesv(dtype, n, nrhs, a_addr, lda, ipiv_addr, b_addr, ldb):
    if n == 0 or nrhs == 0:
        return 0                    # LAPACK quick return
    try:
        st = _st(dtype)
        dt = _DT[dtype]
        a = _wrap(a_addr, n, n, lda, dt)
        b = _wrap(b_addr, n, nrhs, ldb, dt)
        nb = min(max(n, 1), 256)
        from slate_tpu import TiledMatrix
        F, X = st.gesv(st.Matrix(np.ascontiguousarray(a), mb=nb),
                       TiledMatrix.from_dense(np.ascontiguousarray(b),
                                              nb))
        a[:] = F.LU.to_numpy()
        b[:] = X.to_numpy()
        _vec(ipiv_addr, n, np.int32)[:] = np.asarray(F.pivots)[:n]
        return int(F.info)
    except Exception:
        import traceback
        traceback.print_exc()
        return -1


def posv(dtype, n, nrhs, a_addr, lda, b_addr, ldb):
    if n == 0 or nrhs == 0:
        return 0                    # LAPACK quick return
    try:
        st = _st(dtype)
        dt = _DT[dtype]
        a = _wrap(a_addr, n, n, lda, dt)
        b = _wrap(b_addr, n, nrhs, ldb, dt)
        nb = min(max(n, 1), 256)
        from slate_tpu import TiledMatrix
        A = st.HermitianMatrix(st.Uplo.Lower, np.ascontiguousarray(a),
                               mb=nb)
        L, X, info = st.posv(
            A, TiledMatrix.from_dense(np.ascontiguousarray(b), nb),
            return_info=True)
        if int(info) == 0:
            a[:] = np.tril(L.to_numpy())
            b[:] = X.to_numpy()
        return int(info)
    except Exception:
        import traceback
        traceback.print_exc()
        return -1


def gemm(dtype, m, n, k, alpha, a_addr, lda, b_addr, ldb, beta,
         c_addr, ldc):
    if m == 0 or n == 0:
        return 0                    # LAPACK quick return
    try:
        st = _st(dtype)
        dt = _DT[dtype]
        a = _wrap(a_addr, m, k, lda, dt)
        b = _wrap(b_addr, k, n, ldb, dt)
        c = _wrap(c_addr, m, n, ldc, dt)
        nb = min(max(max(m, n, k), 1), 256)
        from slate_tpu import TiledMatrix
        C = st.gemm(dt(alpha), st.Matrix(np.ascontiguousarray(a), mb=nb),
                    st.Matrix(np.ascontiguousarray(b), mb=nb),
                    dt(beta),
                    TiledMatrix.from_dense(np.ascontiguousarray(c), nb))
        c[:] = C.to_numpy()
        return 0
    except Exception:
        import traceback
        traceback.print_exc()
        return -1


def gels(dtype, m, n, nrhs, a_addr, lda, b_addr, ldb):
    if m == 0 or n == 0 or nrhs == 0:
        return 0                    # LAPACK quick return
    try:
        st = _st(dtype)
        dt = _DT[dtype]
        a = _wrap(a_addr, m, n, lda, dt)
        b = _wrap(b_addr, m, nrhs, ldb, dt)
        nb = min(max(m, 1), 256)
        from slate_tpu import TiledMatrix
        X = st.gels(st.Matrix(np.ascontiguousarray(a), mb=nb),
                    TiledMatrix.from_dense(np.ascontiguousarray(b), nb))
        b[:n] = X.to_numpy()[:n]
        return 0
    except Exception:
        import traceback
        traceback.print_exc()
        return -1


def heev(dtype, n, a_addr, lda, w_addr):
    try:
        st = _st(dtype)
        dt = _DT[dtype]
        a = _wrap(a_addr, n, n, lda, dt)
        A = st.HermitianMatrix(st.Uplo.Lower, np.ascontiguousarray(a),
                               mb=min(max(n, 1), 256))
        w, V = st.heev(A)
        _vec(w_addr, n, dt)[:] = np.asarray(w)[:n].astype(dt)
        a[:] = V.to_numpy()
        return 0
    except Exception:
        import traceback
        traceback.print_exc()
        return -1


def svd_vals(dtype, m, n, a_addr, lda, s_addr):
    try:
        st = _st(dtype)
        dt = _DT[dtype]
        a = _wrap(a_addr, m, n, lda, dt)
        s = st.svd_vals(st.Matrix(np.ascontiguousarray(a),
                                  mb=min(max(m, 1), 256)))
        k = min(m, n)
        _vec(s_addr, k, dt)[:] = np.asarray(s)[:k].astype(dt)
        return 0
    except Exception:
        import traceback
        traceback.print_exc()
        return -1
