/* slate_tpu C API implementation: CPython embedding shim.
 *
 * Reference analogue: src/c_api/wrappers.cc. All real work happens in
 * slate_tpu/c_api/bridge.py; this file only (1) boots an interpreter,
 * (2) marshals scalar arguments and raw buffer addresses into a bridge
 * call, (3) converts the bridge's integer return into the info code.
 * Buffers never cross the boundary as Python objects — the bridge maps
 * the addresses with ctypes, so there is no numpy C-API coupling.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <stdint.h>

#include "slate_c.h"

static PyObject* g_bridge = NULL;
static pthread_mutex_t g_init_lock = PTHREAD_MUTEX_INITIALIZER;

static int ensure_init(const char* platform) {
    /* serialize first-time initialization: concurrent first calls from
     * multiple threads must not double-run Py_InitializeEx /
     * PyEval_SaveThread (undefined behavior in CPython). The g_bridge
     * read happens only under the mutex — an unlocked fast-path read
     * would be a C11 data race against the write below. */
    pthread_mutex_lock(&g_init_lock);
    if (g_bridge != NULL) {
        pthread_mutex_unlock(&g_init_lock);
        return 0;
    }
    int py_was_up = Py_IsInitialized();
    if (platform != NULL && !py_was_up) {
        /* safe: no Python (or other host) threads exist yet that could
         * race this setenv with getenv; must precede backend start */
        setenv("JAX_PLATFORMS", platform, 1);
    }
    if (!py_was_up) {
        Py_InitializeEx(0);
        /* release the GIL acquired by initialization so slate_* can be
         * called from ANY thread (each call re-acquires via
         * PyGILState_Ensure; without this, a second thread deadlocks) */
        (void)PyEval_SaveThread();
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject* mod = PyImport_ImportModule("slate_tpu.c_api.bridge");
    int rc = 0;
    if (mod == NULL) {
        PyErr_Print();
        PyErr_Clear();
        rc = -100;
    } else {
        g_bridge = mod;  /* hold the reference forever */
        if (py_was_up && platform != NULL) {
            /* Python predates us: env mutation would race host
             * threads' getenv, so hand the platform to the bridge,
             * which applies it at first framework use */
            PyObject* res = PyObject_CallMethod(mod, "set_platform",
                                                "s", platform);
            if (res == NULL) {
                PyErr_Clear();
                rc = -102;  /* distinct: platform could not be applied */
            }
            Py_XDECREF(res);
        }
    }
    PyGILState_Release(st);
    pthread_mutex_unlock(&g_init_lock);
    return rc;
}

int slate_tpu_init(const char* platform) {
    return ensure_init(platform);
}

/* Call bridge.<name>(args...) -> int info. fmt describes the argument
 * tuple; buffer addresses travel as unsigned long long ("K"). */
static int bridge_call(const char* name, const char* fmt, ...) {
    int rc = ensure_init(NULL);
    if (rc != 0) return rc;
    PyGILState_STATE st = PyGILState_Ensure();
    va_list ap;
    va_start(ap, fmt);
    PyObject* args = Py_VaBuildValue(fmt, ap);
    va_end(ap);
    int info = -101;
    if (args != NULL) {
        PyObject* fn = PyObject_GetAttrString(g_bridge, name);
        if (fn != NULL) {
            PyObject* res = PyObject_CallObject(fn, args);
            Py_DECREF(fn);
            if (res != NULL) {
                info = (int)PyLong_AsLong(res);
                Py_DECREF(res);
            } else {
                PyErr_Print();
                info = -102;
            }
        }
        Py_DECREF(args);
    }
    /* never leave a pending exception behind: the next bridge_call
     * would otherwise violate the CPython calling contract */
    if (PyErr_Occurred()) {
        PyErr_Print();
        PyErr_Clear();
    }
    PyGILState_Release(st);
    return info;
}

int slate_potrf(char dtype, int64_t n, void* a, int64_t lda) {
    return bridge_call("potrf", "(CLKL)", dtype, (long long)n,
                       (unsigned long long)(uintptr_t)a, (long long)lda);
}

int slate_gesv(char dtype, int64_t n, int64_t nrhs, void* a,
               int64_t lda, int32_t* ipiv, void* b, int64_t ldb) {
    return bridge_call("gesv", "(CLLKLKKL)", dtype, (long long)n,
                       (long long)nrhs,
                       (unsigned long long)(uintptr_t)a, (long long)lda,
                       (unsigned long long)(uintptr_t)ipiv,
                       (unsigned long long)(uintptr_t)b, (long long)ldb);
}

int slate_posv(char dtype, int64_t n, int64_t nrhs, void* a,
               int64_t lda, void* b, int64_t ldb) {
    return bridge_call("posv", "(CLLKLKL)", dtype, (long long)n,
                       (long long)nrhs,
                       (unsigned long long)(uintptr_t)a, (long long)lda,
                       (unsigned long long)(uintptr_t)b, (long long)ldb);
}

int slate_gemm(char dtype, int64_t m, int64_t n, int64_t k,
               double alpha, const void* a, int64_t lda,
               const void* b, int64_t ldb,
               double beta, void* c, int64_t ldc) {
    return bridge_call("gemm", "(CLLLdKLKLdKL)", dtype, (long long)m,
                       (long long)n, (long long)k, alpha,
                       (unsigned long long)(uintptr_t)a, (long long)lda,
                       (unsigned long long)(uintptr_t)b, (long long)ldb,
                       beta,
                       (unsigned long long)(uintptr_t)c, (long long)ldc);
}

int slate_gels(char dtype, int64_t m, int64_t n, int64_t nrhs,
               void* a, int64_t lda, void* b, int64_t ldb) {
    return bridge_call("gels", "(CLLLKLKL)", dtype, (long long)m,
                       (long long)n, (long long)nrhs,
                       (unsigned long long)(uintptr_t)a, (long long)lda,
                       (unsigned long long)(uintptr_t)b, (long long)ldb);
}

int slate_heev(char dtype, int64_t n, void* a, int64_t lda, void* w) {
    return bridge_call("heev", "(CLKLK)", dtype, (long long)n,
                       (unsigned long long)(uintptr_t)a, (long long)lda,
                       (unsigned long long)(uintptr_t)w);
}

int slate_svd_vals(char dtype, int64_t m, int64_t n, void* a,
                   int64_t lda, void* s) {
    return bridge_call("svd_vals", "(CLLKLK)", dtype, (long long)m,
                       (long long)n,
                       (unsigned long long)(uintptr_t)a, (long long)lda,
                       (unsigned long long)(uintptr_t)s);
}
