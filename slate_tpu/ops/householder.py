"""Shared Householder reflector construction (lapack larfg semantics).

Single source of truth for the degenerate-case handling (zero columns,
zero beta, complex sign) used by the QR panel (linalg/qr.py), the
Hermitian tridiagonalization (linalg/eig.py) and the Golub-Kahan
bidiagonalization (linalg/svd.py) — the reference similarly centralizes
this in its Tile panel kernels (src/internal/Tile_geqrf.hh).
"""

from __future__ import annotations

import jax.numpy as jnp


def reflect(x, idx, pivot_pos):
    """Householder (v, tau, beta) with H = I - tau v v^H mapping x to
    beta * e_pivot, zeroing entries idx > pivot_pos; entries of x at
    idx < pivot_pos are ignored (assumed already eliminated).

    Degenerate cases: if the sub-pivot part of x is zero (and, for
    complex, the pivot is real), tau = 0, v = 0 and beta = x[pivot]
    (identity reflector), matching lapack larfg."""
    alpha = jnp.sum(jnp.where(idx == pivot_pos, x, 0))
    below = idx > pivot_pos
    xnorm2 = jnp.sum(jnp.where(below, jnp.abs(x) ** 2, 0))
    anorm = jnp.sqrt(jnp.abs(alpha) ** 2 + xnorm2)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(alpha)
        sign = jnp.where(mag == 0, jnp.ones((), x.dtype), alpha / mag)
        trivial = (xnorm2 == 0) & (jnp.imag(alpha) == 0)
    else:
        sign = jnp.where(alpha >= 0, 1.0, -1.0).astype(x.dtype)
        trivial = xnorm2 == 0
    beta = -sign * anorm.astype(x.dtype)
    denom = alpha - beta
    safe = jnp.where(denom == 0, jnp.ones((), x.dtype), denom)
    v = jnp.where(below, x / safe, 0)
    v = v.at[pivot_pos].set(jnp.where(trivial, 0.0, 1.0))
    tau = jnp.where(trivial, jnp.zeros((), x.dtype),
                    (beta - alpha) / jnp.where(beta == 0,
                                               jnp.ones((), x.dtype),
                                               beta))
    beta = jnp.where(trivial, alpha, beta)
    return v, tau, beta
