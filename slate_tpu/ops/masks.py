"""Mask helpers for ragged-edge and structured operations.

The reference's device kernels (src/cuda/device_util.cuh +
device_{geadd,genorm,...}.cu) handle ragged last tiles and uplo triangles
with per-thread bounds checks; here the same discipline is iota-comparison
masks over the padded dense array, which XLA fuses into the consuming op.
"""

from __future__ import annotations

import jax.numpy as jnp


def bounds_mask(shape, m: int, n: int):
    """True inside the logical [:m, :n] region of a padded array."""
    ii = jnp.arange(shape[0])[:, None]
    jj = jnp.arange(shape[1])[None, :]
    return (ii < m) & (jj < n)


def tri_mask(shape, lower: bool, strict: bool = False):
    """True on the kept triangle (including diagonal unless strict)."""
    ii = jnp.arange(shape[0])[:, None]
    jj = jnp.arange(shape[1])[None, :]
    if lower:
        return ii > jj if strict else ii >= jj
    return ii < jj if strict else ii <= jj


def band_mask(shape, kl: int, ku: int):
    ii = jnp.arange(shape[0])[:, None]
    jj = jnp.arange(shape[1])[None, :]
    return (jj - ii <= ku) & (ii - jj <= kl)
