"""Batched tile operations (reference src/cuda device kernels, SURVEY
§2.2: geadd, gecopy, genorm, gescale, gescale_row_col, geset, henorm,
synorm, transpose, trnorm, tzadd, tzcopy, tzscale, tzset —
src/cuda/*.cu, 5103 LoC).

TPU-native design: each kernel is a masked dense op over the padded
storage; XLA fuses mask + elementwise + reduction into single HBM passes,
which is exactly what the hand-written CUDA kernels achieve. The
batched-over-tiles structure of the reference collapses into one 2D op.
All functions are functional (return new TiledMatrix) and jit-safe.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..core.enums import MatrixType, Norm, NormScope, Uplo
from ..core.tiles import TiledMatrix
from .masks import bounds_mask, tri_mask


def _replace_data(A: TiledMatrix, data) -> TiledMatrix:
    return dataclasses.replace(A, data=data)


# -- elementwise set/copy/scale/add (ge* = general, tz* = trapezoid) ------

def geset(A: TiledMatrix, offdiag_value, diag_value) -> TiledMatrix:
    """Reference device_geset.cu / slate::set (slate.hh:121)."""
    r = A.resolve()
    shape = r.data.shape
    ii = jnp.arange(shape[0])[:, None]
    jj = jnp.arange(shape[1])[None, :]
    vals = jnp.where(ii == jj, jnp.asarray(diag_value, r.dtype),
                     jnp.asarray(offdiag_value, r.dtype))
    data = jnp.where(bounds_mask(shape, r.m, r.n), vals,
                     jnp.zeros((), r.dtype))
    return _replace_data(r, data)


def tzset(A: TiledMatrix, offdiag_value, diag_value) -> TiledMatrix:
    """Set only the stored triangle (reference device_tzset.cu)."""
    r = A.resolve()
    shape = r.data.shape
    keep = tri_mask(shape, r.uplo is Uplo.Lower)
    full = geset(r, offdiag_value, diag_value)
    data = jnp.where(keep & bounds_mask(shape, r.m, r.n), full.data, r.data)
    return _replace_data(r, data)


def geadd(alpha, A: TiledMatrix, beta, B: TiledMatrix) -> TiledMatrix:
    """B := alpha*A + beta*B (reference device_geadd.cu, slate::add).
    A and B must conform logically; tile sizes may differ."""
    ra, rb = A.resolve(), B.resolve()
    mp, np_ = rb.data.shape
    a = jnp.pad(ra.data[:ra.m, :ra.n].astype(rb.dtype),
                ((0, mp - ra.m), (0, np_ - ra.n)))
    data = jnp.asarray(alpha, rb.dtype) * a \
        + jnp.asarray(beta, rb.dtype) * rb.data
    return _replace_data(rb, data)


def tzadd(alpha, A: TiledMatrix, beta, B: TiledMatrix) -> TiledMatrix:
    """Trapezoid add on the stored triangle (device_tzadd.cu)."""
    rb = B.resolve()
    full = geadd(alpha, A, beta, rb)
    keep = tri_mask(rb.data.shape, rb.uplo is Uplo.Lower)
    return _replace_data(rb, jnp.where(keep, full.data, rb.data))


def gecopy(A: TiledMatrix, B: TiledMatrix) -> TiledMatrix:
    """Copy A into B's storage incl. dtype conversion (device_gecopy.cu,
    slate::copy slate.hh:62)."""
    ra, rb = A.resolve(), B.resolve()
    mp, np_ = rb.data.shape
    data = jnp.pad(ra.data[:ra.m, :ra.n].astype(rb.dtype),
                   ((0, mp - ra.m), (0, np_ - ra.n)))
    return _replace_data(rb, data)


def tzcopy(A: TiledMatrix, B: TiledMatrix) -> TiledMatrix:
    rb = B.resolve()
    full = gecopy(A, rb)
    keep = tri_mask(rb.data.shape, rb.uplo is Uplo.Lower)
    return _replace_data(rb, jnp.where(keep, full.data, rb.data))


def gescale(numer, denom, A: TiledMatrix) -> TiledMatrix:
    """A *= numer/denom (device_gescale.cu, slate::scale slate.hh:71)."""
    r = A.resolve()
    s = jnp.asarray(numer, r.dtype) / jnp.asarray(denom, r.dtype)
    return _replace_data(r, r.data * s)


def tzscale(numer, denom, A: TiledMatrix) -> TiledMatrix:
    r = A.resolve()
    keep = tri_mask(r.data.shape, r.uplo is Uplo.Lower)
    s = jnp.asarray(numer, r.dtype) / jnp.asarray(denom, r.dtype)
    return _replace_data(r, jnp.where(keep, r.data * s, r.data))


def gescale_row_col(R, C, A: TiledMatrix) -> TiledMatrix:
    """A := diag(R) A diag(C) (device_gescale_row_col.cu,
    slate::scale_row_col slate.hh:111). R: (m,), C: (n,)."""
    r = A.resolve()
    mp, np_ = r.data.shape
    R = jnp.pad(jnp.asarray(R, r.dtype), (0, mp - r.m))
    C = jnp.pad(jnp.asarray(C, r.dtype), (0, np_ - r.n))
    return _replace_data(r, r.data * R[:, None] * C[None, :])


def transpose_tiles(A: TiledMatrix) -> TiledMatrix:
    """Physical transpose (reference device_transpose.cu — in-place batched
    tile transpose). XLA handles layout; exposed for parity."""
    return A.transpose().resolve()


# -- norms ----------------------------------------------------------------

def _abs2(x):
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        return jnp.real(x) ** 2 + jnp.imag(x) ** 2
    return x * x


def _norm_of_dense(a, norm: Norm):
    ax = jnp.abs(a)
    if norm is Norm.Max:
        return ax.max(initial=0.0)
    if norm is Norm.One:
        return ax.sum(axis=0).max(initial=0.0)
    if norm is Norm.Inf:
        return ax.sum(axis=1).max(initial=0.0)
    if norm is Norm.Fro:
        return jnp.sqrt(_abs2(a).sum())
    raise ValueError(norm)


def matrix_norm(A: TiledMatrix, norm: Norm,
                scope: NormScope = NormScope.Matrix):
    """Reference genorm/henorm/synorm/trnorm device kernels + slate::norm
    (slate.hh:462-471). Structure is honored via the logical matrix; XLA
    fuses the mirror/mask into the reduction so symmetric types still do
    one HBM pass over the stored triangle's dense image."""
    a = A.to_dense()
    real_dtype = jnp.real(jnp.zeros((), a.dtype)).dtype
    if scope in (NormScope.Columns, NormScope.Rows):
        axis = 0 if scope is NormScope.Columns else 1
        if norm is Norm.Max:
            v = jnp.abs(a).max(axis=axis, initial=0.0)
        elif norm is Norm.Fro:
            v = jnp.sqrt(_abs2(a).sum(axis=axis))
        else:  # One/Inf per-vector norms are both abs-sums
            v = jnp.abs(a).sum(axis=axis)
        return v.astype(real_dtype)
    return _norm_of_dense(a, norm).astype(real_dtype)


def col_norms(A: TiledMatrix):
    """Reference slate::colNorms (slate.hh:484) — max-abs per column."""
    a = A.to_dense()
    real_dtype = jnp.real(jnp.zeros((), a.dtype)).dtype
    return jnp.abs(a).max(axis=0, initial=0.0).astype(real_dtype)
