from . import masks, tile_ops
from .tile_ops import (col_norms, geadd, gecopy, gescale, gescale_row_col,
                       geset, matrix_norm, transpose_tiles, tzadd, tzcopy,
                       tzscale, tzset)
