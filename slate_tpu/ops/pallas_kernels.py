"""Pallas TPU kernels for structure-aware hot ops.

The reference's device layer (src/cuda/*.cu) exists because vendor BLAS
can't exploit tile structure; the same motivation here:

- ``syrk_lower_update``: the Cholesky trailing update C[lower] -= A A^H
  only ever needs the lower-triangle tiles, but XLA's matmul computes
  the full rectangle. A packed 1D grid over exactly the nt(nt+1)/2
  lower tiles (PrefetchScalarGridSpec: tile coordinate lists are
  scalar-prefetched and drive the BlockSpec index maps) halves MXU work
  and HBM traffic.
- ``chol_panel``: XLA's Cholesky lowers to a multi-dispatch expander
  loop (milliseconds for a 512 block on this chip); the fused kernel
  keeps the panel resident in VMEM and runs a left-looking blocked
  recurrence in one dispatch — the analogue of the reference's
  single-tile lapack::potrf on the device queue (potrf.cc:96).

Float32/bfloat16 only (the TPU backend has no complex support); callers
fall back to the dense jnp path otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:      # pragma: no cover - no backend at all
        return False


def pallas_available(dtype) -> bool:
    return _on_tpu() and jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16)


# -- packed lower-triangle rank-k update ---------------------------------

@functools.partial(jax.jit, static_argnames=("tile",))
def _syrk_lower_pallas(c: jax.Array, a: jax.Array, tile: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = c.shape[0]
    k = a.shape[1]
    nt = n // tile
    ii, jj = np.tril_indices(nt)
    ii = jnp.asarray(ii, jnp.int32)
    jj = jnp.asarray(jj, jnp.int32)

    def kernel(ii_ref, jj_ref, ai_ref, aj_ref, cin_ref, cout_ref):
        prod = jax.lax.dot_general(
            ai_ref[:], aj_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
        cout_ref[:] = cin_ref[:] - prod.astype(cout_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(ii.shape[0],),
        in_specs=[
            pl.BlockSpec((tile, k), lambda t, ii, jj: (ii[t], 0)),
            pl.BlockSpec((tile, k), lambda t, ii, jj: (jj[t], 0)),
            pl.BlockSpec((tile, tile), lambda t, ii, jj: (ii[t], jj[t])),
        ],
        out_specs=pl.BlockSpec((tile, tile),
                               lambda t, ii, jj: (ii[t], jj[t])),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(c.shape, c.dtype),
        # c is tensor input index 4 (scalar-prefetch args count);
        # aliasing makes the update in-place so unvisited upper-triangle
        # blocks keep their input values
        input_output_aliases={4: 0},
    )(ii, jj, a, a, c)


def syrk_lower_update(c: jax.Array, a: jax.Array,
                      precision=jax.lax.Precision.HIGHEST) -> jax.Array:
    """C := C - A A^H, writing ONLY the lower-triangle tiles of C.
    C: (n, n), A: (n, k). Upper-triangle tiles of the result must be
    treated as unspecified by callers (the Cholesky trailing matrix is
    only ever read on its lower triangle).

    Reference analogue: internal::herk Devices path (internal_herk.cc)
    which likewise batches only stored-triangle tiles."""
    n = c.shape[0]
    tile = 256 if n % 256 == 0 else (128 if n % 128 == 0 else None)
    if tile is not None and n // tile >= 2 and pallas_available(c.dtype) \
            and c.dtype == a.dtype:
        return _syrk_lower_pallas(c, a, tile)
    upd = jnp.matmul(a, jnp.conj(a.T), precision=precision)
    return c - upd


# -- fused in-VMEM Cholesky panel kernel ---------------------------------

_CHOL_BLK = 128

#: largest panel kept fully in VMEM (f32: 4 MB at 1024)
CHOL_FUSED_MAX = 1024


@functools.partial(jax.jit, static_argnames=("n",))
def _chol_fused_pallas(a: jax.Array, n: int):
    from jax.experimental import pallas as pl

    blk = min(_CHOL_BLK, n)
    nblk = n // blk

    def kernel(a_ref, out_ref):
        # all intermediates kept rank-2 (Mosaic layouts for 1D vectors
        # are fragile); rows_c is an (n,1) column, colsl_r a (1,blk) row
        rows_c = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)
        colsl_r = jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
        out_ref[:] = a_ref[:]

        def stripe(kb, _):
            k0 = kb * blk
            S = out_ref[:, pl.ds(k0, blk)]                  # (n, blk)
            # left-looking update: S -= L[:, :k0] @ L[k0:k1, :k0]^T via
            # full-width masked matmul (masks stand in for the
            # dynamic-width slice, which Mosaic cannot express)
            colmask = (jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
                       < k0)
            Lm = jnp.where(colmask, out_ref[:], 0.0)
            G = out_ref[pl.ds(k0, blk), :]                  # (blk, n)
            gmask = (jax.lax.broadcasted_iota(jnp.int32, (blk, n), 1)
                     < k0)
            G = jnp.where(gmask, G, 0.0)
            S = S - jax.lax.dot_general(
                Lm, G, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST).astype(S.dtype)

            # projT[r, c] == (r == k0 + c): row-extraction mask standing
            # in for a value dynamic_slice (unsupported in Mosaic)
            projT = (jax.lax.broadcasted_iota(jnp.int32, (n, blk), 0)
                     == jax.lax.broadcasted_iota(jnp.int32, (n, blk), 1)
                     + k0)

            def col(jj, S):
                j = k0 + jj
                sel = colsl_r == jj                          # (1, blk)
                colv = jnp.sum(jnp.where(sel, S, 0.0), axis=1,
                               keepdims=True)               # (n, 1)
                piv = jnp.sum(jnp.where(rows_c == j, colv, 0.0))
                d = jnp.sqrt(piv)
                dsafe = jnp.where(d == 0, 1.0, d).astype(S.dtype)
                v = jnp.where(rows_c > j, colv / dsafe,
                              0.0).astype(S.dtype)          # (n, 1)
                newcol = v + jnp.where(rows_c == j, d,
                                       0.0).astype(S.dtype)
                S = jnp.where(sel, newcol, S)
                vrow = jnp.sum(jnp.where(projT, v, 0.0), axis=0,
                               keepdims=True)               # (1, blk)
                S = S - (v * jnp.where(colsl_r > jj, vrow, 0.0)
                         ).astype(S.dtype)
                return S

            S = jax.lax.fori_loop(0, blk, col, S)
            out_ref[:, pl.ds(k0, blk)] = S
            return 0

        jax.lax.fori_loop(0, nblk, stripe, 0)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
    )(a)


def chol_panel(a: jax.Array) -> jax.Array:
    """Lower Cholesky of an SPD block; fused Pallas kernel on TPU for
    f32 blocks up to CHOL_FUSED_MAX, else XLA's cholesky. Upper triangle
    of the result is unspecified (callers mask), matching LAPACK."""
    n = a.shape[0]
    if pallas_available(a.dtype) and a.dtype == jnp.float32 \
            and n <= CHOL_FUSED_MAX and n % _CHOL_BLK == 0:
        return _chol_fused_pallas(a, n)
    return jax.lax.linalg.cholesky(a)
