"""Pallas TPU kernels for structure-aware hot ops.

ROLE CHANGE (round 3, PERF.md): on the current libtpu, XLA's native
cholesky / TriangularSolve / geqrf / LU beat these fused kernels at
every measured size (e.g. chol 512: 95 vs 341 µs; trtri 512: 35 vs
334 µs; lu panel 4096x256: 774 vs 1187 µs), so the hot paths route to
the natives. The kernels remain as (a) the panel path for dtypes the
native custom calls cannot take (bf16 — the mixed-precision lo
factor), and (b) the measured comparison points `bench.py --micro`
regenerates. The round-1/2 rationale ("TriangularSolve is a
latency-bound ~2 ms expander") no longer holds on this libtpu.

The reference's device layer (src/cuda/*.cu) exists because vendor BLAS
can't exploit tile structure; here the structure-critical, latency-bound
pieces are fused into single VMEM-resident dispatches:

- ``chol_panel``: Cholesky of one diagonal block, left-looking blocked
  recurrence in one dispatch — the analogue of the reference's
  single-tile lapack::potrf on the device queue (potrf.cc:96).
- ``trtri_lower``: triangular block inversion by in-VMEM forward
  substitution (bench comparison only since round 3).
- ``qr_panel``: Householder panel (larfg + rank-1 updates per column)
  in one dispatch — the reference's internal::geqrf device panel
  (geqrf.cc:153); bf16 fallback since round 3.

A packed lower-triangle-tile syrk kernel (PrefetchScalarGridSpec over
the nt(nt+1)/2 stored tiles, mirroring internal_herk.cc) was built and
REMOVED: measured on v5e it loses to the plain dense matmul
(linalg/blocked.py module docstring has the numbers).

Float32/bfloat16 only (the TPU backend has no complex support); callers
fall back to XLA paths otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:      # pragma: no cover - no backend at all
        return False


def pallas_available(dtype) -> bool:
    return _on_tpu() and jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16)


# -- fused in-VMEM Householder QR panel kernel ---------------------------

#: widest panel factored in one VMEM-resident kernel
QR_PANEL_MAX_W = 128
#: tallest panel (f32: 4096 x 128 = 2 MB in VMEM)
QR_PANEL_MAX_M = 8192


@functools.partial(jax.jit, static_argnames=("m", "w"))
def _qr_panel_pallas(a: jax.Array, m: int, w: int):
    """Householder QR of an (m, w) panel in one dispatch: w sequential
    reflections, each a column norm + rank-1 update on the VMEM-resident
    panel. Output: packed V-below-diagonal/R-on-upper plus taus (1, w).
    LAPACK larfg conventions (beta = -sign(alpha)|x|, v0 = 1 implicit).

    Reference analogue: internal::geqrf's device-capable panel kernel
    (geqrf.cc:153, Tile_geqrf.hh) — the latency-critical inner loop the
    reference runs on a dedicated thread team."""
    from jax.experimental import pallas as pl

    def kernel(a_ref, out_ref, tau_ref):
        rows_c = jax.lax.broadcasted_iota(jnp.int32, (m, 1), 0)
        cols_r = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
        out_ref[:] = a_ref[:]
        tau_ref[:] = jnp.zeros((1, w), jnp.float32)

        def step(j, _):
            colsel = cols_r == j                            # (1, w)
            # scalar recurrence in f32: Mosaic cannot squeeze bf16
            # scalars, and the reflection scalars need the headroom
            x = jnp.sum(jnp.where(colsel, out_ref[:], 0.0),
                        axis=1, keepdims=True).astype(jnp.float32)
            x = jnp.where(rows_c >= j, x, 0.0)
            alpha = jnp.sum(jnp.where(rows_c == j, x, 0.0))
            nrm2 = jnp.sum(x * x)
            nrm = jnp.sqrt(nrm2)
            sign = jnp.where(alpha >= 0, 1.0, -1.0)
            beta = -sign * nrm
            # tau = (beta - alpha) / beta; zero column -> tau = 0
            degenerate = nrm2 <= 0.0
            safe_beta = jnp.where(degenerate, 1.0, beta)
            tau = jnp.where(degenerate, 0.0,
                            (beta - alpha) / safe_beta)
            # v = x / (alpha - beta) below row j, v_j = 1
            denom = alpha - safe_beta
            denom = jnp.where(denom == 0, 1.0, denom)
            v = jnp.where(rows_c > j, x / denom, 0.0)
            v = v + jnp.where(rows_c == j, 1.0, 0.0)
            # apply H = I - tau v v^T to columns > j (operands cast to
            # f32: Mosaic rejects bf16 contractions on the sublane dim)
            vta = jax.lax.dot_general(
                v, out_ref[:].astype(jnp.float32),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)        # (1, w)
            upd = (tau * v) * jnp.where(cols_r > j, vta, 0.0)
            newpan = out_ref[:] - upd.astype(out_ref.dtype)
            # write packed column j: beta on the diagonal, v below
            newcol = jnp.where(rows_c > j, v, 0.0) \
                + jnp.where(rows_c == j, beta, 0.0)
            keep = jnp.where(rows_c < j,
                             jnp.sum(jnp.where(colsel, newpan, 0.0),
                                     axis=1,
                                     keepdims=True).astype(jnp.float32),
                             newcol)
            out_ref[:] = jnp.where(colsel, keep.astype(out_ref.dtype),
                                   newpan)
            tau_ref[:] = jnp.where(colsel, tau, tau_ref[:])
            return 0

        jax.lax.fori_loop(0, w, step, 0)

    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((m, w), a.dtype),
                   jax.ShapeDtypeStruct((1, w), jnp.float32)),
    )(a)


def qr_panel(a: jax.Array):
    """(packed, taus) Householder panel factorization; fused Pallas
    kernel for f32/bf16 TPU panels (bf16 = the mixed-precision lo
    path, which XLA's native geqrf custom call cannot take; scalar
    recurrence runs in f32 in-kernel), else None (caller falls back
    to the masked fori_loop panel)."""
    m, w = a.shape
    if pallas_available(a.dtype) \
            and w <= QR_PANEL_MAX_W and m <= QR_PANEL_MAX_M \
            and m % 128 == 0 and w % 8 == 0:
        packed, taus = _qr_panel_pallas(a, m, w)
        return packed, taus[0].astype(a.dtype)
    return None


# -- fused in-VMEM partial-pivot LU panel kernel -------------------------

#: widest LU panel factored in one VMEM-resident kernel
LU_PANEL_MAX_W = 256
#: tallest LU panel (f32: 8192 x 256 = 8 MB in VMEM)
LU_PANEL_MAX_M = 8192


@functools.partial(jax.jit, static_argnames=("m", "w"))
def _lu_panel_pallas(a: jax.Array, m: int, w: int):
    """Partial-pivot LU of an (m, w) panel in one dispatch: w sequential
    steps of column-max pivot search, two-row swap, scale, rank-1
    update, all on the VMEM-resident panel. Returns (packed LU, local
    pivot row indices (1, w) as f32 — exact for m < 2^24).

    Reference analogue: the host-threaded panel with per-column maxloc
    reduction (Tile_getrf.hh:162-320, internal_getrf.cc thread team) —
    here the 'thread team' is the VPU operating on the whole panel."""
    from jax.experimental import pallas as pl

    def kernel(a_ref, out_ref, piv_ref):
        rows_c = jax.lax.broadcasted_iota(jnp.int32, (m, 1), 0)
        cols_r = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
        out_ref[:] = a_ref[:]
        piv_ref[:] = jnp.zeros((1, w), jnp.float32)

        def step(j, _):
            colsel = cols_r == j                            # (1, w)
            # pivot search in f32: Mosaic cannot squeeze bf16 scalars,
            # and f32 keeps the row index exact for m < 2^24 (bf16
            # would corrupt indices past 256)
            col = jnp.sum(jnp.where(colsel, out_ref[:], 0.0),
                          axis=1, keepdims=True).astype(jnp.float32)
            mag = jnp.where(rows_c >= j, jnp.abs(col), -1.0)
            mx = jnp.max(mag)
            p = jnp.min(jnp.where(mag == mx, rows_c, m))    # first max
            piv_ref[:] = jnp.where(colsel, p.astype(jnp.float32),
                                   piv_ref[:])
            # swap rows j <-> p
            rowj = jnp.sum(jnp.where(rows_c == j, out_ref[:], 0.0),
                           axis=0, keepdims=True)           # (1, w)
            rowp = jnp.sum(jnp.where(rows_c == p, out_ref[:], 0.0),
                           axis=0, keepdims=True)
            pan = out_ref[:]
            pan = jnp.where(rows_c == j, rowp,
                            jnp.where(rows_c == p, rowj, pan))
            # scale multipliers and rank-1 update of columns > j
            # (scalar division in f32, data ops in the panel dtype)
            pivval = jnp.sum(jnp.where(colsel, rowp,
                                       0.0)).astype(jnp.float32)
            safe = jnp.where(pivval == 0, 1.0, pivval)
            col2 = jnp.sum(jnp.where(colsel, pan, 0.0), axis=1,
                           keepdims=True)                   # (m, 1)
            mults = jnp.where(rows_c > j,
                              col2.astype(jnp.float32) / safe,
                              0.0).astype(pan.dtype)        # (m, 1)
            urow = jnp.where(cols_r > j, rowp, 0.0)          # (1, w)
            pan = pan - mults * urow
            # write the multiplier column (rows > j keep mults)
            newcol = jnp.where(rows_c > j, mults, col2)
            pan = jnp.where(colsel, newcol, pan)
            out_ref[:] = pan.astype(out_ref.dtype)
            return 0

        jax.lax.fori_loop(0, w, step, 0)

    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((m, w), a.dtype),
                   jax.ShapeDtypeStruct((1, w), jnp.float32)),
    )(a)


def lu_panel_eligible(m: int, w: int, dtype) -> bool:
    """True iff an (m, w) panel of this dtype will run as one fused
    kernel — shared by lu_panel and the driver's panel-width policy.
    f32 AND bf16 (the mixed-precision lo factor, which XLA's native
    LU custom call cannot take — the reason the kernel is retained,
    PERF.md).

    The height cap scales PROPORTIONALLY TO ITEMSIZE for sub-f32
    panels (bf16 halves it; a 1-byte dtype would quarter it): the
    kernel's pivot search and scaling run in f32 (Mosaic cannot
    squeeze bf16 scalars), so a narrower panel dtype buys vmem only
    on the panel itself, not the f32 temporaries — measured
    on v5e: bf16 8192x256 dies in compile at 20.24M of scoped-vmem
    stack vs the 16M limit, bf16 4096x256 and f32 4096x256 both
    compile and run (PERF.md round-3 sweep)."""
    max_m = LU_PANEL_MAX_M * min(jnp.dtype(dtype).itemsize, 4) // 4
    return (pallas_available(dtype)
            and w <= LU_PANEL_MAX_W and m <= max_m
            and m % 128 == 0 and w % 8 == 0)


def lu_panel(a: jax.Array):
    """(packed, piv int32) partial-pivot LU panel; fused Pallas kernel
    for f32/bf16 TPU panels, else None (caller falls back to the
    masked fori_loop panel)."""
    m, w = a.shape
    if lu_panel_eligible(m, w, a.dtype):
        packed, piv = _lu_panel_pallas(a, m, w)
        return packed, piv[0].astype(jnp.int32)
    return None


# -- fused in-VMEM triangular inversion kernel ---------------------------

#: largest block inverted in one VMEM-resident kernel
TRTRI_FUSED_MAX = 512


@functools.partial(jax.jit, static_argnames=("n", "unit"))
def _trtri_lower_pallas(a: jax.Array, n: int, unit: bool):
    """inv(L) for lower-triangular (n, n) by forward substitution kept
    entirely in VMEM: one dispatch, n sequential row steps, each a
    (1, n) x (n, n) MXU product. Substitution-grade numerics (explicit
    Neumann/product forms overflow for unit-lower LU blocks).

    Reference analogue: the trsm diag-block inversion the reference does
    per-tile with lapack::trtri on the device queue (trsm variants via
    work_trsm.cc); upper inputs are handled by the caller via transpose.
    """
    from jax.experimental import pallas as pl

    def kernel(a_ref, out_ref):
        cols_r = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
        out_ref[:] = jnp.zeros((n, n), a_ref.dtype)

        def row(j, _):
            arow = a_ref[pl.ds(j, 1), :]                     # (1, n)
            lj = jnp.where(cols_r < j, arow, 0.0)
            prod = jax.lax.dot_general(
                lj, out_ref[:], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)         # (1, n)
            ej = jnp.where(cols_r == j, 1.0, 0.0).astype(a_ref.dtype)
            xj = ej - prod.astype(a_ref.dtype)
            if not unit:
                ljj = jnp.sum(jnp.where(cols_r == j, arow, 0.0))
                ljj = jnp.where(ljj == 0, 1.0, ljj).astype(a_ref.dtype)
                xj = xj / ljj
            out_ref[pl.ds(j, 1), :] = xj
            return 0

        jax.lax.fori_loop(0, n, row, 0)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
    )(a)


def trtri_lower(a: jax.Array, unit_diagonal: bool = False) -> jax.Array:
    """Lower-triangular inverse of one block: fused Pallas substitution
    on TPU for f32 blocks up to TRTRI_FUSED_MAX, else XLA
    triangular_solve (LAPACK-backed and fast on CPU; latency-bound on
    TPU, which is exactly why the fused kernel exists)."""
    n = a.shape[0]
    if pallas_available(a.dtype) and a.dtype == jnp.float32 \
            and n <= TRTRI_FUSED_MAX and n % 128 == 0:
        return _trtri_lower_pallas(a, n, unit_diagonal)
    return jax.lax.linalg.triangular_solve(
        a, jnp.eye(n, dtype=a.dtype), left_side=True, lower=True,
        unit_diagonal=unit_diagonal)


# -- fused in-VMEM Cholesky panel kernel ---------------------------------

_CHOL_BLK = 128

#: largest panel kept fully in VMEM (f32: 4 MB at 1024)
CHOL_FUSED_MAX = 1024


@functools.partial(jax.jit, static_argnames=("n",))
def _chol_fused_pallas(a: jax.Array, n: int):
    from jax.experimental import pallas as pl

    blk = min(_CHOL_BLK, n)
    nblk = n // blk

    def kernel(a_ref, out_ref):
        # all intermediates kept rank-2 (Mosaic layouts for 1D vectors
        # are fragile); rows_c is an (n,1) column, colsl_r a (1,blk) row
        rows_c = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)
        colsl_r = jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
        out_ref[:] = a_ref[:]

        def stripe(kb, _):
            k0 = kb * blk
            S = out_ref[:, pl.ds(k0, blk)]                  # (n, blk)
            # left-looking update: S -= L[:, :k0] @ L[k0:k1, :k0]^T via
            # full-width masked matmul (masks stand in for the
            # dynamic-width slice, which Mosaic cannot express)
            colmask = (jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
                       < k0)
            Lm = jnp.where(colmask, out_ref[:], 0.0)
            G = out_ref[pl.ds(k0, blk), :]                  # (blk, n)
            gmask = (jax.lax.broadcasted_iota(jnp.int32, (blk, n), 1)
                     < k0)
            G = jnp.where(gmask, G, 0.0)
            S = S - jax.lax.dot_general(
                Lm, G, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST).astype(S.dtype)

            # projT[r, c] == (r == k0 + c): row-extraction mask standing
            # in for a value dynamic_slice (unsupported in Mosaic)
            projT = (jax.lax.broadcasted_iota(jnp.int32, (n, blk), 0)
                     == jax.lax.broadcasted_iota(jnp.int32, (n, blk), 1)
                     + k0)

            def col(jj, S):
                j = k0 + jj
                sel = colsl_r == jj                          # (1, blk)
                colv = jnp.sum(jnp.where(sel, S, 0.0), axis=1,
                               keepdims=True)               # (n, 1)
                piv = jnp.sum(jnp.where(rows_c == j, colv, 0.0))
                d = jnp.sqrt(piv)
                dsafe = jnp.where(d == 0, 1.0, d).astype(S.dtype)
                v = jnp.where(rows_c > j, colv / dsafe,
                              0.0).astype(S.dtype)          # (n, 1)
                newcol = v + jnp.where(rows_c == j, d,
                                       0.0).astype(S.dtype)
                S = jnp.where(sel, newcol, S)
                vrow = jnp.sum(jnp.where(projT, v, 0.0), axis=0,
                               keepdims=True)               # (1, blk)
                S = S - (v * jnp.where(colsl_r > jj, vrow, 0.0)
                         ).astype(S.dtype)
                return S

            S = jax.lax.fori_loop(0, blk, col, S)
            out_ref[:, pl.ds(k0, blk)] = S
            return 0

        jax.lax.fori_loop(0, nblk, stripe, 0)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
    )(a)


def chol_panel(a: jax.Array) -> jax.Array:
    """Lower Cholesky of an SPD block; fused Pallas kernel on TPU for
    f32 blocks up to CHOL_FUSED_MAX, else XLA's cholesky. Upper triangle
    of the result is unspecified (callers mask), matching LAPACK."""
    n = a.shape[0]
    if pallas_available(a.dtype) and a.dtype == jnp.float32 \
            and n <= CHOL_FUSED_MAX and n % _CHOL_BLK == 0:
        return _chol_fused_pallas(a, n)
    # symmetrize_input=False: callers hand blocks whose upper triangle
    # may hold stale values (lower-only trailing updates); averaging it
    # in would corrupt the factor
    return jax.lax.linalg.cholesky(a, symmetrize_input=False)
