"""Pallas TPU kernels for structure-aware hot ops.

DESIGN (round 10): the panel path is **block-recursive**. The round-3
generation of these kernels did one rank-1 VPU update per column,
which loses to XLA's native LU panel for the same reason the native
loses to gemm — a latency-bound column recurrence (~4.6 vs 3.0 µs/col
at 4096x256, PERF.md Round-4 "LU panel wall"). ``lu_panel_rec``
factors an (m, w) panel by recursive halving (w -> w/2 -> ... -> ib):
every flop outside the innermost ib-wide base case lands in an
MXU-shaped rank-ib matmul, and only the base case runs the sequential
per-column recurrence (fused argmax + row-select partial pivoting,
done with masked whole-panel selects — Mosaic dynamic row ops
measured ~1 µs each in round 3, so dynamic indexing never appears).
Panels too tall for one VMEM-resident dispatch split at the JAX level
(same halving), with the trailing rank-w/2 update gridded over row
blocks — this is the path that factors panels the native LU custom
call cannot compile at all (methods.NATIVE_LU_MAX_M). The same
blocked-recurrence shape serves the steqr2/bdsqr bulge chase:
``givens_chain_apply`` materializes a sweep's rotation chain as
banded block factors ((2b, 2b) windows) and applies them as MXU
matmuls instead of composing one dense (n, n) rotation matrix.

ARBITRATION CONTRACT: every public kernel entry point here

  * has an eligibility gate (``*_eligible`` / ``*_reject_reason``) the
    routing layers consult, and returns ``None`` instead of computing
    when the gate rejects — the caller keeps its fallback;
  * has a registered tune-cache op (``KERNEL_REGISTRY`` maps entry ->
    (gate, tune op); tools/check_instrumented.py lints both), so the
    drivers' method arbitration (lu._lu_panel, eig.steqr2_qr,
    svd.bdsqr_qr) can route to it per (op, size, dtype) from a
    MEASURED cache entry — with the cache cold the drivers route
    exactly as they did before these kernels existed (native / fori /
    dense compose), so a losing kernel costs nothing;
  * runs under the Pallas interpreter on non-TPU backends
    (``pallas_interpret``), so tier-1 (JAX_PLATFORMS=cpu) exercises
    the kernel bodies instead of silently skipping them. Interpreted
    execution is for correctness coverage, not speed; the ROUTING
    gates (``pallas_available``-based) still require real TPU, so
    driver cold paths are identical on CPU.

Float32/bfloat16 only on hardware (the TPU backend has no complex
support; scalar recurrences run in f32 because Mosaic cannot squeeze
bf16 scalars); the interpreter additionally takes f64 where a kernel
has no f32-hardcoded recurrence (givens_chain_apply).

Retained round-3 kernels (``chol_panel``, ``trtri_lower``,
``qr_panel``, rank-1 ``lu_panel``): bench comparison points and the
bf16 fallbacks where the native custom calls end; see PERF.md.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:      # pragma: no cover - no backend at all
        return False


def pallas_available(dtype) -> bool:
    """ROUTING gate: the fused kernels run natively (real TPU and a
    dtype Mosaic takes). Drivers consult this (via the ``*_eligible``
    gates) before rerouting a hot path — interpret-mode execution
    never changes production routing."""
    return _on_tpu() and jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16)


def pallas_interpret() -> bool:
    """True when kernels invoked on a non-TPU backend run through the
    Pallas interpreter instead of returning None (ISSUE 6 satellite:
    tier-1 runs the kernel bodies). Default ON off-TPU; disable with
    SLATE_TPU_PALLAS_INTERPRET=0."""
    if _on_tpu():
        return False
    return os.environ.get("SLATE_TPU_PALLAS_INTERPRET", "1").lower() \
        not in ("0", "off", "false", "no")


def pallas_runnable(dtype) -> bool:
    """Entry-point gate: can a kernel EXECUTE at all — natively on
    TPU, or interpreted elsewhere. The one helper next to
    ``pallas_available`` that the public kernel entries share; routing
    keeps using ``pallas_available``."""
    if pallas_available(dtype):
        return True
    return pallas_interpret() \
        and jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16)


def _reject(kernel: str, reason: str, **args) -> None:
    """Publish one obs instant for a rejected kernel dispatch (ISSUE 6
    satellite: eligibility gates report WHY). No-op with obs off."""
    from ..obs import events as obs
    if obs.enabled():
        obs.instant("pallas.%s.reject" % kernel, cat="kernel",
                    reason=reason, **args)


#: public kernel entry point -> (eligibility gate, tune-cache op).
#: The arbitration contract (module doc): tools/check_instrumented.py
#: statically verifies every entry that dispatches a Pallas kernel is
#: listed here, references its gate, and that the tune op has a
#: FROZEN row (tune/cache.py) — a future kernel cannot ship without
#: arbitration.
KERNEL_REGISTRY = {
    "qr_panel": ("qr_panel_eligible", "qr_panel"),
    "lu_panel": ("lu_panel_eligible", "lu_panel"),
    "lu_panel_rec": ("lu_panel_rec_eligible", "lu_panel"),
    "trtri_lower": ("trtri_eligible", "trtri"),
    "chol_panel": ("chol_panel_eligible", "chol_panel"),
    "givens_chain_apply": ("givens_chain_eligible", "steqr2"),
    "ragged_potrf": ("ragged_potrf_eligible", "ragged"),
    "ragged_getrf": ("ragged_getrf_eligible", "ragged"),
    "ragged_trsm": ("ragged_trsm_eligible", "ragged"),
}


# -- fused in-VMEM Householder QR panel kernel ---------------------------

#: widest panel factored in one VMEM-resident kernel
QR_PANEL_MAX_W = 128
#: tallest panel (f32: 4096 x 128 = 2 MB in VMEM)
QR_PANEL_MAX_M = 8192


@functools.partial(jax.jit, static_argnames=("m", "w", "interp"))
def _qr_panel_pallas(a: jax.Array, m: int, w: int, interp: bool):
    """Householder QR of an (m, w) panel in one dispatch: w sequential
    reflections, each a column norm + rank-1 update on the VMEM-resident
    panel. Output: packed V-below-diagonal/R-on-upper plus taus (1, w).
    LAPACK larfg conventions (beta = -sign(alpha)|x|, v0 = 1 implicit).

    Reference analogue: internal::geqrf's device-capable panel kernel
    (geqrf.cc:153, Tile_geqrf.hh) — the latency-critical inner loop the
    reference runs on a dedicated thread team."""
    from jax.experimental import pallas as pl

    def kernel(a_ref, out_ref, tau_ref):
        rows_c = jax.lax.broadcasted_iota(jnp.int32, (m, 1), 0)
        cols_r = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
        out_ref[:] = a_ref[:]
        tau_ref[:] = jnp.zeros((1, w), jnp.float32)

        def step(j, _):
            colsel = cols_r == j                            # (1, w)
            # scalar recurrence in f32: Mosaic cannot squeeze bf16
            # scalars, and the reflection scalars need the headroom
            x = jnp.sum(jnp.where(colsel, out_ref[:], 0.0),
                        axis=1, keepdims=True).astype(jnp.float32)
            x = jnp.where(rows_c >= j, x, 0.0)
            alpha = jnp.sum(jnp.where(rows_c == j, x, 0.0))
            nrm2 = jnp.sum(x * x)
            nrm = jnp.sqrt(nrm2)
            sign = jnp.where(alpha >= 0, 1.0, -1.0)
            beta = -sign * nrm
            # tau = (beta - alpha) / beta; zero column -> tau = 0
            degenerate = nrm2 <= 0.0
            safe_beta = jnp.where(degenerate, 1.0, beta)
            tau = jnp.where(degenerate, 0.0,
                            (beta - alpha) / safe_beta)
            # v = x / (alpha - beta) below row j, v_j = 1
            denom = alpha - safe_beta
            denom = jnp.where(denom == 0, 1.0, denom)
            v = jnp.where(rows_c > j, x / denom, 0.0)
            v = v + jnp.where(rows_c == j, 1.0, 0.0)
            # apply H = I - tau v v^T to columns > j (operands cast to
            # f32: Mosaic rejects bf16 contractions on the sublane dim)
            vta = jax.lax.dot_general(
                v, out_ref[:].astype(jnp.float32),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)        # (1, w)
            upd = (tau * v) * jnp.where(cols_r > j, vta, 0.0)
            newpan = out_ref[:] - upd.astype(out_ref.dtype)
            # write packed column j: beta on the diagonal, v below
            newcol = jnp.where(rows_c > j, v, 0.0) \
                + jnp.where(rows_c == j, beta, 0.0)
            keep = jnp.where(rows_c < j,
                             jnp.sum(jnp.where(colsel, newpan, 0.0),
                                     axis=1,
                                     keepdims=True).astype(jnp.float32),
                             newcol)
            out_ref[:] = jnp.where(colsel, keep.astype(out_ref.dtype),
                                   newpan)
            tau_ref[:] = jnp.where(colsel, tau, tau_ref[:])
            return 0

        jax.lax.fori_loop(0, w, step, 0)

    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((m, w), a.dtype),
                   jax.ShapeDtypeStruct((1, w), jnp.float32)),
        interpret=interp,
    )(a)


def qr_panel_eligible(m: int, w: int, dtype) -> bool:
    """ROUTING gate for the fused QR panel (qr._qr_panel consults it
    before dispatching): f32/bf16 on real TPU — bf16 is the
    mixed-precision lo path, which XLA's native geqrf custom call
    cannot take — within the one-dispatch VMEM caps."""
    return pallas_available(dtype) and _qr_shape_ok(m, w)


def _qr_shape_ok(m: int, w: int) -> bool:
    from ..tune.select import tuned_int
    return w <= tuned_int("qr_panel", "max_w", QR_PANEL_MAX_W) \
        and m <= QR_PANEL_MAX_M and m % 128 == 0 and w % 8 == 0


def qr_panel(a: jax.Array):
    """(packed, taus) Householder panel factorization; fused Pallas
    kernel for eligible TPU panels (scalar recurrence runs in f32
    in-kernel) and interpreted off-TPU, else None (caller falls back
    to the masked fori_loop panel)."""
    m, w = a.shape
    if not (pallas_runnable(a.dtype) and _qr_shape_ok(m, w)):
        if not _qr_shape_ok(m, w):
            reason = "shape"
        elif jnp.dtype(a.dtype) not in (jnp.float32, jnp.bfloat16):
            reason = "dtype"
        else:
            reason = "platform"     # off-TPU with interpreter off
        _reject("qr_panel", reason, m=m, w=w, dtype=str(a.dtype))
        return None
    packed, taus = _qr_panel_pallas(a, m, w, pallas_interpret())
    return packed, taus[0].astype(a.dtype)


# -- fused in-VMEM partial-pivot LU panel kernel (rank-1, round 3) -------

#: widest LU panel factored in one VMEM-resident kernel
LU_PANEL_MAX_W = 256
#: tallest LU panel (f32: 8192 x 256 = 8 MB in VMEM)
LU_PANEL_MAX_M = 8192


@functools.partial(jax.jit, static_argnames=("m", "w", "interp"))
def _lu_panel_pallas(a: jax.Array, m: int, w: int, interp: bool):
    """Partial-pivot LU of an (m, w) panel in one dispatch: w sequential
    steps of column-max pivot search, two-row swap, scale, rank-1
    update, all on the VMEM-resident panel. Returns (packed LU, local
    pivot row indices (1, w) as f32 — exact for m < 2^24).

    This is the round-3 rank-1 kernel, kept as the bench comparison
    point and bf16 fallback; the production Pallas route is
    ``lu_panel_rec`` (module doc)."""
    from jax.experimental import pallas as pl

    def kernel(a_ref, out_ref, piv_ref):
        rows_c = jax.lax.broadcasted_iota(jnp.int32, (m, 1), 0)
        cols_r = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
        out_ref[:] = a_ref[:]
        piv_ref[:] = jnp.zeros((1, w), jnp.float32)

        def step(j, _):
            colsel = cols_r == j                            # (1, w)
            # pivot search in f32: Mosaic cannot squeeze bf16 scalars,
            # and f32 keeps the row index exact for m < 2^24 (bf16
            # would corrupt indices past 256)
            col = jnp.sum(jnp.where(colsel, out_ref[:], 0.0),
                          axis=1, keepdims=True).astype(jnp.float32)
            mag = jnp.where(rows_c >= j, jnp.abs(col), -1.0)
            mx = jnp.max(mag)
            p = jnp.min(jnp.where(mag == mx, rows_c, m))    # first max
            piv_ref[:] = jnp.where(colsel, p.astype(jnp.float32),
                                   piv_ref[:])
            # swap rows j <-> p
            rowj = jnp.sum(jnp.where(rows_c == j, out_ref[:], 0.0),
                           axis=0, keepdims=True)           # (1, w)
            rowp = jnp.sum(jnp.where(rows_c == p, out_ref[:], 0.0),
                           axis=0, keepdims=True)
            pan = out_ref[:]
            pan = jnp.where(rows_c == j, rowp,
                            jnp.where(rows_c == p, rowj, pan))
            # scale multipliers and rank-1 update of columns > j
            # (scalar division in f32, data ops in the panel dtype)
            pivval = jnp.sum(jnp.where(colsel, rowp,
                                       0.0)).astype(jnp.float32)
            safe = jnp.where(pivval == 0, 1.0, pivval)
            col2 = jnp.sum(jnp.where(colsel, pan, 0.0), axis=1,
                           keepdims=True)                   # (m, 1)
            mults = jnp.where(rows_c > j,
                              col2.astype(jnp.float32) / safe,
                              0.0).astype(pan.dtype)        # (m, 1)
            urow = jnp.where(cols_r > j, rowp, 0.0)          # (1, w)
            pan = pan - mults * urow
            # write the multiplier column (rows > j keep mults)
            newcol = jnp.where(rows_c > j, mults, col2)
            pan = jnp.where(colsel, newcol, pan)
            out_ref[:] = pan.astype(out_ref.dtype)
            return 0

        jax.lax.fori_loop(0, w, step, 0)

    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((m, w), a.dtype),
                   jax.ShapeDtypeStruct((1, w), jnp.float32)),
        interpret=interp,
    )(a)


def _lu_max_w() -> int:
    """The rank-1 kernel's width cap, arbitrated like every other
    kernel knob (tune key ("lu_panel", "max_w"), FROZEN default =
    the measured LU_PANEL_MAX_W) — a probe on wider-VMEM parts can
    raise it without a code change."""
    from ..tune.select import tuned_int
    return tuned_int("lu_panel", "max_w", LU_PANEL_MAX_W)


def _lu_shape_ok(m: int, w: int, dtype) -> bool:
    from ..core.methods import vmem_height_cap
    max_m = vmem_height_cap(LU_PANEL_MAX_M, dtype)
    return w <= _lu_max_w() and m <= max_m \
        and m % 128 == 0 and w % 8 == 0


def lu_panel_reject_reason(m: int, w: int, dtype) -> Optional[str]:
    """Why an (m, w) panel of this dtype will NOT run as one fused
    rank-1 kernel (None == eligible): 'platform' (no TPU), 'dtype'
    (not f32/bf16), 'width' (> LU_PANEL_MAX_W), 'height' (above the
    itemsize-scaled VMEM cap — bf16 halves it: the pivot search and
    scaling run in f32, so a narrower panel dtype buys vmem only on
    the panel itself, not the f32 temporaries; measured on v5e: bf16
    8192x256 dies in compile at 20.24M of scoped-vmem stack vs the
    16M limit, PERF.md round-3 sweep), or 'align' (m % 128 / w % 8).
    The ISSUE 6 satellite contract: gates report WHY, and lu_panel /
    getrf surface it via obs instants instead of a silent fori
    fallback."""
    from ..core.methods import vmem_height_cap
    if not _on_tpu():
        return "platform"
    if jnp.dtype(dtype) not in (jnp.float32, jnp.bfloat16):
        return "dtype"
    if w > _lu_max_w():
        return "width"
    if m > vmem_height_cap(LU_PANEL_MAX_M, dtype):
        return "height"
    if m % 128 != 0 or w % 8 != 0:
        return "align"
    return None


def lu_panel_eligible(m: int, w: int, dtype) -> bool:
    """True iff an (m, w) panel of this dtype will run as one fused
    rank-1 kernel on the TPU — the ROUTING gate shared by lu._lu_panel
    and the driver's panel-width policy (lu_panel_reject_reason has
    the per-condition story)."""
    return lu_panel_reject_reason(m, w, dtype) is None


def lu_panel(a: jax.Array):
    """(packed, piv int32) partial-pivot LU panel via the rank-1
    kernel; fused on eligible TPU panels, interpreted off-TPU, else
    None with the rejection reason published as an obs instant
    (caller falls back to the masked fori_loop panel)."""
    m, w = a.shape
    reason = lu_panel_reject_reason(m, w, a.dtype)
    if reason is not None and not (pallas_runnable(a.dtype)
                                   and _lu_shape_ok(m, w, a.dtype)):
        _reject("lu_panel", reason, m=m, w=w, dtype=str(a.dtype))
        return None
    packed, piv = _lu_panel_pallas(a, m, w, pallas_interpret())
    return packed, piv[0].astype(jnp.int32)


# -- block-recursive partial-pivot LU panel kernel (round 10) ------------

#: widest recursive panel (one dispatch OR the JAX-level tall split)
LU_REC_MAX_W = 512
#: innermost base-case width (tune key ("lu_panel", "ib"))
LU_REC_IB = 32
#: single-dispatch budget in f32-equivalent panel ELEMENTS (m * w):
#: the kernel holds the panel plus a couple of f32 (m, w) temporaries,
#: so the budget matches the rank-1 kernel's measured 8192x256 f32
#: ceiling; sub-f32 dtypes shrink it (methods.vmem_height_cap
#: rationale: the temporaries stay f32)
LU_REC_MAX_ELEMS = 8192 * 256


def _rec_ib(w: int, ib: Optional[int]) -> int:
    """Base-case width: the caller's override or the tuned/frozen
    default, clamped to a power-of-two divisor of w (the halving
    contract: w = ib * 2^k)."""
    if ib is None:
        from ..tune.select import tuned_int
        ib = tuned_int("lu_panel", "ib", LU_REC_IB, n=w)
    ib = max(8, min(ib, w))
    while w % ib or (w // ib) & (w // ib - 1):
        ib //= 2
        if ib < 8:
            return 8
    return ib


def _rec_max_elems(dtype, max_elems: Optional[int]) -> int:
    from ..core.methods import vmem_height_cap
    return max_elems if max_elems is not None \
        else vmem_height_cap(LU_REC_MAX_ELEMS, dtype)


def lu_panel_rec_reject_reason(m: int, w: int, dtype,
                               max_elems: Optional[int] = None,
                               ib: Optional[int] = None
                               ) -> Optional[str]:
    """Why (m, w) will not factor through the recursive panel path
    (None == eligible): 'platform'/'dtype' as lu_panel, 'width'
    (> LU_REC_MAX_W or not ib * 2^k after clamping), 'aspect'
    (m < w — recursion assumes a tall panel), 'align' (m % 128 /
    w % 8), or 'height' (too tall even for the narrowest JAX-level
    split: m * ib above the single-dispatch element budget)."""
    if not _on_tpu():
        return "platform"
    if jnp.dtype(dtype) not in (jnp.float32, jnp.bfloat16):
        return "dtype"
    return _rec_shape_reason(m, w, dtype, max_elems, ib)


def _rec_shape_reason(m: int, w: int, dtype,
                      max_elems: Optional[int] = None,
                      ib: Optional[int] = None) -> Optional[str]:
    if w > LU_REC_MAX_W or w % 8 != 0:
        return "width"
    if m < w:
        return "aspect"
    if m % 128 != 0:
        return "align"
    if m * _rec_ib(w, ib) > _rec_max_elems(dtype, max_elems):
        return "height"
    return None


def lu_panel_rec_eligible(m: int, w: int, dtype) -> bool:
    """ROUTING gate for the block-recursive panel (consulted by
    lu._lu_panel's method arbitration when the tune cache routes
    'pallas_rec')."""
    return lu_panel_rec_reject_reason(m, w, dtype) is None


@functools.partial(jax.jit,
                   static_argnames=("m", "w", "ib", "interp"))
def _lu_panel_rec_pallas(a: jax.Array, m: int, w: int, ib: int,
                         interp: bool):
    """Block-recursive partial-pivot LU of an (m, w) panel in ONE
    dispatch. Trace-time recursion halves the width (w -> w/2 -> ...
    -> ib); at each node the left half factors recursively, then the
    right half gets ONE masked-matmul triangular solve (itself
    recursively halved down to an ib-row substitution) and ONE
    masked rank-w/2 MXU matmul; only the ib-wide base case runs the
    sequential per-column recurrence (argmax pivot search + full-row
    swap + segment-confined rank-1), with whole-panel masked selects
    instead of Mosaic dynamic row ops (round-3 lesson: those are
    ~1 µs each). Returns (packed LU, pivot swap targets (1, w) f32 —
    exact for m < 2^24); bitwise the same pivot sequence as
    lu.lu_panel_fori (pinned by the adversarial suite in
    tests/test_pallas_rec.py)."""
    from jax.experimental import pallas as pl

    def kernel(a_ref, out_ref, piv_ref):
        rows_c = jax.lax.broadcasted_iota(jnp.int32, (m, 1), 0)
        cols_r = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
        rows_w = jax.lax.broadcasted_iota(jnp.int32, (w, 1), 0)
        out_ref[:] = a_ref[:]
        piv_ref[:] = jnp.zeros((1, w), jnp.float32)

        def mm_update(r0, r1, k0, k1, c0, c1):
            # out[r0:r1, c0:c1] -= out[r0:r1, k0:k1] @ out[k0:k1, c0:c1]
            # as ONE masked MXU matmul: k indexes L columns == U rows,
            # both masked in place of the dynamic-width slices Mosaic
            # cannot express (the _chol_fused_pallas trick). The U
            # operand comes from the top w rows (k1 <= w <= m always).
            L = jnp.where((rows_c >= r0) & (rows_c < r1)
                          & (cols_r >= k0) & (cols_r < k1),
                          out_ref[:], 0.0).astype(jnp.float32)
            U = jnp.where((rows_w >= k0) & (rows_w < k1)
                          & (cols_r >= c0) & (cols_r < c1),
                          out_ref[0:w, :], 0.0).astype(jnp.float32)
            P = jax.lax.dot_general(
                L, U, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
            out_ref[:] = (out_ref[:] - P.astype(out_ref.dtype))

        def base(c0, wseg):
            # factor columns [c0, c0+wseg): per-column argmax pivot
            # search, FULL-row swap (all w columns, so earlier L and
            # later unfactored columns stay in panel row order — the
            # lu_panel_fori discipline), scale, rank-1 update confined
            # to this segment's columns (the recursion's whole point:
            # columns right of the segment get rank-ib matmuls later)
            def step(jj, _):
                j = c0 + jj
                colsel = cols_r == j                        # (1, w)
                col = jnp.sum(jnp.where(colsel, out_ref[:], 0.0),
                              axis=1,
                              keepdims=True).astype(jnp.float32)
                mag = jnp.where(rows_c >= j, jnp.abs(col), -1.0)
                mx = jnp.max(mag)
                p = jnp.min(jnp.where(mag == mx, rows_c, m))
                piv_ref[:] = jnp.where(colsel, p.astype(jnp.float32),
                                       piv_ref[:])
                rowj = jnp.sum(jnp.where(rows_c == j, out_ref[:], 0.0),
                               axis=0, keepdims=True)       # (1, w)
                rowp = jnp.sum(jnp.where(rows_c == p, out_ref[:], 0.0),
                               axis=0, keepdims=True)
                pan = out_ref[:]
                pan = jnp.where(rows_c == j, rowp,
                                jnp.where(rows_c == p, rowj, pan))
                pivval = jnp.sum(jnp.where(colsel, rowp,
                                           0.0)).astype(jnp.float32)
                safe = jnp.where(pivval == 0, 1.0, pivval)
                col2 = jnp.sum(jnp.where(colsel, pan, 0.0), axis=1,
                               keepdims=True)               # (m, 1)
                mults = jnp.where(rows_c > j,
                                  col2.astype(jnp.float32) / safe,
                                  0.0).astype(pan.dtype)    # (m, 1)
                urow = jnp.where((cols_r > j) & (cols_r < c0 + wseg),
                                 rowp, 0.0)                 # (1, w)
                pan = pan - mults * urow
                newcol = jnp.where(rows_c > j, mults, col2)
                pan = jnp.where(colsel, newcol, pan)
                out_ref[:] = pan.astype(out_ref.dtype)
                return 0

            jax.lax.fori_loop(0, wseg, step, 0)

        def solve(c0, ws, c1, c2):
            # rows [c0, c0+ws) of cols [c1, c2) := L11^{-1} @ (same),
            # L11 unit-lower at [c0:c0+ws) x [c0:c0+ws): recursive
            # halving; base = ib sequential substitution steps, each
            # a masked (m, 1) x (1, w) outer-product AXPY
            if ws <= ib:
                def srow(rr, _):
                    r = c0 + rr
                    rowr = jnp.sum(jnp.where(rows_c == r, out_ref[:],
                                             0.0),
                                   axis=0, keepdims=True)   # (1, w)
                    rowr = jnp.where((cols_r >= c1) & (cols_r < c2),
                                     rowr, 0.0)
                    lcol = jnp.sum(jnp.where(cols_r == r, out_ref[:],
                                             0.0),
                                   axis=1, keepdims=True)   # (m, 1)
                    lcol = jnp.where((rows_c > r)
                                     & (rows_c < c0 + ws), lcol, 0.0)
                    out_ref[:] = (out_ref[:]
                                  - (lcol * rowr).astype(out_ref.dtype))
                    return 0

                jax.lax.fori_loop(0, ws, srow, 0)
            else:
                h = ws // 2
                solve(c0, h, c1, c2)
                mm_update(c0 + h, c0 + ws, c0, c0 + h, c1, c2)
                solve(c0 + h, ws - h, c1, c2)

        def rec(c0, wseg):
            if wseg <= ib:
                base(c0, wseg)
                return
            w1 = wseg // 2
            rec(c0, w1)
            # U12 then the trailing rank-w1 MXU update
            solve(c0, w1, c0 + w1, c0 + wseg)
            mm_update(c0 + w1, m, c0, c0 + w1, c0 + w1, c0 + wseg)
            rec(c0 + w1, wseg - w1)

        rec(0, w)

    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((m, w), a.dtype),
                   jax.ShapeDtypeStruct((1, w), jnp.float32)),
        interpret=interp,
    )(a)


#: row-block heights the gridded trailing update tries, tallest first
_REC_ROW_BLOCKS = (2048, 1024, 512, 256, 128)


@functools.partial(jax.jit, static_argnames=("rb", "interp"))
def _rank_update_pallas(a22: jax.Array, l21: jax.Array,
                        u12: jax.Array, rb: int, interp: bool):
    """A22 - L21 @ U12 GRIDDED OVER ROW BLOCKS — the tall-panel
    trailing update: each grid step holds one (rb, w) row block plus
    the shared (w1, w) U12 in VMEM, so the update runs at any height
    (this is what lets lu_panel_rec factor panels the native LU
    custom call cannot compile, methods.NATIVE_LU_MAX_M)."""
    from jax.experimental import pallas as pl
    m2, w2 = a22.shape
    w1 = l21.shape[1]

    def kernel(a_ref, l_ref, u_ref, o_ref):
        P = jax.lax.dot_general(
            l_ref[:].astype(jnp.float32), u_ref[:].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
        o_ref[:] = a_ref[:] - P.astype(a_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid=(m2 // rb,),
        in_specs=[pl.BlockSpec((rb, w2), lambda i: (i, 0)),
                  pl.BlockSpec((rb, w1), lambda i: (i, 0)),
                  pl.BlockSpec((w1, w2), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((rb, w2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m2, w2), a22.dtype),
        interpret=interp,
    )(a22, l21, u12)


def _rank_update(a22: jax.Array, l21: jax.Array, u12: jax.Array):
    """Trailing update dispatcher: the row-block-gridded Pallas kernel
    when a block height divides, else the plain XLA matmul (value-
    identical in exact arithmetic; the kernel exists for the TPU
    schedule, not different math)."""
    m2 = a22.shape[0]
    for rb in _REC_ROW_BLOCKS:
        if m2 % rb == 0 and m2 >= rb:
            return _rank_update_pallas(a22, l21, u12, rb,
                                       pallas_interpret())
    return a22 - jnp.matmul(l21, u12,
                            precision=jax.lax.Precision.HIGHEST)


def _lu_rec_split(a: jax.Array, ib: int, max_elems: int):
    """JAX-level recursive halving for panels too tall for one
    VMEM-resident dispatch: factor the left half (full height), apply
    its composed pivot permutation to the right half (one gather —
    exactly the deferred-laswp discipline of lu._getrf_carry), solve
    U12, run the row-block-gridded trailing update, recurse on the
    right, then permute the left half's lower rows by the right's
    pivots. The pivot SEQUENCE is identical to factoring the whole
    panel column-by-column (swaps compose), so parity with
    lu_panel_fori survives the split."""
    m, w = a.shape
    if m * w <= max_elems:
        packed, piv = _lu_panel_rec_pallas(a, m, w, _rec_ib(w, ib),
                                           pallas_interpret())
        return packed, piv[0].astype(jnp.int32)
    w1 = w // 2
    left, piv1 = _lu_rec_split(a[:, :w1], ib, max_elems)
    perm1 = jax.lax.linalg.lu_pivots_to_permutation(piv1, m)
    right = a[:, w1:][perm1]
    u12 = jax.lax.linalg.triangular_solve(
        left[:w1, :w1], right[:w1], left_side=True, lower=True,
        unit_diagonal=True)
    a22 = _rank_update(right[w1:], left[w1:, :w1], u12)
    sub, piv2 = _lu_rec_split(a22, ib, max_elems)
    perm2 = jax.lax.linalg.lu_pivots_to_permutation(piv2, m - w1)
    left = jnp.concatenate([left[:w1], left[w1:][perm2]], axis=0)
    packed = jnp.concatenate(
        [left, jnp.concatenate([u12, sub], axis=0)], axis=1)
    return packed, jnp.concatenate([piv1, w1 + piv2])


def lu_panel_rec(a: jax.Array, ib: Optional[int] = None,
                 max_elems: Optional[int] = None):
    """(packed, piv int32) partial-pivot LU panel via BLOCK RECURSION
    (module doc): one VMEM-resident dispatch when (m, w) fits the
    element budget, the JAX-level halving with row-block-gridded
    trailing updates when taller — the exact-pivoting path for panels
    the native LU custom call cannot compile (m >
    methods.NATIVE_LU_MAX_M). Returns None (with the reason as an obs
    instant) when ineligible; `ib` overrides the tuned base-case
    width, `max_elems` the single-dispatch budget (tests force the
    tall split with it)."""
    m, w = a.shape
    reason = lu_panel_rec_reject_reason(m, w, a.dtype, max_elems, ib)
    if reason is not None:
        runnable = pallas_runnable(a.dtype) and _rec_shape_reason(
            m, w, a.dtype, max_elems, ib) is None
        if not runnable:
            _reject("lu_panel_rec", reason, m=m, w=w,
                    dtype=str(a.dtype))
            return None
    return _lu_rec_split(a, ib, _rec_max_elems(a.dtype, max_elems))


# -- blocked Givens-chain apply (steqr2/bdsqr bulge chase) ---------------

#: rotation-group width b: factors are (2b, 2b) windows on b-spaced
#: anchors (tune key ("steqr2", "chain_blk"))
GIVENS_CHAIN_BLK = 128


def _chain_window_matrix(cs: jax.Array, sn: jax.Array, size: int,
                         dtype) -> jax.Array:
    """Compose adjacent-pair rotations G_0..G_{size-2} (G_k on index
    pair (k, k+1)) into one (size, size) matrix — the ONE chain
    compose (svd._givens_chain_matrix), applied to a window. Identity
    rotations (c=1, s=0) pass through exactly, which is what lets a
    group's factor embed in a larger window."""
    from ..linalg.svd import _givens_chain_matrix
    return _givens_chain_matrix(cs, sn, size, dtype)


def _chain_anchor(j: int, n: int, blk: int) -> int:
    """Window anchor for rotation group j: b-spaced, clamped so the
    last (2b)-wide window stays inside [0, n)."""
    return min(j * blk, n - 2 * blk)


def givens_chain_factors(cs: jax.Array, sn: jax.Array, n: int,
                         blk: int, dtype) -> jax.Array:
    """Materialize the sweep's rotation chain as (n/blk, 2*blk,
    2*blk) banded block factors: group j holds rotations
    [j*blk, min((j+1)*blk, n-1)), whose indices all live inside the
    2*blk window at its anchor, identity-padded. Exact identity
    (pinned by test): embedding the factors at their anchors and
    multiplying in group order reproduces svd._givens_chain_matrix."""
    facs = []
    for j in range(n // blk):
        k0, k1 = j * blk, min((j + 1) * blk, n - 1)
        a0 = _chain_anchor(j, n, blk)
        cw = jnp.ones((2 * blk - 1,), dtype)
        sw = jnp.zeros((2 * blk - 1,), dtype)
        cw = cw.at[k0 - a0:k1 - a0].set(cs[k0:k1])
        sw = sw.at[k0 - a0:k1 - a0].set(sn[k0:k1])
        facs.append(_chain_window_matrix(cw, sw, 2 * blk, dtype))
    return jnp.stack(facs)


@functools.partial(jax.jit,
                   static_argnames=("rows", "n", "blk", "rb", "interp"))
def _givens_apply_pallas(Z: jax.Array, facs: jax.Array, rows: int,
                         n: int, blk: int, rb: int, interp: bool):
    """Apply the banded block factors to Z's columns, GRIDDED OVER ROW
    BLOCKS of Z: each grid step holds one (rb, n) row block plus the
    (g, 2b, 2b) factors in VMEM and sweeps the b-spaced windows left
    to right (consecutive windows overlap by b columns, so the order
    is the rotation order), each window one (rb, 2b) x (2b, 2b) MXU
    matmul — O(n^2 b) per sweep instead of the dense compose's
    O(n^3)."""
    from jax.experimental import pallas as pl
    g = n // blk
    pet = jnp.promote_types(Z.dtype, jnp.float32)

    def kernel(z_ref, f_ref, o_ref):
        o_ref[:] = z_ref[:]
        for j in range(g):
            a0 = _chain_anchor(j, n, blk)
            win = o_ref[:, a0:a0 + 2 * blk]
            o_ref[:, a0:a0 + 2 * blk] = jax.lax.dot_general(
                win.astype(pet), f_ref[j].astype(pet),
                (((1,), (0,)), ((), ())),
                preferred_element_type=pet,
                precision=jax.lax.Precision.HIGHEST).astype(o_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid=(rows // rb,),
        in_specs=[pl.BlockSpec((rb, n), lambda i: (i, 0)),
                  pl.BlockSpec((g, 2 * blk, 2 * blk),
                               lambda i: (0, 0, 0))],
        out_specs=pl.BlockSpec((rb, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, n), Z.dtype),
        interpret=interp,
    )(Z, facs)


def _chain_blk(blk: Optional[int]) -> int:
    if blk is not None:
        return blk
    from ..tune.select import tuned_int
    return tuned_int("steqr2", "chain_blk", GIVENS_CHAIN_BLK)


def _chain_shape_ok(rows: int, n: int, blk: int) -> bool:
    return n % blk == 0 and n >= 2 * blk \
        and rows % 8 == 0 and _chain_rb(rows, n, blk) is not None


#: VMEM budget for one gridded chain-apply step: in + out row blocks
#: PLUS the full (g, 2b, 2b) factor stack must fit with pipelining
#: headroom under the 16 MB core limit
_CHAIN_VMEM_BUDGET = 12 << 20


def _chain_rb(rows: int, n: int, blk: int) -> Optional[int]:
    """Row-block height for the gridded apply: largest divisor of
    `rows` whose grid step fits the VMEM budget — the step holds the
    (rb, n) input AND output blocks plus the whole factor stack
    ((n/blk) * (2*blk)^2 f32 = 16*n*blk bytes), so the stack is part
    of the budget (it grows with blk even though it never re-fetches
    per step)."""
    facs_bytes = 16 * n * blk
    if facs_bytes >= _CHAIN_VMEM_BUDGET:
        return None
    for rb in (512, 256, 128, 64, 32, 16, 8):
        if rows % rb == 0 \
                and 2 * rb * n * 4 + facs_bytes <= _CHAIN_VMEM_BUDGET:
            return rb
    return None


def givens_chain_eligible(rows: int, n: int, dtype,
                          blk: Optional[int] = None) -> bool:
    """ROUTING gate for the blocked chain apply (eig.steqr2_qr /
    svd.bdsqr_qr consult it when the tune cache routes 'pallas_rec'):
    TPU dtypes on hardware, any float under the interpreter (the
    kernel has no f32-hardcoded recurrence), n a multiple of the
    block width with at least two windows, and a row-block height
    that divides."""
    b = _chain_blk(blk)
    if not _chain_shape_ok(rows, n, b):
        return False
    if pallas_available(dtype):
        return True
    return pallas_interpret() \
        and jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def givens_chain_apply(Z: jax.Array, cs: jax.Array, sn: jax.Array,
                       blk: Optional[int] = None):
    """Z @ G for G the composed Givens chain of (cs, sn) (identical to
    Z @ svd._givens_chain_matrix(cs, sn, n, dt) — pinned by test),
    computed as banded block factors applied window-by-window as MXU
    matmuls. Returns None when ineligible (caller keeps the dense
    compose)."""
    rows, n = Z.shape
    b = _chain_blk(blk)
    if not givens_chain_eligible(rows, n, Z.dtype, b):
        _reject("givens_chain_apply", "shape", rows=rows, n=n,
                dtype=str(Z.dtype))
        return None
    dt = jnp.promote_types(Z.dtype, cs.dtype)
    facs = givens_chain_factors(cs.astype(dt), sn.astype(dt), n, b, dt)
    return _givens_apply_pallas(Z, facs, rows, n, b,
                                _chain_rb(rows, n, b),
                                pallas_interpret())


# -- fused in-VMEM triangular inversion kernel ---------------------------

#: largest block inverted in one VMEM-resident kernel
TRTRI_FUSED_MAX = 512


@functools.partial(jax.jit, static_argnames=("n", "unit", "interp"))
def _trtri_lower_pallas(a: jax.Array, n: int, unit: bool, interp: bool):
    """inv(L) for lower-triangular (n, n) by forward substitution kept
    entirely in VMEM: one dispatch, n sequential row steps, each a
    (1, n) x (n, n) MXU product. Substitution-grade numerics (explicit
    Neumann/product forms overflow for unit-lower LU blocks).

    Reference analogue: the trsm diag-block inversion the reference does
    per-tile with lapack::trtri on the device queue (trsm variants via
    work_trsm.cc); upper inputs are handled by the caller via transpose.
    """
    from jax.experimental import pallas as pl

    def kernel(a_ref, out_ref):
        cols_r = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
        out_ref[:] = jnp.zeros((n, n), a_ref.dtype)

        def row(j, _):
            arow = a_ref[pl.ds(j, 1), :]                     # (1, n)
            lj = jnp.where(cols_r < j, arow, 0.0)
            prod = jax.lax.dot_general(
                lj, out_ref[:], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)         # (1, n)
            ej = jnp.where(cols_r == j, 1.0, 0.0).astype(a_ref.dtype)
            xj = ej - prod.astype(a_ref.dtype)
            if not unit:
                ljj = jnp.sum(jnp.where(cols_r == j, arow, 0.0))
                ljj = jnp.where(ljj == 0, 1.0, ljj).astype(a_ref.dtype)
                xj = xj / ljj
            out_ref[pl.ds(j, 1), :] = xj
            return 0

        jax.lax.fori_loop(0, n, row, 0)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        interpret=interp,
    )(a)


def trtri_eligible(n: int, dtype) -> bool:
    """ROUTING gate for the fused substitution kernel: f32 TPU blocks
    within the one-dispatch cap (bench comparison point since round
    3 — the XLA solve beats it on current libtpu, PERF.md)."""
    return pallas_available(dtype) and jnp.dtype(dtype) == jnp.float32 \
        and _trtri_shape_ok(n)


def _trtri_shape_ok(n: int) -> bool:
    from ..tune.select import tuned_int
    return n <= tuned_int("trtri", "fused_max", TRTRI_FUSED_MAX) \
        and n % 128 == 0


def trtri_lower(a: jax.Array, unit_diagonal: bool = False) -> jax.Array:
    """Lower-triangular inverse of one block: fused Pallas substitution
    when trtri_eligible (or interpreted off-TPU for f32), else XLA
    triangular_solve (LAPACK-backed and fast on CPU)."""
    n = a.shape[0]
    if trtri_eligible(n, a.dtype) or (
            pallas_runnable(a.dtype) and a.dtype == jnp.float32
            and _trtri_shape_ok(n)):
        return _trtri_lower_pallas(a, n, unit_diagonal,
                                   pallas_interpret())
    return jax.lax.linalg.triangular_solve(
        a, jnp.eye(n, dtype=a.dtype), left_side=True, lower=True,
        unit_diagonal=unit_diagonal)


# -- fused in-VMEM Cholesky panel kernel ---------------------------------

_CHOL_BLK = 128

#: largest panel kept fully in VMEM (f32: 4 MB at 1024)
CHOL_FUSED_MAX = 1024


@functools.partial(jax.jit, static_argnames=("n", "interp"))
def _chol_fused_pallas(a: jax.Array, n: int, interp: bool):
    from jax.experimental import pallas as pl

    blk = min(_CHOL_BLK, n)
    nblk = n // blk

    def kernel(a_ref, out_ref):
        # all intermediates kept rank-2 (Mosaic layouts for 1D vectors
        # are fragile); rows_c is an (n,1) column, colsl_r a (1,blk) row
        rows_c = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)
        colsl_r = jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
        out_ref[:] = a_ref[:]

        def stripe(kb, _):
            k0 = kb * blk
            S = out_ref[:, pl.ds(k0, blk)]                  # (n, blk)
            # left-looking update: S -= L[:, :k0] @ L[k0:k1, :k0]^T via
            # full-width masked matmul (masks stand in for the
            # dynamic-width slice, which Mosaic cannot express)
            colmask = (jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
                       < k0)
            Lm = jnp.where(colmask, out_ref[:], 0.0)
            G = out_ref[pl.ds(k0, blk), :]                  # (blk, n)
            gmask = (jax.lax.broadcasted_iota(jnp.int32, (blk, n), 1)
                     < k0)
            G = jnp.where(gmask, G, 0.0)
            S = S - jax.lax.dot_general(
                Lm, G, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST).astype(S.dtype)

            # projT[r, c] == (r == k0 + c): row-extraction mask standing
            # in for a value dynamic_slice (unsupported in Mosaic)
            projT = (jax.lax.broadcasted_iota(jnp.int32, (n, blk), 0)
                     == jax.lax.broadcasted_iota(jnp.int32, (n, blk), 1)
                     + k0)

            def col(jj, S):
                j = k0 + jj
                sel = colsl_r == jj                          # (1, blk)
                colv = jnp.sum(jnp.where(sel, S, 0.0), axis=1,
                               keepdims=True)               # (n, 1)
                piv = jnp.sum(jnp.where(rows_c == j, colv, 0.0))
                d = jnp.sqrt(piv)
                dsafe = jnp.where(d == 0, 1.0, d).astype(S.dtype)
                v = jnp.where(rows_c > j, colv / dsafe,
                              0.0).astype(S.dtype)          # (n, 1)
                newcol = v + jnp.where(rows_c == j, d,
                                       0.0).astype(S.dtype)
                S = jnp.where(sel, newcol, S)
                vrow = jnp.sum(jnp.where(projT, v, 0.0), axis=0,
                               keepdims=True)               # (1, blk)
                S = S - (v * jnp.where(colsl_r > jj, vrow, 0.0)
                         ).astype(S.dtype)
                return S

            S = jax.lax.fori_loop(0, blk, col, S)
            out_ref[:, pl.ds(k0, blk)] = S
            return 0

        jax.lax.fori_loop(0, nblk, stripe, 0)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        interpret=interp,
    )(a)


def chol_panel_eligible(n: int, dtype) -> bool:
    """ROUTING gate for the fused Cholesky panel: f32 TPU blocks
    within the one-dispatch cap (bench comparison point since round
    3, PERF.md)."""
    return pallas_available(dtype) and jnp.dtype(dtype) == jnp.float32 \
        and _chol_shape_ok(n)


def _chol_shape_ok(n: int) -> bool:
    from ..tune.select import tuned_int
    return n <= tuned_int("chol_panel", "fused_max", CHOL_FUSED_MAX) \
        and n % _CHOL_BLK == 0


def chol_panel(a: jax.Array) -> jax.Array:
    """Lower Cholesky of an SPD block; fused Pallas kernel when
    chol_panel_eligible (or interpreted off-TPU for f32), else XLA's
    cholesky. Upper triangle of the result is unspecified (callers
    mask), matching LAPACK."""
    n = a.shape[0]
    if chol_panel_eligible(n, a.dtype) or (
            pallas_runnable(a.dtype) and a.dtype == jnp.float32
            and _chol_shape_ok(n)):
        return _chol_fused_pallas(a, n, pallas_interpret())
    # symmetrize_input=False: callers hand blocks whose upper triangle
    # may hold stale values (lower-only trailing updates); averaging it
    # in would corrupt the factor
    return jax.lax.linalg.cholesky(a, symmetrize_input=False)


# -- ragged batched kernels (round 15): kill the padding tax -------------
#
# One kernel over a RAGGED batch: the stack is padded to a single
# ceiling shape (the max live size rounded to lane alignment — no pow2
# rounding), and a per-element ``sizes`` vector rides as a
# scalar-prefetch operand (the Ragged Paged Attention play, PAPERS.md,
# applied to dense factorizations). Each grid step owns one element:
# the kernel rebuilds the bucket layer's validity-masked padding
# IN-KERNEL (identity diagonal outside the live block, so garbage in
# the pad region can never leak), bounds its blocked sweep with a
# DYNAMIC trip count ceil(s/blk) — stripes past the element's true
# extent never execute — and masks every base-case op and rank-blk MXU
# update with the whole-panel masks of the PR 6 recursion (no Mosaic
# dynamic row ops). Pivoting discipline matches bucket.py's identity
# padding exactly: live columns hold exact zeros in padded rows, so a
# padded row is unpivotable, and padded columns pivot on their own
# unit diagonal (pinned by tests/test_ragged.py's adversarial suite).
#
# Work accounting: the dynamic trip count confines each element to its
# block-aligned true extent along the FACTOR dimension (ceil(s/blk)
# stripes instead of N/blk), which is where the batch layer's cubic
# padding tax lives; the per-stripe masked matmuls still span the
# ceiling's row/column extent in one VMEM block (a row-block grid over
# the ragged row extent is the TPU hardware round's follow-up). The
# batch queue reports ragged dispatch waste against the block-aligned
# extents (bucket.ragged_report).

#: stripe / base-case width of the ragged batched kernels (tune key
#: ("ragged", "blk")); the ragged ceiling is aligned to lcm(align, blk)
RAGGED_BLK = 32


def ragged_blk(blk: Optional[int] = None, opts=None) -> int:
    """The tuned/frozen ragged block width, clamped to a positive
    multiple of 8 (Mosaic sublane granularity). ``opts`` threads the
    caller's per-call tuning controls (Option.Tune etc.) into the
    cache read."""
    if blk is None:
        from ..tune.select import tuned_int
        blk = tuned_int("ragged", "blk", RAGGED_BLK, opts=opts)
    return max(8, (int(blk) // 8) * 8)


def _ragged_dtype_ok(dtype) -> bool:
    """f32/bf16 on hardware; any float under the interpreter (no
    f32-hardcoded recurrence: arithmetic runs in promote(dtype, f32),
    so tier-1's f64 batches exercise the kernels at full precision)."""
    if pallas_available(dtype):
        return True
    return pallas_interpret() \
        and jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def ragged_supported(dtype) -> bool:
    """Submit-time routing gate for the batch queue's ragged strategy:
    can the ragged kernels execute for this dtype at all (natively on
    TPU, or interpreted elsewhere). Shape eligibility is checked per
    dispatch by the ``ragged_*_eligible`` gates — the queue constructs
    the ceiling to satisfy them (bucket.ragged_ceiling)."""
    return _ragged_dtype_ok(dtype)


def _ragged_shape_ok(n: int, blk: int) -> bool:
    return n >= blk and n % blk == 0


def _ragged_reject_reason(n: int, dtype, blk: int) -> Optional[str]:
    if not _ragged_dtype_ok(dtype):
        return "dtype" if _on_tpu() or pallas_interpret() else "platform"
    if not _ragged_shape_ok(n, blk):
        return "shape"
    return None


def ragged_potrf_eligible(n: int, dtype, blk: Optional[int] = None
                          ) -> bool:
    """Eligibility gate for the ragged batched Cholesky: runnable
    dtype (hardware or interpreter) and a ceiling that is a positive
    multiple of the ragged block width."""
    return _ragged_reject_reason(n, dtype, ragged_blk(blk)) is None


def ragged_getrf_eligible(n: int, dtype, blk: Optional[int] = None
                          ) -> bool:
    """Eligibility gate for the ragged batched partial-pivot LU (same
    conditions as ragged_potrf_eligible; the pivot vector is exact for
    n < 2^24 — f32 index rows, the lu_panel discipline)."""
    return _ragged_reject_reason(n, dtype, ragged_blk(blk)) is None \
        and n < (1 << 24)


def ragged_trsm_eligible(n: int, k: int, dtype,
                         blk: Optional[int] = None) -> bool:
    """Eligibility gate for the ragged batched triangular solve:
    ragged ceiling conditions plus at least one right-hand-side
    column (rhs lane padding is a TPU hardware-round follow-up; the
    interpreter takes any k)."""
    return _ragged_reject_reason(n, dtype, ragged_blk(blk)) is None \
        and k >= 1


def _ragged_donate_ok() -> bool:
    """Buffer donation is a TPU-side win (drivers._donate_ok
    rationale); on CPU it is an unimplemented per-call warning, so it
    is never enabled there. The ragged kernels additionally alias
    their consumed operand onto the output via pallas
    ``input_output_aliases`` (each kernel reads it exactly once, at
    the top of its grid step), so a donated stack factors in place —
    the bucket path's donation contract carried to the ragged route."""
    try:
        return jax.default_backend() != "cpu"
    except Exception:
        return False


def _ragged_potrf_pallas(sizes: jax.Array, stack: jax.Array, B: int,
                         N: int, blk: int, interp: bool):
    """Ragged batched lower Cholesky: grid over the batch, one (N, N)
    element per step, its true order s prefetched from ``sizes``. The
    element is rebuilt as blkdiag(A[:s, :s], I) in VMEM, then the
    fused blocked sweep (_chol_fused_pallas's stripe shape) runs
    ceil(s/blk) stripes — a DYNAMIC trip count, so padded stripes
    never execute; the identity padding factors to identity exactly,
    making the [:s, :s] crop exact (the bucket.py validity-masking
    argument, enforced in-kernel)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    ct = jnp.promote_types(stack.dtype, jnp.float32)

    def kernel(s_ref, a_ref, o_ref):
        s = s_ref[pl.program_id(0)]
        z = jnp.int32(0)
        rows_c = jax.lax.broadcasted_iota(jnp.int32, (N, 1), 0)
        cols_r = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
        live = (rows_c < s) & (cols_r < s)
        eye = (rows_c == cols_r).astype(a_ref.dtype)
        o_ref[:] = jnp.where(live, a_ref[:], eye)
        nlive = (s + blk - 1) // blk
        colsl_r = jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)

        def stripe(kb, _):
            k0 = (kb * blk).astype(jnp.int32)
            S = pl.load(o_ref, (pl.ds(z, N), pl.ds(k0, blk)))
            # left-looking update S -= L[:, :k0] @ L[k0:k1, :k0]^T via
            # whole-panel masks (the _chol_fused_pallas trick)
            colmask = (jax.lax.broadcasted_iota(jnp.int32, (N, N), 1)
                       < k0)
            Lm = jnp.where(colmask, o_ref[:], 0.0).astype(ct)
            G = pl.load(o_ref, (pl.ds(k0, blk), pl.ds(z, N)))
            gmask = (jax.lax.broadcasted_iota(jnp.int32, (blk, N), 1)
                     < k0)
            G = jnp.where(gmask, G, 0.0).astype(ct)
            S = S - jax.lax.dot_general(
                Lm, G, (((1,), (1,)), ((), ())),
                preferred_element_type=ct,
                precision=jax.lax.Precision.HIGHEST).astype(S.dtype)
            projT = (jax.lax.broadcasted_iota(jnp.int32, (N, blk), 0)
                     == jax.lax.broadcasted_iota(jnp.int32, (N, blk), 1)
                     + k0)

            def col(jj, S):
                j = k0 + jj
                sel = colsl_r == jj
                colv = jnp.sum(jnp.where(sel, S, 0.0), axis=1,
                               keepdims=True).astype(ct)     # (N, 1)
                piv = jnp.sum(jnp.where(rows_c == j, colv, 0.0))
                d = jnp.sqrt(piv)
                dsafe = jnp.where(d == 0, 1.0, d)
                v = jnp.where(rows_c > j, colv / dsafe,
                              0.0).astype(S.dtype)
                newcol = v + jnp.where(rows_c == j, d,
                                       0.0).astype(S.dtype)
                S = jnp.where(sel, newcol, S)
                vrow = jnp.sum(jnp.where(projT, v, 0.0), axis=0,
                               keepdims=True)
                S = S - (v * jnp.where(colsl_r > jj, vrow, 0.0)
                         ).astype(S.dtype)
                return S

            S = jax.lax.fori_loop(z, jnp.int32(blk), col, S)
            pl.store(o_ref, (pl.ds(z, N), pl.ds(k0, blk)), S)
            return 0

        jax.lax.fori_loop(z, nlive, stripe, 0)
        o_ref[:] = jnp.where(rows_c >= cols_r, o_ref[:], 0.0)

    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(B,),
        in_specs=[pl.BlockSpec((None, N, N), lambda i, *_: (i, 0, 0))],
        out_specs=pl.BlockSpec((None, N, N), lambda i, *_: (i, 0, 0)))
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((B, N, N), stack.dtype),
        # the stack is read once at the top of each grid step, so it
        # may back the output buffer in place (index 1 = the operand
        # after the scalar-prefetch sizes)
        input_output_aliases={1: 0},
        interpret=interp)(sizes, stack)


@functools.lru_cache(maxsize=None)
def _ragged_potrf_fn(B: int, N: int, blk: int, interp: bool,
                     donate: bool):
    fn = functools.partial(_ragged_potrf_pallas, B=B, N=N, blk=blk,
                           interp=interp)
    return jax.jit(fn, donate_argnums=(1,) if donate else ())


def ragged_potrf(stack: jax.Array, sizes, blk: Optional[int] = None,
                 donate: bool = False):
    """Ragged batched lower Cholesky of a (B, N, N) stack with
    per-element true orders ``sizes`` (int32, scalar-prefetched).
    Element i's [:sizes[i], :sizes[i]] block is its exact factor; the
    pad region comes back as the identity's lower triangle.
    ``donate=True`` hands the stack's buffer to XLA on backends that
    implement donation (throwaway padded copies factor in place —
    the kernel aliases it onto the output). Returns None (reason
    published as an obs instant) when ineligible — the caller keeps
    the bucket strategy."""
    B, N = stack.shape[0], stack.shape[-1]
    b = ragged_blk(blk)
    if not ragged_potrf_eligible(N, stack.dtype, b):
        _reject("ragged_potrf", _ragged_reject_reason(N, stack.dtype, b)
                or "shape", n=N, dtype=str(stack.dtype))
        return None
    sizes = jnp.asarray(sizes, jnp.int32)
    fn = _ragged_potrf_fn(B, N, b, pallas_interpret(),
                          donate and _ragged_donate_ok())
    return fn(sizes, stack)


def _ragged_getrf_pallas(sizes: jax.Array, stack: jax.Array, B: int,
                         N: int, ib: int, interp: bool):
    """Ragged batched partial-pivot LU: per element, a blocked
    right-looking sweep with a DYNAMIC trip count ceil(s/ib); each
    step reuses the lu_panel_rec masked discipline verbatim — the
    ib-wide base case runs the sequential argmax/full-row-swap/rank-1
    recurrence with whole-panel masked selects, the U12 strip solves
    by ib masked substitution rows, and the trailing update is ONE
    masked rank-ib MXU matmul. The in-kernel identity padding keeps
    padded rows unpivotable (live columns hold exact zeros there) and
    padded columns pivot on their own unit diagonal, so the pivot
    vector is exactly the per-element lu_panel_fori sequence extended
    by identity swaps. Returns (packed L\\U (B, N, N), pivot swap
    targets (B, 1, N) f32 — exact for N < 2^24)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    ct = jnp.promote_types(stack.dtype, jnp.float32)

    def kernel(s_ref, a_ref, o_ref, piv_ref):
        s = s_ref[pl.program_id(0)]
        z = jnp.int32(0)
        rows_c = jax.lax.broadcasted_iota(jnp.int32, (N, 1), 0)
        cols_r = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
        live = (rows_c < s) & (cols_r < s)
        eye = (rows_c == cols_r).astype(a_ref.dtype)
        o_ref[:] = jnp.where(live, a_ref[:], eye)
        # identity swap targets everywhere a base step never runs
        piv_ref[:] = jax.lax.broadcasted_iota(
            jnp.float32, (1, N), 1)
        nlive = (s + ib - 1) // ib

        def base(k0):
            # factor columns [k0, k0+ib): the lu_panel_rec base case
            # (argmax pivot search, full-row swap, segment-confined
            # rank-1) with k0 a traced scalar in the masks
            def step(jj, _):
                j = k0 + jj
                colsel = cols_r == j
                col = jnp.sum(jnp.where(colsel, o_ref[:], 0.0),
                              axis=1, keepdims=True).astype(ct)
                mag = jnp.where(rows_c >= j, jnp.abs(col), -1.0)
                mx = jnp.max(mag)
                p = jnp.min(jnp.where(mag == mx, rows_c, N))
                piv_ref[:] = jnp.where(colsel, p.astype(jnp.float32),
                                       piv_ref[:])
                rowj = jnp.sum(jnp.where(rows_c == j, o_ref[:], 0.0),
                               axis=0, keepdims=True)
                rowp = jnp.sum(jnp.where(rows_c == p, o_ref[:], 0.0),
                               axis=0, keepdims=True)
                pan = o_ref[:]
                pan = jnp.where(rows_c == j, rowp,
                                jnp.where(rows_c == p, rowj, pan))
                pivval = jnp.sum(jnp.where(colsel, rowp,
                                           0.0)).astype(ct)
                safe = jnp.where(pivval == 0, 1.0, pivval)
                col2 = jnp.sum(jnp.where(colsel, pan, 0.0), axis=1,
                               keepdims=True)
                mults = jnp.where(rows_c > j,
                                  col2.astype(ct) / safe,
                                  0.0).astype(pan.dtype)
                urow = jnp.where((cols_r > j) & (cols_r < k0 + ib),
                                 rowp, 0.0)
                pan = pan - mults * urow
                newcol = jnp.where(rows_c > j, mults, col2)
                pan = jnp.where(colsel, newcol, pan)
                o_ref[:] = pan.astype(o_ref.dtype)
                return 0

            jax.lax.fori_loop(z, jnp.int32(ib), step, 0)

        def solve(k0, k1):
            # U12: rows [k0, k1) of cols [k1, N) := L11^{-1} @ (same),
            # ib masked substitution rows (lu_panel_rec's solve base)
            def srow(rr, _):
                r = k0 + rr
                rowr = jnp.sum(jnp.where(rows_c == r, o_ref[:], 0.0),
                               axis=0, keepdims=True)
                rowr = jnp.where(cols_r >= k1, rowr, 0.0)
                lcol = jnp.sum(jnp.where(cols_r == r, o_ref[:], 0.0),
                               axis=1, keepdims=True)
                lcol = jnp.where((rows_c > r) & (rows_c < k1),
                                 lcol, 0.0)
                o_ref[:] = (o_ref[:]
                            - (lcol * rowr).astype(o_ref.dtype))
                return 0

            jax.lax.fori_loop(z, jnp.int32(ib), srow, 0)

        def mm_update(k0, k1):
            # out[k1:, k1:] -= L[k1:, k0:k1] @ U[k0:k1, k1:] as ONE
            # masked rank-ib MXU matmul (lu_panel_rec's mm_update)
            L = jnp.where((rows_c >= k1) & (cols_r >= k0)
                          & (cols_r < k1), o_ref[:], 0.0).astype(ct)
            U = jnp.where((rows_c >= k0) & (rows_c < k1)
                          & (cols_r >= k1), o_ref[:], 0.0).astype(ct)
            P = jax.lax.dot_general(
                L, U, (((1,), (0,)), ((), ())),
                preferred_element_type=ct,
                precision=jax.lax.Precision.HIGHEST)
            o_ref[:] = (o_ref[:] - P.astype(o_ref.dtype))

        def block(kb, _):
            k0 = (kb * ib).astype(jnp.int32)
            k1 = k0 + ib
            base(k0)
            solve(k0, k1)
            mm_update(k0, k1)
            return 0

        jax.lax.fori_loop(z, nlive, block, 0)

    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(B,),
        in_specs=[pl.BlockSpec((None, N, N), lambda i, *_: (i, 0, 0))],
        out_specs=(
            pl.BlockSpec((None, N, N), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec((None, 1, N), lambda i, *_: (i, 0, 0))))
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=(jax.ShapeDtypeStruct((B, N, N), stack.dtype),
                   jax.ShapeDtypeStruct((B, 1, N), jnp.float32)),
        # the stack is read once per grid step; alias it onto the
        # packed-LU output (index 1 = after the scalar-prefetch sizes)
        input_output_aliases={1: 0},
        interpret=interp)(sizes, stack)


@functools.lru_cache(maxsize=None)
def _ragged_getrf_fn(B: int, N: int, ib: int, interp: bool,
                     donate: bool):
    fn = functools.partial(_ragged_getrf_pallas, B=B, N=N, ib=ib,
                           interp=interp)
    return jax.jit(fn, donate_argnums=(1,) if donate else ())


def ragged_getrf(stack: jax.Array, sizes, blk: Optional[int] = None,
                 donate: bool = False):
    """Ragged batched partial-pivot LU of a (B, N, N) stack with
    per-element true orders ``sizes``. Returns (packed L\\U stack,
    LAPACK swap-target stack (B, N) int32 — identity past each
    element's extent), or None when ineligible (reason published; the
    caller keeps the bucket strategy). ``donate`` as ragged_potrf."""
    B, N = stack.shape[0], stack.shape[-1]
    b = ragged_blk(blk)
    if not ragged_getrf_eligible(N, stack.dtype, b):
        _reject("ragged_getrf", _ragged_reject_reason(N, stack.dtype, b)
                or "shape", n=N, dtype=str(stack.dtype))
        return None
    sizes = jnp.asarray(sizes, jnp.int32)
    fn = _ragged_getrf_fn(B, N, b, pallas_interpret(),
                          donate and _ragged_donate_ok())
    packed, piv = fn(sizes, stack)
    return packed, piv[:, 0, :].astype(jnp.int32)


def _ragged_trsm_pallas(sizes: jax.Array, packed: jax.Array,
                        rhs: jax.Array, B: int, N: int, K: int,
                        blk: int, upper: bool, trans: bool,
                        unit: bool, interp: bool):
    """Ragged batched triangular solve: per element, blocked
    substitution over ceil(s/blk) blocks (DYNAMIC trip count, in
    reverse for the effective-upper system), each block a sequential
    masked-row base case plus ONE masked rank-blk MXU update of the
    remaining rows. The triangular operand is re-masked to
    blkdiag(T[:s, :s], I) in-kernel and rhs rows past s are zeroed, so
    padded rows solve to exact zeros."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    ct = jnp.promote_types(packed.dtype, jnp.float32)
    #: True => the EFFECTIVE system is upper-triangular (backward
    #: substitution): an upper operand, or a lower one applied
    #: transposed
    back = upper != trans

    def kernel(s_ref, t_ref, b_ref, o_ref):
        s = s_ref[pl.program_id(0)]
        z = jnp.int32(0)
        rows_c = jax.lax.broadcasted_iota(jnp.int32, (N, 1), 0)
        cols_r = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
        live2 = (rows_c < s) & (cols_r < s)
        eye = (rows_c == cols_r).astype(t_ref.dtype)
        t = jnp.where(live2, t_ref[:], eye)
        o_ref[:] = jnp.where(rows_c < s, b_ref[:], 0.0)
        nlive = (s + blk - 1) // blk

        def brow(r, k0, k1):
            # one substitution row: x[r] = (x[r] - T[r, solved] @ x)
            # / T[r, r], with T read transposed when trans (column r
            # of `packed` as the weight vector — no Mosaic transpose).
            # "solved" is confined to THIS block's already-processed
            # rows — cross-block contributions were subtracted by the
            # earlier blocks' rank-blk updates
            if back:
                cmask_r = (cols_r > r) & (cols_r < k1)
                cmask_c = (rows_c > r) & (rows_c < k1)
            else:
                cmask_r = (cols_r < r) & (cols_r >= k0)
                cmask_c = (rows_c < r) & (rows_c >= k0)
            if trans:
                w = jnp.sum(jnp.where(cols_r == r, t, 0.0), axis=1,
                            keepdims=True)                   # (N, 1)
                w = jnp.where(cmask_c, w, 0.0).astype(ct)
                prod = jnp.sum(w * o_ref[:].astype(ct), axis=0,
                               keepdims=True)                # (1, K)
            else:
                w = jnp.sum(jnp.where(rows_c == r, t, 0.0), axis=0,
                            keepdims=True)                   # (1, N)
                w = jnp.where(cmask_r, w, 0.0).astype(ct)
                prod = jax.lax.dot_general(
                    w, o_ref[:].astype(ct), (((1,), (0,)), ((), ())),
                    preferred_element_type=ct,
                    precision=jax.lax.Precision.HIGHEST)     # (1, K)
            if unit:
                d = jnp.ones((), ct)
            else:
                d = jnp.sum(jnp.where((rows_c == r) & (cols_r == r),
                                      t, 0.0)).astype(ct)
                d = jnp.where(d == 0, 1.0, d)
            xr = jnp.sum(jnp.where(rows_c == r, o_ref[:], 0.0),
                         axis=0, keepdims=True).astype(ct)
            new = ((xr - prod) / d).astype(o_ref.dtype)
            o_ref[:] = jnp.where(rows_c == r, new, o_ref[:])

        def block(kbi, _):
            kb = (nlive - 1 - kbi) if back else kbi
            k0 = (kb * blk).astype(jnp.int32)
            k1 = k0 + blk

            def bstep(rr, _):
                brow(k1 - 1 - rr if back else k0 + rr, k0, k1)
                return 0

            jax.lax.fori_loop(z, jnp.int32(blk), bstep, 0)
            # rank-blk MXU update of the not-yet-solved rows
            if back:
                tgt = rows_c < k0
                tgt_c = cols_r < k0
            else:
                tgt = rows_c >= k1
                tgt_c = cols_r >= k1
            X = jnp.where((rows_c >= k0) & (rows_c < k1), o_ref[:],
                          0.0).astype(ct)
            if trans:
                P = jnp.where((rows_c >= k0) & (rows_c < k1) & tgt_c,
                              t, 0.0).astype(ct)
                upd = jax.lax.dot_general(
                    P, X, (((0,), (0,)), ((), ())),
                    preferred_element_type=ct,
                    precision=jax.lax.Precision.HIGHEST)
            else:
                Tb = jnp.where(tgt & (cols_r >= k0) & (cols_r < k1),
                               t, 0.0).astype(ct)
                upd = jax.lax.dot_general(
                    Tb, X, (((1,), (0,)), ((), ())),
                    preferred_element_type=ct,
                    precision=jax.lax.Precision.HIGHEST)
            o_ref[:] = (o_ref[:] - upd.astype(o_ref.dtype))
            return 0

        jax.lax.fori_loop(z, nlive, block, 0)

    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(B,),
        in_specs=[
            pl.BlockSpec((None, N, N), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec((None, N, K), lambda i, *_: (i, 0, 0))],
        out_specs=pl.BlockSpec((None, N, K), lambda i, *_: (i, 0, 0)))
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((B, N, K), rhs.dtype),
        # the rhs is read once per grid step; alias it onto the
        # solution (index 2 = after the sizes and the factors, which
        # stay readable across the whole solve and are NOT aliased)
        input_output_aliases={2: 0},
        interpret=interp)(sizes, packed, rhs)


@functools.lru_cache(maxsize=None)
def _ragged_trsm_fn(B: int, N: int, K: int, blk: int, upper: bool,
                    trans: bool, unit: bool, interp: bool,
                    donate: bool):
    fn = functools.partial(_ragged_trsm_pallas, B=B, N=N, K=K,
                           blk=blk, upper=upper, trans=trans,
                           unit=unit, interp=interp)
    return jax.jit(fn, donate_argnums=(2,) if donate else ())


def ragged_trsm(packed: jax.Array, rhs: jax.Array, sizes,
                upper: bool = False, trans: bool = False,
                unit: bool = False, blk: Optional[int] = None,
                donate: bool = False):
    """Ragged batched triangular solve of (B, N, N) factors against a
    (B, N, K) right-hand-side stack with per-element true orders
    ``sizes``: the `upper`-designated triangle of each packed element
    (optionally `trans`posed, optionally `unit`-diagonal) solves its
    live (s, K) block; padded rows come back zero. ``donate=True``
    donates the RHS buffer (the factors are never donated — the
    posv/gesv compositions reuse them across both sweeps). Returns
    None when ineligible (reason published; the caller keeps the
    bucket strategy)."""
    if rhs is None:
        return None
    B, N = packed.shape[0], packed.shape[-1]
    K = rhs.shape[-1]
    b = ragged_blk(blk)
    if not ragged_trsm_eligible(N, K, packed.dtype, b):
        _reject("ragged_trsm", _ragged_reject_reason(N, packed.dtype, b)
                or "shape", n=N, k=K, dtype=str(packed.dtype))
        return None
    sizes = jnp.asarray(sizes, jnp.int32)
    fn = _ragged_trsm_fn(B, N, K, b, bool(upper), bool(trans),
                         bool(unit), pallas_interpret(),
                         donate and _ragged_donate_ok())
    return fn(sizes, packed, rhs)
