from . import tester
