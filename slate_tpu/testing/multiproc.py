"""Reusable multi-process mesh fixture (ISSUE 7 satellite): the
distributed-init / env-pinning / mesh-construction / result-handshake
boilerplate that lived in tests/multihost_worker.py, promoted so every
multi-process test (the posv smoke, the sharded-OOC workers, future
tuneshare/obs coverage) runs through ONE startup path — the
prerequisite the ROADMAP's dist/tuneshare and streaming-obs items have
been waiting on.

Split of responsibilities:

  * the PARENT (a pytest test) calls :func:`launch` — it probes a free
    coordinator port (with one retry on the rare bind race), spawns
    ``python <worker.py> <process_id> <port>`` per process with the
    pinned environment (:func:`worker_env`: virtual CPU device count +
    JAX_PLATFORMS, set BEFORE the child ever imports jax), reaps on
    timeout, and returns (procs, outs);
  * the WORKER calls :func:`init` first thing — it joins the
    coordinator via ``jax.distributed.initialize`` and sanity-checks
    the global device view (importing slate_tpu does NOT initialize
    the jax backend, so the import order worker scripts naturally use
    is safe — the backend materializes at the first device query,
    which happens inside/after init);
  * results cross the process boundary as one-line JSON records
    (:func:`emit` / :func:`results`), so parents assert on structured
    values instead of grepping ad-hoc prints.

``share_tuning`` in :func:`startup` wires dist/tuneshare into the
startup path: host 0's measured autotuning entries broadcast over the
tree and best-entry-merge into every host's cache before the first
driver call — one probing host, identical routing everywhere
(covered by the 2-process test in tests/test_shard_multiproc.py).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

#: worker handshake line prefix (parents parse with :func:`results`)
_TAG = "MP_RESULT "

#: seconds a surviving worker gets to exit on its own after a sibling
#: died before launch() reaps the mesh (a dead gloo peer usually hangs
#: the survivors in their next collective — the exact forever-hang
#: ISSUE 9 exists to bound)
DEATH_GRACE_S = 20.0


def worker_env(devices_per_proc: int = 4,
               platform: str = "cpu") -> Dict[str, str]:
    """Environment pins a worker subprocess needs BEFORE importing
    jax: the virtual device count (read at backend init) and the
    platform. Merge over os.environ when spawning."""
    return {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=%d"
                     % int(devices_per_proc),
        "JAX_PLATFORMS": platform,
    }


def free_port() -> int:
    """A currently-free localhost port for the coordinator. Racy by
    nature (anything can bind it between close and the coordinator's
    own bind) — launch() retries once on the collision signature."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(worker: str, num_processes: int, port: int,
           extra_args: Sequence[str], env: Optional[Dict[str, str]],
           devices_per_proc: int):
    """Spawn the workers with stdout redirected to per-worker temp
    FILES (not pipes): the parent polls liveness without reading, and
    a worker producing more output than a pipe buffer can never
    deadlock the reap path. Returns (procs, log file handles)."""
    child_env = dict(os.environ)
    child_env.update(worker_env(devices_per_proc))
    if env:
        child_env.update(env)
    tmpdir = tempfile.mkdtemp(prefix="slate_mp_")
    procs, logs = [], []
    for pid in range(num_processes):
        log = open(os.path.join(tmpdir, "worker%d.out" % pid), "w+")
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(pid), str(port),
             *map(str, extra_args)],
            stdout=log, stderr=subprocess.STDOUT,
            text=True, env=child_env))
        logs.append(log)
    return procs, logs, tmpdir


def _read_logs(logs, tmpdir: str) -> List[str]:
    """Slurp and CLOSE every worker log, then remove the launch's
    temp directory — the contents live on in the returned strings."""
    import shutil
    outs = []
    for f in logs:
        try:
            f.flush()
            f.seek(0)
            outs.append(f.read())
        finally:
            f.close()
    shutil.rmtree(tmpdir, ignore_errors=True)
    return outs


def launch(worker: str, num_processes: int = 2,
           extra_args: Sequence[str] = (),
           env: Optional[Dict[str, str]] = None,
           devices_per_proc: int = 4, timeout: int = 420,
           death_grace: float = DEATH_GRACE_S,
           ) -> Tuple[List[subprocess.Popen], List[str]]:
    """Run `worker` as `num_processes` coordinated jax processes and
    collect their outputs (the JSON result handshake is BOUNDED by
    `timeout` — a lost worker can no longer hang the parent forever).

    Reap-with-diagnostics (resil/, ISSUE 9): the parent POLLS the
    mesh. When one worker dies (nonzero exit — including a planned
    ``faults`` kill, exit :data:`~slate_tpu.resil.faults.KILL_EXIT_CODE`)
    while its siblings are still running, the survivors get
    `death_grace` seconds to exit on their own (a dead gloo peer
    usually wedges them in the next collective), then everything is
    killed AND reaped, and a structured
    :class:`~slate_tpu.resil.guard.WorkerLost` surfaces the dead
    worker's id, exit code, and output tail — instead of the old bare
    timeout after `timeout` seconds of silence. The overall deadline
    raises the same structured error naming the first still-running
    worker. Workers that ALL exit (even nonzero) return normally —
    :func:`assert_success` reports those with tails, as before. One
    retry with a fresh port covers the free-port bind race without
    masking real failures."""
    from ..resil.guard import WorkerLost
    for attempt in range(2):
        port = free_port()
        procs, logs, tmpdir = _spawn(worker, num_processes, port,
                                     extra_args, env,
                                     devices_per_proc)
        failed: Optional[Tuple[int, int]] = None
        fail_at = 0.0
        lost: Optional[Tuple[int, Optional[int]]] = None
        deadline = time.monotonic() + timeout
        while True:
            codes = [p.poll() for p in procs]
            if all(c is not None for c in codes):
                break
            now = time.monotonic()
            if failed is None:
                for pid, c in enumerate(codes):
                    if c is not None and c != 0:
                        failed = (pid, c)
                        fail_at = now
                        break
            if now >= deadline or (
                    failed is not None
                    and now - fail_at >= death_grace):
                alive = [i for i, c in enumerate(codes) if c is None]
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                lost = failed if failed is not None \
                    else (alive[0] if alive else 0, None)
                break
            time.sleep(0.05)
        outs = _read_logs(logs, tmpdir)
        # the bind-race retry must run on EVERY exit path: a losing
        # coordinator exits nonzero immediately while its siblings
        # block in connect, which lands here via the death-grace reap
        if attempt == 0 and any(
                p.returncode != 0 and "Address already in use" in out
                for p, out in zip(procs, outs)):
            continue
        if lost is not None:
            pid, rc = lost
            raise WorkerLost(pid, rc, tail=outs[pid], outs=outs)
        break
    return procs, outs


def assert_success(procs: Sequence[subprocess.Popen],
                   outs: Sequence[str]) -> None:
    """Every worker exited 0; failures carry the worker's tail."""
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            "worker %d rc=%s\n%s" % (pid, p.returncode, out[-3000:]))


# -- worker side ----------------------------------------------------------

def init(process_id: int, port: str, num_processes: int = 2,
         expect_devices: Optional[int] = None) -> None:
    """Join the coordinator and sanity-check the global device view.
    Call FIRST in a worker (before any jax computation; the pinned
    env comes from the parent via launch()).

    Resilience hooks (ISSUE 9): a fault plan serialized into
    ``SLATE_RESIL_FAULTS`` by the parent (faults.install_env_var in
    launch()'s env=) is installed here, and the ``worker`` injection
    site fires before the coordinator join — a ``kill`` rule scoped
    ``{"match": {"process": 1}}`` reproduces a worker that dies during
    launch/handshake."""
    from ..resil import faults as _faults
    _faults.install_from_env()
    _faults.check("worker", process=int(process_id))
    import jax
    platform = os.environ.get("JAX_PLATFORMS", "cpu")
    jax.config.update("jax_platforms", platform)
    if platform.startswith("cpu"):
        # cross-process CPU computations need the gloo collectives
        # backend selected BEFORE the distributed client comes up —
        # without it every process-spanning program dies with
        # "Multiprocess computations aren't implemented on the CPU
        # backend" (the silent rake the old per-test boilerplate
        # stepped on). Best-effort: the flag name is jax-version
        # dependent and TPU/GPU paths never need it.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            pass
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:%s" % port,
        num_processes=int(num_processes),
        process_id=int(process_id))
    devs = jax.devices()
    if expect_devices is not None:
        assert len(devs) == expect_devices, \
            "global device view has %d, expected %d" \
            % (len(devs), expect_devices)
    assert jax.process_count() == int(num_processes)


def startup(process_id: int, port: str, num_processes: int = 2,
            expect_devices: Optional[int] = None,
            share_tuning: bool = False):
    """init() + the standard mesh over every global device, optionally
    running the dist/tuneshare broadcast as part of startup (host 0's
    measured entries merged into THIS host's cache before any driver
    resolves a knob). Returns (grid, adopted_entry_count)."""
    init(process_id, port, num_processes, expect_devices)
    import jax
    import slate_tpu as st
    grid = st.make_grid(devices=jax.devices())
    adopted = 0
    if share_tuning:
        from ..dist.tuneshare import share_tuning_table
        adopted = share_tuning_table(grid)
    return grid, adopted


def emit(tag: str, **fields) -> None:
    """One structured handshake line on stdout (flushed — a killed
    worker still leaves everything emitted so far)."""
    print(_TAG + json.dumps({"tag": tag, **fields}, sort_keys=True),
          flush=True)


def results(out: str) -> Dict[str, dict]:
    """Parse a worker's stdout into {tag: record}."""
    recs: Dict[str, dict] = {}
    for line in out.splitlines():
        if line.startswith(_TAG):
            rec = json.loads(line[len(_TAG):])
            recs[rec.pop("tag")] = rec
    return recs


def emit_obs_delta(tag: str = "obs_delta", **fields) -> None:
    """One INCREMENTAL per-host obs-counters record over the result
    handshake (the PR 7 streaming-obs leftover, ISSUE 10 satellite):
    emits only the counters that CHANGED since this host's previous
    ``emit_obs_delta`` call, so a long sharded run streams its
    staging/broadcast progress line by line instead of one snapshot
    at exit. Per-host: each worker process keeps its own baseline
    (obs/metrics.counters_delta under one reserved name). The parent
    parses the lines with :func:`results` — callers give each emit a
    DISTINCT tag (e.g. ``obs_step3``), since results() keys by tag —
    and the summed deltas reconstruct the exact final counters
    (pinned by the 2-process test).

    Flight-recorder tail (ISSUE 14 satellite): when the obs/ledger.py
    recorder is on, the record also carries this host's ledger TAIL —
    every step record committed since the previous call, as compact
    dicts under ``"ledger"`` — so the parent sees per-host, per-step
    phase attribution streaming over the handshake (the per-host
    throughput feed the ROADMAP's elastic-mesh re-mapper needs).
    Recorder off (the FROZEN default): no key, byte-identical
    handshake lines."""
    from ..obs import ledger, metrics
    delta = metrics.counters_delta("multiproc.emit_obs_delta")
    payload = {"counters": {k: float(v)
                            for k, v in sorted(delta.items())}}
    recs = ledger.tail("multiproc.emit_obs_delta")
    if recs:
        payload["ledger"] = [r.to_dict() for r in recs]
    emit(tag, **payload, **fields)
