"""Reusable multi-process mesh fixture (ISSUE 7 satellite): the
distributed-init / env-pinning / mesh-construction / result-handshake
boilerplate that lived in tests/multihost_worker.py, promoted so every
multi-process test (the posv smoke, the sharded-OOC workers, future
tuneshare/obs coverage) runs through ONE startup path — the
prerequisite the ROADMAP's dist/tuneshare and streaming-obs items have
been waiting on.

Split of responsibilities:

  * the PARENT (a pytest test) calls :func:`launch` — it probes a free
    coordinator port (with one retry on the rare bind race), spawns
    ``python <worker.py> <process_id> <port>`` per process with the
    pinned environment (:func:`worker_env`: virtual CPU device count +
    JAX_PLATFORMS, set BEFORE the child ever imports jax), reaps on
    timeout, and returns (procs, outs);
  * the WORKER calls :func:`init` first thing — it joins the
    coordinator via ``jax.distributed.initialize`` and sanity-checks
    the global device view (importing slate_tpu does NOT initialize
    the jax backend, so the import order worker scripts naturally use
    is safe — the backend materializes at the first device query,
    which happens inside/after init);
  * results cross the process boundary as one-line JSON records
    (:func:`emit` / :func:`results`), so parents assert on structured
    values instead of grepping ad-hoc prints.

``share_tuning`` in :func:`startup` wires dist/tuneshare into the
startup path: host 0's measured autotuning entries broadcast over the
tree and best-entry-merge into every host's cache before the first
driver call — one probing host, identical routing everywhere
(covered by the 2-process test in tests/test_shard_multiproc.py).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Sequence, Tuple

#: worker handshake line prefix (parents parse with :func:`results`)
_TAG = "MP_RESULT "


def worker_env(devices_per_proc: int = 4,
               platform: str = "cpu") -> Dict[str, str]:
    """Environment pins a worker subprocess needs BEFORE importing
    jax: the virtual device count (read at backend init) and the
    platform. Merge over os.environ when spawning."""
    return {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=%d"
                     % int(devices_per_proc),
        "JAX_PLATFORMS": platform,
    }


def free_port() -> int:
    """A currently-free localhost port for the coordinator. Racy by
    nature (anything can bind it between close and the coordinator's
    own bind) — launch() retries once on the collision signature."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(worker: str, num_processes: int, port: int,
           extra_args: Sequence[str], env: Optional[Dict[str, str]],
           devices_per_proc: int) -> List[subprocess.Popen]:
    child_env = dict(os.environ)
    child_env.update(worker_env(devices_per_proc))
    if env:
        child_env.update(env)
    return [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), str(port),
             *map(str, extra_args)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=child_env)
        for pid in range(num_processes)
    ]


def launch(worker: str, num_processes: int = 2,
           extra_args: Sequence[str] = (),
           env: Optional[Dict[str, str]] = None,
           devices_per_proc: int = 4, timeout: int = 420,
           ) -> Tuple[List[subprocess.Popen], List[str]]:
    """Run `worker` as `num_processes` coordinated jax processes and
    collect their outputs. On timeout every child is killed and
    REAPED (a bare kill leaves zombies and a silent hang) and the
    partial outputs ride the AssertionError. One retry with a fresh
    port covers the free-port bind race without masking real
    failures."""
    for attempt in range(2):
        port = free_port()
        procs = _spawn(worker, num_processes, port, extra_args, env,
                       devices_per_proc)
        outs: List[str] = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=timeout)
                outs.append(out)
        except subprocess.TimeoutExpired:
            outs = []
            for p in procs:
                p.kill()
            for p in procs:
                out, _ = p.communicate()
                outs.append(out)
            raise AssertionError(
                "multiproc workers timed out\n" +
                "\n---\n".join(o[-2000:] for o in outs))
        if attempt == 0 and any(
                p.returncode != 0 and "Address already in use" in out
                for p, out in zip(procs, outs)):
            continue
        break
    return procs, outs


def assert_success(procs: Sequence[subprocess.Popen],
                   outs: Sequence[str]) -> None:
    """Every worker exited 0; failures carry the worker's tail."""
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            "worker %d rc=%s\n%s" % (pid, p.returncode, out[-3000:]))


# -- worker side ----------------------------------------------------------

def init(process_id: int, port: str, num_processes: int = 2,
         expect_devices: Optional[int] = None) -> None:
    """Join the coordinator and sanity-check the global device view.
    Call FIRST in a worker (before any jax computation; the pinned
    env comes from the parent via launch())."""
    import jax
    platform = os.environ.get("JAX_PLATFORMS", "cpu")
    jax.config.update("jax_platforms", platform)
    if platform.startswith("cpu"):
        # cross-process CPU computations need the gloo collectives
        # backend selected BEFORE the distributed client comes up —
        # without it every process-spanning program dies with
        # "Multiprocess computations aren't implemented on the CPU
        # backend" (the silent rake the old per-test boilerplate
        # stepped on). Best-effort: the flag name is jax-version
        # dependent and TPU/GPU paths never need it.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            pass
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:%s" % port,
        num_processes=int(num_processes),
        process_id=int(process_id))
    devs = jax.devices()
    if expect_devices is not None:
        assert len(devs) == expect_devices, \
            "global device view has %d, expected %d" \
            % (len(devs), expect_devices)
    assert jax.process_count() == int(num_processes)


def startup(process_id: int, port: str, num_processes: int = 2,
            expect_devices: Optional[int] = None,
            share_tuning: bool = False):
    """init() + the standard mesh over every global device, optionally
    running the dist/tuneshare broadcast as part of startup (host 0's
    measured entries merged into THIS host's cache before any driver
    resolves a knob). Returns (grid, adopted_entry_count)."""
    init(process_id, port, num_processes, expect_devices)
    import jax
    import slate_tpu as st
    grid = st.make_grid(devices=jax.devices())
    adopted = 0
    if share_tuning:
        from ..dist.tuneshare import share_tuning_table
        adopted = share_tuning_table(grid)
    return grid, adopted


def emit(tag: str, **fields) -> None:
    """One structured handshake line on stdout (flushed — a killed
    worker still leaves everything emitted so far)."""
    print(_TAG + json.dumps({"tag": tag, **fields}, sort_keys=True),
          flush=True)


def results(out: str) -> Dict[str, dict]:
    """Parse a worker's stdout into {tag: record}."""
    recs: Dict[str, dict] = {}
    for line in out.splitlines():
        if line.startswith(_TAG):
            rec = json.loads(line[len(_TAG):])
            recs[rec.pop("tag")] = rec
    return recs
