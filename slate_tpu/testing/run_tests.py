"""Sweep orchestrator (reference test/run_tests.py:41-60): named
routine groups, size classes, grid sweeps and junit XML output.

Usage:
    python -m slate_tpu.testing.run_tests --quick
    python -m slate_tpu.testing.run_tests chol lu --medium \
        --grid 1x1,2x4 --xml results.xml
"""

from __future__ import annotations

import argparse
import sys
import time
import xml.etree.ElementTree as ET

#: named routine groups (reference run_tests.py routine lists)
GROUPS = {
    "blas3": ["gemm", "gbmm"],
    "chol": ["potrf", "posv", "pbsv"],
    "lu": ["getrf", "gesv", "gbsv"],
    "qr": ["geqrf", "gels"],
    "eig": ["heev"],
    "svd": ["svd"],
    "indefinite": ["hesv"],
}
ALL = [r for g in GROUPS.values() for r in g]

#: size classes (reference --quick/--small/--medium/--large)
SIZES = {
    "quick": ("64:128:*2", "32"),
    "small": ("128:256:*2", "32,64"),
    "medium": ("256:1024:*2", "64,128"),
    "large": ("1024:4096:*2", "256,512"),
}


def write_junit(rows, path: str, elapsed: float) -> None:
    suite = ET.Element(
        "testsuite", name="slate_tpu.tester",
        tests=str(len(rows)),
        failures=str(sum(r["status"] == "FAILED" for r in rows)),
        time=f"{elapsed:.3f}")
    for r in rows:
        case = ET.SubElement(
            suite, "testcase",
            classname=f"tester.{r['routine']}",
            name=f"{r['routine']}_{r['dtype']}_n{r['n']}_nb{r['nb']}"
                 f"_g{r['grid']}",
            time=f"{r['time']:.3f}")
        if r["status"] == "FAILED":
            f = ET.SubElement(
                case, "failure",
                message=r.get("detail") or f"error={r['error']}")
            f.text = str(r)
    ET.ElementTree(suite).write(path, encoding="unicode",
                                xml_declaration=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("groups", nargs="*", default=[],
                   help=f"routine groups or names ({','.join(GROUPS)})")
    for s in SIZES:
        p.add_argument(f"--{s}", action="store_true")
    p.add_argument("--dim", default=None, help="explicit dim override")
    p.add_argument("--nb", default=None)
    p.add_argument("--type", default="s", dest="types")
    p.add_argument("--grid", default="1x1")
    p.add_argument("--ref", default="n")
    p.add_argument("--xml", default=None, help="junit XML output path")
    args = p.parse_args(argv)

    size = next((s for s in SIZES if getattr(args, s)), "quick")
    dim, nb = SIZES[size]
    dim = args.dim or dim
    nb = args.nb or nb

    routines = []
    for g in (args.groups or list(GROUPS)):
        if g in GROUPS:
            routines.extend(GROUPS[g])
        elif g in ALL:
            routines.append(g)
        else:
            p.error(f"unknown routine/group {g!r} "
                    f"(groups: {', '.join(GROUPS)}; "
                    f"routines: {', '.join(ALL)})")

    from .tester import sweep
    t0 = time.perf_counter()
    rows = sweep(routines, dim, args.types, nb, args.grid,
                 check=True, ref=args.ref == "y")
    elapsed = time.perf_counter() - t0
    nfail = sum(r["status"] == "FAILED" for r in rows)
    if args.xml:
        write_junit(rows, args.xml, elapsed)
        print(f"junit written to {args.xml}")
    print(f"\n{len(rows)} configs, "
          f"{'all passed' if nfail == 0 else f'{nfail} FAILED'} "
          f"({elapsed:.1f}s)")
    return 1 if nfail else 0


if __name__ == "__main__":
    sys.exit(main())
