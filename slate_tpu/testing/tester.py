"""Parameter-sweep tester CLI (reference test/ `tester` binary on
TestSweeper + test/run_tests.py; SURVEY §4 tier 2).

Sweeps routine x dim x dtype x block size x grid, times each config,
computes GFLOP/s and a residual check (reference-style error bounds, or
--ref y to compare against numpy/scipy on gathered arrays — the
ScaLAPACK-compare role).

Usage:
    python -m slate_tpu.testing.tester gemm potrf --dim 256:1024:*2 \
        --type s,d --nb 64,128 --grid 1x1 --check y
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Callable, Dict

import numpy as np

DTYPES = {"s": np.float32, "d": np.float64,
          "c": np.complex64, "z": np.complex128}


def _parse_dims(spec: str):
    out = []
    for part in spec.split(","):
        if ":" in part:
            lo, hi, step = part.split(":")
            lo, hi = int(lo), int(hi)
            if step.startswith("*"):
                f = int(step[1:])
                v = lo
                while v <= hi:
                    out.append(v)
                    v *= f
            else:
                out.extend(range(lo, hi + 1, int(step)))
        else:
            out.append(int(part))
    return out


def _gflops(routine: str, m: int, n: int, k: int) -> float:
    f = {
        "gemm": 2.0 * m * n * k,
        "potrf": m ** 3 / 3.0,
        "posv": m ** 3 / 3.0 + 2.0 * m * m * k,
        "getrf": 2.0 * m ** 3 / 3.0,
        "gesv": 2.0 * m ** 3 / 3.0 + 2.0 * m * m * k,
        "geqrf": 2.0 * m * n * n - 2.0 * n ** 3 / 3.0,
        "gels": 2.0 * m * n * n,
        "trsm": 1.0 * m * m * k,
        "herk": 1.0 * m * m * k,
        "heev": 4.0 * m ** 3 / 3.0,
        "svd": 4.0 * m * n * min(m, n),
        "hesv": m ** 3 / 3.0 + 2.0 * m * m * k,
        # band routines: FLOPs depend on kd; the sweep reports time
        # only (gflops column 0), like the reference tester's norm rows
    }.get(routine, 0.0)
    return f / 1e9


def _mk_band(a, kd):
    """Zero a outside the band |i - j| <= kd (no index-array scratch)."""
    return np.triu(np.tril(a, kd), -kd)


def run_one(routine: str, n: int, dtype, nb: int, check: bool,
            ref: bool, seed: int = 42, grid=None) -> Dict:
    """Run one (routine, n, dtype, nb[, grid]) config. With a
    ProcessGrid, inputs are device_put on the mesh and the drivers get
    Option.Grid + MethodFactor.Tiled — the reference tester's `-p -q`
    grid sweep (test.cc:685)."""
    import dataclasses as _dc

    import jax
    import slate_tpu as st
    from slate_tpu.core.methods import MethodFactor
    from slate_tpu.core.options import Option

    opts = None
    if grid is not None:
        opts = {Option.Grid: grid, Option.MethodFactor:
                MethodFactor.Tiled}

    def place(M):
        if grid is None:
            return M
        return _dc.replace(
            M, data=jax.device_put(M.data, grid.matrix_sharding()))

    rng = np.random.default_rng(seed)
    real = np.float64 if dtype in (np.float64, np.complex128) \
        else np.float32
    eps = np.finfo(real).eps

    def mk(shape, herm=False, spd=False):
        a = rng.standard_normal(shape)
        if np.issubdtype(dtype, np.complexfloating):
            a = a + 1j * rng.standard_normal(shape)
        if spd:
            a = a @ a.conj().T / shape[0] + 4 * np.eye(shape[0])
        elif herm:
            a = (a + a.conj().T) / 2
        return a.astype(dtype)

    nrhs = 10
    t0 = time.perf_counter()
    err = None
    if routine == "gemm":
        a, b, c = mk((n, n)), mk((n, n)), mk((n, n))
        C = st.gemm(1.0, place(st.Matrix(a, mb=nb)),
                    place(st.Matrix(b, mb=nb)),
                    0.0, place(st.Matrix(c, mb=nb)), opts)
        out = C.to_numpy()
        t = time.perf_counter() - t0
        if check:
            err = np.linalg.norm(out - a @ b) / (
                np.linalg.norm(a) * np.linalg.norm(b) * n * eps)
        if ref:
            # --ref y: direct comparison to the numpy result (the
            # reference tester's ScaLAPACK-compare role)
            err = np.linalg.norm(out - a @ b) / (
                np.linalg.norm(a @ b) * n * eps + 1e-300)
    elif routine in ("potrf", "posv"):
        a = mk((n, n), spd=True)
        A = place(st.HermitianMatrix(st.Uplo.Lower, a, mb=nb))
        if routine == "potrf":
            L = st.potrf(A, opts)
            out = L.to_numpy()
            t = time.perf_counter() - t0
            if check:
                err = np.linalg.norm(out @ out.conj().T - a) / (
                    np.linalg.norm(a) * n * eps)
            if ref:
                lref = np.linalg.cholesky(a)
                err = np.linalg.norm(np.tril(out) - lref) / (
                    np.linalg.norm(lref) * n * eps + 1e-300)
        else:
            b = mk((n, nrhs))
            _, X = st.posv(A, place(st.Matrix(b, mb=nb)), opts)
            x = X.to_numpy()
            t = time.perf_counter() - t0
            if check:
                err = np.linalg.norm(b - a @ x) / (
                    np.linalg.norm(a) * np.linalg.norm(x) * n * eps)
            if ref:
                xr = np.linalg.solve(a, b)
                err = np.linalg.norm(x - xr) / (
                    np.linalg.norm(xr) * n * eps
                    * max(np.linalg.cond(a), 1.0))
    elif routine in ("getrf", "gesv"):
        a = mk((n, n))
        if routine == "getrf":
            F = st.getrf(place(st.Matrix(a, mb=nb)), opts)
            out = F.LU.to_numpy()
            t = time.perf_counter() - t0
            if check:
                lu = out
                L = np.tril(lu, -1) + np.eye(n)
                U = np.triu(lu)
                pa = a.copy()
                piv = np.asarray(F.pivots)[:n]
                for j in range(n):
                    pa[[j, piv[j]]] = pa[[piv[j], j]]
                err = np.linalg.norm(L @ U - pa) / (
                    np.linalg.norm(a) * n * eps)
            if ref:
                # external reference via SOLVES: element-wise factor
                # comparison against scipy assumes identical pivot
                # choices, which near-tie magnitudes legitimately
                # break. Solving the same rhs through both factor
                # stacks compares the factorizations' actual function
                # while staying pivot-choice-independent.
                import scipy.linalg as _sla
                b = mk((n, nrhs))
                xr = _sla.lu_solve(_sla.lu_factor(a), b)
                x = st.getrs(F, place(st.Matrix(b, mb=nb)),
                             opts).to_numpy()
                err = np.linalg.norm(x - xr) / (
                    np.linalg.norm(xr) * n * eps
                    * max(np.linalg.cond(a), 1.0) + 1e-300)
        else:
            b = mk((n, nrhs))
            _, X = st.gesv(place(st.Matrix(a, mb=nb)),
                           place(st.Matrix(b, mb=nb)), opts)
            x = X.to_numpy()
            t = time.perf_counter() - t0
            if check:
                err = np.linalg.norm(b - a @ x) / (
                    np.linalg.norm(a) * np.linalg.norm(x) * n * eps)
            if ref:
                xr = np.linalg.solve(a, b)
                err = np.linalg.norm(x - xr) / (
                    np.linalg.norm(xr) * n * eps
                    * max(np.linalg.cond(a), 1.0))
    elif routine in ("geqrf", "gels"):
        m2 = n
        a = mk((m2, n))
        if routine == "geqrf":
            F = st.geqrf(place(st.Matrix(a, mb=nb)), opts)
            t = time.perf_counter() - t0
            if check:
                R = np.triu(F.QR.to_numpy())
                from slate_tpu import Side
                eye = np.eye(m2, dtype=dtype)
                Q = st.unmqr(Side.Left, F, st.Matrix(eye, mb=nb),
                             trans=False).to_numpy()
                err = np.linalg.norm(Q @ R - a) / (
                    np.linalg.norm(a) * n * eps)
        else:
            b = mk((m2, nrhs))
            X = st.gels(place(st.Matrix(a, mb=nb)),
                        place(st.Matrix(b, mb=nb)), opts)
            x = X.to_numpy()[:n]
            t = time.perf_counter() - t0
            if check:
                # normal-equations residual for LS solutions
                rr = b - a @ x
                err = np.linalg.norm(a.conj().T @ rr) / (
                    np.linalg.norm(a) ** 2 * np.linalg.norm(x) * n * eps)
            if ref:
                xr = np.linalg.lstsq(a, b, rcond=None)[0]
                err = np.linalg.norm(x - xr) / (
                    np.linalg.norm(xr) * n * eps
                    * max(np.linalg.cond(a), 1.0))
    elif routine == "heev":
        a = mk((n, n), herm=True)
        A = place(st.HermitianMatrix(st.Uplo.Lower, a, mb=nb))
        w, V = st.heev(A, opts)
        t = time.perf_counter() - t0
        if check:
            v = V.to_numpy()
            err = np.linalg.norm(a @ v - v * np.asarray(w)[None, :]) / (
                np.linalg.norm(a) * n * eps)
        if ref:
            wr = np.linalg.eigvalsh(a)
            err = np.linalg.norm(np.asarray(w)[:n] - wr) / (
                np.linalg.norm(wr) * n * eps + 1e-300)
    elif routine == "svd":
        a = mk((n, n))
        s, U, Vh = st.svd(place(st.Matrix(a, mb=nb)), opts)
        t = time.perf_counter() - t0
        if check:
            rec = (U.to_numpy() * np.asarray(s)[None, :]) @ Vh.to_numpy()
            err = np.linalg.norm(rec - a) / (np.linalg.norm(a) * n * eps)
        if ref:
            sr = np.linalg.svd(a, compute_uv=False)
            err = np.linalg.norm(np.asarray(s)[: len(sr)] - sr) / (
                np.linalg.norm(sr) * n * eps + 1e-300)
    elif routine == "hesv":
        a = mk((n, n), herm=True)        # indefinite
        b = mk((n, nrhs))
        A = place(st.HermitianMatrix(st.Uplo.Lower, a, mb=nb))
        _, X = st.hesv(A, place(st.Matrix(b, mb=nb)), opts)
        x = X.to_numpy()
        t = time.perf_counter() - t0
        if check:
            err = np.linalg.norm(b - a @ x) / (
                np.linalg.norm(a) * np.linalg.norm(x) * n * eps)
        if ref:
            xr = np.linalg.solve(a, b)
            err = np.linalg.norm(x - xr) / (
                np.linalg.norm(xr) * n * eps
                * max(np.linalg.cond(a), 1.0))
    elif routine in ("gbsv", "pbsv"):
        kd = max(min(nb // 2, n // 4), 1)
        a = _mk_band(mk((n, n)), kd)
        if routine == "pbsv":
            a = ((a + a.conj().T) / 2
                 + 4 * np.sqrt(n) * np.eye(n)).astype(dtype)
            A = place(st.HermitianBandMatrix(st.Uplo.Lower, kd, a,
                                             mb=nb))
            solve = st.pbsv
        else:
            a = (a + 4 * np.eye(n, dtype=dtype)).astype(dtype)
            A = place(st.BandMatrix(kd, kd, a, mb=nb))
            solve = st.gbsv
        b = mk((n, nrhs))
        _, X = solve(A, place(st.Matrix(b, mb=nb)), opts)
        x = X.to_numpy()
        t = time.perf_counter() - t0
        if check:
            err = np.linalg.norm(b - a @ x) / (
                np.linalg.norm(a) * np.linalg.norm(x) * n * eps)
        if ref:
            import scipy.linalg as _sla
            if routine == "pbsv":
                ab = np.zeros((kd + 1, n), a.dtype)
                for i in range(kd + 1):
                    ab[i, : n - i] = np.diagonal(a, -i)
                xr = _sla.solveh_banded(ab, b, lower=True)
            else:
                ab = np.zeros((2 * kd + 1, n), a.dtype)
                for i in range(-kd, kd + 1):
                    row = kd - i
                    if i >= 0:
                        ab[row, i:] = np.diagonal(a, i)
                    else:
                        ab[row, : n + i] = np.diagonal(a, i)
                xr = _sla.solve_banded((kd, kd), ab, b)
            err = np.linalg.norm(x - xr) / (
                np.linalg.norm(xr) * n * eps
                * max(np.linalg.cond(a), 1.0))
    elif routine == "gbmm":
        kd = max(min(nb // 2, n // 4), 1)
        a = _mk_band(mk((n, n)), kd).astype(dtype)
        b = mk((n, n))
        A = place(st.BandMatrix(kd, kd, a, mb=nb))
        C = st.gbmm(1.0, A, place(st.Matrix(b, mb=nb)), 0.0,
                    place(st.Matrix(np.zeros_like(b), mb=nb)), opts)
        out = C.to_numpy()
        t = time.perf_counter() - t0
        if check or ref:
            # the numpy product IS the external reference here
            err = np.linalg.norm(out - a @ b) / (
                np.linalg.norm(a) * np.linalg.norm(b) * n * eps
                + 1e-300)
    else:
        # ValueError (not SystemExit) so sweep() records one FAILED row
        # and the rest of the sweep still runs
        raise ValueError(f"unknown routine {routine}")

    k_inner = n if routine == "gemm" else nrhs
    gf = _gflops(routine, n, n, k_inner) / t if t > 0 else 0.0
    status = "pass" if (err is None or err < 100) else "FAILED"
    return dict(routine=routine, n=n, dtype=np.dtype(dtype).name, nb=nb,
                time=t, gflops=gf, error=err, status=status)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("routines", nargs="+")
    p.add_argument("--dim", default="256")
    p.add_argument("--type", default="s", dest="types")
    p.add_argument("--nb", default="64")
    p.add_argument("--grid", default="1x1",
                   help="p x q process grid (uses available jax devices)")
    p.add_argument("--check", default="y")
    p.add_argument("--ref", default="n")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write a Perfetto/Chrome trace JSON of the "
                        "sweep (obs event bus: driver spans, phases, "
                        "tuner decisions) to PATH")
    args = p.parse_args(argv)

    if args.trace_out:
        from .. import obs
        obs.enable()

    # fail fast on a dead TPU tunnel (backend init hangs in C code):
    # probe in a subprocess, fall back to CPU with a loud note
    from ..utils.backend import force_cpu, probe_backend
    ok, info = probe_backend()
    if ok:
        print(f"# backend: {info}")
    else:
        print(f"# WARNING: ambient backend unavailable ({info}); "
              "falling back to CPU", file=sys.stderr)
        force_cpu()

    rows = sweep(args.routines, args.dim, args.types, args.nb,
                 args.grid, args.check == "y", args.ref == "y")
    nfail = sum(r["status"] == "FAILED" for r in rows)
    print(f"\n{'All tests passed' if nfail == 0 else f'{nfail} FAILED'}")
    if args.trace_out:
        from ..obs import export as obs_export
        obs_export.write_trace(args.trace_out, clear=True)
        print(f"# trace written: {args.trace_out}")
    return 1 if nfail else 0


def _parse_grids(spec: str):
    """'1x1,2x4' -> ProcessGrid list; grids needing more devices than
    available are skipped with a note (the reference Jenkinsfile-mpi
    runs the same sweep at --np 4)."""
    import jax

    from ..parallel.mesh import make_grid
    grids = []
    nd = len(jax.devices())
    for part in spec.split(","):
        p, q = (int(x) for x in part.lower().split("x"))
        if p * q > nd:
            print(f"# grid {p}x{q} skipped: only {nd} devices")
            continue
        grids.append(make_grid(p, q) if p * q > 1 else None)
    return grids or [None]


def sweep(routines, dim_spec, type_spec, nb_spec, grid_spec,
          check=True, ref=False, out=sys.stdout):
    """The full sweep loop, reusable by run_tests.py; returns result
    row dicts (each also carries 'grid')."""
    dims = _parse_dims(dim_spec)
    nbs = [int(x) for x in nb_spec.split(",")]
    types = [DTYPES[t] for t in type_spec.split(",")]
    grids = _parse_grids(grid_spec)

    header = (f"{'routine':10s} {'type':8s} {'n':>7s} {'nb':>5s} "
              f"{'grid':>6s} {'time(s)':>9s} {'gflops':>9s} "
              f"{'error':>10s}  status")
    print(header, file=out)
    print("-" * len(header), file=out)
    rows = []
    for routine in routines:
        for dtype in types:
            for n in dims:
                for nb in nbs:
                    for grid in grids:
                        gname = "1x1" if grid is None \
                            else f"{grid.p}x{grid.q}"
                        try:
                            r = run_one(routine, n, dtype, nb, check,
                                        ref, grid=grid)
                        except Exception as e:   # noqa: BLE001
                            r = dict(routine=routine, n=n,
                                     dtype=np.dtype(dtype).name, nb=nb,
                                     time=0.0, gflops=0.0, error=None,
                                     status="FAILED",
                                     detail=f"{type(e).__name__}: {e}")
                        r["grid"] = gname
                        err = "-" if r["error"] is None \
                            else f"{r['error']:.2e}"
                        shown = r["status"] if r["status"] == "pass" \
                            else (r.get("detail", r["status"])[:40]
                                  or "FAILED")
                        print(f"{r['routine']:10s} {r['dtype']:8s} "
                              f"{n:7d} {nb:5d} {gname:>6s} "
                              f"{r['time']:9.3f} {r['gflops']:9.1f} "
                              f"{err:>10s}  {shown}", file=out)
                        if r["status"] != "pass":
                            r["status"] = "FAILED"
                        rows.append(r)
    return rows


if __name__ == "__main__":
    sys.exit(main())
