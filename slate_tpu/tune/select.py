"""Selection layer (ISSUE 1 tentpole, part 3): the single decision
path every driver consults for a tunable knob.

Resolution precedence, strictly:

  1. an EXPLICIT user option (``opts[Option.BlockSize]`` etc.) always
     wins — tuning never overrides the caller;
  2. a MEASURED cache entry for (op, backend, device, dtype, bucket),
     when tuning is enabled (``SLATE_TPU_TUNE`` != 0 and the per-call
     ``Option.Tune`` is not False);
  3. the FROZEN shipped default (cache.FROZEN), or the caller-supplied
     ``fallback`` — the caller's pre-tune formula — when the knob's
     default is shape-dependent rather than a constant.

Every decision is counted in tune.stats (and marked on the
utils/trace.py timeline when tracing is on), so a bench run can show
exactly which knobs came from measurement.
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional

from . import cache as _cache
from . import stats

_UNSET = object()

#: process-wide bypass used by bench.py --tune to measure the
#: "before" (frozen-defaults) configuration without touching env vars
_disabled_depth = 0


@contextlib.contextmanager
def disabled():
    """Temporarily bypass cached entries (explicit options and frozen
    defaults still apply) — the before/after switch of bench --tune."""
    global _disabled_depth
    _disabled_depth += 1
    try:
        yield
    finally:
        _disabled_depth -= 1


def _tuning_active(opts) -> bool:
    if _disabled_depth > 0 or not _cache.enabled():
        return False
    from ..core.options import Option, get_option
    return bool(get_option(opts, Option.Tune, True))


def resolve(op: str, param: str, *, opts=None, option=None,
            n: Optional[int] = None, dtype=None,
            fallback: Any = _UNSET) -> Any:
    """Resolve one tunable knob (module doc precedence). `option` is
    the core Option key whose explicit presence in `opts` short-
    circuits tuning; `fallback` is the caller's pre-tune default
    (value, not factory — compute it before the call)."""
    from ..core.options import has_option
    if option is not None and has_option(opts, option):
        from ..core.options import get_option
        v = get_option(opts, option)
        stats.record_decision(op, param, "explicit", v)
        return v
    if _tuning_active(opts):
        v = _cache.get_cache().get_param(op, param, dtype, n)
        if v is not None:
            stats.record_decision(op, param, "cached", v)
            return v
    # the caller's `fallback` IS the shipped default (often a shape-
    # dependent formula); the FROZEN table only serves callers without
    # one — never override a supplied fallback, or cold start would
    # not be bit-identical to the pre-tune routing
    v = fallback if fallback is not _UNSET \
        else _cache.frozen_default(op, param)
    stats.record_decision(op, param, "frozen", v)
    return v


def tuned_int(op: str, param: str, fallback: int, *, opts=None,
              option=None, n=None, dtype=None) -> int:
    """resolve() for integer knobs (block sizes, thresholds, panel
    widths): whatever source wins is coerced to int."""
    v = resolve(op, param, opts=opts, option=option, n=n, dtype=dtype,
                fallback=fallback)
    return int(v)


def tuned_method(op: str, family: str, *, opts=None, option=None,
                 n=None, dtype=None):
    """Method-routing knob: returns a methods.py enum member, or None
    when nothing is cached (caller keeps its Auto heuristic — the
    frozen behavior). Cached values are the enum .value strings
    ("summa", "qr_iteration", ...); an unknown string is ignored
    rather than fatal (a newer cache against an older tree)."""
    from ..core.options import has_option
    if option is not None and has_option(opts, option):
        # explicit methods are handled by the caller's own get_option
        # path before it asks Auto resolution; nothing for us to do
        return None
    if not _tuning_active(opts):
        return None
    v = _cache.get_cache().get_param(op, "method_" + family, dtype, n)
    if v is None:
        return None
    try:
        from ..core.methods import str2method
        m = str2method(family, str(v))
    except KeyError:
        return None
    stats.record_decision(op, "method_" + family, "cached", v)
    return m
