"""Microbenchmark driver (ISSUE 1 tentpole, part 1): measures
candidate configurations on the LIVE backend and writes the winners
into the persistent cache.

Timing discipline (the part that makes numbers trustworthy):

  * warmup / steady state separated — the first call of every
    candidate compiles (jit cache fill) and is EXCLUDED from timing;
  * jit-cache-aware repetition — every timed repetition re-enters the
    same compiled executable, so reps measure run time, not trace
    time; the reported figure is the min over reps (noise floor);
  * too-fast guards — when one call is below `min_time`, calls are
    chained until the measured span is above it, and the per-call
    time is the span divided by the chain length.

Probing is NEVER automatic: it runs only through `autotune()` (or
``python bench.py --tune``). Normal driver calls only READ the cache
(tune/select.py), so the cold-start path stays allocation- and
probe-free.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, List, Optional, Sequence

from . import cache as _cache
from . import stats


#: a probed winner must beat the default baseline by this relative
#: margin before it is persisted — noise-level "wins" (including over
#: a candidate configuration identical to the default) stay uncached
WIN_MARGIN = 0.02


def measure(fn, warmup: int = 1, reps: int = 3,
            min_time: float = 0.02) -> float:
    """Steady-state seconds per call of zero-arg `fn` (module doc)."""
    import jax
    for _ in range(max(warmup, 1)):
        jax.block_until_ready(fn())          # compile + cache fill
    # size the chain so one rep's span is measurable
    t0 = time.perf_counter()
    jax.block_until_ready(fn())
    once = time.perf_counter() - t0
    k = max(1, int(min_time / max(once, 1e-9)))
    best = float("inf")
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = fn()
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / k)
    return best


def _spd(n: int, dtype):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def gen():
        x = jax.random.normal(jax.random.PRNGKey(0), (n, n),
                              jnp.float32)
        s = jnp.matmul(x, x.T,
                       precision=jax.lax.Precision.HIGHEST) / n \
            + 4.0 * jnp.eye(n, dtype=jnp.float32)
        return x.astype(dtype), s.astype(dtype)
    x, s = gen()
    jax.block_until_ready(s)
    return x, s


def _tiled(data, mtype, uplo, nb):
    from ..core.enums import Diag, MatrixType, Op, Uplo
    from ..core.tiles import TiledMatrix
    return TiledMatrix(data=data, m=data.shape[0], n=data.shape[1],
                       mb=nb, nb=nb, mtype=mtype, uplo=uplo,
                       op=Op.NoTrans, diag=Diag.NonUnit)


def _blocksize_runner(op: str, n: int, dtype):
    """Build the op's timed closure factory: cand -> zero-arg fn.
    The candidate block size enters through the channel the driver
    actually tunes on: getrf/geqrf through Option.BlockSize; for
    potrf through the tile geometry (Tiled method) — NOTE the potrf
    winner is ADVISORY (tile-size guidance for callers): the potrf
    driver takes its block size from the caller's tiles, so a cached
    potrf "nb" is never auto-selected (only potrf's lookahead /
    method_factor entries are). cand=None measures the driver's own
    default configuration (no explicit block size) — the before
    baseline of bench.py --tune."""
    from ..core.enums import MatrixType, Uplo
    from ..core.methods import MethodFactor
    from ..core.options import Option
    from .. import linalg
    x, spd = _spd(n, dtype)

    if op == "potrf":
        def mk(cand):
            A = _tiled(spd, MatrixType.Hermitian, Uplo.Lower,
                       cand or 256)
            opts = {Option.MethodFactor: MethodFactor.Tiled}
            return lambda: linalg.potrf(A, opts).data
        return mk
    if op == "getrf":
        def mk(cand):
            G = _tiled(x, MatrixType.General, Uplo.General,
                       min(256, n))
            opts = {Option.BlockSize: cand} if cand else None
            return lambda: linalg.getrf(G, opts).LU.data
        return mk
    if op == "geqrf":
        def mk(cand):
            G = _tiled(x, MatrixType.General, Uplo.General,
                       min(256, n))
            # cand=None is the TRUE Auto default (which routes Fused
            # below the fused_max_n crossover); candidates pin Tiled
            # with an explicit width — a Tiled winner is cached
            # together with fused_max_n=0 so the driver actually
            # routes to it (autotune)
            opts = ({Option.BlockSize: cand,
                     Option.MethodFactor: MethodFactor.Tiled}
                    if cand else None)
            return lambda: linalg.geqrf(G, opts).QR.data
        return mk
    raise KeyError("probe_blocksize: unknown op %r" % op)


def probe_blocksize(op: str, n: int, dtype,
                    candidates: Sequence[int],
                    reps: int = 3) -> List[Dict]:
    """Time `op` at size n for the driver's OWN default configuration
    (entry {"nb": None}, measured with cached entries bypassed — the
    cold-cache baseline every winner must beat) plus every candidate
    nb. Returns fastest first."""
    from ..utils import trace
    from . import select as _select
    t0 = time.perf_counter()
    mk = _blocksize_runner(op, n, dtype)
    out = []
    with trace.block("tune::probe::%s" % op):
        with _select.disabled():
            out.append({"nb": None, "seconds": measure(mk(None),
                                                       reps=reps)})
        for cand in candidates:
            t = measure(mk(int(cand)), reps=reps)
            out.append({"nb": int(cand), "seconds": t})
    stats.add_probe_time(time.perf_counter() - t0)
    return sorted(out, key=lambda d: d["seconds"])


def probe_method_eig(n: int, dtype, reps: int = 2) -> List[Dict]:
    """Time heev's Auto DEFAULT route (the fused QDWH path — the
    baseline a cached decision must beat) against the explicitly
    routed staged pipelines (MethodEig.DC = two-stage Cuppen,
    MethodEig.QRIteration = two-stage QR iteration) at size n.
    Returns results fastest first; "auto" winning means KEEP the
    default (autotune caches nothing in that case, so a probe can
    never regress Auto below the cold-cache behavior). Runs under
    select.disabled() so the Auto measurement is the frozen default,
    not a previously-cached reroute."""
    from ..core.enums import MatrixType, Uplo
    from ..core.methods import MethodEig
    from ..core.options import Option
    from ..utils import trace
    from .. import linalg
    from . import select as _select
    t0 = time.perf_counter()
    _, spd = _spd(n, dtype)
    A = _tiled(spd, MatrixType.Hermitian, Uplo.Lower, min(128, n))
    candidates = [
        ("auto", None),
        ("dc", {Option.MethodEig: MethodEig.DC}),
        ("qr_iteration", {Option.MethodEig: MethodEig.QRIteration}),
    ]
    out = []
    with trace.block("tune::probe::heev"), _select.disabled():
        for label, mopts in candidates:
            t = measure(
                lambda mo=mopts: linalg.heev(A, mo).values,
                reps=reps)
            out.append({"method": label, "seconds": t})
    stats.add_probe_time(time.perf_counter() - t0)
    return sorted(out, key=lambda d: d["seconds"])


def probe_lu_panel(m: int, w: int, dtype, reps: int = 3) -> List[Dict]:
    """Time the LU panel-route candidates at (m, w) (ISSUE 6): the
    cold-default route (entry {"method": None} — lu._lu_panel with
    cached entries bypassed, the baseline a winner must beat), the
    masked fori kernel, and the two Pallas kernels (rank-1 `pallas`,
    block-recursive `pallas_rec`) where their entry gates accept.
    Fastest first; a persisted winner reroutes _lu_panel for the
    whole (backend, device, dtype, bucket) class — and through it
    every LU consumer."""
    import jax
    import jax.numpy as jnp
    from ..linalg.lu import _lu_panel, lu_panel_fori
    from ..ops import pallas_kernels as pk
    from ..utils import trace
    from . import select as _select
    t0 = time.perf_counter()
    key = jax.random.PRNGKey(0)
    p = jax.random.normal(key, (m, w), jnp.float32).astype(dtype)
    out = []
    with trace.block("tune::probe::lu_panel"):
        with _select.disabled():
            out.append({"method": None,
                        "seconds": measure(lambda: _lu_panel(p)[0],
                                           reps=reps)})
        out.append({"method": "fori",
                    "seconds": measure(lambda: lu_panel_fori(p)[0],
                                       reps=reps)})
        for label, fn in (("pallas", pk.lu_panel),
                          ("pallas_rec", pk.lu_panel_rec)):
            if fn(p) is None:        # entry gate rejected this shape
                continue
            out.append({"method": label,
                        "seconds": measure(lambda fn=fn: fn(p)[0],
                                           reps=reps)})
    stats.add_probe_time(time.perf_counter() - t0)
    return sorted(out, key=lambda d: d["seconds"])


def probe_ooc_panel(n: int, candidates: Sequence[int],
                    reps: int = 2) -> List[Dict]:
    """Time the streamed Cholesky at the frozen default width (entry
    {"panel_cols": None}, resolved by the driver with cached entries
    bypassed — the cold-cache baseline) and at each candidate panel
    width (host-resident input, the ooc.py contract); fastest
    first."""
    import numpy as np
    from ..linalg.ooc import potrf_ooc
    from ..utils import trace
    from . import select as _select
    t0 = time.perf_counter()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, n)).astype(np.float32)
    a = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)
    out = []

    def timed(cand):
        best = float("inf")
        potrf_ooc(a, panel_cols=cand)                 # compile fill
        for _ in range(max(reps, 1)):
            t1 = time.perf_counter()
            potrf_ooc(a, panel_cols=cand)
            best = min(best, time.perf_counter() - t1)
        return best

    with trace.block("tune::probe::ooc"):
        with _select.disabled():
            out.append({"panel_cols": None, "seconds": timed(None)})
        for cand in candidates:
            out.append({"panel_cols": int(cand),
                        "seconds": timed(int(cand))})
    stats.add_probe_time(time.perf_counter() - t0)
    return sorted(out, key=lambda d: d["seconds"])


def autotune(ops: Iterable[str] = ("getrf", "geqrf"),
             n: int = 1024, dtype=None,
             nb_candidates: Optional[Sequence[int]] = None,
             write: bool = True, reps: int = 3) -> Dict:
    """Probe each op at size n and (optionally) persist the winners.
    Returns {op: {"chosen": {...}, "results": [...]}}. Accepted op
    names: getrf/geqrf (block size — auto-selected by the drivers),
    potrf (tile-size guidance, ADVISORY: see _blocksize_runner),
    heev (method routing), ooc (panel width), lu_panel (panel-route
    method at height n — native vs fori vs the Pallas kernels,
    ISSUE 6; n is the panel HEIGHT here).

    Never-regress contract: every probe measures the driver's own
    default configuration as a baseline candidate, and a winner is
    persisted ONLY when it beat that baseline by more than the
    WIN_MARGIN ("chosen" is empty otherwise) — so a probe can never
    leave the cache slower than a cold start, and a noise-level
    "win" over a configuration identical to the default is never
    persisted as a measured improvement."""
    import numpy as np
    dtype = np.dtype(dtype or np.float32)
    if nb_candidates is None:
        nb_candidates = [c for c in (64, 128, 256, 512, 1024)
                         if c <= max(n, 64)]
    report: Dict[str, Dict] = {}
    c = _cache.get_cache()

    def beats_default(results, key, default_label=None):
        base = next(r["seconds"] for r in results
                    if r[key] == default_label)
        best = results[0]
        return best[key] != default_label \
            and best["seconds"] < (1.0 - WIN_MARGIN) * base

    for op in ops:
        if op == "heev":
            results = probe_method_eig(n, dtype, reps=reps)
            chosen = {"method_eig": results[0]["method"]} \
                if beats_default(results, "method", "auto") else {}
        elif op == "lu_panel":
            # panel probes key the cache by the panel HEIGHT bucket
            # (the _lu_panel lookup key); width = the driver's frozen
            # cap for the shape class
            w = min(max(n // 16, 64), 512)
            results = probe_lu_panel(n, w, dtype, reps=reps)
            chosen = {"method_lu_panel": results[0]["method"]} \
                if beats_default(results, "method") else {}
        elif op == "ooc":
            cands = [p for p in (max(n // 8, 32), max(n // 4, 64),
                                 max(n // 2, 128))
                     if p <= n] or [n]
            results = probe_ooc_panel(n, sorted(set(cands)),
                                      reps=reps)
            chosen = {"panel_cols": results[0]["panel_cols"]} \
                if beats_default(results, "panel_cols") else {}
        else:
            results = probe_blocksize(op, n, dtype, nb_candidates,
                                      reps=reps)
            chosen = {"nb": results[0]["nb"]} \
                if beats_default(results, "nb") else {}
            if chosen and op == "geqrf":
                # the winner is a Tiled configuration; route the
                # bucket to it (Auto would otherwise take the Fused
                # crossover below fused_max_n and never read nb)
                chosen["fused_max_n"] = 0
        report[op] = {"chosen": chosen, "results": results}
        if write and chosen:
            c.put(op, dtype, n, chosen,
                  meta={"n": n, "results": results})
    if write:
        report["_cache_path"] = c.save()
    return report
