"""Autotuning subsystem (ISSUE 1): measured performance models +
persistent tuning cache for block sizes and method routing.

Three parts: tune/probe.py (microbenchmark driver), tune/cache.py
(versioned JSON cache keyed by op/backend/device/dtype/size-bucket,
with the FROZEN shipped-defaults table), tune/select.py (the single
decision path the drivers consult: explicit option > measured cache >
frozen default). tune/stats.py counts every decision so benches can
attribute wins.

Env switches: ``SLATE_TPU_TUNE=0`` disables lookups (frozen defaults
only, bit-identical to the pre-tune routing); ``SLATE_TPU_TUNE_CACHE``
relocates the cache directory. Populate with ``python bench.py
--tune`` or :func:`autotune`.
"""

from . import cache, probe, select, stats          # noqa: F401
from .cache import TuneCache, get_cache, reset_cache  # noqa: F401
from .probe import autotune                        # noqa: F401
from .select import resolve, tuned_int, tuned_method  # noqa: F401
