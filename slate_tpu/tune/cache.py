"""Persistent tuning cache (ISSUE 1 tentpole, part 2).

Measured-best configurations are keyed by
``(op, backend_kind, device_kind, dtype, size_bucket)`` and stored as
versioned JSON under ``~/.cache/slate_tpu/`` (override the directory
with ``SLATE_TPU_TUNE_CACHE``; disable lookups entirely with
``SLATE_TPU_TUNE=0``). The file is loaded once per process and
memoized; a corrupt or version-mismatched file is treated as empty
(never fatal — tuning is advisory).

Cold-start contract: when no measured entry exists, selection falls
back to FROZEN — the read-only table of shipped defaults, which are
exactly the constants the drivers used before this subsystem existed
(core/options._DEFAULTS nb=256/ib=128/lookahead=1, eig.py
SPECTRAL_DC_MIN_N, spectral_dc.LEAF, ooc.py panel_cols, qr.py's
fused-vs-carry crossover). An empty cache therefore reproduces
today's routing bit-identically; it can never regress below it.

Keys bucket the size (power-of-two buckets, floor 256) so one probe
at n=4096 serves every nearby shape — the same shape-class idea XLA's
own autotuner uses for gemm tilings, and the TPU-vs-CPU block-size
divergence reported by arXiv:2112.09017 is exactly what the
backend_kind/device_kind key components capture.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

from . import stats

#: bump when the on-disk layout changes; mismatched files are ignored
SCHEMA_VERSION = 1

_FILE_NAME = "tune_cache_v%d.json" % SCHEMA_VERSION

#: read-only shipped defaults: (op, param) -> value. These mirror the
#: constants that were hard-coded across the drivers before the tune
#: subsystem (see module doc). select.resolve falls back here (or to
#: the caller's shape-dependent formula) when the cache has no
#: measured entry, so cold start == today's behavior.
FROZEN: Dict[tuple, Any] = {
    ("*", "nb"): 256,            # core/options._DEFAULTS BlockSize
    ("*", "ib"): 128,            # core/options._DEFAULTS InnerBlocking
    ("*", "lookahead"): 1,       # core/options._DEFAULTS Lookahead
    ("heev", "spectral_dc_min_n"): 2048,   # eig.SPECTRAL_DC_MIN_N
    ("heev", "dc_leaf"): 256,              # spectral_dc.LEAF
    ("geqrf", "fused_max_n"): 4096,        # qr.py measured crossover
    ("ooc", "panel_cols"): 8192,           # ooc.py streaming width
    # stream-engine knobs (ISSUE 4): budget 0 = panel cache OFF, the
    # pre-engine uncached schedule bit-identically (linalg/stream.py
    # budget contract); "auto" or an explicit MB count turns it on.
    # mru is the eviction policy a cyclic left-looking revisit wants
    # (LRU degenerates to zero hits once the factor outgrows the
    # budget); prefetch depth 1 = double-buffered H2D
    ("ooc", "cache_budget_mb"): 0,         # stream.PanelCache budget
    ("ooc", "cache_policy"): "mru",        # lru | mru | fifo
    ("ooc", "prefetch_depth"): 1,          # async H2D lookahead
    # sharded-OOC knobs (ISSUE 7): shard_method "stream" = even with a
    # grid supplied, the OOC drivers keep the single-device stream
    # path bit-identically (dist/shard_ooc.py is an earned or explicit
    # route — core/methods.MethodOOC); shard_fanin feeds the factor-
    # panel broadcast tree (dist/tree.py schedule, 2 = binary);
    # shard_min_panels is the per-rank panel floor below which a
    # measured "sharded" entry still demotes to the stream path (the
    # cyclic walk cannot balance fewer panels than ranks)
    ("ooc", "shard_method"): "stream",     # stream | sharded
    ("ooc", "shard_fanin"): 2,             # broadcast tree fan-in
    ("ooc", "shard_min_panels"): 2,        # panels per rank floor
    # sharded broadcast-pipeline depth (ISSUE 11): 0 = the
    # step-synchronous schedule, BIT-IDENTICAL to the pre-lookahead
    # drivers (every depth is bitwise-pinned against 0 — the
    # reordering changes only WHEN identical jitted kernels run, not
    # their operands — but 0 stays the shipped default until the TPU
    # hardware round measures the overlap win; depth 1 is the
    # earned/explicit setting, SLATE's lookahead parameter carried to
    # the mesh broadcast)
    ("ooc", "shard_lookahead"): 0,         # broadcast frames in flight
    # OOC-LU pivot discipline (ISSUE 10): "partial" keeps the PR 9
    # getrf_ooc path (panel-confined partial pivoting + host row-swap
    # fixups) bit-identically on a cold cache; "tournament" is the
    # CALU route (getrf_tntpiv_ooc / shard_getrf_ooc) — immutable
    # factor panels, zero revisit invalidations, sharding-capable —
    # an earned (measured) or explicit decision (core/methods
    # .MethodLUPivot)
    ("ooc", "lu_pivot"): "partial",        # partial | tournament
    # OOC streaming precision (ISSUE 12): "f32" keeps every staged
    # byte and every trailing update in the input dtype — the PR 11
    # stream bit-identically on a cold cache; "bf16" is the
    # mixed-precision mode (f32 panel factors, bf16 trailing updates
    # + bf16 cache residency + bf16 broadcast frames, refinement-
    # guarded solves) — an earned (bench --ooc/--shard precision
    # legs) or explicit decision (core/methods.MethodPrecision)
    ("ooc", "precision"): "f32",           # f32 | bf16
    # OOC issue-loop scheduler (ISSUE 17): "walk" keeps the
    # hand-written static schedules (the linalg/ooc.py loops and the
    # dist/shard_ooc.py _BcastPipeline) bit-identically on a cold
    # cache; "graph" routes the same loop bodies through the
    # task-graph runtime (slate_tpu/sched/ — construct-then-execute,
    # bitwise-pinned against the walks per op and lookahead depth) —
    # an earned (bench --graph) or explicit decision (core/methods
    # .MethodScheduler)
    ("ooc", "scheduler"): "walk",          # walk | graph
    # fused visit sweeps (ISSUE 20): "per_panel" keeps one jitted
    # visit kernel per (factor panel, target panel) pair — the PR 19
    # dispatch schedule bit-identically on a cold cache; "fused"
    # coalesces each step's update sweep into ONE dispatch (wide GEMM
    # over concatenated factor widths for the potrf/getrf
    # left-looking visits, an in-jit lax.scan for geqrf's ordered
    # compact-WY applies and the sharded trailing sweep), compiled
    # once per (height, width, count-bucket) — an earned (bench
    # --fuse, real-MXU hardware round) or explicit decision
    # (core/methods.MethodVisitFuse)
    ("ooc", "visit_fuse"): "per_panel",    # per_panel | fused
    # elastic mesh ownership (ISSUE 19): "static" keeps the pure
    # 2D-block-cyclic CyclicSchedule assignment bit-identically on a
    # cold cache; "elastic" re-derives per-host effective throughput
    # from the ledger tails (EWMA over phase-split-corrected step
    # walls) and re-owns not-yet-factored panels away from stragglers
    # at epoch boundaries by rebuilding the remaining subgraph under
    # the new map (dist/elastic.py) — an earned (bench --elastic) or
    # explicit decision (core/methods.MethodOwnership). remap_every is
    # the segment length in panel steps between remap decisions,
    # remap_threshold the max/min host-speed ratio below which the
    # planner keeps the current map (uniform fleets never remap, so
    # elastic stays bitwise vs static), throughput_alpha the EWMA
    # smoothing weight on new step-wall samples
    ("mesh", "ownership"): "static",       # static | elastic
    ("mesh", "remap_every"): 4,            # panel steps per segment
    ("mesh", "remap_threshold"): 1.25,     # speed ratio to act on
    ("mesh", "throughput_alpha"): 0.4,     # EWMA weight, (0, 1]
    # dist/ subsystem knobs (ISSUE 2): the combine-tree fan-in of the
    # mesh TSQR (2 = the reference's binary ttqrt; larger = shorter
    # tree, fatter (g*w, w) combine QRs), the tall-skinny aspect above
    # which the grid geqrf takes the tree instead of the blocked
    # panel loop, and the distributed stedc leaf size
    ("tsqr", "tree_fanin"): 2,             # dist/tree.py schedule
    ("tsqr", "panel_aspect"): 4,           # qr.py grid TSQR gate
    ("stedc", "leaf"): 32,                 # stedc_solve leaf width
    # batch/ coalescing-queue knobs (ISSUE 5): flush a shape bucket at
    # max_batch occupants or after max_wait_us, whichever first — the
    # latency-vs-occupancy trade a serving tier re-probes per hardware
    # (the ~90 ms tunnel dispatch floor makes a 2 ms coalescing window
    # free there; a direct-attached part may want it near zero)
    ("batch", "max_batch"): 64,            # queue.CoalescingQueue
    ("batch", "max_wait_us"): 2000,        # coalescing window
    # batch stacking strategy (ISSUE 15): "bucket" keeps the PR 5 pow2
    # ladder + validity-masked padding bit-identically on a cold cache;
    # "ragged" is the padding-tax-free route — one dispatch at the max
    # live size rounded to lane alignment, per-element sizes vector,
    # masked ragged Pallas kernels (ops/pallas_kernels.ragged_*) — an
    # earned (bench --serve ragged leg on hardware) or explicit
    # decision (core/methods.MethodBatchStrategy). batch/align is the
    # ladder/ceiling lane alignment: 8 is the CPU-era rung rounding
    # (cold routes unchanged); a TPU probe can earn 128/256-lane rungs
    ("batch", "strategy"): "bucket",       # bucket | ragged
    ("batch", "align"): 8,                 # bucket.ALIGN rung rounding
    ("ragged", "blk"): 32,                 # pk.RAGGED_BLK stripe width
    # serving-daemon knobs (ISSUE 16, serve/): cache_mb bounds the
    # fingerprint-keyed factor cache — FROZEN 0 = cache OFF, and the
    # daemon forwards every request unchanged to the coalescing queue
    # (the cold route is bitwise-identical to direct queue use,
    # pinned by tests); an earned MB budget or explicit argument
    # turns the cached factor + solve-only split path on. The
    # admission thresholds: per-tenant pending-request quota,
    # watchdog-ETA seconds above which lowest-priority requests shed
    # (obs/health.py `health.eta_seconds` gauge), and the oldest-
    # pending-age milliseconds above which degradable f64 requests
    # drop to f32 (serve/admission.py ladder)
    ("serve", "cache_mb"): 0,              # factor cache; 0 = off
    ("serve", "max_pending"): 4096,        # per-tenant quota default
    ("serve", "shed_eta_s"): 30,           # ETA gauge shed threshold
    ("serve", "max_queue_age_ms"): 500,    # degrade-precision gate
    # request-scoped telemetry (ISSUE 18, obs/reqtrace.py +
    # obs/series.py): "off" = Server.submit mints NO span, the RPC
    # header gains NO fields, queue tickets carry None, and the
    # series registry stays empty — the serve/queue cold routes are
    # bitwise and allocation-free vs PR 17 (pinned by tests).
    # serve/slo_ms is the per-tenant latency objective the SLO burn
    # window (series.note_slo) scores against; serve/slo_burn_pct is
    # the violation percentage above which the admission ladder
    # sheds lowest-priority / degrades degradable-f64 requests
    ("obs", "reqtrace"): "off",            # off | on (request tracing)
    ("serve", "metrics"): "off",           # off | on (series + SLO)
    ("serve", "slo_ms"): 500,              # latency objective
    ("serve", "slo_burn_pct"): 50,         # burn shed/degrade gate
    # Pallas kernel arbitration (ISSUE 6): every public kernel entry
    # in ops/pallas_kernels.py registers its tune op here
    # (KERNEL_REGISTRY; linted by tools/check_instrumented.py). The
    # method_* routes ('method_lu_panel', 'chain') are written only
    # by probes — a cold cache keeps the drivers' frozen chains
    # (native/fori, dense compose) bit-identically.
    # resil/ knobs (ISSUE 9): the bounded-retry budget around
    # transfer/collective faults (retries only engage ON failure, so
    # steady state is untouched), the exponential-backoff base, and
    # the checkpoint commit cadence — FROZEN 0 = checkpointing OFF
    # and bit-identical to the pre-resil drivers (resil/checkpoint.py
    # contract; bench --faults pins the 0-byte overhead)
    ("resil", "max_retries"): 2,           # guard.retry budget
    ("resil", "backoff_us"): 500,          # backoff base (*2^attempt)
    ("resil", "ckpt_every"): 0,            # panels per commit; 0 = off
    # flight-recorder knobs (ISSUE 14): "off" = the obs/ledger.py
    # step recorder appends NOTHING and the obs/health.py watchdog
    # starts NO monitor thread — every streaming driver bit-identical
    # to the pre-recorder stack (pinned by tests, single-engine + the
    # 2-process mesh). "on" is an earned (measured-overhead) or
    # explicit decision; obs.ledger.enable()/obs.health.enable()
    # override per process
    ("obs", "ledger"): "off",              # off | on (flight recorder)
    ("obs", "watchdog"): "off",            # off | on (stall monitor)
    ("lu_panel", "ib"): 32,                # lu_panel_rec base width
    ("lu_panel", "max_w"): 256,            # pk.LU_PANEL_MAX_W
    ("steqr2", "chain"): "dense",          # dense | pallas_rec
    ("steqr2", "chain_blk"): 128,          # pk.GIVENS_CHAIN_BLK
    ("bdsqr", "chain"): "dense",           # dense | pallas_rec
    ("qr_panel", "max_w"): 128,            # pk.QR_PANEL_MAX_W
    ("chol_panel", "fused_max"): 1024,     # pk.CHOL_FUSED_MAX
    ("trtri", "fused_max"): 512,           # pk.TRTRI_FUSED_MAX
}


def frozen_default(op: str, param: str, fallback=None):
    """Shipped default for (op, param): exact op entry, then the "*"
    row, then the caller's fallback."""
    if (op, param) in FROZEN:
        return FROZEN[(op, param)]
    if ("*", param) in FROZEN:
        return FROZEN[("*", param)]
    return fallback


def enabled() -> bool:
    """Master switch: SLATE_TPU_TUNE=0/off/false disables every cache
    lookup (selection then sees only explicit options and frozen
    defaults — bit-identical to the pre-tune code paths)."""
    return os.environ.get("SLATE_TPU_TUNE", "1").lower() \
        not in ("0", "off", "false", "no")


def cache_dir() -> str:
    env = os.environ.get("SLATE_TPU_TUNE_CACHE")
    if env:
        return env
    xdg = os.environ.get("XDG_CACHE_HOME")
    base = xdg if xdg else os.path.join(os.path.expanduser("~"), ".cache")
    return os.path.join(base, "slate_tpu")


def cache_path() -> str:
    return os.path.join(cache_dir(), _FILE_NAME)


def size_bucket(n: Optional[int]) -> int:
    """Power-of-two size class (floor 256): one measured entry serves
    every shape in its bucket. n=None (size-independent decisions)
    maps to bucket 0."""
    if n is None:
        return 0
    b = 256
    while b < n:
        b *= 2
    return b


def _backend_device() -> tuple:
    """(backend_kind, device_kind) of the ambient jax backend —
    distinct cache rows per hardware, so a CPU-tuned table never
    leaks onto a TPU run (and re-probing after a backend change is
    automatic: the new backend's keys start cold)."""
    try:
        import jax
        backend = jax.default_backend()
        device = jax.devices()[0].device_kind
    except Exception:                    # backend init failure: tuning
        backend, device = "none", "none"  # is advisory, never fatal
    # device_kind strings can contain spaces ("TPU v5 lite")
    return backend, device.replace(" ", "_").replace("|", "_")


def make_key(op: str, dtype, n: Optional[int]) -> str:
    import numpy as np
    backend, device = _backend_device()
    dt = np.dtype(dtype).name if dtype is not None else "any"
    return "|".join([op, backend, device, dt, str(size_bucket(n))])


class TuneCache:
    """The persistent store: entries[key] = {param: value, ...,
    "_meta": {...probe evidence...}}. Lazy single load per process;
    put() updates memory, save() writes the versioned JSON."""

    def __init__(self, path: Optional[str] = None) -> None:
        self._path = path
        self._lock = threading.Lock()
        self._entries: Optional[Dict[str, Dict[str, Any]]] = None

    @property
    def path(self) -> str:
        return self._path or cache_path()

    @staticmethod
    def _parse(path: str) -> Dict[str, Dict[str, Any]]:
        """Read + validate the versioned JSON; empty dict on missing,
        corrupt, or version-mismatched files (advisory cache, never
        fatal — re-probe repopulates; the next save() overwrites a
        bad file)."""
        try:
            with open(path) as f:
                raw = json.load(f)
            if isinstance(raw, dict) \
                    and raw.get("version") == SCHEMA_VERSION \
                    and isinstance(raw.get("entries"), dict):
                return {str(k): dict(v)
                        for k, v in raw["entries"].items()
                        if isinstance(v, dict)}
        except Exception:
            pass
        return {}

    def _load(self) -> Dict[str, Dict[str, Any]]:
        if self._entries is None:
            # slate-lint: exempt[SL301] every caller holds self._lock
            self._entries = self._parse(self.path)
        return self._entries

    def lookup(self, op: str, dtype, n: Optional[int]
               ) -> Optional[Dict[str, Any]]:
        """The measured entry for (op, backend, device, dtype,
        bucket), or None. Counts hits/misses in tune.stats."""
        with self._lock:
            e = self._load().get(make_key(op, dtype, n))
        stats.record_cache(e is not None)
        return dict(e) if e is not None else None

    def get_param(self, op: str, param: str, dtype, n: Optional[int]):
        e = self.lookup(op, dtype, n)
        if e is None:
            return None
        return e.get(param)

    def put(self, op: str, dtype, n: Optional[int],
            values: Dict[str, Any],
            meta: Optional[Dict[str, Any]] = None) -> None:
        key = make_key(op, dtype, n)
        with self._lock:
            entries = self._load()
            entry = dict(entries.get(key, {}))
            entry.update(values)
            if meta is not None:
                entry["_meta"] = meta
            entries[key] = entry

    def save(self) -> str:
        """Write the versioned JSON atomically (tmp + rename) and
        return the path. Read-merge-write: entries another process
        persisted since our load are kept (our in-memory values win
        per-key conflicts), so concurrent tuning runs don't silently
        drop each other's work."""
        with self._lock:
            entries = self._load()
            path = self.path
            merged = self._parse(path)
            merged.update(entries)
            self._entries = entries = merged
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp.%d" % os.getpid()
            with open(tmp, "w") as f:
                json.dump({"version": SCHEMA_VERSION,
                           "entries": entries}, f, indent=1,
                          sort_keys=True)
            os.replace(tmp, path)
        return path

    def entries(self) -> Dict[str, Dict[str, Any]]:
        """Copy of every loaded entry (the multihost share payload —
        dist/tuneshare.py serializes exactly this)."""
        with self._lock:
            return {k: dict(v) for k, v in self._load().items()}

    def merge(self, entries: Dict[str, Dict[str, Any]]) -> int:
        """BEST-ENTRY merge of another host's table (ROADMAP multihost
        tuning-share item). Per key:

          * missing locally -> adopt the incoming entry;
          * present on both sides -> the entry with the LOWER measured
            best probe time (min over ``_meta.results[*].seconds``)
            wins whole-entry — half-winners are not spliced, a probe's
            parameters are only consistent together;
          * an incoming entry WITHOUT probe evidence never replaces a
            local one (merge must not clobber measurements with
            hearsay).

        In-memory only (like put()); call save() to persist. Returns
        the number of keys adopted/replaced."""
        def best_s(e) -> float:
            try:
                return min(float(r["seconds"])
                           for r in e["_meta"]["results"]
                           if "seconds" in r)
            except Exception:
                return float("inf")

        changed = 0
        with self._lock:
            mine = self._load()
            for key, inc in (entries or {}).items():
                if not isinstance(inc, dict):
                    continue
                cur = mine.get(key)
                if cur is None or best_s(inc) < best_s(cur):
                    mine[key] = dict(inc)
                    changed += 1
        return changed

    def clear_memo(self) -> None:
        """Drop the in-process memo so the next access re-reads the
        file (tests repoint SLATE_TPU_TUNE_CACHE between cases)."""
        with self._lock:
            self._entries = None


_cache = TuneCache()


def get_cache() -> TuneCache:
    return _cache


def reset_cache() -> None:
    """Forget the memoized file contents AND the resolved path (the
    global cache re-reads cache_path() env resolution lazily)."""
    _cache._path = None
    _cache.clear_memo()
