"""Structured counters for the autotuning subsystem (ISSUE 1).

Every tuned decision (select.resolve), cache access (cache.TuneCache)
and probe run (probe.measure) increments a counter here, so a bench
run can attribute wins: how many decisions were explicit / cached /
frozen, how often the persistent cache hit, and how much wall time
probing cost. The surface is deliberately tiny — a process-wide
snapshot dict, the counterpart of utils/trace.py's phase timers for
decisions rather than kernels.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Tuple

_lock = threading.Lock()

#: decision counts keyed by (op, param, source); source is one of
#: "explicit" (user option), "cached" (measured entry), "frozen"
#: (shipped default)
_decisions: Dict[Tuple[str, str, str], int] = {}

#: persistent-cache accesses
_cache_hits = 0
_cache_misses = 0

#: total probe wall seconds (microbenchmark driver)
_probe_seconds = 0.0

#: ring of the most recent decisions, for debugging/bench attribution
_RING_CAP = 64
_recent: List[Dict[str, Any]] = []


def record_decision(op: str, param: str, source: str, value) -> None:
    """One tuned decision taken: `op`/`param` resolved from `source`
    to `value`. Also emits a zero-length trace event when tracing is
    on, so decisions land on the utils/trace.py timeline alongside the
    phase timers they influence."""
    with _lock:
        k = (op, param, source)
        _decisions[k] = _decisions.get(k, 0) + 1
        _recent.append({"op": op, "param": param, "source": source,
                        "value": repr(value)})
        del _recent[:-_RING_CAP]
    from ..utils import trace
    trace.mark("tune::%s.%s=%r [%s]" % (op, param, value, source))


def record_cache(hit: bool) -> None:
    global _cache_hits, _cache_misses
    with _lock:
        if hit:
            _cache_hits += 1
        else:
            _cache_misses += 1


def add_probe_time(seconds: float) -> None:
    global _probe_seconds
    with _lock:
        _probe_seconds += seconds


def snapshot() -> Dict[str, Any]:
    """Point-in-time copy of every counter (bench.py --tune emits
    this into the BENCH trajectory)."""
    with _lock:
        by_source: Dict[str, int] = {}
        for (op, param, source), c in _decisions.items():
            by_source[source] = by_source.get(source, 0) + c
        return {
            "decisions": {"%s.%s[%s]" % k: c
                          for k, c in sorted(_decisions.items())},
            "decisions_by_source": by_source,
            "decisions_total": sum(_decisions.values()),
            "cache_hits": _cache_hits,
            "cache_misses": _cache_misses,
            "probe_seconds": round(_probe_seconds, 3),
            # deep-copied: the ring entries must not alias out of the
            # lock — a caller holding the snapshot while
            # record_decision trims the ring would otherwise race on
            # (and be able to mutate) live dicts
            "recent": [dict(r) for r in _recent],
        }


def reset() -> None:
    global _cache_hits, _cache_misses, _probe_seconds
    with _lock:
        _decisions.clear()
        _recent.clear()
        _cache_hits = 0
        _cache_misses = 0
        _probe_seconds = 0.0
