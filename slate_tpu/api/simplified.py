"""Simplified / modern API names (reference include/slate/
simplified_api.hh — multiply :15, triangular_multiply :106,
triangular_solve :129, rank_k_update :172, lu_* :226-362, chol_* :379-
493, indefinite_* :510-578, least_squares_solve :610, qr_* :626-638,
lq_* :655-667, *_rcondest, eig/eig_vals :695-800)."""

from __future__ import annotations

from ..core.enums import Norm, Side
from ..linalg import blas3 as _blas3
from ..linalg import chol as _chol
from ..linalg import cond as _cond
from ..linalg import eig as _eig
from ..linalg import indefinite as _ind
from ..linalg import lu as _lu
from ..linalg import qr as _qr
from ..linalg.svd import svd as _svd_fn, svd_vals as _svd_vals

# BLAS-3
multiply = _blas3.gemm
triangular_multiply = _blas3.trmm
triangular_solve = _blas3.trsm
rank_k_update = _blas3.herk
rank_2k_update = _blas3.her2k
hermitian_multiply = _blas3.hemm
symmetric_multiply = _blas3.symm
band_multiply = _blas3.gbmm

# LU family (simplified_api.hh:226-362)
lu_factor = _lu.getrf
lu_factor_nopiv = _lu.getrf_nopiv
lu_solve = _lu.gesv
lu_solve_nopiv = _lu.gesv_nopiv
lu_solve_using_factor = _lu.getrs
lu_inverse_using_factor = _lu.getri
lu_rcondest_using_factor = _cond.gecondest
band_lu_factor = _lu.gbtrf
band_lu_solve = _lu.gbsv
band_lu_solve_using_factor = _lu.gbtrs

# Cholesky family (:379-493)
chol_factor = _chol.potrf
chol_solve = _chol.posv
chol_solve_using_factor = _chol.potrs
chol_inverse_using_factor = _chol.potri
chol_rcondest_using_factor = _cond.pocondest
band_chol_factor = _chol.pbtrf
band_chol_solve = _chol.pbsv
band_chol_solve_using_factor = _chol.pbtrs

# indefinite (:510-578)
indefinite_factor = _ind.hetrf
indefinite_solve = _ind.hesv
indefinite_solve_using_factor = _ind.hetrs

# least squares / orthogonal (:610-667)
least_squares_solve = _qr.gels
qr_factor = _qr.geqrf
qr_multiply_by_q = _qr.unmqr
lq_factor = _qr.gelqf
lq_multiply_by_q = _qr.unmlq

# condition estimates
triangular_rcondest = _cond.trcondest

# eigen / svd (:695-800)
eig = _eig.heev
eig_vals = _eig.eig_vals
generalized_eig = _eig.hegv
singular_values = _svd_vals
svd_decompose = _svd_fn
