"""scipy.linalg-compatible drop-in shim (reference lapack_api/ — 32
files intercepting dgesv_/dpotrf_/... and running SLATE on one rank;
here the same role for Python callers: numpy in, numpy out, framework
drivers underneath).

Signatures follow scipy.linalg where the reference intercepts the
corresponding LAPACK entry; only the commonly-used argument subsets are
supported (unsupported combinations raise, never silently diverge).
"""

from __future__ import annotations

import numpy as np


def _st():
    import slate_tpu as st
    return st


def _nb(n: int) -> int:
    return min(max(int(n), 1), 256)


def cholesky(a, lower=False, overwrite_a=False, check_finite=True):
    """scipy.linalg.cholesky (LAPACK potrf)."""
    st = _st()
    a = np.asarray(a)
    n = a.shape[0]
    uplo = st.Uplo.Lower if lower else st.Uplo.Upper
    L, info = st.potrf(st.HermitianMatrix(uplo, a, mb=_nb(n)),
                       return_info=True)
    if int(info) != 0:
        raise np.linalg.LinAlgError(
            f"{int(info)}-th leading minor not positive definite")
    out = L.to_numpy()
    return np.tril(out) if lower else np.triu(out)


def lu_factor(a, overwrite_a=False, check_finite=True):
    """scipy.linalg.lu_factor (LAPACK getrf): (lu, piv)."""
    st = _st()
    a = np.asarray(a)
    F = st.getrf(st.Matrix(a, mb=_nb(a.shape[0])))
    n = min(a.shape)
    return F.LU.to_numpy()[: a.shape[0], : a.shape[1]], \
        np.asarray(F.pivots)[:n]


def lu_solve(lu_and_piv, b, trans=0, overwrite_b=False,
             check_finite=True):
    """scipy.linalg.lu_solve (LAPACK getrs)."""
    st = _st()
    import dataclasses

    from slate_tpu.core.enums import MatrixType, Op
    from slate_tpu.linalg.lu import LUFactors
    lu, piv = lu_and_piv
    lu = np.asarray(lu)
    b = np.asarray(b)
    n = lu.shape[0]
    nb = _nb(n)
    LU = dataclasses.replace(
        st.TiledMatrix.from_dense(lu, nb), mtype=MatrixType.General)
    import jax.numpy as jnp
    pivots = np.arange(max(n, 1), dtype=np.int32)
    pivots[: len(piv)] = piv
    F = LUFactors(LU, jnp.asarray(pivots))
    op = {0: Op.NoTrans, 1: Op.Trans, 2: Op.ConjTrans}[trans]
    b2 = b[:, None] if b.ndim == 1 else b
    X = st.getrs(F, st.TiledMatrix.from_dense(b2, nb), trans=op)
    x = X.to_numpy()
    return x[:, 0] if b.ndim == 1 else x


def solve(a, b, assume_a="gen", lower=False, overwrite_a=False,
          overwrite_b=False, check_finite=True):
    """scipy.linalg.solve (gesv / posv by assume_a)."""
    st = _st()
    a = np.asarray(a)
    b = np.asarray(b)
    nb = _nb(a.shape[0])
    b2 = b[:, None] if b.ndim == 1 else b
    B = st.TiledMatrix.from_dense(b2, nb)
    uplo = st.Uplo.Lower if lower else st.Uplo.Upper
    if assume_a == "pos":
        _, X, info = st.posv(st.HermitianMatrix(uplo, a, mb=nb), B,
                             return_info=True)
        if int(info) != 0:
            raise np.linalg.LinAlgError("matrix not positive definite")
    elif assume_a in ("her", "sym"):
        # symmetric-indefinite solver (reference hesv/sysv)
        _, X = st.hesv(st.HermitianMatrix(uplo, a, mb=nb), B)
    elif assume_a == "gen":
        F, X = st.gesv(st.Matrix(a, mb=nb), B)
        if int(F.info) != 0:
            raise np.linalg.LinAlgError("singular matrix")
    else:
        raise NotImplementedError(f"assume_a={assume_a!r}")
    x = X.to_numpy()
    return x[:, 0] if b.ndim == 1 else x


def solve_triangular(a, b, trans=0, lower=False, unit_diagonal=False,
                     overwrite_b=False, check_finite=True):
    """scipy.linalg.solve_triangular (LAPACK trtrs)."""
    st = _st()
    from slate_tpu.core.enums import Diag
    a = np.asarray(a)
    b = np.asarray(b)
    nb = _nb(a.shape[0])
    uplo = st.Uplo.Lower if lower else st.Uplo.Upper
    diag = Diag.Unit if unit_diagonal else Diag.NonUnit
    T = st.TriangularMatrix(uplo, a, mb=nb, diag=diag)
    if trans == 1:
        T = T.transpose()
    elif trans == 2:
        T = T.conj_transpose()
    b2 = b[:, None] if b.ndim == 1 else b
    X = st.trsm(st.Side.Left, 1.0, T, st.TiledMatrix.from_dense(b2, nb))
    x = X.to_numpy()
    return x[:, 0] if b.ndim == 1 else x


def lstsq(a, b, cond=None, overwrite_a=False, overwrite_b=False,
          check_finite=True, lapack_driver=None):
    """scipy.linalg.lstsq (LAPACK gels) — returns (x, resid, rank, s)
    with rank/s None (gels assumes full rank, like the reference)."""
    st = _st()
    a = np.asarray(a)
    b = np.asarray(b)
    m, n = a.shape
    nb = _nb(m)
    b2 = b[:, None] if b.ndim == 1 else b
    X = st.gels(st.Matrix(a, mb=nb), st.TiledMatrix.from_dense(b2, nb))
    x = X.to_numpy()[:n]
    resid = np.linalg.norm(b2 - a @ x, axis=0) ** 2 if m > n else \
        np.empty((0,))
    return (x[:, 0] if b.ndim == 1 else x), resid, None, None


def eigh(a, lower=True, eigvals_only=False, overwrite_a=False,
         check_finite=True):
    """scipy.linalg.eigh (LAPACK heev) for the standard problem."""
    st = _st()
    a = np.asarray(a)
    n = a.shape[0]
    uplo = st.Uplo.Lower if lower else st.Uplo.Upper
    A = st.HermitianMatrix(uplo, a, mb=_nb(n))
    if eigvals_only:
        return np.asarray(st.heev(A, want_vectors=False).values)[:n]
    w, V = st.heev(A)
    return np.asarray(w)[:n], V.to_numpy()


def svdvals(a, overwrite_a=False, check_finite=True):
    """scipy.linalg.svdvals."""
    st = _st()
    a = np.asarray(a)
    return np.asarray(st.svd_vals(st.Matrix(a, mb=_nb(a.shape[0]))))


def inv(a, overwrite_a=False, check_finite=True):
    """scipy.linalg.inv (getrf + getri)."""
    st = _st()
    a = np.asarray(a)
    F = st.getrf(st.Matrix(a, mb=_nb(a.shape[0])))
    if int(F.info) != 0:
        raise np.linalg.LinAlgError("singular matrix")
    return st.getri(F).to_numpy()
