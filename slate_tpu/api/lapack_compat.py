"""scipy.linalg-compatible drop-in shim (reference lapack_api/ — 32
files intercepting dgesv_/dpotrf_/... and running SLATE on one rank;
here the same role for Python callers: numpy in, numpy out, framework
drivers underneath).

Signatures follow scipy.linalg where the reference intercepts the
corresponding LAPACK entry; only the commonly-used argument subsets are
supported (unsupported combinations raise, never silently diverge).

Batched inputs (ndim > 2, numpy broadcasting convention): cholesky,
lu_factor, solve, eigh and inv route stacked matrices through the
batched execution layer (slate_tpu/batch/) — one shape-bucketed
vmapped dispatch instead of a Python loop over 2-D calls (they used
to hit shape errors deep in the drivers). Routes that stay 2-D-only
(lstsq with its ragged rhs, lu_solve, solve_triangular, svdvals)
raise a ValueError that names the alternative.
"""

from __future__ import annotations

import numpy as np


def _st():
    import slate_tpu as st
    return st


def _nb(n: int) -> int:
    return min(max(int(n), 1), 256)


def _batch_run(op, a, rhs=None):
    """Route a stacked (..., m, n) input through the batched execution
    layer (slate_tpu/batch/): leading dims flatten to one batch, each
    slice coalesces into the shape-bucketed dispatch, results restack.
    Returns a list of per-slice results plus the leading shape.
    Mixed a/rhs dtypes promote numpy-style here (the queue itself is
    strict — a mismatched rhs must not poison a coalesced bucket)."""
    from slate_tpu import batch
    lead = a.shape[:-2]
    if rhs is not None:
        dt = np.result_type(a, rhs)
        a, rhs = a.astype(dt, copy=False), rhs.astype(dt, copy=False)
    mats = list(a.reshape((-1,) + a.shape[-2:]))
    rhss = None
    if rhs is not None:
        rhss = list(rhs.reshape((-1,) + rhs.shape[-2:]))
    return batch.run(op, mats, rhs=rhss), lead


def _mirror_hermitian(a, lower):
    """Materialize the Hermitian matrix a stacked triangular-storage
    input designates (scipy contract: only the `lower`-selected
    triangle is referenced — the other may hold garbage). The batch
    cores read the FULL array, so the unreferenced triangle must be
    rebuilt from the referenced one before dispatch (the 2-D paths get
    this from HermitianMatrix(uplo, ...) / to_dense)."""
    if lower:
        return np.tril(a) + np.conj(np.swapaxes(np.tril(a, -1),
                                                -1, -2))
    return np.triu(a) + np.conj(np.swapaxes(np.triu(a, 1), -1, -2))


def _no_batch(name: str, why: str):
    """The clean ndim>2 refusal for routes that stay 2-D-only —
    batched inputs used to fail with shape errors deep inside the
    drivers; now the route either works (via slate_tpu/batch/) or
    says exactly why not."""
    raise ValueError(
        f"{name}: batched (ndim > 2) input is not supported — {why}. "
        "For uniform-shape stacks use slate_tpu.batch directly "
        "(CoalescingQueue / batch.run); otherwise loop the 2-D call.")


def cholesky(a, lower=False, overwrite_a=False, check_finite=True):
    """scipy.linalg.cholesky (LAPACK potrf). Stacked (..., n, n)
    input routes through the batched layer (one bucketed dispatch
    for the whole stack)."""
    st = _st()
    a = np.asarray(a)
    if a.ndim > 2:
        outs, lead = _batch_run("potrf", _mirror_hermitian(a, lower))
        ls = np.stack([np.asarray(L) for L in outs])
        if not np.isfinite(
                ls[:, range(a.shape[-1]), range(a.shape[-1])]).all():
            raise np.linalg.LinAlgError(
                "a stacked matrix is not positive definite")
        if not lower:
            ls = np.conj(np.swapaxes(ls, -1, -2))
        return ls.reshape(a.shape)
    n = a.shape[0]
    uplo = st.Uplo.Lower if lower else st.Uplo.Upper
    L, info = st.potrf(st.HermitianMatrix(uplo, a, mb=_nb(n)),
                       return_info=True)
    if int(info) != 0:
        raise np.linalg.LinAlgError(
            f"{int(info)}-th leading minor not positive definite")
    out = L.to_numpy()
    return np.tril(out) if lower else np.triu(out)


def lu_factor(a, overwrite_a=False, check_finite=True):
    """scipy.linalg.lu_factor (LAPACK getrf): (lu, piv). Stacked
    square input routes through the batched layer."""
    st = _st()
    a = np.asarray(a)
    if a.ndim > 2:
        if a.shape[-2] != a.shape[-1]:
            _no_batch("lu_factor", "the batch getrf route is "
                      "square-only")
        outs, lead = _batch_run("getrf", a)
        lus = np.stack([np.asarray(lu) for lu, _ in outs])
        pivs = np.stack([np.asarray(p) for _, p in outs])
        return (lus.reshape(a.shape),
                pivs.reshape(lead + pivs.shape[-1:]))
    F = st.getrf(st.Matrix(a, mb=_nb(a.shape[0])))
    n = min(a.shape)
    return F.LU.to_numpy()[: a.shape[0], : a.shape[1]], \
        np.asarray(F.pivots)[:n]


def lu_solve(lu_and_piv, b, trans=0, overwrite_b=False,
             check_finite=True):
    """scipy.linalg.lu_solve (LAPACK getrs)."""
    st = _st()
    import dataclasses

    from slate_tpu.core.enums import MatrixType, Op
    from slate_tpu.linalg.lu import LUFactors
    lu, piv = lu_and_piv
    lu = np.asarray(lu)
    b = np.asarray(b)
    if lu.ndim > 2 or b.ndim > 2:
        _no_batch("lu_solve", "stacked factors would need a batched "
                  "getrs; factor+solve together batches via "
                  "solve(..., assume_a='gen')")
    n = lu.shape[0]
    nb = _nb(n)
    LU = dataclasses.replace(
        st.TiledMatrix.from_dense(lu, nb), mtype=MatrixType.General)
    import jax.numpy as jnp
    pivots = np.arange(max(n, 1), dtype=np.int32)
    pivots[: len(piv)] = piv
    F = LUFactors(LU, jnp.asarray(pivots))
    op = {0: Op.NoTrans, 1: Op.Trans, 2: Op.ConjTrans}[trans]
    b2 = b[:, None] if b.ndim == 1 else b
    X = st.getrs(F, st.TiledMatrix.from_dense(b2, nb), trans=op)
    x = X.to_numpy()
    return x[:, 0] if b.ndim == 1 else x


def solve(a, b, assume_a="gen", lower=False, overwrite_a=False,
          overwrite_b=False, check_finite=True):
    """scipy.linalg.solve (gesv / posv by assume_a). Stacked
    (..., n, n) systems route through the batched layer (gesv / posv
    by assume_a; 'her'/'sym' stay 2-D — no batched indefinite
    solver)."""
    st = _st()
    a = np.asarray(a)
    b = np.asarray(b)
    if a.ndim > 2:
        if assume_a not in ("gen", "pos"):
            _no_batch("solve", f"assume_a={assume_a!r} has no batched "
                      "driver (gen and pos do)")
        squeeze = b.ndim == a.ndim - 1
        b3 = b[..., None] if squeeze else b
        if b3.shape[: a.ndim - 2] != a.shape[:-2]:
            _no_batch("solve", "rhs leading dims must match the "
                      "matrix stack")
        a3 = _mirror_hermitian(a, lower) if assume_a == "pos" else a
        outs, lead = _batch_run("posv" if assume_a == "pos" else "gesv",
                                a3, rhs=b3)
        xs = np.stack([np.asarray(x) for x in outs])
        if not np.isfinite(xs).all():
            raise np.linalg.LinAlgError(
                "a stacked matrix is not positive definite"
                if assume_a == "pos" else
                "a stacked matrix is singular")
        xs = xs.reshape(lead + xs.shape[-2:])
        return xs[..., 0] if squeeze else xs
    nb = _nb(a.shape[0])
    b2 = b[:, None] if b.ndim == 1 else b
    B = st.TiledMatrix.from_dense(b2, nb)
    uplo = st.Uplo.Lower if lower else st.Uplo.Upper
    if assume_a == "pos":
        _, X, info = st.posv(st.HermitianMatrix(uplo, a, mb=nb), B,
                             return_info=True)
        if int(info) != 0:
            raise np.linalg.LinAlgError("matrix not positive definite")
    elif assume_a in ("her", "sym"):
        # symmetric-indefinite solver (reference hesv/sysv)
        _, X = st.hesv(st.HermitianMatrix(uplo, a, mb=nb), B)
    elif assume_a == "gen":
        F, X = st.gesv(st.Matrix(a, mb=nb), B)
        if int(F.info) != 0:
            raise np.linalg.LinAlgError("singular matrix")
    else:
        raise NotImplementedError(f"assume_a={assume_a!r}")
    x = X.to_numpy()
    return x[:, 0] if b.ndim == 1 else x


def solve_triangular(a, b, trans=0, lower=False, unit_diagonal=False,
                     overwrite_b=False, check_finite=True):
    """scipy.linalg.solve_triangular (LAPACK trtrs)."""
    st = _st()
    from slate_tpu.core.enums import Diag
    a = np.asarray(a)
    b = np.asarray(b)
    if a.ndim > 2:
        _no_batch("solve_triangular", "triangular solves are one "
                  "native batched XLA op; jax.lax.linalg."
                  "triangular_solve on the stack is the direct route")
    nb = _nb(a.shape[0])
    uplo = st.Uplo.Lower if lower else st.Uplo.Upper
    diag = Diag.Unit if unit_diagonal else Diag.NonUnit
    T = st.TriangularMatrix(uplo, a, mb=nb, diag=diag)
    if trans == 1:
        T = T.transpose()
    elif trans == 2:
        T = T.conj_transpose()
    b2 = b[:, None] if b.ndim == 1 else b
    X = st.trsm(st.Side.Left, 1.0, T, st.TiledMatrix.from_dense(b2, nb))
    x = X.to_numpy()
    return x[:, 0] if b.ndim == 1 else x


def lstsq(a, b, cond=None, overwrite_a=False, overwrite_b=False,
          check_finite=True, lapack_driver=None):
    """scipy.linalg.lstsq (LAPACK gels) — returns (x, resid, rank, s)
    with rank/s None (gels assumes full rank, like the reference).

    Stays 2-D-only: scipy's lstsq contract ties each matrix to its
    own right-hand side, and stacked callers almost always carry
    RAGGED per-item rhs widths/rows no single stacked dispatch can
    hold; slate_tpu.batch.gels_batched serves the uniform case."""
    st = _st()
    a = np.asarray(a)
    b = np.asarray(b)
    if a.ndim > 2 or b.ndim > 2:
        _no_batch("lstsq", "per-item rhs is ragged in general; "
                  "uniform overdetermined stacks go through "
                  "slate_tpu.batch.gels_batched / batch.run('gels')")
    m, n = a.shape
    nb = _nb(m)
    b2 = b[:, None] if b.ndim == 1 else b
    X = st.gels(st.Matrix(a, mb=nb), st.TiledMatrix.from_dense(b2, nb))
    x = X.to_numpy()[:n]
    resid = np.linalg.norm(b2 - a @ x, axis=0) ** 2 if m > n else \
        np.empty((0,))
    return (x[:, 0] if b.ndim == 1 else x), resid, None, None


def eigh(a, lower=True, eigvals_only=False, overwrite_a=False,
         check_finite=True):
    """scipy.linalg.eigh (LAPACK heev) for the standard problem.
    Stacked (..., n, n) input routes through the batched layer."""
    st = _st()
    a = np.asarray(a)
    if a.ndim > 2:
        outs, lead = _batch_run("heev", _mirror_hermitian(a, lower))
        ws = np.stack([np.asarray(w) for w, _ in outs])
        ws = ws.reshape(lead + ws.shape[-1:])
        if eigvals_only:
            return ws
        vs = np.stack([np.asarray(v) for _, v in outs])
        return ws, vs.reshape(a.shape)
    n = a.shape[0]
    uplo = st.Uplo.Lower if lower else st.Uplo.Upper
    A = st.HermitianMatrix(uplo, a, mb=_nb(n))
    if eigvals_only:
        return np.asarray(st.heev(A, want_vectors=False).values)[:n]
    w, V = st.heev(A)
    return np.asarray(w)[:n], V.to_numpy()


def svdvals(a, overwrite_a=False, check_finite=True):
    """scipy.linalg.svdvals."""
    st = _st()
    a = np.asarray(a)
    if a.ndim > 2:
        _no_batch("svdvals", "no batched SVD driver yet (the staged "
                  "svd pipeline is single-matrix)")
    return np.asarray(st.svd_vals(st.Matrix(a, mb=_nb(a.shape[0]))))


def inv(a, overwrite_a=False, check_finite=True):
    """scipy.linalg.inv (getrf + getri). Stacked input routes
    through the batched gesv against a stacked identity."""
    st = _st()
    a = np.asarray(a)
    if a.ndim > 2:
        n = a.shape[-1]
        if a.shape[-2] != n:
            _no_batch("inv", "stacked matrices must be square")
        eye = np.broadcast_to(np.eye(n, dtype=a.dtype),
                              a.shape).copy()
        outs, lead = _batch_run("gesv", a, rhs=eye)
        xs = np.stack([np.asarray(x) for x in outs])
        if not np.isfinite(xs).all():
            raise np.linalg.LinAlgError("a stacked matrix is singular")
        return xs.reshape(a.shape)
    F = st.getrf(st.Matrix(a, mb=_nb(a.shape[0])))
    if int(F.info) != 0:
        raise np.linalg.LinAlgError("singular matrix")
    return st.getri(F).to_numpy()
