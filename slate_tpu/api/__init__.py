from . import lapack_compat, simplified
