from . import simplified
