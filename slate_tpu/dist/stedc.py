"""Distributed Cuppen divide & conquer for the symmetric tridiagonal
eigenproblem — the reference's rank-parallel stedc (stedc_solve.cc:
97-171 splitting across ranks, stedc.cc:70-97 distributed workspace,
stedc_merge.cc cross-rank back-transform), VERDICT Missing #1.

Same phase functions as the single-device driver (linalg/stedc.py:
stedc_split / stedc_leaves / stedc_merge); what this driver adds is
the PLACEMENT schedule over the mesh:

  * leaf solves and the lower merge levels: the subproblem batch axis
    is sharded over the flattened ('p','q') mesh — each device solves
    and merges its own subproblems whole, the reference's per-rank
    parallelism (bit-identical to single-device: no op crosses a
    shard boundary);
  * top merge levels (fewer pairs than devices): the O(n^3) bulk —
    the G@U rotation compose and the Q@(GU) back-transform
    (stedc_merge.cc's matmuls) — runs with operands and outputs
    constrained P('p','q'), so XLA SPMD splits those FLOPs across the
    mesh like the blocked factorizations' trailing updates. The O(n)
    deflation/secular state machines and the sort/permutation gathers
    stay EXPLICITLY replicated: they are the part the reference also
    runs redundantly per rank, and (measured on this jax) the SPMD
    partitioner miscompiles scan/sort/gather chains whose inputs are
    sharded along the operated dimension — the replication constraint
    is correctness-bearing, not just a placement hint.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..obs.events import instrument_driver
from ..parallel.mesh import ProcessGrid
from ..parallel.sharding import constrain

_HI = jax.lax.Precision.HIGHEST


def matmul_sharded(grid: ProcessGrid, a: jax.Array, b: jax.Array
                   ) -> jax.Array:
    """Explicitly scheduled distributed matmul for the merge bulk:
    shard_map with a's rows over 'p' and b's columns over 'q' — each
    device computes its exact (m/p, n/q) output block from a full-k
    local matmul (no reduction splitting, so the result is
    BIT-IDENTICAL to the replicated product), then the blocks
    replicate back. The explicit schedule matters on this jax: a
    plain sharding constraint here back-propagates into the
    scan/sort producers and the SPMD partitioner miscompiles them
    (module doc). Falls back to the replicated matmul when the mesh
    does not divide the shape."""
    m, n = a.shape[0], b.shape[1]
    if m % grid.p or n % grid.q:
        return jnp.matmul(a, b, precision=_HI)
    from ..parallel.smap import shard_map

    def f(al, bl):
        return jnp.matmul(al, bl, precision=_HI)

    y = shard_map(f, mesh=grid.mesh,
                  in_specs=(P("p", None), P(None, "q")),
                  out_specs=P("p", "q"), check_vma=False)(a, b)
    return constrain(y, grid, P())


def _merge_sharded(grid: ProcessGrid, D1, V1, D2, V2, rho
                   ) -> Tuple[jax.Array, jax.Array]:
    """One Cuppen merge with the back-transform matmuls distributed
    (module doc). Inputs must be replicated; the result is replicated
    again so the next level's vector phases (row slices, sort,
    deflation scan) stay off sharded data."""
    from ..linalg.stedc import (_deflate_rotation_fused, stedc_secular,
                                stedc_sort, stedc_z_vector)
    D = jnp.concatenate([D1, D2])
    z = stedc_z_vector(V1, V2)
    Ds, zs, perm = stedc_sort(D, z)
    defl, G = _deflate_rotation_fused(Ds, zs, rho)
    lam, U = stedc_secular(defl.d, defl.z, rho, defl.keep)
    Q = jax.scipy.linalg.block_diag(V1, V2)[:, perm]
    # pin every matmul operand REPLICATED before it meets the
    # shard_map: without the pin, the shard_map's input specs
    # back-propagate into the scan/secular producers and this jax's
    # partitioner miscompiles their loop-carried state (measured —
    # eigenvalues off by O(1); with the pin, bit-exact)
    G = constrain(G, grid, P())
    U = constrain(U, grid, P())
    Q = constrain(Q, grid, P())
    GU = constrain(matmul_sharded(grid, G, U), grid, P())
    V = matmul_sharded(grid, Q, GU)
    order = jnp.argsort(lam)
    return lam[order], V[:, order]


@instrument_driver("stedc_dist")
def stedc_solve_dist(grid: ProcessGrid, d: jax.Array, e: jax.Array,
                     leaf: int = 32) -> Tuple[jax.Array, jax.Array]:
    """Mesh-distributed stedc_solve: same mathematics, scheduled
    placement (module doc). Returns (w, V) ascending. Matches the
    single-device driver to reduction-order rounding (exactly, below
    the top levels)."""
    from ..linalg.stedc import (stedc_leaves, stedc_merge, stedc_solve,
                                stedc_split)
    from ..obs import events as obs_events
    if obs_events.enabled():
        obs_events.instant("comms:stedc_dist", cat="comms",
                           n=int(jnp.asarray(d).shape[0]), leaf=leaf,
                           nprocs=grid.nprocs)
    d = jnp.asarray(d)
    e = jnp.asarray(e)
    n = d.shape[0]
    if n <= leaf:
        return stedc_solve(d, e, leaf=leaf)
    dp, ep, N, nl = stedc_split(d, e, leaf)
    batch_spec2 = P(("p", "q"), None)
    batch_spec3 = P(("p", "q"), None, None)
    dblk = constrain(dp.reshape(nl, leaf), grid, batch_spec2)
    eblk = ep[:N].reshape(nl, leaf)[:, :-1]
    w, V = stedc_leaves(dblk, eblk)
    s = leaf
    while s < N:
        rhos = ep[np.arange(s, N, 2 * s) - 1]
        pairs = V.shape[0] // 2
        if pairs % grid.nprocs == 0:
            # rank-parallel regime: whole pairs per device
            w = constrain(w, grid, batch_spec2)
            V = constrain(V, grid, batch_spec3)
            w, V = jax.vmap(stedc_merge)(w[0::2], V[0::2], w[1::2],
                                         V[1::2], rhos)
        else:
            # top levels: few large merges, matmuls SPMD-partitioned.
            # A Python loop, not vmap: the pair count here is < the
            # device count, so program size stays O(log nprocs).
            # Replicate the workspace FIRST — _merge_sharded's vector
            # phases must not see shards left over from the
            # rank-parallel levels (module doc).
            w = constrain(w, grid, P())
            V = constrain(V, grid, P())
            merged = [_merge_sharded(grid, w[2 * i], V[2 * i],
                                     w[2 * i + 1], V[2 * i + 1],
                                     rhos[i])
                      for i in range(pairs)]
            w = jnp.stack([mw for mw, _ in merged])
            V = jnp.stack([mv for _, mv in merged])
        s *= 2
    return w[0][:n], V[0][:n, :n]
