"""Sharded out-of-core execution layer (ISSUE 7 tentpole): the
composition of dist/'s explicit-schedule tree engine and the
linalg/stream.py panel-residency engine — the SLATE distribution model
(PAPER.md §1) carried to the beyond-HBM regime.

The two existing halves each cap the problem size at one device's
pipe: dist/ shards IN-HBM problems across a mesh, and stream.py
streams BEYOND-HBM problems host<->one device. Composed, panels are
assigned **2D-block-cyclically to mesh positions** and each host's
StreamEngine stages only its local shard's panels — the aggregate
host-RAM/HBM pipe of the whole pod, which is exactly how "Large Scale
Distributed Linear Algebra With TPUs" (PAPERS.md) reaches
beyond-single-chip n (and JAXMg shows carries to GPU meshes with
different constants).

Schedule shape (right-looking, the reference's potrf.cc/geqrf.cc panel
loop):

  * ``CyclicSchedule`` — panel k of the column stream is owned by the
    mesh position reached by the column-major cyclic walk
    ``(k mod p, (k // p) mod q)`` (the GridOrder.Col convention of
    parallel/mesh.py; the diagonal-ownership walk of the SLATE
    2D-block-cyclic tile map at panel granularity — tile-level row
    distribution within a panel column is the further step). Ownership
    is STATIC, so every host knows, before the stream starts, exactly
    which panels it will stage and in what order — prefetch becomes
    exact rather than heuristic (asserted by test via the obs h2d
    counters: an eviction-free run stages precisely the owned inputs,
    nothing else).
  * per step k: the owner factors its panel in-core (the SAME jitted
    panel kernels as the single-device stream), then ``PanelBroadcaster``
    replicates the factor panel over the dist/tree.py ppermute combine
    tree — payload on the owner's device, exact zeros elsewhere, a
    log-depth add-combine (x + 0 is exact, the dist/tuneshare
    transport shape carried to float panels; fan-in is the
    ``ooc/shard_fanin`` tunable and the scheduled ppermute count lands
    in the obs comms accounting like every tree traversal). Under the
    cyclic walk every position owns trailing panels, so the consumer
    set is the whole grid — the row/column-restricted broadcast of a
    true 2D tile decomposition degenerates to the full tree here.
  * every host applies the broadcast factor to the trailing panels it
    owns (``StreamEngine.stash`` keeps those working states
    device-resident under the per-host HBM budget, spilling evicted
    ones through the async D2H writer), while the engine's prefetch
    thread stages the host's NEXT first-touch input — the reference's
    lookahead, reconstructed from the two existing primitives.

Bit-identity: the right-looking schedule applies, to every panel, the
same update sequence (factors 0..k-1 in order) through the SAME jitted
kernels on bitwise-equal operands as the single-device left-looking
stream, so ``shard_potrf_ooc``/``shard_geqrf_ooc`` reproduce
``potrf_ooc``/``geqrf_ooc`` results exactly — including at budget 0,
where every stash degenerates to write-through (the uncached
schedule). Pinned by tests on the single-process mesh.

Routing: the linalg/ooc.py drivers take ``grid=``/``method=`` and
arbitrate through core/methods.MethodOOC — the FROZEN
``ooc/shard_method`` default is "stream", so a cold cache keeps the
single-device path bit-identically even when a grid is supplied.

``shard_getrf_ooc`` (ISSUE 10) closes the LU deferral that PR 7
recorded: partial pivoting's host-side row-swap fixup rewrites rows
of already-written L panels — under sharding, an epoch-bump broadcast
plus a re-stage storm per cross-panel pivot. The unlock is CALU-style
tournament pivoting (linalg/ca.tournament_pivot_rows, the structure
"Large Scale Distributed Linear Algebra With TPUs" uses for
TPU-distributed LU): the owner finalizes panel k's pivot permutation
BEFORE the factor column is written, the factor is stored in ORIGINAL
row order with the permutation applied at visit time by a device
index gather (ooc._lu_visit_orig), and the broadcast frame carries
the panel's pivot-row selection as one extra payload row the way the
QR frame carries tau — every host rederives the identical permutation
bookkeeping from that row, no retroactive fixup, no cross-shard
invalidation. Results are BITWISE equal to the single-engine
``getrf_tntpiv_ooc`` (same kernels, same operands per (panel, step)
pair); routing is earned the same way (MethodOOC; the partial-pivot
mode never shards).
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tiles import ceil_div
from ..obs import events as obs_events
from ..obs import metrics as obs_metrics
from ..obs.events import instrument_driver
from ..parallel.mesh import ProcessGrid
from ..parallel.smap import shard_map
from ..resil import checkpoint as _ckpt
from ..resil import faults as _faults
from ..resil import guard as _guard
from . import tree as _tree


class CyclicSchedule:
    """Static 2D-block-cyclic panel->mesh-position ownership map (one
    per driver invocation; module doc). The schedule is global
    knowledge — every process computes the same map, which is what
    makes the SPMD broadcast loop and the exact per-host prefetch
    possible without any coordination traffic."""

    def __init__(self, nt: int, grid: ProcessGrid) -> None:
        self.nt = int(nt)
        self.grid = grid
        self.p, self.q = grid.p, grid.q
        self.devs = list(grid.mesh.devices.flat)   # row-major (p, q)

    @property
    def nranks(self) -> int:
        return self.p * self.q

    def owner_coords(self, k: int) -> Tuple[int, int]:
        """Grid position owning panel k: the column-major cyclic walk
        ('p' advances fastest — GridOrder.Col, mesh.py)."""
        return k % self.p, (k // self.p) % self.q

    def owner_flat(self, k: int) -> int:
        """Index of the owner in the row-major flattened device list
        (the broadcast-tree position)."""
        r, c = self.owner_coords(k)
        return r * self.q + c

    def owner_device(self, k: int):
        return self.devs[self.owner_flat(k)]

    def owner_process(self, k: int) -> int:
        return self.owner_device(k).process_index

    def is_mine(self, k: int) -> bool:
        return self.owner_process(k) == jax.process_index()

    def my_panels(self) -> List[int]:
        """Panels THIS PROCESS stages, in factoring order — the exact
        per-host touch schedule prefetch runs on."""
        return [k for k in range(self.nt) if self.is_mine(k)]

    def staged_bytes(self, heights: Dict[int, int], width: int,
                     last_width: int, itemsize: int) -> int:
        """Exact bytes this process's engine stages in an
        eviction-free run: each owned panel's input once.
        `heights[k]` is panel k's staged row count (n - k0 for the
        triangular stream, m for the full-height QR stream)."""
        total = 0
        for k in self.my_panels():
            w = last_width if k == self.nt - 1 else width
            total += heights[k] * w * itemsize
        return total


#: compiled broadcast programs, shared ACROSS driver invocations on
#: the same mesh (Mesh is hashable): without this every stream would
#: re-trace the tree per call — the jit cache keys on the closure
#: object, which a per-instance builder would recreate. Bounded in
#: practice: one entry per (mesh, panel shape, dtype, fanin).
_BCAST_FNS: Dict[Tuple, Callable] = {}


def _bcast_fn(mesh, shape: Tuple[int, ...], dtype, fanin: int,
              size: int) -> Callable:
    key = (mesh, tuple(shape), np.dtype(dtype).str, fanin)
    fn = _BCAST_FNS.get(key)
    if fn is not None:
        return fn

    def combine(xs):
        return _tree.tree_combine(
            xs, lambda vals: functools.reduce(jnp.add, vals),
            ("p", "q"), size, fanin=fanin)

    fn = jax.jit(shard_map(
        combine, mesh=mesh,
        in_specs=P(("p", "q"), *([None] * len(shape))),
        out_specs=P(), check_vma=False))
    _BCAST_FNS[key] = fn
    return fn


class PanelBroadcaster:
    """Factor-panel broadcast over the dist/tree.py combine engine:
    the owner's device holds the payload, every other mesh position
    holds exact zeros, and a log-depth add-combine replicates it
    bitwise (x + 0.0 is exact for finite x). One compiled program per
    (mesh, payload shape) — cached across invocations — so a whole
    stream costs at most two compiles (full panels + the narrow
    tail). Each traversal publishes its scheduled ppermute count to
    the obs comms accounting (tree.record_schedule), exactly like
    tsqr/stedc."""

    def __init__(self, grid: ProcessGrid, fanin: int = 2) -> None:
        self.grid = grid
        self.fanin = max(int(fanin), 2)
        self.mesh = grid.mesh
        self.devs = list(grid.mesh.devices.flat)
        self.size = len(self.devs)
        self._zeros: Dict[Tuple, Any] = {}
        self.panels = 0
        self.bytes = 0

    def _fn(self, shape: Tuple[int, ...], dtype) -> Callable:
        return _bcast_fn(self.mesh, shape, dtype, self.fanin,
                         self.size)

    def _zero(self, dev, shape: Tuple[int, ...], dtype):
        key = (dev.id, tuple(shape), np.dtype(dtype).str)
        z = self._zeros.get(key)
        if z is None:
            z = jax.device_put(jnp.zeros((1,) + tuple(shape), dtype),
                               dev)
            self._zeros[key] = z
        return z

    def broadcast(self, payload, owner_flat: int,
                  shape: Tuple[int, ...], dtype):
        """Replicate `payload` ((shape)-shaped device array on the
        OWNER process; ignored elsewhere) from mesh position
        `owner_flat` to every process. Returns the local replicated
        copy. Every process must call in lockstep (SPMD collective)."""
        me = jax.process_index()
        shards = []
        for i, dev in enumerate(self.devs):
            if dev.process_index != me:
                continue
            if i == owner_flat:
                shards.append(jax.device_put(
                    jnp.reshape(payload, (1,) + tuple(shape)), dev))
            else:
                shards.append(self._zero(dev, shape, dtype))
        sharding = NamedSharding(
            self.mesh, P(("p", "q"), *([None] * len(shape))))
        garr = jax.make_array_from_single_device_arrays(
            (self.size,) + tuple(shape), sharding, shards)
        nb = int(np.dtype(dtype).itemsize) * int(np.prod(shape))
        self.panels += 1
        self.bytes += nb

        def traverse():
            # record_schedule's resil hook IS the `ppermute` injection
            # site, so it lives inside the retried unit: an injected
            # collective fault re-runs the whole traversal (every
            # host retries in lockstep — the occurrence counters are
            # per-process deterministic)
            _tree.record_schedule("shard_bcast", self.size,
                                  self.fanin)
            return self._fn(tuple(shape), dtype)(garr)

        def run():
            if _faults.active() is not None:
                return _guard.retry(traverse, "ppermute",
                                    op="shard_bcast", size=self.size)
            try:
                return traverse()
            except Exception as e:
                # a REAL transient collective failure (not injected)
                # takes the same bounded retry
                if not _guard.is_transient(e):
                    raise
                return _guard.retry_after_failure(
                    traverse, "ppermute", e,
                    op="shard_bcast", size=self.size)

        if obs_events.enabled():
            obs_metrics.inc("ooc.shard.bcast_panels")
            obs_metrics.inc("ooc.shard.bcast_bytes", nb)
            with obs_events.span("shard::bcast", cat="shard",
                                 owner=owner_flat, bytes=nb):
                out = run()
        else:
            out = run()
        return out.addressable_data(0)[0]


def _shard_fanin(fanin: Optional[int], n: int, dtype) -> int:
    if fanin:
        return int(fanin)
    from ..tune.select import resolve
    return int(resolve("ooc", "shard_fanin", n=n, dtype=dtype))


def _host_ckpt_path(path: Optional[str]) -> Optional[str]:
    """Per-host checkpoint directory under the shared `path`: hosts
    snapshot their LOCAL factor mirror independently (each writes
    every factor panel through its own engine), so two processes on
    one filesystem must not share memmaps or meta."""
    if path is None:
        return None
    return os.path.join(path, "host%d" % jax.process_index())


def _agree_epoch(grid: ProcessGrid, epoch: int) -> int:
    """Checkpoint-resume epoch agreement (resil/, ISSUE 9): hosts
    crash at different commit points, so the mesh resumes at the MIN
    committed epoch — a tree min-reduction over every device (the
    dist/tuneshare transport shape). Single-process meshes short-
    circuit (every device is this host's epoch)."""
    devs = list(grid.mesh.devices.flat)
    if len({d.process_index for d in devs}) == 1:
        return int(epoch)
    from ..parallel.collectives import tree_allreduce
    me = jax.process_index()
    shards = [jax.device_put(jnp.asarray([epoch], jnp.int32), d)
              for d in devs if d.process_index == me]
    sharding = NamedSharding(grid.mesh, P(("p", "q")))
    garr = jax.make_array_from_single_device_arrays(
        (len(devs),), sharding, shards)
    out = tree_allreduce(grid, garr, op=jnp.minimum)
    return int(np.asarray(out.addressable_data(0))[0])


#: counters each per-step obs record reports as deltas
_STEP_OBS_KEYS = ("ooc.h2d_bytes", "ooc.d2h_bytes",
                  "ooc.shard.bcast_panels", "ooc.shard.bcast_bytes")


def _step_obs_fn(op: str) -> Callable[[int], None]:
    """Per-step incremental obs publisher (the streaming-obs
    satellite, ISSUE 10): after each panel step the driver publishes
    that step's DELTA of the staging/broadcast counters as one
    ``shard::step_obs`` instant, so a long sharded run's progress is
    visible on the event bus while it runs instead of only in the
    exit snapshot — and multi-process workers can relay the same
    increments over the result handshake
    (testing/multiproc.emit_obs_delta). The baseline lives in this
    closure (per driver invocation) and is seeded from the counters
    AT CREATION, so concurrent drivers never steal each other's
    deltas and step 0 reports only this driver's work — not whatever
    earlier drivers accumulated since the last metrics.reset(). Free
    when obs is disabled."""
    seed = obs_metrics.snapshot()["counters"]
    prev: Dict[str, float] = {key: seed.get(key, 0)
                              for key in _STEP_OBS_KEYS}

    def publish(k: int) -> None:
        if not obs_events.enabled():
            return
        cur = obs_metrics.snapshot()["counters"]
        delta = {key.rsplit(".", 1)[-1]:
                 cur.get(key, 0) - prev.get(key, 0)
                 for key in _STEP_OBS_KEYS}
        prev.update({key: cur.get(key, 0) for key in _STEP_OBS_KEYS})
        obs_events.instant("shard::step_obs", cat="shard", op=op,
                           step=k, **delta)

    return publish


class _ShardState:
    """Per-host trailing-panel working set: first touch stages the
    input through the engine (exact, schedule-known prefetch), later
    touches hit the stash or re-stage the spilled state from the
    host-side scratch (`ws`, allocated lazily — only spilled panels
    ever cost host scratch)."""

    def __init__(self, eng, loader: Callable[[int], Callable],
                 scratch: Callable[[int], Tuple[int, ...]],
                 dtype) -> None:
        self.eng = eng
        self._loader = loader          # k -> input loader callable
        self._scratch = scratch        # k -> spill buffer shape
        self.dtype = dtype
        self.ws: Dict[int, np.ndarray] = {}
        self.staged: set = set()

    def spill_view(self, k: int) -> Callable[[], np.ndarray]:
        def view():
            if k not in self.ws:
                self.ws[k] = np.empty(self._scratch(k), self.dtype)
            return self.ws[k]
        return view

    def take(self, k: int):
        if k not in self.staged:
            self.staged.add(k)
            return self.eng.fetch("S", k, self._loader(k), cache=False)
        return self.eng.fetch("S", k, lambda: self.ws[k])

    def prefetch_next(self, todo: List[int], i: int) -> None:
        """Exact lookahead: stage the next FIRST-TOUCH input this host
        will need (re-stages of spilled states contend with their own
        spill writes and stay synchronous)."""
        nxt = next((j for j in todo[i + 1:] if j not in self.staged),
                   None)
        if nxt is not None:
            self.eng.prefetch("S", nxt, self._loader(nxt), cache=False)

    def stash(self, k: int, arr) -> None:
        self.eng.stash("S", k, arr, self.spill_view(k))

    def discard(self, k: int) -> None:
        self.eng.discard("S", k)
        self.ws.pop(k, None)


@instrument_driver("shard_potrf_ooc")
def shard_potrf_ooc(a: np.ndarray, grid: ProcessGrid,
                    panel_cols: Optional[int] = None,
                    cache_budget_bytes=None,
                    fanin: Optional[int] = None,
                    ckpt_path: Optional[str] = None,
                    ckpt_every: Optional[int] = None) -> np.ndarray:
    """Sharded out-of-core lower Cholesky (module doc): panels owned
    2D-block-cyclically, each host staging only its shard, factor
    panels broadcast over the tree. Returns the full host-resident
    lower factor ON EVERY PROCESS (each broadcast panel is written
    back locally), bitwise equal to ``potrf_ooc``'s.

    ``ckpt_path``/``ckpt_every`` (resil/, ISSUE 9): each host keeps a
    durable per-host mirror of the factor (resil/checkpoint.py memmap
    under ``ckpt_path/host<i>``). On resume the mesh agrees on the
    MIN committed epoch (:func:`_agree_epoch`); panels below it are
    replayed from the durable local mirror — no factor work, no
    broadcast — while each host's trailing panels catch up through
    the SAME jitted update kernel on bitwise-equal operands, so the
    resumed factor is BITWISE the uninterrupted one (pinned by
    tests). FROZEN default 0 = off, bit-identical to the pre-resil
    driver."""
    from ..linalg import stream
    from ..linalg.ooc import _panel_apply, _panel_cols, _panel_factor
    a = np.asarray(a)
    n = a.shape[0]
    w = min(_panel_cols(panel_cols, n, a.dtype), n)
    nt = ceil_div(n, w)
    sched = CyclicSchedule(nt, grid)
    bc = PanelBroadcaster(grid, _shard_fanin(fanin, n, a.dtype))
    ck = _ckpt.maybe_checkpointer(
        _host_ckpt_path(ckpt_path), "shard_potrf_ooc", a, w, nt,
        every=ckpt_every)
    out = ck.factor if ck is not None else np.zeros_like(a)
    epoch = _agree_epoch(grid, ck.epoch) if ck is not None else 0
    local_dev = jax.local_devices()[0]
    eng = stream.engine_for(n, w, a.dtype,
                            budget_bytes=cache_budget_bytes,
                            device=local_dev)
    mine = sched.my_panels()
    if obs_events.enabled():
        obs_events.instant("shard::schedule", cat="shard", op="potrf",
                           nt=nt, ranks=sched.nranks, mine=len(mine),
                           resume_epoch=epoch)

    def loader(k):
        k0, k1 = k * w, min(k * w + w, n)
        return lambda: a[k0:, k0:k1]

    st = _ShardState(eng, loader,
                     lambda k: (n - k * w, min(w, n - k * w)),
                     a.dtype)
    step_obs = _step_obs_fn("potrf")
    try:
        for k in range(nt):
            _faults.check("step", op="shard_potrf_ooc", step=k)
            k0, k1 = k * w, min(k * w + w, n)
            wk = k1 - k0
            if k < epoch:
                # resume replay: panel k's factor is durable in the
                # local mirror — skip factor/broadcast/write and just
                # catch the trailing owned panels up (module doc)
                frame = stream._h2d(out[:, k0:k1])
            else:
                if sched.is_mine(k):
                    S = st.take(k)
                    with obs_events.span("shard::factor", cat="shard",
                                         panel=k):
                        Lk = _panel_factor(S, wk)
                    _guard.check_panel("shard_potrf_ooc", k, Lk,
                                       ref=S)
                    frame = stream._embed_rows(Lk, k0, n=n)
                    st.discard(k)
                else:
                    frame = None
                frame = bc.broadcast(frame, sched.owner_flat(k),
                                     (n, wk), a.dtype)
                # every host mirrors the factor panel into its own
                # copy
                eng.write("L", k, stream._suffix_rows(frame, k0,
                                                      rows=n - k0),
                          out[k0:, k0:k1])
            # trailing updates on my shard, oldest panel first — the
            # same per-panel update order as the left-looking visits.
            # On resume, owned panels BELOW the epoch are durable and
            # skip their own factor step, so updating them would
            # stage dead state into the budget for nothing
            todo = [j for j in mine if j > k and j >= epoch]
            for i, j in enumerate(todo):
                S_j = st.take(j)
                st.prefetch_next(todo, i)
                j0 = j * w
                wj = min(w, n - j0)
                Lr = stream._suffix_rows(frame, j0, rows=n - j0)
                with obs_events.span("shard::update", cat="shard",
                                     panel=j, step=k):
                    S_j = _panel_apply(S_j, Lr, wj)
                st.stash(j, S_j)
            step_obs(k)
            if ck is not None and k >= epoch and ck.due(k):
                eng.wait_writes()   # every panel <= k is durable
                ck.commit(k + 1)
        eng.wait_writes()
    finally:
        eng.finish()
    return out


@instrument_driver("shard_geqrf_ooc")
def shard_geqrf_ooc(a: np.ndarray, grid: ProcessGrid,
                    panel_cols: Optional[int] = None,
                    incore_ib: int = 128,
                    cache_budget_bytes=None,
                    fanin: Optional[int] = None,
                    ckpt_path: Optional[str] = None,
                    ckpt_every: Optional[int] = None):
    """Sharded out-of-core Householder QR: same ownership walk and
    broadcast tree as shard_potrf_ooc, full-height panel states, the
    broadcast payload carrying the factored column frame PLUS one
    extra row holding the panel's taus (one tree traversal per step
    covers both). Returns (QR_packed, taus) on every process, bitwise
    equal to ``geqrf_ooc``'s packed contract.

    ``ckpt_path``/``ckpt_every``: per-host durable factor + taus
    mirrors with the same min-epoch agreement and durable-mirror
    replay as shard_potrf_ooc (resil/, ISSUE 9)."""
    from ..linalg import stream
    from ..linalg.ooc import (_panel_cols, _qr_apply_fresh,
                              _qr_panel_factor, _qr_visit)
    a = np.asarray(a)
    m, n = a.shape
    kmax = min(m, n)
    w = min(_panel_cols(panel_cols, n, a.dtype), n)
    nt = ceil_div(n, w)
    sched = CyclicSchedule(nt, grid)
    bc = PanelBroadcaster(grid, _shard_fanin(fanin, n, a.dtype))
    ck = _ckpt.maybe_checkpointer(
        _host_ckpt_path(ckpt_path), "shard_geqrf_ooc", a, w, nt,
        every=ckpt_every, extra_arrays={"taus": ((kmax,), a.dtype)})
    if ck is not None:
        out, taus = ck.factor, ck.array("taus")
        epoch = _agree_epoch(grid, ck.epoch)
    else:
        out = np.empty_like(a)
        taus = np.zeros((kmax,), a.dtype)
        epoch = 0
    local_dev = jax.local_devices()[0]
    eng = stream.engine_for(max(m, n), w, a.dtype,
                            budget_bytes=cache_budget_bytes,
                            device=local_dev)
    mine = sched.my_panels()
    if obs_events.enabled():
        obs_events.instant("shard::schedule", cat="shard", op="geqrf",
                           nt=nt, ranks=sched.nranks, mine=len(mine))

    def loader(k):
        k0, k1 = k * w, min(k * w + w, n)
        return lambda: a[:, k0:k1]

    st = _ShardState(eng, loader,
                     lambda k: (m, min(w, n - k * w)), a.dtype)
    step_obs = _step_obs_fn("geqrf")
    factor_panels = [k for k in range(nt) if k * w < kmax]
    tail_panels = [k for k in range(nt) if k * w >= kmax]
    try:
        for k in factor_panels:
            _faults.check("step", op="shard_geqrf_ooc", step=k)
            k0, k1 = k * w, min(k * w + w, n)
            wk = k1 - k0
            wf = min(k1, kmax) - k0
            if k < epoch:
                # resume replay from the durable per-host mirror
                # (factor column + taus hold the same device bytes
                # the uninterrupted run broadcast)
                col = stream._h2d(out[:, k0:k1])
                Pk = col[:, :wf]
                tk = stream._h2d(taus[k0:k0 + wf])
            else:
                if sched.is_mine(k):
                    S = st.take(k)
                    with obs_events.span("shard::factor", cat="shard",
                                         panel=k):
                        packed, ptau = _qr_panel_factor(
                            S[:, :wf], k0, incore_ib)
                    _guard.check_panel("shard_geqrf_ooc", k,
                                       packed[:m - k0], ref=S)
                    lo = packed[:m - k0]
                    if wf < wk:
                        # kmax falls inside this panel (m < n): the
                        # tail columns are pure R rows from the fresh
                        # apply — the same composition geqrf_ooc
                        # writes piecewise
                        rest = _qr_apply_fresh(S[k0:, wf:], lo, ptau)
                        lo = jnp.concatenate([lo, rest], axis=1)
                    col = jnp.concatenate([S[:k0], lo], axis=0) \
                        if k0 > 0 else lo
                    tau_row = jnp.zeros((1, wk), a.dtype)
                    tau_row = tau_row.at[0, :wf].set(ptau[:wf])
                    payload = jnp.concatenate([col, tau_row], axis=0)
                    st.discard(k)
                else:
                    payload = None
                payload = bc.broadcast(payload, sched.owner_flat(k),
                                       (m + 1, wk), a.dtype)
                col = payload[:m]
                taus[k0:k0 + wf] = np.asarray(payload[m, :wf])
                eng.write("QR", k, col, out[:, k0:k1])
                Pk = col[:, :wf]
                tk = payload[m, :wf]
            # durable panels below the epoch skip their own factor
            # step — never stage/update them on resume
            todo = [j for j in mine if j > k and j >= epoch]
            for i, j in enumerate(todo):
                S_j = st.take(j)
                st.prefetch_next(todo, i)
                with obs_events.span("shard::update", cat="shard",
                                     panel=j, step=k):
                    S_j = _qr_visit(S_j, Pk, tk, k0)
                st.stash(j, S_j)
            step_obs(k)
            if ck is not None and k >= epoch and ck.due(k):
                eng.wait_writes()   # every panel <= k is durable
                ck.commit(k + 1)
        for k in tail_panels:
            # columns past kmax (m < n): all updates applied, the
            # state IS the final U block — one broadcast replicates it
            # so every host's packed factor is complete
            _faults.check("step", op="shard_geqrf_ooc", step=k)
            k0, k1 = k * w, min(k * w + w, n)
            if k < epoch:
                continue            # durable already
            frame = st.take(k) if sched.is_mine(k) else None
            if frame is not None:
                st.discard(k)
            frame = bc.broadcast(frame, sched.owner_flat(k),
                                 (m, k1 - k0), a.dtype)
            eng.write("QR", k, frame, out[:, k0:k1])
            if ck is not None and ck.due(k):
                eng.wait_writes()
                ck.commit(k + 1)
        eng.wait_writes()
    finally:
        eng.finish()
    return out, taus


@instrument_driver("shard_getrf_ooc")
def shard_getrf_ooc(a: np.ndarray, grid: ProcessGrid,
                    panel_cols: Optional[int] = None,
                    incore_nb: int = 1024,
                    cache_budget_bytes=None,
                    fanin: Optional[int] = None,
                    chunk: Optional[int] = None,
                    ckpt_path: Optional[str] = None,
                    ckpt_every: Optional[int] = None):
    """Sharded out-of-core tournament-pivot LU (module doc — the PR 7
    deferral, closed): same ownership walk and broadcast tree as
    shard_potrf_ooc, full-height panel states kept in ORIGINAL row
    order, the owner of panel k finalizing its pivot permutation via
    the CALU tournament BEFORE the factor column is written. The
    broadcast payload is the (m, wk) original-order factor column
    plus ONE extra row carrying the panel's live-relative pivot-row
    selection (encoded in the panel dtype the way the QR frame
    carries tau — exact for row counts below the dtype's integer
    window, 2^24 for f32); every host rederives (ipiv, permutation)
    from that row with the same host simulation
    (lu.tnt_swaps_host), so the bookkeeping is identical across the
    mesh with no extra coordination traffic. Returns (LU_packed,
    ipiv) in getrf_ooc's LAPACK packed contract ON EVERY PROCESS,
    BITWISE equal to the single-engine ``getrf_tntpiv_ooc`` — the
    trailing updates run the SAME jitted ``_lu_visit_orig`` kernel on
    bitwise-equal operands in the same per-panel order, and the
    factor columns never change after their step (no fixup, no
    cross-shard invalidation). Pinned by tests incl. a real
    2-process gloo mesh.

    ``ckpt_path``/``ckpt_every``: per-host durable mirrors of the
    original-order factor, ipiv, and the per-panel permutation
    snapshots (the "per-host pivot vectors" of the durable epoch),
    with the same min-epoch agreement and durable-mirror replay as
    shard_potrf_ooc; the meta records ``lu_pivot="tournament"`` so a
    mode-mismatched resume starts fresh (resil/checkpoint.py)."""
    from ..core.exceptions import slate_assert
    from ..linalg import stream
    from ..linalg.ca import fix_degenerate_selection
    from ..linalg.lu import tnt_swaps_host
    from ..linalg.ooc import (_lu_visit_orig, _panel_cols,
                              _tnt_factor, _tnt_select,
                              _tnt_tail_cols, _finalize_lapack_order)
    a = np.asarray(a)
    m, n = a.shape
    # the pivot payload row rides the matrix dtype: row indices must
    # sit inside its exact-integer window or np.rint decodes WRONG
    # rows silently — make it a loud error instead
    slate_assert(
        m <= (1 << (np.finfo(a.dtype).nmant + 1)),
        "shard_getrf_ooc encodes pivot rows in the %s payload row; "
        "m=%d exceeds its exact-integer window %d — use a wider "
        "dtype or the single-engine getrf_tntpiv_ooc"
        % (np.dtype(a.dtype).name, m,
           1 << (np.finfo(a.dtype).nmant + 1)))
    kmax = min(m, n)
    w = min(_panel_cols(panel_cols, n, a.dtype), n)
    nt = ceil_div(n, w)
    nf = ceil_div(kmax, w)
    sched = CyclicSchedule(nt, grid)
    bc = PanelBroadcaster(grid, _shard_fanin(fanin, n, a.dtype))
    ck = _ckpt.maybe_checkpointer(
        _host_ckpt_path(ckpt_path), "shard_getrf_ooc", a, w, nt,
        every=ckpt_every,
        extra_arrays={"ipiv": ((kmax,), np.int64),
                      "perms": ((nf, m), np.int64)},
        extra_meta={"lu_pivot": "tournament"})
    if ck is not None:
        stored, ipiv = ck.factor, ck.array("ipiv")
        perms = ck.array("perms")
        epoch = _agree_epoch(grid, ck.epoch)
    else:
        stored = np.empty_like(a)
        ipiv = np.empty((kmax,), np.int64)
        perms = np.empty((nf, m), np.int64)
        epoch = 0
    perm = perms[min(epoch, nf) - 1].copy() if min(epoch, nf) > 0 \
        else np.arange(m)
    local_dev = jax.local_devices()[0]
    eng = stream.engine_for(max(m, n), w, a.dtype,
                            budget_bytes=cache_budget_bytes,
                            device=local_dev)
    mine = sched.my_panels()
    if obs_events.enabled():
        obs_events.instant("shard::schedule", cat="shard", op="getrf",
                           nt=nt, ranks=sched.nranks, mine=len(mine),
                           resume_epoch=epoch)

    def loader(k):
        k0, k1 = k * w, min(k * w + w, n)
        return lambda: a[:, k0:k1]

    st = _ShardState(eng, loader,
                     lambda k: (m, min(w, n - k * w)), a.dtype)
    step_obs = _step_obs_fn("getrf")
    factor_panels = [k for k in range(nt) if k * w < kmax]
    tail_panels = [k for k in range(nt) if k * w >= kmax]
    try:
        for k in factor_panels:
            _faults.check("step", op="shard_getrf_ooc", step=k)
            k0, k1 = k * w, min(k * w + w, n)
            wk = k1 - k0
            wf = min(k1, kmax) - k0
            live = m - k0
            if k < epoch:
                # resume replay: factor column, ipiv, and permutation
                # snapshot are durable in the per-host mirror — skip
                # select/factor/broadcast and catch the trailing
                # owned panels up from the mirror (module doc)
                colfull = stream._h2d(stored[:, k0:k1])
                perm = perms[k].copy()
                Pk = colfull[:, :wf]
            else:
                if sched.is_mine(k):
                    S = st.take(k)
                    idx = np.concatenate([perm[k0:], perm[:k0]])
                    with obs_events.span("shard::factor", cat="shard",
                                         panel=k):
                        sel = _tnt_select(S, jnp.asarray(idx), live,
                                          wf, chunk=chunk)
                    sel = fix_degenerate_selection(np.asarray(sel),
                                                   live, wf)
                    _piv, lperm = tnt_swaps_host(sel, live)
                    new_live = perm[k0:][lperm]
                    idx2 = np.concatenate([new_live, perm[:k0]])
                    col, packed = _tnt_factor(
                        S, jnp.asarray(idx2), live, wf,
                        min(int(incore_nb), max(wf, 1)))
                    _guard.check_panel("shard_getrf_ooc", k, col,
                                       ref=S)
                    if wf < wk:
                        # kmax inside this panel (m < n): the pure-U
                        # tail columns join the broadcast column
                        tail = _tnt_tail_cols(S, packed, new_live, wf)
                        colfull = jnp.concatenate([col, tail], axis=1)
                    else:
                        colfull = col
                    sel_row = jnp.zeros((1, wk), a.dtype)
                    sel_row = sel_row.at[0, :wf].set(
                        jnp.asarray(sel).astype(a.dtype))
                    payload = jnp.concatenate([colfull, sel_row],
                                              axis=0)
                    st.discard(k)
                else:
                    payload = None
                payload = bc.broadcast(payload, sched.owner_flat(k),
                                       (m + 1, wk), a.dtype)
                colfull = payload[:m]
                sel = np.rint(
                    np.asarray(payload[m, :wf]).real).astype(np.int64)
                # EVERY host (owner included) rederives the pivot
                # bookkeeping from the broadcast selection — one
                # deterministic function of one broadcast value
                piv_rel, lperm = tnt_swaps_host(sel, live)
                perm[k0:] = perm[k0:][lperm]
                ipiv[k0:k0 + wf] = k0 + piv_rel
                perms[k] = perm
                eng.write("LU", k, colfull, stored[:, k0:k1])
                Pk = colfull[:, :wf]
            # durable panels below the epoch skip their own factor
            # step — never stage/update them on resume
            todo = [j for j in mine if j > k and j >= epoch]
            if todo:   # no owned trailing panels -> no index upload
                g = jnp.asarray(perms[k].astype(np.int32))
            for i, j in enumerate(todo):
                S_j = st.take(j)
                st.prefetch_next(todo, i)
                with obs_events.span("shard::update", cat="shard",
                                     panel=j, step=k):
                    S_j = _lu_visit_orig(S_j, Pk, g, k0)
                st.stash(j, S_j)
            step_obs(k)
            if ck is not None and k >= epoch and ck.due(k):
                eng.wait_writes()   # every panel <= k is durable
                ck.commit(k + 1)
        for k in tail_panels:
            # columns past kmax (m < n): all updates applied, the
            # original-order state IS the final U block — one
            # broadcast replicates it so every host's factor is
            # complete
            _faults.check("step", op="shard_getrf_ooc", step=k)
            k0, k1 = k * w, min(k * w + w, n)
            if k < epoch:
                continue            # durable already
            frame = st.take(k) if sched.is_mine(k) else None
            if frame is not None:
                st.discard(k)
            frame = bc.broadcast(frame, sched.owner_flat(k),
                                 (m, k1 - k0), a.dtype)
            eng.write("LU", k, frame, stored[:, k0:k1])
            if ck is not None and ck.due(k):
                eng.wait_writes()
                ck.commit(k + 1)
        eng.wait_writes()
    finally:
        eng.finish()
    if ck is not None:
        out = _finalize_lapack_order(stored, perm, w,
                                     out=np.empty_like(stored))
        return out, np.array(ipiv)
    return _finalize_lapack_order(stored, perm, w), ipiv
