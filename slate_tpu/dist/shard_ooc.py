"""Sharded out-of-core execution layer (ISSUE 7 tentpole): the
composition of dist/'s explicit-schedule tree engine and the
linalg/stream.py panel-residency engine — the SLATE distribution model
(PAPER.md §1) carried to the beyond-HBM regime.

The two existing halves each cap the problem size at one device's
pipe: dist/ shards IN-HBM problems across a mesh, and stream.py
streams BEYOND-HBM problems host<->one device. Composed, panels are
assigned **2D-block-cyclically to mesh positions** and each host's
StreamEngine stages only its local shard's panels — the aggregate
host-RAM/HBM pipe of the whole pod, which is exactly how "Large Scale
Distributed Linear Algebra With TPUs" (PAPERS.md) reaches
beyond-single-chip n (and JAXMg shows carries to GPU meshes with
different constants).

Schedule shape (right-looking, the reference's potrf.cc/geqrf.cc panel
loop):

  * ``CyclicSchedule`` — panel k of the column stream is owned by the
    mesh position reached by the column-major cyclic walk
    ``(k mod p, (k // p) mod q)`` (the GridOrder.Col convention of
    parallel/mesh.py; the diagonal-ownership walk of the SLATE
    2D-block-cyclic tile map at panel granularity — tile-level row
    distribution within a panel column is the further step). Ownership
    is STATIC, so every host knows, before the stream starts, exactly
    which panels it will stage and in what order — prefetch becomes
    exact rather than heuristic (asserted by test via the obs h2d
    counters: an eviction-free run stages precisely the owned inputs,
    nothing else).
  * per step k: the owner factors its panel in-core (the SAME jitted
    panel kernels as the single-device stream), then ``PanelBroadcaster``
    replicates the factor panel over the dist/tree.py ppermute combine
    tree — payload on the owner's device, exact zeros elsewhere, a
    log-depth add-combine (x + 0 is exact, the dist/tuneshare
    transport shape carried to float panels; fan-in is the
    ``ooc/shard_fanin`` tunable and the scheduled ppermute count lands
    in the obs comms accounting like every tree traversal). Under the
    cyclic walk every position owns trailing panels, so the consumer
    set is the whole grid — the row/column-restricted broadcast of a
    true 2D tile decomposition degenerates to the full tree here.
  * every host applies the broadcast factor to the trailing panels it
    owns (``StreamEngine.stash`` keeps those working states
    device-resident under the per-host HBM budget, spilling evicted
    ones through the async D2H writer), while the engine's prefetch
    thread stages the host's NEXT first-touch input — the reference's
    lookahead, reconstructed from the two existing primitives.

Bit-identity: the right-looking schedule applies, to every panel, the
same update sequence (factors 0..k-1 in order) through the SAME jitted
kernels on bitwise-equal operands as the single-device left-looking
stream, so ``shard_potrf_ooc``/``shard_geqrf_ooc`` reproduce
``potrf_ooc``/``geqrf_ooc`` results exactly — including at budget 0,
where every stash degenerates to write-through (the uncached
schedule). Pinned by tests on the single-process mesh.

Routing: the linalg/ooc.py drivers take ``grid=``/``method=`` and
arbitrate through core/methods.MethodOOC — the FROZEN
``ooc/shard_method`` default is "stream", so a cold cache keeps the
single-device path bit-identically even when a grid is supplied.

Lookahead v2 (ISSUE 11): the schedule above is step-synchronous —
every host idles while panel k's broadcast completes, then idles
again while the owner of k+1 factors it. SLATE's defining perf trick
(PAPER.md: the lookahead parameter overlapping critical-path panel
work with trailing updates; BLASX is the multi-accelerator
communication/computation-overlap precedent) has an exact mesh-scale
analogue built here as ``_BcastPipeline``: at step k, after frame k
completes, the owner of panel k+1 applies its OWN k-update first
(``CyclicSchedule.update_order`` — owned-next-panel-first), factors
k+1 immediately, and every host dispatches the k+1 broadcast
asynchronously (``PanelBroadcaster.broadcast_async`` — a second
in-flight frame buffer, the way linalg/stream.py double-buffers H2D)
BEFORE running its remaining k-updates; the frame is completed
(``PanelBroadcaster.complete`` -> dist/tree.complete_schedule) only
at step k+1, so the collective's wall hides under the update sweep.
The reordering changes only WHEN identical jitted kernels run, never
their operands — each trailing panel still receives updates
0..k-1 in ascending order through the same compiled programs — so
every depth is BITWISE equal to the synchronous schedule (pinned for
all three drivers, single-engine and on the real 2-process gloo
mesh). Depth rides the FROZEN ``ooc/shard_lookahead`` = 0 tunable
(the synchronous schedule bit-identically; depth 1 is the
earned/explicit setting), the per-step broadcast wait is published
as the ``shard::bcast_wait`` span + ``ooc.shard.bcast_wait_seconds``
counter so the overlap fraction is directly attributable, and the
checkpoint epoch commit trails the deepest in-flight panel (a crash
with two panels live resumes bitwise — the in-flight panel was never
claimed durable).

Mixed-precision frames (ISSUE 12): under the ``ooc/precision`` bf16
mode (FROZEN "f32" — the cold cache keeps every schedule here
bit-identically) the owner demotes the factor frame BEFORE the tree,
so every ppermute hop carries half the bytes (``ooc.shard.bcast_
bytes`` shows exactly the halving); every host applies the lo frame
through the mixed visit kernels (linalg/ooc.py ``*_mx``) and mirrors
the PROMOTED frame into its host factor, so owner and non-owner
copies stay identical across the mesh — the whole mesh's factor is
the bf16-update one, the pod-scale reduced-precision play of the TPU
distributed-linalg paper, with the OOC solves' refinement as the
accuracy contract. The LU pivot selection, whose row indices exceed
bf16's 256-integer window, rides a byte-split PAIR of payload rows
(hi*256 + lo, both exact), keeping the one-frame-per-panel
transport.

``shard_getrf_ooc`` (ISSUE 10) closes the LU deferral that PR 7
recorded: partial pivoting's host-side row-swap fixup rewrites rows
of already-written L panels — under sharding, an epoch-bump broadcast
plus a re-stage storm per cross-panel pivot. The unlock is CALU-style
tournament pivoting (linalg/ca.tournament_pivot_rows, the structure
"Large Scale Distributed Linear Algebra With TPUs" uses for
TPU-distributed LU): the owner finalizes panel k's pivot permutation
BEFORE the factor column is written, the factor is stored in ORIGINAL
row order with the permutation applied at visit time by a device
index gather (ooc._lu_visit_orig), and the broadcast frame carries
the panel's pivot-row selection as one extra payload row the way the
QR frame carries tau — every host rederives the identical permutation
bookkeeping from that row, no retroactive fixup, no cross-shard
invalidation. Results are BITWISE equal to the single-engine
``getrf_tntpiv_ooc`` (same kernels, same operands per (panel, step)
pair); routing is earned the same way (MethodOOC; the partial-pivot
mode never shards).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tiles import ceil_div
from ..obs import events as obs_events
from ..obs import health as _health
from ..obs import ledger as _ledger
from ..obs import metrics as obs_metrics
from ..obs.events import instrument_driver
from ..parallel.mesh import ProcessGrid
from ..parallel.smap import shard_map
from ..resil import checkpoint as _ckpt
from ..resil import faults as _faults
from ..resil import guard as _guard
from . import tree as _tree


class CyclicSchedule:
    """Static 2D-block-cyclic panel->mesh-position ownership map (one
    per driver invocation; module doc). The schedule is global
    knowledge — every process computes the same map, which is what
    makes the SPMD broadcast loop and the exact per-host prefetch
    possible without any coordination traffic."""

    def __init__(self, nt: int, grid: ProcessGrid) -> None:
        self.nt = int(nt)
        self.grid = grid
        self.p, self.q = grid.p, grid.q
        self.devs = list(grid.mesh.devices.flat)   # row-major (p, q)

    @property
    def nranks(self) -> int:
        return self.p * self.q

    def owner_coords(self, k: int) -> Tuple[int, int]:
        """Grid position owning panel k: the column-major cyclic walk
        ('p' advances fastest — GridOrder.Col, mesh.py)."""
        return k % self.p, (k // self.p) % self.q

    def owner_flat(self, k: int) -> int:
        """Index of the owner in the row-major flattened device list
        (the broadcast-tree position)."""
        r, c = self.owner_coords(k)
        return r * self.q + c

    def owner_device(self, k: int):
        return self.devs[self.owner_flat(k)]

    def owner_process(self, k: int) -> int:
        return self.owner_device(k).process_index

    def is_mine(self, k: int) -> bool:
        return self.owner_process(k) == jax.process_index()

    def my_panels(self) -> List[int]:
        """Panels THIS PROCESS stages, in factoring order — the exact
        per-host touch schedule prefetch runs on."""
        return [k for k in range(self.nt) if self.is_mine(k)]

    def update_order(self, k: int, depth: int = 0,
                     epoch: int = 0) -> List[int]:
        """Step k's trailing-update order for THIS process: the
        owned-next-panel-first query (ISSUE 11). Panels inside the
        lookahead window ``(k, k+depth]`` come first — the owner of
        panel k+1 must finish that panel's k-update before ANY host
        can see frame k+1, so its update is the mesh's critical path
        — then the remaining owned trailing panels in ascending
        order. Because the window panels ARE the smallest trailing
        indices, the sequence is IDENTICAL for every depth (the
        promoted head is a prefix of the synchronous walk) — that
        prefix property is exactly why the lookahead reordering is
        bitwise-safe and why :meth:`staged_bytes`'s walk is
        depth-invariant, and this query is where it is stated and
        tested rather than assumed. ``_BcastPipeline.updates`` runs
        the sweep in this order; the prologue's promotion set is the
        window-∩-owned prefix (computed by ``advance`` as it chains
        issues). Panels below ``epoch`` are durable on resume and
        never re-updated (resil/ contract)."""
        todo = [j for j in self.my_panels() if j > k and j >= epoch]
        if depth <= 0:
            return todo
        head = [j for j in todo if j <= k + depth]
        return head + [j for j in todo if j > k + depth]

    def staged_bytes(self, heights: Dict[int, int], width: int,
                     last_width: int, itemsize: int,
                     depth: int = 0) -> int:
        """Exact bytes this process's engine stages in an
        eviction-free run: each owned panel's input once, summed by
        walking the schedule (factor touch, then the step's update
        order) and charging first touches. `heights[k]` is panel k's
        staged row count (n - k0 for the triangular stream, m for the
        full-height QR stream). ``depth`` selects the lookahead walk
        (ISSUE 11): the promotion reorders WITHIN a step but the
        first-touch set and its step assignment are unchanged, so the
        prediction is depth-invariant — asserted by test, and what
        keeps the exact-schedule assertions in ``bench.py --shard``
        green at every depth."""
        total = 0
        touched: set = set()
        for k in range(self.nt):
            walk = ([k] if self.is_mine(k) else []) \
                + self.update_order(k, depth)
            for j in walk:
                if j in touched:
                    continue
                touched.add(j)
                w = last_width if j == self.nt - 1 else width
                total += heights[j] * w * itemsize
        return total


#: compiled broadcast programs, shared ACROSS driver invocations on
#: the same mesh (Mesh is hashable): without this every stream would
#: re-trace the tree per call — the jit cache keys on the closure
#: object, which a per-instance builder would recreate. Bounded in
#: practice: one entry per (mesh, panel shape, dtype, fanin).
_BCAST_FNS: Dict[Tuple, Callable] = {}


def _bcast_fn(mesh, shape: Tuple[int, ...], dtype, fanin: int,
              size: int) -> Callable:
    key = (mesh, tuple(shape), np.dtype(dtype).str, fanin)
    fn = _BCAST_FNS.get(key)
    if fn is not None:
        return fn
    # cache-stats counter (ISSUE 11 satellite): one increment per NEW
    # compiled broadcast program. tau/pivot payload rows change the
    # shape per driver, and the lookahead's second frame buffer reuses
    # the SAME programs — a whole stream must cost <= one compile per
    # distinct payload shape regardless of depth (pinned by test, so
    # a pipeline regression cannot silently double the compile count)
    obs_metrics.inc("ooc.shard.bcast_compiles")

    def combine(xs):
        return _tree.tree_combine(
            xs, lambda vals: functools.reduce(jnp.add, vals),
            ("p", "q"), size, fanin=fanin)

    fn = jax.jit(shard_map(
        combine, mesh=mesh,
        in_specs=P(("p", "q"), *([None] * len(shape))),
        out_specs=P(), check_vma=False))
    _BCAST_FNS[key] = fn
    return fn


class _InflightFrame:
    """One dispatched-but-uncompleted broadcast — the lookahead's
    second frame buffer (module doc). Holds the replicated global
    array (the collective is already running in the backend's async
    stream), the panel index, and the dispatch timestamp the overlap
    accounting keys on."""

    __slots__ = ("out", "panel", "issued_at")

    def __init__(self, out, panel: Optional[int]) -> None:
        self.out = out
        self.panel = panel
        self.issued_at = time.perf_counter()


class PanelBroadcaster:
    """Factor-panel broadcast over the dist/tree.py combine engine:
    the owner's device holds the payload, every other mesh position
    holds exact zeros, and a log-depth add-combine replicates it
    bitwise (x + 0.0 is exact for finite x). One compiled program per
    (mesh, payload shape) — cached across invocations and counted by
    ``ooc.shard.bcast_compiles`` — so a whole stream costs at most
    one compile per distinct payload shape (full panels + the narrow
    tail) at ANY lookahead depth. Each traversal publishes its
    scheduled ppermute count to the obs comms accounting
    (tree.record_schedule), exactly like tsqr/stedc.

    ``broadcast_async`` / ``complete`` split one broadcast into
    dispatch and deferred completion (ISSUE 11): dispatch returns an
    :class:`_InflightFrame` immediately (the jitted traversal runs in
    the backend's async stream), completion blocks only when the
    frame's values are first needed — the wall it fails to hide is
    the ``shard::bcast_wait`` span / ``ooc.shard.bcast_wait_seconds``
    counter, and 1 - wait/in-flight is the overlap fraction
    ``bench.py --shard`` reports per depth. ``broadcast`` composes
    the two (the synchronous form the tail panels keep)."""

    def __init__(self, grid: ProcessGrid, fanin: int = 2) -> None:
        self.grid = grid
        self.fanin = max(int(fanin), 2)
        self.mesh = grid.mesh
        self.devs = list(grid.mesh.devices.flat)
        self.size = len(self.devs)
        self._zeros: Dict[Tuple, Any] = {}
        self.panels = 0
        self.bytes = 0
        # overlap accounting (seconds; plain attributes so the stats
        # read with obs off, like StreamEngine's)
        self.wait_seconds = 0.0
        self.inflight_seconds = 0.0
        self.ahead = 0

    def _fn(self, shape: Tuple[int, ...], dtype) -> Callable:
        return _bcast_fn(self.mesh, shape, dtype, self.fanin,
                         self.size)

    def _zero(self, dev, shape: Tuple[int, ...], dtype):
        key = (dev.id, tuple(shape), np.dtype(dtype).str)
        z = self._zeros.get(key)
        if z is None:
            z = jax.device_put(jnp.zeros((1,) + tuple(shape), dtype),
                               dev)
            self._zeros[key] = z
        return z

    def broadcast_async(self, payload, owner_flat: int,
                        shape: Tuple[int, ...], dtype,
                        panel: Optional[int] = None,
                        ahead: bool = False) -> _InflightFrame:
        """Dispatch the replication of `payload` ((shape)-shaped
        device array on the OWNER process; ignored elsewhere) from
        mesh position `owner_flat` and return the in-flight frame
        WITHOUT waiting for the collective — jit dispatch is async,
        so the traversal executes in the backend stream while the
        caller keeps issuing work. Every process must call in
        lockstep (SPMD collective); the values are realized by
        :meth:`complete`. ``ahead=True`` marks a lookahead issue (the
        ``ooc.shard.bcast_ahead`` counter the cold-route pin reads —
        the frozen depth 0 must never dispatch ahead)."""
        me = jax.process_index()
        shards = []
        for i, dev in enumerate(self.devs):
            if dev.process_index != me:
                continue
            if i == owner_flat:
                shards.append(jax.device_put(
                    jnp.reshape(payload, (1,) + tuple(shape)), dev))
            else:
                shards.append(self._zero(dev, shape, dtype))
        sharding = NamedSharding(
            self.mesh, P(("p", "q"), *([None] * len(shape))))
        garr = jax.make_array_from_single_device_arrays(
            (self.size,) + tuple(shape), sharding, shards)
        nb = int(np.dtype(dtype).itemsize) * int(np.prod(shape))
        self.panels += 1
        self.bytes += nb
        if ahead:
            self.ahead += 1

        def traverse():
            # record_schedule's resil hook IS the `ppermute` injection
            # site, so it lives inside the retried unit: an injected
            # collective fault re-runs the whole traversal (every
            # host retries in lockstep — the occurrence counters are
            # per-process deterministic). A lookahead issue makes the
            # IN-FLIGHT frame the injection site: the fault fires at
            # dispatch, one step before the frame's values are used
            self._check_faults()
            return self._fn(tuple(shape), dtype)(garr)

        def run():
            if _faults.active() is not None:
                return _guard.retry(traverse, "ppermute",
                                    op="shard_bcast", size=self.size)
            try:
                return traverse()
            except Exception as e:
                # a REAL transient collective failure (not injected)
                # takes the same bounded retry
                if not _guard.is_transient(e):
                    raise
                return _guard.retry_after_failure(
                    traverse, "ppermute", e,
                    op="shard_bcast", size=self.size)

        if obs_events.enabled():
            obs_metrics.inc("ooc.shard.bcast_panels")
            obs_metrics.inc("ooc.shard.bcast_bytes", nb)
            if ahead:
                obs_metrics.inc("ooc.shard.bcast_ahead")
            with obs_events.span("shard::bcast", cat="shard",
                                 owner=owner_flat, bytes=nb,
                                 ahead=ahead):
                out = run()
        else:
            out = run()
        return _InflightFrame(out, panel)

    def _check_faults(self) -> None:
        _tree.record_schedule("shard_bcast", self.size, self.fanin)

    def complete(self, fr: _InflightFrame):
        """Realize an in-flight frame: block until the collective's
        local shard is ready and return the replicated panel. The
        blocked wall is the per-step ``shard::bcast_wait`` span and
        the ``ooc.shard.bcast_wait_seconds`` counter; issue-to-
        completion lands in ``ooc.shard.bcast_inflight_seconds`` so
        overlap = 1 - wait/in-flight is directly attributable
        (ISSUE 11 obs satellite)."""
        arr = fr.out.addressable_data(0)[0]
        if obs_events.enabled():
            with obs_events.span("shard::bcast_wait", cat="shard",
                                 panel=fr.panel):
                wait = _tree.complete_schedule("shard_bcast", arr)
        else:
            wait = _tree.complete_schedule("shard_bcast", arr)
        inflight = time.perf_counter() - fr.issued_at
        self.wait_seconds += wait
        self.inflight_seconds += inflight
        # flight-recorder leaf: the blocked completion wall is THE
        # collective-wait phase of the step record (obs/ledger.py)
        _ledger.credit("bcast_wait", wait)
        if obs_events.enabled():
            obs_metrics.inc("ooc.shard.bcast_wait_seconds", wait)
            obs_metrics.inc("ooc.shard.bcast_inflight_seconds",
                            inflight)
        return arr

    def overlap_fraction(self) -> float:
        """Fraction of the total issue-to-completion wall the
        schedule hid behind other work (0.0 for the synchronous
        schedule, which completes every frame at its dispatch site)."""
        if self.inflight_seconds <= 0:
            return 0.0
        return max(0.0, 1.0 - self.wait_seconds
                   / self.inflight_seconds)

    def broadcast(self, payload, owner_flat: int,
                  shape: Tuple[int, ...], dtype,
                  panel: Optional[int] = None):
        """The synchronous form: dispatch + immediate completion
        (depth-0 factor steps and the m<n tail panels)."""
        return self.complete(self.broadcast_async(
            payload, owner_flat, shape, dtype, panel=panel))


def _shard_fanin(fanin: Optional[int], n: int, dtype) -> int:
    if fanin:
        return int(fanin)
    from ..tune.select import resolve
    return int(resolve("ooc", "shard_fanin", n=n, dtype=dtype))


def _shard_lookahead(lookahead: Optional[int], n: int, dtype) -> int:
    """Broadcast-pipeline depth: explicit argument > the tuned/frozen
    ``ooc/shard_lookahead`` row (core/methods.MethodOOC.lookahead;
    FROZEN 0 = the step-synchronous schedule, bit-identical)."""
    if lookahead is not None:
        return max(int(lookahead), 0)
    from ..core.methods import MethodOOC
    return MethodOOC.lookahead(n, dtype)


def _panel_bounds(k: int, w: int, n: int, kmax: int
                  ) -> Tuple[int, int, int, int]:
    """Panel k's (k0, k1, wk, wf): column window, its width, and the
    factored-column count (wf < wk only when kmax = min(m, n) falls
    inside the panel — the m<n boundary the QR/LU payload builders
    share)."""
    k0, k1 = k * w, min(k * w + w, n)
    return k0, k1, k1 - k0, min(k1, kmax) - k0


def _host_ckpt_path(path: Optional[str]) -> Optional[str]:
    """Per-host checkpoint directory under the shared `path`: hosts
    snapshot their LOCAL factor mirror independently (each writes
    every factor panel through its own engine), so two processes on
    one filesystem must not share memmaps or meta."""
    if path is None:
        return None
    return os.path.join(path, "host%d" % jax.process_index())


def _agree_epoch(grid: ProcessGrid, epoch: int) -> int:
    """Checkpoint-resume epoch agreement (resil/, ISSUE 9): hosts
    crash at different commit points, so the mesh resumes at the MIN
    committed epoch — a tree min-reduction over every device (the
    dist/tuneshare transport shape). Single-process meshes short-
    circuit (every device is this host's epoch)."""
    devs = list(grid.mesh.devices.flat)
    if len({d.process_index for d in devs}) == 1:
        return int(epoch)
    from ..parallel.collectives import tree_allreduce
    me = jax.process_index()
    shards = [jax.device_put(jnp.asarray([epoch], jnp.int32), d)
              for d in devs if d.process_index == me]
    sharding = NamedSharding(grid.mesh, P(("p", "q")))
    garr = jax.make_array_from_single_device_arrays(
        (len(devs),), sharding, shards)
    out = tree_allreduce(grid, garr, op=jnp.minimum)
    return int(np.asarray(out.addressable_data(0))[0])


#: counters each per-step obs record reports as deltas
_STEP_OBS_KEYS = ("ooc.h2d_bytes", "ooc.d2h_bytes",
                  "ooc.shard.bcast_panels", "ooc.shard.bcast_bytes")


def _step_obs_fn(op: str) -> Callable[[int], None]:
    """Per-step incremental obs publisher (the streaming-obs
    satellite, ISSUE 10): after each panel step the driver publishes
    that step's DELTA of the staging/broadcast counters as one
    ``shard::step_obs`` instant, so a long sharded run's progress is
    visible on the event bus while it runs instead of only in the
    exit snapshot — and multi-process workers can relay the same
    increments over the result handshake
    (testing/multiproc.emit_obs_delta). The baseline lives in this
    closure (per driver invocation) and is seeded from the counters
    AT CREATION, so concurrent drivers never steal each other's
    deltas and step 0 reports only this driver's work — not whatever
    earlier drivers accumulated since the last metrics.reset(). Free
    when obs is disabled."""
    seed = obs_metrics.snapshot()["counters"]
    prev: Dict[str, float] = {key: seed.get(key, 0)
                              for key in _STEP_OBS_KEYS}

    def publish(k: int) -> None:
        if not obs_events.enabled():
            return
        cur = obs_metrics.snapshot()["counters"]
        delta = {key.rsplit(".", 1)[-1]:
                 cur.get(key, 0) - prev.get(key, 0)
                 for key in _STEP_OBS_KEYS}
        prev.update({key: cur.get(key, 0) for key in _STEP_OBS_KEYS})
        obs_events.instant("shard::step_obs", cat="shard", op=op,
                           step=k, **delta)

    return publish


class _ShardState:
    """Per-host trailing-panel working set: first touch stages the
    input through the engine (exact, schedule-known prefetch), later
    touches hit the stash or re-stage the spilled state from the
    host-side scratch (`ws`, allocated lazily — only spilled panels
    ever cost host scratch).

    ``upto`` is the in-flight-frame bookkeeping (ISSUE 11): the next
    update step each owned panel has NOT yet absorbed. The lookahead
    prologue promotes a panel through its pending frames and marks
    them applied, so the step's own update sweep skips it — with two
    panels live at once this is what keeps every panel's per-step
    update sequence exactly the synchronous walk's (bitwise pin), and
    what keeps prefetch exact (a promoted panel is `staged`, so the
    sweep's lookahead never re-stages it)."""

    def __init__(self, eng, loader: Callable[[int], Callable],
                 scratch: Callable[[int], Tuple[int, ...]],
                 dtype) -> None:
        self.eng = eng
        self._loader = loader          # k -> input loader callable
        self._scratch = scratch        # k -> spill buffer shape
        self.dtype = dtype
        self.ws: Dict[int, np.ndarray] = {}
        self.staged: set = set()
        #: panel -> next update step it still needs (in-flight slot)
        self.upto: Dict[int, int] = {}

    def applied_through(self, j: int) -> int:
        return self.upto.get(j, 0)

    def mark_applied(self, j: int, step: int) -> None:
        self.upto[j] = step + 1

    def spill_view(self, k: int) -> Callable[[], np.ndarray]:
        def view():
            if k not in self.ws:
                self.ws[k] = np.empty(self._scratch(k), self.dtype)
            return self.ws[k]
        return view

    def take(self, k: int):
        if k not in self.staged:
            self.staged.add(k)
            return self.eng.fetch("S", k, self._loader(k), cache=False)
        return self.eng.fetch("S", k, lambda: self.ws[k])

    def prefetch_panel(self, k: Optional[int]) -> None:
        """Exact lookahead by panel index: stage k's first-touch input
        unless it is already staged (re-stages of spilled states
        contend with their own spill writes and stay synchronous).
        The graph policy binds the prefetch target statically at
        construction (sched/policies.py), the walk derives it from the
        live todo list via prefetch_next — same H2D either way."""
        if k is not None and k not in self.staged:
            self.eng.prefetch("S", k, self._loader(k), cache=False)

    def prefetch_next(self, todo: List[int], i: int) -> None:
        """Exact lookahead: stage the next FIRST-TOUCH input this host
        will need."""
        self.prefetch_panel(
            next((j for j in todo[i + 1:] if j not in self.staged),
                 None))

    def stash(self, k: int, arr) -> None:
        self.eng.stash("S", k, arr, self.spill_view(k))

    def discard(self, k: int) -> None:
        self.eng.discard("S", k)
        self.ws.pop(k, None)


class _BcastPipeline:
    """The lookahead-overlapped broadcast schedule (ISSUE 11 tentpole;
    module doc). Depth 0 IS the step-synchronous schedule — no frame
    is ever dispatched ahead, bit-identical to the pre-lookahead
    drivers. Each driver supplies four closures over its own kernels
    and bookkeeping:

      * ``payload_shape(k)`` -> (shape, dtype) of panel k's broadcast
        frame (potrf: (n, wk); geqrf/getrf: (m+1, wk) — the extra
        payload row);
      * ``make_payload(k, S)`` -> the owner-side device payload from
        the fully-updated panel state S (factor kernels +
        guard.check_panel live here);
      * ``complete(k, replicated)`` -> the step's update record
        (host-side bookkeeping — taus/pivot materialization, the
        local factor-mirror write — runs HERE, exactly once per
        panel, in strictly ascending panel order);
      * ``replay(k)`` -> the update record from the durable per-host
        mirror (resume panels below the agreed epoch — no factor
        work, no broadcast);
      * ``apply(S, rec, j)`` -> panel j's state after absorbing the
        record's update (the SAME jitted visit kernel at every
        depth).

    Step k runs three phases: ``obtain(k)`` (phase 1 — the completed
    record for panel k: popped from ``done``, completed from
    ``pending``, replayed, or — synchronous path — factored +
    broadcast + completed inline), ``advance(k, rec)`` (phase 2 — the
    lookahead prologue: for each panel in ``(k, k+depth]`` this
    process owns, promote it through its pending frames via the SAME
    apply closure (``CyclicSchedule.update_order``'s head — the
    owned-next-panel-first rule), factor it, and dispatch its
    broadcast WITHOUT completing it; chaining past depth 1 completes
    the intermediate frame first, since panel i's factor needs frame
    i-1's values), then ``updates(k, rec)`` (phase 3 — the trailing
    sweep over the remaining owned panels, which overlaps every
    in-flight collective). The per-panel ``step`` fault check fires
    exactly once per panel, at the slot that PROCESSES it (issue
    time for ahead panels) — the same ascending once-each sequence as
    the synchronous walk, so seeded plans stay deterministic across
    depths while a kill mid-prologue leaves the in-flight panel
    un-committed (the checkpoint epoch trails it)."""

    def __init__(self, op: str, sched: CyclicSchedule,
                 bc: PanelBroadcaster, st: _ShardState, depth: int,
                 epoch: int, factor_panels: List[int],
                 payload_shape: Callable, make_payload: Callable,
                 complete: Callable, replay: Callable,
                 apply: Callable) -> None:
        self.op = op
        self.sched = sched
        self.bc = bc
        self.st = st
        self.depth = max(int(depth), 0)
        self.epoch = int(epoch)
        self.last = factor_panels[-1] if factor_panels else -1
        self._payload_shape = payload_shape
        self._make_payload = make_payload
        self._complete = complete
        self._replay = replay
        self._apply = apply
        self.pending: Dict[int, _InflightFrame] = {}
        self.done: Dict[int, Any] = {}
        self.issued = -1
        self._checked: set = set()

    def _check(self, k: int) -> None:
        if k not in self._checked:
            self._checked.add(k)
            _faults.check("step", op=self.op, step=k,
                          mine=bool(self.sched.is_mine(k)))

    def _issue(self, k: int, ahead: bool) -> _InflightFrame:
        """Dispatch panel k's factor + broadcast. The owner's panel
        state must already hold updates 0..k-1 (phase-1 history or
        the prologue's promotion)."""
        if self.sched.is_mine(k):
            with _ledger.frame("stage"):
                S = self.st.take(k)
            with obs_events.span("shard::factor", cat="shard",
                                 panel=k, ahead=ahead), \
                    _ledger.frame("factor"):
                payload = self._make_payload(k, S)
            self.st.discard(k)
        else:
            payload = None
        shape, dtype = self._payload_shape(k)
        return self.bc.broadcast_async(payload,
                                       self.sched.owner_flat(k),
                                       shape, dtype, panel=k,
                                       ahead=ahead)

    def _finish(self, fr: _InflightFrame):
        return self._complete(fr.panel, self.bc.complete(fr))

    def obtain(self, k: int):
        """Phase 1: the completed update record for panel k."""
        self._check(k)
        if k in self.done:
            return self.done.pop(k)
        if k < self.epoch:
            return self._replay(k)
        fr = self.pending.pop(k, None)
        if fr is None:              # synchronous path (depth 0 /
            fr = self._issue(k, ahead=False)   # the first panel)
        return self._finish(fr)

    def _promote(self, i: int, k: int, rec) -> None:
        """Apply every frame panel i has not yet absorbed (steps
        upto(i)..i-1, ascending — the synchronous walk's per-panel
        order, bitwise) so its factor sees the finished state."""
        for s in range(self.st.applied_through(i), i):
            r = rec if s == k else self.done[s]
            with _ledger.frame("stage"):
                S = self.st.take(i)
            with obs_events.span("shard::update", cat="shard",
                                 panel=i, step=s, ahead=True), \
                    _ledger.frame("update"):
                S = self._apply(S, r, i)
            self.st.mark_applied(i, s)
            self.st.stash(i, S)

    def advance(self, k: int, rec) -> None:
        """Phase 2: pull the issue cursor up to ``min(k + depth,
        last)`` — the lookahead prologue."""
        if self.issued < k:
            self.issued = k
        limit = min(k + self.depth, self.last)
        while self.issued < limit:
            i = self.issued + 1
            prev = i - 1
            if prev > k and prev not in self.done:
                # chain link: panel i's factor (and, for LU/QR, its
                # host bookkeeping) needs frame i-1 realized first
                self._check(prev)
                if prev < self.epoch:
                    self.done[prev] = self._replay(prev)
                else:
                    self.done[prev] = self._finish(
                        self.pending.pop(prev))
            if i < self.epoch:
                # durable on resume: replays at its own step, no
                # broadcast to pipeline
                self.issued = i
                continue
            self._check(i)
            if self.sched.is_mine(i):
                self._promote(i, k, rec)
            self.pending[i] = self._issue(i, ahead=True)
            self.issued = i

    def updates(self, k: int, rec) -> None:
        """Phase 3: the trailing sweep on this host's remaining owned
        panels — the work every in-flight broadcast hides under."""
        todo = [j for j in self.sched.update_order(k, self.depth,
                                                   self.epoch)
                if self.st.applied_through(j) <= k]
        t0 = time.perf_counter()
        for i, j in enumerate(todo):
            with _ledger.frame("stage"):
                S_j = self.st.take(j)
            self.st.prefetch_next(todo, i)
            with obs_events.span("shard::update", cat="shard",
                                 panel=j, step=k), \
                    _ledger.frame("update"):
                S_j = self._apply(S_j, rec, j)
            self.st.mark_applied(j, k)
            self.st.stash(j, S_j)
        obs_metrics.inc("ooc.shard.update_seconds",
                        time.perf_counter() - t0)


def _publish_overlap(op: str, bc: PanelBroadcaster,
                     depth: int) -> None:
    """Driver-exit overlap record (ISSUE 11 obs satellite): the
    broadcast-wait wall vs the in-flight wall and their fraction, so
    bench/report attribute the lookahead win without re-deriving it
    from spans."""
    if not obs_events.enabled():
        return
    obs_metrics.observe("ooc.shard.bcast_overlap_fraction",
                        bc.overlap_fraction())
    obs_events.instant("shard::overlap", cat="shard", op=op,
                       depth=depth, ahead=bc.ahead,
                       wait_s=round(bc.wait_seconds, 6),
                       inflight_s=round(bc.inflight_seconds, 6),
                       overlap=round(bc.overlap_fraction(), 4))


# -- fused trailing sweeps (ISSUE 20) -------------------------------------
#
# One dispatch per update phase for the sharded right-looking walk:
# every non-promoted owned panel consuming broadcast record s is
# stacked and the record applied across the stack by an in-jit
# lax.scan whose body is the SAME per-panel visit kernel — identical
# operands, identical per-member arithmetic — so the fused sweep is
# BITWISE equal to the per-panel route (pinned by tests). One
# compiled program per (height, frame width, count-bucket); the
# power-of-two bucket ladder (linalg/ooc._fuse_bucket) bounds the jit
# cache exactly the way the single-engine fused visits do. potrf
# members have per-panel suffix heights (n - p*w): each is embedded
# at its global row offset in a full-height slab (stream._embed_rows)
# and the visiting frame masked below the member's offset, so every
# real row sees the exact per-panel dot product while padding rows
# stay exact zero; geqrf/getrf members are all full-height (m, w), so
# the stack is direct. Ragged-width members (the last panel when
# w does not divide n) are applied per-panel by the driver's plain
# ``apply`` inside the fused closure — membership stays the slot's
# whole sweep, arithmetic stays per-panel-exact.

_HI = jax.lax.Precision.HIGHEST


@functools.partial(jax.jit, static_argnames=("w",))
def _fused_sweep_potrf(Ss, frame, offs, w: int):
    """Stacked potrf trailing sweep: Ss (b, n, w) members embedded at
    row offsets `offs` (b,), frame the full-height broadcast factor
    column. Per member: mask the frame below the member's offset and
    run _panel_apply's exact product — rows below the offset are
    0 - 0 @ top = exact zero (the embedding pad survives)."""
    rows = jnp.arange(frame.shape[0])

    def body(c, inp):
        S, off = inp
        masked = jnp.where((rows >= off)[:, None], frame, 0)
        top = jax.lax.dynamic_slice(
            frame, (off, jnp.asarray(0, off.dtype)),
            (w, frame.shape[1]))
        return c, S - jnp.matmul(masked, jnp.conj(top.T),
                                 precision=_HI)

    return jax.lax.scan(body, 0, (Ss, offs))[1]


@functools.partial(jax.jit, static_argnames=("w",))
def _fused_sweep_potrf_mx(Ss, frame, offs, w: int):
    """Mixed twin of _fused_sweep_potrf: frame arrives in the lo
    dtype, each rank-w product accumulates in S's dtype (the
    _panel_apply_mx contract, linalg/ooc.py)."""
    rows = jnp.arange(frame.shape[0])

    def body(c, inp):
        S, off = inp
        masked = jnp.where((rows >= off)[:, None], frame, 0)
        top = jax.lax.dynamic_slice(
            frame, (off, jnp.asarray(0, off.dtype)),
            (w, frame.shape[1]))
        return c, S - jnp.matmul(masked, jnp.conj(top.T),
                                 precision=_HI,
                                 preferred_element_type=S.dtype)

    return jax.lax.scan(body, 0, (Ss, offs))[1]


@jax.jit
def _fused_sweep_qr(Ss, Pk, tk, k0):
    """Stacked geqrf trailing sweep: the scan body IS _qr_visit, so
    each member of Ss (b, m, w) absorbs record (Pk, tk, k0) through
    the per-panel kernel's exact ops."""
    from ..linalg import ooc as _ooc

    def body(c, S):
        return c, _ooc._qr_visit(S, Pk, tk, k0)

    return jax.lax.scan(body, 0, Ss)[1]


@jax.jit
def _fused_sweep_qr_mx(Ss, Pk, tk, k0):
    """Mixed twin of _fused_sweep_qr (body: _qr_visit_mx)."""
    from ..linalg import ooc as _ooc

    def body(c, S):
        return c, _ooc._qr_visit_mx(S, Pk, tk, k0)

    return jax.lax.scan(body, 0, Ss)[1]


@jax.jit
def _fused_sweep_lu(Ss, Pk, g, k0):
    """Stacked getrf trailing sweep: the scan body IS _lu_visit_orig
    (gather to elimination order, strip solve + trailing product,
    scatter back)."""
    from ..linalg import ooc as _ooc

    def body(c, S):
        return c, _ooc._lu_visit_orig(S, Pk, g, k0)

    return jax.lax.scan(body, 0, Ss)[1]


@jax.jit
def _fused_sweep_lu_mx(Ss, Pk, g, k0):
    """Mixed twin of _fused_sweep_lu (body: _lu_visit_orig_mx)."""
    from ..linalg import ooc as _ooc

    def body(c, S):
        return c, _ooc._lu_visit_orig_mx(S, Pk, g, k0)

    return jax.lax.scan(body, 0, Ss)[1]


def _run_stream(op: str, use_graph: bool, *, sched, bc, st, depth,
                epoch, factor_panels, tail_panels, payload_shape,
                make_payload, complete, replay, apply, tail_step,
                led, ck, eng, step_obs, nt, elastic=None,
                fused_apply=None, fuse_meta=None) -> None:
    """One issue loop for all three sharded drivers (ISSUE 17): the
    legacy ``_BcastPipeline`` walk (``scheduler="walk"`` — the frozen
    cold route, bit-identical to the PR 11-16 drivers), or the
    task-graph route (``sched/policies.sharded_stream`` constructed
    once, then ``sched/runtime.execute`` issues ready nodes through
    the SAME closures). The drivers supply the same five pipeline
    closures either way plus ``tail_step(k)`` — the m<n tail-panel
    body (None for potrf, whose every panel factors).

    ``elastic`` (ISSUE 19): an :class:`~.elastic.ElasticController`
    routes the stream through the segmented re-ownership loop
    (dist/elastic.py run_elastic — graph construction per remap
    segment, ownership re-derived from measured throughput at each
    boundary). Elastic always constructs graphs regardless of the
    ``ooc/scheduler`` row: ownership is a graph-construction input,
    which is the whole re-label-and-rebuild mechanism.

    ``fused_apply``/``fuse_meta`` (ISSUE 20): the driver's stacked
    one-dispatch trailing-sweep closure and its per-slot ledger-meta
    sidecar — forwarded to ``sharded_stream`` (and through every
    elastic segment), with the meta folded into the slot's ledger
    commit. Fused implies the graph route (the walk has no fused
    node), so ``use_graph`` is already True whenever these are set."""
    if elastic is not None:
        from . import elastic as _elastic
        _elastic.run_elastic(
            elastic, op=op, bc=bc, st=st, depth=depth, epoch=epoch,
            factor_panels=factor_panels, tail_panels=tail_panels,
            payload_shape=payload_shape, make_payload=make_payload,
            complete=complete, replay=replay, apply=apply,
            tail_step=tail_step, led=led, ck=ck, eng=eng,
            step_obs=step_obs, nt=nt, fused_apply=fused_apply,
            fuse_meta=fuse_meta)
        return
    last = factor_panels[-1] if len(factor_panels) else -1
    if use_graph:
        from ..sched import policies as _policies
        from ..sched.runtime import execute as _execute
        g = _policies.sharded_stream(
            op, sched=sched, bc=bc, st=st, depth=depth, epoch=epoch,
            factor_panels=factor_panels, tail_panels=tail_panels,
            payload_shape=payload_shape, make_payload=make_payload,
            complete=complete, replay=replay, apply=apply,
            tail=tail_step, fused_apply=fused_apply)

        def _begin(k):
            if led is not None:
                led.begin(k, owner=sched.owner_process(k),
                          epoch=epoch)

        def _end(k):
            if k <= last:
                step_obs(k)
            if ck is not None and k >= epoch and ck.due(k):
                eng.wait_writes()   # every panel <= k is durable;
                ck.commit(k + 1)    # the in-flight panel is NOT
            if led is not None:
                led.commit(**(fuse_meta.pop(k, {})
                              if fuse_meta else {}))

        _execute(g, op=op, nt=nt, begin_step=_begin, end_step=_end)
        # deep lookahead keys every node below slot nt-1, so the
        # trailing slots never open and their due() commits never
        # fire from _end — land the walk's final complete
        # checkpoint explicitly
        if ck is not None and ck.epoch < nt:
            eng.wait_writes()
            ck.commit(nt)
        return
    pipe = _BcastPipeline(op, sched, bc, st, depth, epoch,
                          list(factor_panels), payload_shape,
                          make_payload, complete, replay, apply)
    for k in factor_panels:
        if led is not None:
            led.begin(k, owner=sched.owner_process(k), epoch=epoch)
        _health.heartbeat(op, k, nt)
        rec = pipe.obtain(k)
        # lookahead prologue BEFORE the trailing sweep: the next
        # panel's broadcast rides the second frame buffer while this
        # host applies its remaining k-updates (module doc);
        # per-panel update order is unchanged (bitwise pin)
        pipe.advance(k, rec)
        pipe.updates(k, rec)
        step_obs(k)
        if ck is not None and k >= epoch and ck.due(k):
            eng.wait_writes()   # every panel <= k is durable;
            ck.commit(k + 1)    # the in-flight panel is NOT
        if led is not None:
            led.commit()
    for k in tail_panels:
        # columns past kmax (m < n): all updates applied, the state
        # IS the final U block — one broadcast replicates it so every
        # host's packed factor is complete (synchronous: no factor
        # depends on these, nothing to overlap)
        if led is not None:
            led.begin(k, owner=sched.owner_process(k), epoch=epoch)
        _health.heartbeat(op, k, nt)
        _faults.check("step", op=op, step=k,
                      mine=bool(sched.is_mine(k)))
        if k < epoch:
            continue            # durable already
        tail_step(k)
        if ck is not None and ck.due(k):
            eng.wait_writes()
            ck.commit(k + 1)
        if led is not None:
            led.commit()


@instrument_driver("shard_potrf_ooc")
def shard_potrf_ooc(a: np.ndarray, grid: ProcessGrid,
                    panel_cols: Optional[int] = None,
                    cache_budget_bytes=None,
                    fanin: Optional[int] = None,
                    lookahead: Optional[int] = None,
                    ckpt_path: Optional[str] = None,
                    ckpt_every: Optional[int] = None,
                    precision=None,
                    scheduler=None,
                    ownership=None,
                    visit_fuse=None) -> np.ndarray:
    """Sharded out-of-core lower Cholesky (module doc): panels owned
    2D-block-cyclically, each host staging only its shard, factor
    panels broadcast over the tree. Returns the full host-resident
    lower factor ON EVERY PROCESS (each broadcast panel is written
    back locally), bitwise equal to ``potrf_ooc``'s.

    ``lookahead`` (ISSUE 11): the broadcast-pipeline depth (explicit
    argument > the FROZEN ``ooc/shard_lookahead`` = 0). Depth 0 is
    the step-synchronous schedule; depth >= 1 overlaps each step's
    trailing updates with the NEXT panel's in-flight broadcast
    (module doc) — bitwise equal at every depth, pinned by tests.

    ``ckpt_path``/``ckpt_every`` (resil/, ISSUE 9): each host keeps a
    durable per-host mirror of the factor (resil/checkpoint.py memmap
    under ``ckpt_path/host<i>``). On resume the mesh agrees on the
    MIN committed epoch (:func:`_agree_epoch`); panels below it are
    replayed from the durable local mirror — no factor work, no
    broadcast — while each host's trailing panels catch up through
    the SAME jitted update kernel on bitwise-equal operands, so the
    resumed factor is BITWISE the uninterrupted one (pinned by
    tests, including a crash with two panels in flight — the commit
    epoch always trails the deepest in-flight panel). FROZEN default
    0 = off, bit-identical to the pre-resil driver.

    ``precision`` (ISSUE 12): the mixed-precision mode, resolved
    explicit > tuned ``ooc/precision`` > FROZEN "f32" (the cold
    cache keeps this full-precision schedule bit-identically).
    Under "bf16" the factor panel is demoted BEFORE broadcast — the
    ppermute tree carries half the bytes per frame (the
    ``ooc.shard.bcast_bytes`` counter shows exactly the halving) —
    every host applies the bf16 frame with the mixed update kernel,
    and the host factor mirror holds the PROMOTED frame, so every
    process (owner included) derives its copy from the same
    broadcast value: the mesh-wide factor stays identical across
    hosts, at bf16-update accuracy. Resume replay demotes the
    promoted mirror back (an exact roundtrip) so a resumed stream
    applies bitwise the frames the uninterrupted one did.

    ``scheduler`` (ISSUE 17): ``"walk"`` (FROZEN ``ooc/scheduler``
    default — the legacy pipeline loop) or ``"graph"`` (the task-graph
    runtime; bitwise-pinned against the walk at every depth).

    ``ownership`` (ISSUE 19): ``"static"`` (FROZEN ``mesh/ownership``
    default — the pure cyclic map) or ``"elastic"`` (throughput-
    driven re-ownership, dist/elastic.py — bitwise vs static; with
    uniform throughput the remapper never fires).

    ``visit_fuse`` (ISSUE 20): ``"per_panel"`` (FROZEN
    ``ooc/visit_fuse`` default — one update dispatch per (panel,
    step) pair, the bitwise-pinned cold route) or ``"fused"`` — each
    broadcast record's trailing sweep over the owned panels collapses
    into ONE stacked in-jit scan dispatch (_fused_sweep_potrf;
    bitwise equal to per_panel, pinned). Fused implies the graph
    route — the walk has no fused node."""
    from ..linalg import stream
    from ..linalg.ooc import (_fuse_bucket, _fuse_note_compile,
                              _panel_apply, _panel_apply_mx,
                              _panel_cols, _panel_factor,
                              _precision_meta, _resolve_precision,
                              _resolve_scheduler, _resolve_visit_fuse)
    from .elastic import ElasticController, _resolve_ownership
    a = np.asarray(a)
    n = a.shape[0]
    w = min(_panel_cols(panel_cols, n, a.dtype), n)
    nt = ceil_div(n, w)
    lo = _resolve_precision(precision, n, a.dtype)
    use_fuse = _resolve_visit_fuse(visit_fuse, n, a.dtype)
    use_graph = _resolve_scheduler(scheduler, n, a.dtype) or use_fuse
    depth = _shard_lookahead(lookahead, n, a.dtype)
    ctrl = ElasticController("shard_potrf_ooc", grid, nt,
                             n=n, dtype=a.dtype) \
        if _resolve_ownership(ownership, n, a.dtype) else None
    sched = ctrl.sched if ctrl is not None \
        else CyclicSchedule(nt, grid)
    bc = PanelBroadcaster(grid, _shard_fanin(fanin, n, a.dtype))
    ck = _ckpt.maybe_checkpointer(
        _host_ckpt_path(ckpt_path), "shard_potrf_ooc", a, w, nt,
        every=ckpt_every,
        extra_meta={"precision": _precision_meta(lo)})
    out = ck.factor if ck is not None else np.zeros_like(a)
    epoch = _agree_epoch(grid, ck.epoch) if ck is not None else 0
    local_dev = jax.local_devices()[0]
    eng = stream.engine_for(n, w, a.dtype,
                            budget_bytes=cache_budget_bytes,
                            device=local_dev, extra_pins=depth,
                            resident_dtype=lo)
    mine = sched.my_panels()
    if obs_events.enabled():
        obs_events.instant("shard::schedule", cat="shard", op="potrf",
                           nt=nt, ranks=sched.nranks, mine=len(mine),
                           lookahead=depth, resume_epoch=epoch,
                           precision=_precision_meta(lo))

    def loader(k):
        k0, k1 = k * w, min(k * w + w, n)
        return lambda: a[k0:, k0:k1]

    st = _ShardState(eng, loader,
                     lambda k: (n - k * w, min(w, n - k * w)),
                     a.dtype)
    step_obs = _step_obs_fn("potrf")

    def payload_shape(k):
        return (n, min(w, n - k * w)), \
            (a.dtype if lo is None else lo)

    def make_payload(k, S):
        k0 = k * w
        Lk = _panel_factor(S, min(w, n - k0))
        _guard.check_panel("shard_potrf_ooc", k, Lk, ref=S)
        if lo is not None:
            # demote BEFORE broadcast: the tree carries half the
            # bytes, and every host (owner included) derives both
            # its updates and its factor mirror from the same lo
            # frame
            Lk = stream.demote_dev(Lk, lo)
        return stream._embed_rows(Lk, k0, n=n)

    def complete(k, frame):
        # every host mirrors the factor panel into its own copy
        # (promoted back under the mixed mode — the host factor
        # keeps the compute dtype)
        k0, k1 = k * w, min(k * w + w, n)
        col = frame if lo is None \
            else stream.promote_dev(frame, a.dtype)
        eng.write("L", k, stream._suffix_rows(col, k0, rows=n - k0),
                  out[k0:, k0:k1])
        return frame

    def replay(k):
        # resume: panel k's factor is durable in the local mirror —
        # skip factor/broadcast/write and just catch the trailing
        # owned panels up (module doc). Mixed: the mirror holds the
        # promoted frame; demoting it back is an exact roundtrip
        k0, k1 = k * w, min(k * w + w, n)
        if lo is None:
            return stream._h2d(out[:, k0:k1])
        return stream._h2d(stream.demote_host(out[:, k0:k1], lo))

    def apply(S_j, frame, j):
        j0 = j * w
        Lr = stream._suffix_rows(frame, j0, rows=n - j0)
        if lo is None:
            return _panel_apply(S_j, Lr, min(w, n - j0))
        return _panel_apply_mx(S_j, Lr, min(w, n - j0))

    fuse_meta: Dict[int, dict] = {}

    def fused_apply(Ss, frame, ps, s):
        # full-width members stack; the ragged-width last panel (if
        # present) keeps its exact per-panel apply inside this node
        full = [i for i, p in enumerate(ps)
                if min(w, n - p * w) == w]
        if len(full) < 2:
            return [apply(S, frame, p) for S, p in zip(Ss, ps)]
        out_s = list(Ss)
        count = len(full)
        bucket = _fuse_bucket(count)
        stk = [stream._embed_rows(Ss[i], ps[i] * w, n=n)
               for i in full]
        stk += [jnp.zeros_like(stk[0])] * (bucket - count)
        offs = jnp.asarray([ps[i] * w for i in full]
                           + [0] * (bucket - count), jnp.int32)
        _fuse_note_compile("shard_potrf_ooc", n, int(frame.shape[1]),
                           w, bucket, str(frame.dtype))
        fn = _fused_sweep_potrf if lo is None \
            else _fused_sweep_potrf_mx
        res = fn(jnp.stack(stk), frame, offs, w=w)
        for idx, i in enumerate(full):
            p = ps[i]
            out_s[i] = stream._suffix_rows(res[idx], p * w,
                                           rows=n - p * w)
        for i, p in enumerate(ps):
            if i not in full:
                out_s[i] = apply(Ss[i], frame, p)
        fuse_meta[s] = {"fused_members": [ps[i] for i in full],
                        "fused_width": count * w}
        return out_s

    led = _ledger.recorder("shard_potrf_ooc", nt=nt,
                           spill_dir=_host_ckpt_path(ckpt_path))
    try:
        _run_stream("shard_potrf_ooc", use_graph, sched=sched, bc=bc,
                    st=st, depth=depth, epoch=epoch,
                    factor_panels=list(range(nt)), tail_panels=[],
                    payload_shape=payload_shape,
                    make_payload=make_payload, complete=complete,
                    replay=replay, apply=apply, tail_step=None,
                    led=led, ck=ck, eng=eng, step_obs=step_obs,
                    nt=nt, elastic=ctrl,
                    fused_apply=fused_apply if use_fuse else None,
                    fuse_meta=fuse_meta if use_fuse else None)
        _health.heartbeat("shard_potrf_ooc", nt, nt)   # completion
        if led is not None:
            led.begin(nt, epoch=epoch, drain=True)       # final drain record
        eng.wait_writes()
    finally:
        eng.finish()
        if led is not None:
            led.close()
    _publish_overlap("potrf", bc, depth)
    return out


@instrument_driver("shard_geqrf_ooc")
def shard_geqrf_ooc(a: np.ndarray, grid: ProcessGrid,
                    panel_cols: Optional[int] = None,
                    incore_ib: int = 128,
                    cache_budget_bytes=None,
                    fanin: Optional[int] = None,
                    lookahead: Optional[int] = None,
                    ckpt_path: Optional[str] = None,
                    ckpt_every: Optional[int] = None,
                    precision=None,
                    scheduler=None,
                    ownership=None,
                    visit_fuse=None):
    """Sharded out-of-core Householder QR: same ownership walk,
    broadcast tree, and lookahead pipeline as shard_potrf_ooc,
    full-height panel states, the broadcast payload carrying the
    factored column frame PLUS one extra row holding the panel's taus
    (one tree traversal per step covers both). Returns (QR_packed,
    taus) on every process, bitwise equal to ``geqrf_ooc``'s packed
    contract at every ``lookahead`` depth.

    ``ckpt_path``/``ckpt_every``: per-host durable factor + taus
    mirrors with the same min-epoch agreement and durable-mirror
    replay as shard_potrf_ooc (resil/, ISSUE 9).

    ``precision`` "bf16" (ISSUE 12): the broadcast frame — packed
    column AND its tau row — is demoted before the tree (half the
    payload bytes); hosts apply the compact-WY block with the mixed
    kernel and mirror the promoted frame, so the packed factor and
    taus are identical across the mesh at bf16-update accuracy.

    ``ownership`` (ISSUE 19): "static" | "elastic" — the
    shard_potrf_ooc contract.

    ``visit_fuse`` (ISSUE 20): "per_panel" | "fused" — the
    shard_potrf_ooc contract; the fused sweep's scan body IS
    _qr_visit (_fused_sweep_qr), so the route is bitwise equal to
    per_panel (pinned). Fused implies the graph route."""
    from ..linalg import stream
    from ..linalg.ooc import (_fuse_bucket, _fuse_note_compile,
                              _panel_cols, _precision_meta,
                              _qr_apply_fresh, _qr_panel_factor,
                              _qr_visit, _qr_visit_mx,
                              _resolve_precision, _resolve_scheduler,
                              _resolve_visit_fuse)
    from .elastic import ElasticController, _resolve_ownership
    a = np.asarray(a)
    m, n = a.shape
    kmax = min(m, n)
    w = min(_panel_cols(panel_cols, n, a.dtype), n)
    nt = ceil_div(n, w)
    lo = _resolve_precision(precision, n, a.dtype)
    use_fuse = _resolve_visit_fuse(visit_fuse, n, a.dtype)
    use_graph = _resolve_scheduler(scheduler, n, a.dtype) or use_fuse
    depth = _shard_lookahead(lookahead, n, a.dtype)
    ctrl = ElasticController("shard_geqrf_ooc", grid, nt,
                             n=n, dtype=a.dtype) \
        if _resolve_ownership(ownership, n, a.dtype) else None
    sched = ctrl.sched if ctrl is not None \
        else CyclicSchedule(nt, grid)
    bc = PanelBroadcaster(grid, _shard_fanin(fanin, n, a.dtype))
    ck = _ckpt.maybe_checkpointer(
        _host_ckpt_path(ckpt_path), "shard_geqrf_ooc", a, w, nt,
        every=ckpt_every, extra_arrays={"taus": ((kmax,), a.dtype)},
        extra_meta={"precision": _precision_meta(lo)})
    if ck is not None:
        out, taus = ck.factor, ck.array("taus")
        epoch = _agree_epoch(grid, ck.epoch)
    else:
        out = np.empty_like(a)
        taus = np.zeros((kmax,), a.dtype)
        epoch = 0
    local_dev = jax.local_devices()[0]
    eng = stream.engine_for(max(m, n), w, a.dtype,
                            budget_bytes=cache_budget_bytes,
                            device=local_dev, extra_pins=depth,
                            resident_dtype=lo)
    mine = sched.my_panels()
    if obs_events.enabled():
        obs_events.instant("shard::schedule", cat="shard", op="geqrf",
                           nt=nt, ranks=sched.nranks, mine=len(mine),
                           lookahead=depth,
                           precision=_precision_meta(lo))

    def loader(k):
        k0, k1 = k * w, min(k * w + w, n)
        return lambda: a[:, k0:k1]

    st = _ShardState(eng, loader,
                     lambda k: (m, min(w, n - k * w)), a.dtype)
    step_obs = _step_obs_fn("geqrf")
    factor_panels = [k for k in range(nt) if k * w < kmax]
    tail_panels = [k for k in range(nt) if k * w >= kmax]

    def bounds(k):
        return _panel_bounds(k, w, n, kmax)

    def payload_shape(k):
        _k0, _k1, wk, _wf = bounds(k)
        return (m + 1, wk), (a.dtype if lo is None else lo)

    def make_payload(k, S):
        k0, _k1, wk, wf = bounds(k)
        packed, ptau = _qr_panel_factor(S[:, :wf], k0, incore_ib)
        _guard.check_panel("shard_geqrf_ooc", k, packed[:m - k0],
                           ref=S)
        low = packed[:m - k0]
        if wf < wk:
            # kmax falls inside this panel (m < n): the tail columns
            # are pure R rows from the fresh apply — the same
            # composition geqrf_ooc writes piecewise
            rest = _qr_apply_fresh(S[k0:, wf:], low, ptau)
            low = jnp.concatenate([low, rest], axis=1)
        col = jnp.concatenate([S[:k0], low], axis=0) if k0 > 0 \
            else low
        tau_row = jnp.zeros((1, wk), a.dtype)
        tau_row = tau_row.at[0, :wf].set(ptau[:wf])
        payload = jnp.concatenate([col, tau_row], axis=0)
        if lo is not None:
            # one demotion covers column AND tau row — the whole
            # frame rides the tree at half the bytes
            payload = stream.demote_dev(payload, lo)
        return payload

    def complete(k, payload):
        k0, k1, _wk, wf = bounds(k)
        if lo is None:
            col = payload[:m]
            taus[k0:k0 + wf] = np.asarray(payload[m, :wf])
            eng.write("QR", k, col, out[:, k0:k1])
            return col[:, :wf], payload[m, :wf], k0
        colf = stream.promote_dev(payload, a.dtype)
        taus[k0:k0 + wf] = np.asarray(colf[m, :wf])
        eng.write("QR", k, colf[:m], out[:, k0:k1])
        # the update record keeps the LO column (the mixed visit's
        # operand) plus the tau row widened to the compute dtype for
        # the kernel's f32 T algebra. The taus ARE bf16-rounded (the
        # whole frame demotes once) — the same error class as the V
        # columns riding beside them, i.e. the mode's documented
        # bf16-update-grade accuracy, NOT a restoration of full-
        # precision taus
        return payload[:m, :wf], colf[m, :wf], k0

    def replay(k):
        # resume replay from the durable per-host mirror (factor
        # column + taus hold the same device bytes the uninterrupted
        # run broadcast; mixed: demoting the promoted mirror is an
        # exact roundtrip)
        k0, k1, _wk, wf = bounds(k)
        col = stream._h2d(out[:, k0:k1]) if lo is None \
            else stream._h2d(stream.demote_host(out[:, k0:k1], lo))
        return col[:, :wf], stream._h2d(taus[k0:k0 + wf]), k0

    def apply(S_j, rec, j):
        Pk, tk, k0 = rec
        if lo is None:
            return _qr_visit(S_j, Pk, tk, k0)
        return _qr_visit_mx(S_j, Pk, tk, k0)

    fuse_meta: Dict[int, dict] = {}

    def fused_apply(Ss, rec, ps, s):
        full = [i for i, p in enumerate(ps)
                if min(w, n - p * w) == w]
        if len(full) < 2:
            return [apply(S, rec, p) for S, p in zip(Ss, ps)]
        out_s = list(Ss)
        Pk, tk, k0 = rec
        count = len(full)
        bucket = _fuse_bucket(count)
        stk = [Ss[i] for i in full]
        stk += [jnp.zeros_like(stk[0])] * (bucket - count)
        _fuse_note_compile("shard_geqrf_ooc", m, int(Pk.shape[1]),
                           w, bucket, str(Pk.dtype))
        fn = _fused_sweep_qr if lo is None else _fused_sweep_qr_mx
        res = fn(jnp.stack(stk), Pk, tk, k0)
        for idx, i in enumerate(full):
            out_s[i] = res[idx]
        for i, p in enumerate(ps):
            if i not in full:
                out_s[i] = apply(Ss[i], rec, p)
        fuse_meta[s] = {"fused_members": [ps[i] for i in full],
                        "fused_width": count * w}
        return out_s

    def tail_step(k):
        # all updates applied: the state IS the final U block — one
        # broadcast replicates it so every host's factor is complete.
        # Ownership is read LIVE (a remap may have re-owned the tail
        # panel since construction — dist/elastic.py)
        s = ctrl.sched if ctrl is not None else sched
        k0, k1 = k * w, min(k * w + w, n)
        frame = st.take(k) if s.is_mine(k) else None
        if frame is not None:
            st.discard(k)
        frame = bc.broadcast(frame, s.owner_flat(k),
                             (m, k1 - k0), a.dtype, panel=k)
        eng.write("QR", k, frame, out[:, k0:k1])

    led = _ledger.recorder("shard_geqrf_ooc", nt=nt,
                           spill_dir=_host_ckpt_path(ckpt_path))
    try:
        _run_stream("shard_geqrf_ooc", use_graph, sched=sched, bc=bc,
                    st=st, depth=depth, epoch=epoch,
                    factor_panels=factor_panels,
                    tail_panels=tail_panels,
                    payload_shape=payload_shape,
                    make_payload=make_payload, complete=complete,
                    replay=replay, apply=apply, tail_step=tail_step,
                    led=led, ck=ck, eng=eng, step_obs=step_obs,
                    nt=nt, elastic=ctrl,
                    fused_apply=fused_apply if use_fuse else None,
                    fuse_meta=fuse_meta if use_fuse else None)
        _health.heartbeat("shard_geqrf_ooc", nt, nt)   # completion
        if led is not None:
            led.begin(nt, epoch=epoch, drain=True)       # final drain record
        eng.wait_writes()
    finally:
        eng.finish()
        if led is not None:
            led.close()
    _publish_overlap("geqrf", bc, depth)
    return out, taus


@instrument_driver("shard_getrf_ooc")
def shard_getrf_ooc(a: np.ndarray, grid: ProcessGrid,
                    panel_cols: Optional[int] = None,
                    incore_nb: int = 1024,
                    cache_budget_bytes=None,
                    fanin: Optional[int] = None,
                    lookahead: Optional[int] = None,
                    chunk: Optional[int] = None,
                    ckpt_path: Optional[str] = None,
                    ckpt_every: Optional[int] = None,
                    precision=None,
                    scheduler=None,
                    ownership=None,
                    visit_fuse=None):
    """Sharded out-of-core tournament-pivot LU (module doc — the PR 7
    deferral, closed): same ownership walk and broadcast tree as
    shard_potrf_ooc, full-height panel states kept in ORIGINAL row
    order, the owner of panel k finalizing its pivot permutation via
    the CALU tournament BEFORE the factor column is written. The
    broadcast payload is the (m, wk) original-order factor column
    plus ONE extra row carrying the panel's live-relative pivot-row
    selection (encoded in the panel dtype the way the QR frame
    carries tau — exact for row counts below the dtype's integer
    window, 2^24 for f32); every host rederives (ipiv, permutation)
    from that row with the same host simulation
    (lu.tnt_swaps_host), so the bookkeeping is identical across the
    mesh with no extra coordination traffic. Returns (LU_packed,
    ipiv) in getrf_ooc's LAPACK packed contract ON EVERY PROCESS,
    BITWISE equal to the single-engine ``getrf_tntpiv_ooc`` — the
    trailing updates run the SAME jitted ``_lu_visit_orig`` kernel on
    bitwise-equal operands in the same per-panel order, and the
    factor columns never change after their step (no fixup, no
    cross-shard invalidation). Pinned by tests incl. a real
    2-process gloo mesh.

    ``ckpt_path``/``ckpt_every``: per-host durable mirrors of the
    original-order factor, ipiv, and the per-panel permutation
    snapshots (the "per-host pivot vectors" of the durable epoch),
    with the same min-epoch agreement and durable-mirror replay as
    shard_potrf_ooc; the meta records ``lu_pivot="tournament"`` so a
    mode-mismatched resume starts fresh (resil/checkpoint.py).

    ``precision`` "bf16" (ISSUE 12): the factor column demotes
    before the tree and the pivot-row selection rides TWO extra lo
    rows instead of one — bf16's exact-integer window is only 256,
    so the selection is split byte-wise (``hi*256 + lo``, both
    halves < 256 = exact in bf16), widening the window to 2^16 rows;
    hosts decode the same two rows, so the bookkeeping stays
    mesh-identical. Updates run the mixed gather-visit kernel and
    the original-order store mirrors the promoted column.

    ``ownership`` (ISSUE 19): "static" | "elastic" — the
    shard_potrf_ooc contract.

    ``visit_fuse`` (ISSUE 20): "per_panel" | "fused" — the
    shard_potrf_ooc contract; the fused sweep's scan body IS
    _lu_visit_orig (_fused_sweep_lu), so the route is bitwise equal
    to per_panel (pinned). Fused implies the graph route."""
    from ..core.exceptions import slate_assert
    from ..linalg import stream
    from . import elastic as _elastic_mod
    from ..linalg.ca import fix_degenerate_selection
    from ..linalg.lu import tnt_swaps_host
    from ..linalg.ooc import (_fuse_bucket, _fuse_note_compile,
                              _lu_visit_orig, _lu_visit_orig_mx,
                              _panel_cols, _precision_meta,
                              _resolve_precision, _resolve_scheduler,
                              _resolve_visit_fuse, _tnt_factor,
                              _tnt_select, _tnt_tail_cols,
                              _finalize_lapack_order)
    a = np.asarray(a)
    m, n = a.shape
    lo = _resolve_precision(precision, n, a.dtype)
    use_fuse = _resolve_visit_fuse(visit_fuse, n, a.dtype)
    use_graph = _resolve_scheduler(scheduler, n, a.dtype) or use_fuse
    # the pivot payload row(s) ride the FRAME dtype: row indices must
    # sit inside its exact-integer window or np.rint decodes WRONG
    # rows silently — make it a loud error instead. The mixed mode's
    # byte-split pair of lo rows has a 2^16 window (two exact bytes)
    window = (1 << 16) if lo is not None \
        else (1 << (np.finfo(a.dtype).nmant + 1))
    slate_assert(
        m <= window,
        "shard_getrf_ooc encodes pivot rows in the %s payload row%s; "
        "m=%d exceeds the exact-integer window %d — use a wider "
        "dtype or the single-engine getrf_tntpiv_ooc"
        % (np.dtype(a.dtype).name if lo is None
           else np.dtype(lo).name,
           "" if lo is None else " pair", m, window))
    kmax = min(m, n)
    w = min(_panel_cols(panel_cols, n, a.dtype), n)
    nt = ceil_div(n, w)
    nf = ceil_div(kmax, w)
    depth = _shard_lookahead(lookahead, n, a.dtype)
    ctrl = _elastic_mod.ElasticController("shard_getrf_ooc", grid,
                                          nt, n=n, dtype=a.dtype) \
        if _elastic_mod._resolve_ownership(ownership, n, a.dtype) \
        else None
    sched = ctrl.sched if ctrl is not None \
        else CyclicSchedule(nt, grid)
    bc = PanelBroadcaster(grid, _shard_fanin(fanin, n, a.dtype))
    ck = _ckpt.maybe_checkpointer(
        _host_ckpt_path(ckpt_path), "shard_getrf_ooc", a, w, nt,
        every=ckpt_every,
        extra_arrays={"ipiv": ((kmax,), np.int64),
                      "perms": ((nf, m), np.int64)},
        extra_meta={"lu_pivot": "tournament",
                    "precision": _precision_meta(lo)})
    if ck is not None:
        stored, ipiv = ck.factor, ck.array("ipiv")
        perms = ck.array("perms")
        epoch = _agree_epoch(grid, ck.epoch)
    else:
        stored = np.empty_like(a)
        ipiv = np.empty((kmax,), np.int64)
        perms = np.empty((nf, m), np.int64)
        epoch = 0
    perm = perms[min(epoch, nf) - 1].copy() if min(epoch, nf) > 0 \
        else np.arange(m)
    # high-water of the panel whose permutation `perm` currently
    # holds: completes advance it; replays only move it FORWARD (a
    # segmented elastic run replays old steps for catch-up panels
    # AFTER later completes already advanced perm — regressing it
    # would feed make_payload a stale permutation)
    perm_step = [min(epoch, nf) - 1]
    local_dev = jax.local_devices()[0]
    eng = stream.engine_for(max(m, n), w, a.dtype,
                            budget_bytes=cache_budget_bytes,
                            device=local_dev, extra_pins=depth,
                            resident_dtype=lo)
    mine = sched.my_panels()
    if obs_events.enabled():
        obs_events.instant("shard::schedule", cat="shard", op="getrf",
                           nt=nt, ranks=sched.nranks, mine=len(mine),
                           lookahead=depth, resume_epoch=epoch,
                           precision=_precision_meta(lo))

    def loader(k):
        k0, k1 = k * w, min(k * w + w, n)
        return lambda: a[:, k0:k1]

    st = _ShardState(eng, loader,
                     lambda k: (m, min(w, n - k * w)), a.dtype)
    step_obs = _step_obs_fn("getrf")
    factor_panels = [k for k in range(nt) if k * w < kmax]
    tail_panels = [k for k in range(nt) if k * w >= kmax]

    def bounds(k):
        return _panel_bounds(k, w, n, kmax)

    def payload_shape(k):
        _k0, _k1, wk, _wf = bounds(k)
        if lo is None:
            return (m + 1, wk), a.dtype
        return (m + 2, wk), lo

    def make_payload(k, S):
        # the owner's tournament runs against the CURRENT `perm`,
        # which the strictly ascending completion order has advanced
        # through frame k-1 by the time the pipeline issues panel k —
        # lookahead or not, the same host simulation on the same
        # values
        k0, _k1, wk, wf = bounds(k)
        live = m - k0
        idx = np.concatenate([perm[k0:], perm[:k0]])
        sel = _tnt_select(S, jnp.asarray(idx), live, wf, chunk=chunk)
        sel = fix_degenerate_selection(np.asarray(sel), live, wf)
        _piv, lperm = tnt_swaps_host(sel, live)
        new_live = perm[k0:][lperm]
        idx2 = np.concatenate([new_live, perm[:k0]])
        col, packed = _tnt_factor(S, jnp.asarray(idx2), live, wf,
                                  min(int(incore_nb), max(wf, 1)))
        _guard.check_panel("shard_getrf_ooc", k, col, ref=S)
        if wf < wk:
            # kmax inside this panel (m < n): the pure-U tail
            # columns join the broadcast column
            tail = _tnt_tail_cols(S, packed, new_live, wf)
            colfull = jnp.concatenate([col, tail], axis=1)
        else:
            colfull = col
        if lo is None:
            sel_row = jnp.zeros((1, wk), a.dtype)
            sel_row = sel_row.at[0, :wf].set(
                jnp.asarray(sel).astype(a.dtype))
            return jnp.concatenate([colfull, sel_row], axis=0)
        # mixed frame: demoted column + the byte-split selection
        # pair (docstring — bf16 represents 0..255 exactly)
        sel = np.asarray(sel, dtype=np.int64)
        rows = np.zeros((2, wk), dtype=lo)
        rows[0, :wf] = (sel // 256).astype(lo)
        rows[1, :wf] = (sel % 256).astype(lo)
        return jnp.concatenate(
            [stream.demote_dev(colfull, lo), jnp.asarray(rows)],
            axis=0)

    def complete(k, payload):
        k0, k1, _wk, wf = bounds(k)
        live = m - k0
        if lo is None:
            colfull = payload[:m]
            sel = np.rint(
                np.asarray(payload[m, :wf]).real).astype(np.int64)
        else:
            colfull = stream.promote_dev(payload[:m], a.dtype)
            srows = np.asarray(payload[m:m + 2, :wf]) \
                .astype(np.float32)
            sel = (np.rint(srows[0]) * 256
                   + np.rint(srows[1])).astype(np.int64)
        # EVERY host (owner included) rederives the pivot
        # bookkeeping from the broadcast selection — one
        # deterministic function of one broadcast value
        piv_rel, lperm = tnt_swaps_host(sel, live)
        perm[k0:] = perm[k0:][lperm]
        ipiv[k0:k0 + wf] = k0 + piv_rel
        perms[k] = perm
        perm_step[0] = k
        eng.write("LU", k, colfull, stored[:, k0:k1])
        # the update record keeps the LO column under the mixed mode
        # (the visit kernel's operand — the promoted copy only feeds
        # the host mirror)
        Pk = colfull[:, :wf] if lo is None else payload[:m, :wf]
        return {"Pk": Pk, "k": k, "k0": k0, "g": None}

    def replay(k):
        # resume replay: factor column, ipiv, and permutation
        # snapshot are durable in the per-host mirror — skip
        # select/factor/broadcast and catch the trailing owned
        # panels up from the mirror (module doc; mixed demote is an
        # exact roundtrip of the promoted mirror)
        k0, k1, _wk, wf = bounds(k)
        colfull = stream._h2d(stored[:, k0:k1]) if lo is None \
            else stream._h2d(stream.demote_host(stored[:, k0:k1],
                                                lo))
        if k > perm_step[0]:
            perm[:] = perms[k]
            perm_step[0] = k
        return {"Pk": colfull[:, :wf], "k": k, "k0": k0, "g": None}

    def apply(S_j, rec, j):
        if rec["g"] is None:
            # lazy: no owned trailing panels -> no index upload (the
            # perms[k] row is this step's immutable snapshot)
            rec["g"] = jnp.asarray(perms[rec["k"]].astype(np.int32))
        if lo is None:
            return _lu_visit_orig(S_j, rec["Pk"], rec["g"],
                                  rec["k0"])
        return _lu_visit_orig_mx(S_j, rec["Pk"], rec["g"],
                                 rec["k0"])

    fuse_meta: Dict[int, dict] = {}

    def fused_apply(Ss, rec, ps, s):
        if rec["g"] is None:
            rec["g"] = jnp.asarray(perms[rec["k"]].astype(np.int32))
        full = [i for i, p in enumerate(ps)
                if min(w, n - p * w) == w]
        if len(full) < 2:
            return [apply(S, rec, p) for S, p in zip(Ss, ps)]
        out_s = list(Ss)
        count = len(full)
        bucket = _fuse_bucket(count)
        stk = [Ss[i] for i in full]
        stk += [jnp.zeros_like(stk[0])] * (bucket - count)
        _fuse_note_compile("shard_getrf_ooc", m,
                           int(rec["Pk"].shape[1]), w, bucket,
                           str(rec["Pk"].dtype))
        fn = _fused_sweep_lu if lo is None else _fused_sweep_lu_mx
        res = fn(jnp.stack(stk), rec["Pk"], rec["g"], rec["k0"])
        for idx, i in enumerate(full):
            out_s[i] = res[idx]
        for i, p in enumerate(ps):
            if i not in full:
                out_s[i] = apply(Ss[i], rec, p)
        fuse_meta[s] = {"fused_members": [ps[i] for i in full],
                        "fused_width": count * w}
        return out_s

    def tail_step(k):
        # all updates applied: the original-order state IS the final
        # U block — one broadcast replicates it so every host's
        # factor is complete. Ownership read LIVE (a remap may have
        # re-owned the tail panel — dist/elastic.py)
        s = ctrl.sched if ctrl is not None else sched
        k0, k1 = k * w, min(k * w + w, n)
        frame = st.take(k) if s.is_mine(k) else None
        if frame is not None:
            st.discard(k)
        frame = bc.broadcast(frame, s.owner_flat(k),
                             (m, k1 - k0), a.dtype, panel=k)
        eng.write("LU", k, frame, stored[:, k0:k1])

    led = _ledger.recorder("shard_getrf_ooc", nt=nt,
                           spill_dir=_host_ckpt_path(ckpt_path))
    try:
        _run_stream("shard_getrf_ooc", use_graph, sched=sched, bc=bc,
                    st=st, depth=depth, epoch=epoch,
                    factor_panels=factor_panels,
                    tail_panels=tail_panels,
                    payload_shape=payload_shape,
                    make_payload=make_payload, complete=complete,
                    replay=replay, apply=apply, tail_step=tail_step,
                    led=led, ck=ck, eng=eng, step_obs=step_obs,
                    nt=nt, elastic=ctrl,
                    fused_apply=fused_apply if use_fuse else None,
                    fuse_meta=fuse_meta if use_fuse else None)
        _health.heartbeat("shard_getrf_ooc", nt, nt)   # completion
        if led is not None:
            led.begin(nt, epoch=epoch, drain=True)       # final drain record
        eng.wait_writes()
    finally:
        eng.finish()
        if led is not None:
            led.close()
    _publish_overlap("getrf", bc, depth)
    if ck is not None:
        out = _finalize_lapack_order(stored, perm, w,
                                     out=np.empty_like(stored))
        return out, np.array(ipiv)
    return _finalize_lapack_order(stored, perm, w), ipiv
