"""Mesh TSQR: communication-avoiding tall-skinny QR whose reduction
tree is EXPLICITLY scheduled across devices — the reference's
cross-rank ttqrt tree (geqrf.cc:161,220, internal_ttqrt.cc), where the
single-device `linalg/ca.py` tree is a vmap.

One shard_map program per entry point:
  * up-sweep: each device thin-QRs its row chunk (the reference's
    per-rank panel QR), then the (w, w) R factors combine up the
    dist/tree.py butterfly — per round only R-sized blocks ride the
    ppermutes, exactly the communication the reference's hypercube
    ttqrt saves;
  * `tsqr_qt` carries B through the SAME gathers (R and the running
    Q^H B panels share each round's ppermute payload), so the implicit
    tree apply costs no extra communication rounds — the ttmqt role,
    never materializing the (m, w) orthogonal factor;
  * `tsqr` reconstructs the explicit thin Q with a DOWN-sweep that is
    purely local: the butterfly's all-combine property means every
    device already holds its own (2w, w)-block Q factor per level, so
    Q_local = Q0_local @ prod(level blocks) needs zero communication.

Padding: rows pad with zeros to a device multiple (zero rows are exact
for QR — they contribute nothing to R and carry zero Q rows). Each
device chunk must be at least w rows tall for the thin leaf QR;
`eligible` gates callers (qr.gels_tsqr / the grid geqrf tall-skinny
route fall back to the single-device tree below it).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tiles import round_up
from ..parallel.mesh import ProcessGrid
from ..parallel.smap import shard_map
from . import tree

_HI = jax.lax.Precision.HIGHEST


def _fanin(grid: ProcessGrid, opts, n: Optional[int], dtype) -> int:
    """Tree fan-in (tunable 'tsqr'/'tree_fanin', FROZEN default 2 —
    the reference's binary ttqrt); larger values shorten the tree at
    fatter combine steps (each level QRs a (g*w, w) stack)."""
    from ..tune.select import resolve
    return int(resolve("tsqr", "tree_fanin", opts=opts, n=n,
                       dtype=dtype))


def eligible(grid: ProcessGrid, shape: Tuple[int, int],
             axis=("p", "q")) -> bool:
    """True when the mesh tree applies: every per-device row chunk is
    at least as tall as the panel is wide (the leaf thin-QR shape
    requirement)."""
    m, w = shape
    size = tree.axis_size(grid, axis)
    return w >= 1 and round_up(max(m, 1), size) // size >= w


def _up_sweep(r, y, axis, size, fanin):
    """Shared tree up-sweep (inside shard_map): combine R factors up
    the butterfly, carrying the Q^H B panel `y` through the same
    gathers when given. Returns (R_root, y_root, level_qs) where
    level_qs are this device's per-round (g*w, w) Q blocks plus its
    group position (for the local Q down-sweep)."""
    w = r.shape[1]
    idx = jax.lax.axis_index(axis)
    level_qs = []
    for span, g in tree.round_schedule(size, fanin):
        payload = r if y is None else jnp.concatenate([r, y], axis=1)
        vals = tree.group_values(payload, axis, size, span, g)
        stacked = jnp.concatenate([v[:, :w] for v in vals], axis=0)
        qk, r = jax.lax.linalg.qr(stacked, full_matrices=False)
        if y is not None:
            ys = jnp.concatenate([v[:, w:] for v in vals], axis=0)
            y = jnp.matmul(jnp.conj(qk.T), ys, precision=_HI)
        level_qs.append((qk, (idx // span) % g))
    return r, y, level_qs


def tsqr_qt(grid: ProcessGrid, a: jax.Array, b: jax.Array,
            opts=None, axis=("p", "q")) -> Tuple[jax.Array, jax.Array]:
    """R and Q^H B of tall-skinny a = Q R over the mesh tree, both
    replicated ((w, w) and (w, nrhs)) — the gels_tsqr kernel: one
    program, implicit Q, tree-scheduled communication."""
    m, w = a.shape
    size = tree.axis_size(grid, axis)
    fanin = _fanin(grid, opts, w, a.dtype)
    tree.record_schedule("tsqr_qt", size, fanin)
    mp = round_up(max(m, 1), size)
    ap = tree.pad_rows(a, mp)
    bp = tree.pad_rows(b.astype(a.dtype), mp)

    def f(al, bl):
        q0, r = jax.lax.linalg.qr(al, full_matrices=False)
        y = jnp.matmul(jnp.conj(q0.T), bl, precision=_HI)
        r, y, _ = _up_sweep(r, y, axis, size, fanin)
        return r, y

    spec = P(axis, None)
    return shard_map(f, mesh=grid.mesh, in_specs=(spec, spec),
                     out_specs=(P(), P()), check_vma=False)(ap, bp)


def tsqr(grid: ProcessGrid, a: jax.Array, opts=None,
         axis=("p", "q")) -> Tuple[jax.Array, jax.Array]:
    """Explicit mesh TSQR: a (m, w) row-sharded -> (Q (m, w)
    row-sharded orthonormal, R (w, w) replicated). The down-sweep that
    rebuilds Q is communication-free (module doc)."""
    m, w = a.shape
    size = tree.axis_size(grid, axis)
    fanin = _fanin(grid, opts, w, a.dtype)
    tree.record_schedule("tsqr", size, fanin)
    mp = round_up(max(m, 1), size)
    ap = tree.pad_rows(a, mp)

    def f(al):
        q0, r = jax.lax.linalg.qr(al, full_matrices=False)
        r, _, level_qs = _up_sweep(r, None, axis, size, fanin)
        qcur = jnp.eye(w, dtype=al.dtype)
        for qk, pos in reversed(level_qs):
            blk = jax.lax.dynamic_slice_in_dim(qk, pos * w, w, axis=0)
            qcur = jnp.matmul(blk, qcur, precision=_HI)
        return jnp.matmul(q0, qcur, precision=_HI), r

    spec = P(axis, None)
    q, r = shard_map(f, mesh=grid.mesh, in_specs=spec,
                     out_specs=(spec, P()), check_vma=False)(ap)
    return q[:m], r
