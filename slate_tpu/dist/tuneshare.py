"""Multihost tuning-table share (ROADMAP open item; ISSUE 5
satellite): host 0's measured autotuning entries broadcast over the
mesh so one host probes and every host routes identically — riding
the dist/tree.py combine engine instead of ad-hoc host communication
(exactly what the ROADMAP prescribed when dist/ landed).

Mechanics: the table is JSON-serialized to a uint8 payload. Devices
owned by process 0 hold the payload, every other device holds zeros,
and an elementwise-max tree_allreduce (log-depth ppermute schedule,
visible to obs/ comms accounting like every other tree traversal)
replicates it — max is exact because the non-source rows are all
zero. Two rounds: the payload LENGTH first (every process must agree
on the phase-2 array shape before building it), then the payload.

On a single-process mesh (the CPU test topology) process 0 owns every
device and the broadcast degenerates to an exact self-copy — same
code path, same tree schedule.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import ProcessGrid


def _device_rows(grid: ProcessGrid, payload: np.ndarray,
                 width: int) -> np.ndarray:
    """(ndev, width) host array: the payload on every device process 0
    owns, zeros elsewhere (replication on the source process keeps the
    max-combine exact — identical rows, not summed rows)."""
    devs = list(grid.mesh.devices.flat)
    x = np.zeros((len(devs), width), np.uint8)
    row = np.zeros((width,), np.uint8)
    row[: payload.shape[0]] = payload
    for d, dev in enumerate(devs):
        if dev.process_index == 0:
            x[d] = row
    return x


def _bcast_max(grid: ProcessGrid, x: np.ndarray, fanin: int) -> np.ndarray:
    from ..parallel.collectives import tree_allreduce
    return np.asarray(tree_allreduce(grid, jnp.asarray(x),
                                     op=jnp.maximum, fanin=fanin))


def broadcast_entries(grid: ProcessGrid,
                      entries: Optional[Dict[str, Dict[str, Any]]] = None,
                      fanin: int = 2) -> Dict[str, Dict[str, Any]]:
    """Broadcast host 0's tuning entries (default: its loaded cache)
    to every host; returns the received table. Pure transport — no
    cache mutation (share_tuning_table is the merge-into-cache
    wrapper)."""
    if entries is None:
        from ..tune.cache import get_cache
        entries = get_cache().entries() \
            if jax.process_index() == 0 else {}
    payload = np.frombuffer(
        json.dumps(entries, sort_keys=True).encode("utf-8"),
        dtype=np.uint8) if jax.process_index() == 0 \
        else np.zeros((0,), np.uint8)
    # phase 1: agree on the payload length (non-source rows are 0, so
    # the max IS host 0's length on every device)
    ln = _bcast_max(grid, _device_rows(
        grid, np.frombuffer(np.int64(payload.shape[0]).tobytes(),
                            dtype=np.uint8), 8), fanin)
    length = int(np.frombuffer(ln[0].astype(np.uint8).tobytes(),
                               dtype=np.int64)[0])
    if length <= 0:
        return {}
    # phase 2: the payload itself at the agreed width
    out = _bcast_max(grid, _device_rows(grid, payload, length), fanin)
    text = out[0].astype(np.uint8).tobytes().decode("utf-8")
    received = json.loads(text)
    return received if isinstance(received, dict) else {}


def share_tuning_table(grid: ProcessGrid, fanin: int = 2,
                       save: bool = False) -> int:
    """The one-call mesh workflow: probe on host 0 (or load its
    persisted cache), broadcast, best-entry merge into THIS host's
    cache (tune/cache.TuneCache.merge). Returns the number of entries
    adopted; save=True persists the merged table."""
    from ..tune.cache import get_cache
    received = broadcast_entries(grid)
    cache = get_cache()
    changed = cache.merge(received)
    if save and changed:
        cache.save()
    from ..obs import events as obs_events
    if obs_events.enabled():
        from ..obs import metrics as om
        om.inc("tune.share.broadcasts")
        om.inc("tune.share.entries_adopted", changed)
    return changed
