"""Log-depth pairwise-combine engine over mesh axes — the role of the
reference's cross-rank ttqrt binary reduction tree (geqrf.cc:161,220,
internal_ttqrt.cc) and hypercube ReduceList patterns
(internal_comm.cc:72), as an explicit ppermute schedule.

The engine is the butterfly (all-combine) form of the tree: at each
round, devices form groups of `g` along the axis, exchange their
current values with the g-1 partners (g-1 `ppermute`s), and every
member computes the same combine of the group's values in mesh-position
order. After ceil(log_g(size)) rounds every device holds the full
combination — deterministically associated left-to-right, so
structured combines (stacked-R QR in dist/tsqr.py) give bit-identical
results on every device without a broadcast-down phase. `fanin` (the
group size, reference ttqrt is fanin=2) is a tunable: larger fan-in
trades fewer, larger combine steps for more ppermute traffic per
round — the tree-shape knob the tune/ subsystem probes.

These helpers run INSIDE shard_map (they use axis_index/ppermute);
host-level wrappers live in the consumers (dist/tsqr.py,
parallel/collectives.tree_allreduce). `row_apply` is the companion
row-local broadcast-apply shape: shard rows, replicate the operator,
no communication at all (the reference's dsteqr2.f play).
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import ProcessGrid
from ..parallel.smap import shard_map

AxisName = Union[str, Tuple[str, ...]]


def axis_size(grid: ProcessGrid, axis: AxisName) -> int:
    """Device count along `axis` (a mesh axis name or tuple of names —
    a tuple is the flattened product, e.g. ('p','q') = the whole
    mesh)."""
    if isinstance(axis, str):
        return grid.mesh.shape[axis]
    size = 1
    for name in axis:
        size *= grid.mesh.shape[name]
    return size


def pad_rows(x: jax.Array, rows: int) -> jax.Array:
    """Zero-pad x's leading dimension to `rows` — the shared shape fix
    before sharding rows over an axis (zero rows are exact for the
    consumers here: QR leaves, rotation-chain row blocks)."""
    return jnp.zeros((rows,) + x.shape[1:],
                     x.dtype).at[:x.shape[0]].set(x)


def group_values(x: jax.Array, axis: AxisName, size: int, span: int,
                 g: int) -> list:
    """Inside shard_map: the values held by all `g` members of this
    device's combine group, in group-position order (element my_pos is
    this device's own `x`).

    Group structure at a round: devices whose flattened axis index
    differs only in the digit (idx // span) % g — round 0 (span=1)
    groups neighbors, later rounds group the representatives of
    already-combined blocks. g-1 ppermutes move the values; because
    my own position is a traced axis_index, the received buffers are
    reordered into absolute positions with jnp.where selects (g is
    small — the fan-in)."""
    idx = jax.lax.axis_index(axis)
    my_pos = (idx // span) % g
    received = [x]
    for o in range(1, g):
        # the member at position (pos + o) % g sends to position pos
        perm = []
        for i in range(size):
            pos = (i // span) % g
            base = i - pos * span
            perm.append((i, base + ((pos - o) % g) * span))
        received.append(jax.lax.ppermute(x, axis, perm))
    vals = []
    for j in range(g):
        v = received[0]
        for o in range(1, g):
            v = jnp.where((my_pos + o) % g == j, received[o], v)
        vals.append(v)     # j == my_pos: no o matches, own value stays
    return vals


def round_schedule(size: int, fanin: int = 2) -> list:
    """The (span, g) rounds of the combine tree for `size` devices:
    per round g = the largest group size <= fanin that divides the
    remaining count, so any size works (a prime tail degenerates to
    one wide combine). fanin=2 on a power-of-two axis is the
    reference's binary ttqrt tree."""
    if size < 1:
        raise ValueError(f"axis size {size} < 1")
    fanin = max(int(fanin), 2)
    rounds = []
    span = 1
    while span < size:
        rem = size // span
        g = min(fanin, rem)
        while g > 1 and rem % g:
            g -= 1
        if g <= 1:
            # no group size <= fanin divides the remaining count
            # (prime tail): take its smallest divisor above the
            # fan-in — one wider combine instead of stalling
            g = next(k for k in range(fanin + 1, rem + 1)
                     if rem % k == 0)
        rounds.append((span, g))
        span *= g
    return rounds


def schedule_ppermutes(size: int, fanin: int = 2) -> int:
    """Number of ppermute collectives one tree traversal schedules:
    group_values issues g-1 per round. This is the EXACT per-call comms
    count for anything built on the tree (tsqr up-sweep, tree_allreduce)
    — obs/xprof.py counts the same number back out of the compiled HLO
    (collective-permute is ppermute's compiled signature), and the dist
    drivers publish it to the metrics registry per call."""
    return sum(g - 1 for _, g in round_schedule(size, fanin))


def record_schedule(op: str, size: int, fanin: int) -> None:
    """Publish one tree traversal's scheduled comms to the obs bus
    (no-op when observability is off; runs at Python level, so under
    jit it fires once per trace — i.e. per compiled program, which is
    exactly the granularity the HLO count has).

    Also the ``ppermute`` fault-injection site (resil/, ISSUE 9):
    every scheduled traversal announces itself here BEFORE the obs
    gate, so a seeded plan can fail collective round k of a stream
    deterministically — call sites (PanelBroadcaster) run the whole
    traversal, this hook included, inside their retry unit. Without
    an installed plan this is one module-attribute load."""
    from ..resil import faults as _faults
    if _faults.active() is not None:
        _faults.check("ppermute", op=op, size=size, fanin=fanin)
    from ..obs import events as obs_events
    if not obs_events.enabled():
        return
    from ..obs import metrics as obs_metrics
    n = schedule_ppermutes(size, fanin)
    obs_metrics.inc("comms.ppermute.scheduled", n)
    obs_events.instant("comms:%s" % op, cat="comms", ppermutes=n,
                       size=size, fanin=fanin)


def complete_schedule(op: str, x) -> float:
    """Deferred completion of a previously dispatched tree traversal
    (ISSUE 11): block until `x` (the traversal's result array) is
    ready and publish the wait to the comms accounting. The
    dispatch/completion split is what the lookahead-overlapped
    sharded schedule rides — ``record_schedule`` + the jitted
    traversal ISSUE the collective asynchronously, the consumer keeps
    computing, and this twin is called only when the value is needed,
    so the published ``comms.ppermute.wait_seconds`` is exactly the
    wall the schedule failed to hide. Returns the wait in seconds."""
    import time
    t0 = time.perf_counter()
    jax.block_until_ready(x)
    dt = time.perf_counter() - t0
    from ..obs import events as obs_events
    if obs_events.enabled():
        from ..obs import metrics as obs_metrics
        obs_metrics.inc("comms.ppermute.wait_seconds", dt)
    return dt


def tree_combine(x: jax.Array, combine: Callable[[Sequence], jax.Array],
                 axis: AxisName, size: int, fanin: int = 2) -> jax.Array:
    """Inside shard_map: log-depth grouped combine along `axis`.
    `combine` takes the list of group members' values in position
    order and returns one value; after the last round every device
    holds combine applied over all `size` leaves, associated
    left-to-right by mesh position."""
    for span, g in round_schedule(size, fanin):
        x = combine(group_values(x, axis, size, span, g))
    return x


def row_apply(grid: ProcessGrid, f: Callable, x: jax.Array,
              *replicated, axis: AxisName = ("p", "q")) -> jax.Array:
    """Row-local broadcast-apply: shard x's rows over `axis`, replicate
    the remaining operands, and run f on each row block independently —
    zero communication (the reference's dsteqr2.f shape: every rank
    applies the same accumulated transform to its local eigenvector
    rows). x's row count must divide by the axis size; f must map a
    row block to a same-row-count block."""
    spec = P(axis, *([None] * (x.ndim - 1)))
    return shard_map(f, mesh=grid.mesh,
                     in_specs=(spec,) + tuple(P() for _ in replicated),
                     out_specs=spec, check_vma=False)(x, *replicated)
