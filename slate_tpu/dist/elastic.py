"""Elastic mesh: throughput-driven panel re-ownership for the sharded
OOC stream (ISSUE 19 tentpole).

The sharded drivers' :class:`~.shard_ooc.CyclicSchedule` is static —
panel ownership is arithmetic on the panel index, fixed before the
stream starts. One slow host therefore rate-limits every epoch: the
fast hosts finish their trailing updates and then sit in
``bcast_wait`` until the straggler's factor frame lands (BLASX's
observation, PAPERS.md — dynamic work assignment beats static
distribution exactly on heterogeneous fleets, and the pod-scale
regime of "Large Scale Distributed Linear Algebra With TPUs" makes
stragglers the norm). PR 17 made ownership an input to graph
*construction* (sched/policies.sharded_stream), so re-owning panels
is a re-label-and-rebuild of the remaining subgraph, not surgery on a
hand-written walk. This module supplies the pieces:

* :class:`ElasticSchedule` — a CyclicSchedule with an explicit
  ``owners`` table (flat mesh positions). The default table IS the
  cyclic walk, so an elastic schedule that never remaps is
  position-for-position the static one. ``remap(boundary, owners)``
  returns a new schedule that preserves every position below
  ``boundary`` — committed/factored panels are never relabeled (the
  SL902 contract).
* :class:`ThroughputTracker` — per-position effective-throughput
  EWMA over *phase-split-corrected* step walls: the sample is the
  ledger step wall minus its ``bcast_wait`` phase (obs/ledger.py),
  so time spent waiting on someone ELSE's frame never counts as this
  host's slowness. With the ledger off the sample degrades to the
  segment wall minus the broadcaster's wait-seconds delta.
* :func:`agree_speeds` — the SPMD agreement step: every host
  contributes its own measured wall at its mesh positions through a
  psum add over a zero-padded matrix (the ``_agree_epoch`` transport
  shape; exact, because every position has exactly one nonzero
  contributor), so every host derives the IDENTICAL speed vector and
  therefore the identical remap plan — no coordinator, no extra
  protocol.
* :func:`plan_remap` — the deterministic planner: below the
  ``mesh/remap_threshold`` max/min speed ratio it returns None (a
  uniform fleet never remaps, which is what keeps the elastic route
  bitwise vs static), otherwise a deficit-greedy quota assignment of
  the not-yet-factored panels proportional to speed, with
  keep-current-owner and lowest-position tie-breaks.
* :class:`ElasticController` + :func:`run_elastic` — the segmented
  issue loop behind ``shard_ooc._run_stream``: execute the stream in
  ``mesh/remap_every``-panel segments (each a sharded_stream graph
  over the remaining panels under the CURRENT ownership map), and at
  each segment boundary measure, agree, and maybe remap before
  building the next segment. Broadcast/reduce trees, PanelCache
  residency, checkpoint commits and fault sites all follow the
  relabel because they are all derived from the schedule at graph
  construction time.

Bitwise contract: a remap changes only WHO computes — each trailing
panel still absorbs updates 0..k-1 in ascending order through the
same jitted kernels on bitwise-equal operands (fresh frames from the
broadcast, or durable-mirror replays that the resil contract already
pins bitwise), so elastic output equals static output even when
remaps fire; with uniform throughput the planner never fires and the
execution is the static graph route panel for panel.

Shrink-to-fit resume (:func:`shrink_to_fit`): a ``WorkerLost`` from a
multiproc launch no longer means a full-mesh abort — the survivors
relaunch from the durable min-epoch checkpoint (every host mirrors
every broadcast factor panel, so any survivor can replay any
committed panel) with the dead host's unfinished panels re-owned by
the survivor mesh's schedule. The rung rides the resil escalation
ladder as ``shard_shrink``, one step ABOVE ``shard_to_stream`` — it
keeps the sharded route and sheds only the lost capacity.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..obs import events as obs_events
from ..obs import ledger as _ledger
from ..obs import metrics as obs_metrics
from ..resil import guard as _guard
from ..tune.select import resolve as _resolve
from .shard_ooc import CyclicSchedule

#: synthetic per-position speed override (install_speeds) — the
#: deterministic test/bench hook that replaces measured throughput
_SPEEDS: Optional[List[float]] = None


def install_speeds(speeds: Optional[Sequence[float]]) -> None:
    """Install a synthetic per-position speed vector (None clears).

    Measurement and cross-host agreement are bypassed entirely: every
    host planning against the same installed vector derives the same
    remap plan, which is what makes single-process remap coverage and
    the uniform-fleet bitwise pin deterministic under CI timing noise.
    The vector must have one entry per flat mesh position."""
    global _SPEEDS
    _SPEEDS = None if speeds is None else [float(s) for s in speeds]


def installed_speeds() -> Optional[List[float]]:
    return None if _SPEEDS is None else list(_SPEEDS)


#: process-wide remap/shrink bookkeeping readable with the obs bus
#: OFF (the guard.counts mirror shape): running totals plus the last
#: remap's record. serve/admission.py attaches this to its
#: shed/degrade/reject escalation payloads so an SLO decision made
#: during mesh churn is attributable to the churn.
_remap_lock = threading.Lock()
_REMAP_STATS: Dict[str, Any] = {"remaps": 0, "panels_moved": 0,
                                "shrinks": 0, "last": None}


def remap_records() -> Dict[str, Any]:
    """Copy of the process-wide remap/shrink mirror (module comment
    above): ``remaps``/``panels_moved``/``shrinks`` totals and
    ``last`` — the most recent remap's ``{op, boundary, moved}`` (or
    None). Readable with the obs bus off."""
    with _remap_lock:
        out = dict(_REMAP_STATS)
        if out["last"] is not None:
            out["last"] = dict(out["last"])
        return out


def reset_remap_records() -> None:
    with _remap_lock:
        _REMAP_STATS.update(remaps=0, panels_moved=0, shrinks=0,
                            last=None)


class ElasticSchedule(CyclicSchedule):
    """CyclicSchedule with an explicit panel->position owner table.

    The base class derives ownership arithmetically; here the single
    source of truth is ``owners`` (flat row-major device positions,
    one per panel) and BOTH primitive queries — :meth:`owner_flat`
    and :meth:`owner_coords` — read it, so every derived query
    (owner_device/owner_process/is_mine/my_panels/update_order/
    staged_bytes) follows the table too (the SL901 contract). The
    default table is the cyclic walk itself: an un-remapped elastic
    schedule is position-for-position the static one."""

    def __init__(self, nt: int, grid, owners: Optional[Sequence[int]] = None) -> None:
        super().__init__(nt, grid)
        if owners is None:
            # the cyclic walk itself (CyclicSchedule.owner_coords
            # flattened row-major) — written out arithmetically
            # because the base methods dispatch through our override
            owners = [(k % self.p) * self.q + (k // self.p) % self.q
                      for k in range(self.nt)]
        self.owners: List[int] = [int(o) for o in owners]
        if len(self.owners) != self.nt:
            raise ValueError("owner table has %d entries for %d panels"
                             % (len(self.owners), self.nt))
        for k, o in enumerate(self.owners):
            if not 0 <= o < self.nranks:
                raise ValueError("panel %d owner %d outside the %d-"
                                 "position mesh" % (k, o, self.nranks))

    def owner_flat(self, k: int) -> int:
        return self.owners[k]

    def owner_coords(self, k: int):
        f = self.owners[k]
        return f // self.q, f % self.q

    def remap(self, boundary: int,
              owners: Sequence[int]) -> "ElasticSchedule":
        """New schedule under `owners`, preserving every position
        below `boundary` — factored/committed panels are never
        relabeled (their frames are already broadcast and mirrored;
        a relabel would orphan checkpoint bookkeeping)."""
        owners = [int(o) for o in owners]
        if owners[:boundary] != self.owners[:boundary]:
            raise ValueError(
                "remap at boundary %d would relabel an already-"
                "factored panel" % boundary)
        return ElasticSchedule(self.nt, self.grid, owners)


class ThroughputTracker:
    """Per-position effective-throughput EWMA (module doc).

    ``observe(pos, wall)`` folds one effective step-wall sample
    (seconds of OWN work — comms waits already subtracted) into
    position ``pos``'s estimate; ``walls()`` is the current estimate
    vector (None where no sample has landed yet)."""

    def __init__(self, nranks: int, alpha: float) -> None:
        self.nranks = int(nranks)
        self.alpha = min(max(float(alpha), 1e-6), 1.0)
        self._ewma: List[Optional[float]] = [None] * self.nranks

    def observe(self, pos: int, wall: float) -> None:
        wall = max(float(wall), 0.0)
        prev = self._ewma[pos]
        self._ewma[pos] = wall if prev is None \
            else self.alpha * wall + (1.0 - self.alpha) * prev

    def walls(self) -> List[Optional[float]]:
        return list(self._ewma)


#: compiled per-mesh psum for agree_speeds — built once per mesh so
#: every boundary after the first reuses the cached executable (the
#: per-boundary agreement must cost milliseconds, not a retrace)
_AGREE_FN_CACHE: Dict[Any, Any] = {}


def _agree_reduce_fn(mesh):
    fn = _AGREE_FN_CACHE.get(mesh)
    if fn is None:
        import jax
        from jax.sharding import PartitionSpec as P
        from ..parallel.smap import shard_map
        fn = shard_map(lambda xs: jax.lax.psum(xs, ("p", "q")),
                       mesh=mesh, in_specs=P(("p", "q"), None),
                       out_specs=P(), check_vma=False)
        _AGREE_FN_CACHE[mesh] = fn
    return fn


def agree_speeds(grid, my_wall: float) -> List[float]:
    """Mesh-agreed per-position speed vector (module doc).

    Every host contributes its measured effective step wall at each
    of ITS flat positions; positions are disjoint across hosts, so an
    add-reduction over zero-padded rows yields the identical full
    vector everywhere (the ``_agree_epoch`` transport shape with add
    instead of min). The reduction is a plain ``psum``, not the
    explicit ppermute tree: each position has exactly ONE nonzero
    contribution, so any reduction order adds zeros to it and the
    result is exact — and for an nranks^2 f32 control payload the
    tree's per-round dispatch dominates its schedule on every
    backend (~40x on a 2-process gloo mesh). Speed = 1/wall,
    normalized so the fastest position is 1.0. Single-process meshes
    short-circuit (every position is this host)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    devs = list(grid.mesh.devices.flat)
    nranks = len(devs)
    wall = max(float(my_wall), 1e-9)
    if len({d.process_index for d in devs}) == 1:
        walls = np.full(nranks, wall)
    else:
        me = jax.process_index()
        shards = []
        for f, d in enumerate(devs):
            row = np.zeros((1, nranks), np.float32)
            if d.process_index == me:
                row[0, f] = wall
                shards.append(jax.device_put(jnp.asarray(row), d))
        sharding = NamedSharding(grid.mesh, P(("p", "q"), None))
        garr = jax.make_array_from_single_device_arrays(
            (nranks, nranks), sharding, shards)
        out = _agree_reduce_fn(grid.mesh)(garr)
        walls = np.asarray(out.addressable_data(0),
                          np.float64).reshape(-1)[:nranks]
        walls = np.maximum(walls, 1e-9)
    speeds = 1.0 / walls
    return list(speeds / speeds.max())


def plan_remap(owners: Sequence[int], boundary: int,
               speeds: Sequence[float], threshold: float,
               positions: Optional[Sequence[int]] = None
               ) -> Optional[List[int]]:
    """Deterministic re-ownership plan, or None to keep the map.

    Only panels at or past `boundary` (not yet factored) are
    eligible; `positions` restricts the candidate owners (the
    shrink-to-fit path drops the lost host's). The threshold gate
    runs first: below a `threshold` max/min speed ratio the current
    map stands — UNLESS a remaining panel's owner is not a candidate
    (a lost host), which forces a plan regardless. Past the gate,
    each candidate gets a quota proportional to its speed and panels
    are walked in ascending order: a panel keeps its current owner
    while that owner is under quota, otherwise it moves to the
    largest-deficit candidate (lowest position on ties). Everything
    is pure arithmetic on the inputs — every host planning from the
    same (owners, boundary, speeds) derives the same plan."""
    nt = len(owners)
    rem = list(range(max(int(boundary), 0), nt))
    if positions is None:
        positions = list(range(len(speeds)))
    positions = sorted(set(int(p) for p in positions))
    if not rem or not positions:
        return None
    posset = set(positions)
    sp = {i: max(float(speeds[i]), 1e-12) for i in positions}
    forced = any(owners[k] not in posset for k in rem)
    if not forced and max(sp.values()) / min(sp.values()) < threshold:
        return None
    wsum = sum(sp.values())
    quota = {i: len(rem) * sp[i] / wsum for i in positions}
    assigned = {i: 0 for i in positions}
    new = list(owners)
    moved = 0
    for k in rem:
        cur = owners[k]
        if cur in posset and assigned[cur] + 1 <= quota[cur] + 1e-9:
            assigned[cur] += 1
            continue
        tgt = max(positions,
                  key=lambda i: (quota[i] - assigned[i], -i))
        assigned[tgt] += 1
        if tgt != cur:
            new[k] = tgt
            moved += 1
    return new if moved else None


def _resolve_ownership(ownership, n: int, dtype) -> bool:
    """Ownership arbitration for the sharded drivers (ISSUE 19):
    explicit ``ownership`` argument > measured ``mesh/ownership``
    tune entry > FROZEN "static" (core/methods.MethodOwnership — a
    COLD CACHE keeps the pure cyclic map bit-identically; elastic is
    earned or explicit, pinned by the bitwise pin suite). Returns
    True for the elastic route."""
    from ..core.methods import MethodOwnership, str2method
    m = ownership if ownership is not None else MethodOwnership.Auto
    if isinstance(m, str):
        m = str2method("ownership", m)
    if m is MethodOwnership.Auto:
        m = MethodOwnership.resolve(n, dtype)
    return m is MethodOwnership.Elastic


class ElasticController:
    """One driver invocation's remap state: the live
    :class:`ElasticSchedule`, the throughput tracker, and the knobs
    (``mesh/remap_every`` segment length, ``mesh/remap_threshold``
    speed-ratio gate, ``mesh/throughput_alpha`` EWMA weight — all
    FROZEN rows, tune/cache.py)."""

    def __init__(self, op: str, grid, nt: int, *, n: int,
                 dtype=None) -> None:
        self.op = op
        self.grid = grid
        self.sched = ElasticSchedule(nt, grid)
        self.every = max(int(_resolve("mesh", "remap_every",
                                      n=n, dtype=dtype)), 1)
        self.threshold = float(_resolve("mesh", "remap_threshold",
                                        n=n, dtype=dtype))
        alpha = float(_resolve("mesh", "throughput_alpha",
                               n=n, dtype=dtype))
        self.tracker = ThroughputTracker(self.sched.nranks, alpha)
        self.remaps = 0
        self.panels_moved = 0
        self._tail_name = "elastic.%s.%d" % (op, id(self))
        if _ledger.enabled():
            _ledger.tail(self._tail_name)   # set the cursor: earlier
            # runs' retained records must not seed this run's EWMA

    # -- measurement -------------------------------------------------

    def observe_segment(self, steps: int, seg_wall: float,
                        wait_delta: float,
                        first_step: int = 0) -> None:
        """Fold one segment's effective per-step wall into THIS
        host's positions. Ledger on: phase-split-corrected per-step
        walls from the tail (wall minus its ``bcast_wait`` phase —
        comms waits are the OTHER side's slowness). Ledger off: the
        segment wall minus the broadcaster's wait-seconds delta,
        averaged over the segment's steps."""
        import jax
        samples: List[float] = []
        if _ledger.enabled():
            for rec in _ledger.tail(self._tail_name):
                if rec.op != self.op or rec.step < first_step:
                    continue   # catch-up replay slots are not work
                samples.append(max(
                    rec.wall - rec.phases.get("bcast_wait", 0.0),
                    0.0))
        if not samples and steps > 0:
            samples = [max(seg_wall - wait_delta, 0.0)
                       / float(steps)]
        if not samples:
            return
        mean = sum(samples) / len(samples)
        me = jax.process_index()
        for f, d in enumerate(self.grid.mesh.devices.flat):
            if d.process_index == me:
                self.tracker.observe(f, mean)

    def speeds(self) -> List[float]:
        """The agreed (or installed) per-position speed vector."""
        if _SPEEDS is not None:
            if len(_SPEEDS) != self.sched.nranks:
                raise ValueError(
                    "installed speed vector has %d entries for a %d-"
                    "position mesh" % (len(_SPEEDS),
                                       self.sched.nranks))
            return list(_SPEEDS)
        walls = [w for w in self.tracker.walls() if w is not None]
        my_wall = sum(walls) / len(walls) if walls else 0.0
        return agree_speeds(self.grid, my_wall)

    # -- the remap decision ------------------------------------------

    def maybe_remap(self, boundary: int) -> int:
        """Plan + apply a re-ownership at `boundary`; returns the
        panel-move count (0 = map kept). Publishes the decision as a
        ``shard::remap`` instant plus the ``ooc.shard.remaps`` /
        ``ooc.shard.remap_panels_moved`` counters so every remap is
        attributable on the event bus and in the ledger."""
        speeds = self.speeds()
        plan = plan_remap(self.sched.owners, boundary, speeds,
                          self.threshold)
        if plan is None:
            return 0
        moved = sum(1 for a, b in zip(self.sched.owners, plan)
                    if a != b)
        self.sched = self.sched.remap(boundary, plan)
        self.remaps += 1
        self.panels_moved += moved
        with _remap_lock:
            _REMAP_STATS["remaps"] += 1
            _REMAP_STATS["panels_moved"] += moved
            _REMAP_STATS["last"] = {"op": self.op,
                                    "boundary": int(boundary),
                                    "moved": moved}
        if obs_events.enabled():
            obs_events.instant(
                "shard::remap", cat="shard", op=self.op,
                boundary=boundary, moved=moved,
                speeds=[round(s, 4) for s in speeds])
            obs_metrics.inc("ooc.shard.remaps")
            obs_metrics.inc("ooc.shard.remap_panels_moved", moved)
        return moved


def run_elastic(ctrl: ElasticController, *, op: str, bc, st,
                depth: int, epoch: int, factor_panels: Sequence[int],
                tail_panels: Sequence[int], payload_shape: Callable,
                make_payload: Callable, complete: Callable,
                replay: Callable, apply: Callable,
                tail_step: Optional[Callable], led, ck, eng,
                step_obs: Callable, nt: int,
                fused_apply: Optional[Callable] = None,
                fuse_meta: Optional[dict] = None) -> None:
    """The segmented elastic issue loop (shard_ooc._run_stream's
    elastic route; module doc).

    Each segment is a ``sharded_stream`` graph over the panels up to
    the segment boundary under the CURRENT ownership map, with
    ``applied_through`` pruning the updates earlier segments already
    applied and ``trailing_to`` extending the trailing sweep over the
    whole stream — so within a segment every trailing panel absorbs
    exactly the segment's update steps, in the walk's ascending
    order, through the walk's closures (bitwise). At each boundary
    the controller measures, agrees, and maybe remaps; panels moved
    away are dropped from this host's working set (their next owner
    stages them fresh and catches up through durable-mirror replays),
    panels moved here need nothing — the next segment's graph simply
    contains their catch-up nodes. Elastic always runs the graph
    route: ownership is a graph-construction input here, which is
    the whole mechanism.

    ``fused_apply``/``fuse_meta`` (ISSUE 20): forwarded to every
    segment's graph — the fused trailing sweep composes with remap
    because membership is re-derived per segment from the CURRENT
    ownership map and ``applied_through`` prunes sweeps already
    absorbed; the meta sidecar folds into each slot's ledger commit."""
    from ..sched import policies as _policies
    from ..sched.runtime import execute as _execute
    panels = list(factor_panels)
    last = panels[-1] if panels else -1
    b0 = int(epoch)
    while True:
        b1 = min(b0 + ctrl.every, last + 1)
        final = b1 >= last + 1
        sched = ctrl.sched
        g = _policies.sharded_stream(
            op, sched=sched, bc=bc, st=st, depth=depth, epoch=b0,
            factor_panels=[p for p in panels if p < b1],
            tail_panels=(list(tail_panels) if final else []),
            payload_shape=payload_shape, make_payload=make_payload,
            complete=complete, replay=replay, apply=apply,
            tail=tail_step, applied_through=st.applied_through,
            trailing_to=nt, fused_apply=fused_apply)

        def _begin(k, _b0=b0, _sched=sched):
            if led is not None:
                led.begin(k, owner=_sched.owner_process(k),
                          epoch=_b0)

        def _end(k, _b0=b0, _b1=b1):
            if _b0 <= k < _b1:
                step_obs(k)
            if ck is not None and k >= _b0 and ck.due(k):
                eng.wait_writes()   # every panel <= k is durable;
                ck.commit(k + 1)    # the in-flight panel is NOT
            if led is not None:
                led.commit(**(fuse_meta.pop(k, {})
                              if fuse_meta else {}))

        t_seg = time.perf_counter()
        wait0 = bc.wait_seconds
        _execute(g, op=op, nt=nt, begin_step=_begin, end_step=_end)
        if final:
            break
        # trailing panels are applied through b1 now; factored
        # panels leave the in-flight bookkeeping
        for j in ctrl.sched.my_panels():
            if j >= b1:
                st.upto[j] = b1
        for p in range(b0, b1):
            st.upto.pop(p, None)
        ctrl.observe_segment(b1 - b0,
                             time.perf_counter() - t_seg,
                             bc.wait_seconds - wait0,
                             first_step=b0)
        if ctrl.maybe_remap(b1):
            for j in sorted(st.staged):
                if j >= b1 and not ctrl.sched.is_mine(j):
                    st.discard(j)
                    st.staged.discard(j)
                    st.upto.pop(j, None)
        b0 = b1
    if ck is not None and ck.epoch < nt:
        eng.wait_writes()
        ck.commit(nt)


def shrink_to_fit(primary: Callable[[], Any],
                  survivors: Callable[[Any], Any], *,
                  op: str = "", **ctx) -> Any:
    """Shrink-to-fit resume (module doc): run `primary` (the full
    mesh launch); on :class:`~..resil.guard.WorkerLost` record the
    ``shard_shrink`` escalation rung and run `survivors(exc)` — the
    caller's smaller-mesh relaunch against the same checkpoint root.
    Any survivor can resume any committed panel because every host
    mirrors every broadcast factor frame (shard_ooc complete()
    contract), and the resumed schedule re-owns the dead host's
    unfinished panels by construction. Returns whichever launch
    completed."""
    try:
        return primary()
    except _guard.WorkerLost as e:
        _guard.record_escalation(
            "shard_shrink", op=op, lost_process=e.process_id,
            returncode=e.returncode, **ctx)
        with _remap_lock:
            _REMAP_STATS["shrinks"] += 1
        if obs_events.enabled():
            obs_events.instant("shard::shrink", cat="shard", op=op,
                               lost=e.process_id,
                               returncode=e.returncode)
            obs_metrics.inc("ooc.shard.shrinks")
        return survivors(e)
