"""slate_tpu.dist — explicitly scheduled distributed-algorithm core.

Where `parallel/` constrains dense ops and lets XLA's SPMD partitioner
insert the collectives, this package expresses algorithms whose
COMMUNICATION SCHEDULE is itself the algorithm — the capability the
reference builds on MPI rank trees (ttqrt binary reduction,
geqrf.cc:161; rank-parallel stedc, stedc_solve.cc:97-171; row-local
dsteqr2.f) and the pattern arXiv:2112.09017 shows is where TPU pods
win:

  tree.py      — log-depth ppermute pairwise/grouped combine engine +
                 the row-local broadcast-apply shape
  tsqr.py      — mesh TSQR (chunk QR, tree R-combine, implicit-Q apply)
  stedc.py     — distributed Cuppen divide & conquer
  steqr2.py    — row-local QR-iteration transform accumulation
  tuneshare.py — host-0 tuning-table broadcast + best-entry merge
                 (the ROADMAP multihost tuning share, on the tree)
  shard_ooc.py — sharded out-of-core execution: 2D-block-cyclic panel
                 ownership composing the tree engine with the
                 linalg/stream.py per-host staging engine (ISSUE 7)

Consumers: qr.gels_tsqr / the grid geqrf tall-skinny route,
eig.stedc (MethodEig.DC on a grid), eig.steqr2, and the OOC drivers'
grid route (linalg/ooc.py potrf_ooc/geqrf_ooc via MethodOOC). This
package is also the substrate later multi-host features (shared
tuning tables, ROADMAP) ride on.
"""

from . import shard_ooc, stedc, steqr2, tree, tsqr, tuneshare  # noqa: F401
from .shard_ooc import shard_geqrf_ooc, shard_potrf_ooc  # noqa: F401
from .steqr2 import steqr2_qr_dist       # noqa: F401
from .stedc import stedc_solve_dist      # noqa: F401
from .tsqr import tsqr as tsqr_mesh      # noqa: F401
from .tsqr import tsqr_qt                # noqa: F401
from .tree import row_apply, tree_combine  # noqa: F401
from .tuneshare import share_tuning_table  # noqa: F401
