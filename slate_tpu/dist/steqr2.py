"""Row-local distributed tridiagonal QR iteration — the reference's
modified Fortran steqr2 (src/dsteqr2.f driven by src/steqr2.cc,
VERDICT Missing #4): the point of that modification is that every rank
runs the cheap scalar d/e recurrence redundantly while updating ONLY
its own rows of the eigenvector matrix Z, bounding per-rank memory and
flops to n x n/P with ZERO communication in the accumulation.

Here that is one shard_map: Z's rows are sharded over the whole mesh
(dist/tree.row_apply shape), each device runs the identical
steqr2_qr while_loop (linalg/eig.py) on the replicated (d, e) —
composing each sweep's rotation chain into one (n, n) matrix — and
applies it to its local row block with a local matmul. The per-sweep
O(n^2) chain compose is replicated (the redundant part the reference
also accepts); the O(n^3)-total Z accumulation is split P ways. This
is what removed the STEQR_QR_MAX_N=512 reroute: above it the
accumulation is exactly the work worth distributing, not rerouting."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tiles import round_up
from ..obs.events import instrument_driver
from ..parallel.mesh import ProcessGrid
from ..parallel.smap import shard_map
from . import tree


@instrument_driver("steqr2_dist")
def steqr2_qr_dist(grid: ProcessGrid, d: jax.Array, e: jax.Array,
                   z0: Optional[jax.Array] = None,
                   maxit_factor: int = 30, axis=("p", "q")
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """steqr2_qr with the transform accumulation sharded over mesh row
    blocks (module doc). z0: optional initial transform (rows, n) the
    rotations accumulate ONTO (the heev back-transform Q — passing it
    here keeps even that product row-local); default identity.
    Returns (w ascending, Z (rows, n), info) like steqr2_qr."""
    from ..linalg.eig import steqr2_qr
    from ..obs import events as obs_events
    if obs_events.enabled():
        # zero scheduled collectives is the CONTRACT of this driver
        # (row-local accumulation); record it so the report shows the
        # comms budget explicitly rather than by omission
        obs_events.instant("comms:steqr2_dist", cat="comms",
                           ppermutes=0,
                           n=int(jnp.asarray(d).shape[0]))
    d = jnp.asarray(d)
    e = jnp.asarray(e)
    n = d.shape[0]
    z = jnp.eye(n, dtype=d.dtype) if z0 is None else jnp.asarray(z0)
    rows = z.shape[0]
    size = tree.axis_size(grid, axis)
    rp = round_up(max(rows, 1), size)
    zp = tree.pad_rows(z, rp)

    def f(dd, ee, zloc):
        return steqr2_qr(dd, ee, z0=zloc, maxit_factor=maxit_factor)

    spec = P(axis, None)
    w, Z, info = shard_map(f, mesh=grid.mesh,
                           in_specs=(P(), P(), spec),
                           out_specs=(P(), spec, P()),
                           check_vma=False)(d, e, zp)
    return w, Z[:rows], info
