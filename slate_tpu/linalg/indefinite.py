"""Symmetric-indefinite solvers — Aasen's method (reference src/hesv.cc,
hetrf.cc, hetrs.cc; sysv/sytrf/sytrs aliases; slate.hh:827-879).

The reference implements communication-avoiding Aasen (hetrf.cc:21-104):
P A P^T = L T L^H with unit-lower L and tridiagonal Hermitian T. Here the
same contract is produced by a *pivoted* Parlett-Reid congruence
reduction under jit: each step picks the largest remaining entry of the
eliminated column (masked argmax — one tree reduction over the mesh,
like the LU panel), symmetrically swaps that row/column pair, then
applies a two-sided rank-1 congruence update. For complex *symmetric*
(non-Hermitian) input the congruence uses the transpose instead of the
conjugate transpose, giving L T L^T.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..core.enums import Diag, MatrixType, Side, Uplo
from ..core.exceptions import slate_assert
from ..core.options import OptionsLike
from ..core.tiles import TiledMatrix
from .blas3 import trsm


class LTLFactors(NamedTuple):
    """P A P^T = L T L^H (or L T L^T for complex symmetric): L
    unit-lower, T Hermitian/symmetric tridiagonal, perm the row
    permutation P as an index vector (a[perm] == P a)."""
    L: TiledMatrix
    T: TiledMatrix
    pivots: jax.Array        # (m_pad,) permutation vector
    hermitian: bool = True


def _parlett_reid_pivoted(a: jax.Array, hermitian: bool):
    """Pivoted congruence reduction to tridiagonal.

    Returns (T_full, L_multipliers, perm) with
    (P a P^T) == L T L^H (conj) / L T L^T (sym)."""
    n = a.shape[0]
    lm = jnp.zeros((n, n), a.dtype)          # strictly-lower multipliers
    perm = jnp.arange(n)
    rows = jnp.arange(n)

    def conj(x):
        return jnp.conj(x) if hermitian else x

    def _swap2(x, i1, i2, axis):
        """Exchange two rows/cols by O(n) dynamic indexing (the
        round-1 full-matrix double gather cost O(n^2) per step)."""
        r1 = jax.lax.dynamic_index_in_dim(x, i1, axis, keepdims=False)
        r2 = jax.lax.dynamic_index_in_dim(x, i2, axis, keepdims=False)
        x = jax.lax.dynamic_update_index_in_dim(x, r2, i1, axis)
        return jax.lax.dynamic_update_index_in_dim(x, r1, i2, axis)

    def body(j, carry):
        a, lm, perm = carry
        # pivot: largest |a[i, j]| over i > j  (reference Aasen panel
        # pivot search)
        colj = jax.lax.dynamic_index_in_dim(a, j, 1, keepdims=False)
        mag = jnp.where(rows > j, jnp.abs(colj), -jnp.inf)
        p = jnp.argmax(mag).astype(jnp.int32)
        tgt = j + 1
        # symmetric swap rows/cols tgt <-> p (and rows of lm, perm)
        a = _swap2(_swap2(a, tgt, p, 0), tgt, p, 1)
        lm = _swap2(lm, tgt, p, 0)
        perm = _swap2(perm, tgt, p, 0)
        colj = jax.lax.dynamic_index_in_dim(a, j, 1, keepdims=False)
        alpha = jax.lax.dynamic_index_in_dim(colj, tgt, 0,
                                             keepdims=False)
        safe = jnp.where(alpha == 0, jnp.ones((), a.dtype), alpha)
        m = jnp.where(rows > tgt, colj / safe, 0)
        arow = jax.lax.dynamic_index_in_dim(a, tgt, 0, keepdims=False)
        a = a - jnp.outer(m, arow)
        acol = jax.lax.dynamic_index_in_dim(a, tgt, 1, keepdims=False)
        a = a - jnp.outer(acol, conj(m))
        lmcol = jax.lax.dynamic_index_in_dim(lm, tgt, 1, keepdims=False)
        lm = jax.lax.dynamic_update_index_in_dim(lm, lmcol + m, tgt, 1)
        return a, lm, perm

    a, lm, perm = jax.lax.fori_loop(0, max(n - 2, 0), body, (a, lm, perm))
    return a, lm + jnp.eye(n, dtype=a.dtype), perm


def hetrf(A: TiledMatrix, opts: OptionsLike = None,
          hermitian: bool = True, return_info: bool = False):
    """Aasen LTL^H factorization (reference src/hetrf.cc:21-104,
    slate.hh:854). See module docstring for the TPU mapping.

    With return_info=True returns (factors, info): info > 0 is the
    first zero pivot of the tridiagonal T's LU (the factor hetrs must
    invert — reference hetrf info semantics, reduced across ranks via
    internal_reduce_info.cc; a global reduction under SPMD here). The
    info check runs a dedicated LU of T whose factors are discarded
    (hetrs re-factors T at solve time) — an opt-in diagnostic cost of
    the functional design."""
    slate_assert(A.mtype in (MatrixType.Hermitian, MatrixType.Symmetric),
                 "hetrf: A must be Hermitian/symmetric")
    if A.mtype is MatrixType.Symmetric and A.is_complex:
        hermitian = False
    r = A.resolve()
    t, l, perm = _parlett_reid_pivoted(A.to_dense(), hermitian)
    # mask T to tridiagonal (the reduction zeroes beyond it; the mask
    # removes roundoff fill only)
    n = t.shape[0]
    ii = jnp.arange(n)[:, None]
    jj = jnp.arange(n)[None, :]
    t = jnp.where(jnp.abs(ii - jj) <= 1, t, 0)
    # T keeps the dense-general tag: it is numerically tridiagonal and
    # hetrs solves it with a general LU.
    T = TiledMatrix.from_dense(t, r.mb, r.nb)
    L = TiledMatrix.from_dense(l, r.mb, r.nb,
                               mtype=MatrixType.Triangular,
                               uplo=Uplo.Lower, diag=Diag.Unit)
    # extend perm over padded rows
    mp = r.data.shape[0]
    perm_full = jnp.concatenate([perm, jnp.arange(n, mp)]).astype(
        jnp.int32) if mp > n else perm.astype(jnp.int32)
    F = LTLFactors(L, T, perm_full, hermitian)
    if return_info:
        from .lu import getrf
        return F, getrf(T, opts).info
    return F


def _permute_rows(B: TiledMatrix, perm: jax.Array,
                  inverse: bool = False) -> TiledMatrix:
    r = B.resolve()
    p = perm
    if inverse:
        p = jnp.argsort(perm)
    mp = r.data.shape[0]
    if p.shape[0] < mp:
        p = jnp.concatenate([p, jnp.arange(p.shape[0], mp)])
    elif p.shape[0] > mp:
        # A's padding exceeds B's: the extra entries are identity
        # (targets < n <= mp), so truncation is exact
        p = p[:mp]
    return dataclasses.replace(r, data=r.data[p])


def hetrs(F: LTLFactors, B: TiledMatrix,
          opts: OptionsLike = None) -> TiledMatrix:
    """Solve with hetrf factors (reference src/hetrs.cc, slate.hh:879):
    P b, L z = ., T y = . (tridiagonal), L^op x = ., P^T x."""
    from .lu import gesv
    X = _permute_rows(B, F.pivots)
    X = trsm(Side.Left, 1.0, F.L, X, opts)
    _, X = gesv(F.T, X, opts)
    Lh = F.L.conj_transpose() if F.hermitian else F.L.transpose()
    X = trsm(Side.Left, 1.0, Lh, X, opts)
    return _permute_rows(X, F.pivots, inverse=True)


def hesv(A: TiledMatrix, B: TiledMatrix, opts: OptionsLike = None
         ) -> Tuple[LTLFactors, TiledMatrix]:
    """Reference slate.hh:827."""
    F = hetrf(A, opts)
    return F, hetrs(F, B, opts)


def sytrf(A: TiledMatrix, opts: OptionsLike = None) -> LTLFactors:
    """Reference sytrf: for complex symmetric input uses the transpose
    congruence (L T L^T)."""
    return hetrf(A, opts)


def sytrs(F: LTLFactors, B: TiledMatrix,
          opts: OptionsLike = None) -> TiledMatrix:
    return hetrs(F, B, opts)


def sysv(A: TiledMatrix, B: TiledMatrix, opts: OptionsLike = None):
    """Reference slate.hh:839."""
    return hesv(A, B, opts)
