"""Symmetric-indefinite solvers — Aasen's method (reference src/hesv.cc,
hetrf.cc, hetrs.cc; sysv/sytrf/sytrs aliases; slate.hh:827-879).

The reference implements communication-avoiding Aasen (hetrf.cc:21-104):
P A P^T = L T L^H with unit-lower L and BANDED Hermitian T. The default
path here (_aasen_blocked / _aasen_scan, n > 2*nb) is the same
panel-blocked scheme: per block column, a partial-pivot panel LU
nominates pivots, a symmetric permutation applies them, and a block
congruence (two large matmuls) eliminates everything below the first
subdiagonal block — leaving T BLOCK tridiagonal (bandwidth < 2nb,
LAPACK sytrf_aa contract), solved by the windowed band LU. Small
problems (n <= 2*nb) use the unblocked pivoted Parlett-Reid rank-1
reduction, whose T is strictly tridiagonal. For complex *symmetric*
(non-Hermitian) input the congruence uses the transpose instead of the
conjugate transpose, giving L T L^T.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..core.enums import Diag, MatrixType, Side, Uplo
from ..core.exceptions import slate_assert
from ..core.options import OptionsLike
from ..core.tiles import TiledMatrix, ceil_div
from .blas3 import trsm


class LTLFactors(NamedTuple):
    """P A P^T = L T L^H (or L T L^T for complex symmetric): L
    unit-lower, T Hermitian/symmetric BANDED — bandwidth < 2nb from
    the blocked path (GeneralBand-tagged; hetrs uses the windowed band
    solver), strictly tridiagonal only from the small-n unblocked
    path. perm is the row permutation P as an index vector
    (a[perm] == P a)."""
    L: TiledMatrix
    T: TiledMatrix
    pivots: jax.Array        # (m_pad,) permutation vector
    hermitian: bool = True


def _parlett_reid_pivoted(a: jax.Array, hermitian: bool):
    """Pivoted congruence reduction to tridiagonal.

    Returns (T_full, L_multipliers, perm) with
    (P a P^T) == L T L^H (conj) / L T L^T (sym)."""
    n = a.shape[0]
    lm = jnp.zeros((n, n), a.dtype)          # strictly-lower multipliers
    perm = jnp.arange(n)
    rows = jnp.arange(n)

    def conj(x):
        return jnp.conj(x) if hermitian else x

    def _swap2(x, i1, i2, axis):
        """Exchange two rows/cols by O(n) dynamic indexing (the
        round-1 full-matrix double gather cost O(n^2) per step)."""
        r1 = jax.lax.dynamic_index_in_dim(x, i1, axis, keepdims=False)
        r2 = jax.lax.dynamic_index_in_dim(x, i2, axis, keepdims=False)
        x = jax.lax.dynamic_update_index_in_dim(x, r2, i1, axis)
        return jax.lax.dynamic_update_index_in_dim(x, r1, i2, axis)

    def body(j, carry):
        a, lm, perm = carry
        # pivot: largest |a[i, j]| over i > j  (reference Aasen panel
        # pivot search)
        colj = jax.lax.dynamic_index_in_dim(a, j, 1, keepdims=False)
        mag = jnp.where(rows > j, jnp.abs(colj), -jnp.inf)
        p = jnp.argmax(mag).astype(jnp.int32)
        tgt = j + 1
        # symmetric swap rows/cols tgt <-> p (and rows of lm, perm)
        a = _swap2(_swap2(a, tgt, p, 0), tgt, p, 1)
        lm = _swap2(lm, tgt, p, 0)
        perm = _swap2(perm, tgt, p, 0)
        colj = jax.lax.dynamic_index_in_dim(a, j, 1, keepdims=False)
        alpha = jax.lax.dynamic_index_in_dim(colj, tgt, 0,
                                             keepdims=False)
        safe = jnp.where(alpha == 0, jnp.ones((), a.dtype), alpha)
        m = jnp.where(rows > tgt, colj / safe, 0)
        arow = jax.lax.dynamic_index_in_dim(a, tgt, 0, keepdims=False)
        a = a - jnp.outer(m, arow)
        acol = jax.lax.dynamic_index_in_dim(a, tgt, 1, keepdims=False)
        a = a - jnp.outer(acol, conj(m))
        lmcol = jax.lax.dynamic_index_in_dim(lm, tgt, 1, keepdims=False)
        lm = jax.lax.dynamic_update_index_in_dim(lm, lmcol + m, tgt, 1)
        return a, lm, perm

    a, lm, perm = jax.lax.fori_loop(0, max(n - 2, 0), body, (a, lm, perm))
    return a, lm + jnp.eye(n, dtype=a.dtype), perm


#: block-step count above which hetrf switches to the fixed-shape
#: fori_loop form (O(1) program size; see blocked.CHOL_SCAN_THRESHOLD)
AASEN_SCAN_THRESHOLD = 64


@functools.partial(jax.jit, static_argnums=(1, 2))
def _aasen_scan(a: jax.Array, nb: int, hermitian: bool):
    """Blocked Aasen as ONE compiled block step iterated by fori_loop
    (compile-time-safe form of _aasen_blocked for huge nt; same
    roll/mask discipline as lu._lu_scan). `a` is (N, N), N = nt*nb,
    identity-padded past n_real — pad rows are zero in every real
    panel column so they can never win a pivot, and pad block steps
    reduce the identity exactly (W = 0)."""
    from .blocked import invert_triangular
    from .lu import _lu_panel
    HI = jax.lax.Precision.HIGHEST
    N = a.shape[0]
    nt = N // nb
    rows = jnp.arange(N)
    eye = jnp.eye(N, dtype=a.dtype)

    def conj_t(x):
        return jnp.conj(x.T) if hermitian else x.T

    def step(j, carry):
        S, lm, perm = carry
        c0 = j * nb
        r0 = c0 + nb
        r1 = r0 + nb
        live = N - r0
        colblk = jax.lax.dynamic_slice(S, (0, c0), (N, nb))
        rolled = jnp.roll(colblk, -r0, axis=0)
        rolled = jnp.where((rows < live)[:, None], rolled, 0)
        packed, piv = _lu_panel(rolled)
        # when nothing lies below the subdiagonal block (live <= nb)
        # there is nothing to eliminate — suppress the useless pivot
        # permutation so the step is a no-op exactly like the unrolled
        # loop's early break
        active = live > nb
        gpiv = jnp.where(active, r0 + piv,
                         r0 + jnp.arange(nb, dtype=piv.dtype))

        def swap(i, p):
            t = gpiv[i]
            s_ = r0 + i
            pt, ps = p[t], p[s_]
            return p.at[s_].set(pt).at[t].set(ps)

        permv = jax.lax.fori_loop(0, nb, swap, rows)
        S = S[permv][:, permv]           # symmetric permutation
        # lm: permute rows of the FILLED columns (< r0); columns >= r0
        # are still exactly identity, restore them after the gather
        lm = jnp.where((rows >= r0)[None, :], eye, lm[permv])
        perm = perm[permv]
        # W = L3 L2^{-1} from the pivoted panel
        L2 = jnp.tril(packed[:nb], -1) + jnp.eye(nb, dtype=a.dtype)
        L3 = jnp.roll(packed, -nb, axis=0)
        L3 = jnp.where((rows < live - nb)[:, None], L3, 0)
        W = jnp.matmul(L3, invert_triangular(L2, lower=True,
                                             unit_diagonal=True),
                       precision=HI)
        Wg = jnp.roll(W, r1, axis=0)     # rows r1: hold W, rest zero
        # congruence S <- M S M^H: row op then col op on the updated S
        rowblk = jax.lax.dynamic_slice(S, (r0, 0), (nb, N))
        rowblk = jnp.where((rows >= c0)[None, :], rowblk, 0)
        S = S - jnp.matmul(Wg, rowblk, precision=HI)
        colblk2 = jax.lax.dynamic_slice(S, (0, r0), (N, nb))
        colblk2 = jnp.where((rows >= c0)[:, None], colblk2, 0)
        S = S - jnp.matmul(colblk2, conj_t(Wg), precision=HI)
        # record W as L's block column j+1 (rows >= r1)
        cur = jax.lax.dynamic_slice(lm, (0, r0), (N, nb))
        newcol = jnp.where((rows >= r1)[:, None], Wg, cur)
        lm = jax.lax.dynamic_update_slice(lm, newcol, (0, r0))
        return S, lm, perm

    S, lm, perm = jax.lax.fori_loop(
        0, nt - 1, step, (a, eye, jnp.arange(N)))
    ii = rows[:, None]
    jj = rows[None, :]
    t = jnp.where(jnp.abs(ii - jj) <= max(2 * nb - 1, 1), S, 0)
    return t, lm, perm


@functools.partial(jax.jit, static_argnums=(1, 2))
def _aasen_blocked(a: jax.Array, nb: int, hermitian: bool):
    """nb-blocked communication-avoiding Aasen (reference
    hetrf.cc:21-104 panel scheme; LAPACK sytrf_aa contract):
    P A P^T = L T L^H with unit-lower L and T BANDED of width < 2nb
    (block tridiagonal). Sequential depth is n/nb block steps whose
    bulk is three large congruence matmuls — the unblocked
    Parlett-Reid's n dependent rank-1 steps were the known-fatal shape
    on TPU.

    Per block step j (block column c0:c1, sub-rows r0 = c1):
      1. partial-pivot LU of the panel S[r0:, c0:c1] (the reference's
         Aasen panel; the existing fused _lu_panel kernel) nominates
         pivot rows;
      2. the pivots are applied as a SYMMETRIC permutation of the
         trailing matrix (and the filled rows of L);
      3. W = L3 L2^{-1} eliminates S[r1:, c0:c1] exactly (both blocks
         share the panel's U factor), and the two-sided congruence
         S <- M S M^H with M = I - e3 W e2^T is two big matmuls;
      4. W becomes L's block column j+1; the surviving block row/col
         pair (S[r0:r1, c0:c1]) is T's off-diagonal block.
    """
    from .blocked import invert_triangular
    from .lu import _compose_swaps, _lu_panel
    HI = jax.lax.Precision.HIGHEST
    n = a.shape[0]
    nt = ceil_div(n, nb)
    lm = jnp.eye(n, dtype=a.dtype)
    perm = jnp.arange(n)

    def conj_t(x):
        return jnp.conj(x.T) if hermitian else x.T

    S = a
    for j in range(nt - 1):
        c0 = j * nb
        c1 = min(c0 + nb, n)
        r0 = c1
        if n - r0 <= c1 - c0:      # nothing below the subdiagonal block
            break
        panel = S[r0:, c0:c1]
        packed, piv = _lu_panel(panel)
        perm_l = _compose_swaps(piv, n - r0)
        # symmetric permutation of the trailing rows/cols, the filled
        # part of L, and the permutation record
        S = S.at[r0:, :].set(S[r0:, :][perm_l])
        S = S.at[:, r0:].set(S[:, r0:][:, perm_l])
        lm = lm.at[r0:, :r0].set(lm[r0:, :r0][perm_l])
        perm = perm.at[r0:].set(perm[r0:][perm_l])
        # packed is already in pivoted row order (_lu_panel swaps
        # internally), matching the now-permuted S
        w = c1 - c0
        r1 = min(r0 + w, n)
        L2 = jnp.tril(packed[:w], -1) + jnp.eye(w, dtype=a.dtype)
        L3 = packed[w:, :]
        W = jnp.matmul(L3, invert_triangular(L2, lower=True,
                                             unit_diagonal=True),
                       precision=HI)
        # S <- M S M^H, M = I - (block3, block2) W: one row-block and
        # one col-block elimination, each a single matmul
        S = S.at[r1:, c0:].add(-jnp.matmul(W, S[r0:r1, c0:],
                                           precision=HI))
        S = S.at[c0:, r1:].add(-jnp.matmul(S[c0:, r0:r1], conj_t(W),
                                           precision=HI))
        lm = lm.at[r1:, r0:r1].set(W)
    # T = the reduced matrix masked to its block-tridiagonal band
    # (roundoff outside is dropped)
    ii = jnp.arange(n)[:, None]
    jj = jnp.arange(n)[None, :]
    t = jnp.where(jnp.abs(ii - jj) <= max(2 * nb - 1, 1), S, 0)
    return t, lm, perm


def hetrf(A: TiledMatrix, opts: OptionsLike = None,
          hermitian: bool = True, return_info: bool = False):
    """Aasen LTL^H factorization (reference src/hetrf.cc:21-104,
    slate.hh:854). See module docstring for the TPU mapping.

    With return_info=True returns (factors, info): info > 0 is the
    first zero pivot of the tridiagonal T's LU (the factor hetrs must
    invert — reference hetrf info semantics, reduced across ranks via
    internal_reduce_info.cc; a global reduction under SPMD here). The
    info check runs a dedicated LU of T whose factors are discarded
    (hetrs re-factors T at solve time) — an opt-in diagnostic cost of
    the functional design."""
    slate_assert(A.mtype in (MatrixType.Hermitian, MatrixType.Symmetric),
                 "hetrf: A must be Hermitian/symmetric")
    if A.mtype is MatrixType.Symmetric and A.is_complex:
        hermitian = False
    r = A.resolve()
    n = r.m
    nb = r.mb
    if n > 2 * nb:
        # blocked CA-Aasen: n/nb block steps of matmul bulk; T comes
        # out banded (< 2nb) and is tagged so hetrs takes the windowed
        # band solver. Huge nt takes the fixed-shape fori_loop form
        # (program size O(1) in nt).
        if ceil_div(n, nb) > AASEN_SCAN_THRESHOLD:
            from ..core.tiles import round_up
            from .band import _pad_identity_to
            ap = _pad_identity_to(A.to_dense(), round_up(n, nb))
            t, l, perm = _aasen_scan(ap, nb, hermitian)
            t, l, perm = t[:n, :n], l[:n, :n], perm[:n]
        else:
            t, l, perm = _aasen_blocked(A.to_dense(), nb, hermitian)
        T = TiledMatrix.from_dense(t, r.mb, r.nb,
                                   mtype=MatrixType.GeneralBand,
                                   kl=max(2 * nb - 1, 1),
                                   ku=max(2 * nb - 1, 1))
    else:
        t, l, perm = _parlett_reid_pivoted(A.to_dense(), hermitian)
        # mask T to tridiagonal (the reduction zeroes beyond it; the
        # mask removes roundoff fill only)
        ii = jnp.arange(n)[:, None]
        jj = jnp.arange(n)[None, :]
        t = jnp.where(jnp.abs(ii - jj) <= 1, t, 0)
        # T keeps the dense-general tag: it is numerically tridiagonal
        # and hetrs solves it with a general LU.
        T = TiledMatrix.from_dense(t, r.mb, r.nb)
    L = TiledMatrix.from_dense(l, r.mb, r.nb,
                               mtype=MatrixType.Triangular,
                               uplo=Uplo.Lower, diag=Diag.Unit)
    # extend perm over padded rows
    mp = r.data.shape[0]
    perm_full = jnp.concatenate([perm, jnp.arange(n, mp)]).astype(
        jnp.int32) if mp > n else perm.astype(jnp.int32)
    F = LTLFactors(L, T, perm_full, hermitian)
    if return_info:
        from .lu import gbtrf, getrf
        fact = gbtrf(T, opts) if T.mtype is MatrixType.GeneralBand \
            else getrf(T, opts)
        return F, fact.info
    return F


def _permute_rows(B: TiledMatrix, perm: jax.Array,
                  inverse: bool = False) -> TiledMatrix:
    r = B.resolve()
    p = perm
    if inverse:
        p = jnp.argsort(perm)
    mp = r.data.shape[0]
    if p.shape[0] < mp:
        p = jnp.concatenate([p, jnp.arange(p.shape[0], mp)])
    elif p.shape[0] > mp:
        # A's padding exceeds B's: the extra entries are identity
        # (targets < n <= mp), so truncation is exact
        p = p[:mp]
    return dataclasses.replace(r, data=r.data[p])


def hetrs(F: LTLFactors, B: TiledMatrix,
          opts: OptionsLike = None) -> TiledMatrix:
    """Solve with hetrf factors (reference src/hetrs.cc, slate.hh:879):
    P b, L z = ., T y = . (banded; windowed gbsv when tagged), L^op
    x = ., P^T x."""
    from .lu import gbsv, gesv
    X = _permute_rows(B, F.pivots)
    X = trsm(Side.Left, 1.0, F.L, X, opts)
    if F.T.mtype is MatrixType.GeneralBand:
        _, X = gbsv(F.T, X, opts)
    else:
        _, X = gesv(F.T, X, opts)
    Lh = F.L.conj_transpose() if F.hermitian else F.L.transpose()
    X = trsm(Side.Left, 1.0, Lh, X, opts)
    return _permute_rows(X, F.pivots, inverse=True)


def hesv(A: TiledMatrix, B: TiledMatrix, opts: OptionsLike = None
         ) -> Tuple[LTLFactors, TiledMatrix]:
    """Reference slate.hh:827."""
    F = hetrf(A, opts)
    return F, hetrs(F, B, opts)


def sytrf(A: TiledMatrix, opts: OptionsLike = None) -> LTLFactors:
    """Reference sytrf: for complex symmetric input uses the transpose
    congruence (L T L^T)."""
    return hetrf(A, opts)


def sytrs(F: LTLFactors, B: TiledMatrix,
          opts: OptionsLike = None) -> TiledMatrix:
    return hetrs(F, B, opts)


def sysv(A: TiledMatrix, B: TiledMatrix, opts: OptionsLike = None):
    """Reference slate.hh:839."""
    return hesv(A, B, opts)
