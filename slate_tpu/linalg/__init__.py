from .aux import (add, copy, redistribute, scale, scale_row_col, set,
                  set_entries)
from .blas3 import (gbmm, gemm, gemmA, gemmC, hbmm, hemm, her2k, herk,
                    symm, syr2k, syrk, tbsm, trmm, trsm, trsmA, trsmB)
from .chol import (pbsv, pbtrf, pbtrs, posv, posv_mixed,
                   posv_mixed_gmres, potrf, potri, potrs, trtri, trtrm)
from .lu import (LUFactors, apply_pivots, gbsv, gbtrf, gbtrs, gesv,
                 gesv_mixed, gesv_mixed_gmres, gesv_nopiv, gesv_rbt,
                 getrf, getrf_nopiv, getrf_tntpiv, getri, getriOOP,
                 getrs)
from .cond import gecondest, pocondest, trcondest
from .eig import (EigResult, TridiagResult, eig_vals, hb2st, he2hb, heev,
                  hegst, hegv, stedc, steqr2, sterf, syev, sygv,
                  unmtr_hb2st, unmtr_he2hb)
from .indefinite import (LTLFactors, hesv, hetrf, hetrs, sysv, sytrf,
                         sytrs)
from .norms import colNorms, norm
from .ooc import (gemm_ooc, geqrf_ooc, gels_ooc, gesv_ooc, getrf_ooc,
                  getrs_ooc, posv_ooc, potrf_ooc, potrs_ooc, unmqr_ooc)
# the OOC streaming engine (panel-residency cache + async pipeline)
# behind every *_ooc driver — importable for budget/stats access
from .stream import PanelCache, StreamEngine  # noqa: F401
from .qr import (LQFactors, QRFactors, cholqr, gelqf, gels, gels_cholqr,
                 gels_qr, gels_tsqr, geqrf, qr_multiply_by_q, unmlq,
                 unmqr)
from .svd import (BidiagResult, Ge2tbResult, SVDResult, bdsqr, ge2tb,
                  gesvd, svd, svd_vals, tb2bd, unmbr_ge2tb, unmbr_tb2bd)
from .ca import tournament_pivot_rows, tsqr
from .stedc import (Deflation, stedc_deflate, stedc_merge, stedc_rotate,
                    stedc_secular, stedc_solve, stedc_sort,
                    stedc_z_vector)
from .eig import stedc  # noqa: F811 — keep the driver function
# bound over the submodule name (import system sets the module
# attribute 'stedc' when importing the phases above)
