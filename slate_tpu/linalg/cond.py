"""Condition-number estimators (reference src/gecondest.cc,
pocondest.cc, trcondest.cc + internal norm1est; slate.hh:1368-1398).

The reference uses Hager/Higham 1-norm estimation (norm1est) driven by
solves with the factored matrix. Same algorithm here, expressed with a
converging `lax.while_loop` over the solve iterates (stops on repeated
probe index or a non-increasing estimate, itmax-capped). Norm.Inf
estimates use ||A^-1||_inf = ||A^-H||_1: the same estimator with the
solve and its adjoint exchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.enums import Norm, Side
from ..core.exceptions import slate_assert
from ..core.options import OptionsLike
from ..core.tiles import TiledMatrix
from .blas3 import trsm
from .chol import potrs
from .lu import LUFactors, getrs
from .norms import norm as matrix_norm


def _norm1est(solve, solve_h, n: int, dtype, itmax: int = 5):
    """Higham's 1-norm estimator for ||A^-1||_1 given x -> A^-1 x and
    x -> A^-H x (reference internal norm1est / LAPACK dlacn2).

    Iterates under a while_loop with the estimator's convergence
    tests — stop when the estimate fails to increase or the probing
    unit-vector index repeats (reference norm1est's repeated-estimate
    stop) — capped at itmax like the reference; a converged run costs
    only its actual solves."""
    x = jnp.full((n, 1), 1.0 / n, dtype)
    y0 = solve(x)
    est0 = jnp.abs(y0).sum()

    def cond(c):
        it, est, y, jprev, done = c
        return (~done) & (it < itmax)

    def body(c):
        it, est, y, jprev, done = c
        xi = jnp.where(jnp.real(y) >= 0, 1.0, -1.0).astype(dtype)
        z = solve_h(xi)
        j = jnp.argmax(jnp.abs(jnp.real(z))).astype(jnp.int32)
        xnew = jnp.zeros((n, 1), dtype).at[j, 0].set(1.0)
        ynew = solve(xnew)
        estnew = jnp.abs(ynew).sum()
        converged = (j == jprev) | (estnew <= est)
        return (it + 1, jnp.maximum(est, estnew), ynew, j, converged)

    out = jax.lax.while_loop(
        cond, body,
        (jnp.zeros((), jnp.int32), est0, y0,
         jnp.full((), -1, jnp.int32), jnp.zeros((), bool)))
    return out[1]


def _estimate(norm_type: Norm, solve, solve_h, n, dtype, anorm):
    slate_assert(norm_type in (Norm.One, Norm.Inf),
                 "condest supports Norm.One / Norm.Inf")
    if norm_type is Norm.One:
        ainvnorm = _norm1est(solve, solve_h, n, dtype)
    else:   # ||A^-1||_inf = ||A^-H||_1
        ainvnorm = _norm1est(solve_h, solve, n, dtype)
    rcond = 1.0 / (ainvnorm * anorm)
    return jnp.where(jnp.isfinite(rcond), rcond, 0.0)


def gecondest(norm_type: Norm, F: LUFactors, anorm,
              opts: OptionsLike = None):
    """Reciprocal condition estimate from LU factors (reference
    src/gecondest.cc, slate.hh:1368)."""
    LU = F.LU
    nb = LU.nb

    def solve(x):
        return getrs(F, TiledMatrix.from_dense(x, nb), opts).to_dense()

    def solve_h(x):
        return getrs(F, TiledMatrix.from_dense(x, nb), opts,
                     trans=True).to_dense()

    return _estimate(norm_type, solve, solve_h, LU.m, LU.dtype, anorm)


def pocondest(norm_type: Norm, L: TiledMatrix, anorm,
              opts: OptionsLike = None):
    """From the Cholesky factor (reference src/pocondest.cc). A is
    Hermitian, so the solve is self-adjoint."""
    nb = L.nb

    def solve(x):
        return potrs(L, TiledMatrix.from_dense(x, nb), opts).to_dense()

    return _estimate(norm_type, solve, solve, L.m, L.dtype, anorm)


def trcondest(norm_type: Norm, A: TiledMatrix, opts: OptionsLike = None):
    """Triangular condition estimate (reference src/trcondest.cc,
    slate.hh:1398)."""
    nb = A.nb
    anorm = matrix_norm(norm_type if norm_type in (Norm.One, Norm.Inf)
                        else Norm.One, A)

    def solve(x):
        return trsm(Side.Left, 1.0, A,
                    TiledMatrix.from_dense(x, nb), opts).to_dense()

    def solve_h(x):
        return trsm(Side.Left, 1.0, A.conj_transpose(),
                    TiledMatrix.from_dense(x, nb), opts).to_dense()

    return _estimate(norm_type, solve, solve_h, A.m, A.dtype, anorm)
