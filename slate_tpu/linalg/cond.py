"""Condition-number estimators (reference src/gecondest.cc,
pocondest.cc, trcondest.cc + internal norm1est; slate.hh:1368-1398).

The reference uses Hager/Higham 1-norm estimation (norm1est) driven by
solves with the factored matrix. Same algorithm here, expressed with
`lax.fori_loop` over the solve iterates. Norm.Inf estimates use
||A^-1||_inf = ||A^-H||_1: the same estimator with the solve and its
adjoint exchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.enums import Norm, Side
from ..core.exceptions import slate_assert
from ..core.options import OptionsLike
from ..core.tiles import TiledMatrix
from .blas3 import trsm
from .chol import potrs
from .lu import LUFactors, getrs
from .norms import norm as matrix_norm


def _norm1est(solve, solve_h, n: int, dtype, iters: int = 5):
    """Higham's 1-norm estimator for ||A^-1||_1 given x -> A^-1 x and
    x -> A^-H x (reference internal norm1est)."""
    x = jnp.full((n, 1), 1.0 / n, dtype)
    y0 = solve(x)

    def body(i, carry):
        est, y = carry
        xi = jnp.where(jnp.real(y) >= 0, 1.0, -1.0).astype(dtype)
        z = solve_h(xi)
        j = jnp.argmax(jnp.abs(jnp.real(z)))
        xnew = jnp.zeros((n, 1), dtype).at[j, 0].set(1.0)
        y = solve(xnew)
        return jnp.maximum(est, jnp.abs(y).sum()), y

    est, _ = jax.lax.fori_loop(0, iters, body, (jnp.abs(y0).sum(), y0))
    return est


def _estimate(norm_type: Norm, solve, solve_h, n, dtype, anorm):
    slate_assert(norm_type in (Norm.One, Norm.Inf),
                 "condest supports Norm.One / Norm.Inf")
    if norm_type is Norm.One:
        ainvnorm = _norm1est(solve, solve_h, n, dtype)
    else:   # ||A^-1||_inf = ||A^-H||_1
        ainvnorm = _norm1est(solve_h, solve, n, dtype)
    rcond = 1.0 / (ainvnorm * anorm)
    return jnp.where(jnp.isfinite(rcond), rcond, 0.0)


def gecondest(norm_type: Norm, F: LUFactors, anorm,
              opts: OptionsLike = None):
    """Reciprocal condition estimate from LU factors (reference
    src/gecondest.cc, slate.hh:1368)."""
    LU = F.LU
    nb = LU.nb

    def solve(x):
        return getrs(F, TiledMatrix.from_dense(x, nb), opts).to_dense()

    def solve_h(x):
        return getrs(F, TiledMatrix.from_dense(x, nb), opts,
                     trans=True).to_dense()

    return _estimate(norm_type, solve, solve_h, LU.m, LU.dtype, anorm)


def pocondest(norm_type: Norm, L: TiledMatrix, anorm,
              opts: OptionsLike = None):
    """From the Cholesky factor (reference src/pocondest.cc). A is
    Hermitian, so the solve is self-adjoint."""
    nb = L.nb

    def solve(x):
        return potrs(L, TiledMatrix.from_dense(x, nb), opts).to_dense()

    return _estimate(norm_type, solve, solve, L.m, L.dtype, anorm)


def trcondest(norm_type: Norm, A: TiledMatrix, opts: OptionsLike = None):
    """Triangular condition estimate (reference src/trcondest.cc,
    slate.hh:1398)."""
    nb = A.nb
    anorm = matrix_norm(norm_type if norm_type in (Norm.One, Norm.Inf)
                        else Norm.One, A)

    def solve(x):
        return trsm(Side.Left, 1.0, A,
                    TiledMatrix.from_dense(x, nb), opts).to_dense()

    def solve_h(x):
        return trsm(Side.Left, 1.0, A.conj_transpose(),
                    TiledMatrix.from_dense(x, nb), opts).to_dense()

    return _estimate(norm_type, solve, solve_h, A.m, A.dtype, anorm)
