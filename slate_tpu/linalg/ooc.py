"""Out-of-core (out-of-HBM) streaming drivers — the huge-n duty of
SURVEY §2.3.8: matrices larger than accelerator memory live in HOST
memory and stream through the chip one column panel at a time.

Reference analogue: SLATE keeps the global matrix distributed and
streams remote tiles through per-device workspace with receive counts
and `releaseRemoteWorkspace` (BaseMatrix.hh:462-479, potrf.cc:179-192)
— residency is managed per tile. XLA owns residency inside one jitted
program, so the TPU-native equivalent hoists the streaming OUTSIDE
jit: a host loop moves one panel (and one visiting block per
left-looking update) host<->device around small jitted kernels, and
the factor accumulates on the host. HBM footprint is O(n * panel_cols)
instead of O(n^2).

Algorithm (potrf_ooc): classic left-looking out-of-core Cholesky —
for each column panel k: S = A[k0:, k0:k1]; for every previous panel
j: S -= L_j[k0:, :] L_j[k0:k1, :]^H (one streamed visit of L_j's
rows); then factor the panel in-core (diag cholesky + one triangular
solve). Per-panel transfer volume is O(n * panel_cols * nt) reads —
the unavoidable left-looking revisit — and one panel write.

getrf_ooc / geqrf_ooc extend the same left-looking schedule to LU and
QR (reference src/getrf.cc:327 / src/geqrf.cc:26 operate at any n the
cluster's aggregate memory holds; one TPU chip reaches the same
regime by streaming through host RAM):

- getrf_ooc: panel k is read through the CURRENT row permutation,
  visited by every earlier factor panel (U12 strip by one unit-lower
  solve + trailing rank-w update), then factored in-core with partial
  pivoting CONFINED to the resident panel (the standard left-looking
  OOC-LU pivot discipline — LAPACK's out-of-core prototypes and
  CALU's panel-local search share it). The panel's row swaps are then
  applied host-side to the already-written L panels (cheap row
  gathers) and folded into the running permutation for future reads.
- geqrf_ooc: panel k is visited by every earlier panel's compact-WY
  reflector block (V and T rebuilt on the fly from the packed factor
  + taus, exactly like the in-core path), then factored in-core with
  the native panel kernel. No pivoting, so no host-side fixups.
- Both visits run as ONE jitted fixed-shape kernel with a traced
  panel offset (dynamic_slice / masked updates), so the whole stream
  compiles O(1) programs per (panel-width) shape combination, not
  O(nt^2).

Solves stream the same way: getrs_ooc replays pivots then streams
each factor panel twice (unit-lower forward sweep, upper backward
sweep); potrs_ooc runs the non-unit forward sweep then the
conjugate-transposed backward sweep of the Cholesky factor; gels_ooc
applies Q^H by streaming reflector panels against a device-resident
RHS block, then back-substitutes R. posv_ooc/gesv_ooc bundle
factor+solve, so all three north-star families (posv/gesv/gels)
run end-to-end beyond HBM.

gemm_ooc streams A's row panels against a device-resident B (the
common tall-A case); C streams back per panel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tiles import ceil_div
from ..obs import events as obs_events
from ..obs import metrics as obs_metrics
from ..obs.events import instrument_driver
# the expander-temps estimate and cap are shared with the in-core
# trsm safety valve (blocked.py)
from .blocked import SOLVE_TEMP_CAP
from .blocked import solve_temps_bytes as _solve_temps_bytes

_HI = jax.lax.Precision.HIGHEST


def _panel_cols(panel_cols: Optional[int], n: int, dtype=None) -> int:
    """Streaming panel width: explicit argument > measured tune-cache
    entry for op "ooc" > the shipped default in the FROZEN table
    (tune/cache.py, 8192 — the single source of truth, no literal
    here). Every OOC driver's `panel_cols=None` default resolves
    through here, so the width probed by `bench.py --tune` applies
    fleet-wide without touching call sites."""
    if panel_cols:
        return int(panel_cols)
    from ..tune.select import resolve
    return int(resolve("ooc", "panel_cols", n=n, dtype=dtype))


@functools.partial(jax.jit, static_argnames=("w",))
def _panel_apply(S: jax.Array, Lj: jax.Array, w: int) -> jax.Array:
    """S -= L_j L_j_top^H for one visiting panel block (left-looking
    update): Lj is (m, wj) = rows k0: of an earlier factor panel,
    whose top w rows align with S's columns."""
    top = Lj[:w]
    return S - jnp.matmul(Lj, jnp.conj(top.T), precision=_HI)


#: Above this estimate of the TriangularSolve expander's progressive
#: output copies (bytes), the streamed solves switch to
#: invert-the-diag-block + one matmul (their triangles are
#: Cholesky/unit-LU diagonal blocks; hardware-validated at n=65536).
#: Measured: the direct solve of a (57344, 8192) below-block at
#: n=65536/panel=8192 holds 55.4 GB of HLO temps on a 16 GB part.
#: One shared value with the in-core trsm valve (blocked.py) —
#: re-exported under this name so tests can pin the OOC gates alone.
OOC_SOLVE_TEMP_CAP = SOLVE_TEMP_CAP


@functools.partial(jax.jit, static_argnames=("w",))
def _panel_factor(S: jax.Array, w: int) -> jax.Array:
    """Factor one (m, w) column panel in-core: diag cholesky, then the
    below-block by one right-side triangular solve (matmul-rate,
    backward stable) — or, when the solve's expander temps would
    exceed OOC_SOLVE_TEMP_CAP, by invert-then-matmul on the diag block
    (blocked.invert_triangular leaf/recursion; same error constants as
    the grid-path trsm_left, blocked.py)."""
    m = S.shape[0]
    lkk = jnp.tril(jax.lax.linalg.cholesky(S[:w], symmetrize_input=False))
    if m > w:
        if _solve_temps_bytes(m - w, w, S.dtype.itemsize) \
                > OOC_SOLVE_TEMP_CAP:
            from .blocked import invert_triangular
            linv = invert_triangular(lkk, lower=True)
            pan = jnp.matmul(S[w:], jnp.conj(linv.T), precision=_HI)
        else:
            pan = jax.lax.linalg.triangular_solve(
                lkk, S[w:], left_side=False, lower=True,
                transpose_a=True, conjugate_a=True)
        return jnp.concatenate([lkk, pan], axis=0)
    return lkk


@instrument_driver("potrf_ooc")
def potrf_ooc(a: np.ndarray,
              panel_cols: Optional[int] = None) -> np.ndarray:
    """Lower Cholesky of a host-resident Hermitian matrix (lower
    triangle read), streaming one column panel through the accelerator
    at a time. Returns the host-resident lower factor; n is bounded by
    host RAM, not HBM.

    No pivoting/info path (matches potrf's non-guarded contract);
    a must be positive definite.
    """
    a = np.asarray(a)
    n = a.shape[0]
    panel_cols = _panel_cols(panel_cols, n, a.dtype)
    nt = ceil_div(n, panel_cols)
    out = np.zeros_like(a)
    for k in range(nt):
        k0 = k * panel_cols
        k1 = min(k0 + panel_cols, n)
        w = k1 - k0
        S = _h2d(a[k0:, k0:k1])                            # H2D
        for j in range(k):
            j0 = j * panel_cols
            j1 = min(j0 + panel_cols, n)
            Lj = _h2d(out[k0:, j0:j1])                     # H2D visit
            S = _panel_apply(S, Lj, w)
        Lk = _panel_factor(S, w)
        out[k0:, k0:k1] = _d2h(Lk)                   # D2H
    return out


@jax.jit
def _chol_back_visit(S: jax.Array, Pk: jax.Array, k0) -> jax.Array:
    """Backward L^H sweep step of the streamed Cholesky solve: with
    Pk = L[:, k0:k1] (full column panel, lower factor), eliminate the
    already-solved rows below — (L^H)[k0:k1, k1:] = Pk[k1:]^H — then
    solve L_kk^H x_k = the corrected strip. Traced k0, fixed shapes:
    one compiled program for the whole reverse stream."""
    m, w = S.shape
    wk = Pk.shape[1]
    rows = jnp.arange(m)
    Lkk = jax.lax.dynamic_slice(Pk, (k0, 0), (wk, wk))
    Sk = jax.lax.dynamic_slice(S, (k0, 0), (wk, w))
    below = jnp.where((rows >= k0 + wk)[:, None], Pk, 0)
    corr = jnp.matmul(jnp.conj(below.T), S, precision=_HI)
    if _solve_temps_bytes(w, wk, S.dtype.itemsize) > OOC_SOLVE_TEMP_CAP:
        from .blocked import invert_triangular
        linv = invert_triangular(Lkk, lower=True)
        X = jnp.matmul(jnp.conj(linv.T), Sk - corr, precision=_HI)
    else:
        X = jax.lax.linalg.triangular_solve(
            Lkk, Sk - corr, left_side=True, lower=True,
            transpose_a=True, conjugate_a=True)
    return jax.lax.dynamic_update_slice(S, X, (k0, 0))


def potrs_ooc(l: np.ndarray, b: np.ndarray,
              panel_cols: Optional[int] = None) -> np.ndarray:
    """Solve A X = B from potrf_ooc's host-resident lower factor
    (A = L L^H): each factor panel streams through the chip twice —
    the non-unit forward sweep (the left-looking visit kernel with
    unit=False) and the conjugate-transposed backward sweep. B stays
    device-resident (nrhs << n), so HBM holds one (n, w) factor panel
    plus the RHS block (reference src/potrs.cc solves from the
    distributed factor the same two-sweep way)."""
    l = np.asarray(l)
    n = l.shape[0]
    w = min(_panel_cols(panel_cols, n, l.dtype), n)
    panels = list(range(0, n, w))
    X = jnp.asarray(np.asarray(b))
    for k0 in panels:                        # forward: L y = b
        Pk = _h2d(l[:, k0:min(k0 + w, n)])
        X = _lu_visit(X, Pk, k0, unit=False)
    for k0 in reversed(panels):              # backward: L^H x = y
        Pk = _h2d(l[:, k0:min(k0 + w, n)])
        X = _chol_back_visit(X, Pk, k0)
    return np.asarray(X)


def posv_ooc(a: np.ndarray, b: np.ndarray,
             panel_cols: Optional[int] = None):
    """Factor + solve in one call (the OOC twin of posv): returns
    (L, X) with both the factor and the solution host-resident."""
    L = potrf_ooc(a, panel_cols)
    return L, potrs_ooc(L, b, panel_cols)


@jax.jit
def _gemm_block(Ab: jax.Array, B: jax.Array, beta, Cb: jax.Array):
    return beta * Cb + jnp.matmul(Ab, B, precision=_HI)


@jax.jit
def _gemm_block_overwrite(Ab: jax.Array, B: jax.Array):
    return jnp.matmul(Ab, B, precision=_HI)


def _h2d(x: np.ndarray) -> jax.Array:
    """Host-to-device copy via a contiguous staging buffer: jax's
    transfer of a non-contiguous numpy view (any column slice of a
    C-ordered matrix) marshals element-wise and runs ~30x slower than
    a contiguous upload on the dev tunnel (measured 30 s/GB vs
    1.1 s/GB); one host-side memcpy buys the fast path."""
    if not obs_events.enabled():
        return jnp.asarray(np.ascontiguousarray(x))
    obs_metrics.inc("ooc.h2d_bytes", int(x.nbytes))
    with obs_events.span("ooc::h2d", cat="staging",
                         bytes=int(x.nbytes)):
        return jnp.asarray(np.ascontiguousarray(x))


def _d2h(x: jax.Array, threads: int = 8) -> np.ndarray:
    """Device-to-host copy of a big block, chunked over rows and
    issued from a thread pool. On direct-attached hardware this is
    just a copy; on tunneled single-stream transports D2H can be far
    slower than H2D (measured on the dev tunnel: 59 s/GB single-
    stream vs 19 s/GB with 8 parallel chunk reads), and the chunking
    recovers a ~3x. Always returns a writable array."""
    m = x.shape[0]
    if obs_events.enabled():
        obs_metrics.inc("ooc.d2h_bytes",
                        int(np.dtype(x.dtype).itemsize
                            * int(np.prod(x.shape))))
    if m < 2048:
        return np.array(x)
    import concurrent.futures as cf
    step = ceil_div(m, threads)
    parts = [x[i:min(i + step, m)] for i in range(0, m, step)]

    def fetch(part):
        # per-chunk staging span: these run on POOL THREADS — the
        # shared bus (obs/events.py) is what makes them visible at
        # finish/export time (the old thread-local trace lost them)
        with obs_events.span("ooc::d2h_chunk", cat="staging"):
            return np.asarray(part)

    with obs_events.span("ooc::d2h", cat="staging"):
        with cf.ThreadPoolExecutor(len(parts)) as ex:
            hs = list(ex.map(fetch, parts))
        return np.concatenate(hs, axis=0)


# -- out-of-core LU -------------------------------------------------------

def _swaps_to_perm(piv: np.ndarray, mlen: int) -> np.ndarray:
    """Replay LAPACK sequential swap targets (j <-> piv[j], in order)
    on arange(mlen): the host-side twin of lu._compose_swaps."""
    perm = np.arange(mlen)
    for j, t in enumerate(np.asarray(piv)):
        perm[j], perm[t] = perm[t], perm[j]
    return perm


@functools.partial(jax.jit, static_argnames=("unit",))
def _lu_visit(S: jax.Array, Lj: jax.Array, j0, unit: bool = True
              ) -> jax.Array:
    """One left-looking LU visit of panel S (m, w) by an earlier
    factor panel Lj (m, wj), whose diagonal block sits at traced row
    offset j0: compute the U12 strip U = L_jj^{-1} S[j0:j1], subtract
    the trailing product L_j[j1:, :] U, and write the strip in place.
    Fixed shapes + traced offset = one compiled program for every
    (k, j) pair of the stream. `unit=False` makes the same sweep the
    non-unit forward-substitution step of the Cholesky solves."""
    m, w = S.shape
    wj = Lj.shape[1]
    rows = jnp.arange(m)
    Ljj = jax.lax.dynamic_slice(Lj, (j0, 0), (wj, wj))
    Sj = jax.lax.dynamic_slice(S, (j0, 0), (wj, w))
    if _solve_temps_bytes(w, wj, S.dtype.itemsize) > OOC_SOLVE_TEMP_CAP:
        # wide strip vs wide diag block: the direct solve's expander
        # temps blow at OOC panel widths (see OOC_SOLVE_TEMP_CAP)
        from .blocked import invert_triangular
        linv = invert_triangular(Ljj, lower=True, unit_diagonal=unit)
        U = jnp.matmul(linv, Sj, precision=_HI)
    else:
        U = jax.lax.linalg.triangular_solve(
            Ljj, Sj, left_side=True, lower=True, unit_diagonal=unit)
    below = jnp.where((rows >= j0 + wj)[:, None], Lj, 0)
    S = S - jnp.matmul(below, U, precision=_HI)
    return jax.lax.dynamic_update_slice(S, U, (j0, 0))


@functools.partial(jax.jit, static_argnames=("nb",))
def _lu_panel_factor(S: jax.Array, k0, nb: int):
    """In-core partial-pivot LU of the resident panel's live rows
    [k0:, :] via the measured-fastest blocked form (lu._getrf_dense
    routing). The panel is ROLLED so the diagonal sits at row 0 and
    the dead rows (already factored, wrapped to the bottom) are masked
    to exact zero — they can never win a pivot search against live
    entries, and their L entries come out exactly zero. One traced k0
    instead of per-k shapes = ONE compiled program for the whole
    stream (compile time dominated the first on-chip run). Returns
    (packed (m, w) rolled — live rows first, piv relative to k0)."""
    from .lu import _getrf_dense
    m = S.shape[0]
    rows = jnp.arange(m)
    rolled = jnp.roll(S, -k0, axis=0)
    rolled = jnp.where((rows < m - k0)[:, None], rolled, 0)
    return _getrf_dense(rolled, nb, pivot=True)


@jax.jit
def _lu_back_visit(S: jax.Array, Pk: jax.Array, k0) -> jax.Array:
    """Backward U sweep step: x_k = U_kk^{-1} S[k0:k1], then eliminate
    U[:k0, k0:k1] x_k from the rows above (streamed upper solve)."""
    m, w = S.shape
    wk = Pk.shape[1]
    rows = jnp.arange(m)
    Ukk = jax.lax.dynamic_slice(Pk, (k0, 0), (wk, wk))
    Sk = jax.lax.dynamic_slice(S, (k0, 0), (wk, w))
    if _solve_temps_bytes(w, wk, S.dtype.itemsize) > OOC_SOLVE_TEMP_CAP:
        from .blocked import invert_triangular
        uinv = invert_triangular(Ukk, lower=False)
        X = jnp.matmul(uinv, Sk, precision=_HI)
    else:
        X = jax.lax.linalg.triangular_solve(
            Ukk, Sk, left_side=True, lower=False, unit_diagonal=False)
    above = jnp.where((rows < k0)[:, None], Pk, 0)
    S = S - jnp.matmul(above, X, precision=_HI)
    return jax.lax.dynamic_update_slice(S, X, (k0, 0))


@instrument_driver("getrf_ooc")
def getrf_ooc(a: np.ndarray, panel_cols: Optional[int] = None,
              incore_nb: int = 1024):
    """Partial-pivot LU of a host-resident (m, n) matrix, streaming
    one column panel through the accelerator at a time (left-looking;
    reference src/getrf.cc:327 runs the same factorization at any n
    the cluster's aggregate memory holds). Returns (LU_packed, ipiv):
    the packed host factor (unit-lower L below the diagonal, U on and
    above) and LAPACK-convention global sequential swap targets of
    length min(m, n).

    Pivot discipline: partial pivoting CONFINED to the resident panel
    — each column's pivot search sees rows k0: (everything not yet
    factored), exactly the rows in-core getrf would search, so the
    factorization matches the in-core one up to roundoff. Row swaps
    are applied host-side to already-written L panels (O(n*w) gathers
    per panel) and folded into the running permutation that future
    panel reads go through. HBM residency: two (m, w) panels."""
    a = np.asarray(a)
    m, n = a.shape
    kmax = min(m, n)
    w = min(_panel_cols(panel_cols, n, a.dtype), n)
    perm = np.arange(m)
    out = np.empty_like(a)
    ipiv = np.empty((kmax,), np.int64)
    for k0 in range(0, n, w):
        k1 = min(k0 + w, n)
        S = jnp.asarray(np.take(a[:, k0:k1], perm, axis=0))    # H2D
        for j0 in range(0, min(k0, kmax), w):
            j1 = min(j0 + w, kmax)
            Lj = _h2d(out[:, j0:j1])                           # H2D
            S = _lu_visit(S, Lj, j0)
        if k0 < kmax:
            wf = min(k1, kmax) - k0
            packed, piv = _lu_panel_factor(
                S[:, :wf], k0, min(incore_nb, max(wf, 1)))
            piv_h = np.asarray(piv)
            lperm = _swaps_to_perm(piv_h, m - k0)
            # host fixups: swap rows of the L panels already written,
            # and of the running permutation for future reads
            if k0 > 0:
                out[k0:, :k0] = out[k0:, :k0][lperm]
            perm[k0:] = perm[k0:][lperm]
            ipiv[k0:k0 + wf] = k0 + piv_h
            S_h = np.empty((m, k1 - k0), a.dtype)
            if k0 > 0:
                S_h[:k0] = _d2h(S[:k0])     # U rows from the visits
            S_h[k0:, :wf] = _d2h(packed[:m - k0])
            if wf < k1 - k0:
                # kmax falls inside this panel (m < n): the columns
                # right of the last diagonal block are pure U12 rows
                # (live rows == wf here, so the solve covers them all)
                rest = S[k0:, wf:][jnp.asarray(lperm)]
                if _solve_temps_bytes(rest.shape[1], wf,
                                      a.dtype.itemsize) \
                        > OOC_SOLVE_TEMP_CAP:
                    from .blocked import invert_triangular
                    linv = invert_triangular(packed[:wf, :wf],
                                             lower=True,
                                             unit_diagonal=True)
                    U = jnp.matmul(linv, rest[:wf], precision=_HI)
                else:
                    U = jax.lax.linalg.triangular_solve(
                        packed[:wf, :wf], rest[:wf], left_side=True,
                        lower=True, unit_diagonal=True)
                S_h[k0:k0 + wf, wf:] = np.asarray(U)
        else:
            S_h = _d2h(S)                # columns past kmax: all U
        out[:, k0:k1] = S_h                                    # D2H
    return out, ipiv


def getrs_ooc(lu: np.ndarray, ipiv: np.ndarray, b: np.ndarray,
              panel_cols: Optional[int] = None) -> np.ndarray:
    """Solve A X = B from getrf_ooc's host factor: pivots replayed on
    the RHS, then each factor panel streams through the chip twice —
    the unit-lower forward sweep (the SAME kernel as the left-looking
    visit) and the upper backward sweep. B stays device-resident
    (nrhs << n)."""
    lu = np.asarray(lu)
    n = lu.shape[0]
    w = min(_panel_cols(panel_cols, n, lu.dtype), n)
    panels = list(range(0, n, w))
    perm = _swaps_to_perm(ipiv, n)
    X = jnp.asarray(np.take(np.asarray(b), perm, axis=0))
    for k0 in panels:                        # forward: L y = P b
        Pk = _h2d(lu[:, k0:min(k0 + w, n)])
        X = _lu_visit(X, Pk, k0)
    for k0 in reversed(panels):              # backward: U x = y
        Pk = _h2d(lu[:, k0:min(k0 + w, n)])
        X = _lu_back_visit(X, Pk, k0)
    return np.asarray(X)


@instrument_driver("gesv_ooc")
def gesv_ooc(a: np.ndarray, b: np.ndarray,
             panel_cols: Optional[int] = None):
    """Factor + solve in one call (the OOC twin of gesv)."""
    lu, ipiv = getrf_ooc(a, panel_cols)
    return (lu, ipiv), getrs_ooc(lu, ipiv, b, panel_cols)


# -- out-of-core QR -------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("trans",))
def _qr_visit(S: jax.Array, Pj: jax.Array, tauj: jax.Array, j0,
              trans: bool = True) -> jax.Array:
    """Apply an earlier panel's compact-WY block reflector to the
    resident panel S: V is unmasked from the packed factor at traced
    diagonal offset j0 (qr._panel_V handles the traced offset), T
    rebuilt by the closed-form larft, and S -= V (T' (V^H S)) with
    T' = T^H for Q^H (trans=True, the left-looking visit) or T for Q
    (trans=False, the reverse-order apply) — two tall matmuls plus
    one (wj, w) one, all at fixed shapes."""
    from .qr import _larft, _panel_V
    V = _panel_V(Pj, j0)
    T = _larft(V, tauj)
    W = jnp.matmul(jnp.conj(V.T), S, precision=_HI)
    W = jnp.matmul(jnp.conj(T.T) if trans else T, W, precision=_HI)
    return S - jnp.matmul(V, W, precision=_HI)


@functools.partial(jax.jit, static_argnames=("ib",))
def _qr_panel_factor(S: jax.Array, k0, ib: int):
    """Factor the live rows [k0:, :] of the resident panel: same
    roll-and-mask discipline as _lu_panel_factor (dead rows at exact
    zero contribute nothing to reflector norms and get V entries of
    exact zero), so one traced-k0 program serves the whole stream."""
    from .qr import _qr_panel_blocked
    m = S.shape[0]
    rows = jnp.arange(m)
    rolled = jnp.where((rows < m - k0)[:, None],
                       jnp.roll(S, -k0, axis=0), 0)
    return _qr_panel_blocked(rolled, ib=ib)


@jax.jit
def _qr_apply_fresh(S_rest: jax.Array, packed: jax.Array,
                    ptau: jax.Array) -> jax.Array:
    """Apply the just-factored panel's reflectors to the remaining
    columns of the SAME resident panel (only reached when kmax falls
    inside a panel, m < n)."""
    from .qr import _larft, _panel_V
    V = _panel_V(packed, 0)
    T = _larft(V, ptau)
    W = jnp.matmul(jnp.conj(V.T), S_rest, precision=_HI)
    W = jnp.matmul(jnp.conj(T.T), W, precision=_HI)
    return S_rest - jnp.matmul(V, W, precision=_HI)


@instrument_driver("geqrf_ooc")
def geqrf_ooc(a: np.ndarray, panel_cols: Optional[int] = None,
              incore_ib: int = 128):
    """Householder QR of a host-resident (m, n) matrix, streaming one
    column panel at a time (left-looking; reference src/geqrf.cc:26).
    Returns (QR_packed, taus) in the same packed contract as geqrf:
    V below the diagonal (unit implicit), R on and above, taus of
    length min(m, n). HBM residency: two (m, w) panels."""
    a = np.asarray(a)
    m, n = a.shape
    kmax = min(m, n)
    w = min(_panel_cols(panel_cols, n, a.dtype), n)
    out = np.empty_like(a)
    taus = np.zeros((kmax,), a.dtype)
    for k0 in range(0, n, w):
        k1 = min(k0 + w, n)
        S = _h2d(a[:, k0:k1])                                  # H2D
        for j0 in range(0, min(k0, kmax), w):
            j1 = min(j0 + w, kmax)
            Pj = _h2d(out[:, j0:j1])                           # H2D
            S = _qr_visit(S, Pj, jnp.asarray(taus[j0:j1]), j0)
        if k0 < kmax:
            wf = min(k1, kmax) - k0
            packed, ptau = _qr_panel_factor(S[:, :wf], k0, incore_ib)
            S_h = np.empty((m, k1 - k0), a.dtype)
            if k0 > 0:
                S_h[:k0] = _d2h(S[:k0])     # R rows from the visits
            S_h[k0:, :wf] = _d2h(packed[:m - k0])
            taus[k0:k0 + wf] = np.asarray(ptau[:wf])
            if wf < k1 - k0:
                rest = _qr_apply_fresh(S[k0:, wf:], packed[:m - k0],
                                       ptau)
                S_h[k0:, wf:] = np.asarray(rest)
        else:
            S_h = _d2h(S)
        out[:, k0:k1] = S_h                                    # D2H
    return out, taus


def unmqr_ooc(qr: np.ndarray, taus: np.ndarray, c: np.ndarray,
              trans: bool = True,
              panel_cols: Optional[int] = None) -> np.ndarray:
    """Apply Q (trans=False) or Q^H (True) from geqrf_ooc's host
    factor to a device-resident block C, streaming reflector panels
    (Q^H applies panels forward, Q in reverse)."""
    qr = np.asarray(qr)
    kmax = min(qr.shape)
    w = min(_panel_cols(panel_cols, kmax, qr.dtype), kmax)
    starts = list(range(0, kmax, w))
    if not trans:
        starts.reverse()
    X = jnp.asarray(np.asarray(c))
    for j0 in starts:
        j1 = min(j0 + w, kmax)
        Pj = _h2d(qr[:, j0:j1])
        tj = jnp.asarray(taus[j0:j1])
        X = _qr_visit(X, Pj, tj, j0, trans=trans)
    return np.asarray(X)


@instrument_driver("gels_ooc")
def gels_ooc(a: np.ndarray, b: np.ndarray,
             panel_cols: Optional[int] = None):
    """Least squares min ||A X - B|| for host-resident TALL A (m >= n)
    via the streamed QR: Q^H B by reflector-panel visits, then the
    upper back-substitution sweep on R (the same backward kernel as
    getrs_ooc). Returns ((QR_packed, taus), X)."""
    from ..core.exceptions import slate_assert
    a = np.asarray(a)
    m, n = a.shape
    slate_assert(m >= n, "gels_ooc requires tall A (m >= n): the R "
                 "back-substitution sweep indexes n factor rows")
    panel_cols = _panel_cols(panel_cols, n, a.dtype)
    qr_p, taus = geqrf_ooc(a, panel_cols)
    y = unmqr_ooc(qr_p, taus, np.asarray(b), trans=True,
                  panel_cols=panel_cols)
    X = jnp.asarray(y[:n])
    w = min(panel_cols, n)
    for k0 in reversed(range(0, n, w)):
        Pk = _h2d(qr_p[:n, k0:min(k0 + w, n)])
        X = _lu_back_visit(X, Pk, k0)
    return (qr_p, taus), np.asarray(X)


@instrument_driver("gemm_ooc")
def gemm_ooc(alpha, a: np.ndarray, b: np.ndarray, beta,
             c: np.ndarray,
             row_panel: Optional[int] = None) -> np.ndarray:
    """C = alpha A B + beta C with A and C streamed through the chip
    in row panels; B stays device-resident (the tall-A regime — for
    B beyond HBM, tile the k dimension at the call site). Host in,
    host out. BLAS convention: C is neither read nor transferred when
    beta == 0 (so an uninitialized C is legal and the streamed input
    volume halves in the overwrite case)."""
    a = np.asarray(a)
    m = a.shape[0]
    row_panel = _panel_cols(row_panel, m, a.dtype)
    Bd = jnp.asarray(b) * alpha
    out = np.empty_like(c)
    for r0 in range(0, m, row_panel):
        r1 = min(r0 + row_panel, m)
        if beta == 0:
            blk = _gemm_block_overwrite(jnp.asarray(a[r0:r1]), Bd)
        else:
            blk = _gemm_block(jnp.asarray(a[r0:r1]), Bd, beta,
                              jnp.asarray(c[r0:r1]))
        out[r0:r1] = np.asarray(blk)
    return out
