"""Out-of-core (out-of-HBM) streaming drivers — the huge-n duty of
SURVEY §2.3.8: matrices larger than accelerator memory live in HOST
memory and stream through the chip one column panel at a time.

Reference analogue: SLATE keeps the global matrix distributed and
streams remote tiles through per-device workspace with receive counts
and `releaseRemoteWorkspace` (BaseMatrix.hh:462-479, potrf.cc:179-192)
— residency is managed per tile. XLA owns residency inside one jitted
program, so the TPU-native equivalent hoists the streaming OUTSIDE
jit: a host loop moves one panel (and one visiting block per
left-looking update) host<->device around small jitted kernels, and
the factor accumulates on the host. HBM footprint is O(n * panel_cols)
instead of O(n^2).

Algorithm (potrf_ooc): classic left-looking out-of-core Cholesky —
for each column panel k: S = A[k0:, k0:k1]; for every previous panel
j: S -= L_j[k0:, :] L_j[k0:k1, :]^H (one streamed visit of L_j's
rows); then factor the panel in-core (diag cholesky + one triangular
solve). Per-panel transfer volume is O(n * panel_cols * nt) reads —
the unavoidable left-looking revisit — and one panel write.

gemm_ooc streams A's row panels against a device-resident B (the
common tall-A case); C streams back per panel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tiles import ceil_div

_HI = jax.lax.Precision.HIGHEST


@functools.partial(jax.jit, static_argnames=("w",))
def _panel_apply(S: jax.Array, Lj: jax.Array, w: int) -> jax.Array:
    """S -= L_j L_j_top^H for one visiting panel block (left-looking
    update): Lj is (m, wj) = rows k0: of an earlier factor panel,
    whose top w rows align with S's columns."""
    top = Lj[:w]
    return S - jnp.matmul(Lj, jnp.conj(top.T), precision=_HI)


@functools.partial(jax.jit, static_argnames=("w",))
def _panel_factor(S: jax.Array, w: int) -> jax.Array:
    """Factor one (m, w) column panel in-core: diag cholesky + one
    right-side triangular solve (the single-device fast kernels of
    linalg/blocked.py)."""
    lkk = jnp.tril(jax.lax.linalg.cholesky(S[:w], symmetrize_input=False))
    if S.shape[0] > w:
        pan = jax.lax.linalg.triangular_solve(
            lkk, S[w:], left_side=False, lower=True,
            transpose_a=True, conjugate_a=True)
        return jnp.concatenate([lkk, pan], axis=0)
    return lkk


def potrf_ooc(a: np.ndarray, panel_cols: int = 8192) -> np.ndarray:
    """Lower Cholesky of a host-resident Hermitian matrix (lower
    triangle read), streaming one column panel through the accelerator
    at a time. Returns the host-resident lower factor; n is bounded by
    host RAM, not HBM.

    No pivoting/info path (matches potrf's non-guarded contract);
    a must be positive definite.
    """
    a = np.asarray(a)
    n = a.shape[0]
    nt = ceil_div(n, panel_cols)
    out = np.zeros_like(a)
    for k in range(nt):
        k0 = k * panel_cols
        k1 = min(k0 + panel_cols, n)
        w = k1 - k0
        S = jnp.asarray(a[k0:, k0:k1])                     # H2D
        for j in range(k):
            j0 = j * panel_cols
            j1 = min(j0 + panel_cols, n)
            Lj = jnp.asarray(out[k0:, j0:j1])              # H2D visit
            S = _panel_apply(S, Lj, w)
        Lk = _panel_factor(S, w)
        out[k0:, k0:k1] = np.asarray(Lk)                   # D2H
    return out


@jax.jit
def _gemm_block(Ab: jax.Array, B: jax.Array, beta, Cb: jax.Array):
    return beta * Cb + jnp.matmul(Ab, B, precision=_HI)


@jax.jit
def _gemm_block_overwrite(Ab: jax.Array, B: jax.Array):
    return jnp.matmul(Ab, B, precision=_HI)


def gemm_ooc(alpha, a: np.ndarray, b: np.ndarray, beta,
             c: np.ndarray, row_panel: int = 8192) -> np.ndarray:
    """C = alpha A B + beta C with A and C streamed through the chip
    in row panels; B stays device-resident (the tall-A regime — for
    B beyond HBM, tile the k dimension at the call site). Host in,
    host out. BLAS convention: C is neither read nor transferred when
    beta == 0 (so an uninitialized C is legal and the streamed input
    volume halves in the overwrite case)."""
    a = np.asarray(a)
    m = a.shape[0]
    Bd = jnp.asarray(b) * alpha
    out = np.empty_like(c)
    for r0 in range(0, m, row_panel):
        r1 = min(r0 + row_panel, m)
        if beta == 0:
            blk = _gemm_block_overwrite(jnp.asarray(a[r0:r1]), Bd)
        else:
            blk = _gemm_block(jnp.asarray(a[r0:r1]), Bd, beta,
                              jnp.asarray(c[r0:r1]))
        out[r0:r1] = np.asarray(blk)
    return out
